# Analog of the reference's shell-script surface (ref multi/run.sh,
# multi/val.sh, member/diff.sh): run, bench, parity-vs-C++, replay-diff,
# and a sanitizer-mode pass (check, the val.sh analog).

PY ?= python

.PHONY: test test-slow check lint lint-json audit audit-json \
	shard-audit bench bench-sharded parity parity-fast replay-diff \
	replay-diff-member run stress stress-quick fleet fleet-quick \
	evolve evolve-quick mc mc-quick serve serve-quick serve-fleet \
	serve-fleet-quick serve-control serve-control-quick \
	envelope-quick clean

# Fast tier: every feature covered, heavy literal-size / long-schedule
# variants deselected (marked slow).  ~6 min; test-slow runs everything.
test:
	$(PY) -m pytest tests/ -x -q -m "not slow"

test-slow:
	$(PY) -m pytest tests/ -x -q

# paxlint: determinism & JAX-purity static analysis
# (tpu_paxos/analysis/).  Pure-AST — runs without jax, in seconds.
# Exit 0 iff zero unsuppressed findings and no stale baseline entries.
lint:
	$(PY) -m tpu_paxos lint

lint-json:
	$(PY) -m tpu_paxos lint --json

# jaxpr-audit: trace-time IR contracts (IR201-IR205) + pinned op/cost
# budget over the registered entry points of both engines and the
# sharded path (tpu_paxos/analysis/jaxpr_audit.py), PLUS the
# compiled-artifact tier (--hlo, tpu_paxos/analysis/hlo_audit.py):
# normalized-HLO goldens for the hot kernels, per-primitive
# instruction budgets + memory ceilings, and the donation/aliasing
# checker.  Traces on CPU — ops counts are backend-independent; the
# HLO pins are backend-gated AND compiled under the repo's canonical
# CPU environment: the 8-virtual-device mesh tests/conftest.py
# provisions (XLA's CPU backend partitions fusions differently per
# device count, so the goldens only reproduce under the same count —
# tests/test_hlo_audit.py enforces the committed pins from inside
# that mesh).  Re-pin after intentional program growth:
# TPU_PAXOS_OP_BUDGET_PIN=1 make audit (jaxpr tier) /
# TPU_PAXOS_HLO_PIN=1 make audit (HLO goldens + budget).
AUDIT_ENV = JAX_PLATFORMS=cpu \
	XLA_FLAGS="$(XLA_FLAGS) --xla_force_host_platform_device_count=8"

audit:
	$(AUDIT_ENV) $(PY) -m tpu_paxos audit --hlo

audit-json:
	$(AUDIT_ENV) $(PY) -m tpu_paxos audit --hlo --json

# shard-audit: mesh-polymorphic SPMD contracts
# (tpu_paxos/analysis/shard_audit.py) — partition-rule coverage
# (SH301), per-mesh replication ceilings + collective census
# (SH302/SH303, analysis/shard_budget.json), and cross-mesh parity
# certificates (SH304, analysis/shard_certificate.json) over the
# virtual {1,2,4,8} mesh grid the AUDIT_ENV provisions.  Re-pin:
# TPU_PAXOS_SHARD_PIN=1 make shard-audit (certificate) /
# TPU_PAXOS_SHARD_BUDGET_PIN=1 make shard-audit (budget).
shard-audit:
	$(AUDIT_ENV) $(PY) -m tpu_paxos audit --shard-only

# Sanitizer pass (ref multi/val.sh runs the suite under valgrind): the
# static analyzers first (cheapest signal), then the quick-scope model
# check (protocol-level gate; the full scope stays out of the fast
# path — make mc), then the fast tier with NaN-checking on, then an
# un-jitted op-by-op smoke of one tiny config per engine (every cond
# predicate, slice bound, and dtype materializes eagerly).  The pallas
# interpreter path is part of the fast tier (tests/test_fastwin.py).
check: lint audit shard-audit mc-quick evolve-quick envelope-quick serve-quick serve-fleet-quick serve-control-quick
	JAX_DEBUG_NANS=1 $(PY) -m pytest tests/ -x -q -m "not slow"
	JAX_DISABLE_JIT=1 JAX_DEBUG_NANS=1 $(PY) scripts/check_smoke.py

bench:
	$(PY) bench.py

bench-sharded:
	TPU_PAXOS_BENCH_SHARDED=1 $(PY) bench.py

# Full-speed parity anchor: the canonical debug.conf.sample line on the
# C++ reference (~50s wall clock — its delays are real milliseconds),
# then the tpu_paxos equivalent, both judged by the same invariants.
parity:
	$(PY) -c "import json; from tpu_paxos.harness import reference_runner as r; \
	print(json.dumps(r.check_parity(reference_args_list=r.reference_args(), timeout=600), indent=2))"

# Time-scaled parity anchor (seconds instead of ~50s; fault rates identical).
parity-fast:
	$(PY) -c "import json; from tpu_paxos.harness import reference_runner as r; \
	print(json.dumps(r.check_parity(), indent=2))"

# Same-seed reruns produce byte-identical decision logs (spirit of
# ref member/diff.sh).
replay-diff:
	$(PY) -m pytest tests/test_replay.py -x -q

# Record/replay for a wall-clock-paced membership driver: the host's
# injection schedule is the one nondeterministic input; record it,
# replay it, byte-compare decision logs (ref member/run.sh:10-16,
# member/diff.sh:1-3 — the Indet subsystem's workflow).
replay-diff-member:
	$(PY) scripts/replay_diff_member.py

# Randomized sweep: seeds x fault mixes through the general engine,
# full invariant suite on every run (the reference's stated purpose,
# beyond the fixed-seed tests).  SEEDS=n overrides seeds per mix.
# Failing seeds are shrunk to minimal repro artifacts under
# stress-triage/ (`python -m tpu_paxos repro <artifact>` replays them).
stress:
	$(PY) -m tpu_paxos.harness.stress --seeds $(or $(SEEDS),8) --sharded \
	  --triage-dir stress-triage

# Quick pass: 2 seeds x every mix (incl. the correlated-fault episode
# mixes: partition-flap, one-way, pause-heavy, pause-crash).
stress-quick:
	$(PY) -m tpu_paxos.harness.stress --seeds 2 --triage-dir stress-triage

# Fleet schedule search: sample episode schedules from the seeded
# grammar, run them as device-batched lanes (one XLA dispatch per
# generation), shrink every wedge to a repro artifact under
# stress-triage/.  LANES=n / GENS=n override the budget.
fleet:
	$(PY) -m tpu_paxos fleet --lanes $(or $(LANES),8) \
	  --generations $(or $(GENS),4) --triage-dir stress-triage

# Quick pass with the synthetic decision_round_max wedge knob armed:
# slow-converging schedules count as wedges, so the find -> shrink ->
# artifact -> `python -m tpu_paxos repro` path is exercised end to end
# in one short run.
fleet-quick:
	$(PY) -m tpu_paxos fleet --lanes 8 --generations 1 --seed 2 \
	  --decision-round-max 35 --max-wedges 1 --triage-dir stress-triage

# Certified selection loop (tpu_paxos/fleet/evolve.py): mutate-and-
# select over fault-schedule / churn / offered-load genomes, one
# fleet dispatch per generation through the shared envelope cache
# (zero warm compiles after gen 0, census-pinned).  --certified reads
# the lane budget from the mc certificate (quick scope / 4) and
# withholds the bench record unless the shrunk artifact replays
# byte-identically inside it.  AXIS=fleet|member|serve, HUNT=<cause>.
evolve:
	$(PY) -m tpu_paxos evolve --axis $(or $(AXIS),fleet) \
	  --lanes $(or $(LANES),8) --generations $(or $(GENS),8) \
	  $(if $(HUNT),--hunt $(HUNT)) --triage-dir stress-triage

# Quick pass (wired into make check): the synthetic
# decision_round_max wedge knob armed, so sample -> select -> flag ->
# shrink -> artifact -> replay is exercised end to end in one short
# run (same knob and seed discipline as fleet-quick).
evolve-quick:
	$(PY) -m tpu_paxos evolve --lanes 8 --generations 2 --seed 2 \
	  --decision-round-max 35 --max-wedges 1 --triage-dir stress-triage

# Exhaustive bounded model checking (tpu_paxos/analysis/modelcheck.py):
# enumerate EVERY fault scenario of the declared scope — episode kinds
# x quantized intervals x node groups x rate tiers x knob tiers x
# gate tiers x seeds, node-permutation symmetry reduced — as chunked
# device-batched fleet lanes, shrink any counterexample to an
# mc_scenario_<index> repro artifact, and gate on the pinned scope
# certificate (analysis/mc_certificate.json).  Re-pin after an
# intentional scope/engine change: TPU_PAXOS_MC_PIN=1 make mc (and
# the same for mc-quick).
mc:
	$(PY) -m tpu_paxos mc --scope full --triage-dir stress-triage

# All four committed scopes in ONE process: gray shares quick's
# engine envelope so its chunks ride quick's compile; churn and
# control certify the membership fleet and the admission controller's
# policy contracts (~60s cold on cpu, dominated by the three engine
# compiles).
mc-quick:
	$(PY) -m tpu_paxos mc --scope quick,gray,churn,control --triage-dir stress-triage

# Geometry-padded envelope smoke (wired into make check): ONE padded
# fleet executable must serve the whole (geometry x protocol-knob x
# rate) grid — the fast-tier collapse cell dispatches an 8-cell grid
# through one cached runner and pins a ZERO warm-compile census after
# the first dispatch, plus cache-identity across geometries.
# Decision-log parity and the named-rejection surface ride the same
# module's other fast cells (and the tier-1 run).
envelope-quick:
	JAX_PLATFORMS=cpu $(PY) -m pytest \
	  tests/test_envelope_pad.py::test_envelope_compile_collapse -x -q

# Open-loop serving (tpu_paxos/serve/): Poisson arrivals at an
# offered rate (values per 1000 rounds) admitted mid-flight through
# double-buffered dispatch windows; prints the latency-at-load sweep
# + knee judgment.  RATE=milli / VALUES=n override the sweep shape;
# add --sequential via SERVE_FLAGS for the naive-dispatch baseline.
SERVE_RATES ?= 1000,2000,4000,8000,16000,32000
serve:
	$(PY) -m tpu_paxos serve --values $(or $(VALUES),512) \
	  --sweep $(SERVE_RATES) \
	  --drop-rate 500 --dup-rate 1000 --max-delay 2 $(SERVE_FLAGS)

# Quick pass (wired into make check): a small Poisson run at a
# sustained rate plus the zero-load limit; exits non-zero if the
# stream does not drain.
serve-quick:
	$(PY) -m tpu_paxos serve --values 64 --rate-milli 4000 \
	  --drop-rate 500 --dup-rate 1000 --max-delay 2
	$(PY) -m tpu_paxos serve --values 64 --rate-milli 0

# Fleet serving (tpu_paxos/serve/fleet.py): many tenant streams per
# dispatch — the serve window vmapped over [lanes] with on-device
# per-lane SLO verdicts; prints the (lanes x rates) aggregate
# sustained-values/sec + knee SURFACE.  SERVE_LANES=l,l,... /
# VALUES=n override (a ?= variable like SERVE_RATES: commas inside
# $(or ...) would split into separate arguments).
SERVE_LANES ?= 1,2,4,8
serve-fleet:
	$(PY) -m tpu_paxos serve --fleet --lane-counts $(SERVE_LANES) \
	  --values $(or $(VALUES),128) --sweep $(SERVE_RATES) \
	  --drop-rate 500 --dup-rate 1000 --max-delay 2 $(SERVE_FLAGS)

# Quick pass (wired into make check): a small 2-lane fleet at a
# sustained rate with an SLO armed; exits non-zero if any lane fails
# to drain or the confirmed SLO verdict breaches.
serve-fleet-quick:
	$(PY) -m tpu_paxos serve --fleet --lanes 2 --values 48 \
	  --rate-milli 4000 --slo-latency 128 \
	  --drop-rate 500 --dup-rate 1000 --max-delay 2

# Adaptive serving (tpu_paxos/serve/control.py): THE spike A/B
# judgment at the committed BENCH_serve_control.json shape — a 4x
# mid-run load spike on an admission-capped engine (assign_window=8),
# served controller-off then controller-on at the same offered
# trajectory.  Exits non-zero unless controller-on names strictly
# fewer breach windows, sheds only outside gray-region-attributed
# windows, and actually shed something.  Engine seed 3, arrivals
# seed 0 (the decoupled pair the committed record pins).
serve-control:
	$(PY) -m tpu_paxos serve --control-ab --nodes 3 --values 1000 \
	  --rate-milli 2000 --spike-factor 4 --spike-start-frac 0.25 \
	  --spike-len-frac 0.5 --slo-latency 16 --slo-budget-milli 150 \
	  --rounds-per-window 4 --windows-per-dispatch 2 \
	  --window-rounds 32 --instances 2048 --assign-window 8 \
	  --max-rounds 8000 --seed 3 --arrival-seed 0 $(SERVE_FLAGS)

# Quick pass (wired into make check): a small controller-armed run at
# a sustained rate — the controller must stay quiet (no spurious
# degrade), the stream must drain, and the SLO verdict must hold.
serve-control-quick:
	$(PY) -m tpu_paxos serve --nodes 3 --values 60 --rate-milli 2000 \
	  --slo-latency 16 --slo-budget-milli 150 --control \
	  --rounds-per-window 4 --windows-per-dispatch 2 \
	  --window-rounds 32 --max-rounds 4000

# The debug.conf.sample workload end-to-end on the tpu engine.
run:
	$(PY) -m tpu_paxos 4 4 10 --seed=0 --net-drop-rate=500 \
	  --net-dup-rate=1000 --net-min-delay=0 --net-max-delay=2

clean:
	rm -rf build
