#!/usr/bin/env python
"""Headline benchmark: Paxos instances/sec to chosen value.

Runs BASELINE.md config 2 — 5 nodes, 1M instances, single chip — as
the steady-state flow of one prepared proposer: phase-1 once, then
batched accept + commit windows over fresh instances (the reference's
long-running proposer does exactly this: one prepare, then batched
accepts for every subsequent proposal, ref multi/paxos.cpp:1256-1275).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "instances/sec", "vs_baseline": N}

vs_baseline is measured against the repo's north-star target of 10M
instances/sec (BASELINE.json) — the reference itself publishes no
numbers (BASELINE.md), so >1.0 means the north star is beaten.

Environment knobs: TPU_PAXOS_BENCH_INSTANCES (window size, default 1M),
TPU_PAXOS_BENCH_NODES (default 5), TPU_PAXOS_BENCH_REPS (windows per
timed call, default 32), TPU_PAXOS_BENCH_SHARDED=1 (use every visible
device via shard_map — BASELINE config 4 shape).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tpu_paxos.core import ballot as bal
from tpu_paxos.core import fast
from tpu_paxos.core import values as val

NORTH_STAR = 10_000_000.0  # instances/sec, BASELINE.json north_star


def _steady_state_windows(
    state: fast.FastState, vids0, reps: int, quorum: int, span: int | None = None
):
    """Phase-1 once, then `reps` accept+learn windows over fresh
    instance windows (state arrays recycled as the sliding window)."""
    _, ballot = bal.bump_past(
        jnp.int32(0), jnp.int32(0), jnp.max(state.max_seen)
    )
    state, prepared, _, _ = fast.phase1_prepare(state, ballot, quorum)

    def window(carry, k):
        st, total = carry
        # A fresh window of instances: clear per-instance state, new vids.
        st = st._replace(
            acc_ballot=jnp.full_like(st.acc_ballot, bal.NONE),
            acc_vid=jnp.full_like(st.acc_vid, val.NONE),
            learned=jnp.full_like(st.learned, val.NONE),
        )
        # Window k proposes a globally fresh vid range (span = global
        # instance count, not the shard-local slice size).
        vids = jnp.where(
            prepared, vids0 + k * jnp.int32(span or vids0.shape[0]), val.NONE
        )
        st, chosen = fast.phase2_accept(st, ballot, vids, quorum)
        st = fast.phase3_learn(st, vids, chosen)
        n = jnp.sum((st.learned[:, 0] != val.NONE).astype(jnp.int32))
        return (st, total + n), None

    (state, total), _ = jax.lax.scan(
        window, (state, jnp.int32(0)), jnp.arange(reps, dtype=jnp.int32)
    )
    return state, total


def main() -> None:
    n_inst = int(os.environ.get("TPU_PAXOS_BENCH_INSTANCES", 1_000_000))
    n_nodes = int(os.environ.get("TPU_PAXOS_BENCH_NODES", 5))
    reps = int(os.environ.get("TPU_PAXOS_BENCH_REPS", 32))
    use_sharded = os.environ.get("TPU_PAXOS_BENCH_SHARDED", "0") == "1"
    quorum = n_nodes // 2 + 1

    vids0 = jnp.arange(n_inst, dtype=jnp.int32)

    if use_sharded and len(jax.devices()) > 1:
        from tpu_paxos.parallel import mesh as pmesh
        from tpu_paxos.parallel import sharded as psharded
        from jax.sharding import PartitionSpec as P

        mesh = pmesh.make_instance_mesh()
        n_inst -= n_inst % mesh.size or 0
        vids0 = pmesh.shard_instances(mesh, jnp.arange(n_inst, dtype=jnp.int32))
        state = psharded.init_sharded_state(mesh, n_inst, n_nodes)
        def _local(st, v):
            st, local_total = _steady_state_windows(
                st, v, reps=reps, quorum=quorum, span=n_inst
            )
            return st, jax.lax.psum(local_total, pmesh.INSTANCE_AXIS)

        body = jax.shard_map(
            _local,
            mesh=mesh,
            in_specs=(psharded._state_specs(), P(pmesh.INSTANCE_AXIS)),
            out_specs=(psharded._state_specs(), P()),
            check_vma=False,
        )
        step = jax.jit(body, donate_argnums=(0,))
    else:
        state = fast.init_state(n_inst, n_nodes)
        step = jax.jit(
            functools.partial(_steady_state_windows, reps=reps, quorum=quorum),
            donate_argnums=(0,),
        )

    # Warmup / compile.
    state2, total = step(state, vids0)
    total.block_until_ready()
    assert int(total) == n_inst * reps, f"warmup chose {int(total)}"

    t0 = time.perf_counter()
    state3, total = step(state2, vids0)
    total.block_until_ready()
    dt = time.perf_counter() - t0

    n_chosen = int(total)
    assert n_chosen == n_inst * reps, f"bench chose {n_chosen}"
    rate = n_chosen / dt
    print(
        json.dumps(
            {
                "metric": "paxos_instances_per_sec_to_chosen",
                "value": round(rate, 1),
                "unit": "instances/sec",
                "vs_baseline": round(rate / NORTH_STAR, 3),
                "config": {
                    "n_nodes": n_nodes,
                    "n_instances_per_window": n_inst,
                    "windows": reps,
                    "sharded": bool(use_sharded and len(jax.devices()) > 1),
                    "devices": len(jax.devices()),
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
