#!/usr/bin/env python
"""Headline benchmark: Paxos instances/sec to chosen value.

Runs BASELINE.md config 2 — 5 nodes, single chip — as the
steady-state flow of one prepared proposer: phase-1 once, then batched
accept + commit windows over fresh instances (the reference's
long-running proposer does exactly this: one prepare, then batched
accepts for every subsequent proposal, ref multi/paxos.cpp:1256-1275).
The window size is a throughput knob: per-window dispatch overhead
(~3-8 ms) amortizes over the window, so the default drives 128M
instances per window on TPU (~8 GiB of FastState, donated in place;
CPU fallback defaults smaller) — the [A, I] minor-instance layout
keeps every op lane-dense at any size.  On TPU the window loop runs as
one pallas launch (``core/fastwin.py``): a single fused HBM pass per
window instead of XLA's ~5 passes, with 16 windows per call — exactly
filling the int32 vid space at 2^27 instances/window.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "instances/sec", "vs_baseline": N}

vs_baseline is measured against the repo's north-star target of 10M
instances/sec (BASELINE.json) — the reference itself publishes no
numbers (BASELINE.md), so >1.0 means the north star is beaten.

Environment knobs: TPU_PAXOS_BENCH_INSTANCES (window size, default
2^27), TPU_PAXOS_BENCH_NODES (default 5), TPU_PAXOS_BENCH_REPS (windows
per timed call, default 16 on TPU / 4 on CPU), TPU_PAXOS_BENCH_FUSED=0
(force the XLA scan instead of the pallas kernel),
TPU_PAXOS_BENCH_SHARDED=1 (use every visible device via shard_map —
BASELINE config 4 shape), TPU_PAXOS_BENCH_DCN_HOSTS (2-D multi-host
mesh for the sharded paths), TPU_PAXOS_BENCH_SIM_INSTANCES /
TPU_PAXOS_BENCH_SIM_SHARDED_INSTANCES /
TPU_PAXOS_BENCH_SHARDED_FAST_INSTANCES /
TPU_PAXOS_BENCH_MEMBER_INSTANCES (secondary record sizes),
TPU_PAXOS_BENCH_MEMBER=0 (skip the membership churn record),
TPU_PAXOS_BENCH_ENVELOPE=0 (skip the geometry-padded envelope sweep;
TPU_PAXOS_BENCH_ENVELOPE_LANES sizes it),
TPU_PAXOS_BENCH_SERVE_CONTROL=0 (skip the adaptive-serving spike A/B
record; TPU_PAXOS_BENCH_SERVE_CONTROL_VALUES / _ARTIFACT size and
artifact-path knobs), TPU_PAXOS_BENCH_SECONDARY=0 /
TPU_PAXOS_BENCH_SHARDED_CHILD=0 (skip secondary records),
TPU_PAXOS_BENCH_PROFILE=<dir> (jax profiler trace of the timed
window).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import sys
import tempfile
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tpu_paxos.core import ballot as bal
from tpu_paxos.core import fast
from tpu_paxos.core import values as val

NORTH_STAR = 10_000_000.0  # instances/sec, BASELINE.json north_star

# A v5e chip moves ~0.82 TB/s through HBM at peak.  Any measurement
# implying more than this many bytes/sec of state traffic is a timing
# artifact (the axon device tunnel has produced ~2000x-fast timings
# when a call was blocked on a scalar only — BENCH_r04's 22B inst/s sim
# record), not a real number.  Secondary records that trip the guard
# are withheld (an error entry with the raw timings instead); the
# headline falls back to the slowest timing, and if even that is
# impossible NO value is published — ``value`` is null, the raw
# timings are kept, and the hardware-implied ceiling moves to an
# explicit ``value_upper_bound`` field (a bound, never a measurement;
# marked by config.roofline_note either way).
ROOFLINE_BYTES_PER_SEC = 2.0e12


def _state_nbytes(state) -> int:
    return sum(x.nbytes for x in jax.tree_util.tree_leaves(state))


def _guard_headline(dts, min_bytes: int, n_dev: int, n_work: int):
    """Roofline-guard the headline timing set.  Returns
    ``(rate, value_upper_bound, roofline_note)``: the median-derived
    rate when it is physically plausible; the slowest-timing rate when
    only the median is implausible; and ``(None, bound, note)`` when
    EVERY timing is implausible — a number that was never measured is
    withheld, and the hardware-implied ceiling is reported as an
    explicit upper bound instead (ADVICE round 5)."""
    dt = sorted(dts)[1]
    refusal = _implausible(min_bytes, dt, n_dev)
    if refusal is None:
        return n_work / dt, None, None
    dt = sorted(dts)[-1]
    print(f"headline {refusal}; raw timings {dts}", file=sys.stderr)
    if _implausible(min_bytes, dt, n_dev) is None:
        return (
            n_work / dt,
            None,
            refusal + "; value recomputed from slowest timing",
        )
    upper = n_work / (min_bytes / (ROOFLINE_BYTES_PER_SEC * max(1, n_dev)))
    return (
        None,
        upper,
        refusal + "; all timings implausible — value withheld, "
        "roofline bound reported as value_upper_bound",
    )


def _implausible(min_bytes: int, dt: float, n_devices: int = 1) -> str | None:
    """Return a refusal message if `dt` seconds for at least `min_bytes`
    of HBM traffic implies impossible bandwidth, else None.  Aggregate
    HBM bandwidth scales with device count, so the guard does too."""
    roof = ROOFLINE_BYTES_PER_SEC * max(1, n_devices)
    bps = min_bytes / max(dt, 1e-12)
    if bps > roof:
        return (
            f"implied {bps:.3g} B/s of state traffic exceeds the "
            f"{roof:.2g} B/s ({n_devices}-device) roofline guard; "
            "timing artifact — record withheld"
        )
    return None


def _total(counts) -> int:
    """Host-side sum of per-window chosen counts (both window paths
    return [reps] int32 — reps x I can exceed int32)."""
    import numpy as np

    return int(np.asarray(counts, dtype=np.int64).sum())


def _check_total(counts, expected: int) -> None:
    """Host-sync + correctness check in one: transfers the counts (the
    blocking barrier inside every timed window) and raises — not
    asserts, which `python -O` would strip along with the sync — on a
    wrong chosen count."""
    n = _total(counts)
    if n != expected:
        raise RuntimeError(f"window chose {n} instances, expected {expected}")


def _steady_state_windows(
    state: fast.FastState, vids0, reps: int, quorum: int, span: int | None = None
):
    """Phase-1 once, then `reps` accept+learn windows over fresh
    instance windows (state arrays recycled as the sliding window).
    Returns (state, per-window chosen counts [reps]) — counts stay
    per-window because a running int32 total wraps at 2^31 instances
    (reps=16 x 2^27 hits it exactly); callers sum in host integers."""
    if reps * (span or vids0.shape[0]) > 1 << 31:
        raise ValueError(
            f"reps * span = {reps * (span or vids0.shape[0])} exceeds the "
            "int32 vid space (vid 2^31 would wrap to the NONE sentinel)"
        )
    _, ballot = bal.bump_past(
        jnp.int32(0), jnp.int32(0), jnp.max(state.max_seen)
    )
    state, prepared, _, _ = fast.phase1_prepare(state, ballot, quorum)

    def window(st, k):
        # A fresh window of instances: clear per-instance state, new vids.
        st = st._replace(
            acc_ballot=jnp.full_like(st.acc_ballot, bal.NONE),
            acc_vid=jnp.full_like(st.acc_vid, val.NONE),
            learned=jnp.full_like(st.learned, val.NONE),
        )
        # Window k proposes a globally fresh vid range (span = global
        # instance count, not the shard-local slice size).
        vids = jnp.where(
            prepared, vids0 + k * jnp.int32(span or vids0.shape[0]), val.NONE
        )
        st, chosen = fast.phase2_accept(st, ballot, vids, quorum)
        st = fast.phase3_learn(st, vids, chosen)
        n = jnp.sum((st.learned[0] != val.NONE).astype(jnp.int32))
        return st, n

    state, counts = jax.lax.scan(
        window, state, jnp.arange(reps, dtype=jnp.int32)
    )
    return state, counts


def _sharded_fast_setup(n_nodes: int, n_inst: int, reps: int, donate: bool):
    """Mesh + jitted shard_map'd steady-state step for the fast path —
    shared by main()'s sharded mode and the bench child."""
    from jax.sharding import PartitionSpec as P

    from tpu_paxos.parallel import mesh as pmesh
    from tpu_paxos.parallel import sharded as psharded

    quorum = n_nodes // 2 + 1
    mesh = pmesh.make_instance_mesh(
        dcn_hosts=int(os.environ.get("TPU_PAXOS_BENCH_DCN_HOSTS", "1"))
    )
    axes = pmesh.instance_axes(mesh)
    n_inst -= n_inst % mesh.size
    vids0 = pmesh.shard_instances(mesh, jnp.arange(n_inst, dtype=jnp.int32))
    state = psharded.init_sharded_state(mesh, n_inst, n_nodes)

    def _local(st, v):
        st, local_counts = _steady_state_windows(
            st, v, reps=reps, quorum=quorum, span=n_inst
        )
        return st, jax.lax.psum(local_counts, axes)

    body = pmesh.shard_map(
        _local,
        mesh,
        in_specs=(psharded._state_specs(axes), P(axes)),
        out_specs=(psharded._state_specs(axes), P(None)),
    )
    step = jax.jit(body, donate_argnums=(0,) if donate else ())
    return mesh, step, state, vids0, n_inst


def _sim_record(final, dt: float, n_instances: int, config: dict) -> dict:
    """Record dict for a general-engine run — shared by the local and
    sharded sim benches."""
    import numpy as np

    chosen = np.asarray(final.met.chosen_vid)
    r2c = np.asarray(final.met.chosen_round)[chosen != -1]
    return {
        "engine": "sim",
        "metric": "paxos_instances_per_sec_to_chosen",
        "value": round(n_instances / dt, 1),
        "unit": "instances/sec",
        "done": bool(final.done),
        "rounds": int(final.t),
        "rounds_to_chosen": (
            {
                "p50": int(np.percentile(r2c, 50)),
                "p90": int(np.percentile(r2c, 90)),
                "max": int(r2c.max()),
            }
            if r2c.size
            else None  # nothing chosen within max_rounds
        ),
        "config": config,
    }


def bench_sim_record() -> dict:
    """Secondary record: the GENERAL engine (full protocol ladder —
    retries, faults, dueling proposers, hole fill, conflict re-proposal)
    at I >= 100k under the debug.conf.sample fault rates, with the
    rounds-to-chosen distribution (BASELINE config 3 at size)."""
    import numpy as np

    from tpu_paxos.config import FaultConfig, SimConfig
    from tpu_paxos.core import sim as simm
    from tpu_paxos.utils import prng

    i = int(os.environ.get("TPU_PAXOS_BENCH_SIM_INSTANCES", 1 << 23))
    cfg = SimConfig(
        n_nodes=5,
        n_instances=i,
        proposers=(0, 1),
        seed=0,
        # wide first-fit window: assignment is W vids/proposer/round at
        # O(W) cost — window reads/writes are contiguous dynamic
        # slices and the requeue compaction is cond-guarded, so a 1M
        # window costs rounds nothing when idle and keeps the round
        # count flat (~28) as I scales
        assign_window=max(256, min(1 << 20, i // 8)),
        max_rounds=20_000,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    workload = simm.default_workload(cfg)
    pend, gate, tail, c = simm.prepare_queues(cfg, workload)
    root = prng.root_key(cfg.seed)
    state0 = simm.init_state(cfg, pend, gate, tail, root)
    round_fn = simm.build_engine(cfg, c)

    @jax.jit
    def go(root, st):
        def cond(s):
            return (~s.done) & (s.t < cfg.max_rounds)

        def body(s):
            return round_fn(root, s)

        return jax.lax.while_loop(cond, body, st)

    config = {
        "n_nodes": 5,
        "n_instances": i,
        "proposers": 2,
        "faults": "drop500/dup1000/delay0-2",
        "sharded": False,
        "devices": 1,
        "platform": jax.devices()[0].platform,
    }
    return _timed_sim_runs(
        go, lambda k: prng.root_key(cfg.seed + k), state0, i, config
    )


def _timed_sim_runs(go, root_for, state0, n_instances: int, config: dict) -> dict:
    """Artifact-proof timing for a general-engine run (VERDICT r4 #1):
    every timed call runs a genuinely different computation (fresh prng
    root — BENCH_r04's 22B inst/s artifact came from re-invoking with
    identical args), the clock stops only after a chosen-count scalar
    computed from the full per-instance result inside the same jitted
    call has crossed to the host, the median of three runs is the
    record, and a roofline guard withholds any physically impossible
    number (raw timings are reported either way).  The full arrays for
    the rounds-to-chosen stats transfer after the clock stops: the
    axon tunnel moves ~14 MB/s, so an in-clock 32 MB transfer would
    bill ~2.3 s of host I/O to the engine."""
    import types

    @jax.jit
    def go_counted(root, st):
        f = go(root, st)
        return f, jnp.sum(f.met.chosen_vid != val.NONE)

    # Warm with a root OUTSIDE the timed range — a timed call with
    # byte-identical args to the warmup is the exact artifact
    # precondition this function exists to avoid.
    final, nc = go_counted(root_for(3), state0)
    warm_count = int(nc)  # compile + warm run, materialized through the count
    final = None
    runs, counts = [], []
    for k in range(3):
        t0 = time.perf_counter()
        f, nc = go_counted(root_for(k), state0)
        nc = int(nc)  # blocks on a value derived from every instance
        dtk = time.perf_counter() - t0
        # Keep only what the record needs; the full SimState (several
        # GiB at bench sizes) frees before the next run.
        runs.append(
            (
                dtk,
                types.SimpleNamespace(met=f.met, t=int(f.t), done=bool(f.done)),
                nc,
            )
        )
        del f
        counts.append(nc)
    dts = sorted(dt for dt, _, _ in runs)
    dt, final, nc_med = min(runs, key=lambda r: abs(r[0] - dts[1]))  # median
    raw = [round(x, 4) for x in dts]
    # value = n_instances/dt is only meaningful when the selected run
    # actually resolved the same work as the warmup; a seed that hit
    # max_rounds part-done must not publish an overstated number —
    # report the timings without a value instead.
    if nc_med != warm_count or not final.done:
        return {
            "engine": "sim",
            "error": (
                f"median run chose {nc_med} instances "
                f"(done={final.done}), warmup chose {warm_count}; "
                "value withheld"
            ),
            "raw_timings_s": raw,
            "chosen_counts": {"warmup": warm_count, "timed": counts},
            "config": config,
        }
    # Each engine round must stream the whole carried state through HBM
    # at least once — the floor for the bandwidth the timing implies.
    refusal = _implausible(
        _state_nbytes(state0) * int(final.t), dt, config.get("devices", 1)
    )
    if refusal is not None:
        return {"engine": "sim", "error": refusal, "raw_timings_s": raw,
                "config": config}
    rec = _sim_record(final, dt, n_instances, config)
    rec["raw_timings_s"] = raw
    # A non-median seed diverging is still worth surfacing, flagged.
    if any(c != warm_count for c in counts):
        rec["chosen_counts"] = {"warmup": warm_count, "timed": counts}
    return rec


class KernelDivergence(RuntimeError):
    """The pallas kernel produced different state than the XLA scan —
    a wrong-answer bug, not an availability problem; never silently
    fall back from it."""


def check_fused_equivalence(n_nodes: int = 5, reps: int = 2) -> None:
    """On-device CONTENT equivalence of the pallas window kernel vs the
    XLA scan path: full acc_ballot/acc_vid/learned arrays, not just
    chosen counts (a content-corrupting kernel bug that preserved
    counts would otherwise pass).  Runs at a small I on whatever
    backend is active — bench warmup calls it on the real TPU before
    every fused headline; tests/test_fastwin.py covers the CPU
    interpreter and (opt-in) the real chip."""
    import numpy as np

    from tpu_paxos.core import fastwin

    i = 2 * fastwin.TILE
    quorum = n_nodes // 2 + 1
    vids0 = jnp.arange(i, dtype=jnp.int32)
    ref_step = jax.jit(
        functools.partial(_steady_state_windows, reps=reps, quorum=quorum)
    )
    st_ref, cnt_ref = ref_step(fast.init_state(i, n_nodes), vids0)
    # iota_vids synthesizes the same arange workload — the variant the
    # headline actually runs.
    st_new, cnt = fastwin.steady_state_windows_fused(
        fast.init_state(i, n_nodes), None, reps=reps, quorum=quorum,
        iota_vids=True,
    )
    if _total(cnt) != _total(cnt_ref):
        raise KernelDivergence(
            f"fused kernel chose {_total(cnt)}, scan chose {_total(cnt_ref)}"
        )
    for name in ("acc_ballot", "acc_vid", "learned"):
        a = np.asarray(getattr(st_ref, name))
        b = np.asarray(getattr(st_new, name))
        if not (a == b).all():
            bad = int((a != b).sum())
            raise KernelDivergence(
                f"fused kernel diverges from the XLA scan on {name} "
                f"({bad} of {a.size} cells)"
            )


def _fleet_record(dts, state_bytes, rounds_min, n_lanes, n_dev, config):
    """Record-or-error for a fleet timing set — pure, so
    tests/test_bench_guards.py drives it with synthetic timings.  The
    roofline floor: every engine round streams the whole stacked lane
    state through memory at least once, and the batched while-loop
    runs at least the FASTEST lane's round count, so
    ``state_bytes * rounds_min`` bytes is a hard lower bound on the
    traffic the timing implies.  Implausible medians withhold the
    value (an error record with raw timings), per the headline's
    conventions — a roofline-clamped number is never published."""
    dt = sorted(dts)[1]
    raw = [round(x, 4) for x in sorted(dts)]
    refusal = _implausible(state_bytes * max(rounds_min, 1), dt, n_dev)
    if refusal is not None:
        return {"engine": "fleet", "error": refusal, "raw_timings_s": raw,
                "config": config}
    return {
        "engine": "fleet",
        "metric": "fleet_lanes_per_sec_to_verdict",
        "value": round(n_lanes / dt, 2),
        "unit": "lanes/sec",
        "raw_timings_s": raw,
        "config": config,
    }


def bench_fleet_record() -> dict:
    """Secondary record: the FLEET runner (device-batched general
    engine + on-device verdicts, tpu_paxos/fleet/) at a fixed lane
    count — lanes/sec TO VERDICT, i.e. the clock stops when the
    [lanes] verdict vector reaches the host (the dispatch's one
    mandatory transfer), not when per-lane states do.  Lanes carry
    grammar-sampled episode schedules (the search workload) AND a
    heterogeneous per-lane i.i.d. knob mix cycling through the stress
    sweep's rate profiles — the one-executable envelope under its
    production shape.  The cold first dispatch (compile included) is
    reported alongside so the record shows what the envelope cache
    amortizes; the roofline guard judges the steady-state value only
    (_fleet_record)."""
    import numpy as np

    from tpu_paxos.config import FaultConfig, SimConfig
    from tpu_paxos.core import sim as simm
    from tpu_paxos.fleet import runner as frun
    from tpu_paxos.fleet import search as fsearch
    from tpu_paxos.harness import stress as strs
    from tpu_paxos.utils import prng

    on_tpu = jax.devices()[0].platform == "tpu"
    n_lanes = int(
        os.environ.get("TPU_PAXOS_BENCH_FLEET_LANES", 64 if on_tpu else 8)
    )
    wl_rng = np.random.default_rng(0)
    workload, gates, _chains = strs._workload(2, wl_rng)
    cfg = SimConfig(
        n_nodes=5,
        n_instances=2 * sum(len(w) for w in workload),
        proposers=(0, 1),
        seed=0,
        max_rounds=20_000,
        # envelope ring bound 8 (fleet/envelope.MAX_DELAY_BOUND): the
        # delay spread below exercises it to the ring edge (max_delay 8)
        faults=FaultConfig(drop_rate=300, dup_rate=500, max_delay=8),
    )
    runner = frun.FleetRunner(cfg, workload, gates)
    # heterogeneous per-lane knobs, delays capped at the baseline's 2:
    # lanes/sec-to-verdict is rounds-to-converge in disguise, and the
    # delay knob multiplies rounds (a delay-6 lane runs ~3x the
    # rounds of a delay-2 lane; the batched while-loop runs to the
    # slowest lane) — so the headline mix varies the drop/dup rates
    # like the stress sweep's profiles while staying
    # round-count-comparable to the homogeneous baseline record.  The
    # full delay spread is timed separately below, on the SAME
    # executable (that it needs no recompile is the envelope's point).
    knob_mixes = [
        FaultConfig(),
        FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
        FaultConfig(drop_rate=2000, dup_rate=500, max_delay=2),
        FaultConfig(drop_rate=1000, dup_rate=2000, max_delay=2),
    ]
    lane_knobs = [knob_mixes[i % len(knob_mixes)] for i in range(n_lanes)]
    # the envelope's delay dimension, exercised to the ring edge
    delay_mixes = [
        FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
        FaultConfig(drop_rate=2000, dup_rate=500, max_delay=4),
        FaultConfig(drop_rate=200, dup_rate=200, min_delay=1, max_delay=6),
        FaultConfig(drop_rate=300, dup_rate=500, max_delay=8),
    ]
    delay_knobs = [delay_mixes[i % len(delay_mixes)] for i in range(n_lanes)]
    sched_rng = np.random.default_rng(1)
    schedules = [
        fsearch.sample_schedule(sched_rng, cfg.n_nodes, 4, 96)
        for _ in range(n_lanes)
    ]
    pend, gate, tail = runner._tmpl
    state_bytes = n_lanes * _state_nbytes(
        simm.init_state(cfg, pend, gate, tail, prng.root_key(0))
    )
    # cold generation: the first dispatch pays the envelope's one
    # compile (seeds OUTSIDE the timed steady range, same artifact
    # discipline as _timed_sim_runs)
    rep = runner.run(
        [10_000 + i for i in range(n_lanes)], schedules, knobs=lane_knobs
    )
    cold_seconds = rep.seconds
    n_red_warm = len(rep.failing)
    dts, rounds_min = [], 1 << 30
    for k in range(3):
        rep = runner.run(
            [k * n_lanes + i for i in range(n_lanes)], schedules,
            knobs=lane_knobs,
        )
        dts.append(rep.seconds)  # verdict transfer is the blocking sync
        rounds_min = min(rounds_min, int(rep.verdict.rounds.min()))
    # delay-spread set: same compiled executable (no warmup dispatch
    # needed), lanes spanning the whole delay envelope up to the ring
    # edge — slower lanes/sec because slow-delay lanes RUN more
    # rounds, not because the envelope costs compile or per-round time
    delay_dts, delay_rounds_max = [], 0
    for k in range(2):
        rep = runner.run(
            [50_000 + k * n_lanes + i for i in range(n_lanes)], schedules,
            knobs=delay_knobs,
        )
        delay_dts.append(rep.seconds)
        delay_rounds_max = max(delay_rounds_max, int(rep.verdict.rounds.max()))
    # flight-recorder overhead: the telemetry-armed twin of the same
    # envelope (telemetry/recorder.py rides the lane carry, summaries
    # reduced on device) on the headline mix — same seeds, schedules,
    # and knobs, so the delta IS the recorder.  Its own compile (the
    # armed engine is a different traced program) stays outside the
    # timed range, like the cold dispatch above.
    trunner = frun.FleetRunner(cfg, workload, gates, telemetry=True)
    trunner.run(
        [10_000 + i for i in range(n_lanes)], schedules, knobs=lane_knobs
    )
    tele_dts = []
    for k in range(3):
        rep = trunner.run(
            [k * n_lanes + i for i in range(n_lanes)], schedules,
            knobs=lane_knobs,
        )
        tele_dts.append(rep.seconds)
    config = {
        "n_nodes": cfg.n_nodes,
        "n_instances": cfg.n_instances,
        "lanes": n_lanes,
        "schedules": "grammar-sampled, <=4 episodes, horizon 96",
        "knobs": "heterogeneous per-lane: clean / drop500-dup1000-d2 "
                 "/ drop2000-dup500-d2 / drop1000-dup2000-d2 (cycled)",
        "delay_ring_bound": cfg.faults.max_delay,
        "cold_seconds": round(cold_seconds, 4),
        "cold_lanes_per_sec": round(n_lanes / max(cold_seconds, 1e-9), 2),
        "delay_spread_knobs": "d2 / d4 / d1-6 / d8 (ring edge), same "
                              "executable, zero extra compiles",
        "delay_spread_raw_s": [round(x, 4) for x in sorted(delay_dts)],
        "delay_spread_lanes_per_sec": round(
            n_lanes / max(max(delay_dts), 1e-9), 2
        ),
        "delay_spread_rounds_max": delay_rounds_max,
        "telemetry_raw_s": [round(x, 4) for x in sorted(tele_dts)],
        # same median-of-3 convention as the recorder-free headline,
        # so (value - telemetry_lanes_per_sec) reads as the
        # recorder's whole cost
        "telemetry_lanes_per_sec": round(
            n_lanes / max(sorted(tele_dts)[1], 1e-9), 2
        ),
        "red_lanes_warmup": n_red_warm,
        "devices": 1,
        "platform": jax.devices()[0].platform,
    }
    return _fleet_record(dts, state_bytes, rounds_min, n_lanes, 1, config)


def _geo_record(
    preset_dts: dict,
    state_bytes: int,
    rounds_min: int,
    n_lanes: int,
    n_dev: int,
    warm_compiles: int,
    parity_failures: list,
    config: dict,
) -> dict:
    """Record-or-error for the geo-envelope timing sets — pure, so
    tests/test_bench_guards.py drives it with synthetic inputs.
    Three withhold conditions, per the BENCH conventions (a clamped
    or unproven number is never published):

    - roofline: every engine round streams the stacked lane state at
      least once, so ``state_bytes * rounds_min`` bounds the traffic
      any preset's median timing implies;
    - one-executable claim: the record's POINT is that every WAN
      preset rides one envelope executable, so any warm compile
      after the first preset withholds the whole record (the number
      would be real but the headline claim false);
    - parity: scalar-knob runs must be bit-identical to their
      uniform-matrix twins, and each preset's fleet lane 0 must
      decision-log-match its single-run compile-time replay — a
      mismatch means the matrix path forked the model and the record
      is withheld naming the failures.
    """
    raws = {
        name: [round(x, 4) for x in sorted(dts)]
        for name, dts in preset_dts.items()
    }
    if parity_failures:
        return {
            "engine": "geo",
            "error": "parity withheld: " + "; ".join(parity_failures),
            "raw_timings_s": raws,
            "config": config,
        }
    if warm_compiles:
        return {
            "engine": "geo",
            "error": (
                f"{warm_compiles} warm compile(s) after the first "
                "preset — the one-envelope-executable claim does not "
                "hold; record withheld"
            ),
            "raw_timings_s": raws,
            "config": config,
        }
    values = {}
    for name, dts in preset_dts.items():
        dt = sorted(dts)[len(dts) // 2]
        refusal = _implausible(state_bytes * max(rounds_min, 1), dt, n_dev)
        if refusal is not None:
            return {
                "engine": "geo",
                "error": f"{name} timing: {refusal}",
                "raw_timings_s": raws,
                "config": config,
            }
        values[name] = round(n_lanes / dt, 2)
    return {
        "engine": "geo",
        "metric": "geo_fleet_lanes_per_sec_to_verdict",
        "value": values,
        "unit": "lanes/sec",
        "warm_compiles_across_presets": int(warm_compiles),
        "raw_timings_s": raws,
        "config": config,
    }


_GEO_CENSUS = None


def bench_geo_record() -> dict:
    """Secondary record: WAN topology presets (core/wan.py) on fleet
    lanes — per-edge [A, A] drop/latency matrices plus gray-failure
    schedules, every preset normalized to matrix knobs and dispatched
    through ONE compiled envelope executable (the matrix model's
    whole point: a WAN topology is runtime data, not a compile).  The
    guard path (:func:`_geo_record`) withholds the record unless the
    presets share the executable (zero warm compiles after the
    first), the scalar<->uniform-matrix sha parity holds, and each
    preset's fleet lane replays decision-log-identically single-run."""
    import numpy as np

    from tpu_paxos.analysis import tracecount
    from tpu_paxos.config import EdgeFaultConfig, FaultConfig, SimConfig
    from tpu_paxos.core import sim as simm
    from tpu_paxos.core import wan
    from tpu_paxos.fleet import runner as frun
    from tpu_paxos.harness import shrink as shr
    from tpu_paxos.harness import stress as strs
    from tpu_paxos.utils import prng

    on_tpu = jax.devices()[0].platform == "tpu"
    n_lanes = int(
        os.environ.get("TPU_PAXOS_BENCH_GEO_LANES", 64 if on_tpu else 8)
    )
    wl_rng = np.random.default_rng(0)
    workload, gates, chains = strs._workload(2, wl_rng)
    bound = wan.PRESET_DELAY_BOUND
    cfg = SimConfig(
        n_nodes=5,
        n_instances=2 * sum(len(w) for w in workload),
        proposers=(0, 1),
        seed=0,
        max_rounds=20_000,
        faults=FaultConfig(max_delay=bound),
    )
    runner = frun.FleetRunner(cfg, workload, gates)
    presets = {
        "wan-3region": (wan.WAN3, strs.SCHED_WAN_GRAY),
        "wan-5region": (wan.WAN5, strs.SCHED_WAN5_GRAY),
    }
    state_bytes = n_lanes * _state_nbytes(
        simm.init_state(cfg, *runner._tmpl, prng.root_key(0))
    )
    # jax.monitoring has no listener removal: reuse one module-level
    # census across calls (the stress sweep's singleton discipline)
    global _GEO_CENSUS
    if _GEO_CENSUS is None:
        _GEO_CENSUS = tracecount.CompileCensus()
    census = _GEO_CENSUS.start()
    parity_failures: list[str] = []
    preset_dts: dict[str, list] = {}
    rounds_min = 1 << 30
    warm = 0
    try:
        first = True
        for name, (preset, sched) in presets.items():
            knobs = [wan.wan_fault_config(preset, cfg.n_nodes)] * n_lanes
            schedules = [sched] * n_lanes
            before = census.engine_counts.get("fleet", 0)
            # cold dispatch: the FIRST preset pays the envelope's one
            # compile (seeds outside the timed range); later presets
            # must pay zero
            rep = runner.run(
                [10_000 + i for i in range(n_lanes)], schedules,
                knobs=knobs,
            )
            compiled = census.engine_counts.get("fleet", 0) - before
            if not first:
                warm += compiled
            first = False
            # parity guard 1: the lane's single-run compile-time
            # replay (matrix constants + compiled gray tables) must
            # decision-log-match the fleet lane
            case = shr.ReproCase(
                cfg=rep.lane_cfg(0), workload=workload, gates=gates,
                chains=chains,
            )
            single = simm.run(case.cfg, workload, gates)
            lane0 = rep.lane_result(0)
            if shr.decision_log_text(case, single) != shr.decision_log_text(
                case, lane0
            ):
                parity_failures.append(
                    f"{name}: fleet lane 0 != single-run replay"
                )
            dts = []
            for k in range(3):
                rep = runner.run(
                    [k * n_lanes + i for i in range(n_lanes)], schedules,
                    knobs=knobs,
                )
                dts.append(rep.seconds)
                rounds_min = min(rounds_min, int(rep.verdict.rounds.min()))
            preset_dts[name] = dts
        # parity guard 2: scalar knobs == uniform [A, A] matrix,
        # bit-identical (the exact-at-zero contract extended to
        # matrices).  The scalar side runs the COMPILE-TIME scalar
        # path single-run — two fleet lanes would both normalize to
        # the same matrix and compare a value with itself (a dead
        # guard, caught in review); this crosses the real seam.
        scalar_fc = FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2)
        uniform_fc = FaultConfig(
            max_delay=2,
            edges=EdgeFaultConfig.uniform(
                cfg.n_nodes, drop_rate=500, dup_rate=1000, max_delay=2
            ),
        )
        rep = runner.run([77], [None], knobs=[uniform_fc])
        r_u = rep.lane_result(0)
        scalar_cfg = dataclasses.replace(
            rep.cfg, seed=77, faults=scalar_fc
        )
        r_s = simm.run(scalar_cfg, workload, gates)
        case = shr.ReproCase(
            cfg=scalar_cfg, workload=workload, gates=gates,
            chains=chains,
        )
        if shr.decision_log_text(case, r_s) != shr.decision_log_text(
            case, r_u
        ) or not (r_s.chosen_round == r_u.chosen_round).all():
            parity_failures.append(
                "scalar single run != uniform-matrix fleet lane "
                "(sha parity)"
            )
    finally:
        census.stop()
    config = {
        "n_nodes": cfg.n_nodes,
        "n_instances": cfg.n_instances,
        "lanes": n_lanes,
        "delay_ring_bound": bound,
        "presets": {
            name: {
                "regions": list(p.regions),
                "schedule": "gray + cut episodes (stress WAN mixes)",
            }
            for name, (p, _s) in presets.items()
        },
        "devices": 1,
        "platform": jax.devices()[0].platform,
    }
    return _geo_record(
        preset_dts, state_bytes, rounds_min, n_lanes, 1, warm,
        parity_failures, config,
    )


def _envelope_record(
    geom_dts: dict,
    geom_bytes: dict,
    rounds_min: int,
    n_lanes: int,
    n_dev: int,
    warm_compiles: int,
    executables_before: int,
    parity_failures: list,
    unconverged: list,
    config: dict,
) -> dict:
    """Record-or-error for the geometry-padded envelope sweep — pure,
    so tests/test_bench_guards.py drives it with synthetic inputs.
    ``geom_dts[name]`` holds ``{"padded": [...], "unpadded": [...]}``
    timing sets per true geometry; ``geom_bytes`` the matching
    per-variant stacked-state sizes.  Four withhold conditions, per
    the BENCH conventions (a clamped or unproven number is never
    published):

    - parity: every padded dispatch must be decision-log-identical to
      its bound-free twin per (cfg, schedule, seed) — a mismatch
      means padding forked the model and the record is withheld
      naming the failures;
    - to-verdict: the metric is lanes/sec TO VERDICT, so any timed
      lane that hits max_rounds without one makes the timing a
      measurement of the round cap, not the protocol — withheld
      naming the cells;
    - one-executable claim: the record's POINT is that the whole
      (geometry x protocol-knob x rate) grid rides one padded
      executable, so any warm compile after the first dispatch
      withholds the whole record (the toll numbers would be real but
      the headline claim false);
    - roofline: every engine round streams the stacked lane state at
      least once, so ``geom_bytes * rounds_min`` bounds the traffic
      any cell's median timing implies.
    """
    raws = {
        name: {v: [round(x, 4) for x in sorted(dts)]
               for v, dts in variants.items()}
        for name, variants in geom_dts.items()
    }
    if parity_failures:
        return {
            "engine": "envelope",
            "error": "parity withheld: " + "; ".join(parity_failures),
            "raw_timings_s": raws,
            "config": config,
        }
    if unconverged:
        return {
            "engine": "envelope",
            "error": "to-verdict withheld: " + "; ".join(unconverged),
            "raw_timings_s": raws,
            "config": config,
        }
    if warm_compiles:
        return {
            "engine": "envelope",
            "error": (
                f"{warm_compiles} warm compile(s) after the first "
                "dispatch — the one-padded-executable claim does not "
                "hold across the grid; record withheld"
            ),
            "raw_timings_s": raws,
            "config": config,
        }
    values = {}
    for name, variants in geom_dts.items():
        entry = {}
        for variant, dts in variants.items():
            dt = sorted(dts)[len(dts) // 2]
            refusal = _implausible(
                geom_bytes[name][variant] * max(rounds_min, 1), dt, n_dev
            )
            if refusal is not None:
                return {
                    "engine": "envelope",
                    "error": f"{name}/{variant} timing: {refusal}",
                    "raw_timings_s": raws,
                    "config": config,
                }
            entry[f"{variant}_lanes_per_sec"] = round(n_lanes / dt, 2)
        pad = entry.get("padded_lanes_per_sec")
        true = entry.get("unpadded_lanes_per_sec")
        if pad and true:
            entry["padding_toll_pct"] = round((true / pad - 1.0) * 100, 1)
        values[name] = entry
    return {
        "engine": "envelope",
        "metric": "envelope_fleet_lanes_per_sec_to_verdict",
        "value": values,
        "unit": "lanes/sec",
        "executables_before": int(executables_before),
        "executables_after": 1,
        "warm_compiles_in_sweep": int(warm_compiles),
        "raw_timings_s": raws,
        "config": config,
    }


_ENVELOPE_CENSUS = None


def bench_envelope_record() -> dict:
    """Secondary record: the geometry-padded envelope (core/geom.py)
    on fleet lanes — a (geometry 3/5/7 x protocol-knob grid x rate)
    sweep where the bound-free world compiles one executable per
    (geometry, protocol) combo and the padded world compiles ONCE,
    then serves every cell as a warm dispatch (geometry, protocol
    knobs, and fault rates are all runtime data).  The guard path
    (:func:`_envelope_record`) withholds the record unless the padded
    executable really is shared (zero warm compiles after the first
    dispatch, counted live by the compile census) and every timed
    padded dispatch is decision-log-identical to its bound-free twin.
    The published value is the padding toll: lanes/sec at each TRUE
    geometry, padded vs unpadded."""
    import hashlib

    import numpy as np

    from tpu_paxos.analysis import tracecount
    from tpu_paxos.config import FaultConfig, ProtocolConfig, SimConfig
    from tpu_paxos.core import geom as geo
    from tpu_paxos.core import sim as simm
    from tpu_paxos.fleet import runner as frun
    from tpu_paxos.replay.decision_log import decision_log
    from tpu_paxos.utils import prng

    on_tpu = jax.devices()[0].platform == "tpu"
    n_lanes = int(
        os.environ.get("TPU_PAXOS_BENCH_ENVELOPE_LANES", 64 if on_tpu else 8)
    )
    genv = geo.GeometryEnvelope(
        menu=((3, (0,)), (5, (0, 1)), (7, (0, 1, 2)))
    )
    tmpl = [
        np.arange(100, 108, dtype=np.int32),
        np.arange(200, 208, dtype=np.int32),
        np.arange(300, 308, dtype=np.int32),
    ]
    geoms = {3: (0,), 5: (0, 1), 7: (0, 1, 2)}
    protocols = [
        ProtocolConfig(),
        ProtocolConfig(
            prepare_delay_min=1, prepare_delay_max=6,
            prepare_retry_count=2, prepare_retry_timeout=3,
            accept_retry_count=2, accept_retry_timeout=3,
            commit_retry_timeout=3,
        ),
    ]
    rates = [
        FaultConfig(max_delay=2),
        FaultConfig(drop_rate=500, dup_rate=500, max_delay=2),
    ]

    # instances must cover the template's full value count (the
    # 3-proposer bound proposes 24 values) or the 7-node cells can
    # never reach a verdict
    n_inst = 2 * sum(len(w) for w in tmpl)

    def _cfg(n, props, pc):
        return SimConfig(
            n_nodes=n, n_instances=n_inst, proposers=props, seed=0,
            max_rounds=4000, faults=FaultConfig(max_delay=2), protocol=pc,
        )

    def _sha(r):
        stride = int(max(int(np.max(w)) for w in tmpl)) + 1
        text = decision_log(
            r.chosen_vid, r.chosen_ballot, stride=stride,
            n_instances=len(r.chosen_vid),
        )
        return hashlib.sha256(text.encode()).hexdigest()

    # jax.monitoring has no listener removal: module-level singleton
    global _ENVELOPE_CENSUS
    if _ENVELOPE_CENSUS is None:
        _ENVELOPE_CENSUS = tracecount.CompileCensus()
    census = _ENVELOPE_CENSUS.start()
    parity_failures: list[str] = []
    unconverged: list[str] = []
    geom_dts: dict[str, dict] = {}
    geom_bytes: dict[str, dict] = {}
    true_reps: dict[int, object] = {}
    rounds_min = 1 << 30
    warm = 0
    executables_before = 0
    timed_fc = rates[1]
    try:
        # ---- BEFORE: one bound-free executable per (geometry,
        # protocol) combo.  Rates were ALREADY runtime knobs, so the
        # rate axis never multiplied executables; geometry and
        # protocol did — count the combos that pay a compile.
        for n, props in geoms.items():
            wl = tmpl[: len(props)]
            for pi, pc in enumerate(protocols):
                runner = frun.FleetRunner(_cfg(n, props, pc), wl)
                before = census.engine_counts.get("fleet", 0)
                runner.run(
                    [10_000 + i for i in range(n_lanes)],
                    [None] * n_lanes, knobs=[rates[0]] * n_lanes,
                )
                if census.engine_counts.get("fleet", 0) > before:
                    executables_before += 1
                if pi == 0:
                    name = f"{n}-node"
                    geom_bytes[name] = {
                        "unpadded": n_lanes * _state_nbytes(
                            simm.init_state(
                                runner.cfg, *runner._tmpl,
                                prng.root_key(0),
                            )
                        ),
                    }
                    dts = []
                    for k in range(3):
                        rep = runner.run(
                            [k * n_lanes + i for i in range(n_lanes)],
                            [None] * n_lanes,
                            knobs=[timed_fc] * n_lanes,
                        )
                        dts.append(rep.seconds)
                        rounds_min = min(
                            rounds_min, int(rep.verdict.rounds.min())
                        )
                        bad = int((~np.asarray(rep.verdict.ok)).sum())
                        if bad:
                            unconverged.append(
                                f"{name}/unpadded rep {k}: {bad} "
                                "lane(s) without a verdict"
                            )
                        if k == 0:
                            true_reps[n] = rep
                    geom_dts[name] = {"unpadded": dts}
        # ---- AFTER: ONE padded runner serves the whole grid.  The
        # first dispatch pays the envelope's compile (seeds outside
        # the timed range); every later cell must be warm.
        bcfg = genv.bound_cfg(_cfg(3, (0,), protocols[0]))
        padded = frun.FleetRunner(bcfg, tmpl, geometry=genv)
        first = True
        for n, props in geoms.items():
            wl = tmpl[: len(props)]
            for pc in protocols:
                for fc in rates:
                    before = census.engine_counts.get("fleet", 0)
                    padded.run(
                        [10_000 + i for i in range(n_lanes)],
                        [None] * n_lanes,
                        workloads=[(wl, None)] * n_lanes,
                        knobs=[fc] * n_lanes, protocol=pc,
                        geometry=(n, props),
                    )
                    compiled = (
                        census.engine_counts.get("fleet", 0) - before
                    )
                    if not first:
                        warm += compiled
                    first = False
        gm = geo.geometry_for(genv, bcfg.n_nodes, bcfg.proposers)
        pkn = geo.protocol_knobs(
            protocols[0], stall_patience=simm.IDLE_RESTART_ROUNDS
        )
        pad_bytes = n_lanes * _state_nbytes(
            simm.init_state(
                bcfg, *padded._tmpl, prng.root_key(0),
                geometry=genv, geom=gm, pknobs=pkn,
            )
        )
        # timed padded dispatches (warm by now — deltas still count)
        for n, props in geoms.items():
            name = f"{n}-node"
            wl = tmpl[: len(props)]
            geom_bytes[name]["padded"] = pad_bytes
            dts = []
            before = census.engine_counts.get("fleet", 0)
            for k in range(3):
                rep = padded.run(
                    [k * n_lanes + i for i in range(n_lanes)],
                    [None] * n_lanes,
                    workloads=[(wl, None)] * n_lanes,
                    knobs=[timed_fc] * n_lanes, protocol=protocols[0],
                    geometry=(n, props),
                )
                dts.append(rep.seconds)
                rounds_min = min(rounds_min, int(rep.verdict.rounds.min()))
                bad = int((~np.asarray(rep.verdict.ok)).sum())
                if bad:
                    unconverged.append(
                        f"{name}/padded rep {k}: {bad} lane(s) "
                        "without a verdict"
                    )
                if k == 0:
                    # parity guard: the padded dispatch must be
                    # decision-log-identical to the bound-free twin
                    # of the same (cfg, schedule, seed), every lane
                    rt = true_reps[n]
                    for i in range(n_lanes):
                        a = rt.lane_result(i)
                        b = rep.lane_result(i)
                        if (
                            _sha(a) != _sha(b)
                            or a.rounds != b.rounds
                            or not (a.chosen_round == b.chosen_round).all()
                        ):
                            parity_failures.append(
                                f"{name} lane {i}: padded dispatch != "
                                "bound-free twin"
                            )
                            break
            warm += census.engine_counts.get("fleet", 0) - before
            geom_dts[name]["padded"] = dts
    finally:
        census.stop()
    config = {
        "bound": {"n_nodes": bcfg.n_nodes, "proposers": len(bcfg.proposers)},
        "menu": [[n, len(p)] for n, p in genv.menu],
        "n_instances": bcfg.n_instances,
        "lanes": n_lanes,
        "protocol_grid": len(protocols),
        "rate_grid": len(rates),
        "grid_cells": len(geoms) * len(protocols) * len(rates),
        "devices": 1,
        "platform": jax.devices()[0].platform,
    }
    return _envelope_record(
        geom_dts, geom_bytes, rounds_min, n_lanes, 1, warm,
        executables_before, parity_failures, unconverged, config,
    )


def _serve_record(
    pipe_walls,
    seq_walls,
    state_bytes,
    rounds_min,
    n_decided,
    points,
    knee,
    p99_pipe,
    p99_seq,
    config,
    windowed=None,
):
    """Record-or-error for a serve timing pair — pure, so
    tests/test_bench_guards.py drives it with synthetic timings.
    Roofline floor: every engine round streams the loop state through
    memory at least once, and both dispatch modes run at least
    ``rounds_min`` rounds, so ``state_bytes * rounds_min`` bounds the
    traffic EITHER timing implies; an implausible median on either
    side withholds the record (raw timings kept) — a roofline-clamped
    number is never published.  The overlap claim is only meaningful
    at equal latency, so a p99 mismatch between the modes (the
    trajectories are bit-identical by construction — a mismatch means
    the harness broke) also withholds the record."""
    dt_pipe = sorted(pipe_walls)[len(pipe_walls) // 2]
    dt_seq = sorted(seq_walls)[len(seq_walls) // 2]
    raw_p = [round(x, 4) for x in sorted(pipe_walls)]
    raw_s = [round(x, 4) for x in sorted(seq_walls)]
    devices = config.get("devices", 1)
    for label, dt in (("pipelined", dt_pipe), ("sequential", dt_seq)):
        refusal = _implausible(state_bytes * max(rounds_min, 1), dt, devices)
        if refusal is not None:
            return {
                "engine": "serve",
                "error": f"{label} timing: {refusal}",
                "raw_timings_s": raw_p,
                "sequential_raw_s": raw_s,
                "config": config,
            }
    if p99_pipe != p99_seq:
        return {
            "engine": "serve",
            "error": (
                f"p99 mismatch between dispatch modes ({p99_pipe} vs "
                f"{p99_seq}); the modes must run identical "
                "trajectories — overlap speedup withheld"
            ),
            "raw_timings_s": raw_p,
            "sequential_raw_s": raw_s,
            "config": config,
        }
    return {
        "engine": "serve",
        "metric": "serve_sustained_values_per_sec",
        "value": round(n_decided / dt_pipe, 1),
        "unit": "values/sec",
        "raw_timings_s": raw_p,
        **({"windowed": windowed} if windowed is not None else {}),
        "overlap": {
            # same offered rate, same seed, bit-identical trajectory:
            # the speedup is pure dispatch-overhead hiding at exactly
            # equal p50/p99/p999
            "sequential_values_per_sec": round(n_decided / dt_seq, 1),
            "sequential_raw_s": raw_s,
            "speedup": round(dt_seq / dt_pipe, 2),
            "p99_rounds": p99_pipe,
        },
        "latency_at_load": points,
        "knee": knee,
        "config": config,
    }


def bench_serve_record() -> dict:
    """Secondary record: the OPEN-LOOP SERVING harness
    (tpu_paxos/serve/) — commit latency (p50/p99/p999 in rounds) at a
    sustained offered load, a knee-finding sweep bracketing the
    saturation rate, and the double-buffered dispatch win: the same
    Poisson stream served with ``windows_per_dispatch`` admission
    windows amortized per dispatch vs the one-window-per-dispatch
    sequential baseline.  Every sweep point and both overlap twins
    run bit-identical virtual trajectories per rate (fixed round
    windows on the virtual clock), so the latency columns compare at
    EXACTLY equal p99 and the speedup is pure dispatch-overhead
    hiding — the serving twin of the fast path's 16-windows-per-call
    (PERF.md §Headline)."""
    import numpy as np

    from tpu_paxos.config import FaultConfig, SimConfig
    from tpu_paxos.serve import arrivals as arrv
    from tpu_paxos.serve import driver as sdrv
    from tpu_paxos.serve import harness as sharness
    from tpu_paxos.utils import prng

    on_tpu = jax.devices()[0].platform == "tpu"
    n_values = int(
        os.environ.get("TPU_PAXOS_BENCH_SERVE_VALUES",
                       1 << 16 if on_tpu else 1 << 12)
    )
    r_window = 2  # serving-grade: admission latency bound = 2 rounds
    s_dispatch = 32  # amortization depth (the fast path runs 16)
    rate_milli = 16_000  # 16 values/round: sustained, mid-envelope
    # Windowed-plane bucket width for the record: 16 buckets x 128
    # rounds cover the slowest sweep rate's whole run (~2.1k rounds
    # at 2k milli), so the steady-state median and the SLO burn
    # windows resolve actual time instead of collapsing into the
    # overflow bucket.
    w_rounds = 128
    seed = 0
    cfg = SimConfig(
        n_nodes=5,
        n_instances=2 * n_values,
        proposers=(0, 1),
        seed=seed,
        max_rounds=20_000,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    sweep_rates = [2000, 4000, 8000, 16_000, 32_000, 64_000, 128_000,
                   256_000]
    vids = np.arange(n_values, dtype=np.int32)
    rounds = arrv.poisson_rounds(n_values, rate_milli, seed)
    streams, arrs = arrv.split_round_robin(vids, rounds, 2)
    # ONE admit width covering the overlap runs AND every sweep rate:
    # the (S, K) call shape keys the executable, so this is what makes
    # the whole record one compile per dispatch mode
    width = arrv.ArrivalPlan(streams, arrs, r_window).max_block
    for rm in sweep_rates:
        s_r, a_r = arrv.split_round_robin(
            vids, arrv.poisson_rounds(n_values, rm, seed), 2
        )
        width = max(
            width, arrv.ArrivalPlan(s_r, a_r, r_window).max_block
        )

    def one(s, pipelined, window_rounds=w_rounds):
        return sharness.serve_run(
            cfg, streams, arrs,
            rounds_per_window=r_window,
            windows_per_dispatch=s,
            admit_width=width,
            pipelined=pipelined,
            window_rounds=window_rounds,
        )

    # warm all three executables (one per (S, K) call shape, plus
    # the window_rounds=0 plain twin); the product path is
    # windowed-recorder-armed (the serve_run default)
    rep = one(s_dispatch, True)
    one(1, False)
    rep_plain = one(s_dispatch, True, window_rounds=0)
    state_bytes = _state_nbytes(
        sdrv.init_serve_state(
            cfg, streams, sdrv.vid_bound_of(streams), prng.root_key(seed)
        )[0]
    )
    pipe_walls, seq_walls, plain_walls, rounds_min = [], [], [], 1 << 30
    p99_pipe = p99_seq = None
    for _ in range(5):
        # interleave the modes so slow phases of the box hit every
        # timing set, not just one; median-of-5 (the 2-core dev box
        # is noisier than the device-tunnel timings the 3-rep records
        # absorb).  The window_rounds=0 plain twin rides the same
        # interleave — its delta vs the armed walls is the windowed
        # recorder's cost.
        rp = one(s_dispatch, True)
        pipe_walls.append(rp.wall_seconds)
        rounds_min = min(rounds_min, rp.rounds)
        p99_pipe = rp.p99
        rs = one(1, False)
        seq_walls.append(rs.wall_seconds)
        rounds_min = min(rounds_min, rs.rounds)
        p99_seq = rs.p99
        plain_walls.append(
            one(s_dispatch, True, window_rounds=0).wall_seconds
        )
    # Windowed-recorder overhead, armed vs plain: the SAME stream
    # through the window_rounds=0 build (the exact pre-windowing
    # program).  Trajectories are bit-identical (the windowed plane
    # is read-only), so the values/sec delta is pure recorder cost;
    # a p99 mismatch means the neutrality contract broke and the
    # claim is withheld.
    dt_plain = sorted(plain_walls)[len(plain_walls) // 2]
    dt_armed = sorted(pipe_walls)[len(pipe_walls) // 2]
    if rep_plain.p99 != p99_pipe:
        windowed = {
            "error": (
                f"p99 mismatch armed vs plain ({p99_pipe} vs "
                f"{rep_plain.p99}); the windowed plane must be "
                "trajectory-neutral — overhead claim withheld"
            ),
            "plain_raw_s": [round(x, 4) for x in sorted(plain_walls)],
        }
    else:
        windowed = {
            "window_rounds": rep.window_rounds,
            "values_per_sec_armed": round(
                rep.decided_values / dt_armed, 1
            ),
            "values_per_sec_plain": round(
                rep_plain.decided_values / dt_plain, 1
            ),
            "overhead_pct": round(
                100.0 * (1.0 - dt_plain / max(dt_armed, 1e-9)), 1
            ),
            "plain_raw_s": [round(x, 4) for x in sorted(plain_walls)],
            "p99_rounds": p99_pipe,
        }
    # latency-at-load sweep + knee: SAME value count and admit width
    # as the overlap runs, so every rate shares the already-warm
    # executable (the vid table is a static shape — a smaller sweep
    # stream would recompile).  The sweep runs the windowed path and
    # declares a serving SLO, so every point carries its burn-rate
    # verdict and the record names each rate's breach windows — the
    # mid-run story the run-total histogram can't tell.
    sweep = sharness.sweep_load(
        cfg, n_values, sweep_rates,
        seed=seed,
        rounds_per_window=r_window,
        windows_per_dispatch=s_dispatch,
        admit_width=width,
        window_rounds=w_rounds,
        slo=sharness.ServeSLO(latency_rounds=64, budget_milli=100),
    )
    config = {
        "n_nodes": cfg.n_nodes,
        "n_instances": cfg.n_instances,
        "n_values": n_values,
        "rate_milli": rate_milli,
        "rounds_per_window": r_window,
        "windows_per_dispatch": s_dispatch,
        "admit_width": width,
        "window_rounds": w_rounds,
        "faults": "drop500/dup1000/delay0-2",
        "arrivals": "poisson",
        "latency_unit": "rounds (virtual clock)",
        "p50": rep.p50,
        "p99": rep.p99,
        "p999": rep.p999,
        "devices": 1,
        "platform": jax.devices()[0].platform,
    }
    record = _serve_record(
        pipe_walls, seq_walls, state_bytes, rounds_min,
        rep.decided_values, sweep["points"], sweep["knee"],
        p99_pipe, p99_seq, config,
        windowed=windowed,
    )
    if "slo" in sweep:
        record["slo"] = sweep["slo"]
    return record


def _serve_fleet_record(
    cells, knee_surface, warm_compiles, parity_failures, config
) -> dict:
    """Record-or-error for the fleet-serving (lanes x offered-rates)
    surface — pure, so tests/test_bench_guards.py drives it with
    synthetic cells (the ``_geo_record`` discipline).  Three withhold
    conditions, each fatal to the WHOLE record:

    - ``parity_failures``: the 1-lane zero-load fleet run must be
      decision-log sha256-identical to closed-loop ``run()`` (which
      chains through the pinned serve==closed-loop parity) — a
      mismatch means the lane program forked the protocol and every
      latency number is about a different system;
    - ``warm_compiles``: the surface's claim IS the shared envelope
      executable — any XLA compile during the measured grid (after
      the per-lane-count warm pass) withholds the record;
    - roofline: each cell's ``lanes x state_bytes x rounds`` bounds
      the traffic its timing implies; an implausible cell withholds
      the record naming the (lanes, rate) cell.

    ``cells`` carry {lanes, rate_milli, wall_s, rounds, decided,
    state_bytes, sustained}; the published value is the aggregate
    sustained-values/sec SURFACE keyed [lanes][rate_milli], with the
    per-lane-count knee brackets alongside (a knee SURFACE, not a
    knee point)."""
    raw = [
        {k: (round(c[k], 4) if k == "wall_s" else c[k])
         for k in ("lanes", "rate_milli", "wall_s", "rounds",
                   "decided", "sustained")}
        for c in cells
    ]
    if parity_failures:
        return {
            "engine": "serve_fleet",
            "error": (
                "zero-load parity withheld the record: "
                + "; ".join(str(p) for p in parity_failures)
            ),
            "cells": raw,
            "config": config,
        }
    if warm_compiles:
        return {
            "engine": "serve_fleet",
            "error": (
                f"one-envelope-executable claim failed: {warm_compiles} "
                "warm XLA compiles during the measured (lanes x rates) "
                "grid — the surface is not one executable per "
                "lane-count shape, record withheld"
            ),
            "cells": raw,
            "config": config,
        }
    devices = config.get("devices", 1)
    for c in cells:
        refusal = _implausible(
            int(c["lanes"]) * int(c["state_bytes"]) * max(int(c["rounds"]), 1),
            float(c["wall_s"]), devices,
        )
        if refusal is not None:
            return {
                "engine": "serve_fleet",
                "error": (
                    f"cell (lanes={c['lanes']}, "
                    f"rate_milli={c['rate_milli']}): {refusal}"
                ),
                "cells": raw,
                "config": config,
            }
    surface: dict = {}
    for c in cells:
        surface.setdefault(str(c["lanes"]), {})[str(c["rate_milli"])] = (
            round(c["decided"] / max(float(c["wall_s"]), 1e-9), 1)
        )
    return {
        "engine": "serve_fleet",
        "metric": "serve_fleet_sustained_values_per_sec_surface",
        "value": surface,
        "unit": "values/sec (aggregate across lanes)",
        "knee_surface": knee_surface,
        "warm_compiles_across_grid": int(warm_compiles),
        "cells": raw,
        "config": config,
    }


# jax.monitoring has no listener-removal API, so the fleet-serving
# bench reuses one module-level census (the stress sweep's pattern)
# instead of leaking a deactivated listener per call.
_serve_fleet_census = None


def bench_serve_fleet_record() -> dict:
    """Secondary record: FLEET SERVING (tpu_paxos/serve/fleet.py) —
    the headline (lanes x offered-rates) SURFACE: aggregate sustained
    values/sec per cell and the saturation knee per lane count, every
    cell of a lane count riding the envelope cache's one executable
    (zero warm compiles across the measured grid, pinned by the
    record guard), parity-anchored by a 1-lane zero-load fleet run
    that must be decision-log-identical to closed-loop ``run()``."""
    import hashlib

    import numpy as np

    from tpu_paxos.analysis import tracecount
    from tpu_paxos.config import FaultConfig, SimConfig
    from tpu_paxos.core import sim as simm
    from tpu_paxos.replay.decision_log import decision_log
    from tpu_paxos.serve import arrivals as arrv
    from tpu_paxos.serve import driver as sdrv
    from tpu_paxos.serve import fleet as sflt
    from tpu_paxos.serve import harness as sharness
    from tpu_paxos.utils import prng

    on_tpu = jax.devices()[0].platform == "tpu"
    # values per lane: long enough that an overload rate builds REAL
    # queueing inside the windowed series (the knee must be able to
    # cross — a too-short stream drains before its median doubles)
    n_values = int(
        os.environ.get("TPU_PAXOS_BENCH_SERVE_FLEET_VALUES",
                       1 << 12 if on_tpu else 1 << 10)
    )
    lane_counts = [1, 2, 4, 8] if not on_tpu else [1, 8, 64, 256]
    rates = [2000, 8000, 32_000, 128_000]
    r_window, s_dispatch, w_rounds = 2, 32, 128
    seed = 0
    cfg = SimConfig(
        n_nodes=5,
        n_instances=2 * n_values,
        proposers=(0, 1),
        seed=seed,
        max_rounds=20_000,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    slo = sharness.ServeSLO(latency_rounds=64, budget_milli=100)

    # ---- zero-load parity anchor: 1-lane fleet == closed-loop run()
    vids = np.arange(n_values, dtype=np.int32)
    zl_streams, _ = arrv.split_round_robin(
        vids, arrv.immediate_rounds(n_values), 2
    )
    zl_arrs = [np.zeros(len(s), np.int32) for s in zl_streams]

    def _sha(cv, cb):
        return hashlib.sha256(
            decision_log(cv, cb, stride=64, n_instances=len(cv)).encode()
        ).hexdigest()

    parity_failures = []
    zrep = sflt.serve_fleet_run(
        cfg, [sflt.ServeLane(zl_streams, zl_arrs, seed)],
        rounds_per_window=r_window,
        windows_per_dispatch=s_dispatch,
    )
    closed = simm.run(cfg, zl_streams)
    cv, cb = zrep.lane_chosen(0)
    if _sha(cv, cb) != _sha(closed.chosen_vid, closed.chosen_ballot):
        parity_failures.append(
            "1-lane zero-load fleet serve != closed-loop run() "
            "(decision-log sha256)"
        )

    # ---- ONE admit width across the whole measured grid (the call
    # shape per lane count; the grid must not fork executables per
    # rate), then a warm pass per lane-count shape, then the measured
    # grid under the census — 0 compiles expected.  sweep_fleet_load
    # treats the passed width as authoritative, so the grid's plans
    # are built once here and once per measured cell, never twice.
    width = sflt.grid_admit_width(
        cfg, n_values, lane_counts, rates, seed=seed,
        rounds_per_window=r_window,
    )
    for lc in lane_counts:
        sflt.serve_fleet_run(
            cfg, sflt.fleet_lanes(cfg, lc, n_values, rates[0], seed),
            rounds_per_window=r_window,
            windows_per_dispatch=s_dispatch,
            admit_width=width,
            window_rounds=w_rounds,
            slo=slo,
        )
    global _serve_fleet_census
    if _serve_fleet_census is None:
        _serve_fleet_census = tracecount.CompileCensus()
    census = _serve_fleet_census.start()
    before = census.engine_counts.get("serve_fleet", 0)
    try:
        sweep = sflt.sweep_fleet_load(
            cfg, n_values, lane_counts, rates,
            seed=seed,
            rounds_per_window=r_window,
            windows_per_dispatch=s_dispatch,
            admit_width=width,
            window_rounds=w_rounds,
            slo=slo,
        )
    finally:
        warm_compiles = census.engine_counts.get("serve_fleet", 0) - before
        census.stop()

    grid_streams = sflt.fleet_lanes(cfg, 1, n_values, rates[0], seed)[0]
    state_bytes = _state_nbytes(
        sdrv.init_serve_state(
            cfg, grid_streams.workload, n_values, prng.root_key(seed),
            window_rounds=w_rounds,
        )[0]
    )
    cells = []
    for lc in lane_counts:
        for pt in sweep["cells"][str(lc)]["points"]:
            cells.append({
                "lanes": lc,
                "rate_milli": pt["rate_milli"],
                "wall_s": pt["wall_seconds"],
                "rounds": pt["rounds"],
                "decided": pt["decided"],
                "state_bytes": state_bytes,
                "sustained": pt["sustained"],
            })
    config = {
        "n_nodes": cfg.n_nodes,
        "n_instances": cfg.n_instances,
        "n_values_per_lane": n_values,
        "lane_counts": lane_counts,
        "rates_milli": rates,
        "rounds_per_window": r_window,
        "windows_per_dispatch": s_dispatch,
        "admit_width": width,
        "window_rounds": w_rounds,
        "faults": "drop500/dup1000/delay0-2",
        "arrivals": "poisson (per-lane seed-mixed streams)",
        "slo": {"latency_rounds": 64, "budget_milli": 100},
        "latency_unit": "rounds (virtual clock)",
        "devices": 1,
        "platform": jax.devices()[0].platform,
    }
    record = _serve_fleet_record(
        cells, sweep["knee_surface"], warm_compiles, parity_failures,
        config,
    )
    if "error" not in record:
        # the per-lane-count latency columns the knee read (steady
        # medians + breach lanes), small and JSON-ready
        record["latency_at_load"] = {
            str(lc): [
                {k: pt[k] for k in (
                    "rate_milli", "p50", "p99", "decided", "backlog",
                    "sustained", "breach_lanes",
                ) if k in pt}
                | ({"p50_steady": pt["p50_steady"]}
                   if "p50_steady" in pt else {})
                for pt in sweep["cells"][str(lc)]["points"]
            ]
            for lc in lane_counts
        }
    return record


def _serve_control_record(ab, warm_compiles, config) -> dict:
    """Record-or-error for the adaptive-serving spike A/B
    (serve/control.spike_ab) — pure, so tests/test_bench_guards.py
    drives it with synthetic A/B outputs.  Withhold conditions, each
    fatal to the record:

    - the OFF run must breach at all (a spike the uncontrolled
      harness absorbs judges nothing);
    - the ON run must name strictly FEWER breach windows than OFF at
      the same offered trajectory, with at least one shed decision
      actually taken (a controller that never acted proves nothing);
    - zero sheds inside gray-region-attributed windows — shedding on
      gray evidence is the cause-aware policy's one forbidden move;
    - the decision-log replay (protocol decisions + control
      decisions) must match the artifact sha256 byte-for-byte;
    - ``warm_compiles``: the controller rides the serve envelope's
      cached executable — any XLA compile during the measured A/B
      (after the warm pass) withholds the record."""
    off = ab.get("off", {})
    on = ab.get("on", {})
    raw = {
        "breach_windows_off": off.get("breach_windows", []),
        "breach_windows_on": on.get("breach_windows", []),
        "sheds": int(ab.get("sheds", 0)),
        "decisions": int(ab.get("decisions", 0)),
    }

    def _err(msg):
        return {
            "engine": "serve_control",
            "error": msg,
            **raw,
            "config": config,
        }

    if warm_compiles:
        return _err(
            f"envelope-cache claim failed: {warm_compiles} warm XLA "
            "compiles during the measured spike A/B — the controller "
            "must ride the cached serve executable, record withheld"
        )
    if not off.get("breach_windows"):
        return _err(
            "controller-off run breached nowhere — the spike never "
            "bit, so the A/B judges nothing; record withheld"
        )
    if ab.get("gray_shed_violations"):
        return _err(
            "controller shed inside gray-region-attributed windows "
            f"{ab['gray_shed_violations']} — the cause-aware table's "
            "never-shed-on-gray rule broke, record withheld"
        )
    if not ab.get("fewer_breach_windows"):
        return _err(
            "controller-on did not strictly reduce the breach-window "
            f"list ({raw['breach_windows_off']} -> "
            f"{raw['breach_windows_on']}); record withheld"
        )
    if raw["sheds"] < 1:
        return _err(
            "controller-on took zero shed decisions; the breach "
            "reduction is not attributable to control, record withheld"
        )
    replay = ab.get("replay")
    if replay is None or not replay.get("match"):
        return _err(
            "controlled-run artifact did not replay decision-log "
            "sha256-identically; record withheld"
        )
    return {
        "engine": "serve_control",
        "metric": "serve_control_breach_rounds_off_vs_on",
        "value": {
            "off": int(ab["breach_rounds_off"]),
            "on": int(ab["breach_rounds_on"]),
        },
        "unit": "breach-attributed rounds (virtual clock)",
        **raw,
        "gray_shed_violations": [],
        "causes_on": on.get("causes", []),
        "off": off,
        "on": on,
        "policy": ab.get("policy", {}),
        "slo": ab.get("slo", {}),
        "replay": {
            "match": True,
            "decision_log_sha256": replay.get("decision_log_sha256",
                                              replay.get("sha256", "")),
        },
        "warm_compiles_measured": 0,
        "config": config,
    }


# jax.monitoring has no listener-removal API (see the fleet-serving
# census note above) — one module-level census, started per call.
_serve_control_census = None


def bench_serve_control_record() -> dict:
    """Secondary record: ADAPTIVE SERVING (tpu_paxos/serve/control.py)
    — THE judgment cell for the admission controller: one load spike
    (4x the base Poisson rate over the middle half of the stream)
    served twice at the same offered trajectory on a deliberately
    admission-capped engine (``assign_window=8`` bounds concurrent
    assignment, so the spike builds a real queue), controller off
    then on.  The record is the breach-window comparison: ON must
    name strictly fewer saturation-attributed breach windows, shed
    only outside gray-region-attributed windows, replay its combined
    decision log sha256-identically from the committed artifact
    schema, and ride the envelope cache with zero warm compiles
    across the measured A/B."""
    from tpu_paxos.analysis import tracecount
    from tpu_paxos.config import SimConfig
    from tpu_paxos.serve import control as sctl
    from tpu_paxos.serve import harness as sharness

    n_values = int(
        os.environ.get("TPU_PAXOS_BENCH_SERVE_CONTROL_VALUES", 1000)
    )
    # The judgment cell is a fixed marginal-overload shape, not a
    # throughput sweep: base rate 2 values/round against ~2.5
    # values/round of admission capacity (assign_window=8), spiked 4x
    # over the middle half — overload the controller can actually
    # mitigate by shedding the declared tier-2 third of the stream.
    rate_milli = 2000
    spike_factor = 4
    r_window, s_dispatch, w_rounds = 4, 2, 32
    seed = 0
    cfg = SimConfig(
        n_nodes=3,
        n_instances=2048,
        proposers=(0, 1),
        seed=3,
        max_rounds=8000,
        assign_window=8,
    )
    slo = sharness.ServeSLO(latency_rounds=16, budget_milli=150)
    art_path = os.environ.get(
        "TPU_PAXOS_BENCH_SERVE_CONTROL_ARTIFACT",
        os.path.join(tempfile.gettempdir(), "bench_serve_control.json"),
    )

    def _ab():
        return sctl.spike_ab(
            cfg, n_values, rate_milli,
            slo=slo, seed=seed,
            rounds_per_window=r_window,
            windows_per_dispatch=s_dispatch,
            spike_factor=spike_factor,
            spike_start_frac=0.25,
            spike_len_frac=0.5,
            window_rounds=w_rounds,
            artifact_path=art_path,
        )

    _ab()  # warm the envelope executable (off and on share it)
    global _serve_control_census
    if _serve_control_census is None:
        _serve_control_census = tracecount.CompileCensus()
    census = _serve_control_census.start()
    before = sum(
        census.engine_counts.get(k, 0)
        for k in ("serve", "serve_control")
    )
    try:
        ab = _ab()
    finally:
        warm_compiles = sum(
            census.engine_counts.get(k, 0)
            for k in ("serve", "serve_control")
        ) - before
        census.stop()
    config = {
        "n_nodes": cfg.n_nodes,
        "n_instances": cfg.n_instances,
        "assign_window": cfg.assign_window,
        "n_values": n_values,
        "rate_milli": rate_milli,
        "spike_factor": spike_factor,
        "spike_start_frac": 0.25,
        "spike_len_frac": 0.5,
        "rounds_per_window": r_window,
        "windows_per_dispatch": s_dispatch,
        "window_rounds": w_rounds,
        "admit_width": ab["admit_width"],
        "faults": "none (gray-region must stay quiet for the "
                  "never-shed-on-gray clause to be a live check)",
        "arrivals": "poisson + mid-run spike",
        "slo": ab["slo"],
        "latency_unit": "rounds (virtual clock)",
        "devices": 1,
        "platform": jax.devices()[0].platform,
    }
    return _serve_control_record(ab, warm_compiles, config)


def _member_record(host_runs, dev_runs, state_bytes, config) -> dict:
    """Record-or-error for the membership host-vs-device timing pairs
    — pure, so tests/test_bench_guards.py drives it with synthetic
    runs.  ``host_runs[k]`` / ``dev_runs[k]`` are ``(wall_s, rounds,
    decision_log_sha256)`` for the SAME (churn table, seed), so three
    guards apply: (a) the drivers must be decision-log-identical pair
    for pair — a sha mismatch means the ChurnTable interpreters
    diverged and the speedup claim is meaningless, so the record is
    withheld; (b) every engine round streams the [I]-sized state at
    least once, so ``state_bytes * rounds`` roofline-bounds the
    traffic EITHER timing implies; (c) the published value is the
    SLOWEST device run's rounds/sec — conservative for re-run
    timing."""
    raw_h = [round(w, 4) for w, _r, _s in host_runs]
    raw_d = [round(w, 4) for w, _r, _s in dev_runs]
    for k, ((_hw, _hr, hs), (_dw, _dr, ds)) in enumerate(
        zip(host_runs, dev_runs)
    ):
        if hs != ds:
            return {
                "engine": "member",
                "error": (
                    f"decision-log sha256 mismatch between drivers on "
                    f"run {k} ({hs[:16]}... vs {ds[:16]}...); the "
                    "host-stepped and device-resident drivers must "
                    "run identical trajectories — speedup withheld"
                ),
                "raw_timings_s": raw_d,
                "host_raw_s": raw_h,
                "config": config,
            }
    for label, runs in (("host-stepped", host_runs),
                        ("device-resident", dev_runs)):
        for w, r, _s in runs:
            refusal = _implausible(state_bytes * max(r, 1), w)
            if refusal is not None:
                return {
                    "engine": "member",
                    "error": f"{label} timing: {refusal}",
                    "raw_timings_s": raw_d,
                    "host_raw_s": raw_h,
                    "config": config,
                }
    rate_d = min(r / w for w, r, _s in dev_runs)
    rate_h = min(r / w for w, r, _s in host_runs)
    return {
        "engine": "member",
        "metric": "member_rounds_per_sec",
        "value": round(rate_d, 1),
        "unit": "rounds/sec",
        "rounds": dev_runs[0][1],
        "raw_timings_s": raw_d,
        "host_stepped": {
            # the same churn table through the legacy per-round-sync
            # driver (ChurnEngine.run_host) — the cost model every
            # record before PR 12 published
            "member_rounds_per_sec": round(rate_h, 1),
            "raw_timings_s": raw_h,
            "speedup": round(rate_d / max(rate_h, 1e-9), 2),
        },
        "parity": {
            "decision_log_sha256": dev_runs[0][2],
            "drivers": "host-stepped == device-resident, per seed",
        },
        "config": config,
    }


def bench_member_record() -> dict:
    """Secondary record: the MEMBERSHIP engine under the BASELINE
    config-5 churn shape (grow the acceptor set 1->7 with values in
    flight, shrink to 5, Applied sequencing) over a sizeable log —
    HOST-STEPPED vs DEVICE-RESIDENT.  The scenario is a runtime
    ``ChurnTable`` (membership/churn_table.py) driven two ways on the
    same engine build: ``ChurnEngine.run_host`` re-creates the legacy
    per-round host loop (injection + termination decided from
    per-round np reads — the cost model the pre-PR-12 records
    published), ``ChurnEngine.run`` is one ``lax.while_loop``
    dispatch.  Decision-log sha256 parity between the two is enforced
    per seed (``_member_record``); the headline is the device
    driver's rounds/sec.  Timing: fresh seeds per timed run on the
    one compiled program, slowest run reported, roofline-guarded.
    Default size keeps the record inside the bench budget; set
    TPU_PAXOS_BENCH_MEMBER_INSTANCES=1048576 for the BASELINE
    config-5 literal size (tests/test_membership.py runs it on every
    suite pass)."""
    import hashlib

    from tpu_paxos.membership import churn_table as ctm
    from tpu_paxos.membership import engine as meng

    i = int(os.environ.get("TPU_PAXOS_BENCH_MEMBER_INSTANCES", 1 << 17))
    n = 7
    churn = ctm.grow_shrink_schedule(7, 5, values_per_step=1)
    eng = meng.ChurnEngine(n, i, churn=churn, max_rounds=4000)
    state_bytes = _state_nbytes(meng._init(n, i, eng.c))
    warm = eng.run(seed=5)  # compile + warm both paths
    if not warm.done:
        raise RuntimeError("membership churn scenario did not complete")
    eng.run_host(seed=5)

    def sha(res) -> str:
        return hashlib.sha256(res.decision_log().encode()).hexdigest()

    host_runs, dev_runs = [], []
    for seed in (6, 7):  # fresh seeds: timed calls differ in content
        t0 = time.perf_counter()
        r = eng.run(seed=seed)
        dev_runs.append((time.perf_counter() - t0, r.rounds, sha(r)))
        t0 = time.perf_counter()
        rh = eng.run_host(seed=seed)
        host_runs.append((time.perf_counter() - t0, rh.rounds, sha(rh)))
        if not r.done:
            raise RuntimeError(f"device churn run (seed {seed}) stalled")
    config = {
        "n_nodes": n,
        "n_instances": i,
        "churn": "grow 1->7, shrink to 5, 6 values in flight "
                 f"(ChurnTable, {len(churn.events)} events)",
        "churn_events": len(churn.events),
        "devices": 1,
        "platform": jax.devices()[0].platform,
    }
    return _member_record(host_runs, dev_runs, state_bytes, config)


def bench_sharded_child() -> list[dict]:
    """Child-process body (virtual multi-device CPU backend): sharded
    fast path at >= 1M instances and the sharded general engine — the
    BASELINE config 4 shape, honestly labeled as virtual devices."""
    from tpu_paxos.config import FaultConfig, SimConfig
    from tpu_paxos.parallel import sharded_sim
    from tpu_paxos.utils import prng

    n_dev = len(jax.devices())
    platform = f"{jax.devices()[0].platform}-virtual-{n_dev}"
    records = []

    # fast path, 7 nodes, 100M instances over the mesh — BASELINE
    # config 4 at its literal size (the virtual mesh holds the full
    # [7, 100M] state; ~10 GiB host RAM).  Hosts without that much
    # free memory get the 1M size instead of an OOM, unless the env
    # knob asks for a size explicitly.
    n_nodes, reps = 7, 4
    n_fast_env = os.environ.get("TPU_PAXOS_BENCH_SHARDED_FAST_INSTANCES")
    if n_fast_env is not None:
        n_fast = int(n_fast_env)
    else:
        n_fast = 100_000_000
        avail = _available_ram_bytes()
        if avail is not None and avail < 14 << 30:
            print(
                f"only {avail >> 30} GiB RAM available; sharded-fast "
                "record falls back to 1M instances (set "
                "TPU_PAXOS_BENCH_SHARDED_FAST_INSTANCES to override)",
                file=sys.stderr,
            )
            n_fast = 1_000_000
    mesh, step, state, vids0, n_inst = _sharded_fast_setup(
        n_nodes, n_fast, reps, donate=True
    )
    state2, total = step(state, vids0)
    _check_total(total, n_inst * reps)  # warmup, fully materialized
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        state2, total = step(state2, vids0)
        _check_total(total, n_inst * reps)
        dts.append(time.perf_counter() - t0)
    dt = sorted(dts)[1]
    fast_rec = {
        "engine": "fast",
        "baseline_config": 4,
        "metric": "paxos_instances_per_sec_to_chosen",
        "value": round(n_inst * reps / dt, 1),
        "unit": "instances/sec",
        "raw_timings_s": [round(x, 4) for x in sorted(dts)],
        "config": {
            "n_nodes": n_nodes,
            "n_instances_per_window": n_inst,
            "windows": reps,
            "sharded": True,
            "devices": n_dev,
            "platform": platform,
        },
    }
    refusal = _implausible(_state_nbytes(state2) * reps, dt, n_dev)
    if refusal is not None:
        fast_rec = {"engine": "fast", "error": refusal,
                    "raw_timings_s": fast_rec["raw_timings_s"],
                    "config": fast_rec["config"]}
    records.append(fast_rec)
    del step, state, state2, vids0, total

    # same engine on the 2-D multi-host (dcn x ici) mesh — the
    # collectives reduce over both axes; results are bit-identical to
    # the 1-D mesh (tests/test_multihost.py), so this record is about
    # the topology executing, not a new number (smaller size keeps the
    # whole bench inside the driver's budget)
    if n_dev % 2 == 0:
        os.environ["TPU_PAXOS_BENCH_DCN_HOSTS"] = "2"
        try:
            mesh2, step2, st2, v2, n_inst2 = _sharded_fast_setup(
                n_nodes, min(n_fast, 10_000_000), reps, donate=True
            )
            st2b, total = step2(st2, v2)
            _check_total(total, n_inst2 * reps)  # warmup, materialized
            dts2 = []
            for _ in range(3):
                t0 = time.perf_counter()
                st2b, total = step2(st2b, v2)
                _check_total(total, n_inst2 * reps)
                dts2.append(time.perf_counter() - t0)
            dt = sorted(dts2)[1]
            refusal2 = _implausible(_state_nbytes(st2b) * reps, dt, n_dev)
            rec2 = {
                "engine": "fast",
                "baseline_config": 4,
                "metric": "paxos_instances_per_sec_to_chosen",
                "value": round(n_inst2 * reps / dt, 1),
                "unit": "instances/sec",
                "raw_timings_s": [round(x, 4) for x in sorted(dts2)],
                "config": {
                    "n_nodes": n_nodes,
                    "n_instances_per_window": n_inst2,
                    "windows": reps,
                    "sharded": True,
                    "mesh": "2x%d dcn x ici" % (n_dev // 2),
                    "devices": n_dev,
                    "platform": platform,
                },
            }
            if refusal2 is not None:
                rec2 = {"engine": "fast", "error": refusal2,
                        "raw_timings_s": rec2["raw_timings_s"],
                        "config": rec2["config"]}
            records.append(rec2)
            del mesh2, step2, st2, st2b, v2, total
        finally:
            os.environ.pop("TPU_PAXOS_BENCH_DCN_HOSTS", None)

    # general engine, sharded, reference fault rates
    i = int(os.environ.get("TPU_PAXOS_BENCH_SIM_SHARDED_INSTANCES", 1 << 20))
    cfg = SimConfig(
        n_nodes=7,
        n_instances=i,
        proposers=(0, 1),
        seed=0,
        assign_window=max(256, min(1 << 14, i // (8 * n_dev))),
        max_rounds=20_000,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    fn, _root, st0, _ = sharded_sim.build_runner(cfg, mesh)
    try:
        records.append(
            _timed_sim_runs(
                fn,
                lambda k: prng.root_key(cfg.seed + k),
                st0,
                i,
                {
                    "n_nodes": 7,
                    "n_instances": i,
                    "proposers": 2,
                    "faults": "drop500/dup1000/delay0-2",
                    "sharded": True,
                    "devices": n_dev,
                    "platform": platform,
                },
            )
        )
    except Exception as e:
        # the fast-path records above are already measured; never lose
        # them to a sim failure
        records.append({"engine": "sim", "error": str(e)[:500]})
    return records


def _available_ram_bytes() -> int | None:
    """MemAvailable from /proc/meminfo, or None where that can't be
    read (non-Linux) — callers treat unknown as 'enough'."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return None


def _sharded_records_via_subprocess(n_devices: int = 8) -> list[dict]:
    """Spawn the child on a clean n-device virtual CPU backend (the
    in-process backend is the single real chip)."""
    import subprocess

    import __graft_entry__ as ge

    code = ge.virtual_cpu_bootstrap(n_devices) + (
        "import json, bench\n"
        "print('BENCH_CHILD:' + json.dumps(bench.bench_sharded_child()))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=ge._spawn_env(n_devices),
        cwd=os.path.dirname(os.path.abspath(__file__)),
        capture_output=True,
        text=True,
        timeout=840,
    )
    if proc.returncode != 0:
        raise RuntimeError(f"sharded bench child failed:\n{proc.stderr[-2000:]}")
    for line in proc.stdout.splitlines():
        if line.startswith("BENCH_CHILD:"):
            return json.loads(line[len("BENCH_CHILD:"):])
    raise RuntimeError("sharded bench child produced no record line")


def main() -> None:
    # default window is platform-scaled: 128M instances (~8 GiB of
    # FastState) suits the 16 GB v5e; the CPU fallback (no TPU) gets a
    # size that completes on an ordinary host
    on_tpu = jax.devices()[0].platform == "tpu"
    n_inst = int(
        os.environ.get("TPU_PAXOS_BENCH_INSTANCES", 1 << 27 if on_tpu else 1 << 22)
    )
    n_nodes = int(os.environ.get("TPU_PAXOS_BENCH_NODES", 5))
    # 16 windows x 2^27 instances fills the int32 vid space exactly and
    # amortizes the per-dispatch overhead (~90 ms through the device
    # tunnel) over ~400 ms of device work.
    reps = int(os.environ.get("TPU_PAXOS_BENCH_REPS", 16 if on_tpu else 4))
    use_sharded = os.environ.get("TPU_PAXOS_BENCH_SHARDED", "0") == "1"
    quorum = n_nodes // 2 + 1

    def _fresh():
        return fast.init_state(n_inst, n_nodes), jnp.arange(
            n_inst, dtype=jnp.int32
        )

    def _scan_setup():
        state, vids0 = _fresh()
        step = jax.jit(
            functools.partial(_steady_state_windows, reps=reps, quorum=quorum),
            donate_argnums=(0,),
        )
        return state, vids0, step

    fused = (
        on_tpu
        and not use_sharded
        and os.environ.get("TPU_PAXOS_BENCH_FUSED", "1") == "1"
    )
    if use_sharded and len(jax.devices()) > 1:
        _, step, state, vids0, n_inst = _sharded_fast_setup(
            n_nodes, n_inst, reps, donate=True
        )
    elif fused:
        from tpu_paxos.core import fastwin

        state = fast.init_state(n_inst, n_nodes)
        vids0 = None  # the fallback _scan_setup builds its own
        # the bench workload IS sequential ids, so the kernel
        # synthesizes vids in VMEM (iota_vids) instead of streaming
        # the [I] array from HBM
        _fw = functools.partial(
            fastwin.steady_state_windows_fused,
            reps=reps,
            quorum=quorum,
            iota_vids=True,
        )
        step = lambda st, _v: _fw(st, None)  # noqa: E731
    else:
        state, vids0, step = _scan_setup()

    # Warmup / compile.  If the pallas path fails to compile or run on
    # this backend, fall back to the XLA scan rather than losing the
    # bench run — but config errors (ValueError: bad window size, vid
    # space overflow) re-raise, so a typo can't silently demote the
    # headline to the ~3.6x-slower scan.  A fused headline is preceded
    # by an on-device content-equivalence check against the scan path
    # (full arrays, small I) so a corrupt kernel can never record a
    # number.
    fallback_reason = None
    try:
        if fused:
            check_fused_equivalence(n_nodes=n_nodes)
        state2, total = step(state, vids0)
        total.block_until_ready()
    except (ValueError, KernelDivergence):
        # config errors and wrong-answer kernels both abort loudly; the
        # fallback below is only for availability failures (a backend
        # that can't compile/run the kernel at all)
        raise
    except Exception as e:
        if not fused:
            raise
        fallback_reason = repr(e)[:300]
        print(
            f"pallas fused window failed ({e!r}); falling back to XLA scan",
            file=sys.stderr,
        )
        fused = False
        del state
        state, vids0, step = _scan_setup()
        state2, total = step(state, vids0)
        total.block_until_ready()
    _check_total(total, n_inst * reps)  # warmup correctness
    headline_state_nbytes = _state_nbytes(state2)

    # Optional profiler capture of the timed window
    # (TPU_PAXOS_BENCH_PROFILE=<dir>; view with tensorboard/xprof).
    import contextlib

    profile_dir = os.environ.get("TPU_PAXOS_BENCH_PROFILE", "")
    trace = (
        jax.profiler.trace(profile_dir)
        if profile_dir
        else contextlib.nullcontext()
    )
    # Median of three timed calls: per-dispatch latency through the
    # device tunnel varies run to run, and the metric of record should
    # not inherit that jitter.
    dts = []
    with trace:
        for _ in range(3):
            t0 = time.perf_counter()
            state2, total = step(state2, vids0)
            total.block_until_ready()
            dts.append(time.perf_counter() - t0)
            _check_total(total, n_inst * reps)
    # Roofline sanity: each window streams the full state through HBM
    # at least once; _guard_headline withholds any value no timing can
    # physically support (reporting only value_upper_bound instead).
    n_dev = len(jax.devices()) if use_sharded else 1
    rate, value_upper_bound, roofline_note = _guard_headline(
        dts, headline_state_nbytes * reps, n_dev, n_inst * reps
    )
    # Release the headline run's device state (~8 GiB on TPU) before
    # the secondary engines run on the same chip.
    del state, state2, total, vids0, step

    # Secondary records: the general engine on this backend, and the
    # sharded fast+sim engines on an 8-device virtual CPU mesh (no
    # multi-chip hardware here; labeled honestly).  Skippable for quick
    # runs via TPU_PAXOS_BENCH_SECONDARY=0.
    secondary = []
    if os.environ.get("TPU_PAXOS_BENCH_SECONDARY", "1") == "1":
        # never lose the already-measured headline number to a
        # secondary failure — degrade to an error record instead
        try:
            secondary.append(bench_sim_record())
        except Exception as e:
            secondary.append({"engine": "sim", "error": str(e)[:500]})
        if os.environ.get("TPU_PAXOS_BENCH_FLEET", "1") == "1":
            try:
                secondary.append(bench_fleet_record())
            except Exception as e:
                secondary.append({"engine": "fleet", "error": str(e)[:500]})
        if os.environ.get("TPU_PAXOS_BENCH_GEO", "1") == "1":
            try:
                secondary.append(bench_geo_record())
            except Exception as e:
                secondary.append({"engine": "geo", "error": str(e)[:500]})
        if os.environ.get("TPU_PAXOS_BENCH_ENVELOPE", "1") == "1":
            try:
                secondary.append(bench_envelope_record())
            except Exception as e:
                secondary.append(
                    {"engine": "envelope", "error": str(e)[:500]}
                )
        if os.environ.get("TPU_PAXOS_BENCH_SERVE", "1") == "1":
            try:
                secondary.append(bench_serve_record())
            except Exception as e:
                secondary.append({"engine": "serve", "error": str(e)[:500]})
        if os.environ.get("TPU_PAXOS_BENCH_SERVE_FLEET", "1") == "1":
            try:
                secondary.append(bench_serve_fleet_record())
            except Exception as e:
                secondary.append(
                    {"engine": "serve_fleet", "error": str(e)[:500]}
                )
        if os.environ.get("TPU_PAXOS_BENCH_SERVE_CONTROL", "1") == "1":
            try:
                secondary.append(bench_serve_control_record())
            except Exception as e:
                secondary.append(
                    {"engine": "serve_control", "error": str(e)[:500]}
                )
        if os.environ.get("TPU_PAXOS_BENCH_MEMBER", "1") == "1":
            try:
                secondary.append(bench_member_record())
            except Exception as e:
                secondary.append({"engine": "member", "error": str(e)[:500]})
        if os.environ.get("TPU_PAXOS_BENCH_SHARDED_CHILD", "1") == "1":
            try:
                secondary.extend(_sharded_records_via_subprocess(8))
            except Exception as e:
                secondary.append(
                    {"engine": "sharded-child", "error": str(e)[:500]}
                )

    print(
        json.dumps(
            {
                "metric": "paxos_instances_per_sec_to_chosen",
                "value": round(rate, 1) if rate is not None else None,
                "unit": "instances/sec",
                "vs_baseline": (
                    round(rate / NORTH_STAR, 3) if rate is not None else None
                ),
                **(
                    {"value_upper_bound": round(value_upper_bound, 1)}
                    if value_upper_bound is not None
                    else {}
                ),
                "raw_timings_s": [round(x, 4) for x in sorted(dts)],
                "config": {
                    "n_nodes": n_nodes,
                    "n_instances_per_window": n_inst,
                    "windows": reps,
                    "sharded": bool(use_sharded and len(jax.devices()) > 1),
                    "fused_kernel": fused,
                    **(
                        {"fallback_reason": fallback_reason}
                        if fallback_reason
                        else {}
                    ),
                    **(
                        {"roofline_note": roofline_note}
                        if roofline_note
                        else {}
                    ),
                    "devices": len(jax.devices()),
                    "platform": jax.devices()[0].platform,
                },
                "secondary": secondary,
            }
        )
    )


if __name__ == "__main__":
    main()
