"""Whole-run invariants — the reference's self-checking epilogue.

The reference harness asserts, at end of run, that (1) every replica
executed the identical sequence and (2) the multiset of executed ids is
exactly 0..N-1 — agreement + exactly-once (ref multi/main.cpp:567-573);
its state machine additionally checks online that each client's
in-order ids arrive in order (ref multi/main.cpp:202-212).  member/
asserts each node's applied log is a prefix of node 0's
(ref member/main.cpp:260-265).

These are the framework's correctness gates: every engine run finishes
by calling into this module.  Large logs route through
``tpu_paxos.native``'s single-pass C++ scans (built on demand; the
numpy implementations below remain the reference semantics and the
fallback, with native/python equivalence pinned by
tests/test_native.py).  ``reference_runner.check_parity`` runs the
same checks against the C++ reference binary's parsed logs, so one
checker judges both systems.
"""

from __future__ import annotations

import numpy as np

from tpu_paxos.core import apply as apl
from tpu_paxos.core import values as val


class InvariantViolation(AssertionError):
    pass


def _fail(msg: str) -> None:
    raise InvariantViolation(msg)


# Route the O(I*A) scans through tpu_paxos.native's single-pass C++
# above this size (below it, numpy/ctypes overheads wash out).
_NATIVE_MIN_CELLS = 1 << 16


def _use_native(learned: np.ndarray) -> bool:
    from tpu_paxos import native

    return learned.size >= _NATIVE_MIN_CELLS and native.available()


def _chosen_per_instance(learned: np.ndarray) -> np.ndarray:
    """Per instance: the vid learned by any knowing node (max over
    knowing nodes), or NONE where no node knows a value."""
    learned = np.asarray(learned)
    if _use_native(learned):
        from tpu_paxos import native

        return native.chosen_per_instance(learned)
    known = learned != int(val.NONE)
    best = np.where(known, learned, np.iinfo(np.int32).min).max(axis=1)
    return np.where(known.any(axis=1), best, int(val.NONE))


def check_agreement(learned: np.ndarray) -> None:
    """No two nodes learned different values for the same instance
    (chosen is unique — the core Paxos safety property; the reference
    asserts it per-commit at multi/paxos.cpp:1509-1510 and whole-run at
    multi/main.cpp:567-570)."""
    learned = np.asarray(learned)
    if _use_native(learned):
        from tpu_paxos import native

        bad_i = native.check_agreement(learned)
        if bad_i is not None:
            _fail(
                f"agreement violated at instance {bad_i}: nodes learned "
                f"{learned[bad_i].tolist()}"
            )
        return
    known = learned != int(val.NONE)
    ref_col = _chosen_per_instance(learned)
    bad = (known & (learned != ref_col[:, None])).any(axis=1)
    if bad.any():
        i = int(np.flatnonzero(bad)[0])
        _fail(
            f"agreement violated at instance {i}: nodes learned "
            f"{learned[i].tolist()}"
        )


def check_exactly_once(
    learned: np.ndarray, expected_vids: np.ndarray | None = None
) -> None:
    """Every real (non-no-op) value is chosen at most once across the
    log, and — when the expected proposal set is given — each expected
    value exactly once (ref multi/main.cpp:571-573: executed ids sorted
    equal 0..N-1)."""
    chosen = _chosen_per_instance(learned)
    if _use_native(np.asarray(learned)):
        # single-pass C++ duplicate scan in BOTH branches; only the
        # expected-set comparison below stays in numpy
        from tpu_paxos import native

        dup = native.check_unique(chosen)
        if dup is not None:
            _fail(f"value {dup} chosen for more than one instance")
        if expected_vids is None:
            return
        real = chosen[chosen >= 0]
        uniq = np.unique(real)
    else:
        real = chosen[chosen >= 0]
        uniq, counts = np.unique(real, return_counts=True)
        if (counts > 1).any():
            v = int(uniq[np.flatnonzero(counts > 1)[0]])
            _fail(f"value {v} chosen for more than one instance")
    if expected_vids is not None:
        expected = np.unique(np.asarray(expected_vids))
        missing = np.setdiff1d(expected, uniq)
        extra = np.setdiff1d(uniq, expected)
        if missing.size:
            _fail(f"values never chosen: {missing[:10].tolist()}...")
        if extra.size:
            _fail(f"unexpected values chosen: {extra[:10].tolist()}...")


def check_executed_identical(learned: np.ndarray) -> list[np.ndarray]:
    """All replicas execute the same sequence (over their applied
    prefixes — shorter prefixes must be prefixes of longer ones;
    combines multi/main.cpp:567-570 with member/main.cpp:260-265)."""
    seqs = apl.executed_sequences(np.asarray(learned))
    longest = max(seqs, key=len)
    for a, s in enumerate(seqs):
        if not np.array_equal(s, longest[: len(s)]):
            _fail(f"node {a} executed sequence diverges from longest prefix")
    return seqs


def check_in_order_clients(
    executed: np.ndarray, in_order_vids: list[np.ndarray]
) -> None:
    """Per in-order client: its values appear in the executed sequence
    in proposal order (ref multi/main.cpp:202-212, where half the
    clients propose strictly in order)."""
    executed = np.asarray(executed)
    pos = {int(v): i for i, v in enumerate(executed)}
    for c, vids in enumerate(in_order_vids):
        last = -1
        for v in vids:
            p = pos.get(int(v))
            if p is None:
                _fail(f"in-order client {c}: value {int(v)} never executed")
            if p < last:
                _fail(f"in-order client {c}: value {int(v)} executed out of order")
            last = p


def check_prefix_consistency(logs: list[np.ndarray]) -> None:
    """member/ validation: every node's applied log is a prefix of the
    longest one (ref member/main.cpp:260-265 checks vs node 0; using
    the longest is the same invariant without privileging a node)."""
    longest = max(logs, key=len)
    for a, s in enumerate(logs):
        if not np.array_equal(np.asarray(s), np.asarray(longest)[: len(s)]):
            _fail(f"node {a} applied log is not a prefix of the longest log")


def check_all(
    learned: np.ndarray, expected_vids: np.ndarray | None = None
) -> list[np.ndarray]:
    check_agreement(learned)
    check_exactly_once(learned, expected_vids)
    return check_executed_identical(learned)
