"""Simulation harnesses, validation invariants, CLI."""
