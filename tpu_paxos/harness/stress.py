"""Randomized stress sweep: many seeds x fault mixes through the
general engine, every run judged by the full invariant suite.

This is the framework acting as what the reference sets out to be —
"verify the whole system behaviour under different simulated
circumstances like network failure and process crash" (ref README) —
beyond the fixed-seed pytest scenarios: each sweep samples fresh
seeds against a grid of fault mixes (including crashes, in-order gate
chains, and correlated-fault *episode* schedules — partition flaps,
one-way link cuts, node pauses, burst loss; core/faults.py) and
asserts agreement, exactly-once, executed-identical, in-order
clients, and quiescence on every run.

Failure triage: with ``--triage-dir`` (or ``triage_dir=``), any
failing seed is handed to ``harness/shrink.py`` — the fault schedule
is greedily shrunk to a minimal still-failing case and written as a
JSON repro artifact that ``python -m tpu_paxos repro <artifact>``
re-executes byte-identically.

Engine shapes are held fixed per fault mix so each mix compiles once
and every seed reuses the executable (the seed only changes the PRNG
root, a runtime argument).

CLI: ``python -m tpu_paxos.harness.stress [--seeds N] [--base-seed S]
[--triage-dir D]`` (or ``make stress`` / ``make stress-quick``) prints
one JSON summary line and exits non-zero on any violation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import zlib

import jax
import numpy as np

from tpu_paxos.analysis import tracecount
from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import faults as flt
from tpu_paxos.core import sim as simm
from tpu_paxos.core import values as val
from tpu_paxos.harness import shrink as shr
from tpu_paxos.harness import validate
from tpu_paxos.utils import log as logm

# Correlated-fault schedules for the episode mixes (5-node clusters).
# Every episode heals; convergence is owed (and asserted) after the
# last heal with a full max_rounds budget (SimConfig.round_budget).
SCHED_PARTITION_FLAP = flt.FaultSchedule((
    # flapping bisections: each window isolates a different minority
    flt.partition(6, 26, (0, 1), (2, 3, 4)),
    flt.partition(40, 62, (0, 2, 4), (1, 3)),
    flt.partition(76, 96, (1, 4), (0, 2, 3)),
))
SCHED_ONE_WAY = flt.FaultSchedule((
    # asymmetric cuts, overlapping: 0 can still hear 2/3 but not talk
    # to them, then 0 goes reply-deaf to 3/4, then 1 goes send-dark
    flt.one_way(5, 30, (0,), (2, 3)),
    flt.one_way(22, 48, (3, 4), (0,)),
    flt.one_way(60, 80, (1,), (2, 3, 4)),
))
SCHED_PAUSE_HEAVY = flt.FaultSchedule((
    # rolling GC-style pauses (incl. proposer node 1) + a loss burst
    flt.pause(4, 26, 1),
    flt.pause(18, 44, 3),
    flt.pause(34, 58, 4),
    flt.burst(10, 30, 2500),
))
SCHED_PAUSE_CRASH = flt.FaultSchedule((
    # pauses on top of i.i.d. fail-stop crashes: the engine must keep
    # pause- and crash-excusals apart (a paused node's obligations
    # resume; a crashed node's never do)
    flt.pause(6, 30, 1),
    flt.pause(36, 60, 2),
))

# Fault mixes: (label, FaultConfig kwargs, n_nodes, n_proposers).
# Rates are per-1e4 (drop/dup) and per-1e6 (crash), as in the
# reference's debug.conf (ref multi/main.cpp:51-162,
# member/indet.h:146-150); ``schedule`` adds the correlated layer.
MIXES = [
    ("clean", dict(), 3, 1),
    ("debug.conf", dict(drop_rate=500, dup_rate=1000, max_delay=2), 5, 2),
    ("lossy", dict(drop_rate=2000, dup_rate=500, max_delay=4), 5, 2),
    ("duel-heavy", dict(drop_rate=1000, dup_rate=2000, max_delay=3), 5, 3),
    (
        "crashy",
        dict(drop_rate=500, dup_rate=1000, max_delay=2, crash_rate=4000),
        5,
        2,
    ),
    (
        "delay-heavy",
        dict(drop_rate=200, dup_rate=200, min_delay=1, max_delay=6),
        7,
        2,
    ),
    (
        "partition-flap",
        dict(
            drop_rate=300, dup_rate=500, max_delay=2,
            schedule=SCHED_PARTITION_FLAP,
        ),
        5,
        2,
    ),
    (
        "one-way",
        dict(
            drop_rate=300, dup_rate=500, max_delay=2,
            schedule=SCHED_ONE_WAY,
        ),
        5,
        2,
    ),
    (
        "pause-heavy",
        dict(
            drop_rate=200, dup_rate=500, max_delay=2,
            schedule=SCHED_PAUSE_HEAVY,
        ),
        5,
        2,
    ),
    (
        "pause-crash",
        dict(
            drop_rate=500, dup_rate=1000, max_delay=2, crash_rate=3000,
            schedule=SCHED_PAUSE_CRASH,
        ),
        5,
        2,
    ),
]
# The correlated-fault mixes (used by sweep_sharded and the episode
# smoke) — derived structurally so reordering MIXES cannot drift it.
EPISODE_MIXES = [m for m in MIXES if "schedule" in m[1]]

# WAN geo mixes (core/wan.py): per-edge [A, A] latency/loss matrices
# from the topology presets, a gray episode (the slow-region outage no
# crash or pause can express), and an asymmetric long-haul cut.  Same
# 5-node/2-proposer geometry and the envelope's default ring bound as
# the episode mixes above, so sweep_fleet runs them on the SAME
# compiled executable (zero warm compiles across mixes — the
# BENCH_geo.json claim).  Fleet-only: the sharded sweep keeps the
# classic episode mixes.
from tpu_paxos.core import wan as wanm  # noqa: E402  (pure numpy)

SCHED_WAN_GRAY = flt.FaultSchedule((
    # the lone 'ap' node (round-robin region map of 5 nodes over 3
    # regions puts node 2 alone in ap) goes gray mid-run, then the
    # transpacific link drops one direction
    flt.gray(8, 40, 2, delay=3),
    flt.one_way(20, 48, (2,), (0, 1)),
))
SCHED_WAN5_GRAY = flt.FaultSchedule((
    # a whole region slows (nodes 3 = ap, 4 = sa on the 5-region
    # round-robin), composing with a short partition of the tail
    flt.gray(6, 36, 3, 4, delay=2),
    flt.partition(24, 44, (0, 1, 2), (3, 4)),
))
WAN_MIXES = [
    (
        "wan-3region",
        dict(
            max_delay=wanm.PRESET_DELAY_BOUND,
            edges=wanm.edge_faults(wanm.WAN3, 5),
            schedule=SCHED_WAN_GRAY,
        ),
        5,
        2,
    ),
    (
        "wan-5region",
        dict(
            max_delay=wanm.PRESET_DELAY_BOUND,
            edges=wanm.edge_faults(wanm.WAN5, 5),
            schedule=SCHED_WAN5_GRAY,
        ),
        5,
        2,
    ),
]
#: node->region maps per WAN mix label (the recorder's region-pair
#: counters; sweep_fleet threads them through run(regions=))
WAN_REGIONS = {
    "wan-3region": wanm.node_regions(wanm.WAN3, 5),
    "wan-5region": wanm.node_regions(wanm.WAN5, 5),
}
#: preset region names per WAN mix label — the recorder's
#: ``region_pairs`` blocks render pairs by NAME (``us->ap``), not
#: bare index, wherever a preset is in scope
WAN_NAMES = {
    "wan-3region": wanm.WAN3.regions,
    "wan-5region": wanm.WAN5.regions,
}

N_IDS = 6  # ids per client chain (gated, in-order)
N_FREE = 8  # ungated values per proposer


def _workload(
    n_prop: int,
    rng: np.random.Generator,
    n_ids: int = N_IDS,
    n_free: int = N_FREE,
):
    """Per-proposer workload: one in-order gate chain + free values,
    with globally unique vids.  ``n_ids``/``n_free`` size the chain
    and the free set (the model checker's scopes shrink them to keep
    exhaustive sweeps cheap; the sweep defaults stay canonical)."""
    workload, gates, chains = [], [], []
    nxt = 100
    for p in range(n_prop):
        chain = np.arange(nxt, nxt + n_ids, dtype=np.int32)
        nxt += n_ids
        free = np.arange(nxt, nxt + n_free, dtype=np.int32)
        nxt += n_free
        rng.shuffle(free)
        w = np.concatenate([chain, free])
        g = np.concatenate(
            [
                np.asarray([int(val.NONE)] + chain[:-1].tolist(), np.int32),
                np.full(n_free, int(val.NONE), np.int32),
            ]
        )
        workload.append(w)
        gates.append(g)
        chains.append(chain)
    return workload, gates, chains


# Crash-aware invariant suite — shared with the shrinker so a shrunk
# repro artifact is judged by exactly the sweep's rules.  Kept as a
# module-level name: tests monkeypatch it to inject failures.
_validate_run = shr.validate_run


def _check_run(r, cfg: SimConfig, workload, chains) -> None:
    """Quiescence (excused only when every proposer crashed — nobody
    is left to close the log) + the crash-aware suite; mirrors
    shrink.check_run through the patchable ``_validate_run`` seam."""
    all_props_crashed = all(r.crashed[node] for node in cfg.proposers)
    if not r.done and not all_props_crashed:
        raise validate.InvariantViolation(
            f"no quiescence in {r.rounds} rounds"
        )
    _validate_run(r, cfg, workload, chains)


def sweep(
    n_seeds: int = 8,
    base_seed: int = 0,
    verbose: bool = True,
    triage_dir: str | None = None,
    mixes=None,
) -> dict:
    logger = logm.get_logger(
        "stress", logm.parse_level("INFO" if verbose else "WARN")
    )
    runs, failures = 0, []
    t0 = time.perf_counter()
    from tpu_paxos.utils import prng

    for label, fkw, n_nodes, n_prop in (MIXES if mixes is None else mixes):
        go = None  # compiled once per mix; seeds share shapes
        for s in range(n_seeds):
            seed = base_seed + s
            rng = np.random.default_rng(
                seed * 7919 + zlib.crc32(label.encode()) % 1000
            )
            workload, gates, chains = _workload(n_prop, rng)
            cfg = SimConfig(
                n_nodes=n_nodes,
                n_instances=2 * sum(len(w) for w in workload),
                proposers=tuple(range(n_prop)),
                seed=seed,
                max_rounds=20_000,
                faults=FaultConfig(**fkw),
            )
            pend, gate, tail, c = simm.prepare_queues(cfg, workload, gates)
            if go is None:
                round_fn = simm.build_engine(
                    cfg, c, vid_cap=simm.gates_vid_cap(workload, gates)
                )

                @jax.jit
                def go(root, st, _round_fn=round_fn, _mr=cfg.round_budget):
                    return jax.lax.while_loop(
                        lambda x: (~x.done) & (x.t < _mr),
                        lambda x: _round_fn(root, x),
                        st,
                    )

            root = prng.root_key(cfg.seed)
            state = simm.init_state(cfg, pend, gate, tail, root)
            r = simm.to_result(
                go(root, state), np.unique(np.concatenate(workload))
            )
            runs += 1
            try:
                _check_run(r, cfg, workload, chains)
            except validate.InvariantViolation as e:
                failure = {"mix": label, "seed": seed, "error": str(e)[:300]}
                logger.error("FAIL mix=%s seed=%d: %s", label, seed, e)
                if triage_dir:
                    # shrink the failing case to a minimal schedule and
                    # pin it as a one-command repro artifact
                    os.makedirs(triage_dir, exist_ok=True)
                    path = os.path.join(
                        triage_dir, f"repro_{label}_{seed}.json"
                    )
                    try:
                        case = shr.ReproCase(
                            cfg=cfg, workload=workload, gates=gates,
                            chains=chains,
                        )
                        art = shr.triage(case, path, logger=logger)
                        failure["artifact"] = path
                        failure["shrink_seconds"] = art.get("shrink_seconds")
                        logger.error("repro artifact written to %s", path)
                    except Exception as te:  # triage must never mask a failure
                        failure["triage_error"] = str(te)[:300]
                failures.append(failure)
        logger.info(
            "mix %-14s: %d seeds done (cumulative %d runs, %d failures)",
            label, n_seeds, runs, len(failures),
        )
    n_mixes = len(MIXES if mixes is None else mixes)
    return {
        "metric": "stress_sweep",
        "runs": runs,
        "mixes": n_mixes,
        "seeds_per_mix": n_seeds,
        "failures": failures,
        "ok": not failures,
        "seconds": round(time.perf_counter() - t0, 1),
    }


def _mix_telemetry(rep, cfg: SimConfig, region_names: tuple = ()) -> dict:
    """One mix's flight-recorder block: every value is a pure function
    of (cfg, seeds) — no wall clock — so the block is golden-testable
    (tests/test_telemetry.py pins it against
    tests/data/stress_telemetry_golden.json).

    ``drop_rate_observed`` is the built-in sanity column: i.i.d.-layer
    drops over fault-layer offered edges, in the knob's per-1e4
    units.  For burst-free mixes it should straddle the configured
    ``drop_rate``; burst episodes push it above (their windows add to
    the sampled rate).

    The ``windows`` column is the TIME-RESOLVED view of the same
    lanes (telemetry/recorder.reduce_lanes_windows): per-bucket
    latency quantiles, drop counts, and stall depth over the virtual
    clock, so a mix's latency blowout can be read against the bucket
    its episodes live in rather than smeared over the whole run."""
    from tpu_paxos.telemetry import recorder as telem

    ts = rep.telemetry
    if ts is None:
        return {}
    agg = telem.reduce_lanes(
        ts, getattr(rep, "windows", None),
        region_names=tuple(region_names),
    )
    offered, dropped = agg["offered"], agg["dropped"]
    return {
        **{k: agg[k] for k in (
            "offered", "dropped", "duped", "delayed",
            "latency_p50", "latency_p99", "latency_max",
            "decided", "takeovers", "requeues", "restarts",
            "heal_gap_min", "stall_depth_max", "duel_depth_max",
        )},
        # the WAN plane: offered-vs-dropped per region pair (all-zero
        # maps collapse to one 1x1 "region" for the classic mixes)
        "region_pairs": agg["region_pairs"],
        **({"windows": agg["windows"]} if "windows" in agg else {}),
        "drop_rate_configured": cfg.faults.drop_rate,
        "drop_rate_observed": (
            round(1e4 * dropped / offered, 1) if offered else 0.0
        ),
    }


# jax.monitoring has no listener-removal API, so every CompileCensus
# stays registered for the life of the process once started; reuse one
# module-level census across sweep_fleet calls instead of leaking a
# deactivated listener per call (compiles_per_mix reads deltas, so
# counts carried over from earlier sweeps are harmless).
_fleet_census: tracecount.CompileCensus | None = None


def sweep_fleet(
    n_seeds: int = 8,
    base_seed: int = 0,
    verbose: bool = True,
    triage_dir: str | None = None,
    mixes=None,
) -> dict:
    """The episode-mix sweeps through the FLEET runner: per mix, every
    seed becomes a lane of one device-batched dispatch
    (fleet/runner.py) — the schedule rides per-lane runtime tables
    and the i.i.d. knobs ride per-lane runtime FaultKnobs, so mixes
    of one geometry share ONE compiled executable (the envelope cache,
    fleet/envelope.py: all four episode mixes are 5-node/2-proposer
    and hit the same envelope) and every seed's whole run happens in a
    single XLA call.  Lanes are judged on device by the invariant
    subset (fleet/verdict.py); only failing lanes transfer for the
    full crash-aware suite + shrink triage.  The host loop (``sweep``)
    stays the fallback and the single-run default.

    Each lane is decision-log-identical to the host loop's run of the
    same (mix, seed) — same cfg, workload, and PRNG root — so a lane
    failure here IS a seed failure there.  The summary's
    ``compiles_per_mix`` pins the envelope win: XLA compiles inside
    each mix's dispatch, counted via ``tracecount.engine_scope`` —
    after the first mix warms the envelope, subsequent mixes read 0."""
    from tpu_paxos.fleet import envelope as env

    logger = logm.get_logger(
        "stress", logm.parse_level("INFO" if verbose else "WARN")
    )
    mixes = (EPISODE_MIXES + WAN_MIXES) if mixes is None else mixes
    runs, failures = 0, []
    lane_seconds, lanes_total = 0.0, 0
    compiles_per_mix: dict[str, int] = {}
    telemetry_per_mix: dict[str, dict] = {}
    global _fleet_census
    if _fleet_census is None:
        _fleet_census = tracecount.CompileCensus()
    census = _fleet_census.start()
    t0 = time.perf_counter()
    try:
        for label, fkw, n_nodes, n_prop in mixes:
            sched = fkw["schedule"]
            base_kw = {k: v for k, v in fkw.items() if k != "schedule"}
            lanes = []  # (seed, workload, gates, chains)
            for s in range(n_seeds):
                seed = base_seed + s
                rng = np.random.default_rng(
                    seed * 7919 + zlib.crc32(label.encode()) % 1000
                )
                workload, gates, chains = _workload(n_prop, rng)
                lanes.append((seed, workload, gates, chains))
            cfg = SimConfig(
                n_nodes=n_nodes,
                n_instances=2 * sum(len(w) for w in lanes[0][1]),
                proposers=tuple(range(n_prop)),
                seed=base_seed,
                max_rounds=20_000,
                faults=FaultConfig(**base_kw),
            )
            runner = env.runner_for(
                cfg, lanes[0][1], lanes[0][2], telemetry=True
            )
            before = census.engine_counts.get("fleet", 0)
            rmap = WAN_REGIONS.get(label)
            rep = runner.run(
                [ln[0] for ln in lanes],
                [sched] * n_seeds,
                workloads=[(ln[1], ln[2]) for ln in lanes],
                knobs=[cfg.faults] * n_seeds,
                regions=None if rmap is None else [rmap] * n_seeds,
            )
            compiles_per_mix[label] = (
                census.engine_counts.get("fleet", 0) - before
            )
            telemetry_per_mix[label] = _mix_telemetry(
                rep, cfg, region_names=WAN_NAMES.get(label, ())
            )
            runs += n_seeds
            lanes_total += n_seeds
            lane_seconds += rep.seconds
            for i in rep.failing:
                seed, workload, gates, chains = lanes[i]
                r = rep.lane_result(i)
                try:
                    _check_run(r, rep.lane_cfg(i), workload, chains)
                    # device verdict flagged a lane the full suite clears:
                    # a parity/verdict bug — report it as its own failure
                    failures.append({
                        "mix": label, "seed": seed,
                        "error": "fleet verdict flagged a lane the full "
                        "suite clears (verdict/parity drift)",
                    })
                    logger.error(
                        "FLEET ANOMALY mix=%s seed=%d: verdict red, "
                        "suite green", label, seed,
                    )
                except validate.InvariantViolation as e:
                    failure = {"mix": label, "seed": seed, "error": str(e)[:300]}
                    logger.error("FAIL mix=%s seed=%d: %s", label, seed, e)
                    if triage_dir:
                        os.makedirs(triage_dir, exist_ok=True)
                        path = os.path.join(
                            triage_dir, f"repro_{label}_{seed}.json"
                        )
                        try:
                            case = shr.ReproCase(
                                cfg=rep.lane_cfg(i), workload=workload,
                                gates=gates, chains=chains,
                            )
                            art = shr.triage(case, path, logger=logger)
                            failure["artifact"] = path
                            failure["shrink_seconds"] = art.get("shrink_seconds")
                            logger.error("repro artifact written to %s", path)
                        except Exception as te:
                            failure["triage_error"] = str(te)[:300]
                    failures.append(failure)
            logger.info(
                "fleet mix %-14s: %d lanes in %.2fs (%.1f lanes/sec, "
                "%d compiles)",
                label, n_seeds, rep.seconds, rep.lanes_per_sec,
                compiles_per_mix[label],
            )
    finally:
        # jax.monitoring has no listener-removal API, so an
        # abandoned census would keep counting every later
        # compile in the process — stop() must run on all paths
        census.stop()
    return {
        "metric": "stress_sweep_fleet",
        "runs": runs,
        "mixes": len(mixes),
        "seeds_per_mix": n_seeds,
        "lanes": lanes_total,
        "lanes_per_sec": round(lanes_total / max(lane_seconds, 1e-9), 2),
        "compiles_per_mix": compiles_per_mix,
        "telemetry": telemetry_per_mix,
        "failures": failures,
        "ok": not failures,
        "seconds": round(time.perf_counter() - t0, 1),
    }


def sweep_sharded(
    n_seeds: int = 2, base_seed: int = 0, verbose: bool = True,
    triage_dir: str | None = None,
) -> dict:
    """The debug.conf and crashy mixes PLUS every episode mix through
    the SHARDED engine on the current device mesh (run under a virtual
    multi-device CPU backend via ``--sharded``, which re-execs in a
    clean subprocess).  Chains stay shard-affine via split_workload,
    so the same crash-aware invariant suite applies; episode schedules
    are compile-time constants replicated across shards.

    With ``triage_dir``, failing seeds are shrunk and written as
    ``engine="sharded"`` repro artifacts — ``python -m tpu_paxos
    repro`` replays them through ``parallel/sharded_sim.py`` on a mesh
    of the recorded device count (sharded placement differs from the
    unsharded engine's, so the byte-compare only holds engine-for-
    engine at the same mesh size)."""
    import jax

    from tpu_paxos.parallel import mesh as pmesh
    from tpu_paxos.parallel import sharded_sim

    logger = logm.get_logger(
        "stress", logm.parse_level("INFO" if verbose else "WARN")
    )
    mesh = pmesh.make_instance_mesh()
    runs, failures = 0, []
    t0 = time.perf_counter()
    for label, fkw, n_nodes, n_prop in (MIXES[1], MIXES[4], *EPISODE_MIXES):
        for s in range(n_seeds):
            seed = base_seed + s
            rng = np.random.default_rng(
                seed * 7919 + zlib.crc32(label.encode()) % 1000
            )
            workload, gates, chains = _workload(n_prop, rng)
            n_inst = 2 * sum(len(w) for w in workload)
            n_inst = max(
                n_inst + (-n_inst) % mesh.size,
                sharded_sim.min_instances(workload, gates, mesh.size),
            )
            cfg = SimConfig(
                n_nodes=n_nodes,
                n_instances=n_inst,
                proposers=tuple(range(n_prop)),
                seed=seed,
                max_rounds=20_000,
                faults=FaultConfig(**fkw),
            )
            r = sharded_sim.run_sharded(cfg, mesh, workload, gates)
            runs += 1
            try:
                _check_run(r, cfg, workload, chains)
            except validate.InvariantViolation as e:
                failure = {"mix": label, "seed": seed, "error": str(e)[:300]}
                logger.error("FAIL sharded mix=%s seed=%d: %s", label, seed, e)
                if triage_dir:
                    os.makedirs(triage_dir, exist_ok=True)
                    path = os.path.join(
                        triage_dir, f"repro_sharded_{label}_{seed}.json"
                    )
                    try:
                        case = shr.ReproCase(
                            cfg=cfg, workload=workload, gates=gates,
                            chains=chains, engine="sharded",
                            devices=mesh.size,
                        )
                        shr.triage(case, path, logger=logger)
                        failure["artifact"] = path
                        logger.error("repro artifact written to %s", path)
                    except Exception as te:  # triage must never mask
                        failure["triage_error"] = str(te)[:300]
                failures.append(failure)
        logger.info("sharded mix %-11s: %d seeds done", label, n_seeds)
    return {
        "metric": "stress_sweep_sharded",
        "runs": runs,
        "devices": mesh.size,
        "platform": jax.devices()[0].platform,
        "failures": failures,
        "ok": not failures,
        "seconds": round(time.perf_counter() - t0, 1),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=8, help="seeds per mix")
    ap.add_argument("--base-seed", type=int, default=0)
    ap.add_argument(
        "--sharded",
        action="store_true",
        help="also run the sharded engine on an 8-device virtual CPU "
        "mesh (subprocess)",
    )
    ap.add_argument(
        "--triage-dir",
        type=str,
        default="",
        help="on any failing seed, shrink the fault schedule to a "
        "minimal failing case and write a repro artifact here "
        "(replay with `python -m tpu_paxos repro <artifact>`)",
    )
    ap.add_argument(
        "--fleet",
        action="store_true",
        help="route the episode mixes through the device-batched "
        "fleet runner (seeds become lanes of one dispatch per mix; "
        "the host loop keeps the i.i.d.-only mixes and remains the "
        "fallback)",
    )
    args = ap.parse_args(argv)
    if args.fleet:
        host_mixes = [m for m in MIXES if "schedule" not in m[1]]
        summary = sweep(
            args.seeds, args.base_seed, triage_dir=args.triage_dir or None,
            mixes=host_mixes,
        )
        print(json.dumps(summary))
        fleet_summary = sweep_fleet(
            args.seeds, args.base_seed, triage_dir=args.triage_dir or None,
        )
        print(json.dumps(fleet_summary))
        ok = summary["ok"] and fleet_summary["ok"]
    else:
        summary = sweep(
            args.seeds, args.base_seed, triage_dir=args.triage_dir or None
        )
        print(json.dumps(summary))
        ok = summary["ok"]
    if args.sharded:
        import os
        import subprocess

        try:
            # repo-root helper (not shipped in the wheel): provides the
            # virtual-CPU child bootstrap.  An installed package has no
            # repo root — skip the sharded sweep with a clear note
            # instead of an ImportError.
            import __graft_entry__ as ge
        except ImportError:
            print(
                json.dumps(
                    {
                        "metric": "stress_sweep_sharded",
                        "skipped": "__graft_entry__ not importable "
                        "(installed-package run; sharded sweep needs "
                        "the repo checkout)",
                    }
                )
            )
            return 0 if ok else 1

        code = ge.virtual_cpu_bootstrap(8) + (
            "import json\n"
            "from tpu_paxos.harness import stress\n"
            f"s = stress.sweep_sharded(n_seeds=2, base_seed={args.base_seed},"
            f" triage_dir={(args.triage_dir or None)!r})\n"
            "print('STRESS_SHARDED:' + json.dumps(s))\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=ge._spawn_env(8),
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
            capture_output=True,
            text=True,
            timeout=1200,
        )
        out = [
            ln for ln in proc.stdout.splitlines()
            if ln.startswith("STRESS_SHARDED:")
        ]
        if proc.returncode != 0 or not out:
            print(
                json.dumps(
                    {
                        "metric": "stress_sweep_sharded",
                        "ok": False,
                        "error": proc.stderr[-500:],
                    }
                )
            )
            ok = False
        else:
            sharded = json.loads(out[0][len("STRESS_SHARDED:"):])
            print(json.dumps(sharded))
            ok = ok and sharded["ok"]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
