"""C++ reference runner — the decision-parity anchor.

The north star is "≥10M instances/sec with decision parity vs the C++
``multi/`` binary" (BASELINE.json).  This module closes the loop: it
compiles the reference (with its own flags, ref multi/Makefile:1-2),
runs it on the canonical debug.conf workload (ref
multi/debug.conf.sample:1, multi/run.sh:5), parses each server's
final committed-value dump in the documented grammar (ref
multi/paxos.cpp:18-22, printed at multi/paxos.cpp:1694-1703), and
checks the reference's own end-of-run invariants (ref
multi/main.cpp:566-573) *independently* on the parsed logs — the same
checks ``harness/validate`` applies to tpu_paxos runs.  Parity =
both systems satisfy identical agreement / exactly-once /
in-order-client invariants on the equivalent workload (SURVEY §7
hard part (c): the C++ run is wall-clock nondeterministic, so parity
is invariant parity per config, not byte-equal logs).

Nothing here writes to /root/reference: sources are compiled in place
into a build directory under the repo.
"""

from __future__ import annotations

import dataclasses
import os
import re
import subprocess
from typing import Sequence

import numpy as np

REFERENCE_DIR = os.environ.get("TPU_PAXOS_REFERENCE", "/root/reference/multi")
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BUILD_DIR = os.path.join(_REPO, "build", "ref_multi")

# One committed entry in the debug grammar (ref multi/paxos.cpp:18-22):
#   <proposal-id>(proposer:value-id)+value   normal
#   <proposal-id>(proposer:value-id)-        no-op
#   <proposal-id>(proposer:value-id)m+id=..  add member (disabled in multi/)
#   <proposal-id>(proposer:value-id)m-id     del member (disabled in multi/)
_ENTRY = re.compile(
    r"<(?P<ballot>\d+)>\((?P<proposer>\d+):(?P<vid>\d+)\)"
    r"(?P<kind>m\+|m-|\+|-)(?P<value>[^,(]*)"
)
_FINAL = re.compile(
    r"\[srv-(?P<server>\d+)-paxos:\d+\].*final committed values: "
    r"(?P<body>.*) \((?P<count>\d+) in total\)"
)


@dataclasses.dataclass(frozen=True)
class CommittedEntry:
    """One decided instance as the reference dumps it (instance ids are
    implicit: the dump iterates the committed map in instance order)."""

    ballot: int
    proposer: int
    value_id: int
    noop: bool
    value: str  # payload text for normal values ("" for no-ops)


@dataclasses.dataclass(frozen=True)
class ReferenceRun:
    returncode: int
    all_done: bool  # the reference's own asserts all passed
    logs: dict[int, list[CommittedEntry]]  # server index -> committed seq
    raw_log: str


def build_reference(build_dir: str = DEFAULT_BUILD_DIR) -> str:
    """Compile the reference binary (its own one-line Makefile recipe,
    ref multi/Makefile:1-2) into ``build_dir``; returns the binary path.
    Recompiles only when sources are newer than the binary."""
    os.makedirs(build_dir, exist_ok=True)
    binary = os.path.join(build_dir, "main")
    # paxos.h is a staleness dependency but NOT a compilation unit: it has
    # no standalone #include <map> (its .cpp consumers include that first),
    # and the reference Makefile compiles only the two .cpp files.
    srcs = [
        os.path.join(REFERENCE_DIR, "main.cpp"),
        os.path.join(REFERENCE_DIR, "paxos.cpp"),
    ]
    deps = srcs + [os.path.join(REFERENCE_DIR, "paxos.h")]
    if os.path.exists(binary) and all(
        os.path.getmtime(binary) >= os.path.getmtime(s) for s in deps
    ):
        return binary
    subprocess.run(
        ["g++", "-g", "-Wall", "-o", binary, "-lrt", "-pthread", *srcs],
        check=True,
        capture_output=True,
        text=True,
    )
    return binary


def reference_args(
    srvcnt: int = 4,
    cltcnt: int = 4,
    idcnt: int = 10,
    propose_interval: int = 100,
    seed: int = 0,
    prepare_delay_min: int = 1000,
    prepare_delay_max: int = 3000,
    prepare_retry_count: int = 3,
    prepare_retry_timeout: int = 500,
    accept_retry_count: int = 2,
    accept_retry_timeout: int = 300,
    commit_retry_timeout: int = 1000,
    drop_rate: int = 500,
    dup_rate: int = 1000,
    min_delay: int = 0,
    max_delay: int = 500,
    log_level: int = 1,
) -> list[str]:
    """The reference CLI line (ref multi/main.cpp:456-496); defaults are
    the canonical debug.conf.sample values (ref multi/debug.conf.sample:1).
    ``log_level=1`` (DEBUG) is required so the final committed dump is
    emitted (ref multi/paxos.cpp:1703 logs at DEBUG)."""
    return [
        str(srvcnt),
        str(cltcnt),
        str(idcnt),
        str(propose_interval),
        f"--seed={seed}",
        f"--paxos-prepare-delay-min={prepare_delay_min}",
        f"--paxos-prepare-delay-max={prepare_delay_max}",
        f"--paxos-prepare-retry-count={prepare_retry_count}",
        f"--paxos-prepare-retry-timeout={prepare_retry_timeout}",
        f"--paxos-accept-retry-count={accept_retry_count}",
        f"--paxos-accept-retry-timeout={accept_retry_timeout}",
        f"--paxos-commit-retry-timeout={commit_retry_timeout}",
        f"--log-level={log_level}",
        f"--net-drop-rate={drop_rate}",
        f"--net-dup-rate={dup_rate}",
        f"--net-min-delay={min_delay}",
        f"--net-max-delay={max_delay}",
    ]


def fast_reference_args(seed: int = 0, **overrides) -> list[str]:
    """The debug.conf workload with every wall-clock knob scaled down
    10-20x (fault *rates* untouched) so a CI parity check runs in
    seconds instead of the canonical ~50s.  Timeouts scale together, so
    the retry-ladder geometry — and therefore the set of reachable
    interleavings — is preserved."""
    kw = dict(
        propose_interval=10,
        seed=seed,
        prepare_delay_min=100,
        prepare_delay_max=300,
        prepare_retry_timeout=50,
        accept_retry_timeout=30,
        commit_retry_timeout=100,
        max_delay=50,
    )
    kw.update(overrides)
    return reference_args(**kw)


def parse_committed_logs(log_text: str) -> dict[int, list[CommittedEntry]]:
    """Extract every server's final committed sequence from a run log.

    The dump line (ref multi/paxos.cpp:1694-1703) renders the committed
    map in instance order; entry k of the list is the k-th committed
    instance of that server."""
    logs: dict[int, list[CommittedEntry]] = {}
    for m in _FINAL.finditer(log_text):
        server = int(m.group("server"))
        body = m.group("body")
        entries = [
            CommittedEntry(
                ballot=int(e.group("ballot")),
                proposer=int(e.group("proposer")),
                value_id=int(e.group("vid")),
                noop=e.group("kind") == "-",
                value=e.group("value").strip(),
            )
            for e in _ENTRY.finditer(body)
        ]
        if len(entries) != int(m.group("count")):
            raise ValueError(
                f"server {server}: parsed {len(entries)} entries, "
                f"dump claims {m.group('count')}"
            )
        logs[server] = entries
    return logs


def run_reference(
    args: Sequence[str],
    binary: str | None = None,
    timeout: float = 600.0,
) -> ReferenceRun:
    """Run the reference binary and parse its committed logs."""
    if binary is None:
        binary = build_reference()
    try:
        proc = subprocess.run(
            [binary, *args],
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd=os.path.dirname(binary),
        )
    except subprocess.TimeoutExpired as e:
        raise RuntimeError(
            f"reference binary timed out after {timeout}s; partial "
            f"output:\n{(e.output or '')[-2000:]}"
        ) from e
    log = (proc.stdout or "") + (proc.stderr or "")
    return ReferenceRun(
        returncode=proc.returncode,
        all_done="All done" in log,
        logs=parse_committed_logs(log),
        raw_log=log,
    )


# ------------------------------------------------------------ invariants


def in_order_chains(cltcnt: int, idcnt: int) -> list[np.ndarray]:
    """Per in-order client, the id chain that must execute in order:
    clients 0..cltcnt/2-1, ids k=0..idcnt/2 (ref multi/main.cpp:398-411
    gates the proposal of each on the previous; the SM checks execution
    order for exactly this range, :202-212)."""
    return [
        np.asarray([c * idcnt + k for k in range(idcnt // 2 + 1)], np.int64)
        for c in range(cltcnt // 2)
    ]


def check_reference_invariants(
    run: ReferenceRun, srvcnt: int, cltcnt: int, idcnt: int
) -> None:
    """Independently re-assert the reference's end-of-run invariants on
    the parsed logs (ref multi/main.cpp:566-573 + the SM's online
    in-order check at :202-212).  The binary asserts these itself
    (rc=0 + "All done"), but re-deriving them from the dump is what
    makes the tpu_paxos comparison meaningful: both systems are judged
    by the same external checker."""
    from tpu_paxos.harness import validate

    if run.returncode != 0 or not run.all_done:
        raise validate.InvariantViolation(
            f"reference run failed (rc={run.returncode}, "
            f"all_done={run.all_done})"
        )
    if set(run.logs.keys()) != set(range(srvcnt)):
        raise validate.InvariantViolation(
            f"expected committed dumps from servers 0..{srvcnt - 1}, "
            f"got {sorted(run.logs)}"
        )
    seqs = [
        np.asarray(
            [int(e.value) for e in run.logs[s] if not e.noop], np.int64
        )
        for s in range(srvcnt)
    ]
    # Agreement: identical executed sequences (ref multi/main.cpp:568-570).
    for s in range(1, srvcnt):
        if not np.array_equal(seqs[s], seqs[0]):
            raise validate.InvariantViolation(
                f"server {s} executed sequence differs from server 0"
            )
    # Exactly-once: sorted ids are exactly 0..N-1 (ref :571-573).
    want = np.arange(cltcnt * idcnt, dtype=np.int64)
    if not np.array_equal(np.sort(seqs[0]), want):
        raise validate.InvariantViolation(
            f"executed ids are not exactly 0..{cltcnt * idcnt - 1}"
        )
    # In-order clients: clients 0..cltcnt/2-1 propose ids with
    # seq <= idcnt/2 strictly in order (ref multi/main.cpp:398-411,
    # SM check :202-212).
    validate.check_in_order_clients(seqs[0], in_order_chains(cltcnt, idcnt))


# ------------------------------------------ equivalent tpu_paxos config


def equivalent_workload(srvcnt: int, cltcnt: int, idcnt: int):
    """Reproduce the reference client workload as per-proposer queues.

    Client c proposes ids [c*idcnt, (c+1)*idcnt); its k-th id goes to
    server ``srvcnt - 1 - k % srvcnt`` (ref multi/main.cpp:414).
    Clients c < cltcnt/2 propose their first idcnt/2+1 ids strictly in
    order — the next only after the previous is chosen (ref
    multi/main.cpp:398-411) — expressed as gate chains.  vids are the
    reference's global ids themselves, so exactly-once means "vids are
    exactly 0..cltcnt*idcnt-1", the reference's own check.

    Returns (workload, gates, in_order_vids): per-proposer vid arrays,
    per-proposer gate arrays, and the per-client in-order chains for
    validation."""
    per_server: list[list[int]] = [[] for _ in range(srvcnt)]
    per_server_gate: list[list[int]] = [[] for _ in range(srvcnt)]
    # Interleave clients round-robin by k, as concurrent clients do.
    for k in range(idcnt):
        for c in range(cltcnt):
            vid = c * idcnt + k
            sidx = srvcnt - 1 - (k % srvcnt)
            gate = (
                vid - 1
                if c < cltcnt // 2 and 1 <= k <= idcnt // 2
                else -1
            )
            per_server[sidx].append(vid)
            per_server_gate[sidx].append(gate)
    workload = [np.asarray(w, np.int32) for w in per_server]
    gates = [np.asarray(g, np.int32) for g in per_server_gate]
    return workload, gates, in_order_chains(cltcnt, idcnt)


def run_equivalent_sim(
    srvcnt: int = 4,
    cltcnt: int = 4,
    idcnt: int = 10,
    seed: int = 0,
    drop_rate: int = 500,
    dup_rate: int = 1000,
    max_delay_rounds: int = 2,
    n_instances: int | None = None,
    max_rounds: int = 4000,
):
    """Run the tpu_paxos general engine on the workload equivalent of a
    reference config; returns (SimResult, in_order_vids).

    Wall-clock delays map to round delays: the canonical 0-500ms range
    with ~100ms round-trip granularity is 0-2 rounds of the
    bulk-synchronous schedule."""
    from tpu_paxos import config as cfgm
    from tpu_paxos.core import sim

    workload, gates, in_order = equivalent_workload(srvcnt, cltcnt, idcnt)
    if n_instances is None:
        n_instances = cltcnt * idcnt * 2  # headroom for no-op holes
    cfg = cfgm.SimConfig(
        n_nodes=srvcnt,
        n_instances=n_instances,
        proposers=tuple(range(srvcnt)),
        seed=seed,
        max_rounds=max_rounds,
        faults=cfgm.FaultConfig(
            drop_rate=drop_rate,
            dup_rate=dup_rate,
            min_delay=0,
            max_delay=max_delay_rounds,
        ),
    )
    return sim.run(cfg, workload, gates), in_order


def check_parity(
    srvcnt: int = 4,
    cltcnt: int = 4,
    idcnt: int = 10,
    seed: int = 0,
    reference_args_list: Sequence[str] | None = None,
    timeout: float = 600.0,
) -> dict:
    """The full parity anchor (BASELINE config 1): run the C++ binary
    and the tpu_paxos engine on the equivalent config and assert the
    SAME invariants on both.  Returns a summary dict."""
    from tpu_paxos.harness import validate

    ref = run_reference(
        reference_args_list
        if reference_args_list is not None
        else fast_reference_args(seed=seed),
        timeout=timeout,
    )
    check_reference_invariants(ref, srvcnt, cltcnt, idcnt)

    res, in_order = run_equivalent_sim(srvcnt, cltcnt, idcnt, seed=seed)
    if not res.done:
        raise validate.InvariantViolation(
            f"tpu_paxos run did not quiesce in {res.rounds} rounds"
        )
    seqs = validate.check_all(res.learned, res.expected_vids)
    validate.check_in_order_clients(seqs[0], in_order)
    return {
        "reference": {
            "rc": ref.returncode,
            "executed": len([e for e in ref.logs[0] if not e.noop]),
            "instances": len(ref.logs[0]),
        },
        "tpu_paxos": {
            "rounds": res.rounds,
            "executed": int((res.chosen_vid >= 0).sum()),
            "instances": int((res.chosen_vid != -1).sum()),
        },
        "invariants": ["agreement", "exactly_once", "in_order_clients"],
        "parity": True,
    }
