"""Failure triage: greedy shrinking of a failing stress case into a
minimal, one-command repro artifact.

When a stress seed violates an invariant, the interesting question is
never "which seed" — it is "which *part of the fault schedule* makes
the violation happen".  This module answers it the property-testing
way: re-run the deterministic case under progressively smaller inputs
and keep every reduction that still fails —

1. drop whole episodes from the ``FaultSchedule`` (greedy, to a fixed
   point);
2. narrow each surviving episode's ``[t0, t1)`` interval by bisection
   (cut the tail half, then the head half, while the case still
   fails), and halve surviving gray episodes' delay inflation;
3. collapse a per-edge fault matrix (``cfg.faults.edges``) — drop it
   entirely, else flatten it to the equivalent uniform scalar knobs —
   so geo repros shrink to scalar configs when the matrix structure
   is irrelevant;
4. zero the i.i.d. fault knobs (drop/dup/delay/crash) and the
   ``delivery_cut`` flag one at a time;
5. minimize the seed (try 0 and successive bisections toward 0).

The result is written as a JSON *repro artifact* — fully
self-contained: config, workload, gates, in-order chains, extra
checks, the violation text, and the decision-log sha256 — which
``python -m tpu_paxos repro <artifact>`` re-executes byte-identically
(the engine is a pure function of the artifact's fields; the spirit
of ref member/diff.sh's record-vs-replay byte compare).

Everything here drives the *general* engine (core/sim.run); the
membership engine has its own record/replay artifact (the injection
log, membership/engine.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

import numpy as np

from tpu_paxos.analysis.artifact_schema import (
    ARTIFACT_FORMAT,
    ArtifactSchemaError,
    validate_artifact,
)
from tpu_paxos.config import (
    EdgeFaultConfig,
    FaultConfig,
    ProtocolConfig,
    SimConfig,
)
from tpu_paxos.core import faults as fltm
from tpu_paxos.core import sim as simm
from tpu_paxos.harness import validate
from tpu_paxos.replay.decision_log import decision_log

# Cap on shrink re-runs: each candidate evaluation is a full engine
# run (tiny configs, but a compile each when the schedule changes
# shape).  The greedy passes converge long before this in practice.
MAX_EVALS = 200


@dataclasses.dataclass
class ReproCase:
    """A fully-specified deterministic run plus its judgment criteria.

    ``engine`` selects which runner re-executes the case: ``"sim"``
    (core/sim.run, the default) or ``"sharded"``
    (parallel/sharded_sim.run_sharded over a ``devices``-wide instance
    mesh).  The sharded engine's instance PLACEMENT differs from the
    unsharded one's, so its decision logs only byte-compare against
    sharded replays at the SAME device count — the artifact records
    both fields and ``python -m tpu_paxos repro`` provisions the mesh
    accordingly."""

    cfg: SimConfig
    workload: list[np.ndarray]
    gates: list[np.ndarray] | None
    chains: list[np.ndarray]  # in-order client chains (may be empty)
    extra_checks: dict = dataclasses.field(default_factory=dict)
    engine: str = "sim"
    devices: int = 1

    def with_faults(self, faults: FaultConfig) -> "ReproCase":
        return dataclasses.replace(
            self, cfg=dataclasses.replace(self.cfg, faults=faults)
        )

    def with_schedule(self, sched: fltm.FaultSchedule | None) -> "ReproCase":
        if sched is not None and not sched.episodes:
            sched = None
        return self.with_faults(
            dataclasses.replace(self.cfg.faults, schedule=sched)
        )


def validate_run(r, cfg: SimConfig, workload, chains) -> None:
    """Crash-aware invariant suite shared by the stress sweep and the
    shrinker: safety (agreement, executed-identical, at-most-once,
    only-workload values) holds unconditionally; liveness is owed only
    to values whose proposer survived — a crashed proposer's undrained
    queue is legitimately lost (cf.
    tests/test_sim.py::test_crash_minority_safety_and_liveness).
    Paused/partitioned proposers get no such waiver: after the last
    heal their values are owed like anyone else's."""
    crashed_props = [
        i for i, node in enumerate(cfg.proposers) if r.crashed[node]
    ]
    full = np.unique(np.concatenate(workload))
    if not crashed_props:
        seqs = validate.check_all(r.learned, full)
    else:
        validate.check_agreement(r.learned)
        seqs = validate.check_executed_identical(r.learned)
        validate.check_exactly_once(r.learned, None)  # at most once
        chosen = r.chosen_vid[r.chosen_vid >= 0]
        extra = np.setdiff1d(chosen, full)
        if extra.size:
            raise validate.InvariantViolation(
                f"non-workload values chosen: {extra[:8].tolist()}"
            )
        live = [
            w for i, w in enumerate(workload) if i not in crashed_props
        ]
        if live:  # with every proposer crashed, no liveness is owed
            missing = np.setdiff1d(np.unique(np.concatenate(live)), chosen)
            if missing.size:
                raise validate.InvariantViolation(
                    f"surviving proposers' values never chosen: "
                    f"{missing[:8].tolist()}"
                )
    live_chains = [
        ch for i, ch in enumerate(chains) if i not in crashed_props and len(ch)
    ]
    if live_chains:
        validate.check_in_order_clients(max(seqs, key=len), live_chains)


def _extra_checks(case: ReproCase, r) -> None:
    """Artifact-recorded auxiliary invariants.  ``decision_round_max``
    is the test hook the acceptance path uses: assert every decision
    lands by round R (a deliberately-tight R turns a slow-converging
    schedule into a reproducible 'violation' without touching the real
    invariants)."""
    rmax = case.extra_checks.get("decision_round_max")
    if rmax is not None:
        rounds = r.chosen_round[r.chosen_vid != -1]
        if rounds.size and int(rounds.max()) > int(rmax):
            raise validate.InvariantViolation(
                f"decision at round {int(rounds.max())} exceeds "
                f"decision_round_max={int(rmax)}"
            )


def check_run(r, cfg: SimConfig, workload, chains) -> None:
    """Quiescence + the crash-aware suite.  Quiescence is excused only
    when EVERY proposer crashed — then no one is left to drive the log
    closed and liveness is vacuously unowed (safety still checked)."""
    all_props_crashed = all(r.crashed[node] for node in cfg.proposers)
    if not r.done and not all_props_crashed:
        raise validate.InvariantViolation(
            f"no quiescence in {r.rounds} rounds"
        )
    validate_run(r, cfg, workload, chains)


def _judge(case: ReproCase, r):
    """Shared judgment: quiescence + crash-aware suite + artifact-
    recorded extra checks; returns the violation string or None."""
    try:
        check_run(r, case.cfg, case.workload, case.chains)
        _extra_checks(case, r)
    except validate.InvariantViolation as e:
        return str(e)
    return None


def _runtime_candidate_eval(case: ReproCase):
    """Candidate evaluator on the shared runtime-knob fleet
    executable (fleet/envelope.py): every shrink move — episode
    drops, interval bisections, knob zeroings, seed minimization —
    changes only RUNTIME inputs (the schedule table, the FaultKnobs
    vector, the PRNG root), so all candidates of a case ride one
    compile; ``run_case`` recompiles per distinct schedule shape.
    Decision-log parity (tests/test_knobs.py) makes the two judges
    agree, and ``save_artifact`` re-verifies the shrunk case on the
    compile-time path regardless.  Returns ``eval(cand) ->
    violation-or-None``, or None when the case cannot ride the
    runtime engine (sharded cases)."""
    if case.engine != "sim":
        return None
    from tpu_paxos.fleet import envelope as env
    from tpu_paxos.fleet import runner as frun

    sched = case.cfg.faults.schedule
    max_eps = max(
        frun.MAX_EPISODES, 0 if sched is None else len(sched.episodes)
    )
    # telemetry=True: the stress sweep and the schedule search both
    # arm the recorder, so the shrinker's candidates land on the SAME
    # envelope key and reuse their compile (the recorder is
    # decision-log-neutral, so the judge's verdicts are unchanged)
    runner = env.runner_for(
        case.cfg, case.workload, case.gates, max_episodes=max_eps,
        telemetry=True,
    )

    def _eval(cand: ReproCase):
        fc = cand.cfg.faults
        rep = runner.run(
            [cand.cfg.seed],
            [fc.schedule],
            workloads=[(cand.workload, cand.gates)],
            knobs=[dataclasses.replace(fc, schedule=None)],
        )
        return _judge(cand, rep.lane_result(0))

    return _eval


#: Fixed lane width of the shrinker's batched candidate dispatches:
#: every batch pads to exactly this many lanes (modelcheck.chunk_pad)
#: so the whole greedy descent uses ONE lane shape — candidate count
#: never becomes a compile key.
SHRINK_BATCH_LANES = 8


def _runtime_batch_eval(case: ReproCase):
    """Multi-lane twin of :func:`_runtime_candidate_eval`: the
    independent candidates of one greedy pass (all episode drops of
    the current schedule, both bisection halves, the knob zeroings,
    the seed pair) become lanes of a single fleet dispatch via the
    model checker's chunk-padding path (analysis/chunking.chunk_pad
    — the ROADMAP item-2 follow-on).  Rides the SAME cached envelope
    runner as the sequential evaluator, so verdicts are pinned equal
    lane for lane (tests/test_modelcheck.py) and a warmed sweep pays
    dispatches, not compiles.  Returns ``eval_many(cands) ->
    [violation-or-None]``, or None for cases that cannot ride the
    runtime engine (sharded)."""
    if case.engine != "sim":
        return None
    from tpu_paxos.analysis import chunking
    from tpu_paxos.fleet import envelope as env
    from tpu_paxos.fleet import runner as frun

    sched = case.cfg.faults.schedule
    max_eps = max(
        frun.MAX_EPISODES, 0 if sched is None else len(sched.episodes)
    )
    runner = env.runner_for(
        case.cfg, case.workload, case.gates, max_episodes=max_eps,
        telemetry=True,
    )

    def eval_many(cands):
        out = []
        for chunk, n_real in chunking.chunk_pad(
            list(cands), SHRINK_BATCH_LANES
        ):
            rep = runner.run(
                [c.cfg.seed for c in chunk],
                [c.cfg.faults.schedule for c in chunk],
                workloads=[(c.workload, c.gates) for c in chunk],
                knobs=[
                    dataclasses.replace(c.cfg.faults, schedule=None)
                    for c in chunk
                ],
            )
            out.extend(
                _judge(chunk[i], rep.lane_result(i)) for i in range(n_real)
            )
        return out

    return eval_many


def run_case(case: ReproCase):
    """Execute the case; returns (SimResult, violation-string-or-None)."""
    if case.engine == "sharded":
        from tpu_paxos.parallel import mesh as pmesh
        from tpu_paxos.parallel import sharded_sim

        mesh = pmesh.make_instance_mesh(case.devices)
        if mesh.size != case.devices:
            raise RuntimeError(
                f"sharded repro needs {case.devices} devices; only "
                f"{mesh.size} visible (provision with --backend cpu, "
                "which the repro CLI does from the artifact's own "
                "device count)"
            )
        r = sharded_sim.run_sharded(
            case.cfg, mesh, case.workload, case.gates
        )
    else:
        r = simm.run(case.cfg, case.workload, case.gates)
    return r, _judge(case, r)


def decision_log_text(case: ReproCase, r) -> str:
    """Canonical decision-log rendering for the byte-compare surface;
    stride is derived from the workload so arbitrary vids decode
    stably."""
    stride = int(max(int(np.max(w)) for w in case.workload if len(w))) + 1
    return decision_log(
        r.chosen_vid, r.chosen_ballot,
        stride=stride, n_instances=case.cfg.n_instances,
    )


def _sha256(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()


class _Budget:
    def __init__(self, n: int):
        self.left = n

    def spend(self) -> bool:
        self.left -= 1
        return self.left >= 0


def shrink_case(
    case: ReproCase, max_evals: int = MAX_EVALS, logger=None,
    batch: bool = True, stats: dict | None = None,
) -> tuple[ReproCase, str]:
    """Greedily minimize a failing case (see module doc for the move
    set).  Returns (shrunk case, its violation).  Raises ValueError if
    the input case does not fail — there is nothing to triage.

    Each pass's independent candidates (every episode drop of the
    current base, both bisection halves, the knob zeroings, the seed
    pair) are evaluated in ONE multi-lane fleet dispatch
    (``_runtime_batch_eval``); the greedy control flow then consumes
    the batched verdicts exactly as it would sequential ones, so the
    accepted move sequence — and the final case — is identical to
    ``batch=False`` (pinned by tests/test_modelcheck.py).  The budget
    is spent per candidate either way; a batch may evaluate
    candidates the lazy path would have skipped, which only matters
    within one dispatch of exhaustion."""
    _, viol = run_case(case)
    if viol is None:
        raise ValueError("case does not fail; nothing to shrink")
    budget = _Budget(max_evals)
    # Candidate evaluation rides the shared runtime-knob executable
    # when the case can (one compile for the whole greedy descent —
    # and zero, when the sweep that found the case already compiled
    # this envelope); run_case stays the judge of record for the
    # initial failure above and the artifact pin (save_artifact).
    evaluator = _runtime_candidate_eval(case)
    batch_eval = _runtime_batch_eval(case) if batch else None

    def note(msg):
        if logger is not None:
            logger.info("shrink: %s", msg)

    def try_batch(cands):
        """Same-base candidates judged together: verdict-for-verdict
        equal to evaluating each alone (same executable, per-lane
        decision-log parity).  Candidates past the budget come back
        None (= not accepted), like the sequential path's refusal."""
        cands = list(cands)
        n = min(len(cands), max(budget.left, 0))
        take = cands[:n]
        for _ in take:
            budget.spend()
        if not take:
            return [None] * len(cands)
        if batch_eval is not None and len(take) > 1:
            vs = batch_eval(take)
        elif evaluator is not None:
            vs = [evaluator(c) for c in take]
        else:
            vs = [run_case(c)[1] for c in take]
        return vs + [None] * (len(cands) - n)

    changed = True
    while changed and budget.left > 0:
        changed = False
        # 1. drop episodes, greedily to a fixed point: all drops of
        #    the current base ride one dispatch; each acceptance
        #    changes the base, so the not-yet-visited SUFFIX re-
        #    batches (indices below i are never re-read — charging
        #    budget for them would make the batched pass O(E^2)
        #    evals where the lazy path is O(E))
        sched = case.cfg.faults.schedule

        def _drop_verdicts(s, start):
            if s is None or start >= len(s.episodes):
                return []
            return try_batch(
                [case.with_schedule(s.without(j))
                 for j in range(start, len(s.episodes))]
            )

        i = 0
        base = 0
        vs = _drop_verdicts(sched, 0)
        while sched is not None and i < len(sched.episodes):
            v = vs[i - base]
            if v is not None:
                ep = sched.episodes[i]
                note(f"dropped {ep.kind}[{ep.t0},{ep.t1})")
                case, viol = case.with_schedule(sched.without(i)), v
                sched = case.cfg.faults.schedule
                changed = True
                base = i
                vs = _drop_verdicts(sched, i)
            else:
                i += 1
        # 2. narrow surviving intervals by bisection (tail half
        #    preferred, as in the sequential order)
        sched = case.cfg.faults.schedule
        if sched is not None:
            for i in range(len(sched.episodes)):
                while budget.left > 0:
                    sched = case.cfg.faults.schedule
                    ep = sched.episodes[i]
                    w = ep.t1 - ep.t0
                    if w <= 1:
                        break
                    halves = (
                        (ep.t0, ep.t0 + w // 2),  # cut the tail half
                        (ep.t1 - w // 2, ep.t1),  # cut the head half
                    )
                    cands = [
                        case.with_schedule(
                            sched.replaced(i, ep.shifted(t0, t1))
                        )
                        for t0, t1 in halves
                    ]
                    vs = try_batch(cands)
                    narrowed = None
                    for (t0, t1), cand, v in zip(halves, cands, vs):
                        if v is not None:
                            narrowed, viol = cand, v
                            note(
                                f"narrowed {ep.kind} to [{t0},{t1})"
                            )
                            break
                    if narrowed is None:
                        break
                    case, changed = narrowed, True
        # 2b. halve surviving gray episodes' delay inflation toward 1
        #     (the gray twin of interval bisection: a minimal repro
        #     should carry the least slowness that still wedges)
        sched = case.cfg.faults.schedule
        if sched is not None:
            for i in range(len(sched.episodes)):
                while budget.left > 0:
                    sched = case.cfg.faults.schedule
                    ep = sched.episodes[i]
                    if ep.kind != "gray" or ep.delay <= 1:
                        break
                    cand = case.with_schedule(sched.replaced(
                        i, dataclasses.replace(ep, delay=ep.delay // 2)
                    ))
                    v = try_batch([cand])[0]
                    if v is None:
                        break
                    note(f"gray delay -> {ep.delay // 2}")
                    case, viol, changed = cand, v, True
        # 3. collapse the per-edge fault matrix: drop it entirely
        #    first (the reliable-network candidate), else flatten to
        #    the equivalent uniform SCALAR knobs (max rates over the
        #    matrix — keeps the fault pressure, kills the structure)
        if case.cfg.faults.edges is not None and budget.left > 0:
            fc = case.cfg.faults
            e = fc.edges
            flat = dataclasses.replace(
                fc, edges=None,
                drop_rate=max(max(r) for r in e.drop_rate),
                dup_rate=max(max(r) for r in e.dup_rate),
                min_delay=min(min(r) for r in e.min_delay),
            )
            cands = [
                case.with_faults(dataclasses.replace(fc, edges=None)),
                case.with_faults(flat),
            ]
            labels = ["edges dropped", "edges -> uniform scalars"]
            vs = try_batch(cands)
            for lbl, cand, v in zip(labels, cands, vs):
                if v is not None:
                    note(lbl)
                    case, viol, changed = cand, v, True
                    break
        # 4. zero the i.i.d. fault knobs one at a time (an acceptance
        #    changes the base; the remaining zeroings re-batch)
        repls = [
            {"drop_rate": 0},
            {"dup_rate": 0},
            {"min_delay": 0, "max_delay": 0},
            {"crash_rate": 0},
            {"delivery_cut": False},
        ]
        while repls and budget.left > 0:
            fc = case.cfg.faults
            live = [
                r for r in repls
                if not all(getattr(fc, k) == v for k, v in r.items())
                # a surviving edge matrix pins the ring bound: zeroing
                # max_delay under it would fail config validation (the
                # matrix collapse above is the move that removes it)
                and not ("max_delay" in r and fc.edges is not None)
            ]
            if not live:
                break
            vs = try_batch(
                [case.with_faults(dataclasses.replace(fc, **r))
                 for r in live]
            )
            for k, (r, v) in enumerate(zip(live, vs)):
                if v is not None:
                    note(f"zeroed {'/'.join(r)}")
                    case = case.with_faults(
                        dataclasses.replace(case.cfg.faults, **r)
                    )
                    viol, changed = v, True
                    repls = live[k + 1:]
                    break
            else:
                break
        # 4. seed minimization (bisect toward 0)
        while case.cfg.seed > 0 and budget.left > 0:
            cand_seeds = [
                s for s in (0, case.cfg.seed // 2) if s != case.cfg.seed
            ]
            cands = [
                dataclasses.replace(
                    case, cfg=dataclasses.replace(case.cfg, seed=s)
                )
                for s in cand_seeds
            ]
            vs = try_batch(cands)
            for s, cand, v in zip(cand_seeds, cands, vs):
                if v is not None:
                    note(f"seed -> {s}")
                    case, viol, changed = cand, v, True
                    break
            else:
                break
    if stats is not None:
        # Candidate-eval count for the caller's recall accounting
        # (evolve's lanes-to-shrunk-artifact); an out-param so the
        # (case, violation) return shape every caller unpacks stays
        # put.  left can undershoot 0 by at most the final batch.
        stats["evals"] = max_evals - max(budget.left, 0)
    return case, viol


# ---------------- artifact (de)serialization ----------------

def _cfg_to_dict(cfg: SimConfig) -> dict:
    fc = cfg.faults
    return {
        "n_nodes": cfg.n_nodes,
        "n_instances": cfg.n_instances,
        "proposers": list(cfg.proposers),
        "seed": cfg.seed,
        "max_rounds": cfg.max_rounds,
        "assign_window": cfg.assign_window,
        "protocol": dataclasses.asdict(cfg.protocol),
        "faults": {
            "drop_rate": fc.drop_rate,
            "dup_rate": fc.dup_rate,
            "min_delay": fc.min_delay,
            "max_delay": fc.max_delay,
            "crash_rate": fc.crash_rate,
            "schedule": (
                fc.schedule.to_dict() if fc.schedule is not None else None
            ),
            # WAN fields are written only when non-default, so classic
            # artifacts stay byte-identical to the pre-matrix format
            **({"edges": fc.edges.to_dict()} if fc.edges is not None
               else {}),
            **({"delivery_cut": True} if fc.delivery_cut else {}),
        },
    }


def _cfg_from_dict(d: dict) -> SimConfig:
    f = dict(d["faults"])
    sched = f.pop("schedule", None)
    edges = f.pop("edges", None)
    return SimConfig(
        n_nodes=d["n_nodes"],
        n_instances=d["n_instances"],
        proposers=tuple(d["proposers"]),
        seed=d["seed"],
        max_rounds=d["max_rounds"],
        assign_window=d["assign_window"],
        protocol=ProtocolConfig(**d["protocol"]),
        faults=FaultConfig(
            **f,
            schedule=(
                fltm.FaultSchedule.from_dict(sched) if sched else None
            ),
            edges=(
                EdgeFaultConfig.from_dict(edges) if edges else None
            ),
        ),
    )


def save_artifact(path: str, case: ReproCase, violation: str) -> dict:
    """Run the (already-shrunk) case once more to pin its decision-log
    hash, then write the self-contained artifact."""
    r, v = run_case(case)
    if v != violation:
        # the case must be deterministic — a drifting violation means
        # the artifact would not reproduce and must not be written
        raise RuntimeError(
            f"violation drifted between runs: {violation!r} -> {v!r}"
        )
    art = {
        "format": ARTIFACT_FORMAT,
        "engine": case.engine,
        "devices": case.devices,
        "cfg": _cfg_to_dict(case.cfg),
        "workload": [np.asarray(w).tolist() for w in case.workload],
        "gates": (
            None
            if case.gates is None
            else [np.asarray(g).tolist() for g in case.gates]
        ),
        "chains": [np.asarray(c).tolist() for c in case.chains],
        "extra_checks": case.extra_checks,
        "violation": violation,
        "decision_log_sha256": _sha256(decision_log_text(case, r)),
        "rounds": r.rounds,
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(art, f, indent=1)
    os.replace(tmp, path)
    return art


def load_artifact(path: str) -> tuple[ReproCase, dict]:
    # every rejection — unreadable file, truncated JSON, wrong format,
    # bad field — flows through ArtifactSchemaError so it carries a
    # field path (when one exists) and reaches the CLI's clean exit-2
    # surface instead of a raw traceback
    try:
        with open(path) as f:
            art = json.load(f)
    except OSError as e:
        raise ArtifactSchemaError("", f"unreadable artifact: {e}") from None
    except json.JSONDecodeError as e:
        raise ArtifactSchemaError(
            "", f"invalid JSON (truncated write?): {e}"
        ) from None
    try:
        validate_artifact(art)
    except ArtifactSchemaError as e:
        raise ArtifactSchemaError(
            e.field, f"{e.problem} (artifact {path!r})"
        ) from None
    try:
        case = ReproCase(
            cfg=_cfg_from_dict(art["cfg"]),
            workload=[np.asarray(w, np.int32) for w in art["workload"]],
            gates=(
                None
                if art["gates"] is None
                else [np.asarray(g, np.int32) for g in art["gates"]]
            ),
            chains=[np.asarray(c, np.int32) for c in art["chains"]],
            extra_checks=art.get("extra_checks") or {},
            engine=art.get("engine", "sim"),
            devices=art.get("devices", 1),
        )
    except (ValueError, TypeError) as e:
        # semantic constraints the config/episode constructors enforce
        # beyond the schema's type/range checks (empty intervals,
        # zero retry counts, ...) still get the clean exit-2 surface
        raise ArtifactSchemaError(
            "cfg", f"rejected by config validation: {e} (artifact {path!r})"
        ) from None
    return case, art


def reproduce(path: str) -> dict:
    """Re-execute an artifact; returns the comparison against its
    recorded outcome.  ``match`` is True iff the identical violation
    recurs AND the decision log byte-compares equal (via sha256)."""
    case, art = load_artifact(path)
    r, violation = run_case(case)
    log_text = decision_log_text(case, r)
    sha = _sha256(log_text)
    return {
        "artifact": path,
        "violation": violation,
        "recorded_violation": art["violation"],
        "decision_log_sha256": sha,
        "recorded_sha256": art["decision_log_sha256"],
        "rounds": r.rounds,
        "done": r.done,
        "decision_log": log_text,
        "match": (
            violation == art["violation"] and sha == art["decision_log_sha256"]
        ),
    }


def triage(
    case: ReproCase, out_path: str, max_evals: int = MAX_EVALS, logger=None
) -> dict:
    """The sweep's failure hook: shrink the failing case and write its
    repro artifact.  Returns the artifact dict plus a
    ``shrink_seconds`` wall-time key and a ``shrink_evals``
    candidate-eval count (reported in the sweep/search/evolve
    summaries; NOT written to the artifact file, whose schema is
    closed)."""
    import time

    t0 = time.perf_counter()  # paxlint: allow[DET001] triage wall-time metric, never serialized into the artifact
    stats: dict = {}
    small, viol = shrink_case(
        case, max_evals=max_evals, logger=logger, stats=stats
    )
    art = save_artifact(out_path, small, viol)
    seconds = time.perf_counter() - t0  # paxlint: allow[DET001] triage wall-time metric, never serialized into the artifact
    if logger is not None:
        logger.info("shrink: wall time %.2fs", seconds)
    return dict(
        art,
        shrink_seconds=round(seconds, 2),
        shrink_evals=int(stats.get("evals", 0)),
    )
