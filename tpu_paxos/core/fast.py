"""Fast path: fault-free batched multi-Paxos as fused array ops.

This is the bulk-synchronous reframing of the reference's *batched*
protocol flow for a single prepared proposer: one prepare covering
every instance (interval-set prepare, ref multi/paxos.cpp:809-828), one
batched accept (ref multi/paxos.cpp:1299-1326), one batched commit
(ref multi/paxos.cpp:1446-1479).  With a reliable network each phase is
one array op over the ``[nodes, instances]`` SoA state, so driving I
instances to chosen is three fused elementwise/reduction kernels — this
is the headline-benchmark path.

Layout: arrays are [A, I] — nodes MAJOR, instances MINOR — because the
TPU tiles the minor dimension across 128 vector lanes: an [I, A] layout
with A=5 pads every row to 128 lanes and wastes ~96% of VPU/HBM
throughput (measured: the [I, A] build ran at 34 GB/s logical, ~25x
under roofline; this layout removes the padding).  Host-side consumers
(the validators) take [I, A]; callers transpose once at the boundary.

Protocol semantics preserved exactly:
- promise iff ballot strictly greater than promised
  (ref multi/paxos.cpp:865), where ``promised`` is one scalar per
  acceptor covering all instances (ref multi/paxos.cpp: single
  ``promised_proposal_id_`` member);
- prepare replies return pre-accepted values, adopted by max accepted
  ballot (ref multi/paxos.cpp:1201-1223 ``UpdateByPreAcceptedValues``) —
  computed as two fused masked-max passes (ballot ties across acceptors
  carry the same value: one proposer per ballot, one value per
  instance), not argmax + gather, whose lowering is slow on TPU;
- accept iff ballot >= promised (ref multi/paxos.cpp:1366);
- quorum is n//2 + 1 (ref multi/paxos.cpp:1047);
- chosen values are broadcast to every node (commit,
  ref multi/paxos.cpp:1446-1479) and recorded in each node's learner
  state.

The fault-tolerant, multi-proposer, retrying engine lives in
``core/sim.py``; this module trades generality for peak throughput.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from tpu_paxos.core import ballot as bal
from tpu_paxos.core import values as val

_NEG = jnp.int32(jnp.iinfo(jnp.int32).min)


class FastState(NamedTuple):
    """SoA consensus state, shapes [A] / [A, I] (instances minor)."""

    promised: jax.Array  # [A] int32  — per-acceptor promised ballot
    max_seen: jax.Array  # [A] int32  — max ballot ever seen (for rejects)
    acc_ballot: jax.Array  # [A, I] int32 — accepted ballot (-1 none)
    acc_vid: jax.Array  # [A, I] int32 — accepted value id (-1 none)
    learned: jax.Array  # [A, I] int32 — chosen vid known to node a (-1)


def init_state(n_instances: int, n_nodes: int) -> FastState:
    i, a = n_instances, n_nodes
    return FastState(
        promised=jnp.zeros((a,), jnp.int32),
        max_seen=jnp.zeros((a,), jnp.int32),
        acc_ballot=jnp.full((a, i), bal.NONE, jnp.int32),
        acc_vid=jnp.full((a, i), val.NONE, jnp.int32),
        learned=jnp.full((a, i), val.NONE, jnp.int32),
    )


def learned_ia(state: FastState):
    """Host-boundary view in the validators' [I, A] convention."""
    import numpy as np

    return np.asarray(state.learned).T


def phase1_prepare(state: FastState, ballot: jax.Array, quorum: int):
    """Broadcast prepare; collect promises + pre-accepted values.

    Returns (state, prepared, adopted_ballot [I], adopted_vid [I]):
    ``prepared`` is the quorum bool; adopted_* is the max-ballot
    pre-accepted value per instance over promising acceptors (NONE
    where no acceptor reported one).
    """
    promise = ballot > state.promised  # strict >, ref multi/paxos.cpp:865
    promised = jnp.where(promise, ballot, state.promised)
    max_seen = jnp.maximum(state.max_seen, ballot)
    prepared = jnp.sum(promise.astype(jnp.int32)) >= quorum

    # Adoption: among promising acceptors, the value with the largest
    # accepted ballot (ref multi/paxos.cpp:1201-1223) — two masked-max
    # passes over the node axis; ties carry equal values.
    rep_ballot = jnp.where(promise[:, None], state.acc_ballot, bal.NONE)
    best = jnp.max(rep_ballot, axis=0)  # [I]
    has = best > 0
    adopted_vid_raw = jnp.max(
        jnp.where(rep_ballot == best[None, :], state.acc_vid, _NEG), axis=0
    )
    adopted_ballot = jnp.where(has, best, bal.NONE)
    adopted_vid = jnp.where(has, adopted_vid_raw, val.NONE)

    return (
        state._replace(promised=promised, max_seen=max_seen),
        prepared,
        adopted_ballot,
        adopted_vid,
    )


def phase2_accept(state: FastState, ballot: jax.Array, vids: jax.Array, quorum: int):
    """Broadcast one batched accept of ``vids`` [I]; count acks.

    Returns (state, chosen [bool scalar]): the whole batch is accepted
    or rejected per acceptor (the reference acceptor stores every value
    in the batch iff ballot >= promised, ref multi/paxos.cpp:1359-1397),
    so the quorum decision is per batch.
    """
    ok = ballot >= state.promised  # >=, ref multi/paxos.cpp:1366
    max_seen = jnp.maximum(state.max_seen, ballot)
    store = ok[:, None] & (vids != val.NONE)[None, :]
    acc_ballot = jnp.where(store, ballot, state.acc_ballot)
    acc_vid = jnp.where(store, vids[None, :], state.acc_vid)
    chosen = jnp.sum(ok.astype(jnp.int32)) >= quorum
    return state._replace(
        max_seen=max_seen, acc_ballot=acc_ballot, acc_vid=acc_vid
    ), chosen


def phase3_learn(state: FastState, vids: jax.Array, chosen) -> FastState:
    """Broadcast commit of chosen ``vids`` to every node's learner
    (ref multi/paxos.cpp:1446-1518: committed_values_ insert)."""
    mask = chosen & (vids != val.NONE)
    learn = mask if jnp.ndim(mask) else jnp.broadcast_to(mask, vids.shape)
    learned = jnp.where(learn[None, :], vids[None, :], state.learned)
    return state._replace(learned=learned)


def choose_all(
    state: FastState, vids: jax.Array, proposer: int, quorum: int
) -> tuple[FastState, jax.Array]:
    """Drive every instance with a value to chosen: the fused
    prepare → accept → commit pipeline of one prepared proposer.

    Returns (state, n_chosen).  Under jit this compiles to a handful of
    fused elementwise + reduce ops — the instances/sec headline number.
    """
    count, ballot = bal.bump_past(
        jnp.int32(0), jnp.int32(proposer), jnp.max(state.max_seen)
    )
    del count
    state, prepared, adopted_ballot, adopted_vid = phase1_prepare(
        state, ballot, quorum
    )
    # Pre-accepted values win over our own proposals for their
    # instances (ref multi/paxos.cpp:1078-1101).
    use_adopted = adopted_ballot != bal.NONE
    batch = jnp.where(use_adopted, adopted_vid, vids)
    batch = jnp.where(prepared, batch, val.NONE)
    state, chosen = phase2_accept(state, ballot, batch, quorum)
    state = phase3_learn(state, batch, chosen)
    n_chosen = jnp.sum((state.learned[0] != val.NONE).astype(jnp.int32))
    return state, n_chosen


choose_all_jit = jax.jit(choose_all, static_argnames=("proposer", "quorum"))


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """Canonical trace of the fast path (analysis/registry.py)."""
    from tpu_paxos.analysis.registry import AuditEntry

    def build():
        n, a = 16, 3
        state = init_state(n, a)
        vids = jnp.arange(n, dtype=jnp.int32)

        def fn(state, vids):
            return choose_all(state, vids, proposer=0, quorum=2)

        return fn, (state, vids)

    return [AuditEntry("fast.choose_all", build, covers=("choose_all_jit",))]
