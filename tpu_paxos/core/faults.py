"""Correlated fault schedules: deterministic, round-interval episodes.

The i.i.d. layer (``config.FaultConfig``: per-message drop/dup/delay,
per-node fail-stop crashes) reproduces the reference's ``THNetWork`` +
``RandomFailure`` model — but real consensus deployments die to
*correlated* faults the reference never injects: network partitions,
asymmetric (one-way) links, GC-style node pauses, and burst-loss
windows.  This module adds that layer as a ``FaultSchedule`` of
*episodes*, each active over a half-open round interval ``[t0, t1)``:

- ``partition(t0, t1, *groups)`` — symmetric partition: nodes in
  different groups cannot exchange messages in either direction
  (nodes listed in no group form one implicit extra group);
- ``one_way(t0, t1, src, dst)`` — asymmetric link cut: messages from
  ``src`` nodes to ``dst`` nodes are lost, the reverse direction
  stays up;
- ``pause(t0, t1, *nodes)`` — node pause (a long GC / VM migration):
  ALL of the node's I/O is suppressed while paused, but unlike a
  crash its state is preserved and it resumes at ``t1``;
- ``burst(t0, t1, drop_rate)`` — loss burst: ``drop_rate``/1e4 is
  ADDED to the i.i.d. drop rate inside the window (clamped to 1e4);
- ``crash(t0, *nodes)`` — deterministic fail-stop CRASH POINT: the
  nodes fail-stop at the end of round ``t0`` (the same
  takes-effect-next-round timing as the i.i.d. crash injection) and
  never return — unlike every other kind a crash does not heal, so
  its interval is the single round ``[t0, t0+1)`` and the liveness
  contract's crash excusals apply exactly as for sampled crashes.
  This is the model checker's deterministic crash axis
  (analysis/modelcheck.py): a (node, round) grid instead of a rate.
- ``gray(t0, t1, *nodes, delay=k)`` — GRAY FAILURE: the nodes are
  *slow*, not dead.  Every message a gray node sends or receives
  while the episode is active has ``k`` extra rounds added to its
  sampled delay (sums along an edge when both ends are gray, and
  across overlapping gray episodes), clamped at the engine's arrival
  ring bound (``cfg.faults.max_delay``) — gray NEVER drops a
  message, which is exactly what makes gray failures invisible to
  crash- and pause-shaped detectors.  Like a pause the node heals at
  ``t1`` with its state intact; unlike a pause it keeps acting every
  round, just at WAN-shaped latency.

Episodes compose: overlapping cuts AND their reachability, pauses OR,
burst rates add, crash sets union (and stay crashed forever), gray
inflations ADD per node.  ``compile_schedule`` lowers a schedule into
dense per-round tables — ``reach [H+1, N, N]``, ``paused [H+1, N]``,
``extra_drop [H+1]``, ``gray [H+1, N]`` with row ``H`` (the horizon =
last episode end) fully healed — which the engines index with
``min(t, H)``; one gather per round, fully jit/shard_map-compatible,
composing with the THNetWork-style sampling in ``core/net.py`` at
*send* time (a message sent while its edge is cut is lost at the
sender's NIC; copies already in flight still deliver by default — a
schedule the i.i.d. drop fault already contains — unless the config
arms ``delivery_cut``, which additionally drops in-flight copies AT
the partition edge on their arrival round).

Liveness contract (enforced by the engines): paused nodes are excused
only *while* paused, and quiescence is never declared before the last
heal — convergence is owed within ``max_rounds`` rounds past the
final episode end (``SimConfig.round_budget``).

Schedules are plain data (tuples of ints) so they serialize to JSON —
the unit of the stress harness's shrink-and-repro artifacts
(``harness/shrink.py``) — and hash/compare structurally, so they can
be baked statically into an engine closure.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import numpy as np

KINDS = ("partition", "one_way", "pause", "burst", "crash", "gray")


@dataclasses.dataclass(frozen=True)
class Episode:
    """One correlated-fault episode, active over rounds [t0, t1)."""

    kind: str
    t0: int
    t1: int
    groups: tuple[tuple[int, ...], ...] = ()  # partition
    src: tuple[int, ...] = ()  # one_way
    dst: tuple[int, ...] = ()  # one_way
    nodes: tuple[int, ...] = ()  # pause / crash / gray
    drop_rate: int = 0  # burst, per 1e4, added to FaultConfig.drop_rate
    delay: int = 0  # gray, extra delay rounds per affected message

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(f"unknown episode kind {self.kind!r}")
        if not 0 <= self.t0 < self.t1:
            raise ValueError(
                f"episode interval [{self.t0}, {self.t1}) must be "
                "non-empty and start at round >= 0"
            )
        # canonicalize container args so episodes hash/compare stably
        object.__setattr__(
            self, "groups", tuple(tuple(int(x) for x in g) for g in self.groups)
        )
        for f in ("src", "dst", "nodes"):
            object.__setattr__(
                self, f, tuple(sorted(int(x) for x in getattr(self, f)))
            )
        if self.kind == "partition":
            flat = [x for g in self.groups for x in g]
            if not self.groups or not all(self.groups):
                raise ValueError("partition needs non-empty groups")
            if len(flat) != len(set(flat)):
                raise ValueError("partition groups must be disjoint")
        if self.kind == "one_way" and (not self.src or not self.dst):
            raise ValueError("one_way needs non-empty src and dst")
        if self.kind == "pause" and not self.nodes:
            raise ValueError("pause needs at least one node")
        if self.kind == "burst" and not 0 < self.drop_rate <= 10_000:
            raise ValueError("burst drop_rate must be in (0, 10000]")
        if self.kind == "crash":
            if not self.nodes:
                raise ValueError("crash needs at least one node")
            if self.t1 != self.t0 + 1:
                # crashes are permanent — a wider interval would imply
                # a heal that never happens
                raise ValueError(
                    "crash episodes are instants: t1 must be t0 + 1"
                )
        if self.kind == "gray":
            if not self.nodes:
                raise ValueError("gray needs at least one node")
            if self.delay < 1:
                raise ValueError("gray delay must be >= 1 round")

    def shifted(self, t0: int, t1: int) -> "Episode":
        """Same episode over a different interval (the shrinker's
        interval-narrowing move)."""
        return dataclasses.replace(self, t0=t0, t1=t1)

    def _max_node(self) -> int:
        return max(
            [x for g in self.groups for x in g]
            + list(self.src) + list(self.dst) + list(self.nodes)
            + [0]
        )


def partition(t0: int, t1: int, *groups) -> Episode:
    """Symmetric partition: nodes in different groups are mutually
    unreachable during [t0, t1); unlisted nodes form one implicit
    extra group."""
    return Episode("partition", t0, t1, groups=tuple(tuple(g) for g in groups))


def one_way(t0: int, t1: int, src, dst) -> Episode:
    """One-way link cut: src -> dst messages are lost during [t0, t1)."""
    return Episode("one_way", t0, t1, src=tuple(src), dst=tuple(dst))


def pause(t0: int, t1: int, *nodes) -> Episode:
    """Pause nodes during [t0, t1): state preserved, all I/O suppressed."""
    return Episode("pause", t0, t1, nodes=tuple(nodes))


def burst(t0: int, t1: int, drop_rate: int) -> Episode:
    """Loss burst: add drop_rate/1e4 to the i.i.d. drop rate in [t0, t1)."""
    return Episode("burst", t0, t1, drop_rate=drop_rate)


def crash(t0: int, *nodes) -> Episode:
    """Deterministic crash point: ``nodes`` fail-stop at the end of
    round ``t0`` and never return (module doc)."""
    return Episode("crash", t0, t0 + 1, nodes=tuple(nodes))


def gray(t0: int, t1: int, *nodes, delay: int = 2) -> Episode:
    """Gray failure: ``nodes`` are slow during [t0, t1) — ``delay``
    extra rounds on every message they send or receive, clamped at
    the ring bound, never dropped (module doc)."""
    return Episode("gray", t0, t1, nodes=tuple(nodes), delay=delay)


@dataclasses.dataclass(frozen=True)
class FaultSchedule:
    """An immutable, hashable sequence of episodes (see module doc)."""

    episodes: tuple[Episode, ...] = ()

    def __post_init__(self) -> None:
        object.__setattr__(self, "episodes", tuple(self.episodes))
        for e in self.episodes:
            if not isinstance(e, Episode):
                raise TypeError(f"episodes must be Episode, got {type(e)}")

    @property
    def horizon(self) -> int:
        """First round at which every episode has ended (0 if empty)."""
        return max((e.t1 for e in self.episodes), default=0)

    def without(self, i: int) -> "FaultSchedule":
        """Schedule minus episode ``i`` (the shrinker's drop move)."""
        eps = self.episodes
        return FaultSchedule(eps[:i] + eps[i + 1:])

    def replaced(self, i: int, ep: Episode) -> "FaultSchedule":
        eps = list(self.episodes)
        eps[i] = ep
        return FaultSchedule(tuple(eps))

    # -- JSON plumbing for repro artifacts / injection logs --
    def to_dict(self) -> dict:
        return {
            "episodes": [
                {
                    k: (list(map(list, v)) if k == "groups" else
                        list(v) if isinstance(v, tuple) else v)
                    for k, v in dataclasses.asdict(e).items()
                }
                for e in self.episodes
            ]
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSchedule":
        eps = []
        for e in d.get("episodes", []):
            eps.append(
                Episode(
                    kind=e["kind"],
                    t0=e["t0"],
                    t1=e["t1"],
                    groups=tuple(tuple(g) for g in e.get("groups", ())),
                    src=tuple(e.get("src", ())),
                    dst=tuple(e.get("dst", ())),
                    nodes=tuple(e.get("nodes", ())),
                    drop_rate=e.get("drop_rate", 0),
                    delay=e.get("delay", 0),
                )
            )
        return cls(tuple(eps))


class CompiledSchedule(NamedTuple):
    """Dense per-round tables, horizon+1 rows; row ``horizon`` is the
    healed steady state (engines index with ``min(t, horizon)``) —
    except ``crashed``, which is CUMULATIVE: crash points never heal,
    so row ``horizon`` carries every crash and the min-index read
    stays correct forever.  The ``has_*`` flags are compile-time: an
    engine elides the table gather (and, for ``reach``, the per-edge
    send masking) entirely when a dimension is absent from the
    schedule."""

    reach: np.ndarray  # [H+1, N, N] bool, True = src row can reach dst col
    paused: np.ndarray  # [H+1, N] bool
    extra_drop: np.ndarray  # [H+1] int32, additional per-1e4 drop rate
    crashed: np.ndarray  # [H+1, N] bool, cumulative scheduled crashes
    gray: np.ndarray  # [H+1, N] int32, per-node extra delay rounds
    horizon: int
    has_reach: bool
    has_pause: bool
    has_burst: bool
    has_crash: bool
    has_gray: bool


def validate_episode(e: Episode, n_nodes: int) -> None:
    """Cluster-size checks shared by both schedule lowerings (the
    compile-time tables below and the fleet's runtime encoding,
    fleet/schedule_table.py)."""
    if e._max_node() >= n_nodes:
        raise ValueError(
            f"episode {e.kind}[{e.t0},{e.t1}) names node "
            f"{e._max_node()} but the cluster has {n_nodes} nodes"
        )
    if e.kind == "partition":
        # a single group needs unlisted nodes to form the implicit
        # complement, or the 'partition' cuts nothing
        listed = sum(len(g) for g in e.groups)
        if len(e.groups) < 2 and listed >= n_nodes:
            raise ValueError(
                f"partition[{e.t0},{e.t1}) lists every node in one "
                "group — nothing is cut; name >= 2 groups or leave "
                "nodes unlisted to form the implicit complement"
            )


def episode_tables(e: Episode, n_nodes: int):
    """Static per-episode masks — the single source of truth both
    lowerings share: ``(cut [N, N] bool, paused [N] bool, extra_drop
    int, crash [N] bool, gray [N] int32)`` where ``cut[s, d]`` means
    the s->d edge is severed while the episode is active, ``crash``
    names the nodes a crash point fail-stops (active from ``t0``
    FOREVER — crashes never heal), and ``gray`` is the per-node extra
    delay a gray episode inflicts while active.  The diagonal is
    never cut (a node always reaches itself).  Only the episode's own
    dimension is non-trivial; the others return zeros."""
    validate_episode(e, n_nodes)
    cut = np.zeros((n_nodes, n_nodes), bool)
    paused = np.zeros((n_nodes,), bool)
    crash_m = np.zeros((n_nodes,), bool)
    gray_v = np.zeros((n_nodes,), np.int32)
    extra = 0
    if e.kind == "partition":
        group_of = np.full((n_nodes,), len(e.groups), np.int32)
        for gi, g in enumerate(e.groups):
            group_of[list(g)] = gi
        cut = group_of[:, None] != group_of[None, :]
    elif e.kind == "one_way":
        cut[np.ix_(list(e.src), list(e.dst))] = True
        np.fill_diagonal(cut, False)
    elif e.kind == "pause":
        paused[list(e.nodes)] = True
    elif e.kind == "burst":
        extra = e.drop_rate
    elif e.kind == "crash":
        crash_m[list(e.nodes)] = True
    elif e.kind == "gray":
        gray_v[list(e.nodes)] = e.delay
    return cut, paused, extra, crash_m, gray_v


def compile_schedule(
    sched: FaultSchedule | None, n_nodes: int
) -> CompiledSchedule | None:
    """Lower a schedule to per-round tables for ``n_nodes`` nodes.
    Returns None for an absent/empty schedule (engines then compile
    with zero overhead)."""
    if sched is None or not sched.episodes:
        return None
    h = sched.horizon
    reach = np.ones((h + 1, n_nodes, n_nodes), bool)
    paused = np.zeros((h + 1, n_nodes), bool)
    extra = np.zeros((h + 1,), np.int64)
    crashed = np.zeros((h + 1, n_nodes), bool)
    gray_t = np.zeros((h + 1, n_nodes), np.int64)
    for e in sched.episodes:
        rows = slice(e.t0, e.t1)  # t1 <= h, so row h stays healed
        cut, pmask, xd, cmask, gv = episode_tables(e, n_nodes)
        reach[rows] &= ~cut[None]
        paused[rows] |= pmask[None]
        extra[rows] += xd
        gray_t[rows] += gv[None]
        # crash points are permanent: from t0 through row h inclusive,
        # so the engines' min(t, horizon) read never un-crashes a node
        crashed[e.t0:] |= cmask[None]
    np.einsum("tnn->tn", reach)[:] = True  # a node always reaches itself
    return CompiledSchedule(
        reach=reach,
        paused=paused,
        extra_drop=np.minimum(extra, 10_000).astype(np.int32),
        crashed=crashed,
        # uncapped sum here; the engines clamp the INFLATED delay at
        # the ring bound, which also bounds any overlapping-gray sum
        gray=np.minimum(gray_t, np.iinfo(np.int32).max).astype(np.int32),
        horizon=h,
        has_reach=any(e.kind in ("partition", "one_way") for e in sched.episodes),
        has_pause=any(e.kind == "pause" for e in sched.episodes),
        has_burst=any(e.kind == "burst" for e in sched.episodes),
        has_crash=any(e.kind == "crash" for e in sched.episodes),
        has_gray=any(e.kind == "gray" for e in sched.episodes),
    )
