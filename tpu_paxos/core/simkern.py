"""Pallas kernels for the general engine's two hottest event blocks.

The gated round (core/sim.py) pays most of its event-round time in two
[A, I]-wide blocks that XLA lowers to ~2.5x their bandwidth floor
(multiple fusions re-reading the same operands):

- the ACCEPT-STORE: per (a, i) pick the max-ballot eligible incoming
  accept across proposers and store it (ref multi/paxos.cpp:1359-1397
  OnAccept, with the safe-acceptor deviation documented in
  core/sim.py);
- the ECHO-ACK accumulation: per (p, a, i) certify an accept reply by
  store-or-match against the acceptor's current state and fold the
  per-instance ack counts (ref multi/paxos.cpp:1407-1444
  OnAcceptReply).

Each kernel runs ONE fused HBM pass per event round: every operand
read exactly once, outputs written exactly once (acceptor arrays and
the ack cube aliased in place), with the per-proposer loop unrolled in
VMEM.  Semantics are bit-identical to the jnp formulations in
core/sim.py (pinned by tests/test_simkern.py on the interpreter and,
opt-in, on the real chip) — the jnp path stays canonical and is what
every non-TPU backend runs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_paxos.core import ballot as bal
from tpu_paxos.core import values as val

_B_NONE = int(bal.NONE)
_V_NONE = int(val.NONE)

# [A, TILE] int32 tiles: the store kernel holds ~4 refs of A rows, the
# ack kernel adds the [P, A, TILE] cube; 32k instances per tile keeps
# both inside VMEM with double buffering at A, P <= 9.
TILE = 32768


def supported(n_instances: int, n_nodes: int = 5, n_proposers: int = 2) -> bool:
    """The kernels require whole tiles AND a geometry whose per-tile
    working set fits VMEM double-buffered (the ack kernel dominates:
    the [P, A, TILE] int8 cube in+out, three [A, TILE] int32 tiles,
    and the [P, TILE] batch + count rows); core/sim.py falls back to
    the jnp path otherwise (and on every non-TPU backend)."""
    a, p = n_nodes, n_proposers
    bytes_per_i = 2 * p * a + 3 * 4 * a + 3 * 4 * p  # ack-kernel refs
    vmem_budget = 12 << 20  # of ~16 MiB scoped VMEM
    return n_instances % TILE == 0 and 2 * TILE * bytes_per_i <= vmem_budget


def _check_aligned(i: int) -> None:
    # A truncated grid would silently skip the tail AND leave the
    # non-aliased n_ack output uninitialized — hard error, never
    # garbage.
    if i % TILE:
        raise ValueError(
            f"n_instances ({i}) is not a multiple of TILE ({TILE}); "
            "use the jnp path (simkern.supported() gates this)"
        )


def _store_kernel(scals_ref, bat_ref, ab_in, av_in, lr_ref, ab_out, av_out):
    """scals: [P] abal then [P*A] elig (int32 0/1), row-major."""
    a, _ = ab_in.shape
    p, _ = bat_ref.shape
    ab = ab_in[:, :]
    av = av_in[:, :]
    is_comm = lr_ref[:, :] != _V_NONE  # [A, T]
    best_b = jnp.full_like(ab, _B_NONE)
    best_v = jnp.full_like(av, _V_NONE)
    for pi in range(p):
        abal_p = scals_ref[pi]
        # per-acceptor eligibility column for this proposer: [A, 1]
        elig_p = jnp.stack(
            [scals_ref[p + pi * a + ai] for ai in range(a)]
        )[:, None] != 0
        batp = bat_ref[pi, :][None, :]  # [1, T]
        # boolean algebra instead of where-on-i1: mosaic rejects a
        # select with 1-bit operand values ("unsupported target
        # bitwidth for truncation")
        store_ok = (is_comm & (batp == lr_ref[:, :])) | (
            ~is_comm & (abal_p >= ab)
        )
        ackp = elig_p & (batp != _V_NONE) & store_ok
        candp = jnp.where(ackp & ~is_comm, abal_p, _B_NONE)
        take = candp > best_b
        best_b = jnp.where(take, candp, best_b)
        best_v = jnp.where(take, jnp.broadcast_to(batp, best_v.shape), best_v)
    do_store = best_b != _B_NONE
    ab_out[:, :] = jnp.where(do_store, best_b, ab)
    av_out[:, :] = jnp.where(do_store, best_v, av)


def store_accepts(acc_ballot, acc_vid, learned, abat, abal, elig,
                  interpret=False):
    """Pallas twin of core/sim.py's _store_accepts body — called from
    inside the (already-jitted) round, so no jit wrapper of its own;
    input_output_aliases carries the in-place contract.

    acc_ballot/acc_vid/learned [A, I], abat [P, I], abal [P] int32,
    elig [P, A] bool.  Returns (acc_ballot', acc_vid') aliased in
    place."""
    a, i = acc_ballot.shape
    p = abat.shape[0]
    _check_aligned(i)
    scals = jnp.concatenate(
        [abal.astype(jnp.int32), elig.astype(jnp.int32).reshape(-1)]
    )
    tile = pl.BlockSpec((a, TILE), lambda t, s: (0, t))
    ptile = pl.BlockSpec((p, TILE), lambda t, s: (0, t))
    ab, av = pl.pallas_call(
        _store_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(i // TILE,),
            in_specs=[ptile, tile, tile, tile],
            out_specs=[tile, tile],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((a, i), jnp.int32),
            jax.ShapeDtypeStruct((a, i), jnp.int32),
        ],
        input_output_aliases={2: 0, 3: 1},  # acc_ballot, acc_vid in place
        # (operand indices count the scalar-prefetch arg: scals=0,
        # abat=1, acc_ballot=2, acc_vid=3, learned=4)
        interpret=interpret,
    )(scals, abat, acc_ballot, acc_vid, learned)
    return ab, av


def _ack_kernel(
    scals_ref, acks_in, cb_ref, ab_ref, av_ref, lr_ref, acks_out, nack_ref
):
    """scals: [P] ballot then [P*A] amatch (int32 0/1, [P, A]
    row-major)."""
    p, a, _ = acks_in.shape
    abv = ab_ref[:, :]
    avv = av_ref[:, :]
    lrv = lr_ref[:, :]
    for pi in range(p):
        ballot_p = scals_ref[pi]
        am_p = jnp.stack(
            [scals_ref[p + pi * a + ai] for ai in range(a)]
        )[:, None] != 0  # [A, 1]
        cb = cb_ref[pi, :][None, :]  # [1, T]
        holdp = (avv == cb) & (abv == ballot_p)
        commp = (lrv == cb) & (lrv != _V_NONE)
        newa = acks_in[pi, :, :] | (
            am_p & (cb != _V_NONE) & (holdp | commp)
        ).astype(jnp.int8)
        acks_out[pi, :, :] = newa
        nack_ref[pi, :] = jnp.sum(newa.astype(jnp.int32), axis=0)


def accum_acks(acks, cur_batch, acc_ballot, acc_vid, learned, ballot,
               amatch_pa, interpret=False):
    """Pallas twin of the ack-accumulation head of core/sim.py's
    _accum_acks: returns (acks', n_ack), acks aliased in place.

    acks [P, A, I] int8 (0/1 — i1 refs are i32-backed in mosaic,
    which would 4x the cube traffic), cur_batch [P, I], acc_* /
    learned [A, I], ballot [P] int32, amatch_pa [P, A] bool."""
    p, a, i = acks.shape
    _check_aligned(i)
    scals = jnp.concatenate(
        [ballot.astype(jnp.int32), amatch_pa.astype(jnp.int32).reshape(-1)]
    )
    cube = pl.BlockSpec((p, a, TILE), lambda t, s: (0, 0, t))
    tile = pl.BlockSpec((a, TILE), lambda t, s: (0, t))
    ptile = pl.BlockSpec((p, TILE), lambda t, s: (0, t))
    acks2, n_ack = pl.pallas_call(
        _ack_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(i // TILE,),
            in_specs=[cube, ptile, tile, tile, tile],
            out_specs=[cube, ptile],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((p, a, i), jnp.int8),
            jax.ShapeDtypeStruct((p, i), jnp.int32),
        ],
        input_output_aliases={1: 0},  # acks in place
        interpret=interpret,
    )(scals, acks, cur_batch, acc_ballot, acc_vid, learned)
    return acks2, n_ack


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """Canonical one-tile traces of both kernels (interpret mode, so
    the trace works on every backend; the IR rules recurse into the
    pallas_call's inner jaxpr, which is where a kernel dtype leak
    would live).  cost=False: interpret-mode lowering's flop counts
    measure the interpreter, not the kernel."""
    from tpu_paxos.analysis.registry import AuditEntry

    a, p, i = 3, 2, TILE

    def _acceptor_arrays():
        acc_ballot = jnp.full((a, i), _B_NONE, jnp.int32)
        acc_vid = jnp.full((a, i), _V_NONE, jnp.int32)
        learned = jnp.full((a, i), _V_NONE, jnp.int32)
        return acc_ballot, acc_vid, learned

    def build_store():
        acc_ballot, acc_vid, learned = _acceptor_arrays()
        abat = jnp.zeros((p, i), jnp.int32)
        abal = jnp.zeros((p,), jnp.int32)
        elig = jnp.ones((p, a), jnp.bool_)

        def fn(acc_ballot, acc_vid, learned, abat, abal, elig):
            return store_accepts(
                acc_ballot, acc_vid, learned, abat, abal, elig,
                interpret=True,
            )

        return fn, (acc_ballot, acc_vid, learned, abat, abal, elig)

    def build_ack():
        acc_ballot, acc_vid, learned = _acceptor_arrays()
        acks = jnp.zeros((p, a, i), jnp.int8)
        cur_batch = jnp.zeros((p, i), jnp.int32)
        ballot = jnp.zeros((p,), jnp.int32)
        amatch = jnp.ones((p, a), jnp.bool_)

        def fn(acks, cur_batch, acc_ballot, acc_vid, learned, ballot,
               amatch):
            return accum_acks(
                acks, cur_batch, acc_ballot, acc_vid, learned, ballot,
                amatch, interpret=True,
            )

        return fn, (acks, cur_batch, acc_ballot, acc_vid, learned,
                    ballot, amatch)

    return [
        AuditEntry("simkern.store_accepts", build_store,
                   covers=("store_accepts",), cost=False,
                   hlo_golden=True),
        AuditEntry("simkern.accum_acks", build_ack,
                   covers=("accum_acks",), cost=False,
                   hlo_golden=True),
    ]
