"""Learner execution frontier — in-order apply via prefix scans.

The reference learner walks ``next_id_to_apply_`` forward while the
next instance is committed, executing non-no-op values in instance
order (ref multi/paxos.cpp:1584-1620; member/paxos.cpp:1029-1060).
On TPU the frontier is a prefix reduction: an instance is *applicable*
when every instance at or below it is learned, so the frontier is the
length of the leading all-learned prefix, computed with ``cumprod`` /
``cummin`` instead of a sequential walk.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from tpu_paxos.core import values as val


def frontier(learned_col) -> jnp.ndarray:
    """Index of the first unlearned instance for one node's learner
    state ``learned_col`` [I] (vid or NONE) — everything below it is
    applicable, matching the reference's next_id_to_apply_ walk."""
    known = (jnp.asarray(learned_col) != val.NONE).astype(jnp.int32)
    return jnp.sum(jnp.cumprod(known))


def frontiers(learned) -> jnp.ndarray:
    """Per-node frontiers for learned [I, A]."""
    known = (jnp.asarray(learned) != val.NONE).astype(jnp.int32)
    return jnp.sum(jnp.cumprod(known, axis=0), axis=0)


def executed_sequence(learned_col: np.ndarray) -> np.ndarray:
    """Host-side: the sequence of non-no-op vids a node's state machine
    executes, in instance order up to the frontier (the reference skips
    no-ops at ref multi/paxos.cpp:1598-1599)."""
    learned_col = np.asarray(learned_col)
    known = learned_col != int(val.NONE)
    f = int(np.cumprod(known.astype(np.int64)).sum())
    prefix = learned_col[:f]
    return prefix[prefix >= 0]  # drop no-ops (vid <= -2); NONE can't appear


def executed_sequences(learned: np.ndarray) -> list[np.ndarray]:
    return [executed_sequence(learned[:, a]) for a in range(learned.shape[1])]
