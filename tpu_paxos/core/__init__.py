"""Protocol core: ballots, values, acceptor/proposer/learner round functions."""

from tpu_paxos.core import ballot, values  # noqa: F401
