"""Protocol core: ballots, values, acceptor/proposer/learner round functions.

Submodules are lazily re-exported (PEP 562), mirroring the top-level
package: ``config.py`` imports ``core.faults`` (pure numpy) at package
import, and that must NOT drag in ``ballot``/``values`` — they build
jax device constants at import, which would initialize the backend
before the CLI can select ``--backend``/``--mesh`` provisioning (and
on a TPU-plugin container without ``JAX_PLATFORMS`` set, backend init
blocks for minutes on instance-metadata fetches).
"""

_SUBMODULES = (
    "apply", "ballot", "fast", "fastwin", "faults", "net", "sim",
    "simkern", "values", "wan",
)


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib

        return importlib.import_module(f"tpu_paxos.core.{name}")
    raise AttributeError(f"module 'tpu_paxos.core' has no attribute {name!r}")
