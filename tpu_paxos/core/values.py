"""Value model: interned int32 value ids (SoA — SURVEY.md component 14).

The reference's ``Value`` carries (proposer, value_id, noop flag,
payload-or-membership-change) and is compared field-wise
(ref multi/paxos.cpp:185-223).  Variable-length payloads do not belong
on a TPU, so the framework interns every distinct value to one int32
``vid``; protocol state and messages carry only vids, and equality is
integer equality.  Payload bytes (and membership-change descriptors)
live host-side in the workload's intern table.

vid space:
- ``vid == -1``       : NONE (no value)
- ``vid >= 0``        : real values, assigned by the workload; the
  canonical harness assignment is ``vid = proposer * stride + seq`` so
  (proposer, value_id) decode without a table.
- ``vid <= -2``       : no-op hole fillers, generated *on device* by
  the hole-filling pass, encoded ``-(2 + proposer * n_instances +
  instance)`` so each (proposer, instance) no-op is distinct — the
  reference gives each no-op a fresh (proposer, value_id) identity too
  (ref multi/paxos.cpp:1124 ``Value(index_, ++value_id_)``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

NONE = jnp.int32(-1)
NOOP_BASE = -2


def real_vid(proposer, seq, stride):
    """Canonical real-value id: globally unique, decodable without a table."""
    return jnp.asarray(proposer, jnp.int32) * jnp.int32(stride) + jnp.asarray(
        seq, jnp.int32
    )


def real_proposer_of(vid, stride):
    return jnp.asarray(vid, jnp.int32) // jnp.int32(stride)


def real_seq_of(vid, stride):
    return jnp.asarray(vid, jnp.int32) % jnp.int32(stride)


def noop_vid(instance, proposer, n_instances):
    """Device-side no-op id for hole filling; distinct per (proposer, instance)."""
    k = jnp.asarray(proposer, jnp.int32) * jnp.int32(n_instances) + jnp.asarray(
        instance, jnp.int32
    )
    return jnp.int32(NOOP_BASE) - k


def is_noop(vid):
    return jnp.asarray(vid, jnp.int32) <= jnp.int32(NOOP_BASE)


def is_none(vid):
    return jnp.asarray(vid, jnp.int32) == NONE


def noop_decode(vid, n_instances):
    """(proposer, instance) of a no-op vid — host or device."""
    k = jnp.int32(NOOP_BASE) - jnp.asarray(vid, jnp.int32)
    return k // jnp.int32(n_instances), k % jnp.int32(n_instances)


# ---------------------------------------------------------------- host side


class InternTable:
    """Host-side payload intern table: bytes/str <-> vid.

    The harness seam the reference exposes as ``StateMachine::Debug``
    (ref multi/paxos.h:214-222): a way to render a value.  Real
    payloads are interned on propose; no-ops never enter the table.
    """

    def __init__(self) -> None:
        self._by_payload: dict[bytes, int] = {}
        self._payloads: list[bytes] = []

    def intern(self, payload: bytes | str) -> int:
        if isinstance(payload, str):
            payload = payload.encode()
        vid = self._by_payload.get(payload)
        if vid is None:
            vid = len(self._payloads)
            self._by_payload[payload] = vid
            self._payloads.append(payload)
        return vid

    def payload(self, vid: int) -> bytes:
        if not 0 <= vid < len(self._payloads):
            raise KeyError(f"vid {vid} is not an interned real value")
        return self._payloads[vid]

    def __len__(self) -> int:
        return len(self._payloads)


def decode_host(vid: int, stride: int, n_instances: int):
    """Decode a vid to (proposer, value_id, noop) on host (numpy ints ok)."""
    vid = int(vid)
    if vid <= NOOP_BASE:
        k = NOOP_BASE - vid
        return k // n_instances, k % n_instances, True
    if vid < 0:
        raise ValueError("NONE has no decoding")
    return vid // stride, vid % stride, False


def decode_host_array(vids: np.ndarray, stride: int, n_instances: int):
    """Vectorized host decode: returns (proposer, value_id, noop) arrays."""
    vids = np.asarray(vids, np.int64)
    if (vids == int(NONE)).any():
        raise ValueError("NONE has no decoding")
    noop = vids <= NOOP_BASE
    k = NOOP_BASE - vids
    proposer = np.where(noop, k // n_instances, vids // stride)
    value_id = np.where(noop, k % n_instances, vids % stride)
    return proposer.astype(np.int64), value_id.astype(np.int64), noop
