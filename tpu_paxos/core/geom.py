"""Geometry-padded envelopes: ONE compiled executable for every
tenant geometry on the menu.

The reference serves any cluster geometry from one binary — its
protocol constants (``paxos::Config``, ref multi/paxos.h:251-274) and
its node count are plain runtime values.  Before this module our
envelope cache still keyed on ``(n_nodes, proposers, protocol)``, so
a service hosting 3-, 5-, and 7-node tenants compiled one executable
per geometry.  Here the node/proposer axes of ``SimState`` are PADDED
to an envelope bound and the true geometry arrives as runtime data:

- :class:`GeometryEnvelope` — the static compile-time fact: a MENU of
  ``(n_nodes, proposers)`` entries and the bound shapes they pad to.
  Part of the engine closure and the envelope cache key.
- :class:`Geometry` — the traced per-dispatch fact: which menu entry
  this run is, plus the masks/indices the round function needs
  (node_mask, proposer->node map, quorum, crash room).  Absent nodes
  are permanently masked: never sampled, never quorum-counted, never
  send or receive — the same exact-at-zero masked-form discipline as
  the runtime fault knobs (core/net.FaultKnobs).
- :class:`ProtocolKnobs` — the remaining compile-time protocol
  constants (retry patience, backoff spans, commit-ladder stall
  patience) promoted to traced int32 scalars threaded through
  ``round_fn``.  ``static_protocol`` returns the same field set as
  plain Python ints, so the degenerate (unpadded) engine traces the
  byte-identical pre-envelope program.

Why a MENU and not just a bound: jax's threefry bits are
shape-dependent — ``randint(key, (5,))`` is NOT a prefix of
``randint(key, (7,))`` — so an engine that sampled its fault coins at
the bound shape would fork every true geometry's coins and break
decision-log parity with the unpadded build.  Every PRNG draw whose
shape depends on the geometry is therefore dispatched through
``lax.switch`` over the menu (:func:`menu_randint`; the engine does
the same for its per-edge copy plans): branch ``m`` draws at entry
``m``'s TRUE static shape — bit-identical to the unpadded engine —
and pads the result to the bound with values that provably never
matter (a crash coin of 1e6 never crashes; a pad proposer's backoff
is never consulted).  Decision-log sha256 parity between the padded
and unpadded builds is pinned per (cfg, schedule, seed) by
tests/test_envelope_pad.py.

True nodes are always ids ``0..n-1`` (a menu entry's node set is a
prefix of the bound's), so fault schedules, churn tables, and knob
matrices encoded at the bound width carry the true geometry's values
in their leading block and zeros beyond it.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_paxos.config import PROTOCOL_SPANS, ProtocolConfig, SimConfig


@dataclasses.dataclass(frozen=True)
class GeometryEnvelope:
    """The static geometry menu one padded executable serves.

    ``menu`` is a tuple of ``(n_nodes, proposers)`` entries; the
    engine pads every [A]/[P]-shaped array to ``bound_nodes`` /
    ``bound_proposers`` (the menu maxima) and ``lax.switch``es its
    shape-dependent PRNG draws over the entries.  Hashable by
    construction: it is an envelope-cache key component."""

    menu: tuple

    def __post_init__(self) -> None:
        entries = []
        for entry in self.menu:
            n, props = entry
            n = int(n)
            props = tuple(sorted({int(x) for x in props})) or (0,)
            if n < 1:
                raise ValueError("menu entry needs n_nodes >= 1")
            for x in props:
                if not 0 <= x < n:
                    raise ValueError(
                        f"menu entry ({n}, {props}): proposer {x} out "
                        "of range"
                    )
            entries.append((n, props))
        if not entries:
            raise ValueError("a GeometryEnvelope needs at least one entry")
        if len(set(entries)) != len(entries):
            raise ValueError("menu entries must be distinct")
        object.__setattr__(self, "menu", tuple(entries))

    @property
    def bound_nodes(self) -> int:
        return max(n for n, _ in self.menu)

    @property
    def bound_proposers(self) -> int:
        return max(len(props) for _, props in self.menu)

    def bound_cfg(self, cfg: SimConfig) -> SimConfig:
        """``cfg`` re-shaped onto this envelope's bound: ``n_nodes``
        raised to the node bound and ``proposers`` widened to
        ``bound_proposers`` distinct slots (the slot->node map is
        runtime data — :class:`Geometry` — so which nodes the bound
        cfg names is immaterial; it only sizes the [P] axis)."""
        return dataclasses.replace(
            cfg,
            n_nodes=self.bound_nodes,
            proposers=tuple(range(self.bound_proposers)),
        )

    def index_of(self, n_nodes: int, proposers) -> int:
        """Menu index of a true geometry, with NAMED rejections: a
        geometry past the bound is rejected as such (the fleet-runner
        contract), anything else missing as not on the menu."""
        entry = (
            int(n_nodes),
            tuple(sorted({int(x) for x in proposers})) or (0,),
        )
        if entry in self.menu:
            return self.menu.index(entry)
        if entry[0] > self.bound_nodes or len(entry[1]) > self.bound_proposers:
            raise ValueError(
                f"geometry {entry} exceeds the envelope geometry bound "
                f"({self.bound_nodes} nodes, {self.bound_proposers} "
                "proposers)"
            )
        raise ValueError(
            f"geometry {entry} is not in the envelope menu {self.menu}"
        )

    def index_of_nodes(self, n_nodes: int) -> int:
        """Menu index by NODE COUNT alone — the membership engine's
        lookup (member/ has no static proposer axis; every node may
        propose through its view).  First menu entry with that node
        count wins; same named rejections as :meth:`index_of`."""
        n = int(n_nodes)
        for i, (n_m, _) in enumerate(self.menu):
            if n_m == n:
                return i
        if n > self.bound_nodes:
            raise ValueError(
                f"geometry ({n} nodes) exceeds the envelope geometry "
                f"bound ({self.bound_nodes} nodes)"
            )
        raise ValueError(
            f"geometry ({n} nodes) is not in the envelope menu "
            f"{self.menu}"
        )


class Geometry(NamedTuple):
    """The traced per-dispatch geometry of one padded run (broadcast
    across a fleet's lanes).  Built host-side by :func:`geometry_for`;
    every field is data, so changing tenant geometry costs a dispatch,
    not a compile."""

    geom_idx: jax.Array  # int32 menu index (the lax.switch selector)
    n_true: jax.Array  # int32 true node count
    quorum: jax.Array  # int32 n_true // 2 + 1
    max_crash: jax.Array  # int32 (n_true - 1) // 2 crash-injection room
    node_mask: jax.Array  # [A_bound] bool: ids < n_true
    pn: jax.Array  # [P_bound] int32 proposer slot -> node id (pad: 0)
    prop_mask: jax.Array  # [P_bound] bool: true proposer slots


class ProtocolKnobs(NamedTuple):
    """The protocol liveness constants as TRACED int32 scalars — the
    reference's ``paxos::Config`` values as runtime data, so a
    protocol-knob sweep shares one executable.  ``static_protocol``
    mirrors the field set with plain Python ints for the degenerate
    compile-time path."""

    prepare_delay_min: jax.Array
    prepare_delay_max: jax.Array
    prepare_retry_count: jax.Array
    prepare_retry_timeout: jax.Array
    accept_retry_count: jax.Array
    accept_retry_timeout: jax.Array
    commit_retry_timeout: jax.Array
    stall_patience: jax.Array


def geometry_for(
    env: GeometryEnvelope, n_nodes: int, proposers
) -> Geometry:
    """Host-side :class:`Geometry` for one true geometry of ``env``
    (named rejection via ``env.index_of`` when it is off the menu)."""
    idx = env.index_of(n_nodes, proposers)
    n, props = env.menu[idx]
    a, p = env.bound_nodes, env.bound_proposers
    pn = np.zeros((p,), np.int32)
    pn[: len(props)] = props
    return Geometry(
        geom_idx=np.int32(idx),
        n_true=np.int32(n),
        quorum=np.int32(n // 2 + 1),
        max_crash=np.int32((n - 1) // 2),
        node_mask=np.arange(a) < n,
        pn=pn,
        prop_mask=np.arange(p) < len(props),
    )


def protocol_knobs(
    pc: ProtocolConfig, stall_patience: int = 8
) -> ProtocolKnobs:
    """Host-side traced-knob encoding of a ProtocolConfig, span-checked
    against the DECLARED spans (config.PROTOCOL_SPANS): the compiled
    program is shared across knob mixes, so an out-of-span knob must
    be rejected by name, never silently clamped.  ``stall_patience``
    is the idle-liveness restart patience (sim.IDLE_RESTART_ROUNDS is
    the compile-time default)."""
    values = {
        "prepare_delay_min": pc.prepare_delay_min,
        "prepare_delay_max": pc.prepare_delay_max,
        "prepare_retry_count": pc.prepare_retry_count,
        "prepare_retry_timeout": pc.prepare_retry_timeout,
        "accept_retry_count": pc.accept_retry_count,
        "accept_retry_timeout": pc.accept_retry_timeout,
        "commit_retry_timeout": pc.commit_retry_timeout,
        "stall_patience": int(stall_patience),
    }
    for name, v in values.items():
        lo, hi = PROTOCOL_SPANS[name]
        if not lo <= int(v) <= hi:
            raise ValueError(
                f"protocol knob {name}={v} is outside its declared "
                f"span [{lo}, {hi}] (config.PROTOCOL_SPANS)"
            )
    return ProtocolKnobs(**{k: np.int32(v) for k, v in values.items()})


def static_protocol(
    pc: ProtocolConfig, stall_patience: int = 8
) -> ProtocolKnobs:
    """The same field set as plain Python ints — the compile-time
    constants of the degenerate (non-runtime-protocol) engine.  Using
    one accessor object for both paths keeps the round function free
    of per-site forks; closing over Python ints traces the
    byte-identical pre-envelope program."""
    return ProtocolKnobs(
        prepare_delay_min=pc.prepare_delay_min,
        prepare_delay_max=pc.prepare_delay_max,
        prepare_retry_count=pc.prepare_retry_count,
        prepare_retry_timeout=pc.prepare_retry_timeout,
        accept_retry_count=pc.accept_retry_count,
        accept_retry_timeout=pc.accept_retry_timeout,
        commit_retry_timeout=pc.commit_retry_timeout,
        stall_patience=int(stall_patience),
    )


def menu_lengths(env: GeometryEnvelope, axis: str) -> list[int]:
    """Per-menu-entry TRUE length along one padded axis."""
    if axis == "nodes":
        return [n for n, _ in env.menu]
    if axis == "proposers":
        return [len(props) for _, props in env.menu]
    raise ValueError(f"unknown padded axis {axis!r}")


def menu_randint(
    env: GeometryEnvelope,
    geom_idx: jax.Array,
    key: jax.Array,
    axis: str,
    lo,
    hi,
    pad_value: int,
):
    """Menu-switched 1-D ``randint``: branch ``m`` draws at entry
    ``m``'s TRUE static length along ``axis`` (threefry bits are
    shape-dependent — the bit-exactness anchor of the whole padding
    scheme) and pads to the bound with ``pad_value``.  ``lo``/``hi``
    may be traced scalars: with bound values equal to the static ones
    the draw is bit-identical (randint's bits depend only on
    key/shape/dtype)."""
    bound = env.bound_nodes if axis == "nodes" else env.bound_proposers

    def _branch(n_m: int):
        def _b(k):
            v = jax.random.randint(k, (n_m,), lo, hi, dtype=jnp.int32)
            return jnp.full((bound,), pad_value, jnp.int32).at[:n_m].set(v)

        return _b

    return jax.lax.switch(
        geom_idx,
        [_branch(n_m) for n_m in menu_lengths(env, axis)],
        key,
    )
