"""Bulk-synchronous network: edge-scalar ring calendars + fault masks.

This is the TPU-native reframing of the reference's ``NetWork`` SPI and
``THNetWork`` fault injector (ref multi/paxos.h:193-212,
multi/main.cpp:51-162).  Point-to-point async messages become entries
in fixed-size *arrival calendars*: for each message type there is a
ring buffer whose leading axis is "arrives in k rounds"; a message
sent at round ``t`` with sampled delay ``d`` is written at slot
``(t + 1 + d) % S`` and popped when the round counter reaches it.

Every calendar stores only a per-edge scalar (a ballot, or a presence
bit) — O(S * P * A) memory, independent of the instance count.  The
per-instance payloads the reference serializes into each message
(prepare-reply accepted-value snapshots, accept batches, commit
batches, per-instance acks) are *materialized at delivery time* from
the sender's state arrays instead of being buffered.  Each
materialized payload equals the payload of a message the sender could
legally have sent at the delivery round: sender state only grows
monotonically along the protocol's safe directions (promises and
``max_seen`` are monotone; accepted values are only replaced at >=
ballots; ``learned``/``commit_vid`` are write-once), so reading it at
delivery time is exactly equivalent to the reference scheduling the
sender's reply later and delivering it instantly — a schedule
``THNetWork``'s random delays already contain.  Payloads whose
validity condition no longer holds at delivery (an accept whose
proposer has since moved to a higher ballot) are treated as dropped,
which is likewise a schedule the reference's drop fault contains.

Fault semantics follow ``THNetWork::HijackSend``
(ref multi/main.cpp:116-132) exactly:
- the original copy is dropped with probability drop_rate/10000;
- duplicates are spawned recursively with probability dup_rate/10000,
  up to 3 extra copies, and duplicates are never dropped (the
  reference's drop check runs only for ``dup == 0``);
- every surviving copy independently samples a uniform integer delay
  in [min_delay, max_delay] rounds (the reference delays in ms via its
  Timer; one round here is one message exchange).

Coalescing model: at most one message per (edge, type) is delivered
per round; when two in-flight copies land on the same slot the
higher-ballot / newer one wins.  Every such coalescing artifact is
equivalent to a legal drop-and-delay schedule of the reference
network, because the per-edge scalar is a ballot (monotone — the
higher one governs at the receiver, ref multi/paxos.cpp:1366) or a
presence bit (idempotent).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_paxos.config import FaultConfig
from tpu_paxos.core import ballot as bal

MAX_COPIES = 4  # original + up to 3 recursive duplicates, ref multi/main.cpp:120


class FaultKnobs(NamedTuple):
    """The i.i.d. fault knobs as RUNTIME values: traced int32 scalars
    (or ``[lanes]`` vectors under the fleet vmap) instead of
    compile-time constants baked into the engine closure.

    This is what makes ONE compiled executable cover every stress
    mix: ``copy_plan`` with ``knobs=`` samples in always-on masked
    form — ``randint(.., 0, 10000) < rate`` is all-false at rate 0
    and a ``[0, 0]`` delay span samples 0 — so a zero knob produces
    bit-identical draws to the static path's elided branch (the PRNG
    keys are split per site, not consumed sequentially, and
    ``jax.random.randint``'s bits depend only on key/shape/dtype).
    Decision-log sha256 parity with the compile-time path is pinned
    per (cfg, schedule, seed) by tests/test_knobs.py.

    ``max_delay`` must stay <= the engine's ring envelope bound
    (``cfg.faults.max_delay`` of the engine the knobs are fed to —
    the arrival ring is statically sized to ``bound + 2`` slots);
    callers enforce this host-side (fleet/runner.py).  The ring size
    itself is decision-log-neutral: a message sent at ``t`` with
    delay ``d <= S - 2`` always pops at round ``t + 1 + d``.
    """

    drop_rate: jax.Array  # int32, per 1e4 (THNetWork semantics)
    dup_rate: jax.Array  # int32, per 1e4
    min_delay: jax.Array  # int32 rounds
    max_delay: jax.Array  # int32 rounds, <= the engine's envelope bound
    crash_rate: jax.Array  # int32, per 1e6 (member/ RandomFailure)


def knobs_from_faults(fc: FaultConfig) -> FaultKnobs:
    """Host-side encoding of a FaultConfig's i.i.d. knobs (the
    schedule is NOT part of the knobs — it rides the runtime
    ScheduleTable, fleet/schedule_table.py)."""
    return FaultKnobs(
        drop_rate=np.int32(fc.drop_rate),
        dup_rate=np.int32(fc.dup_rate),
        min_delay=np.int32(fc.min_delay),
        max_delay=np.int32(fc.max_delay),
        crash_rate=np.int32(fc.crash_rate),
    )


class NetBuffers(NamedTuple):
    """Arrival calendars, leading axis S = max_delay + 2 ring slots.

    P = number of proposers, A = number of nodes (acceptors/learners).
    ``NONE`` (-1) marks "no message".  All per-instance payloads are
    delivery-time materialized (see module docstring).
    """

    # PREPARE (ref MSG_PREPARE): proposer -> acceptor, ballot only (the
    # interval-set payload is implicit: all instances).
    prep_req: jax.Array  # [S, P, A] int32 ballot
    # PREPARE_REPLY (granted only, ref MSG_PREPARE_REPLY): acceptor ->
    # proposer, echo ballot; the accepted-state snapshot is read from
    # the acceptor's arrays at delivery.
    prep_echo: jax.Array  # [S, A, P] int32 ballot echo
    # REJECT (ref MSG_REJECT, shared by both phases): max ballot seen.
    rej: jax.Array  # [S, A, P] int32 max ballot (NONE = no reject)
    # ACCEPT (ref MSG_ACCEPT): per-edge ballot; the batch content is
    # the sending proposer's cur_batch at delivery, valid iff its
    # ballot still equals the edge ballot.
    acc_req: jax.Array  # [S, P, A] int32 ballot (NONE = no message)
    # ACCEPT_REPLY (ref MSG_ACCEPT_REPLY): echo; per-instance acks are
    # derived from the acceptor's accepted/learned state at delivery.
    acc_echo: jax.Array  # [S, A, P] int32 ballot echo
    # COMMIT (ref MSG_COMMIT): presence; content is the sender's
    # (write-once) commit_vid array at delivery.
    com_pres: jax.Array  # [S, P, A] bool edge presence
    # COMMIT_REPLY (ref MSG_COMMIT_REPLY): presence; per-instance acks
    # derive from learned-state match at delivery.
    com_rep: jax.Array  # [S, A, P] bool


def init_buffers(s: int, p: int, a: int) -> NetBuffers:
    none = lambda *shape: jnp.full(shape, bal.NONE, jnp.int32)  # noqa: E731
    false = lambda *shape: jnp.zeros(shape, jnp.bool_)  # noqa: E731
    return NetBuffers(
        prep_req=none(s, p, a),
        prep_echo=none(s, a, p),
        rej=none(s, a, p),
        acc_req=none(s, p, a),
        acc_echo=none(s, a, p),
        com_pres=false(s, p, a),
        com_rep=false(s, a, p),
    )


def clear_slot(buffers: NetBuffers, slot) -> NetBuffers:
    """Zero the just-popped arrival slot so the ring can be rewritten."""

    def _clr(buf):
        fill = jnp.zeros((), buf.dtype) if buf.dtype == jnp.bool_ else bal.NONE
        return buf.at[slot].set(fill)

    return jax.tree.map(_clr, buffers)


def copy_plan(
    key: jax.Array,
    edge_shape: tuple[int, ...],
    fc: FaultConfig,
    extra_drop=None,
    knobs: FaultKnobs | None = None,
):
    """Sample the THNetWork fault plan for one broadcast/send.

    Returns (alive [MAX_COPIES, *edge_shape] bool,
             delay [MAX_COPIES, *edge_shape] int32): which of the up to
    4 copies of each edge's message survive, and each copy's delay in
    rounds.  Copy 0 is the original (droppable); copies 1..3 exist via
    the recursive duplication chain and are never dropped
    (ref multi/main.cpp:116-123).

    ``extra_drop`` (traced int32 scalar, or None) is the fault
    schedule's burst-loss addition for this round (core/faults.py):
    it adds to ``fc.drop_rate``, clamped to 10_000.  Engines pass it
    only when the schedule contains burst episodes, so burst-free
    configs keep the static drop-sampling elision.

    With ``knobs`` set the rates/delays come from the traced
    :class:`FaultKnobs` instead of ``fc`` and every branch runs in
    its always-on masked form — exact when a knob is zero (see the
    FaultKnobs docstring for the parity argument), so one executable
    serves every knob mix.
    """
    k_drop, k_dup, k_delay = jax.random.split(key, 3)
    if knobs is not None:
        rate = jnp.asarray(knobs.drop_rate, jnp.int32)
        if extra_drop is not None:
            rate = jnp.minimum(rate + extra_drop, 10_000)
        drop = jax.random.randint(k_drop, edge_shape, 0, 10_000) < rate
        coins = (
            jax.random.randint(k_dup, (MAX_COPIES - 1, *edge_shape), 0, 10_000)
            < jnp.asarray(knobs.dup_rate, jnp.int32)
        )
        dup1 = coins[0]
        dup2 = dup1 & coins[1]
        dup3 = dup2 & coins[2]
        alive = jnp.concatenate(
            [(~drop)[None], jnp.stack([dup1, dup2, dup3])], axis=0
        )
        delay = jax.random.randint(
            k_delay,
            (MAX_COPIES, *edge_shape),
            jnp.asarray(knobs.min_delay, jnp.int32),
            jnp.asarray(knobs.max_delay, jnp.int32) + 1,
            dtype=jnp.int32,
        )
        return alive, delay
    if extra_drop is not None:
        rate = jnp.minimum(jnp.int32(fc.drop_rate) + extra_drop, 10_000)
        drop = jax.random.randint(k_drop, edge_shape, 0, 10_000) < rate
    elif fc.drop_rate:
        drop = jax.random.randint(k_drop, edge_shape, 0, 10_000) < fc.drop_rate
    else:
        drop = jnp.zeros(edge_shape, jnp.bool_)
    if fc.dup_rate:
        coins = (
            jax.random.randint(k_dup, (MAX_COPIES - 1, *edge_shape), 0, 10_000)
            < fc.dup_rate
        )
        # Recursive chain: copy k+1 exists iff copy k spawned it.
        dup1 = coins[0]
        dup2 = dup1 & coins[1]
        dup3 = dup2 & coins[2]
        dups = jnp.stack([dup1, dup2, dup3])
    else:
        dups = jnp.zeros((MAX_COPIES - 1, *edge_shape), jnp.bool_)
    alive = jnp.concatenate([(~drop)[None], dups], axis=0)
    if fc.max_delay:
        delay = jax.random.randint(
            k_delay,
            (MAX_COPIES, *edge_shape),
            fc.min_delay,
            fc.max_delay + 1,
            dtype=jnp.int32,
        )
    else:
        delay = jnp.zeros((MAX_COPIES, *edge_shape), jnp.int32)
    return alive, delay


def _slot_onehot(t, s: int, alive, delay):
    """[MAX_COPIES, *edge] arrival slots -> [S, *edge] bool write mask."""
    slots = (t + 1 + delay) % s  # arrival round's ring slot
    oh = jnp.arange(s).reshape((s,) + (1,) * slots[0].ndim)
    # any copy of the edge's message lands on slot s'
    return jnp.any((slots[None] == oh[:, None]) & alive[None], axis=1)


def write_ballot(buf, t, alive, delay, value, send_mask):
    """Coalesce-max write of a ballot-valued message into its calendar.

    ``value``/``send_mask`` are per-edge; NONE means no send.
    """
    s = buf.shape[0]
    mask = _slot_onehot(t, s, alive, delay) & send_mask[None]
    return jnp.maximum(buf, jnp.where(mask, value[None], bal.NONE))


def write_flag(buf, t, alive, delay, send_mask):
    """Coalesce-or write of a presence-bit message into its calendar."""
    s = buf.shape[0]
    return buf | (_slot_onehot(t, s, alive, delay) & send_mask[None])
