"""Bulk-synchronous network: edge-scalar ring calendars + fault masks.

This is the TPU-native reframing of the reference's ``NetWork`` SPI and
``THNetWork`` fault injector (ref multi/paxos.h:193-212,
multi/main.cpp:51-162).  Point-to-point async messages become entries
in fixed-size *arrival calendars*: for each message type there is a
ring buffer whose leading axis is "arrives in k rounds"; a message
sent at round ``t`` with sampled delay ``d`` is written at slot
``(t + 1 + d) % S`` and popped when the round counter reaches it.

Every calendar stores only a per-edge scalar (a ballot, or a presence
bit) — O(S * P * A) memory, independent of the instance count.  The
per-instance payloads the reference serializes into each message
(prepare-reply accepted-value snapshots, accept batches, commit
batches, per-instance acks) are *materialized at delivery time* from
the sender's state arrays instead of being buffered.  Each
materialized payload equals the payload of a message the sender could
legally have sent at the delivery round: sender state only grows
monotonically along the protocol's safe directions (promises and
``max_seen`` are monotone; accepted values are only replaced at >=
ballots; ``learned``/``commit_vid`` are write-once), so reading it at
delivery time is exactly equivalent to the reference scheduling the
sender's reply later and delivering it instantly — a schedule
``THNetWork``'s random delays already contain.  Payloads whose
validity condition no longer holds at delivery (an accept whose
proposer has since moved to a higher ballot) are treated as dropped,
which is likewise a schedule the reference's drop fault contains.

Fault semantics follow ``THNetWork::HijackSend``
(ref multi/main.cpp:116-132) exactly:
- the original copy is dropped with probability drop_rate/10000;
- duplicates are spawned recursively with probability dup_rate/10000,
  up to 3 extra copies, and duplicates are never dropped (the
  reference's drop check runs only for ``dup == 0``);
- every surviving copy independently samples a uniform integer delay
  in [min_delay, max_delay] rounds (the reference delays in ms via its
  Timer; one round here is one message exchange).

Coalescing model: at most one message per (edge, type) is delivered
per round; when two in-flight copies land on the same slot the
higher-ballot / newer one wins.  Every such coalescing artifact is
equivalent to a legal drop-and-delay schedule of the reference
network, because the per-edge scalar is a ballot (monotone — the
higher one governs at the receiver, ref multi/paxos.cpp:1366) or a
presence bit (idempotent).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_paxos.config import FaultConfig
from tpu_paxos.core import ballot as bal

MAX_COPIES = 4  # original + up to 3 recursive duplicates, ref multi/main.cpp:120


class FaultKnobs(NamedTuple):
    """The i.i.d. fault knobs as RUNTIME values: traced int32 scalars
    (or ``[lanes]`` vectors under the fleet vmap) instead of
    compile-time constants baked into the engine closure.

    The first four fields may also be per-edge ``[A, A]`` int32
    MATRICES (``[lanes, A, A]`` under the fleet vmap) — the WAN
    generalization: entry ``[s, d]`` governs node ``s`` -> node ``d``
    messages.  ``copy_plan`` samples the same PRNG bits either way
    (bits depend only on key/shape/dtype) and applies the rates/spans
    elementwise, so a UNIFORM matrix draws bit-identically to the
    scalar knob — the parity contract that makes every scalar config
    the degenerate case of the matrix model (tests/test_geo.py pins
    the decision-log sha256).  Matrix knobs must be pre-sliced to the
    edge shape before they reach ``copy_plan`` (``edge_knobs``).
    ``crash_rate`` stays a scalar: crashes are per-node, not
    per-edge.

    This is what makes ONE compiled executable cover every stress
    mix: ``copy_plan`` with ``knobs=`` samples in always-on masked
    form — ``randint(.., 0, 10000) < rate`` is all-false at rate 0
    and a ``[0, 0]`` delay span samples 0 — so a zero knob produces
    bit-identical draws to the static path's elided branch (the PRNG
    keys are split per site, not consumed sequentially, and
    ``jax.random.randint``'s bits depend only on key/shape/dtype).
    Decision-log sha256 parity with the compile-time path is pinned
    per (cfg, schedule, seed) by tests/test_knobs.py.

    ``max_delay`` must stay <= the engine's ring envelope bound
    (``cfg.faults.max_delay`` of the engine the knobs are fed to —
    the arrival ring is statically sized to ``bound + 2`` slots);
    callers enforce this host-side (fleet/runner.py).  The ring size
    itself is decision-log-neutral: a message sent at ``t`` with
    delay ``d <= S - 2`` always pops at round ``t + 1 + d``.
    """

    drop_rate: jax.Array  # int32, per 1e4 (THNetWork semantics)
    dup_rate: jax.Array  # int32, per 1e4
    min_delay: jax.Array  # int32 rounds
    max_delay: jax.Array  # int32 rounds, <= the engine's envelope bound
    crash_rate: jax.Array  # int32, per 1e6 (member/ RandomFailure)
    delay_bound: jax.Array  # int32 scalar: the CONFIG's declared
    #     max_delay (the lane's own ring headroom) — the gray-failure
    #     inflation clamp.  A runtime knob, NOT the engine's static
    #     ring size: the fleet envelope's ring may be wider than the
    #     lane's declared bound, and clamping at the engine bound
    #     would make gray delays depend on which executable ran the
    #     lane — a decision-visible fork between a fleet lane and its
    #     lane_cfg() single-run replay (caught by review; pinned by
    #     tests/test_geo.py's min_delay-bearing gray parity cell).


def knobs_from_faults(fc: FaultConfig) -> FaultKnobs:
    """Host-side encoding of a FaultConfig's i.i.d. knobs (the
    schedule is NOT part of the knobs — it rides the runtime
    ScheduleTable, fleet/schedule_table.py).  An ``edges``-bearing
    config encodes to matrix-form knobs (``matrix_knobs``)."""
    if fc.edges is not None:
        return matrix_knobs(fc)
    return FaultKnobs(
        drop_rate=np.int32(fc.drop_rate),
        dup_rate=np.int32(fc.dup_rate),
        min_delay=np.int32(fc.min_delay),
        max_delay=np.int32(fc.max_delay),
        crash_rate=np.int32(fc.crash_rate),
        delay_bound=np.int32(fc.max_delay),
    )


def matrix_knobs(fc: FaultConfig, n_nodes: int | None = None) -> FaultKnobs:
    """Matrix-form host knobs for ``fc``: its ``edges`` tables when
    present, else the scalar knobs broadcast to a UNIFORM ``[A, A]``
    matrix (bit-identical to the scalar path — the FaultKnobs parity
    contract).  ``n_nodes`` is required for the uniform broadcast of
    an edge-free config."""
    e = fc.edges
    if e is not None:
        return FaultKnobs(
            drop_rate=np.asarray(e.drop_rate, np.int32),
            dup_rate=np.asarray(e.dup_rate, np.int32),
            min_delay=np.asarray(e.min_delay, np.int32),
            max_delay=np.asarray(e.max_delay, np.int32),
            crash_rate=np.int32(fc.crash_rate),
            delay_bound=np.int32(fc.max_delay),
        )
    if n_nodes is None:
        raise ValueError("matrix_knobs needs n_nodes for an edge-free config")
    full = lambda v: np.full((n_nodes, n_nodes), v, np.int32)  # noqa: E731
    return FaultKnobs(
        drop_rate=full(fc.drop_rate),
        dup_rate=full(fc.dup_rate),
        min_delay=full(fc.min_delay),
        max_delay=full(fc.max_delay),
        crash_rate=np.int32(fc.crash_rate),
        delay_bound=np.int32(fc.max_delay),
    )


def pad_matrix_knobs(knobs: FaultKnobs, bound: int) -> FaultKnobs:
    """Pad matrix-form knob fields from a true ``[n, n]`` geometry to
    the envelope's ``[bound, bound]`` with zeros: a geometry-padded
    engine menu-slices the TRUE leading block back out per edge shape
    (``edge_knobs`` inside each ``lax.switch`` branch), so the pad
    region is never consulted — true nodes are always ids ``0..n-1``.
    Scalar fields pass through untouched (a uniform scalar knob is
    slice-invariant already)."""
    def pad(x):
        x = np.asarray(x)
        if x.ndim < 2:
            return x
        n = x.shape[-1]
        if n > bound:
            raise ValueError(
                f"knob matrix is [{n}, {n}]; the envelope geometry "
                f"bound is {bound} nodes"
            )
        out = np.zeros(x.shape[:-2] + (bound, bound), np.int32)
        out[..., :n, :n] = x
        return out

    return FaultKnobs(
        drop_rate=pad(knobs.drop_rate),
        dup_rate=pad(knobs.dup_rate),
        min_delay=pad(knobs.min_delay),
        max_delay=pad(knobs.max_delay),
        crash_rate=knobs.crash_rate,
        delay_bound=knobs.delay_bound,
    )


def edge_knobs(knobs: FaultKnobs, rows, cols) -> FaultKnobs:
    """Slice matrix-form knob fields to one edge shape: ``rows`` are
    the source node ids of the edge-shape's leading axis, ``cols``
    the destination ids of its trailing axis (e.g. proposer->node
    sends slice ``[pn, :]``; node->proposer replies ``[:, pn]``).
    Scalar fields pass through untouched, so the helper is a no-op
    view for scalar knobs and mixing forms per field is legal."""
    import jax.numpy as jnp

    def sl(x):
        x = jnp.asarray(x)
        return x if x.ndim < 2 else x[rows][:, cols]

    return FaultKnobs(
        drop_rate=sl(knobs.drop_rate),
        dup_rate=sl(knobs.dup_rate),
        min_delay=sl(knobs.min_delay),
        max_delay=sl(knobs.max_delay),
        crash_rate=knobs.crash_rate,
        delay_bound=knobs.delay_bound,
    )


class NetBuffers(NamedTuple):
    """Arrival calendars, leading axis S = max_delay + 2 ring slots.

    P = number of proposers, A = number of nodes (acceptors/learners).
    ``NONE`` (-1) marks "no message".  All per-instance payloads are
    delivery-time materialized (see module docstring).
    """

    # PREPARE (ref MSG_PREPARE): proposer -> acceptor, ballot only (the
    # interval-set payload is implicit: all instances).
    prep_req: jax.Array  # [S, P, A] int32 ballot
    # PREPARE_REPLY (granted only, ref MSG_PREPARE_REPLY): acceptor ->
    # proposer, echo ballot; the accepted-state snapshot is read from
    # the acceptor's arrays at delivery.
    prep_echo: jax.Array  # [S, A, P] int32 ballot echo
    # REJECT (ref MSG_REJECT, shared by both phases): max ballot seen.
    rej: jax.Array  # [S, A, P] int32 max ballot (NONE = no reject)
    # ACCEPT (ref MSG_ACCEPT): per-edge ballot; the batch content is
    # the sending proposer's cur_batch at delivery, valid iff its
    # ballot still equals the edge ballot.
    acc_req: jax.Array  # [S, P, A] int32 ballot (NONE = no message)
    # ACCEPT_REPLY (ref MSG_ACCEPT_REPLY): echo; per-instance acks are
    # derived from the acceptor's accepted/learned state at delivery.
    acc_echo: jax.Array  # [S, A, P] int32 ballot echo
    # COMMIT (ref MSG_COMMIT): presence; content is the sender's
    # (write-once) commit_vid array at delivery.
    com_pres: jax.Array  # [S, P, A] bool edge presence
    # COMMIT_REPLY (ref MSG_COMMIT_REPLY): presence; per-instance acks
    # derive from learned-state match at delivery.
    com_rep: jax.Array  # [S, A, P] bool


def init_buffers(s: int, p: int, a: int) -> NetBuffers:
    none = lambda *shape: jnp.full(shape, bal.NONE, jnp.int32)  # noqa: E731
    false = lambda *shape: jnp.zeros(shape, jnp.bool_)  # noqa: E731
    return NetBuffers(
        prep_req=none(s, p, a),
        prep_echo=none(s, a, p),
        rej=none(s, a, p),
        acc_req=none(s, p, a),
        acc_echo=none(s, a, p),
        com_pres=false(s, p, a),
        com_rep=false(s, a, p),
    )


def clear_slot(buffers: NetBuffers, slot) -> NetBuffers:
    """Zero the just-popped arrival slot so the ring can be rewritten."""

    def _clr(buf):
        fill = jnp.zeros((), buf.dtype) if buf.dtype == jnp.bool_ else bal.NONE
        return buf.at[slot].set(fill)

    return jax.tree.map(_clr, buffers)


def copy_plan(
    key: jax.Array,
    edge_shape: tuple[int, ...],
    fc: FaultConfig,
    extra_drop=None,
    knobs: FaultKnobs | None = None,
    gray=None,
    delay_bound: int | None = None,
):
    """Sample the THNetWork fault plan for one broadcast/send.

    Returns (alive [MAX_COPIES, *edge_shape] bool,
             delay [MAX_COPIES, *edge_shape] int32): which of the up to
    4 copies of each edge's message survive, and each copy's delay in
    rounds.  Copy 0 is the original (droppable); copies 1..3 exist via
    the recursive duplication chain and are never dropped
    (ref multi/main.cpp:116-123).

    ``extra_drop`` (traced int32 scalar, or None) is the fault
    schedule's burst-loss addition for this round (core/faults.py):
    it adds to ``fc.drop_rate``, clamped to 10_000.  Engines pass it
    only when the schedule contains burst episodes, so burst-free
    configs keep the static drop-sampling elision.

    With ``knobs`` set the rates/delays come from the traced
    :class:`FaultKnobs` instead of ``fc`` and every branch runs in
    its always-on masked form — exact when a knob is zero (see the
    FaultKnobs docstring for the parity argument), so one executable
    serves every knob mix.  Knob fields pre-sliced to ``edge_shape``
    (``edge_knobs``) give per-EDGE rates/spans: the drawn bits are
    identical, the compares/arithmetic elementwise, so a uniform
    matrix is bit-identical to the scalar knob.

    ``gray`` (``[*edge_shape]`` int32, or None) is the fault
    schedule's gray-failure inflation for this round: extra delay
    rounds ADDED to every surviving copy's sampled delay, clamped at
    the CONFIG's declared delay bound — ``knobs.delay_bound`` (a
    traced per-lane scalar) on the knobs path, the static
    ``delay_bound`` (= ``fc.max_delay``) otherwise.  The clamp must
    NOT be the engine's ring size: a fleet envelope's ring is wider
    than a lane's declared bound, and clamping there would fork the
    lane from its single-run replay.  Gray never drops — the clamp
    is the contract (tests/test_geo.py): an all-zero gray round is
    exact (``min(d + 0, bound) == d`` for every in-bound sample).
    """
    k_drop, k_dup, k_delay = jax.random.split(key, 3)

    def _gray(delay):
        if gray is None:
            return delay
        if knobs is not None:
            bound = jnp.asarray(knobs.delay_bound, jnp.int32)
        else:
            bound = jnp.int32(int(delay_bound))
        return jnp.minimum(delay + gray[None], bound)
    if knobs is not None:
        rate = jnp.asarray(knobs.drop_rate, jnp.int32)
        if extra_drop is not None:
            rate = jnp.minimum(rate + extra_drop, 10_000)
        drop = jax.random.randint(k_drop, edge_shape, 0, 10_000) < rate
        coins = (
            jax.random.randint(k_dup, (MAX_COPIES - 1, *edge_shape), 0, 10_000)
            < jnp.asarray(knobs.dup_rate, jnp.int32)
        )
        dup1 = coins[0]
        dup2 = dup1 & coins[1]
        dup3 = dup2 & coins[2]
        alive = jnp.concatenate(
            [(~drop)[None], jnp.stack([dup1, dup2, dup3])], axis=0
        )
        delay = jax.random.randint(
            k_delay,
            (MAX_COPIES, *edge_shape),
            jnp.asarray(knobs.min_delay, jnp.int32),
            jnp.asarray(knobs.max_delay, jnp.int32) + 1,
            dtype=jnp.int32,
        )
        return alive, _gray(delay)
    if fc.edges is not None:
        # trace-time guard: an edges-bearing config must arrive via
        # the masked knobs path (matrix_knobs) — the scalar branches
        # below would silently sample its zeroed scalar knobs
        raise ValueError(
            "copy_plan with per-edge tables needs knobs= "
            "(net.matrix_knobs); the static scalar path would drop "
            "the matrix"
        )
    if extra_drop is not None:
        rate = jnp.minimum(jnp.int32(fc.drop_rate) + extra_drop, 10_000)
        drop = jax.random.randint(k_drop, edge_shape, 0, 10_000) < rate
    elif fc.drop_rate:
        drop = jax.random.randint(k_drop, edge_shape, 0, 10_000) < fc.drop_rate
    else:
        drop = jnp.zeros(edge_shape, jnp.bool_)
    if fc.dup_rate:
        coins = (
            jax.random.randint(k_dup, (MAX_COPIES - 1, *edge_shape), 0, 10_000)
            < fc.dup_rate
        )
        # Recursive chain: copy k+1 exists iff copy k spawned it.
        dup1 = coins[0]
        dup2 = dup1 & coins[1]
        dup3 = dup2 & coins[2]
        dups = jnp.stack([dup1, dup2, dup3])
    else:
        dups = jnp.zeros((MAX_COPIES - 1, *edge_shape), jnp.bool_)
    alive = jnp.concatenate([(~drop)[None], dups], axis=0)
    if fc.max_delay and fc.edges is None:
        delay = jax.random.randint(
            k_delay,
            (MAX_COPIES, *edge_shape),
            fc.min_delay,
            fc.max_delay + 1,
            dtype=jnp.int32,
        )
    else:
        # edges-bearing configs never reach this branch (the engine
        # routes them through the masked knobs path with the matrix
        # baked in as a constant); a delay-free config samples 0
        delay = jnp.zeros((MAX_COPIES, *edge_shape), jnp.int32)
    return alive, _gray(delay)


def delivery_mask(ar: NetBuffers, reach_pa, reach_ap) -> NetBuffers:
    """Delivery-time partition cut: void the popped arrival slot's
    entries on edges severed at the ARRIVAL round (``reach_pa`` is
    the [P, A] proposer->node reachability, ``reach_ap`` its [A, P]
    node->proposer transpose view).  Same-side arrivals pass through
    untouched, and an all-true reach round is the identity — the
    exactness anchor for cut-free schedules.  Armed by
    ``FaultConfig.delivery_cut`` (a compile-time engine flag); the
    default send-time-only semantics leave in-flight copies alone."""
    return NetBuffers(
        prep_req=jnp.where(reach_pa, ar.prep_req, bal.NONE),
        prep_echo=jnp.where(reach_ap, ar.prep_echo, bal.NONE),
        rej=jnp.where(reach_ap, ar.rej, bal.NONE),
        acc_req=jnp.where(reach_pa, ar.acc_req, bal.NONE),
        acc_echo=jnp.where(reach_ap, ar.acc_echo, bal.NONE),
        com_pres=ar.com_pres & reach_pa,
        com_rep=ar.com_rep & reach_ap,
    )


def _slot_onehot(t, s: int, alive, delay):
    """[MAX_COPIES, *edge] arrival slots -> [S, *edge] bool write mask."""
    slots = (t + 1 + delay) % s  # arrival round's ring slot
    oh = jnp.arange(s).reshape((s,) + (1,) * slots[0].ndim)
    # any copy of the edge's message lands on slot s'
    return jnp.any((slots[None] == oh[:, None]) & alive[None], axis=1)


def write_ballot(buf, t, alive, delay, value, send_mask):
    """Coalesce-max write of a ballot-valued message into its calendar.

    ``value``/``send_mask`` are per-edge; NONE means no send.
    """
    s = buf.shape[0]
    mask = _slot_onehot(t, s, alive, delay) & send_mask[None]
    return jnp.maximum(buf, jnp.where(mask, value[None], bal.NONE))


def write_flag(buf, t, alive, delay, send_mask):
    """Coalesce-or write of a presence-bit message into its calendar."""
    s = buf.shape[0]
    return buf | (_slot_onehot(t, s, alive, delay) & send_mask[None])
