"""Pallas-fused steady-state accept/learn window for the fast path.

The headline bench drives windows of I fresh instances through one
prepared proposer's batched accept + commit (``bench._steady_state_windows``,
mirroring the reference's long-running proposer: one prepare, then
batched accepts forever, ref multi/paxos.cpp:1256-1326, commit
1446-1479).  Under XLA that loop lowers to ~5 separate HBM passes per
window (recycle-fill of each state array, the accept stores, the learn
store, the vid materialization) — measured ~30 ms per 128M-instance
window on a v5e chip, ~3.5x the single-pass roofline.

This module fuses one FULL window into a single pallas pass: for each
[A, TILE] tile it computes the fresh-window vids, the per-acceptor
store mask, and writes ``acc_ballot``/``acc_vid``/``learned`` exactly
once, accumulating the per-window chosen count in SMEM.  The ``reps``
window loop is the outer grid dimension, so one kernel launch runs the
whole steady-state scan with zero intermediate materialization.

Semantics are bit-identical to the XLA scan path (asserted by
``tests/test_fastwin.py`` on the CPU interpreter): per window k
  vid[i]            = prepared ? vids0[i] + k*span : NONE
  store[a, i]       = ok[a] & (vid[i] != NONE)      (ok = ballot >= promised,
                                                     ref multi/paxos.cpp:1366)
  acc_ballot[a, i]  = store ? ballot : NONE          (recycle-fill + accept)
  acc_vid[a, i]     = store ? vid[i] : NONE
  learned[a, i]     = chosen & vid!=NONE ? vid : NONE  (commit broadcast)
  count            += sum(learned[0] != NONE)
where ``prepared``/``chosen`` are the phase-1/phase-2 quorum bools —
scalars, computed outside the kernel (they are [A]-reductions).

Only the single-device TPU path uses this kernel; the sharded and CPU
paths keep the XLA scan (`bench._steady_state_windows`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_paxos.core import ballot as bal
from tpu_paxos.core import fast
from tpu_paxos.core import values as val

_B_NONE = int(bal.NONE)  # -1
_V_NONE = int(val.NONE)  # -1

# Instances per tile: (5, 65536) int32 = 1.25 MB per ref, 3.75 MB over
# the three outputs (+0.25 MB vids in), ~8 MB double-buffered — inside
# the ~16 MB VMEM budget at A=5; revisit before raising TILE or A.
TILE = 65536


def _window_body(scals_ref, ok_ref, v, k, t, ab_ref, av_ref, lr_ref, cnt_ref):
    """Shared per-tile body: store mask, the three state writes, and
    the per-window count — ``v`` is this tile's [1, T] vid vector."""
    ballot = scals_ref[0]
    chosen = scals_ref[3] != 0
    has = v != _V_NONE  # [1, T]

    ok = ok_ref[:, :] != 0  # [A, 1] per-acceptor accept mask (VMEM)
    store = ok & has  # [A, T]
    ab_ref[:, :] = jnp.where(store, ballot, _B_NONE)
    av_ref[:, :] = jnp.where(store, v, _V_NONE)

    learn = chosen & has  # [1, T] commit broadcast mask
    lr_ref[:, :] = jnp.broadcast_to(
        jnp.where(learn, v, _V_NONE), lr_ref.shape
    )

    @pl.when(t == 0)
    def _init():
        cnt_ref[k, 0] = 0

    # Per-window chosen count, taken from node 0's learner row as in
    # the scan path (rows are identical under the broadcast commit).
    # One int32 slot per window — a single running total would wrap at
    # 2^31 instances (reps x I overflows int32 from reps=16 at I=2^27);
    # callers sum the per-window counts in host integers.
    cnt_ref[k, 0] += jnp.sum(learn.astype(jnp.int32))


def _window_kernel(
    scals_ref, ok_ref, vids_ref, ab_in, av_in, lr_in, ab_ref, av_ref, lr_ref, cnt_ref
):
    # ab_in/av_in/lr_in are the previous window's buffers, aliased to
    # the outputs so the 8 GiB state is recycled in place; the kernel
    # never reads them (every cell is overwritten).
    del ab_in, av_in, lr_in
    k = pl.program_id(0)  # window (rep) index
    t = pl.program_id(1)  # instance tile index
    span = scals_ref[1]
    prepared = scals_ref[2] != 0

    # Fresh-window vids for this tile: [1, T].
    v = vids_ref[:, :] + k * span
    v = jnp.where(prepared, v, _V_NONE)
    _window_body(scals_ref, ok_ref, v, k, t, ab_ref, av_ref, lr_ref, cnt_ref)


def _window_kernel_iota(
    scals_ref, ok_ref, ab_in, av_in, lr_in, ab_ref, av_ref, lr_ref, cnt_ref
):
    # Sequential-vid variant: vid = global instance index + k*span,
    # synthesized in VMEM — the [I] vid stream never touches HBM (the
    # bench workload is sequential client ids, as in the reference
    # harness's id counters).
    del ab_in, av_in, lr_in
    k = pl.program_id(0)
    t = pl.program_id(1)
    span = scals_ref[1]
    prepared = scals_ref[2] != 0

    v = (
        t * TILE
        + jax.lax.broadcasted_iota(jnp.int32, (1, TILE), 1)
        + k * span
    )
    v = jnp.where(prepared, v, _V_NONE)
    _window_body(scals_ref, ok_ref, v, k, t, ab_ref, av_ref, lr_ref, cnt_ref)


@functools.partial(
    jax.jit,
    static_argnames=("reps", "quorum", "span", "interpret", "iota_vids"),
    donate_argnums=(0,),
)
def steady_state_windows_fused(
    state: fast.FastState,
    vids0: jax.Array | None,
    reps: int,
    quorum: int,
    span: int | None = None,
    interpret: bool = False,
    iota_vids: bool = False,
):
    """Pallas twin of ``bench._steady_state_windows`` running all
    ``reps`` windows in one launch (single HBM pass per array per
    window).  Returns ``(state, per_window_counts [reps])`` — counts
    stay per-window so host summation can exceed int32.

    ``iota_vids=True`` asserts the workload is sequential ids
    (vids0 == arange(I), the reference harness's id counters) and
    synthesizes them in VMEM — the [I] vid stream never touches HBM;
    ``vids0`` may then be None."""
    a, i = state.acc_ballot.shape
    if i % TILE:
        raise ValueError(f"n_instances ({i}) must be a multiple of {TILE}")
    if iota_vids and vids0 is not None:
        raise ValueError(
            "iota_vids=True synthesizes arange vids; passing vids0 too is "
            "almost certainly a mistake (it would be silently ignored)"
        )
    if not iota_vids and vids0 is None:
        raise ValueError("vids0 is required unless iota_vids=True")
    # Window k proposes vids0 + k*span: the top of the int32 vid space
    # is the hard capacity bound — one id per instance ever chosen
    # (vid 2^31 would wrap to the NONE sentinel).
    if reps * (span or i) > 1 << 31:
        raise ValueError(
            f"reps * span = {reps * (span or i)} exceeds the int32 vid space"
        )

    # Phase 1 once — identical to the scan path.
    _, ballot = bal.bump_past(
        jnp.int32(0), jnp.int32(0), jnp.max(state.max_seen)
    )
    state, prepared, _, _ = fast.phase1_prepare(state, ballot, quorum)

    # The scalar protocol decisions for every window (the state they
    # depend on does not change while only accepts flow; phase 1 has
    # already folded this ballot into max_seen).
    ok = ballot >= state.promised  # [A], ref multi/paxos.cpp:1366
    chosen = jnp.sum(ok.astype(jnp.int32)) >= quorum

    scals = jnp.stack(
        [
            ballot,
            jnp.int32(span or i),
            prepared.astype(jnp.int32),
            chosen.astype(jnp.int32),
        ]
    )
    ok_col = ok.astype(jnp.int32)[:, None]  # [A, 1]

    grid = (reps, i // TILE)
    out_shape = [
        jax.ShapeDtypeStruct((a, i), jnp.int32),  # acc_ballot
        jax.ShapeDtypeStruct((a, i), jnp.int32),  # acc_vid
        jax.ShapeDtypeStruct((a, i), jnp.int32),  # learned
        jax.ShapeDtypeStruct((reps, 1), jnp.int32),  # per-window counts
    ]
    tile_spec = pl.BlockSpec((a, TILE), lambda k, t, s: (0, t))
    out_specs = [
        tile_spec,
        tile_spec,
        tile_spec,
        pl.BlockSpec(
            (reps, 1), lambda k, t, s: (0, 0), memory_space=pltpu.SMEM
        ),
    ]
    ok_spec = pl.BlockSpec((a, 1), lambda k, t, s: (0, 0))
    alias_specs = [pl.BlockSpec(memory_space=pl.ANY)] * 3
    aliased = (state.acc_ballot, state.acc_vid, state.learned)
    if iota_vids:
        kernel = _window_kernel_iota
        vid_specs, vid_args, n_lead = [], (), 2
    else:
        kernel = _window_kernel
        vid_specs = [pl.BlockSpec((1, TILE), lambda k, t, s: (0, t))]
        vid_args, n_lead = (vids0[None, :],), 3
    ab, av, lr, cnt = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[ok_spec, *vid_specs, *alias_specs],
            out_specs=out_specs,
        ),
        out_shape=out_shape,
        input_output_aliases={n_lead + j: j for j in range(3)},
        interpret=interpret,
    )(scals, ok_col, *vid_args, *aliased)

    state = state._replace(acc_ballot=ab, acc_vid=av, learned=lr)
    return state, cnt[:, 0]


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """Canonical two-window trace of the fused steady-state kernel
    (interpret mode, so it traces and compiles on every backend; the
    IR rules walk the pallas_call's inner jaxpr).  cost=False like the
    simkern entries: interpret-mode flop counts measure the
    interpreter, not the kernel.

    The HLO tier lowers through the jitted surface ITSELF
    (``hlo_build``) — ``donate_argnums=(0,)`` recycles the whole
    FastState in place, and the donation checker verifies the
    compiled artifact still carries the input/output aliasing for
    every state leaf.  A wrapper re-jit here would silently re-add
    whatever the product jit dropped, which is exactly the regression
    the checker exists to catch."""
    from tpu_paxos.analysis.registry import AuditEntry

    reps, quorum = 2, 2

    def build():
        state = fast.init_state(TILE, 3)

        def fn(state):
            return steady_state_windows_fused(
                state, None, reps=reps, quorum=quorum,
                interpret=True, iota_vids=True,
            )

        return fn, (state,)

    def hlo_build():
        state = fast.init_state(TILE, 3)
        return steady_state_windows_fused, (state, None), dict(
            reps=reps, quorum=quorum, interpret=True, iota_vids=True,
        )

    return [AuditEntry(
        "fastwin.steady_windows", build,
        covers=("steady_state_windows_fused",),
        cost=False,
        donate_argnums=(0,),
        hlo_build=hlo_build,
        hlo_golden=True,
    )]
