"""WAN topology presets: geo-replicated cluster shapes as per-edge
fault matrices plus a node->region assignment.

A geo-replicated Paxos cluster does not fail like a rack: its latency
is an asymmetric ``[A, A]`` matrix set by the speed of light between
regions, its loss concentrates on the long-haul links, and its worst
outages are *gray* — a slow region, not a dead one.  This module
ships that shape as data the whole triage stack already understands:
each preset lowers to a :class:`~tpu_paxos.config.EdgeFaultConfig`
(per-edge drop/delay tables, ``config.py``) plus an ``[A]`` region
map (the flight recorder's per-region-pair counters and the serve
harness's per-region SLOs key off it), with every delay bounded by
the fleet envelope's ring bound
(``fleet/envelope.MAX_DELAY_BOUND``) — so every preset of a geometry
rides ONE compiled executable (BENCH_geo.json pins zero warm compiles
across presets).

Delay units are protocol rounds.  The RTT ratios are the classic
WAN shape (intra-region ~0, cross-continent 2-4x a regional hop),
not a claim about any particular provider; what matters for the
protocol is the RATIO structure — quorums form at the speed of the
median region pair, and the far region rides the retry ladder.

Nodes are assigned to regions round-robin (``node_regions``), so a
5-node cluster on the 3-region preset lands 2/2/1 — the standard
multi-region quorum layout.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpu_paxos.config import EdgeFaultConfig, FaultConfig

#: Every preset's delay entries stay <= this bound so presets share
#: the fleet envelope's default ring (fleet/envelope.MAX_DELAY_BOUND).
PRESET_DELAY_BOUND = 8


@dataclasses.dataclass(frozen=True)
class WanPreset:
    """One WAN topology: region names, a symmetric one-way base
    latency matrix (rounds, per region pair), per-pair delay jitter,
    and per-pair loss (per 1e4, applied to cross-region edges)."""

    name: str
    regions: tuple[str, ...]
    latency: tuple  # [R][R] base one-way delay in rounds
    jitter: int = 1  # extra max-delay rounds on every edge
    loss: tuple | None = None  # [R][R] drop per 1e4 (None = lossless)

    def __post_init__(self) -> None:
        r = len(self.regions)
        lat = tuple(tuple(int(x) for x in row) for row in self.latency)
        if len(lat) != r or any(len(row) != r for row in lat):
            raise ValueError(f"latency must be {r}x{r}")
        object.__setattr__(self, "latency", lat)
        if self.loss is not None:
            ls = tuple(tuple(int(x) for x in row) for row in self.loss)
            if len(ls) != r or any(len(row) != r for row in ls):
                raise ValueError(f"loss must be {r}x{r}")
            object.__setattr__(self, "loss", ls)
        hi = max(max(row) for row in lat) + self.jitter
        if hi > PRESET_DELAY_BOUND:
            raise ValueError(
                f"preset {self.name!r} peaks at delay {hi} > the "
                f"envelope ring bound {PRESET_DELAY_BOUND}"
            )

    @property
    def n_regions(self) -> int:
        return len(self.regions)


#: 3-region preset (us / eu / ap): one regional hop is ~1 round, the
#: transatlantic link 2, transpacific 3-4 — the realistic RTT ratio
#: triangle.  Modest loss on the long links, asymmetric (the return
#: path is slightly worse — real WANs are).
WAN3 = WanPreset(
    name="wan-3region",
    regions=("us", "eu", "ap"),
    latency=(
        (0, 2, 3),
        (2, 0, 4),
        (3, 4, 0),
    ),
    jitter=1,
    loss=(
        (0, 50, 80),
        (60, 0, 100),
        (90, 120, 0),
    ),
)

#: 5-region preset (us-east / us-west / eu / ap / sa): finer ratio
#: ladder — coast-to-coast 1, transatlantic 2, transpacific 3-4,
#: south-america tail 3-4 with the worst loss.
WAN5 = WanPreset(
    name="wan-5region",
    regions=("use", "usw", "eu", "ap", "sa"),
    latency=(
        (0, 1, 2, 4, 3),
        (1, 0, 3, 3, 4),
        (2, 3, 0, 4, 4),
        (4, 3, 4, 0, 5),
        (3, 4, 4, 5, 0),
    ),
    jitter=1,
    loss=(
        (0, 20, 60, 100, 120),
        (20, 0, 80, 80, 140),
        (60, 80, 0, 100, 150),
        (100, 80, 100, 0, 180),
        (120, 140, 150, 180, 0),
    ),
)

PRESETS = {p.name: p for p in (WAN3, WAN5)}


def node_regions(preset: WanPreset, n_nodes: int) -> np.ndarray:
    """Round-robin node->region assignment: ``[A]`` int32 region
    indices (the recorder's runtime region map; also the serve
    harness's per-region SLO key)."""
    return (np.arange(n_nodes, dtype=np.int32) % preset.n_regions)


def edge_faults(preset: WanPreset, n_nodes: int) -> EdgeFaultConfig:
    """Lower a preset to the per-edge ``[A, A]`` tables for an
    ``n_nodes`` cluster: each edge inherits its region pair's base
    latency as ``min_delay``, plus ``jitter`` as the span, and the
    pair's loss rate (intra-region edges stay fast and lossless)."""
    rmap = node_regions(preset, n_nodes)
    lat = np.asarray(preset.latency, np.int32)[rmap[:, None], rmap[None, :]]
    if preset.loss is not None:
        drop = np.asarray(preset.loss, np.int32)[rmap[:, None], rmap[None, :]]
    else:
        drop = np.zeros((n_nodes, n_nodes), np.int32)
    np.fill_diagonal(drop, 0)
    # EdgeFaultConfig canonicalizes any iterable-of-iterables (incl.
    # numpy rows) to int tuples in __post_init__
    return EdgeFaultConfig(
        drop_rate=drop,
        dup_rate=np.zeros_like(drop),
        min_delay=lat,
        max_delay=lat + preset.jitter,
    )


def wan_fault_config(
    preset: WanPreset,
    n_nodes: int,
    *,
    delay_bound: int = PRESET_DELAY_BOUND,
    crash_rate: int = 0,
    schedule=None,
    delivery_cut: bool = False,
) -> FaultConfig:
    """A ready-to-run :class:`FaultConfig` for one preset: the edge
    tables plus the envelope ring bound as the scalar ``max_delay``
    (so every preset of a geometry lands on one fleet envelope
    key)."""
    edges = edge_faults(preset, n_nodes)
    if edges.delay_bound > delay_bound:
        raise ValueError(
            f"preset {preset.name!r} needs ring bound "
            f"{edges.delay_bound} > requested {delay_bound}"
        )
    return FaultConfig(
        max_delay=delay_bound,
        crash_rate=crash_rate,
        schedule=schedule,
        edges=edges,
        delivery_cut=delivery_cut,
    )
