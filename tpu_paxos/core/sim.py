"""The general multi-round engine: fault-tolerant multi-Paxos as a
bulk-synchronous round loop (``lax.while_loop`` over pure array ops).

This is the TPU-native equivalent of the reference's event-driven
protocol core (ref multi/paxos.cpp:320-521 ``PaxosImpl`` state,
:1643-1706 ``Loop``): every node's proposer/acceptor/learner state
lives in SoA arrays, one loop iteration is one network round, and all
asynchrony — retries, randomized backoff, drops, duplicates, delays —
is expressed as per-round masks, counters, and arrival-calendar
buffers (core/net.py).

Protocol semantics (each with its reference anchor):
- promise iff ballot strictly > promised; equal ballots get silence,
  lower get REJECT with the max ballot seen
  (ref multi/paxos.cpp:858-899 OnPrepare);
- prepare replies snapshot the acceptor's accepted AND committed
  values (committed reported at a +inf-like ballot so adoption always
  prefers them — ref FilterAcceptedValues includes committed_values_,
  multi/paxos.cpp:913-922);
- adoption merges pre-accepted values by max ballot as replies arrive
  (ref multi/paxos.cpp:1201-1223 UpdateByPreAcceptedValues);
- accept iff ballot >= promised (ref multi/paxos.cpp:1366), with one
  deliberate deviation: an acceptor only overwrites its accepted
  value when the new ballot is >= the currently *accepted* ballot,
  and only acks the instances it actually stored.  The reference
  overwrites with any ballot >= promised (multi/paxos.cpp:1385) and
  acks the whole batch, which under reordered delivery can report a
  stale lower-ballot value to a later prepare and lose a chosen
  value; keeping the highest-ballot accepted value is the standard
  safe acceptor rule (Lamport's Voting.tla) and is a superset of the
  behaviours the reference exhibits in its own test configs;
- per-acceptor promised is a single scalar covering all instances
  (ref: one ``promised_proposal_id_`` member) — this is what makes
  hole-filling and the in-order-client property work;
- retry ladder: prepare resent (count-1) times then restart with a
  bumped ballot after a randomized anti-dueling delay
  (ref multi/paxos.cpp:757-801, 1244-1247); accept resent then falls
  back to prepare (AcceptRejected, ref :969-983, 1328-1343); commit
  retried until every node replied (ref :1022-1027, 1625-1641);
- REJECT only updates the proposer's max-ballot-seen — the deadline
  ladder performs the actual restart (ref multi/paxos.cpp:1224-1230
  OnReject);
- batch assembly at prepare quorum: adopted pre-accepted values
  first, then no-op hole fills for every gap below the open tail
  (including over the proposer's own earlier assignments — they wait
  for conflict re-proposal), then own initial proposals still in the
  open tail, then new values at the lowest free instances
  (ref multi/paxos.cpp:1047-1182 OnPrepareReply);
- conflict re-proposal: when an instance a proposer initially
  assigned commits with a different value, the displaced value is
  re-queued and assigned a fresh instance
  (ref multi/paxos.cpp:1540-1569 OnCommit).

Network model: calendars hold only per-edge scalars (ballots /
presence bits); every per-instance payload — prepare-reply snapshots,
accept batches, commit batches, per-instance acks — is materialized
at delivery time from the sender's current state arrays, which is
equivalent to the reference scheduling the sender's send later (see
core/net.py's module docstring for the legality argument).  This
makes network memory O(S*P*A), independent of the instance count, so
the general engine scales to millions of instances.

Fault injection (drop/dup/delay per THNetWork, crash per member/'s
RandomFailure) rides the network layer — see core/net.py.  Crashes
are fail-stop node silences capped at a minority of nodes (the
reference's member/ crash aborts the whole run and validates the
prefix; here the run continues on the surviving majority and the same
prefix validation applies).

Correlated faults (core/faults.py) compose on top of the i.i.d.
layer: a ``FaultSchedule`` of partition / one-way-cut / pause /
burst-loss episodes compiles to per-round tables the round function
indexes with ``min(t, horizon)``.  Edge reachability masks AND into
every send mask (a message on a cut edge is lost at the sender's
NIC); pauses subtract from the I/O-alive mask exactly like crashes —
no sends, no receives, no timer actions — but the node's state is
preserved and it resumes at the episode end; burst windows add to the
drop rate sampled in ``net.copy_plan``.  The liveness contract:
quiescence is never declared before the last heal, only *crashed*
proposers are excused from frontier extension (a paused proposer's
values are owed after it resumes), the commit-until-all-acked ladder
survives its proposer via the stall-triggered commit takeover, and
the watchdog budget is ``max_rounds`` past the final episode end
(``SimConfig.round_budget``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_paxos.analysis import tracecount
from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import ballot as bal
from tpu_paxos.core import faults as fltm
from tpu_paxos.core import geom as geo
from tpu_paxos.core import net as netm
from tpu_paxos.core import values as val
from tpu_paxos.utils import prng

# Proposer modes
DELAY = jnp.int32(0)  # waiting out the randomized prepare delay
PREPARING = jnp.int32(1)  # prepare broadcast in flight
PREPARED = jnp.int32(2)  # phase-1 quorum held; accepts in flight

# Ballot reported for committed values in prepare-reply snapshots so
# adoption always prefers them (they are chosen; re-proposing them is
# always safe).  Real ballots stay far below (count << 16 | node).
COMMITTED_BALLOT = jnp.int32(2**30)

_NEG = jnp.int32(jnp.iinfo(jnp.int32).min)  # -inf sentinel for masked max

# Idle-liveness patience: a PREPARED proposer with nothing in flight
# while the log still has holes (or unlearned chosen values) restarts
# its prepare after this many rounds, so holes and undelivered commits
# left by a crashed proposer get repaired by the survivors' no-op
# hole-filling + committed-value re-adoption (the reference repairs
# these through the same path whenever any proposer re-prepares, ref
# multi/paxos.cpp:1106-1130, 1184-1197).
IDLE_RESTART_ROUNDS = 8


def seeded_wedge() -> str:
    """Checker-recall knob: ``TPU_PAXOS_SEEDED_WEDGE=takeover``
    re-introduces the PR-1 pause-crash commit-TAKEOVER wedge (the
    stall-triggered commit takeover below is compiled OUT, so a
    committer crashing while a receiver is paused starves the paused
    node's learner forever — the exact bug the takeover was added to
    fix).  Read at ENGINE BUILD time: it selects a different traced
    program, so it is part of the fleet envelope key
    (fleet/envelope.envelope_key) and artifacts recorded under the
    flag only replay under the flag.  The model checker's pinned
    recall test (tests/test_modelcheck.py) arms it to prove the quick
    scope finds and shrinks the wedge exhaustively; it must never be
    set in production runs (``mc --pin`` refuses it)."""
    return os.environ.get("TPU_PAXOS_SEEDED_WEDGE", "")


class AcceptorState(NamedTuple):
    promised: jax.Array  # [A] int32 scalar promised ballot per acceptor
    max_seen: jax.Array  # [A] int32 max ballot ever seen
    acc_ballot: jax.Array  # [A, I] int32 accepted ballot (NONE none)
    acc_vid: jax.Array  # [A, I] int32 accepted vid


class ProposerState(NamedTuple):
    mode: jax.Array  # [P] int32 DELAY / PREPARING / PREPARED
    count: jax.Array  # [P] int32 ballot count
    ballot: jax.Array  # [P] int32 current ballot
    pmax_seen: jax.Array  # [P] int32 max ballot seen via rejects
    delay_until: jax.Array  # [P] int32 round to start the next prepare
    prep_deadline: jax.Array  # [P] int32
    prep_retries: jax.Array  # [P] int32
    promises: jax.Array  # [P, A] bool promises for current ballot
    adopted_b: jax.Array  # [P, I] int32 adopted pre-accepted ballot
    adopted_v: jax.Array  # [P, I] int32 adopted pre-accepted vid
    cur_batch: jax.Array  # [P, I] int32 vids being accepted at ballot
    acks: jax.Array  # [P, A, I] int8 0/1 per-instance accept acks
    #     (int8, not bool: the pallas ack kernel reads it natively —
    #     mosaic backs i1 operands with i32, which would 4x the
    #     cube's HBM traffic and overflow scoped VMEM)
    acc_deadline: jax.Array  # [P] int32
    acc_retries: jax.Array  # [P] int32
    own_assign: jax.Array  # [P, I] int32 own initial proposals by instance
    pend: jax.Array  # [P, C+W] int32 pending-value ring (W-padded, see
    #     prepare_queues: [C, C+W) is invariantly NONE so window ops
    #     are clamp-free dynamic slices)
    gate: jax.Array  # [P, C+W] int32 vid that must be chosen first (NONE
    #     free); padded like pend
    head: jax.Array  # [P] int32 ring head (absolute)
    tail: jax.Array  # [P] int32 ring tail (absolute)
    commit_vid: jax.Array  # [P, I] int32 values this proposer is committing
    commit_acked: jax.Array  # [P, A, I] bool
    commit_deadline: jax.Array  # [P] int32
    stall: jax.Array  # [P] int32 rounds spent idle while the log has holes
    commit_wait: jax.Array  # [P] bool: any committed instance not yet
    #     acked by every live node — a cached reduction of the
    #     commit_acked cube, refreshed only on the rounds that can
    #     change it (commit replies, new commits, crashes), so the
    #     resend/idle logic never pays a [P, A, I] pass on quiet rounds


class Metrics(NamedTuple):
    chosen_vid: jax.Array  # [I] int32 decided value (NONE undecided)
    chosen_round: jax.Array  # [I] int32 round of decision
    chosen_ballot: jax.Array  # [I] int32 deciding ballot
    msgs: jax.Array  # [7] int32 logical sends per message type


class SimState(NamedTuple):
    t: jax.Array  # int32 round counter (the virtual clock)
    acc: AcceptorState
    learned: jax.Array  # [A, I] int32 learner state per node (instances minor)
    prop: ProposerState
    net: netm.NetBuffers
    met: Metrics
    crashed: jax.Array  # [A] bool fail-stop crash mask
    done: jax.Array  # bool quiescence predicate
    qsums: jax.Array  # [1 + A + 3P] int32 cached global quiescence
    #     counts (chosen, learned-per-node, inflight/queue/own per
    #     proposer) — already collective-reduced, so replicated under
    #     sharding; refreshed only on rounds whose events can change
    #     them (see the quiescence block)
    qhmax: jax.Array  # int32 cached global chosen high-water mark


@dataclasses.dataclass(frozen=True)
class SimResult:
    learned: np.ndarray  # [I, A]
    chosen_vid: np.ndarray  # [I]
    chosen_round: np.ndarray  # [I]
    chosen_ballot: np.ndarray  # [I]
    rounds: int
    done: bool
    crashed: np.ndarray  # [A] bool
    msgs: np.ndarray  # [7] logical send counts
    expected_vids: np.ndarray  # union of workload vids (all proposers)

    @property
    def rounds_to_chosen(self) -> np.ndarray:
        """Per decided instance, rounds from t=0 to decision."""
        return self.chosen_round[self.chosen_vid != int(val.NONE)]

    def value_status(self, vid: int) -> dict:
        """Per-proposal completion status — the Callback SPI surface
        (ref multi/paxos.h:241-246 ``Run``; member/paxos.h:142-163
        ``Accepted``/``Applied``):

        - ``pending``: never chosen (still queued, lost with a crashed
          proposer, or displaced and re-proposed after this snapshot);
        - ``accepted``: chosen — accepted by a majority of acceptors
          (safe while a majority lives, ref member/paxos.h:149-151);
        - ``applied``: additionally learned by a majority of nodes
          (the Applied quorum that sequences membership changes, ref
          member/paxos.h:155-162).

        The reference's ``Unproposable`` (node is not a proposer) is a
        config-time error here: workloads only target cfg.proposers.
        """
        if vid < 0:
            # NONE / no-op sentinels are not proposals and must not
            # alias against undecided or hole-filled instances
            return {"status": "pending"}
        where = np.flatnonzero(self.chosen_vid == vid)
        if not where.size:
            return {"status": "pending"}
        i = int(where[0])
        n_nodes = self.learned.shape[1]
        learners = int((self.learned[i] != int(val.NONE)).sum())
        applied = learners >= n_nodes // 2 + 1
        return {
            "status": "applied" if applied else "accepted",
            "instance": i,
            "round": int(self.chosen_round[i]),
            "ballot": int(self.chosen_ballot[i]),
            "learners": learners,
        }


def _init_state(
    cfg: SimConfig, pend, gate, tail, root: jax.Array,
    geometry=None, geom=None, pknobs=None,
) -> SimState:
    a, i = cfg.n_nodes, cfg.n_instances
    p = len(cfg.proposers)
    c = pend.shape[1]
    s = cfg.faults.max_delay + 2
    k0 = prng.stream(root, prng.STREAM_PREPARE_DELAY, 0)
    lo = (
        cfg.protocol.prepare_delay_min if pknobs is None
        else pknobs.prepare_delay_min
    )
    hi = (
        cfg.protocol.prepare_delay_max if pknobs is None
        else pknobs.prepare_delay_max
    )
    if geometry is None:
        delay0 = jax.random.randint(k0, (p,), lo, hi + 1, dtype=jnp.int32)
    else:
        # menu-switched initial backoff: the same bit-exactness
        # contract as the in-round draws (core/geom.menu_randint)
        delay0 = geo.menu_randint(
            geometry, geom.geom_idx, k0, "proposers", lo, hi + 1,
            pad_value=0,
        )
    none = lambda *sh: jnp.full(sh, bal.NONE, jnp.int32)  # noqa: E731
    return SimState(
        t=jnp.int32(0),
        acc=AcceptorState(
            promised=jnp.zeros((a,), jnp.int32),
            max_seen=jnp.zeros((a,), jnp.int32),
            acc_ballot=none(a, i),
            acc_vid=none(a, i),
        ),
        learned=none(a, i),
        prop=ProposerState(
            mode=jnp.full((p,), DELAY, jnp.int32),
            count=jnp.zeros((p,), jnp.int32),
            ballot=jnp.zeros((p,), jnp.int32),
            pmax_seen=jnp.zeros((p,), jnp.int32),
            delay_until=delay0,
            prep_deadline=jnp.zeros((p,), jnp.int32),
            prep_retries=jnp.zeros((p,), jnp.int32),
            promises=jnp.zeros((p, a), jnp.bool_),
            adopted_b=none(p, i),
            adopted_v=none(p, i),
            cur_batch=none(p, i),
            acks=jnp.zeros((p, a, i), jnp.int8),
            acc_deadline=jnp.zeros((p,), jnp.int32),
            acc_retries=jnp.zeros((p,), jnp.int32),
            own_assign=none(p, i),
            pend=pend,
            gate=gate,
            head=jnp.zeros((p,), jnp.int32),
            tail=tail,
            commit_vid=none(p, i),
            commit_acked=jnp.zeros((p, a, i), jnp.bool_),
            commit_deadline=jnp.zeros((p,), jnp.int32),
            stall=jnp.zeros((p,), jnp.int32),
            commit_wait=jnp.zeros((p,), jnp.bool_),
        ),
        net=netm.init_buffers(s, p, a),
        met=Metrics(
            chosen_vid=none(i),
            chosen_round=none(i),
            chosen_ballot=none(i),
            msgs=jnp.zeros((7,), jnp.int32),
        ),
        crashed=jnp.zeros((a,), jnp.bool_),
        done=jnp.bool_(False),
        # initial counts are exact for the all-NONE initial state
        qsums=jnp.zeros((1 + a + 3 * p,), jnp.int32),
        qhmax=jnp.int32(-1),
    )


def _gate_satisfied(g, chosen_mask):
    """Gate test shared by _assignable_window and the engine's gated
    assignment branch: an entry is proposable when ungated or its gate
    vid is in the chosen-membership bitmap; gates on out-of-workload
    vids never satisfy (the semantics of gating on a value that is
    never proposed)."""
    v_cap = chosen_mask.shape[0]
    g_chosen = (
        chosen_mask[jnp.clip(g, 0, v_cap - 1)]
        & (g != val.NONE)
        & (g < v_cap)
    )
    return (g == val.NONE) | g_chosen


def _window_ops(w: int):
    """Contiguous-window read/write on one ring row at absolute
    position h.  Rows come pre-padded by the assignment-window width
    (prepare_queues), so both ops are bare dynamic slices — no
    per-round copy — and never clamp the start (h <= c always: h is
    head or tail, both bounded by the capacity proof)."""

    def read(row, h):
        return jax.lax.dynamic_slice(row, (h,), (w,))

    def write(row, wv, h):
        return jax.lax.dynamic_update_slice(row, wv, (h,))

    return read, write


def _assignable_window(pend, gate, head, tail, chosen_mask, w):
    """First-fit view of the head window: which of the next W queue
    entries are live and gate-satisfied.  Gated entries (the in-order
    client seam, ref multi/main.cpp:398-401: next value only after the
    previous one's callback) do NOT block later entries — the
    reference's propose queue is a set, and a conflict-requeued value
    must be able to run ahead of entries gated on it.

    Under sharding the gate test stays purely LOCAL (this shard's gate
    vids against this shard's chosen slice): ``split_workload`` places
    every gated entry on the shard of its gate's value, and conflict
    requeues stay on their shard, so a gate's predecessor is always
    chosen on this shard or not at all.  A cross-shard reduction here
    would be wrong anyway — window slots of different shards hold
    unrelated queue entries, so a positional OR mixes meanings (and
    would let the NONE sentinel match unchosen instances).

    ``chosen_mask`` is a [vid_cap] bool chosen-membership bitmap (or
    None for gate-free runs, eliding gate logic entirely): a direct
    ``g == chosen_vid`` compare materializes an O(W * I) intermediate
    — 17 GB/round at W=1024, I=1M, the single largest tensor in the
    profile — while the bitmap gather is O(W) on top of the O(I)
    scatter its caller pays once per round.

    Returns (qvid [P, W], ok [P, W])."""
    offs = jnp.arange(w)
    # The window is CONTIGUOUS from head, so reads are padded dynamic
    # slices, not gathers (a [P, W] gather from the [P, C] ring was
    # ~40% of the round's device time at W = 256k).
    wread, _ = _window_ops(w)
    qvid = jax.vmap(wread)(pend, head)
    live = ((head[:, None] + offs[None]) < tail[:, None]) & (qvid != val.NONE)
    if chosen_mask is None:
        return qvid, live
    g = jax.vmap(wread)(gate, head)  # [P, W]
    ok = live & _gate_satisfied(g, chosen_mask)
    return qvid, ok


def build_engine(
    cfg: SimConfig,
    n_pend_cap: int,
    axis_name: str | tuple[str, ...] | None = None,
    n_shards: int = 1,
    vid_cap: int = 0,
    use_pallas: bool | None = None,
    runtime_schedule: bool = False,
    runtime_knobs: bool = False,
    telemetry: bool = False,
    window_rounds: int = 0,
    geometry: "geo.GeometryEnvelope | None" = None,
    runtime_protocol: bool = False,
):
    """Compile-time closure: returns ``round_fn(root_key, state) ->
    state`` plus static geometry.  Everything data-dependent lives in
    the state; everything shape-like is baked in.

    With ``telemetry=True`` the flight recorder
    (telemetry/recorder.py) rides the loop carry: ``round_fn(...,
    tele=Telemetry)`` returns ``(state, telemetry)``, with every
    recorder field computed from values the round already produced —
    the recorder consumes NO PRNG streams and never feeds back into
    the state, so the armed engine is decision-log-identical to the
    plain one (sha256 parity pinned by tests/test_telemetry.py) and
    ``telemetry=False`` traces the exact pre-recorder program.
    Unsupported together with ``axis_name`` (the sharded path keeps
    its per-shard state replication argument recorder-free for now).

    A nonzero ``window_rounds`` (telemetry only) additionally arms
    the WINDOWED time-series plane: ``tele`` becomes a ``(Telemetry,
    TelemetryWindows)`` pair and the recorder block also buckets the
    fault-layer counters, stall depth, and takeover/restart events by
    virtual round into ``[NUM_WINDOWS]`` rings (bucket width =
    ``window_rounds`` rounds, last bucket overflow).  Still strictly
    read-only — the same neutrality contract and sha256 parity hold,
    and ``window_rounds=0`` traces the exact pre-windowing armed
    program.

    With ``runtime_knobs=True`` the i.i.d. fault knobs are NOT baked
    in either: ``round_fn(root, state, tab, knobs)`` takes a traced
    ``net.FaultKnobs`` (drop/dup/delay/crash as int32 scalars) and
    every ``if fc.*`` Python branch below runs in its always-on
    masked form — drop/dup coins compared against the traced rates
    (all-false at rate 0), the delay drawn from the traced
    ``[min_delay, max_delay]`` span (a ``[0, 0]`` span samples 0),
    crash injection against the traced crash rate, and the
    crash-coupled cached blocks (commit-ack refresh, quiescence
    counts) always-on (exact: the caches are only ever skipped when
    provably current, so measuring every round returns the same
    values).  ``cfg.faults.max_delay`` then acts as the ENVELOPE
    delay bound: it sizes the arrival ring (``init_state``), and
    every per-call ``knobs.max_delay`` must stay <= it (enforced
    host-side by fleet/runner.py; the ring size itself is
    decision-log-neutral).  Decision-log sha256 parity with the
    compile-time path is pinned per (cfg, schedule, seed) by
    tests/test_knobs.py.

    With ``runtime_schedule=True`` the correlated-fault schedule is NOT
    baked in: ``round_fn(root, state, tab)`` takes a traced
    ``fleet.schedule_table.ScheduleTable`` and computes the per-round
    reach/pause/drop masks inside the step (``masks_at``), so ONE
    compiled executable covers every episode mix of the table's
    ``(max_episodes, n_nodes)`` envelope — the fleet runner vmaps this
    over a lane axis of tables.  ``cfg.faults.schedule`` must be None
    in this mode (the schedule arrives per call); the single-run
    constant path below stays the default and the two are
    decision-log-identical for the same schedule (the mask values and
    the PRNG streams are equal round for round — parity pinned by
    tests/test_fleet.py).

    With ``axis_name`` set (one mesh axis name, or a tuple of names
    for the 2-D dcn x ici multi-host mesh — ``lax`` collectives and
    ``axis_index`` reduce/linearize over the whole tuple), the round
    function is the per-shard body of
    an instance-axis ``shard_map``: every [.., I, ..] array it sees is
    a shard of ``n_instances // n_shards`` instances (with the queue
    arrays per-shard private), instance indices are globalized via
    ``lax.axis_index``, and the handful of places where instance-axis
    information crosses shards — high-water marks, send predicates,
    gate membership, quiescence — become ``pmax``/``psum`` collectives
    over ICI (and DCN between hosts on the 2-D mesh).  All [P]/[A]-shaped protocol state stays replicated: its
    updates are functions of replicated network arrivals and these
    global reductions, so every shard computes identical copies (the
    sharded-vs-unsharded equivalence test pins this).

    With ``geometry`` set (a :class:`geom.GeometryEnvelope`), ``cfg``
    must be the envelope's BOUND shape (``geometry.bound_cfg``): every
    [A]/[P]-shaped array pads to the bound and the TRUE geometry
    arrives per call as a traced :class:`geom.Geometry` —
    ``round_fn(..., geom=Geometry)``.  Absent nodes are permanently
    masked (never sampled, never quorum-counted, never send or
    receive: the exact-at-zero masked-form discipline of the runtime
    fault knobs), and every PRNG draw whose shape depends on the
    geometry dispatches through ``lax.switch`` over the menu so each
    true geometry's coins are bit-identical to its unpadded build
    (threefry bits are shape-dependent — see core/geom.py; sha256
    parity pinned by tests/test_envelope_pad.py).  ``geometry=None``
    traces the byte-identical pre-envelope program.

    With ``runtime_protocol=True`` the protocol liveness constants
    (retry ladders, backoff spans, commit-ladder stall patience) are
    NOT baked in: ``round_fn(..., pknobs=ProtocolKnobs)`` takes them
    as traced int32 scalars (geom.protocol_knobs — span-checked
    against config.PROTOCOL_SPANS).  Exact: randint's bits depend
    only on key/shape/dtype, so traced delay spans draw the same
    values as static ones, and every comparison/arithmetic use is
    elementwise on the traced scalar.
    """
    a, i_cap = cfg.n_nodes, cfg.n_instances
    p = len(cfg.proposers)
    c = n_pend_cap
    pc, fc = cfg.protocol, cfg.faults
    # Static geometry of the degenerate path; under a GeometryEnvelope
    # the round function shadows these with the traced Geometry's
    # fields (same names, so every use-site is fork-free).
    _quorum0 = cfg.quorum
    _pn0 = jnp.asarray(cfg.proposers, jnp.int32)  # [P] proposer -> node
    _max_crash0 = (a - 1) // 2
    if geometry is not None:
        if not isinstance(geometry, geo.GeometryEnvelope):
            raise TypeError("geometry must be a GeometryEnvelope or None")
        if a != geometry.bound_nodes or p != geometry.bound_proposers:
            raise ValueError(
                f"a geometry-padded engine must be built at the "
                f"envelope bound ({geometry.bound_nodes} nodes, "
                f"{geometry.bound_proposers} proposers); cfg has "
                f"({a}, {p}) — use geometry.bound_cfg(cfg)"
            )
    # Protocol constants: one accessor for both paths — plain Python
    # ints (byte-identical degenerate program) or the traced
    # ProtocolKnobs passed per call.
    _pk0 = geo.static_protocol(pc, stall_patience=IDLE_RESTART_ROUNDS)
    if i_cap % n_shards:
        raise ValueError(f"n_instances {i_cap} not divisible by {n_shards}")
    i_loc = i_cap // n_shards  # instances per shard ([I]-axis array size)
    # Seeded-wedge selection happens at BUILD time so the engine's
    # traced program is fixed per closure (see seeded_wedge()).
    _wedge_no_takeover = seeded_wedge() == "takeover"
    if runtime_schedule and fc.schedule is not None:
        raise ValueError(
            "runtime_schedule engines take their schedule per call "
            "(ScheduleTable); cfg.faults.schedule must be None"
        )
    if telemetry and axis_name is not None:
        raise ValueError(
            "telemetry is not supported on the sharded engine yet "
            "(the recorder's per-instance ledger is unsharded)"
        )
    if window_rounds and not telemetry:
        raise ValueError(
            "window_rounds arms the recorder's windowed plane; it "
            "requires telemetry=True"
        )
    _ww = int(window_rounds)
    if telemetry:
        from tpu_paxos.telemetry import recorder as _rec
    if runtime_schedule:
        from tpu_paxos.fleet import schedule_table as _stm
    # Correlated-fault schedule, lowered to dense per-round tables and
    # baked in as compile-time constants (replicated under shard_map —
    # every shard indexes identical tables with the replicated round
    # counter, so schedule faults never diverge across shards).
    comp = fltm.compile_schedule(fc.schedule, a)
    horizon = comp.horizon if comp is not None else 0
    reach_tab = (
        jnp.asarray(comp.reach) if comp is not None and comp.has_reach else None
    )
    pause_tab = (
        jnp.asarray(comp.paused) if comp is not None and comp.has_pause else None
    )
    drop_tab = (
        jnp.asarray(comp.extra_drop)
        if comp is not None and comp.has_burst
        else None
    )
    crash_tab = (
        jnp.asarray(comp.crashed)
        if comp is not None and comp.has_crash
        else None
    )
    gray_tab = (
        jnp.asarray(comp.gray)
        if comp is not None and comp.has_gray
        else None
    )
    # Per-edge [A, A] fault tables (cfg.faults.edges) ride the masked
    # knobs-path sampling with the matrices baked in as compile-time
    # CONSTANTS — the scalar branches in copy_plan cannot express
    # per-edge rates.  Bit-identical to the scalar path for a uniform
    # matrix (the FaultKnobs parity contract, tests/test_geo.py).
    if runtime_knobs and fc.edges is not None:
        raise ValueError(
            "runtime_knobs engines take their knobs per call (matrix "
            "or scalar FaultKnobs); cfg.faults.edges must be None"
        )
    static_mknobs = (
        jax.tree.map(jnp.asarray, netm.matrix_knobs(fc))
        if fc.edges is not None else None
    )
    # Delivery-time partition cut (FaultConfig.delivery_cut): a
    # compile-time flag — armed engines void in-flight arrivals on
    # edges cut at the DELIVERY round.  Meaningful only where reach
    # masks exist: the constant path elides it for cut-free schedules
    # (identical program), the runtime path arms it whenever the flag
    # is set (exact for cut-free tables: an all-true reach round is
    # the identity).
    delivery_cut = bool(fc.delivery_cut)
    # Scheduled crash points (or a runtime table that may carry them)
    # mean `crashed` can change without any i.i.d. draw — the
    # crash-coupled cached blocks (commit-ack refresh, quiescence
    # counts) must then refresh every round, exactly like a nonzero
    # crash rate (exact: the caches are only ever skipped when
    # provably current).
    crash_faults = bool(
        runtime_knobs or fc.crash_rate or runtime_schedule
        or crash_tab is not None
    )
    from tpu_paxos.core import simkern as _sk

    if use_pallas is None:
        # Fused single-pass kernels for the two hottest event blocks
        # (core/simkern.py) on TPU backends at supported geometries;
        # the jnp formulations below stay canonical and run everywhere
        # else (bit-identical — tests/test_simkern.py).
        use_pallas = (
            jax.default_backend() == "tpu" and _sk.supported(i_loc, a, p)
        )
    elif use_pallas and (
        jax.default_backend() != "tpu" or not _sk.supported(i_loc, a, p)
    ):
        # an explicit request outside the kernels' envelope (or off
        # TPU) must fail loudly, not truncate the grid or die in a
        # cryptic mosaic lowering error
        raise ValueError(
            f"use_pallas=True unsupported here (backend="
            f"{jax.default_backend()}, I={i_loc}, A={a}, P={p}); "
            "see simkern.supported()"
        )

    if axis_name is None:
        def gmax(x):
            return x

        def gany(b):
            return b

        def gsum(x):
            return x
    else:
        def gmax(x):
            return jax.lax.pmax(x, axis_name)

        def gany(b):
            return jax.lax.pmax(b.astype(jnp.int32), axis_name).astype(bool)

        def gsum(x):
            return jax.lax.psum(x, axis_name)

    def gall(b):
        return ~gany(~b)

    # rany: an any-reduction over REPLICATED inputs — network arrivals
    # (the calendars are replicated), [P]/[A] protocol scalars, and
    # values already derived from collective outputs.  Every shard
    # computes the identical result, so consistent branching needs no
    # collective; issuing one anyway (as earlier rounds of this code
    # did) adds a tiny latency-bound collective per site per round on
    # a real mesh.  Use gany ONLY when the reduced value involves
    # instance-sharded data.
    def rany(b):
        return jnp.any(b)

    def round_fn(
        root: jax.Array, st: SimState, tab=None, knobs=None, tele=None,
        geom=None, pknobs=None,
    ):
        if runtime_schedule and tab is None:
            raise TypeError(
                "this engine was built with runtime_schedule=True; "
                "round_fn needs a ScheduleTable argument"
            )
        if runtime_knobs and knobs is None:
            raise TypeError(
                "this engine was built with runtime_knobs=True; "
                "round_fn needs a FaultKnobs argument"
            )
        if telemetry and tele is None:
            raise TypeError(
                "this engine was built with telemetry=True; round_fn "
                "needs a Telemetry accumulator argument"
            )
        if (geometry is not None) != (geom is not None):
            raise TypeError(
                "a GeometryEnvelope engine takes its Geometry per "
                "call (round_fn geom=); a bound-free engine takes "
                "none"
            )
        if runtime_protocol and pknobs is None:
            raise TypeError(
                "this engine was built with runtime_protocol=True; "
                "round_fn needs a ProtocolKnobs argument"
            )
        # Geometry + protocol accessors: the degenerate bindings are
        # the build-time constants, so geometry=None and
        # runtime_protocol=False trace the byte-identical
        # pre-envelope program.
        pn = _pn0 if geom is None else geom.pn
        quorum = _quorum0 if geom is None else geom.quorum
        max_crash = _max_crash0 if geom is None else geom.max_crash
        node_mask = None if geom is None else geom.node_mask
        prop_mask = None if geom is None else geom.prop_mask
        pk = _pk0 if pknobs is None else pknobs
        # queue rows must be pre-padded by the window width (see
        # prepare_queues) so window ops are copy-free dynamic slices.
        # ValueError, not assert: this is trace-time-only (zero runtime
        # cost) and must still fail fast under `python -O` — an
        # unpadded state (e.g. a checkpoint from before the padding
        # change) would otherwise silently clamp window slices.
        for _name in ("pend", "gate"):
            _w = getattr(st.prop, _name).shape[-1]
            if _w != c + cfg.assign_window:
                raise ValueError(
                    f"{_name} rows are {_w} wide; expected {c} + "
                    f"assign_window {cfg.assign_window} padding"
                )
        t = st.t
        if axis_name is None:
            off = jnp.int32(0)
        else:
            off = (jax.lax.axis_index(axis_name) * i_loc).astype(jnp.int32)
        # global instance ids of this shard (noop encoding, high-water
        # ordering, and the decision log all use global ids)
        idx = off + jnp.arange(i_loc, dtype=jnp.int32)
        s = st.net.prep_req.shape[0]
        slot = t % s
        ar = jax.tree.map(lambda b: b[slot], st.net)
        net = netm.clear_slot(st.net, slot)

        if runtime_schedule:
            # Per-round masks computed from the traced per-lane table
            # (fleet/schedule_table.masks_at) — same composition
            # semantics as the constant rows below, so the two paths
            # are decision-log-identical for the same schedule.  All
            # five dimensions are live (the table's content, not its
            # shape, says which episodes exist).
            reach_t, paused_t, xdrop_t, gray_t = _stm.masks_at(tab, t)
            crash_t = _stm.crashes_at(tab, t)  # [A]
        else:
            # Fault-schedule tables for this round (min(t, horizon):
            # row `horizon` is the healed steady state, so
            # post-schedule rounds read all-clear masks at no branch
            # cost — crash rows are cumulative, so the same read keeps
            # scheduled crashes in force forever).
            tt = (
                jnp.minimum(t, jnp.int32(horizon)) if comp is not None
                else None
            )
            paused_t = pause_tab[tt] if pause_tab is not None else None  # [A]
            reach_t = reach_tab[tt] if reach_tab is not None else None
            xdrop_t = drop_tab[tt] if drop_tab is not None else None  # int32
            crash_t = crash_tab[tt] if crash_tab is not None else None  # [A]
            gray_t = gray_tab[tt] if gray_tab is not None else None  # [A]

        # I/O-alive mask: crashed OR currently paused nodes neither
        # send, receive, nor act on timers this round.  Excusals
        # (quiescence, frontier extension, commit-ack waivers) stay on
        # `st.crashed` alone — a paused node's obligations are only
        # deferred, never waived.
        alive_a = ~st.crashed  # [A]
        if node_mask is not None:
            # absent nodes: permanently dead for ALL I/O and timers
            # (and, unlike crashes below, excused from every
            # obligation via dead_a)
            alive_a = alive_a & node_mask
        if paused_t is not None:
            alive_a = alive_a & ~paused_t
        prop_alive = alive_a[pn]  # [P]
        if prop_mask is not None:
            # pad proposer slots gather node 0's aliveness through
            # pn's 0-padding — mask them out so they never start,
            # resend, restart, or take over
            prop_alive = prop_alive & prop_mask

        # Per-edge reachability cuts ANDed into every send mask below
        # (send-time semantics: copies already in the calendars still
        # deliver — a schedule the i.i.d. drop fault already contains
        # — unless delivery_cut is armed, below).
        reach_pa = reach_t[pn] if reach_t is not None else None  # [P, A]
        reach_ap = reach_t[:, pn] if reach_t is not None else None  # [A, P]

        if delivery_cut and reach_pa is not None:
            # Delivery-time cut (the PR-1 follow-on): in-flight copies
            # whose edge is severed on their ARRIVAL round are dropped
            # at the partition edge; same-side copies deliver
            # untouched (net.delivery_mask — exact for cut-free
            # rounds, where reach is all-true).
            ar = netm.delivery_mask(ar, reach_pa, reach_ap)

        def _cut_pa(m):  # [P, A] proposer->node send mask through cuts
            return m if reach_pa is None else m & reach_pa

        def _cut_ap(m):  # [A, P] node->proposer send mask through cuts
            return m if reach_ap is None else m & reach_ap

        # Sampling knobs: per-call traced (runtime_knobs) or the
        # compile-time constant matrices of an edges-bearing config.
        # Matrix fields are sliced to each direction's edge shape
        # (net.edge_knobs — a no-op passthrough for scalar fields, so
        # the scalar runtime-knob program is unchanged); gray delay
        # inflation composes per edge as src + dst slowness, clamped
        # at the ring bound inside copy_plan.
        kn_eff = knobs if runtime_knobs else static_mknobs
        if geom is None:
            if kn_eff is not None:
                aidx_n = jnp.arange(a)
                kn_pa = netm.edge_knobs(kn_eff, pn, aidx_n)
                kn_ap = netm.edge_knobs(kn_eff, aidx_n, pn)
            else:
                kn_pa = kn_ap = None
            if gray_t is not None:
                gray_pa = gray_t[pn][:, None] + gray_t[None, :]  # [P, A]
                gray_ap = gray_t[:, None] + gray_t[pn][None, :]  # [A, P]
            else:
                gray_pa = gray_ap = None

            def _plan(key, edge_shape, pa):
                return netm.copy_plan(
                    key, edge_shape, fc, extra_drop=xdrop_t,
                    knobs=kn_pa if pa else kn_ap,
                    gray=gray_pa if pa else gray_ap,
                    delay_bound=fc.max_delay,
                )
        else:
            # Menu-switched copy plans: threefry bits are
            # shape-dependent, so branch m samples at menu entry m's
            # TRUE edge shape — bit-identical to the unpadded engine —
            # with the knob matrices / gray vectors statically sliced
            # to the entry's node prefix and proposer map, then pads
            # the plan to the bound with dead copies (alive=False,
            # delay=0: the pad region is never sent into anyway).
            def _plan(key, edge_shape, pa):
                def _branch(n_m, props_m):
                    p_m = len(props_m)
                    pn_m = jnp.asarray(props_m, jnp.int32)

                    def _b(k):
                        if kn_eff is not None:
                            ai_m = jnp.arange(n_m)
                            kn_m = (
                                netm.edge_knobs(kn_eff, pn_m, ai_m) if pa
                                else netm.edge_knobs(kn_eff, ai_m, pn_m)
                            )
                        else:
                            kn_m = None
                        if gray_t is not None:
                            if pa:
                                gr_m = (
                                    gray_t[pn_m][:, None]
                                    + gray_t[None, :n_m]
                                )
                            else:
                                gr_m = (
                                    gray_t[:n_m, None]
                                    + gray_t[pn_m][None, :]
                                )
                        else:
                            gr_m = None
                        shp = (p_m, n_m) if pa else (n_m, p_m)
                        al_m, dl_m = netm.copy_plan(
                            k, shp, fc, extra_drop=xdrop_t, knobs=kn_m,
                            gray=gr_m, delay_bound=fc.max_delay,
                        )
                        al_f = jnp.zeros(
                            (netm.MAX_COPIES, *edge_shape), jnp.bool_
                        )
                        dl_f = jnp.zeros(
                            (netm.MAX_COPIES, *edge_shape), jnp.int32
                        )
                        r, co = (p_m, n_m) if pa else (n_m, p_m)
                        al_f = al_f.at[:, :r, :co].set(al_m)
                        dl_f = dl_f.at[:, :r, :co].set(dl_m)
                        return al_f, dl_f

                    return _b

                return jax.lax.switch(
                    geom.geom_idx,
                    [_branch(n_m, pr_m) for n_m, pr_m in geometry.menu],
                    key,
                )

        keys = jax.random.split(prng.stream(root, prng.STREAM_NET_DROP, t), 8)

        # ---------------- acceptor side ----------------
        acc = st.acc
        learned = st.learned

        # PREPARE arrivals (crashed acceptors ignore everything).
        preq = jnp.where(alive_a[None, :], ar.prep_req, bal.NONE)  # [P, A]
        grant = preq > acc.promised[None, :]  # strict >, ref :866
        rej_prep = (preq != bal.NONE) & (preq < acc.promised[None, :])
        max_seen = jnp.maximum(acc.max_seen, jnp.max(preq, axis=0))
        promised = jnp.maximum(
            acc.promised, jnp.max(jnp.where(grant, preq, bal.NONE), axis=0)
        )

        # ACCEPT arrivals.  Batch content is materialized at delivery
        # from the sending proposer's cur_batch (pre-round state), valid
        # iff its ballot still equals the arriving edge ballot and it is
        # still PREPARED; stale in-flight accepts (the proposer has
        # since restarted at a higher ballot and cleared the batch) are
        # dropped — a schedule the drop fault already contains.
        apres = jnp.where(alive_a[None, :], ar.acc_req, bal.NONE)  # [P, A]
        abal = st.prop.ballot  # [P] content ballot (current)
        abat = st.prop.cur_batch  # [P, I]
        has_acc = (apres != bal.NONE) & (apres == abal[:, None]) & (
            st.prop.mode == PREPARED
        )[:, None]
        # the edge ballot itself did travel: it bumps max_seen even
        # when the content is stale-dropped (ref acceptor sees it).
        max_seen = jnp.maximum(max_seen, jnp.max(apres, axis=0))
        elig = has_acc & (abal[:, None] >= promised)  # >=, ref :1366
        rej_acc = has_acc & ~elig
        # The [P, A, I] store cube exists only on rounds where an
        # eligible accept actually arrives (roughly a third of rounds
        # at the reference fault rates) — cond-gated on a GLOBAL
        # predicate so every shard branches identically.  When the
        # branch is skipped the acceptor arrays pass through
        # untouched, exactly what the all-false cube would produce.
        any_acc_arr = rany(elig)

        def _store_accepts(acc_ballot, acc_vid):
            if use_pallas:
                return _sk.store_accepts(
                    acc_ballot, acc_vid, learned, abat, abal, elig
                )
            # Per-instance ack: store-or-match (see module docstring
            # for the deviation from the reference's blanket batch
            # ack).  The proposer axis is UNROLLED (P is a small
            # static constant) into a running elementwise masked-max
            # over [A, I] — a single fused HBM pass — instead of
            # materializing the [P, A, I] candidate cube and reducing
            # it (the cube's ~4 intermediate passes were the single
            # largest block in the round profile).  Exact because
            # ballots are unique per proposer ((count << 16) | node),
            # so the running max never ties across P.
            is_comm = learned != val.NONE  # [A, I]
            best_b = jnp.full_like(acc_ballot, bal.NONE)
            best_v = jnp.full_like(acc_vid, val.NONE)
            for pi in range(p):
                batp = abat[pi]  # [I]
                ackp = (
                    elig[pi][:, None]
                    & (batp != val.NONE)[None, :]
                    & jnp.where(
                        is_comm,
                        batp[None, :] == learned,
                        abal[pi] >= acc_ballot,
                    )
                )  # [A, I]
                candp = jnp.where(ackp & ~is_comm, abal[pi], bal.NONE)
                take = candp > best_b
                best_b = jnp.where(take, candp, best_b)
                best_v = jnp.where(
                    take, jnp.broadcast_to(batp[None, :], best_v.shape), best_v
                )
            do_store = best_b != bal.NONE
            return (
                jnp.where(do_store, best_b, acc_ballot),
                jnp.where(do_store, best_v, acc_vid),
            )

        acc_ballot, acc_vid = jax.lax.cond(
            any_acc_arr,
            _store_accepts,
            lambda b, v: (b, v),
            acc.acc_ballot,
            acc.acc_vid,
        )

        # COMMIT arrivals -> learner state (ref OnCommit,
        # multi/paxos.cpp:1494-1518).  Content is the sender's
        # write-once commit_vid array at delivery (a superset of the
        # send-time batch — a legal later send).
        cpres = ar.com_pres & alive_a[None, :]  # [P, A]
        cbat = st.prop.commit_vid  # [P, I]
        # Same gating pattern as the accept store: the [P, A, I]
        # delivery cube only on rounds a commit actually arrives.
        any_com_arr = rany(cpres)

        def _learn_commits(learned):
            # Unrolled over P like _store_accepts: a running
            # elementwise max over [A, I], no [P, A, I] cube.
            inc_v = jnp.full_like(learned, _NEG)
            for pi in range(p):
                incp = cpres[pi][:, None] & (cbat[pi] != val.NONE)[None, :]
                inc_v = jnp.maximum(
                    inc_v, jnp.where(incp, cbat[pi][None, :], _NEG)
                )
            return jnp.where(
                (inc_v != _NEG) & (learned == val.NONE), inc_v, learned
            )

        learned = jax.lax.cond(
            any_com_arr, _learn_commits, lambda l: l, learned
        )

        acc = AcceptorState(promised, max_seen, acc_ballot, acc_vid)

        # ---------------- proposer side ----------------
        pr = st.prop
        # A->P arrivals are masked on BOTH ends: the sending acceptor
        # must be I/O-alive at delivery (reply payloads materialize
        # from its state) and so must the receiving proposer — a
        # paused proposer's inbound I/O is suppressed, not buffered
        # (for a crashed receiver this is behavior-neutral: every
        # action mask already excludes it forever).
        rx_p = alive_a[:, None] & prop_alive[None, :]  # [A, P]
        # REJECT arrivals only update max-ballot-seen (ref OnReject).
        rejs = jnp.where(rx_p, ar.rej, bal.NONE)  # [A, P]
        pmax_seen = jnp.maximum(pr.pmax_seen, jnp.max(rejs, axis=0))

        # PREPARE_REPLY arrivals: promises + adoption merge.  The
        # accepted-state snapshot is the acceptor's state at delivery
        # INCLUDING this round's accept/commit updates (the post-round
        # snap_b/snap_v inside _adopt below) — equivalent to the
        # acceptor generating its reply at the end of the delivery
        # round, which is strictly safer: its promise took effect
        # earlier, and a fresher snapshot's max-ballot value is
        # exactly what a later-generated reply would report.  Using
        # the post-update arrays (rather than reaching back to
        # st.acc/st.learned) also ends the pre-round buffers' liveness
        # at the accept/commit conds, letting XLA alias their
        # pass-through branches instead of copying [A, I] carries
        # every round.
        pecho = jnp.where(rx_p, ar.prep_echo, bal.NONE)  # [A, P]
        match = (pecho == pr.ballot[None, :]) & (pr.mode[None, :] == PREPARING)
        promises2 = pr.promises | match.T  # [P, A]
        # Prepare replies only arrive while some proposer is in its
        # (rare) phase-1 — the acceptor snapshot and the [P, A, I]
        # adoption passes run under a cond (global predicate: every
        # shard branches identically).
        any_reply = rany(match)

        def _adopt(ab, av):
            # Accepted-state snapshot at delivery (this round's
            # updated arrays — see the block comment above for why a
            # fresher snapshot is legal and cheaper); committed values
            # are included at COMMITTED_BALLOT (ref
            # FilterAcceptedValues includes committed_values_,
            # multi/paxos.cpp:913-922).
            snap_b = jnp.where(
                learned != val.NONE, COMMITTED_BALLOT, acc.acc_ballot
            )
            snap_v = jnp.where(
                learned != val.NONE, learned, acc.acc_vid
            )
            # Adoption merge as two fused masked-max passes (argmax +
            # take_along_axis gather cost ~1/3 of the whole round's
            # wall time at 1M instances).  Exact: cells tied at the
            # max ballot hold the same value — one proposer per ballot
            # sends one value per instance, and committed-sentinel
            # rows all hold the agreed chosen value.
            rep_mask = match.T[:, :, None]  # [P, A, 1]
            best_b = jnp.max(
                jnp.where(rep_mask, snap_b[None], bal.NONE), axis=1
            )  # [P, I]
            best_v = jnp.max(
                jnp.where(
                    rep_mask & (snap_b[None] == best_b[:, None, :]),
                    snap_v[None],
                    _NEG,
                ),
                axis=1,
            )
            take = (best_b != bal.NONE) & (best_b > ab)
            return jnp.where(take, best_b, ab), jnp.where(take, best_v, av)

        adopted_b, adopted_v = jax.lax.cond(
            any_reply, _adopt, lambda ab, av: (ab, av),
            pr.adopted_b, pr.adopted_v,
        )

        # Phase-1 quorum -> PREPARED; build the accept batch skeleton
        # (adopted values + noop hole fills + own initial proposals;
        # new values are assigned in the shared step below).
        n_prom = jnp.sum(promises2, axis=1)
        now_prepared = (
            (pr.mode == PREPARING) & (n_prom >= quorum) & prop_alive
        )
        # Batch assembly is several [P, I] passes plus a [P, A, I]
        # clear, and a proposer reaches phase-1 quorum only a handful
        # of times per run — the whole skeleton is cond-gated (global
        # predicate: the gmax inside must branch identically on every
        # shard).
        any_p1 = rany(now_prepared)

        def _build_batches(cur_batch, acks):
            committed_p = learned[pn] != val.NONE  # [P, I]
            use_adopt = ~committed_p & (adopted_b != bal.NONE)
            covered0 = committed_p | use_adopt
            # Hole-fill frontier: local while this shard still has
            # values to place (their space below the global frontier is
            # capacity, not holes); extended to the global frontier
            # only once EVERY proposer's queue on this shard is drained
            # — the shard's instance space is shared, so one drained
            # proposer must not noop-fill space another proposer's
            # queued values need, and all-drained also implies no
            # future conflict requeue can ever re-open a queue here
            # (conflicts need a live own_assign).  Then each shard's
            # region closes with no-ops and global contiguity (the
            # apply frontier, quiescence) is reached.  Unsharded: gmax
            # is identity — hi is the usual frontier.
            hi_loc = jnp.max(jnp.where(covered0, idx[None], -1), axis=1)
            # crashed proposers are excused (their queues are dead,
            # exactly as q_empty excuses them) or the shard could
            # never close.  PAUSED proposers are NOT excused — their
            # queued values are owed after the heal, so the frontier
            # must not no-op past space they still need.
            drained = (
                (pr.head >= pr.tail)
                & jnp.all(pr.own_assign == val.NONE, axis=1)
            ) | st.crashed[pn]  # [P] this shard's queue fully placed
            hi = jnp.where(jnp.all(drained), gmax(hi_loc), hi_loc)
            below = idx[None] <= hi[:, None]
            noop_fill = below & ~covered0
            own_has = pr.own_assign != val.NONE
            use_own = ~below & own_has
            batch0 = jnp.where(
                use_adopt,
                adopted_v,
                jnp.where(
                    noop_fill,
                    val.noop_vid(idx[None], pn[:, None], i_cap),
                    jnp.where(use_own, pr.own_assign, val.NONE),
                ),
            )
            batch0 = jnp.where(committed_p, val.NONE, batch0)
            return (
                jnp.where(now_prepared[:, None], batch0, cur_batch),
                jnp.where(now_prepared[:, None, None], jnp.int8(0), acks),
            )

        cur_batch, acks = jax.lax.cond(
            any_p1, _build_batches, lambda cb, ak: (cb, ak),
            pr.cur_batch, pr.acks,
        )
        mode = jnp.where(now_prepared, PREPARED, pr.mode)
        acc_retries = jnp.where(
            now_prepared, pk.accept_retry_count, pr.acc_retries
        )
        acc_deadline = jnp.where(
            now_prepared, t + 1 + pk.accept_retry_timeout, pr.acc_deadline
        )

        # New-value assignment for every PREPARED proposer: gate-ready
        # queue entries (first-fit) onto the lowest free instances in
        # the open tail (ref unproposed_instance_ids_.Next).
        can_assign = (mode == PREPARED) & prop_alive
        w = cfg.assign_window
        # The whole assignment — gate bitmap, [P, I] frontier scan,
        # rank scatter, queue write-back — runs only on rounds where a
        # PREPARED proposer actually has a live window entry.  The
        # predicate reads just the O(W) window view (gate satisfaction
        # is evaluated inside the branch: a gate-blocked window pays
        # the branch while it waits, an empty queue pays nothing).
        qvid, live = _assignable_window(
            pr.pend, pr.gate, pr.head, pr.tail, None, w
        )
        any_window = gany(jnp.any(live & can_assign[:, None]))

        def _assign(cur_batch, own_assign, pend, head):
            if vid_cap:
                # chosen-vid membership bitmap for the gate test (only
                # True scatters; invalid indices routed out of range)
                chosen_mask = jnp.zeros((vid_cap,), jnp.bool_).at[
                    jnp.where(
                        st.met.chosen_vid >= 0, st.met.chosen_vid, vid_cap
                    )
                ].set(True, mode="drop")
                wread, _ = _window_ops(w)
                g = jax.vmap(wread)(pr.gate, head)  # [P, W]
                ok = live & _gate_satisfied(g, chosen_mask)
            else:
                ok = live  # gate-free run: no gate logic at all
            activity = (
                (learned[pn] != val.NONE)
                | (cur_batch != val.NONE)
                | (own_assign != val.NONE)
            )
            # Assignment frontier is shard-LOCAL: each shard first-fits
            # its own queue onto its own lowest free instances
            # (placement differs from the unsharded engine; safety and
            # the chosen multiset do not — see parallel/sharded_sim.py).
            # Free instances are by construction the CONTIGUOUS suffix
            # (hi2, end-of-shard), so free ranks are closed-form
            # arithmetic and placement is a dynamic slice — no [P, I]
            # cumsum and no 1M-element gather (which cost ~40% of the
            # round's wall time).
            hi2 = jnp.max(jnp.where(activity, idx[None], -1), axis=1)
            hi2l = jnp.maximum(hi2, off - 1)  # clamp sentinel into shard
            free = idx[None] > hi2l[:, None]  # [P, I] contiguous suffix
            free_rank = idx[None] - hi2l[:, None] - 1  # [P, I]
            n_free = (off + i_loc - 1) - hi2l  # [P]
            ok_rank = jnp.cumsum(ok.astype(jnp.int32), axis=1) - 1
            k = jnp.minimum(jnp.sum(ok, axis=1), n_free)
            k = jnp.where(can_assign, k, 0)
            take_q = ok & (ok_rank < k[:, None])  # queue entries consumed
            prow = jnp.arange(p)[:, None]
            takev = free & (free_rank < k[:, None])  # instances filled
            start = jnp.clip(hi2l + 1 - off, 0, i_loc)
            # Rounds with a live-but-unassignable window (gated, or no
            # free instances) still skip the rank scatter; global
            # predicate as above.
            any_assign = gany(jnp.any(k > 0))

            def _compute_newv(qvid_, take_q_, start_):
                # vid of the r-th taken entry by rank.  In the common
                # case (ungated queues, fully-drained windows) the
                # taken entries are a contiguous PREFIX of the window,
                # so rank r is position r and the ranking is a pure
                # elementwise select.  Otherwise: an O(W) rank scatter
                # (taken entries have distinct ranks; untaken slots
                # are routed out of range and dropped — an equality
                # one-hot would cost O(W^2) and cap the window size).
                # The scatter serializes on TPU (~10 ms at W = 1M),
                # which is why the prefix fast path is worth a cond.
                offs_w = jnp.arange(w, dtype=jnp.int32)[None]
                is_prefix = gall(jnp.all(take_q_ == (offs_w < k[:, None])))

                def _by_rank_prefix(qvid_, take_q_):
                    return jnp.where(take_q_, qvid_, val.NONE)

                def _by_rank_scatter(qvid_, take_q_):
                    rank_pos = jnp.where(take_q_, ok_rank, w)  # [P, W]
                    return jnp.full((p, w), val.NONE, jnp.int32).at[
                        prow, rank_pos
                    ].set(qvid_, mode="drop")

                by_rank = jax.lax.cond(
                    is_prefix, _by_rank_prefix, _by_rank_scatter,
                    qvid_, take_q_,
                )

                # place the ranked vids at the contiguous free window:
                # a padded dynamic-slice write (start is always in
                # [0, i_loc], so nothing clamps or shifts), truncated
                # back to shard size
                def _place(br, h):
                    buf = jnp.full((i_loc + w,), val.NONE, jnp.int32)
                    return jax.lax.dynamic_update_slice(buf, br, (h,))[
                        :i_loc
                    ]

                return jax.vmap(_place)(by_rank, start_)

            newv = jax.lax.cond(
                any_assign,
                _compute_newv,
                lambda *_: jnp.full((p, i_loc), val.NONE, jnp.int32),
                qvid, take_q, start,
            )  # [P, I]
            cur_batch = jnp.where(takev, newv, cur_batch)
            own_assign = jnp.where(takev, newv, own_assign)
            # consume taken entries in place: the window is contiguous
            # from head, so this is a masked window write-back, not a
            # scatter (positions beyond tail hold NONE in qvid and
            # rewrite NONE); then advance head over the leading
            # consumed run
            new_win = jnp.where(take_q, val.NONE, qvid)  # [P, W]
            _, wwrite = _window_ops(w)
            pend = jax.vmap(wwrite)(pend, new_win, head)
            lead_dead = (
                (head[:, None] + jnp.arange(w)[None]) < pr.tail[:, None]
            ) & (new_win == val.NONE)
            head = head + jnp.sum(
                jnp.cumprod(lead_dead.astype(jnp.int32), axis=1), axis=1
            )
            return cur_batch, own_assign, pend, head, k

        cur_batch, own_assign, pend, head, k = jax.lax.cond(
            any_window,
            _assign,
            lambda cb, oa, pe, hd: (
                cb, oa, pe, hd, jnp.zeros((p,), jnp.int32),
            ),
            cur_batch, pr.own_assign, pr.pend, pr.head,
        )
        added = gany(k > 0)  # any shard assigned -> (re)send accepts

        # ACCEPT_REPLY arrivals: per-instance acks for current ballot,
        # derived at delivery: the acceptor currently holds this
        # batch's value at this ballot (so it certifiably stored
        # (ballot, v)), or committed exactly this value.  Acks lost to
        # higher-ballot overwrites in between are reply drops — legal.
        aecho = jnp.where(rx_p, ar.acc_echo, bal.NONE)  # [A, P]
        amatch = (aecho == pr.ballot[None, :]) & (mode[None, :] == PREPARED)
        # Ack accumulation and chosen-detection only on rounds a reply
        # actually arrives: acks (hence n_ack, hence a new decision)
        # can only grow here, so skipping the block on reply-free
        # rounds is exact.  Global predicate as above.
        any_echo = rany(amatch)

        def _accum_acks(acks, commit_vid, mvid, mround, mballot):
            if use_pallas:
                acks, n_ack = _sk.accum_acks(
                    acks, cur_batch, acc.acc_ballot, acc.acc_vid,
                    learned, pr.ballot, amatch.T,
                )
            else:
                hold = (acc.acc_vid[None] == cur_batch[:, None, :]) & (
                    acc.acc_ballot[None] == pr.ballot[:, None, None]
                )  # [P, A, I]
                comm = (learned[None] == cur_batch[:, None, :]) & (
                    learned[None] != val.NONE
                )
                acks = acks | (
                    amatch.T[:, :, None]
                    & (cur_batch != val.NONE)[:, None, :]
                    & (hold | comm)
                ).astype(jnp.int8)
                n_ack = jnp.sum(acks, axis=1, dtype=jnp.int32)  # [P, I]
            inst_chosen = (cur_batch != val.NONE) & (n_ack >= quorum)
            newly = (
                inst_chosen & (commit_vid == val.NONE) & prop_alive[:, None]
            )
            if _wedge_no_takeover:
                # seeded-wedge build: a survivor re-accepting an
                # ALREADY-chosen instance does not re-commit it (the
                # pre-PR-1-fix behavior the commit takeover exists to
                # repair) — with the takeover also compiled out, a
                # committer crashing while a receiver is paused
                # starves the paused node's learner
                newly = newly & (mvid == val.NONE)[None]
            commit_vid = jnp.where(newly, cur_batch, commit_vid)

            # Decision metrics (the decision log's source of truth).
            any_new = jnp.any(newly, axis=0) & (mvid == val.NONE)
            new_v = jnp.max(jnp.where(newly, cur_batch, _NEG), axis=0)
            new_b = jnp.max(jnp.where(newly, pr.ballot[:, None], _NEG), axis=0)
            return (
                acks,
                commit_vid,
                jnp.where(any_new, new_v, mvid),
                jnp.where(any_new, t, mround),
                jnp.where(any_new, new_b, mballot),
                newly,
            )

        acks, commit_vid, mvid, mround, mballot, newly = jax.lax.cond(
            any_echo,
            _accum_acks,
            lambda ak, cv, v, r, b: (
                ak, cv, v, r, b, jnp.zeros((p, i_loc), jnp.bool_),
            ),
            acks, pr.commit_vid, st.met.chosen_vid, st.met.chosen_round,
            st.met.chosen_ballot,
        )
        met = st.met._replace(
            chosen_vid=mvid, chosen_round=mround, chosen_ballot=mballot
        )
        if telemetry:
            # Latency-ledger admission: the first round each instance
            # carried a value in an accept batch, captured BEFORE the
            # mode-ladder clears below — this is the batch the ack
            # accumulation above judged, so admission always precedes
            # (or equals) the instance's decision round.
            _adm_any = jnp.any(cur_batch != val.NONE, axis=0)  # [I]

        # COMMIT sends: newly chosen + deadline resends of batches not
        # yet acked by every live node (ref :1625-1641 retries until
        # ALL nodes replied; crashed nodes are excused).
        # COMMIT_REPLY delivery: a presence bit; the per-instance ack
        # derives from learned-state match (learned is write-once, so
        # this is exact — the replier has learned the value iff its
        # learned cell equals the committed vid).
        crep = ar.com_rep & rx_p  # [A, P]
        any_crep = rany(crep)

        def _accum_commit_acks(commit_acked):
            ca = commit_acked | (
                crep.T[:, :, None]
                & (commit_vid != val.NONE)[:, None, :]
                & (learned[None] == commit_vid[:, None, :])
            )
            # Refresh the cached not-fully-acked flag from the cube —
            # the only [P, A, I] pass left on the commit path, paid
            # only when a reply arrives (or every round under crash
            # faults, where excusal can clear it without any arrival).
            excused = (
                st.crashed if node_mask is None
                else st.crashed | ~node_mask
            )
            wait = gany(jnp.any(
                (commit_vid != val.NONE)
                & ~jnp.all(ca | excused[None, :, None], axis=1),
                axis=1,
            ))  # [P]
            return ca, wait

        if crash_faults:
            # Runtime knobs may carry a nonzero crash rate (and a
            # schedule may carry crash points), so the cached flag
            # refreshes every round (exact at crash rate 0: without
            # crashes the excusal never clears without an arrival, so
            # the cond-gated path below computes the same values).
            commit_acked, commit_wait = _accum_commit_acks(pr.commit_acked)
        else:
            commit_acked, commit_wait = jax.lax.cond(
                any_crep,
                _accum_commit_acks,
                lambda ca: (ca, pr.commit_wait),
                pr.commit_acked,
            )
        # Commit TAKEOVER: the commit-until-all-acked obligation
        # (ref :1625-1641) must not die with its proposer.  If the
        # committer crashes (or pauses through its ladder) after a
        # quorum chose a value but before every live node learned it,
        # no hole remains — every survivor sees the instance as
        # committed, builds an EMPTY batch, and the undelivered
        # learners starve (the exact wedge: a node paused through the
        # commit window whose committer then crashed).  So a proposer
        # whose idle-liveness patience runs out (same stall threshold
        # that triggers its re-prepare below) adopts commit_vid :=
        # its own learned values wherever it holds no commitment yet
        # — re-committing a learned (hence chosen, write-once) value
        # is always safe — and the ordinary resend ladder delivers to
        # the lagging nodes.  Fires only on stall-threshold rounds, so
        # the [P, I] pass is cond-gated off the common path.  (The
        # membership engine needs no analog: its learners anti-entropy
        # PULL their gaps each round.)
        take_commit = (
            (pr.mode == PREPARED)
            & (pr.stall >= pk.stall_patience)
            & prop_alive
        )
        if _wedge_no_takeover:
            # seeded-wedge build (seeded_wedge() == "takeover"): the
            # takeover never fires — the pre-PR-1-fix engine, compiled
            # in only for checker-recall pins
            take_commit = jnp.zeros_like(take_commit)
        any_take = rany(take_commit)

        def _takeover(commit_vid, commit_wait):
            taken = (
                take_commit[:, None]
                & (learned[pn] != val.NONE)
                & (commit_vid == val.NONE)
            )
            took = gany(jnp.any(taken, axis=1))  # [P]
            return (
                jnp.where(taken, learned[pn], commit_vid),
                commit_wait | took,
            )

        commit_vid, commit_wait = jax.lax.cond(
            any_take,
            _takeover,
            lambda cv, cw: (cv, cw),
            commit_vid, commit_wait,
        )
        # A fresh decision is by construction not fully acked yet.
        any_newly = gany(jnp.any(newly, axis=1))  # [P]
        commit_wait = commit_wait | any_newly
        resend_c = (t >= pr.commit_deadline) & commit_wait  # [P]
        send_commit = (any_newly | resend_c | (take_commit & commit_wait)) & prop_alive
        commit_deadline = jnp.where(
            send_commit, t + 1 + pk.commit_retry_timeout, pr.commit_deadline
        )

        # Conflict re-proposal + own-value completion
        # (ref OnCommit, multi/paxos.cpp:1540-1569).
        learned_p = learned[pn]  # [P, I] post-commit view
        own_has2 = own_assign != val.NONE
        conflict = own_has2 & (learned_p != val.NONE) & (learned_p != own_assign)
        own_done = own_has2 & (learned_p == own_assign)
        # Completed own-values clear under their own gate (disjoint
        # from conflicts, so ordering vs the requeue is immaterial);
        # rounds with neither pay no [P, I] write at all.
        any_own_done = gany(jnp.any(own_done))
        own_assign = jax.lax.cond(
            any_own_done,
            lambda oa: jnp.where(own_done, val.NONE, oa),
            lambda oa: oa,
            own_assign,
        )
        # Requeue at most assign_window conflicts per round, in
        # instance order; the remainder keep their own_assign entry and
        # are re-detected next round (drain rate >= the assignment
        # rate, so the cap never throttles below the proposer's own
        # placement throughput).  The conflicted vids are compacted by
        # a pair sort and appended with ONE contiguous block write at
        # the tail — replacing a [P, I]-indexed ring scatter that
        # serialized on TPU (~40% of round wall time at I >= 1M).
        r_cap = min(cfg.assign_window, i_loc)
        # Most rounds have no conflicts at all, so the whole requeue —
        # the rank cumsum, the compaction sort, and the tail append —
        # runs under a cond.  The predicate MUST stay global (gany):
        # every shard has to take the same branch, because a
        # collective (the narrow/full sort-width vote below) now lives
        # inside the taken branch.
        any_conflict = gany(jnp.any(conflict))

        # Compaction-sort width: conflicts cluster around the frontier
        # (both duelists assign the same lowest-free window), so when
        # every proposer's conflict spread fits a 2*r_cap window the
        # sort runs at that width; sparse spreads (crash leftovers,
        # capped carry-overs drifting from a new wave) fall back to
        # the full instance width.  Both branches produce the same
        # first-r_cap-by-instance-order prefix.
        span = min(2 * r_cap, i_loc)

        def _do_requeue(pend, own_assign, ptail):
            idxb = jnp.broadcast_to(idx[None], conflict.shape)
            has_c = jnp.any(conflict, axis=1)  # [P]
            ncf = jnp.sum(conflict.astype(jnp.int32), axis=1)  # [P]
            cmin = jnp.min(
                jnp.where(conflict, idxb, jnp.iinfo(jnp.int32).max), axis=1
            )
            cmax = jnp.max(jnp.where(conflict, idxb, -1), axis=1)
            nreq = jnp.minimum(ncf, r_cap)  # [P]
            # In a duel the conflicted instances form a FULLY-conflicted
            # contiguous run (the winner's batch commits as a block over
            # the loser's contiguous first-fit assignment), so the
            # first-r_cap-by-instance-order prefix is a padded dynamic
            # slice at cmin and the taken set is a range test — no sort,
            # no cumsum.  Sparse sprays (crash leftovers, capped
            # carry-overs colliding with a new wave) take the sort path.
            contig = gall(jnp.all(~has_c | (ncf == cmax - cmin + 1)))

            def _take_contig(own_assign):
                startc = jnp.where(has_c, cmin - off, 0)
                rowpad = jnp.concatenate(
                    [own_assign, jnp.full((p, r_cap), val.NONE, jnp.int32)],
                    axis=1,
                )

                def _sl(row, h):
                    return jax.lax.dynamic_slice(row, (h,), (r_cap,))

                block = jax.vmap(_sl)(rowpad, startc)
                take_req = conflict & (idxb < (cmin + nreq)[:, None])
                return block, take_req

            def _take_sorted(own_assign):
                req_rank = jnp.cumsum(conflict.astype(jnp.int32), axis=1) - 1
                take_req = conflict & (req_rank < r_cap)
                # Compaction-sort width: conflicts cluster around the
                # frontier, so when every proposer's spread fits a
                # 2*r_cap window the sort runs at that width; wider
                # spreads fall back to the full instance width.  Both
                # branches produce the same first-r_cap prefix.
                fits = jnp.all(~has_c | (cmax - cmin < span))
                narrow = gall(fits)

                # unstable sorts throughout: conflict keys are unique
                # (global ids / window offsets) and the sentinel-keyed
                # remainder is discarded (a stable sort would pay for a
                # third, hidden iota operand)
                def _sort_narrow(own_assign):
                    start = jnp.clip(
                        jnp.where(has_c, cmin - off, 0), 0, i_loc - span
                    )

                    def _slice(row, h):
                        return jax.lax.dynamic_slice(row, (h,), (span,))

                    win_conf = jax.vmap(_slice)(conflict, start)
                    win_vids = jax.vmap(_slice)(own_assign, start)
                    keys = jnp.where(
                        win_conf,
                        jnp.broadcast_to(
                            jnp.arange(span, dtype=jnp.int32)[None],
                            win_conf.shape,
                        ),
                        jnp.int32(span),
                    )
                    _, sv = jax.lax.sort(
                        (keys, win_vids), dimension=1, num_keys=1,
                        is_stable=False,
                    )
                    return sv[:, :r_cap]

                def _sort_full(own_assign):
                    sort_keys = jnp.where(conflict, idxb, jnp.int32(i_cap))
                    _, sv = jax.lax.sort(
                        (sort_keys, own_assign), dimension=1, num_keys=1,
                        is_stable=False,
                    )
                    return sv[:, :r_cap]

                block = jax.lax.cond(
                    narrow, _sort_narrow, _sort_full, own_assign
                )
                return block, take_req

            block, take_req = jax.lax.cond(
                contig, _take_contig, _take_sorted, own_assign
            )
            req_block = jnp.where(
                jnp.arange(r_cap)[None] < nreq[:, None],
                block,
                val.NONE,
            )  # [P, R]
            # Slots >= tail are NONE by construction (tail is
            # monotone; nothing ever writes past it), so block
            # positions beyond nreq overwrite NONE with NONE
            # (capacity proof: tail + nreq <= c, see prepare_queues).
            _, wwrite_r = _window_ops(r_cap)
            pend = jax.vmap(wwrite_r)(pend, req_block, ptail)
            own2 = jnp.where(take_req, val.NONE, own_assign)
            return pend, nreq, own2

        pend, nreq, own_assign = jax.lax.cond(
            any_conflict,
            _do_requeue,
            lambda pend, own_assign, ptail: (
                pend,
                jnp.zeros((p,), jnp.int32),
                own_assign,
            ),
            pend, own_assign, pr.tail,
        )
        # gate slots >= tail are NONE from init (requeues are ungated
        # by construction), so no gate write is needed.
        gate = pr.gate
        tail = pr.tail + nreq

        # ---------------- timers / mode ladder ----------------
        # PREPARING deadline: resend (count-1 times) then restart with
        # a bumped ballot (ref PrepareRetryTimeout, :757-790).
        pdl = (mode == PREPARING) & (t >= pr.prep_deadline) & prop_alive
        resend_prep = pdl & (pr.prep_retries > 1)
        restart_p = pdl & (pr.prep_retries <= 1)
        prep_retries = jnp.where(resend_prep, pr.prep_retries - 1, pr.prep_retries)
        prep_deadline = jnp.where(
            resend_prep, t + 1 + pk.prepare_retry_timeout, pr.prep_deadline
        )

        # Accept deadline: resend outstanding then AcceptRejected ->
        # back to prepare (ref AcceptRetryTimeout, :955-983, 1328-1343).
        # The [P, I] outstanding scan only runs on rounds a deadline
        # actually fires (global predicate, cheap [P] inputs).
        ddl_hit = (mode == PREPARED) & (t >= acc_deadline) & prop_alive

        def _outstanding_any():
            outstanding = (
                (cur_batch != val.NONE)
                & (commit_vid == val.NONE)
                & (learned[pn] == val.NONE)  # == ~committed_p
            )
            return gany(jnp.any(outstanding, axis=1))

        adl = ddl_hit & jax.lax.cond(
            rany(ddl_hit),
            _outstanding_any,
            lambda: jnp.zeros((p,), jnp.bool_),
        )
        resend_acc = adl & (acc_retries > 1)
        acc_fail = adl & (acc_retries <= 1)
        acc_retries = jnp.where(resend_acc, acc_retries - 1, acc_retries)

        # Idle-liveness restart: the stall counter (updated at the end
        # of the previous round) has run out of patience.
        idle_restart = (
            (mode == PREPARED)
            & (pr.stall >= pk.stall_patience)
            & prop_alive
        )

        do_restart = restart_p | acc_fail | idle_restart
        _kd = prng.stream(root, prng.STREAM_PREPARE_DELAY, t + 1)
        if geom is None:
            rnd_delay = jax.random.randint(
                _kd,
                (p,),
                pk.prepare_delay_min,
                pk.prepare_delay_max + 1,
                dtype=jnp.int32,
            )
        else:
            # menu-switched backoff draw (pad slots 0: a pad
            # proposer's delay_until is never consulted — it can
            # never restart)
            rnd_delay = geo.menu_randint(
                geometry, geom.geom_idx, _kd, "proposers",
                pk.prepare_delay_min, pk.prepare_delay_max + 1,
                pad_value=0,
            )
        delay_until = jnp.where(do_restart, t + 1 + rnd_delay, pr.delay_until)
        mode = jnp.where(do_restart, DELAY, mode)
        promises2 = jnp.where(do_restart[:, None], False, promises2)

        # DELAY -> send prepare with a ballot bumped past everything
        # seen (ref UpdateProposalID, :792-799).  A restarting proposer
        # can never also start_prep this round (its delay_until is in
        # the future), so the two clear masks are disjoint and the
        # combined array-clear cond below is order-independent.
        start_prep = (mode == DELAY) & (t >= delay_until) & prop_alive
        ncount, nballot = bal.bump_past(
            pr.count, pn, jnp.maximum(pmax_seen, pr.ballot)
        )
        count = jnp.where(start_prep, ncount, pr.count)
        ballot = jnp.where(start_prep, nballot, pr.ballot)
        mode = jnp.where(start_prep, PREPARING, mode)
        prep_retries = jnp.where(start_prep, pk.prepare_retry_count, prep_retries)
        prep_deadline = jnp.where(
            start_prep, t + 1 + pk.prepare_retry_timeout, prep_deadline
        )
        promises2 = jnp.where(start_prep[:, None], False, promises2)

        # The big-array clears (adopted state, batch, ack cube) gate
        # together on any mode transition this round; quiet rounds
        # write none of them.
        any_reset = rany(do_restart | start_prep)

        def _clear_arrays(ab, av, cb, ak):
            both = (do_restart | start_prep)[:, None]
            ab = jnp.where(both, bal.NONE, ab)
            av = jnp.where(both, val.NONE, av)
            cb = jnp.where(do_restart[:, None], val.NONE, cb)
            ak = jnp.where(do_restart[:, None, None], jnp.int8(0), ak)
            return ab, av, cb, ak

        adopted_b, adopted_v, cur_batch, acks = jax.lax.cond(
            any_reset,
            _clear_arrays,
            lambda ab, av, cb, ak: (ab, av, cb, ak),
            adopted_b, adopted_v, cur_batch, acks,
        )

        send_prep = start_prep | resend_prep
        # gany: the network calendars are replicated, so the send
        # predicate must agree across shards even when only some
        # shards' batches have content.  The [P, I] batch-content scan
        # runs only when something wants to send at all.
        want_acc_send = now_prepared | added | resend_acc
        send_accept = want_acc_send & jax.lax.cond(
            rany(want_acc_send),
            lambda: gany(jnp.any(cur_batch != val.NONE, axis=1)),
            lambda: jnp.zeros((p,), jnp.bool_),
        )

        # ---------------- network writes ----------------
        # Every send mask passes through the schedule's reachability
        # cut (_cut_pa/_cut_ap); burst windows ride copy_plan's
        # extra_drop (_plan).  Message counters below stay pre-fault.
        # With telemetry armed, each site's (copy plan, post-cut mask)
        # pair also feeds the recorder's fault-layer counters
        # (_tsites) — reading values already computed, never sampling.
        edge_pa = (p, a)
        # broadcast fan-out: the bound's full node set, restricted to
        # the TRUE nodes under a geometry (decision-neutral — pad
        # destinations never read their arrivals — but load-bearing
        # for the telemetry offered counters and msgs parity)
        bcast_a = (
            jnp.ones((p, a), jnp.bool_) if node_mask is None
            else jnp.broadcast_to(node_mask[None, :], (p, a))
        )
        # [(alive, delay, post-cut mask, pre-cut mask, is_pa)] in MSG
        # order: the pre-cut mask exists so the recorder can count
        # copies lost at SEVERED edges (pre & ~post) — offered stays
        # post-cut for drop-rate exactness, so partitions would
        # otherwise be invisible in the fault-layer counters.
        _tsites = []
        # prepare requests
        al, dl = _plan(keys[0], edge_pa, True)
        pre_prep = send_prep[:, None] & bcast_a
        m_prep = _cut_pa(pre_prep)
        _tsites.append((al, dl, m_prep, pre_prep, True))
        net = net._replace(
            prep_req=netm.write_ballot(
                net.prep_req, t, al, dl, ballot[:, None], m_prep
            )
        )
        # prepare replies (granted only; snapshot read at delivery)
        al, dl = _plan(keys[1], (a, p), False)
        send_rep = grant.T  # [A, P]
        echo_val = preq.T  # [A, P] the granted ballot
        m_rep = _cut_ap(send_rep)
        _tsites.append((al, dl, m_rep, send_rep, False))
        net = net._replace(
            prep_echo=netm.write_ballot(
                net.prep_echo, t, al, dl, echo_val, m_rep
            )
        )
        # rejects (both phases share one message, ref MSG_REJECT)
        al, dl = _plan(keys[2], (a, p), False)
        send_rej = (rej_prep | rej_acc).T
        m_rej = _cut_ap(send_rej)
        _tsites.append((al, dl, m_rej, send_rej, False))
        net = net._replace(
            rej=netm.write_ballot(
                net.rej, t, al, dl,
                jnp.broadcast_to(max_seen[:, None], (a, p)),
                m_rej,
            )
        )
        # accepts: per-edge ballot (batch content read at delivery)
        al, dl = _plan(keys[3], edge_pa, True)
        pre_acc = send_accept[:, None] & bcast_a
        m_acc = _cut_pa(pre_acc)
        _tsites.append((al, dl, m_acc, pre_acc, True))
        net = net._replace(
            acc_req=netm.write_ballot(
                net.acc_req, t, al, dl, ballot[:, None], m_acc
            )
        )
        # accept replies (ack rows derived at delivery)
        al, dl = _plan(keys[4], (a, p), False)
        send_arep = elig.T  # [A, P] reply whenever ballot >= promised
        aecho_val = jnp.broadcast_to(abal[None, :], (a, p))
        m_arep = _cut_ap(send_arep)
        _tsites.append((al, dl, m_arep, send_arep, False))
        net = net._replace(
            acc_echo=netm.write_ballot(
                net.acc_echo, t, al, dl, aecho_val, m_arep
            )
        )
        # commits: per-edge presence (content read at delivery from
        # the sender's write-once commit_vid)
        al, dl = _plan(keys[5], edge_pa, True)
        pre_com = send_commit[:, None] & bcast_a
        m_com = _cut_pa(pre_com)
        _tsites.append((al, dl, m_com, pre_com, True))
        net = net._replace(
            com_pres=netm.write_flag(net.com_pres, t, al, dl, m_com)
        )
        # commit replies: presence; ack-by-learned-match at delivery
        al, dl = _plan(keys[6], (a, p), False)
        send_crep = cpres.T  # [A, P]
        m_crep = _cut_ap(send_crep)
        _tsites.append((al, dl, m_crep, send_crep, False))
        net = net._replace(
            com_rep=netm.write_flag(net.com_rep, t, al, dl, m_crep)
        )

        # message counters (logical sends, pre-fault); broadcast
        # fan-out counts the TRUE node set under a geometry
        na = a if geom is None else geom.n_true
        msgs = met.msgs + jnp.stack(
            [
                jnp.sum(send_prep) * na,
                jnp.sum(send_rep),
                jnp.sum(send_rej),
                jnp.sum(send_accept) * na,
                jnp.sum(send_arep),
                jnp.sum(send_commit) * na,
                jnp.sum(send_crep),
            ]
        ).astype(jnp.int32)
        met = met._replace(msgs=msgs)

        # ---------------- crash injection ----------------
        crashed = st.crashed
        if crash_t is not None:
            # Scheduled crash points (deterministic fail-stops) apply
            # before the i.i.d. draw, so the minority-cap `room` below
            # accounts for them; like the i.i.d. injection they take
            # effect at the end of the round (first silent round is
            # t0 + 1).  The schedule author owns the minority cap for
            # scheduled crashes — the model checker's scope
            # enumeration never exceeds it.
            crashed = crashed | crash_t
        if runtime_knobs or fc.crash_rate:
            # Always-on under runtime knobs: the draw consumes only
            # its own stream key, and a zero traced rate makes `want`
            # all-false — identical to the elided static branch.
            ku = prng.stream(root, prng.STREAM_CRASH, t)
            if geom is None:
                u = jax.random.randint(ku, (a,), 0, 1_000_000)
            else:
                # menu-switched crash coins; pad nodes draw the 1e6
                # sentinel (never < any rate) so they can neither
                # crash nor consume minority-cap room
                u = geo.menu_randint(
                    geometry, geom.geom_idx, ku, "nodes", 0, 1_000_000,
                    pad_value=1_000_000,
                )
            c_rate = (
                jnp.asarray(knobs.crash_rate, jnp.int32)
                if runtime_knobs else fc.crash_rate
            )
            want = (u < c_rate) & ~crashed
            room = max_crash - jnp.sum(crashed)
            allow = jnp.cumsum(want.astype(jnp.int32)) <= room
            crashed = crashed | (want & allow)

        # ---------------- quiescence ----------------
        alive2 = ~crashed
        palive2 = alive2[pn]
        if prop_mask is not None:
            palive2 = palive2 & prop_mask
        # obligation excusal: crashed nodes — and, under a geometry,
        # nodes absent from the true cluster
        dead2 = crashed if node_mask is None else crashed | ~node_mask
        # Packed reductions: the naive formulation issues ~8 small
        # collectives here, two of them CHAINED (hole and learned
        # checks needed the global high-water mark first).  Counting
        # reformulation instead: chosen instances are distinct cells,
        # so contiguity is `global chosen count == hmax + 1`, and a
        # node has learned everything below the frontier iff its
        # global learned count matches (learned ⊆ chosen, so no
        # learned cell sits above hmax).  Everything folds into ONE
        # psum vector plus ONE pmax scalar, issued in parallel.
        # Unsharded, gsum/gmax are identity and the math is unchanged.
        # The counted inputs change only under an enumerable set of
        # events (learned: commit delivery; chosen/hmax: echo rounds;
        # cur_batch: phase-1 build / assignment / restart clears;
        # own_assign: assignment / completion / requeue; head/tail:
        # assignment / requeue) — on any other round the cached counts
        # from the previous round are exactly current, so quiet rounds
        # skip every count pass AND both collectives.  t == 0 forces
        # the first round to measure (tests seed custom arrays into
        # fresh states whose cached counts would be stale); crash
        # faults recompute every round (a crash excuses learners
        # without any arrival).
        q_change = (
            any_com_arr | any_echo | any_p1 | any_window | any_reset
            | any_own_done | any_conflict | (t == jnp.int32(0))
        )

        def _measure(_):
            inflight = (cur_batch != val.NONE) & (
                met.chosen_vid[None] == val.NONE
            )
            local = jnp.concatenate([
                jnp.sum(met.chosen_vid != val.NONE, dtype=jnp.int32)[None],
                jnp.sum(learned != val.NONE, axis=1, dtype=jnp.int32),  # [A]
                jnp.sum(inflight, axis=1, dtype=jnp.int32),  # [P]
                (head != tail).astype(jnp.int32),  # [P] per-shard queues
                jnp.sum(own_assign != val.NONE, axis=1, dtype=jnp.int32),
            ])
            return gsum(local), gmax(jnp.max(
                jnp.where(met.chosen_vid != val.NONE, idx, -1)
            ))

        if crash_faults:
            # Runtime knobs / crash schedules: measure every round (a
            # crash can excuse learners without any arrival; exact at
            # rate 0 — the cache is only ever skipped when provably
            # current).
            sums, hmax = _measure(None)
        else:
            sums, hmax = jax.lax.cond(
                q_change, _measure, lambda _: (st.qsums, st.qhmax), None
            )
        n_chosen = sums[0]
        n_learned = sums[1:1 + a]  # [A] global learned count per node
        inflight_n = sums[1 + a:1 + a + p]  # [P]
        q_pending = sums[1 + a + p:1 + a + 2 * p]  # [P] shards w/ queue
        own_n = sums[1 + a + 2 * p:1 + a + 3 * p]  # [P]
        q_empty = ~jnp.any(palive2 & (q_pending > 0))
        own_none = ~jnp.any(palive2 & (own_n > 0))
        contiguous = n_chosen == hmax + 1
        learned_ok = jnp.all((n_learned == hmax + 1) | dead2)
        done = q_empty & own_none & contiguous & learned_ok & (t > 0)
        if runtime_schedule:
            # Heal-then-converge with a TRACED horizon: the per-lane
            # table carries its own last-heal round; past it the
            # comparison is vacuous, so schedule-free lanes lose
            # nothing.
            done = done & (t >= jnp.asarray(tab.horizon, jnp.int32))
        elif horizon:
            # Heal-then-converge contract: quiescence is never declared
            # before the last episode ends — a paused node's catch-up
            # (and a partitioned minority's repair) is owed, not
            # waived, and the watchdog budget (round_budget) grants
            # max_rounds past this point to deliver it.
            done = done & (t >= jnp.int32(horizon))

        # Stall accounting for the idle-liveness restart: a proposer is
        # idle when PREPARED with nothing undecided in flight, an empty
        # queue and no own assignments outstanding; it accumulates
        # stall only while the log is unresolved (holes below the
        # chosen high-water mark, or chosen values some live node
        # never learned).
        unresolved = ~(contiguous & learned_ok)
        idle_now = (
            (mode == PREPARED)
            & (inflight_n == 0)
            & ~commit_wait  # commit repair in flight (cached [P] flag)
            & (q_pending == 0)
            & (own_n == 0)
            & palive2
        )
        stall = jnp.where(idle_now & unresolved & ~done, pr.stall + 1, 0)

        new_st = SimState(
            t=t + 1,
            acc=acc,
            learned=learned,
            prop=ProposerState(
                mode=mode,
                count=count,
                ballot=ballot,
                pmax_seen=pmax_seen,
                delay_until=delay_until,
                prep_deadline=prep_deadline,
                prep_retries=prep_retries,
                promises=promises2,
                adopted_b=adopted_b,
                adopted_v=adopted_v,
                cur_batch=cur_batch,
                acks=acks,
                acc_deadline=jnp.where(
                    resend_acc, t + 1 + pk.accept_retry_timeout, acc_deadline
                ),
                acc_retries=acc_retries,
                own_assign=own_assign,
                pend=pend,
                gate=gate,
                head=head,
                tail=tail,
                commit_vid=commit_vid,
                commit_acked=commit_acked,
                commit_deadline=commit_deadline,
                stall=stall,
                commit_wait=commit_wait,
            ),
            net=net,
            met=met,
            crashed=crashed,
            done=done,
            qsums=sums,
            qhmax=hmax,
        )
        if not telemetry:
            return new_st
        # ---------------- flight recorder (read-only) ----------------
        # Every field below reduces values the round already computed;
        # nothing here samples PRNG streams or writes back into the
        # state, so the armed engine stays decision-log-identical.
        if _ww:
            tele, wins = tele  # windowed builds carry the pair
        tc = [
            _rec.count_copies(al_, dl_, m_)
            for (al_, dl_, m_, _pre, _pa) in _tsites
        ]
        # Per-edge offered/dropped/cut/delay breakdown (the WAN
        # plane): the already-computed copy plans, pre-cut send masks,
        # and post-cut masks, summed per direction and scattered into
        # [A, A] round-increment matrices via the proposer->node map
        # (pn rows are distinct nodes, so the two scatters never
        # collide within themselves).  ``cut`` counts copies lost at
        # severed edges (pre & ~post — offered is post-cut by design,
        # so partitions need their own counter); ``dsum`` sums the
        # sampled delays of surviving copies (a gray node's inflation
        # signal, attributable per node below).
        aidx_t = jnp.arange(a)
        off_pa = drop_pa = cut_pa = dsum_pa = jnp.zeros((p, a), jnp.int32)
        off_ap = drop_ap = cut_ap = dsum_ap = jnp.zeros((a, p), jnp.int32)
        for (al_, dl_, m_, pre_, is_pa) in _tsites:
            offc = m_.astype(jnp.int32)
            drpc = (m_ & ~al_[0]).astype(jnp.int32)
            cutc = (pre_ & ~m_).astype(jnp.int32)
            dsc = jnp.sum(jnp.where(m_[None] & al_, dl_, 0), axis=0)
            if is_pa:
                off_pa = off_pa + offc
                drop_pa = drop_pa + drpc
                cut_pa = cut_pa + cutc
                dsum_pa = dsum_pa + dsc
            else:
                off_ap = off_ap + offc
                drop_ap = drop_ap + drpc
                cut_ap = cut_ap + cutc
                dsum_ap = dsum_ap + dsc

        def _edge_inc(m_pa, m_ap):
            return jnp.zeros((a, a), jnp.int32).at[
                pn[:, None], aidx_t[None, :]
            ].add(m_pa).at[aidx_t[:, None], pn[None, :]].add(m_ap)

        inc_off = _edge_inc(off_pa, off_ap)
        inc_drp = _edge_inc(drop_pa, drop_ap)
        inc_cut = _edge_inc(cut_pa, cut_ap)
        edge_off = tele.edge_offered + inc_off
        edge_drp = tele.edge_dropped + inc_drp
        edge_cut = tele.edge_cut + inc_cut
        cv_new = (commit_vid != val.NONE) & (pr.commit_vid == val.NONE)
        took = cv_new & ~newly  # [P, I] commit-takeover adoptions
        took_p = jnp.any(took, axis=1)  # [P]
        # Phase-ledger stamps (write-once, like admit_round): learned
        # when an Applied quorum (majority of nodes) holds the value;
        # committed when the commit-until-all-acked ladder completed —
        # some proposer's commitment acked by every non-crashed node.
        # Both read state the round already computed; the [P, A, I]
        # all-reduce is the armed build's cost, never the plain one's.
        learn_ok = (
            jnp.sum((learned != val.NONE).astype(jnp.int32), axis=0)
            >= quorum
        )  # [I]
        full_ack = jnp.any(
            (commit_vid != val.NONE)
            & jnp.all(commit_acked | dead2[None, :, None], axis=1),
            axis=0,
        )  # [I]
        new_tele = _rec.Telemetry(
            offered=tele.offered + jnp.stack([c[0] for c in tc]),
            dropped=tele.dropped + jnp.stack([c[1] for c in tc]),
            duped=tele.duped + jnp.stack([c[2] for c in tc]),
            delayed=tele.delayed + jnp.stack([c[3] for c in tc]),
            learns=tele.learns + jnp.sum(
                (learned != val.NONE) & (st.learned == val.NONE),
                dtype=jnp.int32,
            ),
            commit_acks=tele.commit_acks + jnp.sum(crep, dtype=jnp.int32),
            takeovers=tele.takeovers + jnp.sum(took, dtype=jnp.int32),
            requeues=tele.requeues + jnp.sum(nreq, dtype=jnp.int32),
            restarts=tele.restarts + jnp.sum(do_restart, dtype=jnp.int32),
            admit_round=jnp.where(
                (tele.admit_round == val.NONE) & _adm_any,
                t, tele.admit_round,
            ),
            learned_round=jnp.where(
                (tele.learned_round == val.NONE) & learn_ok,
                t, tele.learned_round,
            ),
            committed_round=jnp.where(
                (tele.committed_round == val.NONE) & full_ack,
                t, tele.committed_round,
            ),
            takeover_round=jnp.where(
                (tele.takeover_round == val.NONE) & took_p,
                t, tele.takeover_round,
            ),
            stall_max=jnp.maximum(tele.stall_max, jnp.max(stall)),
            edge_offered=edge_off,
            edge_dropped=edge_drp,
            edge_cut=edge_cut,
        )
        if not _ww:
            return new_st, new_tele
        # Windowed plane: the same already-computed values, bucketed
        # by the virtual round (decision-time series are derived at
        # the epilogue from chosen_round — no accumulation needed).
        # node_offered/node_delay charge each copy to BOTH endpoints
        # (inc matrices summed along each axis), so a gray node's
        # delay inflation shows on its row whichever direction the
        # traffic flows; backlog is the post-round queue depth summed
        # over proposers (tail - head counts not-yet-assigned values).
        wb = _rec.window_bucket(t, _ww)
        inc_delay = _edge_inc(dsum_pa, dsum_ap)
        new_wins = _rec.TelemetryWindows(
            offered=wins.offered.at[wb].add(
                sum(c[0] for c in tc)
            ),
            dropped=wins.dropped.at[wb].add(sum(c[1] for c in tc)),
            duped=wins.duped.at[wb].add(sum(c[2] for c in tc)),
            delayed=wins.delayed.at[wb].add(sum(c[3] for c in tc)),
            stall_max=wins.stall_max.at[wb].max(jnp.max(stall)),
            takeovers=wins.takeovers.at[wb].add(
                jnp.sum(took, dtype=jnp.int32)
            ),
            restarts=wins.restarts.at[wb].add(
                jnp.sum(do_restart, dtype=jnp.int32)
            ),
            cut=wins.cut.at[wb].add(jnp.sum(inc_cut, dtype=jnp.int32)),
            backlog_max=wins.backlog_max.at[wb].max(
                jnp.sum(tail - head, dtype=jnp.int32)
            ),
            node_offered=wins.node_offered.at[wb].add(
                inc_off.sum(axis=0) + inc_off.sum(axis=1)
            ),
            node_delay=wins.node_delay.at[wb].add(
                inc_delay.sum(axis=0) + inc_delay.sum(axis=1)
            ),
        )
        return new_st, (new_tele, new_wins)

    return round_fn


def admit_block(
    st: SimState, admit: jax.Array, keep: jax.Array | None = None
) -> SimState:
    """Open-loop admission: append one NONE-padded block of fresh vids
    per proposer at the queue tail (the serve harness's per-window
    upload; tpu_paxos/serve/driver.py runs this inside the donated
    dispatch window, between windows of rounds).

    ``keep`` is the admit-block PRIORITY MASK (``[P, K]`` bool, or
    None): the admission controller's shed path
    (tpu_paxos/serve/control.py) uploads shed values IN the block
    with ``keep=False`` so the device masks them to NONE before the
    append — the shed happens on device, countable there, and the
    block layout stays exactly the plan's.  ``keep=None`` (every
    caller but the controller) traces the identical program as before
    the mask existed — no branch, no extra ops.

    ``admit`` is ``[P, K]`` int32 with each row a value PREFIX padded
    by ``val.NONE``.  Slots at and past tail are invariantly NONE
    (nothing ever writes past tail), so the block's padding
    overwrites NONE with NONE and the ring invariants hold.  The
    write goes through a K-padded row (the ``_assign`` placement
    pattern) so the dynamic slice NEVER clamps, for any block width:
    a bare ``dynamic_update_slice`` would clamp its start when
    ``tail + K`` passes the row end — rewriting live entries below
    tail — and wide admission blocks (a bursty arrival plan's
    ``admit_width`` can exceed ``assign_window``) reach that corner
    when a queue nears capacity.  Real values never truncate at the
    pad boundary: ``tail + count <= c`` by the capacity proof in
    ``prepare_queues`` (total enqueues are bounded by the full
    planned stream + requeues), so only NONE padding ever spills.
    Gates are untouched (serve traffic is ungated; gate rows stay
    all-NONE), and admission happens BETWEEN dispatch windows, so it
    never races the in-round conflict requeue that also appends at
    tail."""
    if keep is not None:
        # shed-mask path: kept values must stay a NONE-padded PREFIX
        # (a masked hole mid-row would put NONE below the new tail —
        # a dead slot inside the live ring), so a stable argsort
        # compacts survivors to the front in plan order
        kept = keep & (admit != val.NONE)
        order = jnp.argsort(jnp.logical_not(kept), axis=1, stable=True)
        admit = jnp.where(
            jnp.take_along_axis(kept, order, axis=1),
            jnp.take_along_axis(admit, order, axis=1),
            val.NONE,
        )
    pr = st.prop
    k = admit.shape[1]
    width = pr.pend.shape[-1]

    def _append(row, blk, h):
        buf = jnp.concatenate([row, jnp.full((k,), val.NONE, jnp.int32)])
        return jax.lax.dynamic_update_slice(buf, blk, (h,))[:width]

    pend = jax.vmap(_append)(pr.pend, admit, pr.tail)
    counts = jnp.sum((admit != val.NONE).astype(jnp.int32), axis=1)
    return st._replace(prop=pr._replace(pend=pend, tail=pr.tail + counts))


def default_workload(cfg: SimConfig) -> list[np.ndarray]:
    """``n_instances // 2`` values split round-robin over the
    proposers, leaving instance headroom for no-op fills."""
    p = len(cfg.proposers)
    stride = max(cfg.n_instances, 1024)
    total = max(cfg.n_instances // 2, 1)
    counts = [total // p + (1 if pi < total % p else 0) for pi in range(p)]
    return [
        np.asarray([pi * stride + s for s in range(counts[pi])], np.int32)
        for pi in range(p)
    ]


def prepare_queues(
    cfg: SimConfig,
    workload: list[np.ndarray],
    gates: list[np.ndarray] | None = None,
):
    """Build the (pend, gate, tail) queue arrays from per-proposer
    value sequences; returns (pend, gate, tail, capacity).

    The queue uses absolute (non-wrapping) indices: per proposer, each
    instance can receive at most one own-assignment over the whole run
    (assignments only target instances above the committed high-water
    mark, and a conflicted instance is committed), so total enqueues
    are bounded by initial workload + n_instances and the capacity
    below can never overflow."""
    p = len(cfg.proposers)
    c = max(len(wl) for wl in workload) + cfg.n_instances + 8
    # Rows are over-allocated by the assignment-window width so the
    # engine's window reads/writes are plain dynamic slices at any
    # position <= c, with no per-round padding copies; the pad region
    # [c, c+w) holds NONE invariantly (window writes only ever spill
    # NONE into it).
    width = c + cfg.assign_window
    pend = np.full((p, width), int(val.NONE), np.int32)
    gate = np.full((p, width), int(val.NONE), np.int32)
    tail = np.zeros((p,), np.int32)
    for pi, wl in enumerate(workload):
        wl = np.asarray(wl, np.int32)
        if len(wl) > c:
            raise ValueError(f"workload for proposer {pi} exceeds queue cap")
        pend[pi, : len(wl)] = wl
        tail[pi] = len(wl)
        if gates is not None and len(gates[pi]):
            g = np.asarray(gates[pi], np.int32)
            if len(g) > len(wl):
                # load-bearing for the requeue path: gate slots at and
                # past tail must be NONE (requeues are appended there
                # ungated, without a clearing write)
                raise ValueError(
                    f"gates for proposer {pi} ({len(g)}) exceed its "
                    f"workload ({len(wl)})"
                )
            gate[pi, : len(g)] = g
    return pend, gate, tail, c


def gates_vid_cap(
    workload: list[np.ndarray], gates: list[np.ndarray] | None
) -> int:
    """Static vid-space bound for the gate-membership bitmap: 0 when
    the run has no gates (eliding gate logic entirely), else one past
    the largest workload vid — gates reference workload values, and a
    gate on anything larger can never be satisfied, matching the
    semantics of gating on a value that is never proposed."""
    if gates is None or all(
        g is None or not len(g) or (np.asarray(g) == int(val.NONE)).all()
        for g in gates
    ):
        return 0
    return max(int(np.max(w)) for w in workload if len(w)) + 1


def init_state(
    cfg: SimConfig, pend, gate, tail, root: jax.Array,
    geometry=None, geom=None, pknobs=None,
) -> SimState:
    """Public initial-state constructor (tests seed custom acceptor
    state through this).  With ``geometry``/``geom``/``pknobs`` set
    (a padded-envelope build), the initial prepare-delay draw is
    menu-switched and span-traced exactly like the engine's in-round
    draws; ``cfg`` must then be the envelope's bound shape."""
    return _init_state(
        cfg, jnp.asarray(pend), jnp.asarray(gate), jnp.asarray(tail), root,
        geometry=geometry, geom=geom, pknobs=pknobs,
    )


def run_state(
    cfg: SimConfig,
    state: SimState,
    root: jax.Array,
    expected_vids: np.ndarray,
    queue_cap: int,
    vid_cap: int | None = None,
) -> SimResult:
    """Drive a prepared SimState to quiescence (or cfg.max_rounds).

    ``vid_cap`` sizes the gate-membership bitmap; ``None`` (default)
    derives it from the state's own gate/pend arrays so gate-bearing
    states are never silently run ungated.  Pass 0 explicitly for a
    known gate-free run."""
    if vid_cap is None:
        gate_np = np.asarray(state.prop.gate)
        if (gate_np != int(val.NONE)).any():
            pend_np = np.asarray(state.prop.pend)
            vid_cap = int(max(pend_np.max(), gate_np.max())) + 1
        else:
            vid_cap = 0
    round_fn = build_engine(cfg, queue_cap, vid_cap=vid_cap)
    _go = _run_loop(cfg, round_fn)
    with tracecount.engine_scope("sim"):
        final = _go(root, state)
    return to_result(final, expected_vids)


def _run_loop(cfg: SimConfig, round_fn):
    """The jitted whole-run driver: while(not done and under the
    round budget) round_fn.  Shared by ``run_state`` and the IR audit
    (analysis/jaxpr_audit.py traces exactly this surface)."""

    @jax.jit
    def _go(root, state):
        def cond(st):
            return (~st.done) & (st.t < cfg.round_budget)

        def body(st):
            return round_fn(root, st)

        return jax.lax.while_loop(cond, body, state)

    return _go


def _run_loop_knobs(cfg: SimConfig, round_fn):
    """Whole-run driver for a ``runtime_schedule + runtime_knobs``
    engine: the schedule table AND the i.i.d. knobs arrive per call,
    so one executable serves every (schedule, knob, seed) mix of the
    envelope.  The round cap is ``max_rounds`` past the table's own
    (traced) horizon — the same heal-then-converge budget as
    ``cfg.round_budget`` on the constant path.  This is the surface
    the fleet runner vmaps (fleet/runner.py) and the IR audit traces
    as ``sim.run_rounds_knobs``."""

    @jax.jit
    def _go(root, state, tab, knobs):
        def cond(st):
            return (~st.done) & (
                st.t < cfg.max_rounds + jnp.asarray(tab.horizon, jnp.int32)
            )

        def body(st):
            return round_fn(root, st, tab, knobs)

        return jax.lax.while_loop(cond, body, state)

    return _go


def _run_loop_envelope(cfg: SimConfig, round_fn):
    """Whole-run driver for a geometry-padded ``runtime_schedule +
    runtime_knobs + runtime_protocol`` engine: schedule, fault knobs,
    TRUE geometry, and protocol knobs all arrive per call, so ONE
    executable serves every (geometry, knob, schedule, seed) mix of
    the envelope menu.  The IR audit traces this surface as
    ``sim.run_rounds_envelope``."""

    @jax.jit
    def _go(root, state, tab, knobs, gm, pknobs):
        def cond(st):
            return (~st.done) & (
                st.t < cfg.max_rounds + jnp.asarray(tab.horizon, jnp.int32)
            )

        def body(st):
            return round_fn(root, st, tab, knobs, geom=gm, pknobs=pknobs)

        return jax.lax.while_loop(cond, body, state)

    return _go


def _run_loop_telemetry(
    cfg: SimConfig, round_fn, window_rounds: int = 0, region_map=None,
    return_ledger: bool = False,
):
    """Whole-run driver for a ``telemetry=True`` engine: the loop
    carries ``(state, Telemetry)`` and the epilogue reduces the
    recorder to its fixed-shape :class:`TelemetrySummary` INSIDE the
    same jit — the per-instance admission ledger never crosses to
    host (IR201 holds: no transfers in the loop body either).  This
    is the surface the IR audit traces as ``sim.run_rounds_telemetry``
    (and, with a nonzero ``window_rounds`` matching the engine build,
    as ``sim.run_rounds_timeseries``: the carry's telemetry leg is the
    ``(Telemetry, TelemetryWindows)`` pair and the epilogue also
    closes the windowed series)."""
    from tpu_paxos.telemetry import recorder as telem

    sched = cfg.faults.schedule
    horizon = sched.horizon if sched is not None else 0
    ww = int(window_rounds)
    # node->region assignment for the per-region-pair fault counters:
    # a trace-time CONSTANT here (the single-run path compiles per
    # cfg anyway; the fleet passes it as a runtime per-lane input).
    # None traces the same program as an all-zero map.
    rmap = (
        None if region_map is None
        else jnp.asarray(np.asarray(region_map, np.int32))
    )

    @jax.jit
    def _go(root, state, tele):
        def cond(c):
            return (~c[0].done) & (c[0].t < cfg.round_budget)

        def body(c):
            return round_fn(root, c[0], tele=c[1])

        final, tl = jax.lax.while_loop(cond, body, (state, tele))
        if not ww:
            base = tl
            out = (final, telem.summarize(tl, final, horizon, rmap))
        else:
            base, wins = tl
            out = (
                final,
                telem.summarize(base, final, horizon, rmap),
                telem.summarize_windows(
                    wins, base.admit_round, final.met.chosen_vid,
                    final.met.chosen_round, ww,
                    batch_round=base.admit_round,
                    learned_round=base.learned_round,
                    committed_round=base.committed_round,
                ),
            )
        if return_ledger:
            # the per-instance phase ledger, for OFFLINE export only
            # (the Perfetto flow spans): a trailing output of the same
            # traced loop, transferred post-run — the serving/fleet
            # hot paths never build with this flag
            out = out + ({
                "admit_round": base.admit_round,
                "batch_round": base.admit_round,
                "learned_round": base.learned_round,
                "committed_round": base.committed_round,
            },)
        return out

    return _go


def run_with_telemetry(
    cfg: SimConfig,
    workload: list[np.ndarray] | None = None,
    gates: list[np.ndarray] | None = None,
    window_rounds: int | None = None,
    region_map=None,
    return_ledger: bool = False,
):
    """``run()`` with the flight recorder armed: returns ``(SimResult,
    TelemetrySummary, WindowSummary | None)`` (summary fields as host
    numpy).  Decision-log identical to ``run()`` for the same (cfg,
    workload, gates) — the recorder is read-only (parity pinned by
    tests/test_telemetry.py).  ``window_rounds`` sets the windowed
    plane's bucket width (default :data:`~tpu_paxos.telemetry.
    recorder.WINDOW_ROUNDS`; pass 0 for the window-free PR-6-shaped
    recorder, whose WindowSummary slot comes back None).

    ``return_ledger=True`` (offline export only — Perfetto flow
    spans) appends the per-instance phase-ledger dict (admit / batch /
    learned / committed rounds, host numpy) as a fourth element; the
    flag selects a traced program with the ledger as a trailing
    output, so hot-path callers must leave it off."""
    from tpu_paxos.telemetry import recorder as telem

    if window_rounds is None:
        window_rounds = telem.WINDOW_ROUNDS
    ww = int(window_rounds)
    if workload is None:
        workload = default_workload(cfg)
    pend, gate, tail, c = prepare_queues(cfg, workload, gates)
    root = prng.root_key(cfg.seed)
    state = init_state(cfg, pend, gate, tail, root)
    expected = np.unique(
        np.concatenate([np.asarray(w, np.int32).reshape(-1) for w in workload])
    )
    round_fn = build_engine(
        cfg, c, vid_cap=gates_vid_cap(workload, gates), telemetry=True,
        window_rounds=ww,
    )
    _go = _run_loop_telemetry(
        cfg, round_fn, window_rounds=ww, region_map=region_map,
        return_ledger=return_ledger,
    )
    tele0 = telem.init_telemetry(cfg.n_instances, len(cfg.proposers), cfg.n_nodes)
    if ww:
        tele0 = (tele0, telem.init_windows(cfg.n_nodes))
    with tracecount.engine_scope("sim"):
        out = _go(root, state, tele0)
    final, summ = out[0], out[1]
    wsum = out[2] if ww else None
    ret = (
        to_result(final, expected),
        jax.tree.map(np.asarray, summ),
        jax.tree.map(np.asarray, wsum) if wsum is not None else None,
    )
    if return_ledger:
        ret = ret + (jax.tree.map(np.asarray, out[-1]),)
    return ret


def to_result(final: SimState, expected_vids: np.ndarray) -> SimResult:
    """Marshal a final device state into the host-convention result
    (shared by run_state, the sharded runner, and the stress sweep)."""
    return SimResult(
        learned=np.asarray(final.learned).T,  # host convention [I, A]
        chosen_vid=np.asarray(final.met.chosen_vid),
        chosen_round=np.asarray(final.met.chosen_round),
        chosen_ballot=np.asarray(final.met.chosen_ballot),
        rounds=int(final.t),
        done=bool(final.done),
        crashed=np.asarray(final.crashed),
        msgs=np.asarray(final.met.msgs),
        expected_vids=expected_vids,
    )


def run(
    cfg: SimConfig,
    workload: list[np.ndarray] | None = None,
    gates: list[np.ndarray] | None = None,
) -> SimResult:
    """Run the engine to quiescence (or cfg.max_rounds).

    ``workload[p]`` is the vid sequence proposer ``p`` proposes;
    ``gates[p][k]`` (optional) is the vid that must be chosen before
    entry ``k`` becomes proposable (in-order clients) or ``NONE``.
    """
    p = len(cfg.proposers)
    if workload is None:
        workload = default_workload(cfg)
    pend, gate, tail, c = prepare_queues(cfg, workload, gates)
    root = prng.root_key(cfg.seed)
    state = init_state(cfg, pend, gate, tail, root)
    expected = np.unique(
        np.concatenate([np.asarray(w, np.int32).reshape(-1) for w in workload])
    )
    return run_state(
        cfg, state, root, expected, c, vid_cap=gates_vid_cap(workload, gates)
    )


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_canonical_cfg() -> SimConfig:
    """The canonical small config the IR audit traces this engine
    under: multi-proposer with i.i.d. faults on, so the retry ladder,
    crash masks, and fault sampling are all in the traced program
    (what the op budget pins)."""
    return SimConfig(
        n_nodes=3,
        n_instances=16,
        proposers=(0, 1),
        seed=0,
        max_rounds=64,
        faults=FaultConfig(drop_rate=500, crash_rate=1000),
    )


def audit_entries():
    """Registered entry points for the trace-time IR audit (see
    analysis/registry.py — a new jitted surface in this module must
    be covered here or the audit's sweep fails)."""
    from tpu_paxos.analysis.registry import AuditEntry

    def build():
        cfg = audit_canonical_cfg()
        workload = default_workload(cfg)
        pend, gate, tail, c = prepare_queues(cfg, workload, None)
        root = prng.root_key(cfg.seed)
        state = init_state(cfg, pend, gate, tail, root)
        return _run_loop(cfg, build_engine(cfg, c, vid_cap=0)), (root, state)

    def build_episodes():
        # Episode-schedule-bearing config: the compile-time schedule
        # tables (reach/pause/drop rows) are baked into the traced
        # program as CONSTANTS — this is the const-table path IR205's
        # const budget was written to watch (an accidentally-huge
        # horizon or node count shows up as const bloat here).
        sched = fltm.FaultSchedule((
            fltm.partition(2, 10, (0,), (1, 2)),
            fltm.one_way(4, 14, (1,), (2,)),
            fltm.pause(6, 12, 2),
            fltm.burst(3, 9, 1500),
        ))
        cfg = dataclasses.replace(
            audit_canonical_cfg(),
            faults=FaultConfig(drop_rate=500, crash_rate=1000,
                               schedule=sched),
        )
        workload = default_workload(cfg)
        pend, gate, tail, c = prepare_queues(cfg, workload, None)
        root = prng.root_key(cfg.seed)
        state = init_state(cfg, pend, gate, tail, root)
        return _run_loop(cfg, build_engine(cfg, c, vid_cap=0)), (root, state)

    def build_knobs():
        # The one-executable stress-envelope surface: schedule AND
        # i.i.d. knobs as traced runtime inputs (runtime_schedule +
        # runtime_knobs).  The envelope delay bound sizes the ring;
        # IR205's const budget watches that no schedule/knob table
        # sneaks back in as a baked constant.
        from tpu_paxos.fleet import schedule_table as stm

        cfg = dataclasses.replace(
            audit_canonical_cfg(),
            faults=FaultConfig(drop_rate=500, crash_rate=1000, max_delay=2),
        )
        workload = default_workload(cfg)
        pend, gate, tail, c = prepare_queues(cfg, workload, None)
        root = prng.root_key(cfg.seed)
        state = init_state(cfg, pend, gate, tail, root)
        sched = fltm.FaultSchedule((
            fltm.partition(2, 10, (0,), (1, 2)),
            fltm.pause(3, 8, 2),
        ))
        tab = jax.tree.map(
            jnp.asarray, stm.encode_schedule(sched, cfg.n_nodes, 4)
        )
        knobs = jax.tree.map(
            jnp.asarray, netm.knobs_from_faults(cfg.faults)
        )
        rf = build_engine(
            cfg, c, vid_cap=0, runtime_schedule=True, runtime_knobs=True
        )
        return _run_loop_knobs(cfg, rf), (root, state, tab, knobs)

    def build_telemetry():
        # The flight-recorder surface: telemetry accumulators in the
        # loop carry + the on-device summary reduction in the epilogue.
        # Episode-schedule-bearing so every recorder family (fault-
        # layer counters under cuts/bursts, pauses feeding the stall
        # margin) is in the traced program the op budget pins; IR201
        # must stay green — the ledger never leaves the device.
        from tpu_paxos.telemetry import recorder as telem

        sched = fltm.FaultSchedule((
            fltm.partition(2, 10, (0,), (1, 2)),
            fltm.pause(3, 8, 2),
            fltm.burst(4, 9, 1500),
        ))
        cfg = dataclasses.replace(
            audit_canonical_cfg(),
            faults=FaultConfig(drop_rate=500, crash_rate=1000,
                               schedule=sched),
        )
        workload = default_workload(cfg)
        pend, gate, tail, c = prepare_queues(cfg, workload, None)
        root = prng.root_key(cfg.seed)
        state = init_state(cfg, pend, gate, tail, root)
        rf = build_engine(cfg, c, vid_cap=0, telemetry=True)
        tele0 = telem.init_telemetry(cfg.n_instances, len(cfg.proposers), cfg.n_nodes)
        return _run_loop_telemetry(cfg, rf), (root, state, tele0)

    def build_timeseries():
        # The windowed time-series plane: the telemetry build above
        # PLUS the [W] metric rings in the loop carry and the
        # summarize_windows epilogue (per-bucket commit counts and
        # latency deltas from the decision metrics).  Same
        # episode-schedule config so the windowed fault-layer
        # counters are in the pinned program; sim.run_rounds_telemetry
        # stays the window-free armed program — window_rounds=0 must
        # keep tracing the exact pre-windowing recorder.
        from tpu_paxos.telemetry import recorder as telem

        sched = fltm.FaultSchedule((
            fltm.partition(2, 10, (0,), (1, 2)),
            fltm.pause(3, 8, 2),
            fltm.burst(4, 9, 1500),
        ))
        cfg = dataclasses.replace(
            audit_canonical_cfg(),
            faults=FaultConfig(drop_rate=500, crash_rate=1000,
                               schedule=sched),
        )
        workload = default_workload(cfg)
        pend, gate, tail, c = prepare_queues(cfg, workload, None)
        root = prng.root_key(cfg.seed)
        state = init_state(cfg, pend, gate, tail, root)
        ww = telem.WINDOW_ROUNDS
        rf = build_engine(
            cfg, c, vid_cap=0, telemetry=True, window_rounds=ww
        )
        tele0 = (
            telem.init_telemetry(cfg.n_instances, len(cfg.proposers), cfg.n_nodes),
            telem.init_windows(cfg.n_nodes),
        )
        return (
            _run_loop_telemetry(cfg, rf, window_rounds=ww),
            (root, state, tele0),
        )

    def build_envelope():
        # The geometry-padded envelope surface: node/proposer axes
        # padded to the menu bound, the TRUE geometry and the protocol
        # constants as traced runtime inputs (geometry +
        # runtime_protocol on top of the runtime schedule + knob
        # path).  The menu-switched PRNG draws and the masked-absent
        # node plumbing are in the traced program, so padding waste is
        # a NAMED per-primitive budget breach, not silent drift;
        # IR205's const budget watches that no geometry table bakes
        # back in as a constant.
        from tpu_paxos.fleet import schedule_table as stm

        genv = geo.GeometryEnvelope(menu=((3, (0, 1)), (5, (0, 1, 2))))
        cfg = dataclasses.replace(
            audit_canonical_cfg(),
            faults=FaultConfig(drop_rate=500, crash_rate=1000, max_delay=2),
        )
        bcfg = genv.bound_cfg(cfg)
        # true workload rows padded to the proposer bound (empty row:
        # pad slots never propose)
        workload = default_workload(cfg) + [np.zeros((0,), np.int32)]
        pend, gate, tail, c = prepare_queues(bcfg, workload, None)
        root = prng.root_key(cfg.seed)
        gm = geo.geometry_for(genv, cfg.n_nodes, cfg.proposers)
        pkn = geo.protocol_knobs(
            cfg.protocol, stall_patience=IDLE_RESTART_ROUNDS
        )
        state = init_state(
            bcfg, pend, gate, tail, root,
            geometry=genv, geom=gm, pknobs=pkn,
        )
        sched = fltm.FaultSchedule((
            fltm.partition(2, 10, (0,), (1, 2)),
            fltm.pause(3, 8, 2),
        ))
        tab = jax.tree.map(
            jnp.asarray, stm.encode_schedule(sched, bcfg.n_nodes, 4)
        )
        knobs = jax.tree.map(jnp.asarray, netm.pad_matrix_knobs(
            netm.matrix_knobs(cfg.faults, cfg.n_nodes), bcfg.n_nodes
        ))
        rf = build_engine(
            bcfg, c, vid_cap=0, runtime_schedule=True,
            runtime_knobs=True, geometry=genv, runtime_protocol=True,
        )
        return (
            _run_loop_envelope(bcfg, rf),
            (root, state, tab, knobs,
             jax.tree.map(jnp.asarray, gm),
             jax.tree.map(jnp.asarray, pkn)),
        )

    def build_gates():
        # Gate-bearing config: a nonzero vid_cap puts the gate-
        # membership bitmap and the gated-admission logic in the
        # traced program (every other sim entry elides it at
        # vid_cap=0) — the PR-3 follow-on's "remaining gate-bearing
        # configs".  Gates reference the other proposer's first vid,
        # so satisfaction crosses proposers in the trace.
        cfg = audit_canonical_cfg()
        workload = default_workload(cfg)
        gates = [
            np.asarray([int(val.NONE), int(workload[1][0])], np.int32),
            np.asarray([int(workload[0][0])], np.int32),
        ]
        pend, gate, tail, c = prepare_queues(cfg, workload, gates)
        root = prng.root_key(cfg.seed)
        state = init_state(cfg, pend, gate, tail, root)
        rf = build_engine(
            cfg, c, vid_cap=gates_vid_cap(workload, gates)
        )
        return _run_loop(cfg, rf), (root, state)

    ir204_why = (
        "conflict-requeue compaction sorts on provably-unique keys "
        "(global instance ids / window offsets); instability cannot "
        "reorder equal keys because there are none, and a stable "
        "sort would pay for a third, hidden iota operand — see the "
        "comment at the _sort_narrow/_sort_full sites"
    )
    return [
        AuditEntry(
            "sim.run_rounds", build, covers=("_run_loop",),
            allow=("IR204",), why=ir204_why, hlo_golden=True,
        ),
        AuditEntry(
            "sim.run_rounds_episodes", build_episodes,
            allow=("IR204",), why=ir204_why, hlo_golden=True,
        ),
        AuditEntry(
            "sim.run_rounds_knobs", build_knobs,
            covers=("_run_loop_knobs",),
            allow=("IR204",), why=ir204_why, hlo_golden=True,
        ),
        AuditEntry(
            "sim.run_rounds_telemetry", build_telemetry,
            covers=("_run_loop_telemetry",),
            allow=("IR204",), why=ir204_why, hlo_golden=True,
        ),
        AuditEntry(
            "sim.run_rounds_timeseries", build_timeseries,
            allow=("IR204",), why=ir204_why, hlo_golden=True,
        ),
        AuditEntry(
            "sim.run_rounds_envelope", build_envelope,
            covers=("_run_loop_envelope",),
            allow=("IR204",), why=ir204_why, hlo_golden=True,
        ),
        AuditEntry(
            "sim.run_rounds_gates", build_gates,
            allow=("IR204",), why=ir204_why,
        ),
    ]
