"""Ballot (proposal id) encoding and bumping.

The reference encodes a ballot as ``(count << 16) | node_index`` and,
when (re)starting a prepare, bumps ``count`` until the ballot exceeds
the largest ballot ever seen (ref multi/paxos.cpp:792-799
``UpdateProposalID``; member/paxos.cpp:1569-1574 is identical).  The
node index in the low bits makes ballots globally unique and totally
ordered, with ties between counts broken by node id.

Everything here is pure int32 arithmetic, safe under ``jit``/``vmap``.
int32 bounds the retry count at 2**15 restarts per proposer, far above
anything the liveness ladder produces.
"""

from __future__ import annotations

import jax.numpy as jnp

NODE_BITS = 16
NONE = jnp.int32(-1)  # "no ballot" sentinel (valid ballots are > 0)


def make(count, node):
    """Ballot from (count, node): ``(count << 16) | node``."""
    count = jnp.asarray(count, jnp.int32)
    node = jnp.asarray(node, jnp.int32)
    return (count << NODE_BITS) | node


def count_of(b):
    return jnp.asarray(b, jnp.int32) >> NODE_BITS


def node_of(b):
    return jnp.asarray(b, jnp.int32) & ((1 << NODE_BITS) - 1)


def bump_past(count, node, max_seen):
    """Smallest (new_count, ballot) with new_count > count and
    ballot > max_seen — a closed form of the reference's
    ``while (proposal_id_ < max_proposal_id_) ++proposal_count_`` loop
    (ref multi/paxos.cpp:792-799), branch-free for jit.
    """
    count = jnp.asarray(count, jnp.int32)
    node = jnp.asarray(node, jnp.int32)
    max_seen = jnp.asarray(max_seen, jnp.int32)
    # The candidate must beat both the proposer's own count and the max
    # ballot seen from peers / rejects.
    floor_count = jnp.maximum(count + 1, count_of(max_seen))
    cand = make(floor_count, node)
    # If max_seen has the same count but a higher node index, one more
    # count increment is needed.
    new_count = jnp.where(cand > max_seen, floor_count, floor_count + 1)
    return new_count, make(new_count, node)
