"""Checkpoint / resume: whole-system state save and restore.

The reference has NO persistence — all protocol state is in-memory and
a crash loses every promise (SURVEY.md §5 notes this as a real-world
gap; the indet replay logs record the *schedule*, not a state
snapshot).  Here the entire system — acceptors, proposers, learners,
network calendars, metrics, crash masks — is one pytree of arrays, so
checkpointing is a flat array dump and resume is exact: the round
function is pure and every PRNG stream is a function of (seed, tag,
round), so a resumed run continues bit-identically to an uninterrupted
one (pinned by tests/test_checkpoint.py).

Works for any engine state pytree (core.sim.SimState,
membership.engine.MemberState, core.fast.FastState).  The treedef is
not serialized — the caller supplies a structurally identical example
(e.g. a freshly built initial state for the same config), which also
guards against restoring into a mismatched geometry.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np

_META = "tpu_paxos_meta"

# Checkpoint format version, stamped into every checkpoint's metadata.
# Bump when the serialized layout changes meaning (leaf set, dtypes,
# field semantics) so a stale-format checkpoint is distinguishable
# from a wrong-geometry one: "v2" = the post-qsums/commit_wait
# SimState era (acks int8).  Checkpoints written before versioning
# have no format string at all and restore() names that explicitly.
FORMAT = "tpu-paxos-ckpt-v2"


def save(path: str, state, meta: dict | None = None) -> None:
    """Write a state pytree (plus optional JSON-able metadata) to one
    ``.npz`` file."""
    leaves = jax.tree.leaves(state)
    payload = {f"leaf_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
    payload[_META] = np.frombuffer(
        json.dumps({"format": FORMAT, **(meta or {})}).encode(), dtype=np.uint8
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)  # atomic: no torn checkpoints


def restore(path: str, like):
    """Rebuild the pytree saved at ``path`` using ``like``'s structure.
    Returns ``(state, meta)``.  Shapes and dtypes must match ``like``'s
    leaves exactly — a mismatch means the checkpoint belongs to a
    different config and is refused."""
    structure = jax.tree.structure(like)
    ref_leaves = jax.tree.leaves(like)
    with np.load(path) as z:
        meta = json.loads(bytes(z[_META]).decode()) if _META in z.files else {}
        fmt = meta.get("format")
        # A format mismatch is named FIRST: a checkpoint from another
        # format era usually also trips the structural checks below,
        # and "wrong config" would misdiagnose what is really a stale
        # file.  (Same-format structural mismatches still mean wrong
        # geometry/engine and keep their own error.)
        fmt_note = (
            ""
            if fmt == FORMAT
            else (
                f" (checkpoint format {fmt!r} != current {FORMAT!r}"
                if fmt
                else f" (unversioned pre-{FORMAT!r} checkpoint"
            )
            + " — the file predates or postdates this build's state "
            "layout)"
        )
        n = len([k for k in z.files if k.startswith("leaf_")])
        if n != len(ref_leaves):
            raise ValueError(
                f"checkpoint has {n} leaves, expected {len(ref_leaves)} — "
                f"wrong config or engine for this checkpoint{fmt_note}"
            )
        leaves = []
        for i, ref in enumerate(ref_leaves):
            arr = z[f"leaf_{i}"]
            ref = np.asarray(ref)
            if arr.shape != ref.shape or arr.dtype != ref.dtype:
                raise ValueError(
                    f"checkpoint leaf {i} is {arr.dtype}{list(arr.shape)}, "
                    f"expected {ref.dtype}{list(ref.shape)} — wrong "
                    f"config{fmt_note}"
                )
            leaves.append(arr)
    return jax.tree.unflatten(structure, leaves), meta
