"""hlo-audit: compiled-artifact contracts for the registered entries.

The third static-analysis tier.  paxlint reads source (AST), the
jaxpr audit reads the traced IR — both stop above the compiler, so a
fusion break, a silently-copied donated buffer, or padding waste from
an envelope bound shows up only as an unexplained lanes/sec
regression.  This tier lowers every :class:`~tpu_paxos.analysis.
registry.AuditEntry` through the product's own jit surface, compiles
it, and holds the *compiled module* to three contracts:

1. **Normalized HLO goldens** (hot kernels, ``entry.hlo_golden``):
   the post-optimization module text, normalized by ``hlo_norm``
   (ids/metadata/layout noise stripped), must match the pinned golden
   under ``tests/data/hlo/`` byte-for-byte.  A mismatch dumps a
   unified diff to ``stress-triage/`` (the IR205 convention) and
   fails naming the entry.  Re-pin: ``TPU_PAXOS_HLO_PIN=1 make
   audit`` (or ``--pin``); commit the golden diff.
2. **Per-primitive budgets + memory ceilings** (every entry):
   instruction counts for the regression-prone families (fusion /
   copy / convert / transpose / while) and peak buffer bytes
   (``compiled.memory_analysis()``; ``cost_analysis`` bytes where
   unavailable) against ``analysis/hlo_budget.json`` with the same
   headroom+slack+re-pin machinery as ``op_budget.json``.  Compiled
   text is backend-shaped, so enforcement is gated on the pinning
   backend — like the flops/bytes pins of the jaxpr tier.
3. **Donation/aliasing checker** (entries with ``donate_argnums``):
   every array leaf of a donated argument must appear as an
   ``input_output_alias`` parameter in the compiled module header.
   This one is enforced on EVERY backend: a donation dropped behind a
   flag or lost in a wrapper re-jit is a doubled buffer wherever it
   compiles, and the serving harness's double-buffered queue state
   (ROADMAP item 1) rides on this guarantee.

``python -m tpu_paxos audit --hlo`` (what ``make audit`` runs) adds
this tier after the jaxpr tier; ``--hlo-only`` runs it alone.
Tier-1 enforcement lives in ``tests/test_hlo_audit.py`` (the full
golden sweep is slow-tier; the cheap entries run fast-tier).

Import discipline: jax only inside the lowering functions;
``hlo_norm`` and the budget/golden machinery stay jax-free so a raw
text dump can be re-judged in a jax-free image.
"""

from __future__ import annotations

import difflib
import gzip
import json
import os

from tpu_paxos.analysis import hlo_norm, triage
from tpu_paxos.analysis import registry as regm

DEFAULT_BUDGET = os.path.join(os.path.dirname(__file__), "hlo_budget.json")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
#: Goldens live with the other pinned test data, one gzip per entry
#: (normalized text is ~1 MB for the big drivers; gzip with mtime=0
#: keeps the committed bytes deterministic).
DEFAULT_GOLDEN_DIR = os.path.join(_REPO, "tests", "data", "hlo")

PIN_ENV = "TPU_PAXOS_HLO_PIN"

#: Budget caps: count keys get ceil(v*(1+headroom))+slack; the memory
#: ceiling gets its own (looser) pair — allocator jitter is coarser
#: than instruction-count jitter.
HEADROOM, SLACK = 0.25, 2
MEM_HEADROOM, MEM_SLACK = 0.3, 4096

#: Max unified-diff lines dumped per golden breach (the full normalized
#: text is megabytes; the head of the diff names the divergence).
DIFF_CAP = 400


# ---------------- lowering ----------------

def lower_entry(entry):
    """-> (lowered, args) via the entry's canonical call.  Entries
    with ``hlo_build`` lower through the product's own jitted callable
    (donation must not be re-added by a wrapper jit); the rest reuse
    the jaxpr-tier ``build()``."""
    import jax

    if entry.hlo_build is not None:
        lowerable, args, kwargs = entry.hlo_build()
    else:
        fn, args = entry.build()
        kwargs = {}
        lowerable = fn if hasattr(fn, "lower") else jax.jit(fn)
    if entry.x64:
        import jax.experimental

        with jax.experimental.enable_x64():
            return lowerable.lower(*args, **kwargs), args
    return lowerable.lower(*args, **kwargs), args


def expected_donated_params(args, donate_argnums) -> dict[int, str]:
    """Flattened parameter numbers the compiled module must alias:
    donated args' array leaves, numbered by position among all array
    leaves of the positional args.  Non-array leaves are assumed
    static (consumed by static_argnames, no parameter) — sound only
    when every arg up to the last donated one is all-array, which
    :func:`run_hlo_audit` verifies."""
    import jax

    expected: dict[int, str] = {}
    offset = 0
    last_donated = max(donate_argnums, default=-1)
    for i, arg in enumerate(args):
        leaves = jax.tree.leaves(arg)
        arrays = [
            leaf for leaf in leaves
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
        ]
        if i <= last_donated and len(arrays) != len(leaves):
            raise regm.RegistryError(
                f"donation accounting needs all-array args up to arg "
                f"{last_donated} (arg {i} has non-array leaves) — "
                "reorder the entry's canonical call or drop "
                "donate_argnums"
            )
        if i in donate_argnums:
            for j, leaf in enumerate(arrays):
                expected[offset + j] = (
                    f"arg {i} leaf {j} "
                    f"({getattr(leaf, 'dtype', '?')}"
                    f"{list(getattr(leaf, 'shape', ()))})"
                )
        offset += len(arrays)
    return expected


def memory_ceiling(compiled) -> dict:
    """Peak buffer bytes of the compiled executable: argument +
    output + temp, minus aliased (donated buffers are not double
    counted).  Falls back to cost_analysis 'bytes accessed' where the
    backend has no memory_analysis."""
    try:
        ma = compiled.memory_analysis()
        total = int(
            ma.argument_size_in_bytes + ma.output_size_in_bytes
            + ma.temp_size_in_bytes - ma.alias_size_in_bytes
        )
        return {"mem_bytes": total, "mem_source": "memory_analysis"}
    except Exception:
        pass
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict) and ca.get("bytes accessed"):
            return {
                "mem_bytes": int(ca["bytes accessed"]),
                "mem_source": "cost_analysis",
            }
    except Exception:
        pass
    return {"mem_bytes": 0, "mem_source": "unavailable"}


# ---------------- goldens ----------------

def golden_path(name: str, goldens_dir: str = DEFAULT_GOLDEN_DIR) -> str:
    return os.path.join(
        goldens_dir, triage.dump_name("golden", name, "hlo.gz")
    )


def load_golden(name: str, goldens_dir: str = DEFAULT_GOLDEN_DIR
                ) -> str | None:
    path = golden_path(name, goldens_dir)
    if not os.path.exists(path):
        return None
    with gzip.open(path, "rt", encoding="utf-8") as fh:
        return fh.read()


def save_golden(name: str, text: str,
                goldens_dir: str = DEFAULT_GOLDEN_DIR) -> str:
    os.makedirs(goldens_dir, exist_ok=True)
    path = golden_path(name, goldens_dir)
    tmp = path + ".tmp"
    # mtime=0 → byte-identical gzip for identical text (re-pinning an
    # unchanged golden produces no diff)
    with open(tmp, "wb") as raw:
        with gzip.GzipFile(fileobj=raw, mode="wb", mtime=0) as fh:
            fh.write(text.encode("utf-8"))
    os.replace(tmp, path)
    return path


def golden_diff(want: str, got: str, name: str) -> str:
    """Bounded unified diff (golden vs measured) for the triage dump."""
    lines = list(difflib.unified_diff(
        want.splitlines(), got.splitlines(),
        fromfile=f"golden/{name}", tofile=f"compiled/{name}", lineterm="",
    ))
    clipped = lines[:DIFF_CAP]
    if len(lines) > DIFF_CAP:
        clipped.append(
            f"... diff clipped at {DIFF_CAP} of {len(lines)} lines "
            f"(re-pin: {PIN_ENV}=1 make audit)"
        )
    return "\n".join(clipped) + "\n"


# ---------------- budget ----------------

_COUNT_KEYS = ("hlo_ops",) + hlo_norm.SUMMARY_KEYS


def load_budget(path: str = DEFAULT_BUDGET) -> dict:
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def save_budget(measured: dict[str, dict], path: str, backend: str,
                jax_version: str, keep: dict | None = None) -> dict:
    """Pin the measured census with headroom+slack (op_budget.json
    semantics; ``keep`` preserves entries a scoped pin did not trace)."""
    entries = dict(keep or {})
    for name, m in sorted(measured.items()):
        caps = {
            k: int(m[k] * (1 + HEADROOM)) + SLACK
            for k in _COUNT_KEYS if k in m
        }
        if m.get("mem_bytes"):
            caps["mem_bytes"] = (
                int(m["mem_bytes"] * (1 + MEM_HEADROOM)) + MEM_SLACK
            )
        entries[name] = caps
    data = {
        "version": 1,
        "backend": backend,
        "jax": jax_version,
        "headroom": HEADROOM,
        "slack": SLACK,
        "mem_headroom": MEM_HEADROOM,
        "mem_slack": MEM_SLACK,
        "entries": dict(sorted(entries.items())),
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return data


def check_budget(measured: dict[str, dict], budget: dict,
                 backend: str) -> tuple[list[dict], list[str], bool]:
    """-> (violations, stale, enforced).  Compiled text is
    backend-shaped, so nothing is enforced against a budget pinned on
    a different backend (enforced=False) — mirroring the flops/bytes
    gate of the jaxpr tier.  On the pinning backend, unpinned entries
    are violations (nothing stays uncapped) and entries for names no
    longer registered are stale."""
    entries: dict = budget.get("entries", {})
    if budget and budget.get("backend") != backend:
        return [], [], False
    # an EMPTY budget (missing/deleted file) is not a pass: every
    # entry reports unpinned below — nothing stays uncapped
    violations: list[dict] = []
    for name in sorted(measured):
        m = measured[name]
        caps = entries.get(name)
        if caps is None:
            violations.append({
                "entry": name, "key": "hlo_ops",
                "measured": m.get("hlo_ops", 0), "cap": None,
                "detail": f"entry {name} has no pinned HLO budget — "
                f"re-pin hlo_budget.json ({PIN_ENV}=1)",
            })
            continue
        for key in _COUNT_KEYS + ("mem_bytes",):
            if key in m and key in caps and m[key] > caps[key]:
                violations.append({
                    "entry": name, "key": key, "measured": m[key],
                    "cap": caps[key],
                    "detail": (
                        f"entry {name}: {m[key]} {key} > budget "
                        f"{caps[key]} (+{m[key] - caps[key]}) — the "
                        "compiled module grew; if intentional, re-pin "
                        f"hlo_budget.json ({PIN_ENV}=1)"
                    ),
                })
    stale = [n for n in sorted(entries) if n not in measured]
    return violations, stale, True


# ---------------- the audit ----------------

def check_donation(entry, args, text: str) -> list[dict]:
    """Donation contract for one entry: every expected donated
    parameter must appear in the compiled header's alias table."""
    if not entry.donate_argnums:
        return []
    expected = expected_donated_params(args, entry.donate_argnums)
    got = hlo_norm.aliased_params(text)
    problems = []
    for param in sorted(set(expected) - got):
        problems.append({
            "entry": entry.name, "param": param,
            "detail": (
                f"entry {entry.name}: donated parameter {param} "
                f"[{expected[param]}] is NOT aliased to any output in "
                "the compiled module — the donation was dropped "
                "(check the jit's donate_argnums and any wrapper "
                "re-jit); the buffer is silently doubled"
            ),
        })
    return problems


def run_hlo_audit(
    providers=regm.AUDIT_PROVIDERS,
    budget_path: str | None = DEFAULT_BUDGET,
    goldens_dir: str = DEFAULT_GOLDEN_DIR,
    pin: bool = False,
    triage_dir: str = "stress-triage",
) -> dict:
    """Compile every registered entry and enforce the three compiled-
    artifact contracts.  Returns a JSON-ready report; ``ok`` iff
    donation clean AND (pinning, or budget+goldens clean / not
    enforceable on this backend)."""
    import jax

    backend = jax.default_backend()
    jax_version = jax.__version__
    entries = regm.collect(providers)
    full = tuple(providers) == tuple(regm.AUDIT_PROVIDERS)

    measured: dict[str, dict] = {}
    texts: dict[str, str] = {}
    report_entries: dict[str, dict] = {}
    donation: list[dict] = []
    dumped: list[str] = []
    golden_status: dict[str, str] = {}
    golden_texts: dict[str, str] = {}

    for entry in entries:
        lowered, args = lower_entry(entry)
        compiled = lowered.compile()
        text = compiled.as_text() or ""
        norm = hlo_norm.normalize(text)
        texts[entry.name] = norm
        hist = hlo_norm.histogram_summary(hlo_norm.opcode_histogram(norm))
        hist.update(memory_ceiling(compiled))
        measured[entry.name] = hist
        donation.extend(check_donation(entry, args, text))
        if entry.hlo_golden:
            golden_texts[entry.name] = norm
        report_entries[entry.name] = dict(hist) | {
            "aliased_params": sorted(hlo_norm.aliased_params(text)),
            "golden": "pinned" if entry.hlo_golden else "-",
        }

    budget = load_budget(budget_path) if budget_path else {}
    violations: list[dict] = []
    stale: list[str] = []
    stale_goldens: list[str] = []
    enforced = False
    backend_mismatch = bool(budget) and budget.get("backend") != backend

    if pin:
        path = budget_path or DEFAULT_BUDGET
        existing = load_budget(path)
        keep = None if full else {
            n: caps for n, caps in existing.get("entries", {}).items()
            if n not in measured
            and existing.get("backend") == backend
        }
        save_budget(measured, path, backend, jax_version, keep=keep)
        for name, norm in sorted(golden_texts.items()):
            save_golden(name, norm, goldens_dir)
        if full and os.path.isdir(goldens_dir):
            want = {os.path.basename(golden_path(n, goldens_dir))
                    for n in golden_texts}
            for fname in sorted(os.listdir(goldens_dir)):
                if fname.endswith(".hlo.gz") and fname not in want:
                    os.remove(os.path.join(goldens_dir, fname))
    else:
        if budget_path:
            violations, stale, enforced = check_budget(
                measured, budget, backend
            )
            if not full:
                stale = []  # scoped runs never traced the rest
        if budget_path and enforced:
            # goldens ride the budget's backend gate;
            # budget_path=None (--no-budget) skips goldens like every
            # other pin — donation-only mode
            for name, norm in sorted(golden_texts.items()):
                want = load_golden(name, goldens_dir)
                if want is None:
                    golden_status[name] = "unpinned"
                    violations.append({
                        "entry": name, "key": "golden", "measured": None,
                        "cap": None,
                        "detail": f"entry {name} is golden-pinned but "
                        f"has no committed golden under {goldens_dir} "
                        f"— re-pin ({PIN_ENV}=1)",
                    })
                elif want != norm:
                    golden_status[name] = "mismatch"
                    diff = golden_diff(want, norm, name)
                    try:
                        dumped.append(triage.write_dump(
                            triage_dir, "hlo", name, diff, ext="diff"
                        ))
                    except OSError:
                        pass  # read-only checkout must not mask it
                    violations.append({
                        "entry": name, "key": "golden", "measured": None,
                        "cap": None,
                        "detail": (
                            f"entry {name}: normalized compiled HLO "
                            "drifted from the pinned golden — the "
                            "compiled program changed structurally; "
                            "diff dumped; if intentional, re-pin "
                            f"({PIN_ENV}=1)"
                        ),
                    })
                else:
                    golden_status[name] = "ok"
            if full and os.path.isdir(goldens_dir):
                want = {os.path.basename(golden_path(n, goldens_dir))
                        for n in golden_texts}
                stale_goldens = [
                    fname for fname in sorted(os.listdir(goldens_dir))
                    if fname.endswith(".hlo.gz") and fname not in want
                ]
        for name, status in golden_status.items():
            report_entries[name]["golden"] = status

    for v in violations:
        name = v["entry"]
        if v["key"] != "golden" and name in texts:
            try:
                dumped.append(triage.write_dump(
                    triage_dir, "hlo", name, texts[name], ext="txt"
                ))
            except OSError:
                pass

    report = {
        "version": 1,
        "backend": backend,
        "jax": jax_version,
        "enforced": bool(enforced),
        "backend_mismatch": backend_mismatch,
        "entries": dict(sorted(report_entries.items())),
        "donation": donation,
        "budget": {
            "path": budget_path or "",
            "pinned": bool(pin),
            "violations": violations,
            "stale": stale,
            "stale_goldens": stale_goldens,
            "dumped": sorted(set(dumped)),
        },
        "ok": not donation and not violations and not stale
        and not stale_goldens,
    }
    return report
