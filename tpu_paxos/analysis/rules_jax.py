"""JAX rule family: jit-hygiene, statically.

These are the compile-time mirrors of the compile-census guard
(``tracecount.py``): each pattern below either fails at trace time
with an opaque ``TracerBoolConversionError``, silently bakes stale
state into a compiled function, or causes retrace storms / per-round
host-device ping-pong that the census then catches at runtime.

Scope: *traced scopes* — functions decorated with / wrapped in
``jax.jit`` (including ``functools.partial(jax.jit, ...)``), bodies
handed to ``jax.lax.scan`` / ``while_loop`` / ``fori_loop`` /
``cond`` / ``switch`` / ``map``, and any function lexically nested
inside one.

- JAX101  Python ``if``/``while`` on a traced value: branching on a
          non-static parameter of a traced scope needs ``lax.cond``/
          ``lax.select``/``jnp.where`` (or the parameter declared in
          ``static_argnames``).  Shape/dtype/ndim tests are static
          and exempt.
- JAX102  mutable capture: reading a ``global`` or a module-level
          ``list``/``dict``/``set`` inside a traced scope bakes the
          value at trace time — mutations after the first call are
          silently ignored.
- JAX103  host-device sync inside a host-side loop: ``.item()``,
          ``.block_until_ready()``, ``np.asarray``/``np.array``/
          ``jax.device_get`` called once per iteration serializes the
          device pipeline (the per-round-loop antipattern).
- JAX104  jit without static args on a function whose parameter
          shapes Python control flow: a param used in ``range()`` or
          as an array-constructor shape wants ``static_argnames`` —
          without it the call fails on tracers or retraces per value.
"""

from __future__ import annotations

import ast

from tpu_paxos.analysis import lint

lint.RULES.update({
    "JAX101": "Python if/while on a traced value inside jitted/"
              "scanned code",
    "JAX102": "mutable global/closure capture inside jitted code",
    "JAX103": "host-device sync inside a per-round host loop",
    "JAX104": "jit without static_argnames on a shape-controlling "
              "parameter",
})

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}
#: lax control-flow: positional index -> which args are traced bodies.
_LAX_BODY_ARGS = {
    "scan": (0,), "while_loop": (0, 1), "fori_loop": (2,),
    "cond": (1, 2), "switch": None, "map": (0,),  # switch: args[1:]
}
_SYNC_ATTRS = {"item", "block_until_ready"}
_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
               "jax.device_get", "device_get"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _lax_kind(name: str) -> str | None:
    """'cond' for jax.lax.cond / lax.cond, etc.; None otherwise."""
    parts = name.split(".")
    if len(parts) >= 2 and parts[-2] == "lax" and parts[-1] in _LAX_BODY_ARGS:
        return parts[-1]
    return None


def _is_jit_expr(node: ast.AST) -> tuple[bool, ast.Call | None]:
    """Is this expression ``jax.jit`` / ``partial(jax.jit, ...)``?
    Returns (is_jit, the call carrying static-arg kwargs or None)."""
    if lint.call_name(node) in _JIT_NAMES and not isinstance(node, ast.Call):
        return True, None
    if isinstance(node, ast.Call):
        name = lint.call_name(node)
        if name in _JIT_NAMES:
            return True, node
        if name in _PARTIAL_NAMES and node.args and (
            lint.call_name(node.args[0]) in _JIT_NAMES
        ):
            return True, node
    return False, None


def _static_params(func: ast.FunctionDef, jit_call: ast.Call | None
                   ) -> set[str]:
    """Parameter names declared static at the jit site."""
    if jit_call is None:
        return set()
    params = [a.arg for a in func.args.posonlyargs + func.args.args]
    out: set[str] = set()
    for kw in jit_call.keywords:
        if kw.arg == "static_argnames":
            for el in _const_strs(kw.value):
                out.add(el)
        elif kw.arg == "static_argnums":
            for idx in _const_ints(kw.value):
                if 0 <= idx < len(params):
                    out.add(params[idx])
    return out


def _const_strs(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _const_ints(node: ast.AST) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def _collect_traced(
    tree: ast.Module,
) -> tuple[dict[ast.AST, set[str]], set[ast.AST]]:
    """Traced scopes: FunctionDef/Lambda -> static param names, plus
    the subset that are *jit sites* (where static_argnames is an
    available fix — lax bodies are traced but take no static args).

    Passes: (1) decorators; (2) ``jax.jit(f, ...)`` value positions
    resolved by name, plus direct ``jax.jit(lambda ...)``; (3) lax
    control-flow body arguments (Name refs to local defs / lambdas)."""
    defs_by_name: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs_by_name.setdefault(node.name, []).append(node)
    traced: dict[ast.AST, set[str]] = {}
    jit_sites: set[ast.AST] = set()

    def mark(func, static: set[str], jit: bool = False) -> None:
        if func is None:
            return
        # a function can be marked from several sites (lax body AND a
        # named jit wrap); union the static declarations so a param
        # declared static anywhere is never a JAX101 false positive
        traced[func] = traced.get(func, set()) | static
        if jit:
            jit_sites.add(func)

    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                is_jit, call = _is_jit_expr(dec)
                if is_jit:
                    mark(node, _static_params(node, call), jit=True)
        if not isinstance(node, ast.Call):
            continue
        name = lint.call_name(node)
        if name in _JIT_NAMES and node.args:
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                mark(target, set(), jit=True)
            elif isinstance(target, ast.Name):
                for fd in defs_by_name.get(target.id, ()):
                    mark(fd, _static_params(fd, node), jit=True)
        kind = _lax_kind(name)
        if kind is not None:
            idxs = _LAX_BODY_ARGS[kind]
            bodies = (
                node.args[1:] if idxs is None
                else [node.args[i] for i in idxs if i < len(node.args)]
            )
            for b in bodies:
                if isinstance(b, ast.Lambda):
                    mark(b, set())
                elif isinstance(b, ast.Name):
                    for fd in defs_by_name.get(b.id, ()):
                        mark(fd, set())
    # closure pass: a def lexically nested inside a traced scope runs
    # under the same trace (its own params carry traced values from
    # the call sites in the jitted body), so JAX101/JAX102 must see it
    # too — it inherits the enclosing scope's static names
    frontier = list(traced)
    while frontier:
        scope = frontier.pop()
        for sub in ast.walk(scope):
            if sub is scope or not isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if sub not in traced:
                traced[sub] = set(traced[scope])
                frontier.append(sub)
    return traced, jit_sites


def _params(func: ast.AST) -> list[str]:
    args = func.args
    return [a.arg for a in args.posonlyargs + args.args
            + ([args.vararg] if args.vararg else [])
            + args.kwonlyargs
            + ([args.kwarg] if args.kwarg else [])]


def _traced_scope_of(node: ast.AST, traced: dict[ast.AST, set[str]]):
    """Innermost traced scope containing ``node`` (lexical nesting in
    a traced function keeps tracing), or None for host code."""
    cur = getattr(node, "paxlint_parent", None)
    while cur is not None:
        if cur in traced:
            return cur
        cur = getattr(cur, "paxlint_parent", None)
    return None


def _mutable_globals(tree: ast.Module) -> set[str]:
    """Module-level names bound to mutable literals/constructors."""
    out: set[str] = set()
    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        mutable = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp)) or (
            isinstance(value, ast.Call)
            and lint.call_name(value) in ("list", "dict", "set",
                                          "bytearray", "defaultdict",
                                          "collections.defaultdict")
        )
        if not mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


def check_module(ctx: lint.ModuleContext) -> list[lint.Finding]:
    findings: list[lint.Finding] = []
    traced, jit_sites = _collect_traced(ctx.tree)
    mut_globals = _mutable_globals(ctx.tree)
    for scope, static in traced.items():
        _check_traced_branching(ctx, scope, static, traced, findings)
        _check_mutable_capture(ctx, scope, mut_globals, findings)
    _check_host_sync_loops(ctx, traced, findings)
    _check_missing_static(ctx, traced, jit_sites, findings)
    return findings


# ---------------- JAX101 ----------------

def _static_test(test: ast.AST, param_names: set[str]) -> set[str]:
    """Traced params referenced by ``test`` in a *value* position
    (shape/dtype/ndim/size attribute reads and len()/isinstance()
    arguments are static and excluded)."""
    hot: set[str] = set()
    for node in ast.walk(test):
        if not (isinstance(node, ast.Name) and node.id in param_names):
            continue
        parent = getattr(node, "paxlint_parent", None)
        if isinstance(parent, ast.Attribute) and parent.attr in _STATIC_ATTRS:
            continue
        if isinstance(parent, ast.Call) and lint.call_name(parent) in (
            "len", "isinstance", "type", "callable", "hasattr"
        ):
            continue
        if _is_none_check(parent, node):
            continue  # `x is None` specializes on presence: static
        hot.add(node.id)
    return hot


def _is_none_check(parent: ast.AST, node: ast.Name) -> bool:
    """``x is None`` / ``x is not None`` — a trace-time presence test
    on an optional argument, not a branch on traced data."""
    if not isinstance(parent, ast.Compare):
        return False
    if not all(isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops):
        return False
    others = [parent.left] + list(parent.comparators)
    return all(
        o is node
        or (isinstance(o, ast.Constant) and o.value is None)
        for o in others
    )


def _check_traced_branching(ctx, scope, static, traced, findings) -> None:
    params = set(_params(scope)) - static
    if not params:
        return
    for node in lint._walk_scope(scope):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        hot = _static_test(node.test, params)
        if hot:
            kw = "while" if isinstance(node, ast.While) else "if"
            findings.append(ctx.finding(
                "JAX101", node,
                f"Python `{kw}` on traced value(s) "
                f"{sorted(hot)} inside a jitted/scanned function — "
                "fails at trace time or silently specializes",
                "use jax.lax.cond/select/jnp.where, or declare the "
                "parameter in static_argnames; `# paxlint: "
                "allow[JAX101] <reason>` if provably static",
            ))


# ---------------- JAX102 ----------------

def _check_mutable_capture(ctx, scope, mut_globals, findings) -> None:
    # pre-collect locally-bound names: a local shadowing a module-level
    # mutable is not a capture, regardless of statement order
    local_names = set(_params(scope))
    globals_declared: set[str] = set()
    for node in lint._walk_scope(scope):
        if isinstance(node, ast.Global):
            globals_declared.update(node.names)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            local_names.add(node.id)
    local_names -= globals_declared
    for node in lint._walk_scope(scope):
        if isinstance(node, ast.Global):
            findings.append(ctx.finding(
                "JAX102", node,
                f"`global {', '.join(node.names)}` inside a jitted "
                "function — the value is baked in at trace time",
                "thread the value through function arguments (retraced "
                "on change) or close over an immutable",
            ))
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in mut_globals
            and node.id not in local_names
        ):
            findings.append(ctx.finding(
                "JAX102", node,
                f"jitted code reads module-level mutable `{node.id}` — "
                "mutations after the first call are invisible to the "
                "compiled function",
                "pass it as an argument, or bind an immutable "
                "(tuple/frozenset) snapshot",
            ))


# ---------------- JAX103 ----------------

def _attr_rooted(expr: ast.AST) -> bool:
    """Does ``expr`` peel (through subscripts/slices) to an attribute
    chain?  Device state hangs off objects (``st.chosen_vid``,
    ``self.state.crashed``); plain local names are usually host data,
    so ``np.asarray(local_list)`` stays unflagged."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    return isinstance(expr, ast.Attribute)


def _check_host_sync_loops(ctx, traced, findings) -> None:
    flagged: set[ast.AST] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        if _traced_scope_of(node, traced) is not None:
            continue  # in jitted code these are trace-time no-ops
        # only code that runs once PER ITERATION: the body (+ the
        # while test); a For's iterable evaluates once on entry and
        # an else: block runs once after exit
        per_iter = node.body + (
            [node.test] if isinstance(node, ast.While) else []
        )
        for sub in (s for stmt in per_iter for s in ast.walk(stmt)):
            if not isinstance(sub, ast.Call) or sub in flagged:
                continue
            # don't descend into nested defs: they execute elsewhere
            fn = lint.enclosing_function(sub)
            loop_fn = lint.enclosing_function(node)
            if fn is not loop_fn:
                continue
            name = lint.call_name(sub)
            attr = name.rsplit(".", 1)[-1] if "." in name else ""
            sync = attr in _SYNC_ATTRS or (
                name in _SYNC_CALLS
                and sub.args and _attr_rooted(sub.args[0])
            )
            if sync:
                flagged.add(sub)
                findings.append(ctx.finding(
                    "JAX103", sub,
                    f"host-device sync `{name}()` inside a host-side "
                    "loop — serializes the device pipeline every "
                    "iteration",
                    "hoist the transfer out of the loop, batch rounds "
                    "on device (lax.while_loop), or `# paxlint: "
                    "allow[JAX103] <reason>` for host-driven engines",
                ))


# ---------------- JAX104 ----------------

def _shapeish_params(func: ast.FunctionDef) -> set[str]:
    """Params used where only a static Python int works: range()
    bounds or array-constructor shape arguments."""
    names = set(_params(func))
    out: set[str] = set()
    for node in lint._walk_scope(func):
        if not isinstance(node, ast.Call):
            continue
        cname = lint.call_name(node)
        is_range = cname == "range"
        is_ctor = cname.rsplit(".", 1)[-1] in (
            "zeros", "ones", "full", "empty", "arange", "eye",
        ) and cname.split(".", 1)[0] in ("jnp", "jax", "np", "numpy")
        if not (is_range or is_ctor):
            continue
        check_args = node.args if is_range else node.args[:1]
        for a in check_args:
            for n in ast.walk(a):
                if isinstance(n, ast.Name) and n.id in names:
                    out.add(n.id)
    return out


def _check_missing_static(ctx, traced, jit_sites, findings) -> None:
    for scope in jit_sites:
        static = traced.get(scope, set())
        if static or not isinstance(scope, ast.FunctionDef):
            continue
        shapeish = _shapeish_params(scope) - static
        if shapeish:
            findings.append(ctx.finding(
                "JAX104", scope,
                f"jitted `{scope.name}` uses parameter(s) "
                f"{sorted(shapeish)} as range/shape bounds but the "
                "jit has no static_argnames — calls fail on tracers "
                "or retrace per value",
                f"jit with static_argnames={tuple(sorted(shapeish))!r} "
                "(and watch the compile census for retrace storms)",
            ))
