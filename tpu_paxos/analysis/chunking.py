"""Fixed-width lane chunking — the mc dispatch path's one shared
mechanical piece.

The model checker (``analysis/modelcheck.py``) and the greedy
shrinker's batched candidate evaluator
(``harness/shrink._runtime_batch_eval``) both dispatch work-lists as
fleet lanes, and both need every dispatch to carry IDENTICAL lane
shapes so one executable serves the whole sweep.  This module holds
the padding rule they share; it is pure stdlib and imports nothing,
so the shrinker's replay-critical import closure (paxlint's DET
scope) stays at exactly one extra file.
"""

from __future__ import annotations


def chunk_pad(items: list, lanes: int) -> list[tuple[list, int]]:
    """Split ``items`` into fixed-width chunks, padding the last by
    repeating its final item, so EVERY dispatch has identical lane
    shapes (one executable).  Returns ``[(padded_chunk, n_real),
    ...]``; padding lanes' results must be ignored."""
    if lanes < 1:
        raise ValueError("lanes must be >= 1")
    out = []
    for i in range(0, len(items), lanes):
        chunk = list(items[i:i + lanes])
        n_real = len(chunk)
        chunk.extend(chunk[-1:] * (lanes - n_real))
        out.append((chunk, n_real))
    return out
