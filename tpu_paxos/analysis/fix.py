"""paxlint ``--fix``: mechanical rewrite scaffolding (jax-free).

``python -m tpu_paxos lint --fix`` turns the lint report's findings
into *mechanical* edits and prints them as a unified diff (dry-run);
``--fix --write`` applies them.  Two rewrite families:

- **DET003 sorted() wrap** — the finding pins the iterated
  set/dict-view expression; the fix wraps exactly that expression in
  ``sorted(...)``, which is the rule's own suggested remediation and
  is behavior-preserving up to iteration order (which is the point:
  order becomes deterministic).
- **Pragma scaffold** (every other rule) — a standalone
  ``# paxlint: allow[RULE] TODO: <reason>`` comment line is inserted
  directly above the finding, at its indentation.  This is
  deliberately NOT a silent suppression: the TODO text is a review
  speed bump — the author must replace it with a real justification
  (or a real fix) before review, but CI stops bleeding while they do.

Only findings that block CI are fixed (post-baseline, post-pragma:
what ``run_lint`` reports).  The rewriter is position-based: it
re-parses each file, locates the AST node at the finding's exact
(line, col), and splices source text using the node's end position —
no reformatting, no AST unparse round-trip, so untouched lines are
byte-identical.

Dry-run output is a standard unified diff (``patch``-appliable);
``--write`` rewrites files in place, bottom-up so earlier edits never
shift later spans.
"""

from __future__ import annotations

import ast
import difflib
import os

#: Rules fixed by wrapping the pinned expression in sorted(...).
SORT_WRAP_RULES = ("DET003",)

TODO_REASON = "TODO: justify this suppression or fix the finding"


def _node_at(tree: ast.Module, line: int, col: int) -> ast.expr | None:
    """The expression node whose position matches a finding's pin
    (findings are emitted via ``ctx.finding(rule, node, ...)``, so
    (lineno, col_offset) identifies the node; prefer the OUTERMOST
    match so ``d.items()`` wraps the whole call, not ``d``)."""
    best: ast.expr | None = None
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.expr)
            and getattr(node, "lineno", None) == line
            and getattr(node, "col_offset", None) == col
        ):
            if best is None:
                best = node
            else:
                b_end = (best.end_lineno, best.end_col_offset)
                n_end = (node.end_lineno, node.end_col_offset)
                if n_end > b_end:
                    best = node
    return best


def _splice_sorted(src_lines: list[str], node: ast.expr) -> list[str]:
    """Wrap the node's exact source span in ``sorted(...)``."""
    l0, c0 = node.lineno - 1, node.col_offset
    l1, c1 = node.end_lineno - 1, node.end_col_offset
    out = list(src_lines)
    # end first, so the start splice does not shift the end offsets
    out[l1] = out[l1][:c1] + ")" + out[l1][c1:]
    out[l0] = out[l0][:c0] + "sorted(" + out[l0][c0:]
    return out


def _insert_pragma(src_lines: list[str], line: int, rule: str
                   ) -> list[str]:
    """Standalone pragma comment directly above ``line`` (1-based), at
    the finding line's indentation (lint honors a pragma on the
    immediately preceding comment line)."""
    idx = line - 1
    target = src_lines[idx] if idx < len(src_lines) else ""
    indent = target[: len(target) - len(target.lstrip())]
    pragma = f"{indent}# paxlint: allow[{rule}] {TODO_REASON}"
    return src_lines[:idx] + [pragma] + src_lines[idx:]


def plan_file_fixes(root: str, rel: str, findings: list[dict]
                    ) -> tuple[str, str] | None:
    """-> (original_text, fixed_text) for one file, or None if nothing
    is mechanically fixable.  Edits are applied bottom-up (by line,
    then column) so earlier splices never shift later spans; two
    DET003 wraps on the SAME expression span are deduplicated."""
    path = os.path.join(root, rel)
    with open(path, encoding="utf-8") as fh:
        original = fh.read()
    try:
        tree = ast.parse(original, filename=rel)
    except SyntaxError:
        return None  # a PARSE finding: nothing mechanical to do
    lines = original.splitlines()
    trailing_nl = original.endswith("\n")

    # Phase 1: sorted() wraps.  A wrap splices WITHIN its start/end
    # lines and never changes the line count, so every node's
    # coordinates stay valid across wraps; rightmost-first ordering
    # keeps same-line spans from shifting each other.
    wraps = [f for f in findings if f["rule"] in SORT_WRAP_RULES]
    pragmas = [
        f for f in findings
        if f["rule"] not in SORT_WRAP_RULES and f["rule"] != "PARSE"
    ]
    seen_spans: set[tuple] = set()
    changed = False
    for f in sorted(wraps, key=lambda f: (f["line"], f["col"]),
                    reverse=True):
        node = _node_at(tree, f["line"], f["col"])
        if node is None:
            continue  # position drifted (edited file) — skip, not guess
        span = (node.lineno, node.col_offset,
                node.end_lineno, node.end_col_offset)
        if span in seen_spans:
            continue
        seen_spans.add(span)
        lines = _splice_sorted(lines, node)
        changed = True
    # Phase 2: pragma scaffolds, AFTER every wrap (an insert shifts
    # all following line indices, which would corrupt wrap
    # coordinates), bottom-up by line so earlier insert points are
    # unaffected by later ones; one pragma per (line, rule).
    seen_pragmas: set[tuple] = set()
    for f in sorted(pragmas, key=lambda f: (f["line"], f["rule"]),
                    reverse=True):
        if (f["line"], f["rule"]) in seen_pragmas:
            continue
        seen_pragmas.add((f["line"], f["rule"]))
        lines = _insert_pragma(lines, f["line"], f["rule"])
        changed = True
    if not changed:
        return None
    fixed = "\n".join(lines) + ("\n" if trailing_nl else "")
    # never plan a corrupting rewrite: a pragma spliced into a
    # backslash continuation (or any other splice landing badly) must
    # drop the file, not ship unimportable code under --write
    try:
        ast.parse(fixed, filename=rel)
    except SyntaxError:
        return None
    return original, fixed


def plan_fixes(report: dict, root: str) -> dict[str, tuple[str, str]]:
    """Group the lint report's findings per file and plan edits.
    -> {relpath: (original, fixed)}."""
    by_file: dict[str, list[dict]] = {}
    for f in report["findings"]:
        by_file.setdefault(f["file"], []).append(f)
    plans: dict[str, tuple[str, str]] = {}
    for rel in sorted(by_file):
        plan = plan_file_fixes(root, rel, by_file[rel])
        if plan is not None:
            plans[rel] = plan
    return plans


def render_diff(plans: dict[str, tuple[str, str]]) -> str:
    """One unified diff over all planned edits (dry-run output)."""
    chunks: list[str] = []
    for rel, (original, fixed) in sorted(plans.items()):
        chunks.extend(difflib.unified_diff(
            original.splitlines(keepends=True),
            fixed.splitlines(keepends=True),
            fromfile=f"a/{rel}", tofile=f"b/{rel}",
        ))
    return "".join(chunks)


def apply_fixes(plans: dict[str, tuple[str, str]], root: str
                ) -> list[str]:
    """Write the fixed text in place (--fix --write).  Refuses a file
    whose on-disk content no longer matches the plan's original (the
    lint ran against different bytes) — validated for EVERY file
    before the first write, so a stale plan never leaves the tree
    half-rewritten."""
    for rel, (original, _fixed) in sorted(plans.items()):
        with open(os.path.join(root, rel), encoding="utf-8") as fh:
            if fh.read() != original:
                raise RuntimeError(
                    f"{rel} changed since the lint pass — re-run "
                    "`lint --fix`"
                )
    written: list[str] = []
    for rel, (original, fixed) in sorted(plans.items()):
        path = os.path.join(root, rel)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(fixed)
        os.replace(tmp, path)
        written.append(rel)
    return written
