"""SHARD rule family: mesh axis names stay inside ``parallel/``.

The shard-audit tier (``analysis/shard_audit.py``) certifies SPMD
layout against ONE committed source of truth: the partition-rule
table (``parallel/partition_rules.py``) plus the mesh helpers
(``parallel/mesh.py`` — ``instance_spec`` / ``replicated_spec`` /
``shard_map``, which rejects specs naming axes the mesh does not
have).  That certification is only sound if no other module
hand-builds sharding objects: a ``PartitionSpec("i")`` spelled at a
call site bakes in an axis-name literal the table never sees, works
on the 1-D mesh, and silently mis-lays-out (or crashes) on the 2-D
``('dcn', 'i')`` multi-host mesh.

Rules (scope: every linted module OUTSIDE ``tpu_paxos/parallel/``,
which owns the axis vocabulary):

- SH001  importing ``PartitionSpec`` / ``NamedSharding`` from
         ``jax.sharding`` (or ``Mesh``-building ``shard_map`` from
         ``jax.experimental``), or referencing those dotted names —
         build specs from the committed table instead
         (``parallel/partition_rules.tree_spec``,
         ``parallel/mesh.instance_spec``) and tile through
         ``parallel/mesh.shard_map``.
"""

from __future__ import annotations

import ast

from tpu_paxos.analysis import lint

lint.RULES.update({
    "SH001": "hand-built sharding primitive (PartitionSpec / "
             "NamedSharding / raw shard_map) outside tpu_paxos/parallel/",
})

#: The package that owns mesh axis names and the partition table.
_OWNER_PREFIX = "tpu_paxos/parallel/"

#: Names whose import from jax's sharding surface is the violation.
_SHARDING_NAMES = {"PartitionSpec", "NamedSharding"}

_HINT = (
    "build specs from the committed table "
    "(parallel/partition_rules.tree_spec, parallel/mesh.instance_spec "
    "/ replicated_spec) and tile through parallel/mesh.shard_map; "
    "or mark intentional: `# paxlint: allow[SH001] <reason>`"
)


def _dotted(expr: ast.AST) -> str:
    """``a.b.c`` for an attribute chain rooted at a Name ('' else)."""
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return ""
    parts.append(expr.id)
    return ".".join(reversed(parts))


def check_module(ctx: lint.ModuleContext) -> list[lint.Finding]:
    if ctx.path.replace("\\", "/").startswith(_OWNER_PREFIX):
        return []
    findings: list[lint.Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "jax.sharding":
                for alias in node.names:
                    if alias.name in _SHARDING_NAMES:
                        findings.append(ctx.finding(
                            "SH001", node,
                            f"importing {alias.name} from jax.sharding "
                            "outside parallel/ — the axis-name "
                            "vocabulary and the partition table live "
                            "in tpu_paxos/parallel",
                            _HINT,
                        ))
            elif mod in ("jax.experimental.shard_map",
                         "jax.experimental"):
                for alias in node.names:
                    if alias.name == "shard_map":
                        findings.append(ctx.finding(
                            "SH001", node,
                            "importing raw shard_map outside "
                            "parallel/ — parallel/mesh.shard_map is "
                            "the one tiling surface (it validates "
                            "spec axis names against the mesh)",
                            _HINT,
                        ))
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax.experimental.shard_map":
                    findings.append(ctx.finding(
                        "SH001", node,
                        "importing jax.experimental.shard_map outside "
                        "parallel/ — parallel/mesh.shard_map is the "
                        "one tiling surface",
                        _HINT,
                    ))
        elif isinstance(node, ast.Attribute):
            dotted = _dotted(node)
            if dotted in (
                "jax.sharding.PartitionSpec",
                "jax.sharding.NamedSharding",
                "jax.experimental.shard_map.shard_map",
            ):
                findings.append(ctx.finding(
                    "SH001", node,
                    f"{dotted} referenced outside parallel/ — a "
                    "hand-built sharding primitive bypasses the "
                    "committed partition table",
                    _HINT,
                ))
    return findings
