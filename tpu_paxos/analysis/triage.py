"""Bounded, deterministic breach-artifact dumps (stress-triage/).

Both analysis tiers dump diffable artifacts on a breach — jaxpr text
(IR205 / op-budget breaches, ``jaxpr_audit.dump_jaxpr``) and now HLO
golden diffs (``hlo_audit``).  Two contracts, pinned by test:

- **Stable deterministic filenames**: the name is a pure function of
  the entry name and artifact kind (no timestamps, no counters), so a
  repeated ``make audit`` *overwrites* its own dumps instead of
  accumulating, and a test can assert the exact path.
- **Retention cap**: the analysis-dump namespace (``jaxpr_*`` /
  ``hlo_*`` / ``mc_*`` files) is pruned oldest-first past :data:`RETENTION_CAP`
  files after every write, so a long-lived checkout's triage dir stays
  bounded even as entries come and go across PRs.  Repro artifacts
  from the stress sweep share the directory but NOT the namespace —
  pruning never touches them.

Pure stdlib; the tiers call :func:`write_dump`.
"""

from __future__ import annotations

import os
import re

#: Max analysis-dump files kept per triage dir (oldest pruned first).
RETENTION_CAP = 32

#: Filename prefixes owned by the analysis tiers — the pruning
#: namespace: jaxpr/HLO breach dumps plus the model checker's
#: ``mc_scenario_<index>`` counterexample artifacts
#: (analysis/modelcheck.py), whose deterministic scenario-index names
#: make repeat runs overwrite.  Stress-sweep repro artifacts
#: (``repro_*``) never match.
DUMP_PREFIXES = ("jaxpr_", "hlo_", "mc_", "shard_")

_SAFE = re.compile(r"[^A-Za-z0-9_]")


def dump_name(kind: str, entry: str, ext: str = "txt") -> str:
    """Deterministic artifact filename: ``<kind>_<entry>.<ext>`` with
    the entry name flattened to ``[A-Za-z0-9_]`` (dots/slashes become
    underscores — ``hlo_sim_run_rounds.diff``)."""
    kind = kind.rstrip("_")
    return f"{kind}_{_SAFE.sub('_', entry)}.{ext}"


def prune(triage_dir: str, cap: int = RETENTION_CAP) -> list[str]:
    """Delete analysis dumps past ``cap``, oldest mtime first (name as
    the deterministic tiebreaker).  Returns the pruned paths."""
    try:
        names = os.listdir(triage_dir)
    except OSError:
        return []
    dumps = sorted(
        n for n in names
        if n.startswith(DUMP_PREFIXES)
        and os.path.isfile(os.path.join(triage_dir, n))
    )
    if len(dumps) <= cap:
        return []
    keyed = sorted(
        dumps,
        key=lambda n: (os.path.getmtime(os.path.join(triage_dir, n)), n),
    )
    pruned = []
    for n in keyed[: len(dumps) - cap]:
        path = os.path.join(triage_dir, n)
        try:
            os.remove(path)
            pruned.append(path)
        except OSError:
            pass  # a racing cleanup is not a failure
    return pruned


def write_dump(triage_dir: str, kind: str, entry: str, text: str,
               ext: str = "txt", cap: int = RETENTION_CAP) -> str:
    """Write one breach artifact under its deterministic name, then
    prune the namespace to ``cap`` files.  Returns the path."""
    os.makedirs(triage_dir, exist_ok=True)
    path = os.path.join(triage_dir, dump_name(kind, entry, ext))
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(text)
    os.replace(tmp, path)
    prune(triage_dir, cap=cap)
    return path
