"""paxlint — static analysis for the determinism/replay contract.

The package's whole value proposition is *verifiable* behaviour under
faults: every engine run must be byte-replayable from (seed, config,
schedule) alone.  That contract has failure modes that are invisible
at unit-test time and expensive to rediscover one shrink-triage at a
time (PR 1's ``jax_threefry_partitionable`` incident: a config flag
silently changed sampled values and broke CLI replay of
pytest-recorded artifacts).  This subpackage enforces the contract
*statically*:

- ``lint.py`` — the AST lint engine: file walking, import-graph
  reachability from the replay-critical roots, pragma suppression
  (``# paxlint: allow[RULE]``), the committed-baseline mechanism, and
  the ``python -m tpu_paxos lint`` CLI;
- ``rules_det.py`` — the DET rule family (wall-clock, unseeded
  randomness, unordered iteration that escapes the process,
  ``jax.config.update`` containment);
- ``rules_jax.py`` — the JAX rule family (traced-value Python
  branches, mutable closure/global capture in jitted code,
  host-device syncs in per-round loops, missing-static-args
  heuristics);
- ``artifact_schema.py`` — JSON-schema validation for shrink/repro
  artifacts (applied on ``python -m tpu_paxos repro`` load);
- ``tracecount.py`` — the compile-census regression guard: counts XLA
  compilations during the tier-1 suite against the pinned per-module
  budget in ``compile_budget.json`` (the runtime shadow of the static
  JAX rules), attributed per test module AND per engine scope
  (``engine_scope``);
- ``registry.py`` / ``ir_rules.py`` / ``jaxpr_audit.py`` — the
  trace-time tier: the auditable-entry-point registry (entries live
  with the engines), IR-level rules IR201-IR205 over the traced
  jaxprs, and the audit driver with the pinned op/cost budget
  (``op_budget.json``, ``python -m tpu_paxos audit``);
- ``hlo_norm.py`` / ``hlo_audit.py`` / ``triage.py`` — the
  compiled-artifact tier (``python -m tpu_paxos audit --hlo``):
  normalized post-optimization HLO goldens for the hot kernels
  (``tests/data/hlo/``), per-primitive instruction budgets + memory
  ceilings (``hlo_budget.json``), the donation/aliasing checker, and
  the bounded deterministic breach-dump namespace shared with IR205;
- ``fix.py`` — paxlint's ``--fix`` scaffolding: mechanical rewrites
  (sorted() wraps for DET003, pragma scaffolds with TODO reasons)
  emitted as a dry-run unified diff, applied with ``--write``.

Import discipline: everything except ``tracecount`` and
``jaxpr_audit`` is pure stdlib and MUST import without jax (same lazy
discipline as ``core/__init__.py``) — ``make lint`` runs jax-free in
well under 10 s.  ``tracecount`` only touches jax inside
``CompileCensus.start``/``engine_scope``; ``jaxpr_audit`` only inside
the tracing functions (``ir_rules`` walks jaxprs duck-typed, without
importing jax).
"""

_SUBMODULES = (
    "artifact_schema", "fix", "hlo_audit", "hlo_norm", "ir_rules",
    "jaxpr_audit", "lint", "registry", "rules_det", "rules_jax",
    "tracecount", "triage",
)


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib

        return importlib.import_module(f"tpu_paxos.analysis.{name}")
    raise AttributeError(
        f"module 'tpu_paxos.analysis' has no attribute {name!r}"
    )
