"""paxlint — static analysis for the determinism/replay contract.

The package's whole value proposition is *verifiable* behaviour under
faults: every engine run must be byte-replayable from (seed, config,
schedule) alone.  That contract has failure modes that are invisible
at unit-test time and expensive to rediscover one shrink-triage at a
time (PR 1's ``jax_threefry_partitionable`` incident: a config flag
silently changed sampled values and broke CLI replay of
pytest-recorded artifacts).  This subpackage enforces the contract
*statically*:

- ``lint.py`` — the AST lint engine: file walking, import-graph
  reachability from the replay-critical roots, pragma suppression
  (``# paxlint: allow[RULE]``), the committed-baseline mechanism, and
  the ``python -m tpu_paxos lint`` CLI;
- ``rules_det.py`` — the DET rule family (wall-clock, unseeded
  randomness, unordered iteration that escapes the process,
  ``jax.config.update`` containment);
- ``rules_jax.py`` — the JAX rule family (traced-value Python
  branches, mutable closure/global capture in jitted code,
  host-device syncs in per-round loops, missing-static-args
  heuristics);
- ``artifact_schema.py`` — JSON-schema validation for shrink/repro
  artifacts (applied on ``python -m tpu_paxos repro`` load);
- ``tracecount.py`` — the compile-census regression guard: counts XLA
  compilations during the tier-1 suite against the pinned per-module
  budget in ``compile_budget.json`` (the runtime shadow of the static
  JAX rules).

Import discipline: everything except ``tracecount`` is pure
stdlib-AST and MUST import without jax (same lazy discipline as
``core/__init__.py``) — ``make lint`` runs jax-free in well under
10 s.  ``tracecount`` only touches jax inside ``CompileCensus.start``.
"""

_SUBMODULES = (
    "artifact_schema", "lint", "rules_det", "rules_jax", "tracecount",
)


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib

        return importlib.import_module(f"tpu_paxos.analysis.{name}")
    raise AttributeError(
        f"module 'tpu_paxos.analysis' has no attribute {name!r}"
    )
