"""jaxpr-audit: trace-time IR contracts + pinned op/cost budgets.

``python -m tpu_paxos audit`` (or ``make audit``) traces every
registered entry point of both engines and the sharded path
(``analysis/registry.py`` — the registry itself lives with the
engines) under canonical small configs, then:

1. **IR rules** (``ir_rules.py``, IR201-IR205): walks the closed
   jaxprs, recursing into scan/while/cond/pjit/shard_map sub-jaxprs,
   and reports contract violations pinned to a primitive path.
2. **Unregistered-function sweep**: statically finds every
   ``jax.jit`` / ``pallas_call`` / ``shard_map`` surface in the
   provider files and fails unless it is named by some entry's
   ``covers`` or the module's ``AUDIT_EXEMPT`` — a new jitted surface
   must opt in to the audit or CI goes red.
3. **Op/cost census**: per-entry primitive counts (from the jaxpr —
   backend-independent) and XLA ``cost_analysis`` FLOP / bytes
   estimates (backend-dependent, enforced only against a budget
   pinned on the same backend), checked against
   ``analysis/op_budget.json`` with the same baseline / re-pin /
   headroom machinery as the compile census: a PR that doubles an
   engine's per-round HLO fails tier-1 naming the entry point and the
   delta.  On a breach the offending entry's jaxpr is dumped to
   ``stress-triage/`` (the repro-artifact dir convention) so the
   culprit is diffable without rerunning.

Re-pin workflow (intentional changes): ``TPU_PAXOS_OP_BUDGET_PIN=1
python -m tpu_paxos audit`` (or ``--pin``) rewrites
``op_budget.json`` from the measured census with headroom; commit the
diff.  Tier-1 enforcement lives in ``tests/test_jaxpr_audit.py``,
which runs this audit in-process against the committed budget.

Import discipline: this module itself imports jax only inside the
tracing functions, and ``ir_rules``/``registry`` never do — but
collecting entries imports the provider modules (the engines), which
need jax.  ``--rules`` and ``sweep_module`` stay fully jax-free.
"""

from __future__ import annotations

import ast
import json
import os

from tpu_paxos.analysis import ir_rules, lint
from tpu_paxos.analysis import registry as regm

DEFAULT_BUDGET = os.path.join(os.path.dirname(__file__), "op_budget.json")

#: Default triage dir — shared with the stress sweep's repro artifacts.
DEFAULT_TRIAGE_DIR = "stress-triage"

PIN_ENV = "TPU_PAXOS_OP_BUDGET_PIN"

#: Call names whose appearance makes a jit surface (the sweep's
#: definition of "jitted surface": a site where Python becomes a
#: compiled XLA program).  Plain jit forms — including
#: ``functools.partial(jax.jit, ...)`` — are detected via
#: ``rules_jax._is_jit_expr``; this set adds the non-jit compile
#: entries.
_JIT_CALLS = frozenset({
    "jax.jit", "jit", "jax.pjit", "pjit",
    "pl.pallas_call", "pallas_call",
    "shard_map", "jax.shard_map", "pmesh_shard_map", "pmesh.shard_map",
})


# ---------------- unregistered-function sweep (static, jax-free) ----

def _surface_name(node: ast.AST) -> str:
    """Name of the jit surface containing ``node``: the enclosing
    function qualname (``MemberSim.__init__``), or the assignment
    target for a module-level ``x = jax.jit(f)``."""
    # module-level assignment target wins for top-level wraps
    parent = getattr(node, "paxlint_parent", None)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1 and (
        isinstance(parent.targets[0], ast.Name)
    ):
        grand = getattr(parent, "paxlint_parent", None)
        if isinstance(grand, ast.Module):
            return parent.targets[0].id
    parts: list[str] = []
    cur = parent
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            parts.append(cur.name)
        elif isinstance(cur, ast.ClassDef):
            parts.append(cur.name)
        cur = getattr(cur, "paxlint_parent", None)
    return ".".join(reversed(parts)) if parts else "<module>"


def sweep_module(path: str) -> set[str]:
    """Statically-visible jit/pallas/shard_map surface names in one
    provider file."""
    from tpu_paxos.analysis import rules_jax

    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    lint.attach_parents(tree)
    surfaces: set[str] = set()
    for node in ast.walk(tree):
        hit = False
        if isinstance(node, ast.Call):
            # reuse the lint tier's jit detector: it knows the
            # functools.partial(jax.jit, static_argnames=...) idiom
            is_jit, _call = rules_jax._is_jit_expr(node)
            hit = is_jit or lint.call_name(node) in _JIT_CALLS
        elif isinstance(node, (ast.Name, ast.Attribute)):
            # bare decorator form: @jax.jit
            parent = getattr(node, "paxlint_parent", None)
            if isinstance(parent, ast.FunctionDef) and node in getattr(
                parent, "decorator_list", ()
            ):
                hit = lint.call_name(node) in _JIT_CALLS
        if hit:
            surfaces.add(_surface_name(node))
    return surfaces


def run_sweep(providers=regm.AUDIT_PROVIDERS, root: str | None = None,
              entries: list | None = None) -> list[dict]:
    """Cross-check static surfaces against registered coverage.
    Returns a list of problem dicts (empty = clean).  Coverage is
    scoped PER PROVIDER MODULE: an entry in core/sim.py covering
    ``build_runner`` must not silently cover a same-named new surface
    in another module, or the opt-in guarantee is gone.  (``entries``
    is accepted for signature compatibility but coverage always comes
    from each module's own ``audit_entries()``.)"""
    del entries  # coverage is per-module by design; see docstring
    problems: list[dict] = []

    def is_covered(surface: str, names: set[str]) -> bool:
        # prefix match: covering "_run_loop" also covers its nested
        # defs ("_run_loop._go") — the jit site is inside the builder
        return any(
            surface == n or surface.startswith(n + ".") for n in names
        )
    root = root or os.getcwd()
    for modname in providers:
        mod = regm.provider_module(modname)
        path = getattr(mod, "__file__", None)
        if not path or not os.path.exists(path):
            problems.append({
                "kind": "missing_provider_file", "module": modname,
                "detail": f"no source file for provider {modname}",
            })
            continue
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        exempt = regm.exemptions(mod)
        prov = getattr(mod, "audit_entries", None)
        covered: set[str] = set()
        if prov is not None:
            for e in prov():
                covered.update(e.covers)
        surfaces = sweep_module(path)
        exempt_names = set(exempt)
        for s in sorted(
            s for s in surfaces
            if not is_covered(s, covered) and not is_covered(s, exempt_names)
        ):
            problems.append({
                "kind": "unregistered_surface", "module": modname,
                "surface": s,
                "detail": (
                    f"jitted surface `{s}` in {relpath} is not named "
                    "by this module's AuditEntry.covers nor "
                    "AUDIT_EXEMPT — register an entry for it "
                    "(analysis/registry.py)"
                ),
            })
        for s in sorted(covered & exempt_names):
            if s in surfaces:
                problems.append({
                    "kind": "double_booked_surface", "module": modname,
                    "surface": s,
                    "detail": f"`{s}` is both covered and exempt — "
                    "drop one",
                })
    return problems


# ---------------- tracing + census ----------------

def trace_entry(entry):
    """-> (closed_jaxpr, fn, args).  The one place jax is imported for
    tracing; ``entry.x64`` wraps the trace in enable_x64 (fixtures)."""
    import jax

    fn, args = entry.build()
    if entry.x64:
        import jax.experimental

        with jax.experimental.enable_x64():
            return jax.make_jaxpr(fn)(*args), fn, args
    return jax.make_jaxpr(fn)(*args), fn, args


def op_census(closed_jaxpr) -> dict:
    """Per-primitive counts over the whole (recursive) jaxpr."""
    prims: dict[str, int] = {}
    for eqn, _path, _loop in ir_rules.iter_eqns(closed_jaxpr, ""):
        name = eqn.primitive.name
        prims[name] = prims.get(name, 0) + 1
    return {"ops": sum(prims.values()), "prims": dict(sorted(prims.items()))}


def cost_estimate(entry, fn, args) -> dict:
    """XLA cost_analysis of the lowered entry: flops / bytes accessed
    (ints; 0-omitted).  Backend-dependent — the budget records which
    backend pinned it and only enforces on a match."""
    import jax

    try:
        lowered = (
            fn.lower(*args) if hasattr(fn, "lower")
            else jax.jit(fn).lower(*args)
        )
        ca = lowered.cost_analysis()
    except Exception as e:  # lowering quirks must not kill the audit
        return {"cost_error": type(e).__name__}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out = {}
    if ca.get("flops"):
        out["flops"] = int(ca["flops"])
    if ca.get("bytes accessed"):
        out["bytes"] = int(ca["bytes accessed"])
    return out


# ---------------- budget ----------------

def load_budget(path: str = DEFAULT_BUDGET) -> dict:
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def save_budget(measured: dict[str, dict], path: str,
                headroom: float = 0.3, slack: int = 8,
                backend: str = "", keep: dict | None = None) -> dict:
    """Pin the measured census: per-entry cap = ceil(v * (1+headroom))
    + slack for each of ops/flops/bytes (same machinery as
    compile_budget.json).  ``keep`` carries already-capped entries to
    preserve verbatim (a partial re-pin must not drop the rest of the
    committed budget)."""
    cap = lambda v: int(v * (1 + headroom)) + slack  # noqa: E731
    entries = dict(keep or {})
    entries.update({
        name: {
            k: cap(v) for k, v in sorted(m.items())
            if k in ("ops", "flops", "bytes")
        }
        for name, m in sorted(measured.items())
    })
    entries = dict(sorted(entries.items()))
    data = {
        "version": 1,
        "backend": backend,
        "headroom": headroom,
        "slack": slack,
        "entries": entries,
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return data


def check_budget(measured: dict[str, dict], budget: dict,
                 backend: str = "") -> tuple[list[dict], list[str]]:
    """-> (violations, stale).  ``ops`` caps are backend-independent
    and always enforced; ``flops``/``bytes`` only when the budget was
    pinned on the same backend.  Unpinned entries are violations
    (nothing stays uncapped); budget entries for names no longer
    registered are stale (the budget may only shrink with the code)."""
    entries: dict = budget.get("entries", {})
    same_backend = backend and budget.get("backend") == backend
    violations: list[dict] = []
    for name in sorted(measured):
        m = measured[name]
        caps = entries.get(name)
        if caps is None:
            violations.append({
                "entry": name, "key": "ops", "measured": m.get("ops", 0),
                "cap": None,
                "detail": f"entry {name} has no pinned op budget — "
                f"re-pin op_budget.json ({PIN_ENV}=1)",
            })
            continue
        for key in ("ops", "flops", "bytes"):
            if key in ("flops", "bytes") and not same_backend:
                continue
            if key in m and key in caps and m[key] > caps[key]:
                violations.append({
                    "entry": name, "key": key, "measured": m[key],
                    "cap": caps[key],
                    "detail": (
                        f"entry {name}: {m[key]} {key} > budget "
                        f"{caps[key]} (+{m[key] - caps[key]}) — the "
                        "traced program grew; if intentional, re-pin "
                        f"op_budget.json ({PIN_ENV}=1)"
                    ),
                })
    stale = [n for n in sorted(entries) if n not in measured]
    return violations, stale


def dump_jaxpr(name: str, closed_jaxpr, triage_dir: str) -> str:
    """Write the offending entry's jaxpr text under the triage dir
    (the repro-artifact convention) so a budget breach is diffable
    against a clean checkout without rerunning the audit.  Routed
    through ``analysis/triage.py``: deterministic filename, namespace
    retention cap."""
    from tpu_paxos.analysis import triage

    text = f"# jaxpr audit dump: entry {name}\n{closed_jaxpr}\n"
    return triage.write_dump(triage_dir, "jaxpr", name, text)


# ---------------- the audit ----------------

def run_audit(
    providers=regm.AUDIT_PROVIDERS,
    budget_path: str | None = DEFAULT_BUDGET,
    pin: bool = False,
    triage_dir: str = DEFAULT_TRIAGE_DIR,
    root: str | None = None,
) -> dict:
    """Full audit as a JSON-ready report dict.  ``ok`` iff zero IR
    findings, a clean sweep, and the census within budget (or
    ``budget_path=None`` / ``pin=True``)."""
    import jax

    backend = jax.default_backend()
    entries = regm.collect(providers)
    findings: list[ir_rules.IRFinding] = []
    measured: dict[str, dict] = {}
    jaxprs: dict[str, object] = {}
    for entry in entries:
        closed, fn, args = trace_entry(entry)
        jaxprs[entry.name] = closed
        findings.extend(ir_rules.check_entry(entry, closed))
        census = op_census(closed)
        if entry.cost:
            census.update(cost_estimate(entry, fn, args))
        measured[entry.name] = census
    sweep = run_sweep(providers, root=root, entries=entries)

    violations: list[dict] = []
    stale: list[str] = []
    dumped: list[str] = []
    full = tuple(providers) == tuple(regm.AUDIT_PROVIDERS)
    if pin:
        path = budget_path or DEFAULT_BUDGET
        # a scoped pin (fixture provider, one module) must not drop
        # the other committed entries; only a full-registry pin may
        # rewrite the file outright (that is what retires stale pins)
        existing = load_budget(path)
        keep = None if full else {
            n: (caps if existing.get("backend") == backend
                # kept flops/bytes caps were pinned on a different
                # backend and the file is about to be re-tagged with
                # this one — only the backend-independent ops cap
                # stays comparable
                else {k: v for k, v in caps.items() if k == "ops"})
            for n, caps in existing.get("entries", {}).items()
            if n not in measured
        }
        save_budget(measured, path, backend=backend, keep=keep)
    elif budget_path:
        violations, stale = check_budget(
            measured, load_budget(budget_path), backend=backend
        )
        if not full:
            # a scoped run never traced the other registered entries;
            # only a full-registry audit may call a pin stale
            stale = []
        seen_dump: set[str] = set()
        for v in violations:
            name = v["entry"]
            if name in jaxprs and name not in seen_dump:
                seen_dump.add(name)
                try:
                    dumped.append(
                        dump_jaxpr(name, jaxprs[name], triage_dir)
                    )
                except OSError:
                    pass  # a read-only checkout must not mask the breach

    report = {
        "version": 1,
        "backend": backend,
        "entries": {
            name: {
                k: v for k, v in m.items() if k != "prims"
            } | {"prims_top": dict(sorted(
                m.get("prims", {}).items(),
                key=lambda kv: (-kv[1], kv[0]))[:8])}
            for name, m in sorted(measured.items())
        },
        "findings": [f.to_json() for f in findings],
        "sweep": sweep,
        "budget": {
            "path": budget_path or "",
            "pinned": bool(pin),
            "violations": violations,
            "stale": stale,
            "dumped": sorted(set(dumped)),
        },
        "ok": not findings and not sweep and not violations and not stale,
    }
    return report


# ---------------- CLI ----------------

def _load_provider_arg(spec: str) -> tuple[str, ...]:
    """--providers: comma-separated module names, or a path to a .py
    file (loaded as a one-off module) — the fixture/golden path."""
    names = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if item.endswith(".py") or os.sep in item:
            import importlib.util
            import sys

            modname = "_audit_fixture_" + os.path.basename(item)[:-3]
            s = importlib.util.spec_from_file_location(modname, item)
            if s is None or s.loader is None:
                raise FileNotFoundError(f"audit provider not found: {item}")
            mod = importlib.util.module_from_spec(s)
            sys.modules[modname] = mod
            s.loader.exec_module(mod)
            names.append(modname)
        else:
            names.append(item)
    return tuple(names)


def main(argv=None) -> int:
    """``python -m tpu_paxos audit`` — exits 0 iff the traced tree
    honors the IR contracts and the pinned op/cost budget."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tpu_paxos audit",
        description="jaxpr-audit: trace-time IR contracts + op/cost "
                    "budget for the engines",
    )
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON report on stdout")
    ap.add_argument("--list", action="store_true",
                    help="list registered entry points and exit "
                    "(jax-free)")
    ap.add_argument("--rules", action="store_true",
                    help="list IR rule ids and exit")
    ap.add_argument("--budget", default=DEFAULT_BUDGET,
                    help="op/cost budget file (committed pins)")
    ap.add_argument("--no-budget", action="store_true",
                    help="skip budget enforcement (IR rules + sweep "
                    "only)")
    ap.add_argument("--pin", action="store_true",
                    help=f"re-pin the budget from this run (also via "
                    f"{PIN_ENV}=1); commit the diff")
    ap.add_argument("--providers", default="",
                    help="comma-separated provider modules or a .py "
                    "path (default: the engine registry)")
    ap.add_argument("--triage-dir", default=DEFAULT_TRIAGE_DIR,
                    help="where breach jaxpr dumps go (repro-artifact "
                    "dir convention)")
    ap.add_argument("--backend", choices=("cpu", "tpu", "auto"),
                    default="auto",
                    help="jax platform for tracing (ops counts are "
                    "backend-independent; flops/bytes pins are not)")
    ap.add_argument("--hlo", action="store_true",
                    help="also run the compiled-artifact tier "
                    "(analysis/hlo_audit.py): normalized-HLO goldens, "
                    "per-primitive budgets, memory ceilings, donation "
                    "checker")
    ap.add_argument("--hlo-only", action="store_true",
                    help="run ONLY the compiled-artifact tier")
    ap.add_argument("--hlo-budget", default=None,
                    help="HLO budget file (default: "
                    "analysis/hlo_budget.json)")
    ap.add_argument("--hlo-goldens", default=None,
                    help="golden dir for normalized compiled HLO "
                    "(default: tests/data/hlo)")
    ap.add_argument("--shard", action="store_true",
                    help="also run the mesh-polymorphic SPMD tier "
                    "(analysis/shard_audit.py): partition-rule "
                    "coverage, per-mesh replication/collective "
                    "budgets, cross-mesh parity certificates")
    ap.add_argument("--shard-only", action="store_true",
                    help="run ONLY the mesh-polymorphic SPMD tier "
                    "(what make shard-audit runs)")
    ap.add_argument("--shard-budget", default=None,
                    help="shard budget file (default: "
                    "analysis/shard_budget.json)")
    ap.add_argument("--shard-cert", default=None,
                    help="shard parity-certificate file (default: "
                    "analysis/shard_certificate.json)")
    args = ap.parse_args(argv)

    if args.rules:
        from tpu_paxos.analysis import shard_rules as _shr

        for rid, doc in sorted(ir_rules.RULES.items()):
            print(f"{rid}  {doc}")
        for rid, doc in sorted(_shr.RULES.items()):
            print(f"{rid}  {doc}")
        return 0
    providers = (
        _load_provider_arg(args.providers) if args.providers
        else regm.AUDIT_PROVIDERS
    )
    if args.list:
        # static-only listing: provider modules import jax at module
        # level, so "jax-free" here means no tracing, not no import
        lines = []
        for e in regm.collect(providers):
            lines.append(
                f"{e.name:<28s} covers={','.join(e.covers) or '-'} "
                f"mesh_axes={','.join(e.mesh_axes) or '-'}"
                + (f" allow={','.join(e.allow)}" if e.allow else "")
            )
        print("\n".join(lines))
        return 0
    if args.backend != "auto":
        # env alone is too late when jax is preloaded (sitecustomize)
        # or JAX_PLATFORMS is already exported — switch through
        # jax.config like the rest of the repo's drivers
        os.environ["JAX_PLATFORMS"] = args.backend
        import jax

        try:
            # paxlint: allow[DET004] platform selection, value-neutral
            jax.config.update("jax_platforms", args.backend)
        except RuntimeError:
            pass  # backend already initialized; env var did its best
    # --no-budget disables the budget side entirely, pin included — a
    # fixture/scoped run with TPU_PAXOS_OP_BUDGET_PIN exported must
    # never rewrite the committed engine pins
    pin = not args.no_budget and (
        args.pin or os.environ.get(PIN_ENV, "") not in ("", "0")
    )
    from tpu_paxos.analysis import hlo_audit

    hlo_pin = not args.no_budget and (
        args.pin
        or os.environ.get(hlo_audit.PIN_ENV, "") not in ("", "0")
    )
    # an exported HLO pin implies running the tier it re-pins
    run_hlo = args.hlo or args.hlo_only or (
        os.environ.get(hlo_audit.PIN_ENV, "") not in ("", "0")
    )
    from tpu_paxos.analysis import shard_rules as shr

    shard_pin = os.environ.get(shr.PIN_ENV, "") not in ("", "0")
    shard_budget_pin = not args.no_budget and (
        os.environ.get(shr.BUDGET_PIN_ENV, "") not in ("", "0")
    )
    run_shard = (
        args.shard or args.shard_only or shard_pin or shard_budget_pin
    )
    hreport = None
    sreport = None
    report = None
    if not args.hlo_only and not args.shard_only:
        try:
            report = run_audit(
                providers=providers,
                budget_path=None if args.no_budget else args.budget,
                pin=pin,
                triage_dir=args.triage_dir,
            )
        except regm.RegistryError as e:
            print(f"jaxpr-audit: {e}")
            return 2
    if run_hlo and not args.shard_only:
        try:
            hreport = hlo_audit.run_hlo_audit(
                providers=providers,
                budget_path=(
                    None if args.no_budget
                    else args.hlo_budget or hlo_audit.DEFAULT_BUDGET
                ),
                goldens_dir=args.hlo_goldens or hlo_audit.DEFAULT_GOLDEN_DIR,
                pin=hlo_pin,
                triage_dir=args.triage_dir,
            )
        except regm.RegistryError as e:
            print(f"hlo-audit: {e}")
            return 2
    if run_shard and not args.hlo_only:
        from tpu_paxos.analysis import shard_audit

        try:
            sreport = shard_audit.run_shard_audit(
                providers=providers,
                budget_path=(
                    None if args.no_budget
                    else args.shard_budget or shr.DEFAULT_BUDGET
                ),
                cert_path=args.shard_cert or shr.DEFAULT_CERT,
                pin=shard_pin,
                pin_budget=shard_budget_pin,
                triage_dir=args.triage_dir,
            )
        except (regm.RegistryError, ValueError) as e:
            print(f"shard-audit: {e}")
            return 2
    if args.hlo_only:
        if args.json:
            print(json.dumps(hreport, indent=1, sort_keys=True))
        else:
            _print_hlo(hreport, hlo_pin)
        return 0 if hreport["ok"] else 1
    if args.shard_only:
        if args.json:
            print(json.dumps(sreport, indent=1, sort_keys=True))
        else:
            _print_shard(sreport, shard_pin, shard_budget_pin)
        return 0 if sreport["ok"] else 1
    if hreport is not None:
        report = dict(report)
        report["hlo"] = hreport
        report["ok"] = report["ok"] and hreport["ok"]
    if sreport is not None:
        report = dict(report)
        report["shard"] = sreport
        report["ok"] = report["ok"] and sreport["ok"]
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        for f in report["findings"]:
            print(
                f"{f['path']}: {f['rule']} {f['message']}\n"
                f"    hint: {f['hint']}"
            )
        for p in report["sweep"]:
            print(f"sweep: {p['detail']}")
        for v in report["budget"]["violations"]:
            print(f"budget: {v['detail']}")
        for d in report["budget"]["dumped"]:
            print(f"    jaxpr dumped: {d}")
        for s in report["budget"]["stale"]:
            print(
                f"budget: stale entry {s} — no longer registered; "
                "re-pin op_budget.json"
            )
        if pin:
            print(f"op budget pinned to {args.budget} "
                  f"({len(report['entries'])} entries, backend "
                  f"{report['backend']})")
        n = len(report["findings"])
        print(
            f"jaxpr-audit: {len(report['entries'])} entry points, "
            f"{n} finding{'s' if n != 1 else ''}, "
            f"{len(report['sweep'])} sweep problems, "
            f"{len(report['budget']['violations'])} budget violations"
        )
        if hreport is not None:
            _print_hlo(hreport, hlo_pin)
        if sreport is not None:
            _print_shard(sreport, shard_pin, shard_budget_pin)
    return 0 if report["ok"] else 1


def _print_hlo(hreport: dict, pinned: bool) -> None:
    """Human-readable epilogue for the compiled-artifact tier."""
    from tpu_paxos.analysis import hlo_audit

    for d in hreport["donation"]:
        print(f"hlo donation: {d['detail']}")
    for v in hreport["budget"]["violations"]:
        print(f"hlo budget: {v['detail']}")
    for d in hreport["budget"]["dumped"]:
        print(f"    hlo artifact dumped: {d}")
    for s in hreport["budget"]["stale"]:
        print(f"hlo budget: stale entry {s} — no longer registered; "
              f"re-pin hlo_budget.json ({hlo_audit.PIN_ENV}=1)")
    for s in hreport["budget"]["stale_goldens"]:
        print(f"hlo golden: stale file {s} — no longer golden-pinned; "
              f"re-pin ({hlo_audit.PIN_ENV}=1)")
    if pinned:
        print(
            f"hlo budget + goldens pinned "
            f"({len(hreport['entries'])} entries, backend "
            f"{hreport['backend']})"
        )
    if hreport.get("backend_mismatch"):
        print(
            "hlo-audit: budget pinned on a different backend — "
            "histogram/memory/golden enforcement skipped "
            "(donation checker still ran)"
        )
    print(
        f"hlo-audit: {len(hreport['entries'])} entry points, "
        f"{len(hreport['donation'])} donation violations, "
        f"{len(hreport['budget']['violations'])} budget/golden "
        f"violations"
    )


def _print_shard(sreport: dict, pinned: bool, budget_pinned: bool) -> None:
    """Human-readable epilogue for the mesh-polymorphic SPMD tier."""
    from tpu_paxos.analysis import shard_rules as shr

    cov = sreport["coverage"]
    for u in cov["unmatched"]:
        print(
            f"shard SH301: no committed partition rule matches leaf "
            f"{u['path']} (entry {u['entry']}, shape {u['shape']}) — "
            "an unruled leaf silently replicates; add a rule to "
            "parallel/partition_rules.py"
        )
    for r in cov["rank"]:
        print(
            f"shard SH301: rule {r['rule']!r} matched {r['path']} "
            f"but {r['detail']} (entry {r['entry']})"
        )
    for s in cov["stale_rules"]:
        print(
            f"shard SH301: stale rule {s['rule']!r} (row {s['index']}) "
            "matches no registered state leaf — remove it from "
            "parallel/partition_rules.py"
        )
    for v in sreport["budget"]["violations"]:
        print(f"shard budget: {v['detail']}")
    for s in sreport["budget"]["stale"]:
        print(
            f"shard budget: stale cell {s} — no longer measured; "
            f"re-pin shard_budget.json ({shr.BUDGET_PIN_ENV}=1)"
        )
    for f in sreport["parity"]["failures"]:
        print(f"shard SH304: {f['detail']}")
    for d in sreport["dumped"]:
        print(f"    shard artifact dumped: {d}")
    if budget_pinned:
        print(
            f"shard budget pinned over grid {sreport['grid']} "
            f"(backend {sreport['backend']})"
        )
    if pinned:
        print(
            f"shard parity certificate pinned "
            f"({len(sreport['parity']['entries'])} entries, backend "
            f"{sreport['backend']})"
        )
    if sreport.get("grid_truncated"):
        print(
            f"shard-audit: grid truncated to {sreport['grid']} — the "
            "host exposes fewer virtual devices than the committed "
            "grid (run under the make audit env for all shapes)"
        )
    if not sreport.get("enforced") and not budget_pinned:
        print(
            "shard-audit: budget pinned on a different backend (or "
            "unpinned) — SH302/SH303 enforcement skipped"
        )
    n_cov = len(cov["unmatched"]) + len(cov["rank"]) + len(
        cov["stale_rules"]
    )
    print(
        f"shard-audit: grid {sreport['grid']}, "
        f"{cov['leaves']} state leaves / {cov['rules']} rules, "
        f"{n_cov} coverage problems, "
        f"{len(sreport['budget']['violations'])} budget violations, "
        f"{len(sreport['parity']['failures'])} parity failures"
    )
