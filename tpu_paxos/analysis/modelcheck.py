"""smallcheck: exhaustive bounded model checking of the protocol on
device-batched fleet lanes.

The three analysis tiers shipped so far judge the PROGRAM — paxlint
reads the AST, jaxpr-audit the traced IR, hlo-audit the compiled
artifact.  Everything that judges the PROTOCOL is sampled: i.i.d.
knobs, the seeded schedule grammar, a stroll-not-hunt search.  This
module is the fourth tier: a declarative **scope** discretizes the
fault universe — episode kinds x quantized round intervals x node
groups x rate tiers, i.i.d. knob tiers (the crash points), workload
gate tiers, and engine seeds — and the ENTIRE cross product is
enumerated, so "no counterexample found" means *no scenario in the
declared scope wedges*, not "none of the samples did".

The pieces:

- **Scope** (:class:`McScope`, ``analysis/mc_scope.json``): the
  declared bounds.  Everything is quantized to a finite alphabet of
  episodes (:func:`episode_alphabet`) plus finite knob/gate/seed
  axes, so the scenario space is a computable integer.  Gray/WAN
  weather is a first-class axis: ``gray(t0, t1, *nodes, delay=k)``
  letters ride a quantized delay-tier grid (``gray_delays``, finite
  because the engines clamp inflated delays at the envelope's ring
  bound — :data:`MAX_GRAY_DELAY`).  Scope files may also declare
  CHURN scopes (``"type": "churn"`` — bounded membership-change
  grids through the member fleet, ``analysis/mc_member.py``) and
  CONTROLLER scopes (``"type": "control"`` — the admission
  controller's policy invariants, ``analysis/mc_control.py``); all
  three types share this module's codec helpers, chunking, and
  certificate machinery.
- **Codec**: a bijective index <-> scenario mapping
  (:meth:`ScopeEnum.decode` / :meth:`ScopeEnum.encode`) over the
  mixed-radix cross product (episode combination, knob tier, gate
  tier, seed) with the combination axis ranked by the combinatorial
  number system.  A scenario's full-codec index is its STABLE NAME:
  certificates, counterexample artifacts, and failure messages all
  use it, and it never shifts when symmetry reduction is toggled.
- **Symmetry reduction**: acceptor-only nodes (every node outside the
  proposer set) are interchangeable — permuting their labels permutes
  the schedule's masks without changing the protocol structure — so
  only the lexicographically-least member of each orbit under the
  movable-node permutation group is dispatched
  (:meth:`ScopeEnum.canon_combo`).  For deterministic knob tiers this
  is an exact behavioral quotient; for stochastic tiers the orbit
  members differ only in which i.i.d. realization they draw, and
  i.i.d. coverage is owned by the scope's SEED axis, not the symmetry
  axis.  The certificate records both the full and the reduced count
  — the honest denominator ROADMAP item 2's recall target divides by.
- **Chunked dispatch**: the reduced scenario list is decoded in
  fixed-width chunks (the last chunk padded by repeating a lane, so
  every dispatch has identical shapes) into the ``[lanes]``
  ScheduleTable + FaultKnobs + workload-table stacks that the fleet
  runner takes as pure data, and dispatched through the shared
  envelope cache (``fleet/envelope.runner_for``) with on-device
  verdicts — zero XLA compiles after the first chunk
  (``compiles_per_chunk`` in the summary pins it), thousands of
  exhaustive scenarios per dispatch.
- **Certificate** (``analysis/mc_certificate.json``, re-pin
  ``TPU_PAXOS_MC_PIN=1 make mc``): scope sha256, scenario counts
  (full and post-reduction), chunk geometry, and the per-scenario
  verdict nibbles (hex, reduced order) with their sha256.  Scope
  drift or a new counterexample fails ``make mc`` naming the first
  diverging scenario's full-codec index.  Verdict bits are compared
  only on the pinning backend (like the flops/HLO pins).
- **Counterexamples** drop straight into the existing triage stack:
  the lane's config is re-derived single-run, judged by the FULL
  invariant suite, greedily shrunk (``harness/shrink.py`` — whose
  batched candidate evaluator rides this module's
  :func:`chunk_pad`), and written as a ``mc_scenario_<index>.json``
  repro artifact under the analysis-dump retention namespace
  (``analysis/triage.py``): deterministic names, repeat runs
  overwrite, 32-file cap.

Recall is proven, not assumed: ``TPU_PAXOS_SEEDED_WEDGE=takeover``
re-introduces the PR-1 pause-crash commit-TAKEOVER wedge
(core/sim.py), and the pinned slow-tier test asserts the quick scope
finds it exhaustively, shrinks it, and replays the artifact
byte-identically.

CLI: ``python -m tpu_paxos mc [--scope quick|full]`` (``make mc`` /
``make mc-quick``).  Exit 0 iff no counterexample and the pinned
certificate matches.
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import math
import os
import sys
from itertools import combinations, permutations

import numpy as np

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import faults as fltm

#: Default scope + certificate homes (committed next to the other
#: analysis pins).
DEFAULT_SCOPE = os.path.join(os.path.dirname(__file__), "mc_scope.json")
DEFAULT_CERT = os.path.join(
    os.path.dirname(__file__), "mc_certificate.json"
)
PIN_ENV = "TPU_PAXOS_MC_PIN"

#: Movable-node permutation groups past this size are a scope-design
#: error, not a reduction opportunity (8! canonical-form checks per
#: combo would dominate the enumeration itself).
MAX_PERMS = 5040

#: Scenario episode-count ceiling == fleet.runner.MAX_EPISODES (the
#: fleet's default envelope capacity; cross-checked by
#: tests/test_modelcheck.py).  Hardcoded rather than imported so the
#: codec/scope layer stays jax-free.  The bound is what lets the
#: shrinker's candidate evaluators (harness/shrink, which floor their
#: episode capacity at the same default) land on the SAME envelope
#: key as the mc sweep — a larger scope would silently recompile per
#: counterexample triage.
MAX_SCOPE_EPISODES = 8

#: Gray delay-tier ceiling == fleet.envelope.MAX_DELAY_BOUND (the
#: floor of every fleet envelope's delay ring; cross-checked by
#: tests/test_modelcheck.py, hardcoded for the same jax-free reason
#: as MAX_SCOPE_EPISODES).  The engines clamp the INFLATED per-
#: message delay at the ring bound, so a gray tier past it would be
#: indistinguishable from the tier AT it — the clamp is exactly what
#: makes the delay axis finite, and the validator keeps the declared
#: grid inside the distinguishable range.
MAX_GRAY_DELAY = 8

#: Episode kinds the letter builder cannot enumerate: kind -> reason.
#: NAMED rejection, never silent exclusion — a scope declaring a kind
#: listed here fails loudly rather than certifying a universe it
#: silently never enumerated.  Empty today: every ``faults.KINDS``
#: member has a codec axis (gray landed with the quantized
#: ``gray_delays`` tier grid).  The table stays so a future grammar
#: kind lands HERE (with its reason) until its axis exists, and so
#: sibling scope validators (mc_member's member-engine alphabet) can
#: declare their own rejections the same data-driven way.
UNSUPPORTED_KINDS: dict[str, str] = {}


class ScopeError(Exception):
    """The scope file is malformed or internally inconsistent."""


@dataclasses.dataclass(frozen=True)
class McScope:
    """One declared model-checking scope (see module doc).  All
    fields are plain data so the scope serializes, hashes, and
    certificates stably."""

    n_nodes: int
    proposers: int  # proposer count; proposer nodes are 0..proposers-1
    horizon: int  # every episode ends by this round
    max_rounds: int  # convergence budget past the last heal
    intervals: tuple  # ((t0, t1), ...) quantized episode intervals
    kinds: tuple  # episode kinds in the alphabet, listed order
    partition_group_sizes: tuple = (1,)
    pause_set_sizes: tuple = (1,)
    burst_rates: tuple = ()
    #: deterministic crash points (faults.crash): the rounds at which
    #: a crash letter fail-stops its nodes.  Crash letters ignore the
    #: interval grid — a crash is an instant, not a window.
    crash_rounds: tuple = ()
    crash_set_sizes: tuple = (1,)
    #: gray axis (PR-13 weather joins the alphabet): one letter per
    #: (interval x node set x delay tier).  ``gray_delays`` is the
    #: quantized delay-tier grid — empty unless "gray" is in kinds.
    #: Both fields serialize ONLY when non-default (to_dict elides
    #: them) so pre-gray scopes hash — and certify — byte-identically.
    gray_set_sizes: tuple = (1,)
    gray_delays: tuple = ()
    max_episodes: int = 2  # scenarios combine up to this many episodes
    knob_tiers: tuple = ()  # (FaultConfig kwargs dict, ...) — crash points
    gate_tiers: tuple = (True,)  # workload-gate on/off axis
    seeds: tuple = (0,)
    symmetry_reduction: bool = True
    chunk_lanes: int = 16
    workload_seed: int = 0
    n_ids: int = 4  # gate-chain length per proposer
    n_free: int = 4  # ungated values per proposer

    _FIELDS = (
        "n_nodes", "proposers", "horizon", "max_rounds", "intervals",
        "kinds", "partition_group_sizes", "pause_set_sizes",
        "burst_rates", "crash_rounds", "crash_set_sizes",
        "gray_set_sizes", "gray_delays",
        "max_episodes", "knob_tiers", "gate_tiers",
        "seeds", "symmetry_reduction", "chunk_lanes", "workload_seed",
        "n_ids", "n_free",
    )

    #: Fields added AFTER certificates were first pinned: serialized
    #: only when non-default, so every pre-existing scope's sha256 —
    #: and therefore its pinned certificate — stays byte-identical.
    _ELIDED_DEFAULTS = {"gray_set_sizes": (1,), "gray_delays": ()}

    @classmethod
    def from_dict(cls, d: dict) -> "McScope":
        if not isinstance(d, dict):
            raise ScopeError("scope must be a JSON object")
        unknown = sorted(set(d) - set(cls._FIELDS))
        if unknown:
            raise ScopeError(f"unknown scope field(s): {', '.join(unknown)}")
        missing = [
            f for f in ("n_nodes", "proposers", "horizon", "max_rounds",
                        "intervals", "kinds")
            if f not in d
        ]
        if missing:
            raise ScopeError(f"scope missing field(s): {', '.join(missing)}")
        kw = dict(d)
        kw["intervals"] = tuple(
            (int(t0), int(t1)) for t0, t1 in kw["intervals"]
        )
        for f in ("kinds", "partition_group_sizes", "pause_set_sizes",
                  "burst_rates", "crash_rounds", "crash_set_sizes",
                  "gray_set_sizes", "gray_delays",
                  "gate_tiers", "seeds"):
            if f in kw:
                kw[f] = tuple(kw[f])
        if "knob_tiers" in kw:
            kw["knob_tiers"] = tuple(dict(t) for t in kw["knob_tiers"])
        try:
            scope = cls(**kw)
        except TypeError as e:
            raise ScopeError(f"bad scope field types: {e}") from None
        scope.validate()
        return scope

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["intervals"] = [list(iv) for iv in self.intervals]
        for f in ("kinds", "partition_group_sizes", "pause_set_sizes",
                  "burst_rates", "crash_rounds", "crash_set_sizes",
                  "gate_tiers", "seeds"):
            d[f] = list(d[f])
        d["knob_tiers"] = [dict(t) for t in self.knob_tiers]
        for f, dflt in self._ELIDED_DEFAULTS.items():
            # post-pin fields leave the serialization (and the
            # sha256) untouched at their defaults — see _ELIDED_DEFAULTS
            if getattr(self, f) == dflt:
                del d[f]
            else:
                d[f] = list(d[f])
        return d

    def sha256(self) -> str:
        """The scope's identity hash — certificate key; any scope edit
        (even a reordering, which changes the codec) changes it."""
        text = json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(text.encode()).hexdigest()

    def validate(self) -> None:
        if self.n_nodes < 2:
            raise ScopeError("n_nodes must be >= 2")
        if not 1 <= self.proposers < self.n_nodes + 1:
            raise ScopeError("proposers must be in [1, n_nodes]")
        if self.horizon < 1:
            raise ScopeError("horizon must be >= 1")
        if self.max_rounds < 1:
            raise ScopeError("max_rounds must be >= 1")
        if not self.intervals:
            raise ScopeError("intervals must be non-empty")
        for t0, t1 in self.intervals:
            if not 0 <= t0 < t1 <= self.horizon:
                raise ScopeError(
                    f"interval [{t0}, {t1}) must be non-empty inside "
                    f"[0, horizon={self.horizon}]"
                )
        bad = sorted(set(self.kinds) - set(fltm.KINDS))
        if bad:
            raise ScopeError(f"unknown episode kind(s): {', '.join(bad)}")
        for k in self.kinds:
            if k in UNSUPPORTED_KINDS:
                raise ScopeError(
                    f"episode kind {k!r} is not enumerable by this "
                    f"checker: {UNSUPPORTED_KINDS[k]}"
                )
        if "gray" in self.kinds:
            if not self.gray_delays:
                raise ScopeError("gray in kinds needs gray_delays")
            if len(set(self.gray_delays)) != len(self.gray_delays):
                raise ScopeError("gray_delays must be distinct")
            for dly in self.gray_delays:
                if not 1 <= dly <= MAX_GRAY_DELAY:
                    raise ScopeError(
                        f"gray_delays entries must be in "
                        f"[1, {MAX_GRAY_DELAY}] (the fleet envelope's "
                        "delay-ring bound — the engines clamp inflated "
                        "delays there, so tiers past it collapse into "
                        "the boundary tier)"
                    )
        elif self.gray_delays:
            raise ScopeError("gray_delays declared without gray in kinds")
        if "gray" in self.kinds:
            # the fleet's named dispatch rejection, moved to scope
            # parse time: the delay-inflation clamp is each lane's OWN
            # declared bound (fleet/runner._knob_arrays), so a zero-
            # max_delay tier would turn every gray letter into a no-op
            for t in self.knob_tiers:
                if int(t.get("max_delay", 0)) < 1:
                    raise ScopeError(
                        f"gray in kinds needs max_delay >= 1 on every "
                        f"knob tier (tier {t} clamps gray inflation "
                        "to its own declared bound; at 0 every gray "
                        "episode is a no-op)"
                    )
        if "burst" in self.kinds and not self.burst_rates:
            raise ScopeError("burst in kinds needs burst_rates")
        for r in self.burst_rates:
            if not 0 < r <= 10_000:
                raise ScopeError("burst rates must be in (0, 10000]")
        if "crash" in self.kinds and not self.crash_rounds:
            raise ScopeError("crash in kinds needs crash_rounds")
        for t in self.crash_rounds:
            if not 0 <= t < self.horizon:
                raise ScopeError(
                    "crash rounds must be in [0, horizon)"
                )
        for sizes, what in (
            (self.partition_group_sizes, "partition_group_sizes"),
            (self.pause_set_sizes, "pause_set_sizes"),
            (self.crash_set_sizes, "crash_set_sizes"),
            (self.gray_set_sizes, "gray_set_sizes"),
        ):
            for k in sizes:
                if not 1 <= k < self.n_nodes:
                    raise ScopeError(
                        f"{what} entries must be in [1, n_nodes)"
                    )
        if not 0 <= self.max_episodes <= MAX_SCOPE_EPISODES:
            raise ScopeError(
                f"max_episodes must be in [0, {MAX_SCOPE_EPISODES}] "
                "(the fleet envelope's episode capacity — the mc "
                "sweep and the shrinker's candidate evaluators share "
                "one compiled executable only within it)"
            )
        if not self.knob_tiers:
            raise ScopeError("knob_tiers must be non-empty")
        for t in self.knob_tiers:
            if "schedule" in t:
                raise ScopeError(
                    "knob tiers are i.i.d. only; schedules come from "
                    "the episode axes"
                )
            try:
                FaultConfig(**t)
            except (TypeError, ValueError) as e:
                raise ScopeError(f"bad knob tier {t}: {e}") from None
        if not self.gate_tiers:
            raise ScopeError("gate_tiers must be non-empty")
        if not self.seeds or len(set(self.seeds)) != len(self.seeds):
            raise ScopeError("seeds must be non-empty and distinct")
        if self.chunk_lanes < 1:
            raise ScopeError("chunk_lanes must be >= 1")
        if self.symmetry_reduction:
            movable = self.n_nodes - self.proposers
            if math.factorial(max(movable, 1)) > MAX_PERMS:
                raise ScopeError(
                    f"{movable} movable nodes = "
                    f"{math.factorial(movable)} permutations per "
                    "canonical-form check; shrink the scope or set "
                    "symmetry_reduction: false"
                )


def _scope_types() -> dict:
    """The scope-type registry: JSON ``"type"`` discriminator ->
    ``(scope_cls, enum_cls, run_fn)``.  ``"fault"`` (the default, and
    the only type pre-gray scope files could name) is this module's
    own McScope/ScopeEnum/run_scope; the churn and controller scopes
    live in sibling modules that import THIS module for the shared
    codec/certificate machinery, so the registry is built lazily to
    keep the import acyclic (and the codec layer jax-free)."""
    from tpu_paxos.analysis import mc_control, mc_member

    return {
        "fault": (McScope, ScopeEnum, run_scope),
        "churn": (
            mc_member.ChurnScope, mc_member.ChurnEnum,
            mc_member.run_scope,
        ),
        "control": (
            mc_control.ControlScope, mc_control.ControlEnum,
            mc_control.run_scope,
        ),
    }


def load_scopes(path: str = DEFAULT_SCOPE) -> dict:
    """Parse the scope file: a JSON object of name -> scope.  Each
    entry's optional ``"type"`` field picks the scope family
    (:func:`_scope_types`); absent = ``"fault"``, so pre-existing
    scope files parse — and hash — exactly as before."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except OSError as e:
        raise ScopeError(f"unreadable scope file: {e}") from None
    except json.JSONDecodeError as e:
        raise ScopeError(f"invalid scope JSON: {e}") from None
    if not isinstance(raw, dict) or not raw:
        raise ScopeError("scope file must map scope names to scopes")
    types = _scope_types()
    out = {}
    for name in sorted(raw):
        d = raw[name]
        kind = d.get("type", "fault") if isinstance(d, dict) else "fault"
        if kind not in types:
            raise ScopeError(
                f"scope {name!r}: unknown scope type {kind!r} "
                f"(one of {', '.join(sorted(types))})"
            )
        cls = types[kind][0]
        if kind != "fault":
            d = {k: v for k, v in d.items() if k != "type"}
        try:
            out[name] = cls.from_dict(d)
        except ScopeError as e:
            raise ScopeError(f"scope {name!r}: {e}") from None
    return out


def scope_type(scope) -> str:
    """A loaded scope's type discriminator (its class name is not the
    contract; the registry key is)."""
    for kind, (cls, _, _) in _scope_types().items():
        if isinstance(scope, cls):
            return kind
    raise ScopeError(f"unregistered scope object {type(scope).__name__}")


def enum_for(scope):
    """The scope's enumerator (``.reduced`` is the dispatch order the
    certificate's verdict nibbles follow, for every scope type)."""
    return _scope_types()[scope_type(scope)][1](scope)


def run_for(scope):
    """The scope's run function (``run_scope``-shaped: same kwargs,
    same summary keys — the certificate machinery is shared)."""
    return _scope_types()[scope_type(scope)][2]


# ---------------- episode alphabet ----------------

def _table_key(e: fltm.Episode, n_nodes: int) -> tuple:
    """An episode's SEMANTIC identity: its interval plus the static
    masks the engine actually sees (faults.episode_tables).  Two
    grammar spellings with equal masks — e.g. a partition group and
    its complement — are the same letter."""
    cut, paused, extra, crash_m, gray_v = fltm.episode_tables(e, n_nodes)
    return (
        e.t0, e.t1, cut.tobytes(), paused.tobytes(), int(extra),
        crash_m.tobytes(), gray_v.tobytes(),
    )


def episode_alphabet(scope: McScope) -> list[fltm.Episode]:
    """The scope's finite episode alphabet, in deterministic order:
    intervals in listed order, kinds in listed order, node structures
    in lexicographic order; semantic duplicates (by mask) keep the
    first spelling."""
    nodes = range(scope.n_nodes)
    out: list[fltm.Episode] = []
    seen: set[tuple] = set()

    def add(e: fltm.Episode) -> None:
        key = _table_key(e, scope.n_nodes)
        if key not in seen:
            seen.add(key)
            out.append(e)

    for t0, t1 in scope.intervals:
        for kind in scope.kinds:
            if kind == "partition":
                for k in scope.partition_group_sizes:
                    for grp in combinations(nodes, k):
                        if k < scope.n_nodes:  # implicit complement
                            add(fltm.partition(t0, t1, grp))
            elif kind == "one_way":
                for src in nodes:
                    for dst in nodes:
                        if src != dst:
                            add(fltm.one_way(t0, t1, (src,), (dst,)))
            elif kind == "pause":
                for k in scope.pause_set_sizes:
                    for grp in combinations(nodes, k):
                        add(fltm.pause(t0, t1, *grp))
            elif kind == "burst":
                for r in scope.burst_rates:
                    add(fltm.burst(t0, t1, int(r)))
            elif kind == "gray":
                # the (node set x delay tier) grid: the ring-bound
                # clamp (MAX_GRAY_DELAY) already bounded the tiers
                for k in scope.gray_set_sizes:
                    for grp in combinations(nodes, k):
                        for dly in scope.gray_delays:
                            add(fltm.gray(t0, t1, *grp, delay=int(dly)))
    # crash points ride their own round grid (a crash is an instant,
    # not a window), appended after the interval letters
    if "crash" in scope.kinds:
        for t in scope.crash_rounds:
            for k in scope.crash_set_sizes:
                for grp in combinations(nodes, k):
                    add(fltm.crash(int(t), *grp))
    return out


def _permute_episode(e: fltm.Episode, perm: dict[int, int]) -> fltm.Episode:
    """The episode with every node label mapped through ``perm``
    (Episode.__post_init__ re-canonicalizes the containers)."""
    if e.kind == "partition":
        return fltm.partition(
            e.t0, e.t1, *[tuple(perm[x] for x in g) for g in e.groups]
        )
    if e.kind == "one_way":
        return fltm.one_way(
            e.t0, e.t1,
            tuple(perm[x] for x in e.src), tuple(perm[x] for x in e.dst),
        )
    if e.kind == "pause":
        return fltm.pause(e.t0, e.t1, *(perm[x] for x in e.nodes))
    if e.kind == "crash":
        return fltm.crash(e.t0, *(perm[x] for x in e.nodes))
    if e.kind == "gray":
        # gray names nodes exactly like pause — the delay tier rides
        # along unchanged, so gray letters break acceptor symmetry
        # the same way pause sets do (closure = full node-set orbit
        # per delay tier)
        return fltm.gray(
            e.t0, e.t1, *(perm[x] for x in e.nodes), delay=e.delay
        )
    return e  # burst names no nodes


# ---------------- combination codec ----------------

def n_combos(m: int, k_max: int) -> int:
    """Episode combinations of size 0..k_max over an m-letter
    alphabet."""
    return sum(math.comb(m, k) for k in range(k_max + 1))


def combo_unrank(r: int, m: int, k_max: int) -> tuple[int, ...]:
    """Rank -> strictly-increasing index tuple: sizes in increasing
    order, lexicographic within a size (combinatorial number
    system)."""
    if r < 0:
        raise IndexError(f"combo rank {r} out of range")
    for k in range(k_max + 1):
        c = math.comb(m, k)
        if r < c:
            out = []
            x = 0
            for i in range(k):
                while True:
                    below = math.comb(m - x - 1, k - i - 1)
                    if r < below:
                        out.append(x)
                        x += 1
                        break
                    r -= below
                    x += 1
            return tuple(out)
        r -= c
    raise IndexError("combo rank past the scope's combination count")


def combo_rank(combo: tuple[int, ...], m: int, k_max: int) -> int:
    """Inverse of :func:`combo_unrank` (bijection pinned by
    tests/test_modelcheck.py)."""
    k = len(combo)
    if k > k_max:
        raise ValueError(f"combo larger than max_episodes={k_max}")
    if any(not 0 <= x < m for x in combo) or list(combo) != sorted(set(combo)):
        raise ValueError(f"combo must be strictly increasing in [0, {m})")
    r = sum(math.comb(m, j) for j in range(k))
    prev = -1
    for i, x in enumerate(combo):
        for y in range(prev + 1, x):
            r += math.comb(m - y - 1, k - i - 1)
        prev = x
    return r


class Scenario:
    """One decoded scenario: the full-codec ``index`` is its stable
    name; ``combo`` holds alphabet indices."""

    __slots__ = ("index", "combo", "tier", "gate", "seed")

    def __init__(self, index, combo, tier, gate, seed):
        self.index = index
        self.combo = combo
        self.tier = tier
        self.gate = gate
        self.seed = seed


class ScopeEnum:
    """The scope's enumerator: alphabet, bijective codec, symmetry
    reduction, and scenario materialization."""

    def __init__(self, scope: McScope):
        self.scope = scope
        self.alphabet = episode_alphabet(scope)
        self.m = len(self.alphabet)
        self.n_combos = n_combos(self.m, scope.max_episodes)
        self.n_tiers = len(scope.knob_tiers)
        self.n_gates = len(scope.gate_tiers)
        self.n_seeds = len(scope.seeds)
        self.total = self.n_combos * self.n_tiers * self.n_gates * self.n_seeds
        self._index_of = {
            _table_key(e, scope.n_nodes): i
            for i, e in enumerate(self.alphabet)
        }
        self._perms = self._node_perms() if scope.symmetry_reduction else []
        if self._perms:
            self._check_closure()
        self.reduced = self._reduced_indices()

    # -- codec --

    def decode(self, index: int) -> Scenario:
        if not 0 <= index < self.total:
            raise IndexError(
                f"scenario index {index} outside [0, {self.total})"
            )
        r, seed = divmod(index, self.n_seeds)
        r, gate = divmod(r, self.n_gates)
        cr, tier = divmod(r, self.n_tiers)
        combo = combo_unrank(cr, self.m, self.scope.max_episodes)
        return Scenario(index, combo, tier, gate, seed)

    def encode(self, sc: Scenario) -> int:
        cr = combo_rank(sc.combo, self.m, self.scope.max_episodes)
        return (
            (cr * self.n_tiers + sc.tier) * self.n_gates + sc.gate
        ) * self.n_seeds + sc.seed

    # -- symmetry --

    def _node_perms(self):
        movable = list(range(self.scope.proposers, self.scope.n_nodes))
        perms = []
        for p in permutations(movable):
            if tuple(movable) == p:
                continue  # identity adds nothing to the orbit min
            perm = {i: i for i in range(self.scope.proposers)}
            perm.update(dict(zip(movable, p)))
            perms.append(perm)
        return perms

    def _check_closure(self) -> None:
        # the alphabet must be closed under the movable-node group, or
        # canonicalization would map a scenario outside the scope
        for i, e in enumerate(self.alphabet):
            for perm in self._perms:
                pe = _permute_episode(e, perm)
                if _table_key(pe, self.scope.n_nodes) not in self._index_of:
                    raise ScopeError(
                        f"alphabet not closed under node-permutation "
                        f"symmetry: letter {i} ({e.kind}[{e.t0},{e.t1})) "
                        "permutes outside the scope — enumerate the "
                        "full structure orbit or set "
                        "symmetry_reduction: false"
                    )

    def canon_combo(self, combo: tuple[int, ...]) -> tuple[int, ...]:
        """The combo's canonical orbit representative: the
        lexicographically-least index tuple over all movable-node
        permutations (idempotent — pinned by test)."""
        if not self._perms:
            return tuple(combo)
        best = tuple(combo)
        for perm in self._perms:
            mapped = tuple(sorted(
                self._index_of[
                    _table_key(
                        _permute_episode(self.alphabet[i], perm),
                        self.scope.n_nodes,
                    )
                ]
                for i in combo
            ))
            if mapped < best:
                best = mapped
        return best

    def combo_feasible(self, combo: tuple[int, ...]) -> bool:
        """A combo is dispatchable iff its scheduled crash points stay
        within the fail-stop minority cap ``(n_nodes - 1) // 2`` —
        beyond it no quorum survives and liveness is vacuously
        unjudgeable (the same cap the i.i.d. crash injection
        enforces), so those combos are excluded from the scenario set
        rather than reported as fake wedges."""
        crashed: set[int] = set()
        for i in combo:
            e = self.alphabet[i]
            if e.kind == "crash":
                crashed.update(e.nodes)
        return len(crashed) <= (self.scope.n_nodes - 1) // 2

    def _reduced_indices(self) -> list[int]:
        """Full-codec indices of the dispatched scenarios, increasing:
        canonical under the movable-node group (when reduction is on)
        AND feasible under the crash minority cap."""
        per_combo = self.n_tiers * self.n_gates * self.n_seeds
        out = []
        for cr in range(self.n_combos):
            combo = combo_unrank(cr, self.m, self.scope.max_episodes)
            if not self.combo_feasible(combo):
                continue
            if self._perms and self.canon_combo(combo) != combo:
                continue
            base = cr * per_combo
            out.extend(range(base, base + per_combo))
        return out

    # -- materialization --

    def schedule_of(self, sc: Scenario) -> fltm.FaultSchedule | None:
        if not sc.combo:
            return None
        return fltm.FaultSchedule(tuple(self.alphabet[i] for i in sc.combo))

    def faults_of(self, sc: Scenario) -> FaultConfig:
        return FaultConfig(**self.scope.knob_tiers[sc.tier])

    def describe(self, sc: Scenario) -> dict:
        """JSON-ready scenario description for counterexample
        reports."""
        sched = self.schedule_of(sc)
        return {
            "index": sc.index,
            "combo": list(sc.combo),
            "episodes": sched.to_dict()["episodes"] if sched else [],
            "knob_tier": dict(self.scope.knob_tiers[sc.tier]),
            "gates": bool(self.scope.gate_tiers[sc.gate]),
            "seed": int(self.scope.seeds[sc.seed]),
        }


# ---------------- chunked dispatch ----------------

# The padding rule is shared with the greedy shrinker's batched
# candidate evaluator (harness/shrink._runtime_batch_eval) and lives
# in its own stdlib-only module so the shrinker's replay-critical
# import closure never reaches this module's CLI machinery.
from tpu_paxos.analysis.chunking import chunk_pad  # noqa: E402


# jax.monitoring has no listener-removal API (see stress._fleet_census)
# — one module-level census, reused across runs.
_mc_census = None


def run_scope(
    scope: McScope,
    triage_dir: str | None = None,
    verbose: bool = True,
    max_counterexamples: int = 8,
    chunk_limit: int | None = None,
) -> dict:
    """Enumerate and dispatch the scope; returns the JSON-ready
    summary (verdict bits, compile counts, counterexamples).
    ``chunk_limit`` bounds the dispatched chunks (the slow-tier smoke
    over the full scope checks a verdict-bit PREFIX against the
    pinned certificate without paying the whole sweep), and the sweep
    stops early once ``max_counterexamples`` have been collected
    (wedged lanes burn the whole watchdog budget, so certifying a
    known-red scope is wasted work — an early-stopped run is never
    pinnable)."""
    import jax

    from tpu_paxos.analysis import tracecount
    from tpu_paxos.analysis import triage as triage_mod
    from tpu_paxos.fleet import envelope as env
    from tpu_paxos.fleet import runner as frun
    from tpu_paxos.harness import shrink as shr
    from tpu_paxos.harness import stress as strs
    from tpu_paxos.utils import log as logm

    logger = logm.get_logger(
        "mc", logm.parse_level("INFO" if verbose else "WARN")
    )
    enum = ScopeEnum(scope)
    wl_rng = np.random.default_rng(scope.workload_seed)
    workload, gates, chains = strs._workload(
        scope.proposers, wl_rng, n_ids=scope.n_ids, n_free=scope.n_free
    )
    cfg = SimConfig(
        n_nodes=scope.n_nodes,
        n_instances=2 * sum(len(w) for w in workload),
        proposers=tuple(range(scope.proposers)),
        seed=0,
        max_rounds=scope.max_rounds,
    )
    # Shared envelope: the episode capacity floors at the fleet
    # default so the shrinker's candidate evaluator lands on the SAME
    # envelope key (capacity is decision-log-neutral), and the
    # recorder is armed for the same reason (it is decision-log-
    # neutral and the whole runtime triage stack arms it).
    runner = env.runner_for(
        cfg, workload, gates,
        max_episodes=max(scope.max_episodes, frun.MAX_EPISODES),
        telemetry=True,
    )
    global _mc_census
    if _mc_census is None:
        _mc_census = tracecount.CompileCensus()
    census = _mc_census.start()
    all_chunks = chunk_pad(enum.reduced, scope.chunk_lanes)
    chunks = all_chunks[:chunk_limit] if chunk_limit else all_chunks
    nibbles: list[str] = []
    compiles_per_chunk: list[int] = []
    counterexamples: list[dict] = []
    anomalies: list[dict] = []
    lanes_total = 0
    seconds = 0.0
    try:
        for ci, (chunk, n_real) in enumerate(chunks):
            scenarios = [enum.decode(i) for i in chunk]
            before = census.engine_counts.get("fleet", 0)
            rep = runner.run(
                [scope.seeds[sc.seed] for sc in scenarios],
                [enum.schedule_of(sc) for sc in scenarios],
                workloads=[
                    (workload, gates if scope.gate_tiers[sc.gate] else None)
                    for sc in scenarios
                ],
                knobs=[enum.faults_of(sc) for sc in scenarios],
            )
            compiles_per_chunk.append(
                census.engine_counts.get("fleet", 0) - before
            )
            lanes_total += n_real
            seconds += rep.seconds
            for li in range(n_real):
                v = rep.verdict
                ok, ag = bool(v.ok[li]), bool(v.agreement[li])
                cov, qu = bool(v.coverage[li]), bool(v.quiescent[li])
                nibbles.append(
                    f"{(ok << 3) | (ag << 2) | (cov << 1) | qu:x}"
                )
                if ok:
                    continue
                sc = scenarios[li]
                gated = bool(scope.gate_tiers[sc.gate])
                case = shr.ReproCase(
                    cfg=rep.lane_cfg(li),
                    workload=workload,
                    gates=gates if gated else None,
                    chains=chains if gated else [],
                )
                _, viol = shr.run_case(case)
                if viol is None:
                    # device subset flagged a lane the full suite
                    # clears — surface the parity break, never hide it
                    anomalies.append({
                        "scenario": enum.describe(sc),
                        "verdict": {"ok": ok, "agreement": ag,
                                    "coverage": cov, "quiescent": qu},
                    })
                    continue
                cx = {
                    "scenario": enum.describe(sc),
                    "violation": viol[:300],
                }
                logger.error(
                    "COUNTEREXAMPLE scenario %d: %s", sc.index, viol
                )
                if triage_dir and len(counterexamples) < max_counterexamples:
                    os.makedirs(triage_dir, exist_ok=True)
                    # deterministic mc_ name: repeat runs overwrite,
                    # and the analysis-dump retention cap applies
                    path = os.path.join(
                        triage_dir,
                        triage_mod.dump_name(
                            "mc", f"scenario_{sc.index}", "json"
                        ),
                    )
                    try:
                        art = shr.triage(case, path, logger=logger)
                        cx["artifact"] = path
                        cx["shrink_seconds"] = art.get("shrink_seconds")
                        triage_mod.prune(triage_dir)
                    except Exception as te:  # triage must never mask a find
                        cx["triage_error"] = str(te)[:300]
                counterexamples.append(cx)
            if verbose and (ci % 8 == 0 or ci == len(chunks) - 1):
                logger.info(
                    "chunk %d/%d: %d scenarios judged, %d "
                    "counterexamples (%.1f lanes/sec)",
                    ci + 1, len(chunks), lanes_total,
                    len(counterexamples), rep.lanes_per_sec,
                )
            if len(counterexamples) >= max_counterexamples:
                logger.error(
                    "counterexample budget (%d) reached after chunk "
                    "%d/%d; stopping early", max_counterexamples,
                    ci + 1, len(chunks),
                )
                chunks = chunks[:ci + 1]
                break
    finally:
        census.stop()
    bits = "".join(nibbles)
    return {
        "metric": "modelcheck",
        "backend": jax.default_backend(),
        "scope_sha256": scope.sha256(),
        "alphabet": enum.m,
        "combos": enum.n_combos,
        "scenarios_full": enum.total,
        "scenarios_reduced": len(enum.reduced),
        "chunk_lanes": scope.chunk_lanes,
        "chunks": len(all_chunks),
        "chunks_run": len(chunks),
        "lanes_judged": lanes_total,
        "lanes_per_sec": round(lanes_total / max(seconds, 1e-9), 2),
        "compiles_per_chunk": compiles_per_chunk,
        "verdict_bits": bits,
        "verdict_bits_sha256": hashlib.sha256(bits.encode()).hexdigest(),
        "counterexamples": counterexamples,
        "anomalies": anomalies,
        "seeded_wedge": _seeded_wedge_flag(),
        "ok": not counterexamples and not anomalies,
    }


def _seeded_wedge_flag() -> str:
    from tpu_paxos.core import sim as simm

    return simm.seeded_wedge()


# ---------------- scope certificate ----------------

#: Certificate fields that must match exactly on every backend (the
#: scope's shape); verdict bits are additionally compared on the
#: pinning backend only, like the flops/HLO pins.
_CERT_SHAPE_FIELDS = (
    "scope_sha256", "alphabet", "combos", "scenarios_full",
    "scenarios_reduced", "chunk_lanes", "chunks",
)


def make_certificate(summary: dict) -> dict:
    """The pinnable subset of a FULL run's summary."""
    if summary["chunks_run"] != summary["chunks"]:
        raise ValueError(
            "cannot certify a chunk-limited run: the verdict bits "
            "must cover the whole reduced scope"
        )
    return {
        "version": 1,
        "backend": summary["backend"],
        **{f: summary[f] for f in _CERT_SHAPE_FIELDS},
        "verdict_bits": summary["verdict_bits"],
        "verdict_bits_sha256": summary["verdict_bits_sha256"],
        "counterexamples": len(summary["counterexamples"]),
    }


def check_certificate(pinned: dict, summary: dict, enum: ScopeEnum) -> list[str]:
    """Compare a run against the pinned certificate; returns failure
    strings (empty = pass).  A verdict drift names the first diverging
    scenario's full-codec index."""
    fails = []
    for f in _CERT_SHAPE_FIELDS:
        if pinned.get(f) != summary[f]:
            fails.append(
                f"certificate field {f!r} drifted: pinned "
                f"{pinned.get(f)!r} vs measured {summary[f]!r} "
                "(scope edits re-pin with TPU_PAXOS_MC_PIN=1 make mc)"
            )
    if fails:
        return fails  # verdict bits are meaningless across scope drift
    if pinned.get("backend") != summary["backend"]:
        return fails  # verdict pins are backend-gated
    old, new = pinned.get("verdict_bits", ""), summary["verdict_bits"]
    limit = min(len(old), len(new))
    for i in range(limit):
        if old[i] != new[i]:
            idx = enum.reduced[i]
            fails.append(
                f"verdict drifted at scenario index {idx} (reduced "
                f"position {i}): pinned nibble {old[i]} vs measured "
                f"{new[i]} — a new counterexample or an engine "
                "behavior change"
            )
            break
    else:
        if len(old) != len(new) and summary["chunks_run"] == summary["chunks"]:
            fails.append(
                f"verdict bit count drifted: pinned {len(old)} vs "
                f"measured {len(new)}"
            )
    return fails


def load_certificates(path: str = DEFAULT_CERT) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except OSError:
        return {}
    except json.JSONDecodeError as e:
        raise ScopeError(f"invalid certificate JSON: {e}") from None


def save_certificate(path: str, scope_name: str, cert: dict) -> None:
    certs = load_certificates(path)
    certs[scope_name] = cert
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(certs, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


# ---------------- CLI ----------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_paxos mc",
        description="exhaustive bounded model checking: enumerate "
        "every fault scenario of a declared scope, dispatch them as "
        "device-batched fleet lanes, shrink any counterexample, and "
        "gate on the pinned scope certificate",
    )
    ap.add_argument("--scope", default="quick",
                    help="comma-separated scope name(s) in the scope "
                    "file (default: quick); scopes sharing an engine "
                    "envelope share its compile within one invocation")
    ap.add_argument("--scope-file", default=DEFAULT_SCOPE)
    ap.add_argument("--cert-file", default=DEFAULT_CERT)
    ap.add_argument("--chunk-limit", type=int, default=0,
                    help="dispatch at most this many chunks (0 = all; "
                    "a limited run is never certified/pinned)")
    ap.add_argument("--triage-dir", type=str, default="",
                    help="shrink counterexamples into mc_scenario_<i> "
                    "repro artifacts here")
    ap.add_argument("--max-counterexamples", type=int, default=8)
    ap.add_argument("--backend", choices=("tpu", "cpu", "auto"),
                    default="auto")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--pin", action="store_true",
                    help="re-pin the scope certificate from this run "
                    f"(or set {PIN_ENV}=1)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    from tpu_paxos.__main__ import _select_backend

    _select_backend(args.backend)
    names = [n for n in args.scope.split(",") if n]
    try:
        scopes = load_scopes(args.scope_file)
        plan = []
        for name in names:
            if name not in scopes:
                raise ScopeError(
                    f"scope {name!r} not in {args.scope_file} "
                    f"(available: {', '.join(sorted(scopes))})"
                )
            plan.append((name, scopes[name], enum_for(scopes[name])))
        if not plan:
            raise ScopeError("--scope named no scopes")
    except ScopeError as e:
        print(f"mc: {e}", file=sys.stderr)
        return 2
    rc = 0
    for name, scope, enum in plan:
        rc = max(rc, _run_one(name, scope, enum, args))
    return rc


def _run_one(name, scope, enum, args) -> int:
    """Run + certificate-gate one scope (any type); the CLI's exit
    code is the max over the listed scopes."""
    summary = run_for(scope)(
        scope,
        triage_dir=args.triage_dir or None,
        verbose=not args.quiet,
        max_counterexamples=args.max_counterexamples,
        chunk_limit=args.chunk_limit or None,
    )
    summary["scope"] = name
    pin = args.pin or os.environ.get(PIN_ENV, "") == "1"
    full_run = summary["chunks_run"] == summary["chunks"]
    cert_fails: list[str] = []
    if pin:
        if summary["seeded_wedge"]:
            print(
                "mc: refusing to pin with TPU_PAXOS_SEEDED_WEDGE set "
                "— the certificate would enshrine the seeded bug",
                file=sys.stderr,
            )
            return 1
        if not summary["ok"] or not full_run:
            print(
                "mc: refusing to pin a failing or chunk-limited run",
                file=sys.stderr,
            )
            return 1
        save_certificate(
            args.cert_file, name, make_certificate(summary)
        )
        summary["pinned"] = args.cert_file
    else:
        pinned = load_certificates(args.cert_file).get(name)
        if pinned is None:
            cert_fails = [
                f"no pinned certificate for scope {name!r} "
                f"in {args.cert_file}; pin with {PIN_ENV}=1"
            ]
        elif full_run:
            cert_fails = check_certificate(pinned, summary, enum)
        else:
            # chunk-limited smoke: the shape fields plus the verdict
            # PREFIX must agree
            cert_fails = check_certificate(
                dict(pinned,
                     verdict_bits=pinned.get("verdict_bits", "")[
                         : len(summary["verdict_bits"])
                     ]),
                summary, enum,
            )
        summary["certificate_failures"] = cert_fails
    ok = summary["ok"] and not cert_fails
    if args.json:
        print(json.dumps(summary, sort_keys=True))
    else:
        for fail in cert_fails:
            print(f"mc: {fail}", file=sys.stderr)
        status = "SCOPE CLEAN" if ok else "FAILED"
        print(
            f"[mc:{name}] {status} "
            f"({summary['scenarios_reduced']}/{summary['scenarios_full']} "
            f"scenarios post-reduction, {summary['chunks_run']}/"
            f"{summary['chunks']} chunks, "
            f"{len(summary['counterexamples'])} counterexamples, "
            f"compiles/chunk {summary['compiles_per_chunk'][:3]}...)"
        )
    return 0 if ok else 1


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """The mc lane surface: one canonical chunk of a tiny scope,
    decoded through the codec and stacked exactly as run_scope
    dispatches it (runtime schedule tables + knob vectors + per-lane
    gate toggles through the telemetry-armed fleet program).  Covers
    the chunked dispatch build — the op/HLO budgets pin the program
    the model checker actually runs."""
    import jax
    import jax.numpy as jnp

    from tpu_paxos.analysis.registry import AuditEntry
    from tpu_paxos.fleet import runner as frun
    from tpu_paxos.fleet import schedule_table as stm
    from tpu_paxos.harness import stress as strs
    from tpu_paxos.utils import prng

    def build():
        scope = McScope.from_dict({
            "n_nodes": 3, "proposers": 2, "horizon": 12,
            "max_rounds": 64, "intervals": [[2, 8]],
            "kinds": ["pause", "burst"], "pause_set_sizes": [1],
            "burst_rates": [2000], "max_episodes": 2,
            "knob_tiers": [
                {"drop_rate": 500, "crash_rate": 1000, "max_delay": 2},
            ],
            "gate_tiers": [True, False],
            "seeds": [0], "chunk_lanes": 2, "n_ids": 2, "n_free": 2,
        })
        enum = ScopeEnum(scope)
        rng = np.random.default_rng(scope.workload_seed)
        workload, gates, _ = strs._workload(
            scope.proposers, rng, n_ids=scope.n_ids, n_free=scope.n_free
        )
        cfg = SimConfig(
            n_nodes=scope.n_nodes,
            n_instances=2 * sum(len(w) for w in workload),
            proposers=(0, 1),
            seed=0,
            max_rounds=scope.max_rounds,
            faults=FaultConfig(max_delay=2),
        )
        runner = frun.FleetRunner(
            cfg, workload, gates, max_episodes=scope.max_episodes,
            telemetry=True,
        )
        (chunk, _), = chunk_pad(enum.reduced[:2], scope.chunk_lanes)
        scenarios = [enum.decode(i) for i in chunk]
        tabs = jax.tree.map(
            jnp.asarray,
            stm.encode_batch(
                [enum.schedule_of(sc) for sc in scenarios],
                cfg.n_nodes, scope.max_episodes,
            ),
        )
        roots = jnp.stack([
            prng.root_key(scope.seeds[sc.seed]) for sc in scenarios
        ])
        kn, _ = runner._knob_arrays(
            len(scenarios), [enum.faults_of(sc) for sc in scenarios]
        )
        pend, gate, tail, exp, own, _ = runner._queues(
            len(scenarios),
            [(workload, gates if scope.gate_tiers[sc.gate] else None)
             for sc in scenarios],
        )
        states = runner._init(
            jnp.asarray(pend), jnp.asarray(gate), jnp.asarray(tail), roots
        )
        return runner._fn, (
            roots, states, tabs,
            jax.tree.map(jnp.asarray, kn),
            jnp.asarray(exp), jnp.asarray(own),
            jnp.zeros((len(scenarios), cfg.n_nodes), jnp.int32),
        )

    return [
        AuditEntry(
            "mc.run_chunk", build,
            allow=("IR204",),
            why=(
                "the mc chunk body IS core/sim's round_fn under the "
                "fleet vmap — same unique-key compaction sorts as "
                "sim.run_rounds"
            ),
        ),
    ]


if __name__ == "__main__":
    sys.exit(main())
