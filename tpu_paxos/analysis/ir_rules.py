"""IR rule family: trace-time contracts on the engines' jaxprs.

paxlint's AST rules (rules_det/rules_jax) see *source*; these rules
see what JAX actually traced — the layer where a host sync hidden
behind a helper, an accidental float64 widening, or a new cross-shard
collective actually lives.  The checkers walk closed jaxprs (recursing
into ``scan`` / ``while`` / ``cond`` / ``pjit`` / ``shard_map`` /
``pallas_call`` sub-jaxprs) and report findings pinned to a primitive
*path* (``sim.run_rounds/while/scan/convert_element_type``), so a
violation names where in the traced program it sits, not just which
Python file built it.

Rules:

- IR201  host-transfer / callback primitives (``pure_callback``,
         ``io_callback``, ``debug_callback``, ``infeed`` / ``outfeed``,
         ``device_put``...) inside a loop body (``scan`` / ``while``):
         each firing is a per-iteration host round-trip — the
         device-side round loop must stay host-free.
- IR202  dtype widening past the engines' 32-bit lattice: any
         equation output (or constvar) with a 64-bit or complex dtype.
         The engines are int32/int8/bool machines; a float64/int64
         leak changes decision bytes between backends.
- IR203  collectives (``psum`` / ``pmax`` / ``all_gather`` /
         ``ppermute``...) only where the entry declares mesh axes,
         and only on those axes — a new collective in a single-chip
         entry point, or one on an undeclared axis, is cross-replica
         traffic the perf model doesn't know about.
- IR204  ``sort``-class primitives with ``is_stable=False`` in a
         replay-critical entry: unstable sort order is
         backend/version-dependent and can reach decision bytes.
         Waive per entry with ``allow=("IR204",)`` + a reason.
- IR205  constant bloat: a jaxpr const larger than the entry's
         ``const_budget`` — catches a fault table or host array baked
         into the compiled program by accidental closure capture.

Import discipline: the walkers duck-type jaxpr objects (``.eqns``,
``.jaxpr``, ``.aval``) and never import jax — the module stays
importable on jax-less CI images alongside the rest of the analysis
package; only ``jaxpr_audit`` (which must trace) touches jax.
"""

from __future__ import annotations

import dataclasses

RULES = {
    "IR201": "host transfer/callback primitive inside a scanned/while "
             "loop body",
    "IR202": "dtype widening past the 32-bit lattice (float64/int64 "
             "leak)",
    "IR203": "collective primitive outside the entry's declared mesh "
             "axes",
    "IR204": "unstable sort in a replay-critical entry point",
    "IR205": "oversized jaxpr constant (accidentally baked-in host "
             "array)",
}

#: IR201: primitives that move data to/from the host (or call into
#: it).  ``device_put`` inside a traced loop means a host value is
#: re-staged per iteration.
HOST_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback_call", "infeed", "outfeed",
    "debug_print", "device_put",
})

#: IR203: cross-replica communication primitives.  ``axis_index`` is
#: included: it binds the program to a mesh axis even though it moves
#: no data, so it must be declared like the rest.
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "pgather", "reduce_scatter", "axis_index",
    "psum2", "all_gather_invariant",
})

#: IR202: allowed dtype names — the engines' declared lattice.  Keys
#: (uint32 pairs) and float32 intermediates (PRNG uniforms, cost
#: shaping) are legitimate; anything 64-bit or complex is a leak.
DTYPE_LATTICE = frozenset({
    "bool", "int8", "uint8", "int16", "uint16", "int32", "uint32",
    "float32", "float16", "bfloat16", "float8_e4m3fn", "float8_e5m2",
    "key<fry>",  # typed PRNG key aval (uint32 pair underneath)
})

#: Loop-entering primitives: their sub-jaxprs execute once per
#: iteration (a while's cond jaxpr runs every iteration too).
_LOOP_PRIMS = frozenset({"scan", "while"})


@dataclasses.dataclass(frozen=True)
class IRFinding:
    """One IR-level finding, pinned to a primitive path."""

    rule: str
    entry: str  # audit entry name ("sim.run_rounds")
    path: str   # primitive path ("sim.run_rounds/while/scan/convert_element_type")
    message: str
    hint: str

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "entry": self.entry,
            "path": self.path,
            "message": self.message,
            "hint": self.hint,
        }


# ---------------- jaxpr walking (duck-typed) ----------------

def _as_jaxpr(obj):
    """Unwrap ClosedJaxpr -> Jaxpr; pass Jaxpr through; None for
    anything else.  Duck-typed: a ClosedJaxpr has .jaxpr (+ .consts),
    a Jaxpr has .eqns."""
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    if hasattr(obj, "eqns") and hasattr(obj, "invars"):
        return obj
    return None


def sub_jaxprs(eqn):
    """Sub-jaxprs referenced by an equation's params (scan/while
    bodies, cond branches, pjit/shard_map/pallas_call inner jaxprs),
    in deterministic param order."""
    out = []
    for key in sorted(eqn.params):
        val = eqn.params[key]
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            j = _as_jaxpr(v)
            if j is not None:
                out.append(j)
    return out


def iter_eqns(jaxpr, path: str, in_loop: bool = False):
    """Yield ``(eqn, path, in_loop)`` over a jaxpr and every nested
    sub-jaxpr.  ``path`` accumulates primitive names; ``in_loop`` is
    True once inside a scan/while sub-jaxpr (inherited downward)."""
    j = _as_jaxpr(jaxpr)
    if j is None:
        return
    for eqn in j.eqns:
        name = eqn.primitive.name
        yield eqn, path, in_loop
        child_loop = in_loop or (name in _LOOP_PRIMS)
        for sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub, f"{path}/{name}", child_loop)


def iter_consts(jaxpr, path: str):
    """Yield ``(const, path)`` for the top-level consts and every
    nested ClosedJaxpr's consts."""
    consts = getattr(jaxpr, "consts", None)
    if consts:
        for c in consts:
            yield c, path
    j = _as_jaxpr(jaxpr)
    if j is None:
        return
    for eqn in j.eqns:
        name = eqn.primitive.name
        for key in sorted(eqn.params):
            val = eqn.params[key]
            vals = val if isinstance(val, (tuple, list)) else (val,)
            for v in vals:
                if _as_jaxpr(v) is not None:
                    yield from iter_consts(v, f"{path}/{name}")


def _collective_axes(eqn) -> tuple[str, ...]:
    """Named (string) axes a collective reduces/operates over.
    Positional-int axes (vmap-internal) don't bind a mesh axis and
    are ignored."""
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if axes is None:
        axes = ()
    if isinstance(axes, str):
        axes = (axes,)
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _nbytes(const) -> int:
    nb = getattr(const, "nbytes", None)
    if nb is not None:
        return int(nb)
    size = getattr(const, "size", None)
    itemsize = getattr(const, "itemsize", None)
    if size is not None and itemsize is not None:
        return int(size) * int(itemsize)
    return 0


def _dtype_name(aval) -> str | None:
    dt = getattr(aval, "dtype", None)
    return None if dt is None else str(dt)


# ---------------- the checker ----------------

def check_entry(entry, closed_jaxpr) -> list[IRFinding]:
    """Run every IR rule over one entry's closed jaxpr.  ``entry`` is
    an :class:`analysis.registry.AuditEntry`; findings waived by its
    ``allow`` tuple are dropped (the trace-time pragma)."""
    findings: list[IRFinding] = []
    name = entry.name
    declared = set(entry.mesh_axes)

    for eqn, path, in_loop in iter_eqns(closed_jaxpr, name):
        prim = eqn.primitive.name
        ppath = f"{path}/{prim}"
        if prim in HOST_PRIMS and in_loop:
            findings.append(IRFinding(
                "IR201", name, ppath,
                f"host transfer/callback `{prim}` inside a traced loop "
                "body — one host round-trip per simulated round",
                "hoist the transfer out of the loop or express it as "
                "device-side state; waive per entry with "
                "allow=('IR201',) and a reason",
            ))
        if prim in COLLECTIVE_PRIMS:
            axes = _collective_axes(eqn)
            bad = [a for a in axes if a not in declared]
            if not declared:
                findings.append(IRFinding(
                    "IR203", name, ppath,
                    f"collective `{prim}` over axes {axes or '()'} in "
                    "an entry point that declares no mesh axes",
                    "collectives belong to the parallel/ entry points; "
                    "declare mesh_axes on the AuditEntry if this "
                    "surface is genuinely sharded",
                ))
            elif bad:
                findings.append(IRFinding(
                    "IR203", name, ppath,
                    f"collective `{prim}` reduces over undeclared "
                    f"axes {tuple(bad)} (declared: "
                    f"{tuple(sorted(declared))})",
                    "add the axis to the entry's mesh_axes if the new "
                    "traffic is intentional — it changes the ICI/DCN "
                    "cost model",
                ))
        if prim == "sort" and not eqn.params.get("is_stable", False):
            findings.append(IRFinding(
                "IR204", name, ppath,
                "unstable `sort` in a replay-critical entry — tie "
                "order is backend/version-dependent and can reach "
                "decision bytes",
                "pass is_stable=True (jnp.sort(kind='stable')), or "
                "waive per entry with allow=('IR204',) and a proof "
                "ties are impossible",
            ))
        for v in eqn.outvars:
            dn = _dtype_name(getattr(v, "aval", None))
            if dn is not None and dn not in DTYPE_LATTICE:
                findings.append(IRFinding(
                    "IR202", name, ppath,
                    f"`{prim}` produces dtype {dn} — outside the "
                    "32-bit lattice the engines declare",
                    "find the widening input (Python int/float, x64 "
                    "flag, np.int64 index) and cast at the source; "
                    "64-bit values change decision bytes across "
                    "backends",
                ))
                break  # one finding per equation is enough

    for const, path in iter_consts(closed_jaxpr, name):
        nb = _nbytes(const)
        if nb > entry.const_budget:
            shape = tuple(getattr(const, "shape", ()))
            dt = getattr(const, "dtype", "?")
            findings.append(IRFinding(
                "IR205", name, f"{path}/<const>",
                f"jaxpr constant of {nb} bytes ({dt}{list(shape)}) "
                f"exceeds the entry's const budget "
                f"({entry.const_budget})",
                "a host array was baked in by closure capture — pass "
                "it as an argument, or raise const_budget on the "
                "AuditEntry if the table is intentional",
            ))
        dn = _dtype_name(const) or str(
            getattr(const, "dtype", None) or ""
        )
        if dn and dn not in DTYPE_LATTICE:
            findings.append(IRFinding(
                "IR202", name, f"{path}/<const>",
                f"jaxpr constant has dtype {dn} — outside the 32-bit "
                "lattice",
                "cast the captured table to an allowed dtype at its "
                "definition site",
            ))

    waived = set(entry.allow)
    return [f for f in findings if f.rule not in waived]
