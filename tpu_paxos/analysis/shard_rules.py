"""shard-audit contracts + budget/certificate machinery (pure stdlib).

The SH3xx rule documentation and every piece of the fifth tier that
can be judged without jax: the collective census over an opcode
histogram (``hlo_norm.opcode_histogram`` output — raw dumps re-judge
in a jax-free image), the per-mesh replication/collective budget
(``analysis/shard_budget.json``), and the cross-mesh parity
certificate (``analysis/shard_certificate.json``).  The lowering /
compiling / end-to-end-running half lives in ``shard_audit.py``,
which owns the jax dependency; the committed partition-rule table
itself is ``parallel/partition_rules.py`` (its matching logic is also
jax-free on purpose).

Budget semantics differ from the hlo tier where sharding makes the
looser contract wrong:

- **Collective counts are pinned EXACT, per mesh shape.**  Headroom
  on a collective count would let an accidental extra all-reduce ride
  inside the slack — but the committed SPMD story is "lanes are
  independent; the only collectives are the sharded fast path's pmax
  and psum", so the census is an equality, and a mismatch in EITHER
  direction fails naming (entry, mesh, opcode).  A collective that
  disappears is as suspicious as one that appears: it usually means
  the tile stopped spanning the mesh.
- **Per-device bytes get headroom** (allocator jitter is real), with
  the hlo tier's looser memory pair.  The budget is per mesh shape:
  the whole point of the tile is that per-device bytes FALL as the
  mesh grows, and a flat curve (replication creep) must breach the
  larger shapes' ceilings even when the 1-device number still fits.

Certificates mirror ``mc_certificate.json``: the pin is the 1-device
run (vmap semantics, no mesh), every other shape must reproduce it
bitwise — per-lane verdict nibbles and per-lane decision-log sha256 —
and drift fails naming the FIRST diverging (entry, mesh, lane).
"""

from __future__ import annotations

import json
import os

#: Rule ids -> one-line contracts (``--rules`` output; the long form
#: is the shard_audit module doc).
RULES = {
    "SH301": "every array leaf of every registered stacked-state "
             "pytree matches a committed partition rule "
             "(parallel/partition_rules.py), and every rule matches "
             "some leaf — unmatched leaves and stale rules fail by "
             "pytree path / rule index",
    "SH302": "per-device peak bytes of every shard_build entry stay "
             "under the per-mesh-shape ceilings pinned in "
             "analysis/shard_budget.json — replication creep breaches "
             "the large-mesh ceilings first",
    "SH303": "the collective census (all-reduce / all-gather / "
             "collective-permute / reduce-scatter) of every compiled "
             "entry equals the per-mesh-shape counts pinned in "
             "analysis/shard_budget.json — exact, both directions",
    "SH304": "per-lane verdict nibbles + decision-log sha256 of the "
             "fleet drivers are bitwise identical across every mesh "
             "shape and match analysis/shard_certificate.json — drift "
             "names the first diverging (entry, mesh, lane)",
}

#: HLO collective families the census counts.  Async pairs fold into
#: the base family via their ``-start`` half only (``-done`` retires
#: the same collective; counting both would double it).
COLLECTIVE_FAMILIES = (
    "all-gather", "all-reduce", "collective-permute", "reduce-scatter",
)

DEFAULT_BUDGET = os.path.join(
    os.path.dirname(__file__), "shard_budget.json"
)
DEFAULT_CERT = os.path.join(
    os.path.dirname(__file__), "shard_certificate.json"
)

PIN_ENV = "TPU_PAXOS_SHARD_PIN"
BUDGET_PIN_ENV = "TPU_PAXOS_SHARD_BUDGET_PIN"

#: Seeded-regression switch (the PR-7 / modelcheck recall proof): each
#: value arms ONE deliberate breach so the tier's failure path — and
#: its naming — is tested, not assumed.  Pinning refuses while armed.
WEDGE_ENV = "TPU_PAXOS_SHARD_WEDGE"
WEDGES = ("unruled-leaf", "undeclared-collective", "parity-fork")

#: Memory-ceiling caps (hlo tier's looser pair — allocator jitter).
MEM_HEADROOM, MEM_SLACK = 0.3, 4096


def collective_census(hist: dict) -> dict:
    """Collective counts per family from an opcode histogram — sync
    form plus the ``-start`` half of async pairs (see module doc)."""
    out = {fam: 0 for fam in COLLECTIVE_FAMILIES}
    for fam in COLLECTIVE_FAMILIES:
        out[fam] = int(hist.get(fam, 0)) + int(hist.get(fam + "-start", 0))
    return out


# ---------------- budget (SH302 + SH303) ----------------

def load_budget(path: str = DEFAULT_BUDGET) -> dict:
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def save_budget(measured: dict, path: str, backend: str,
                jax_version: str, keep: dict | None = None) -> dict:
    """Pin the measured grid: collective counts exact, bytes with
    headroom.  ``measured`` is ``{entry: {mesh: {"bytes_per_device",
    "collectives"}}}`` with string mesh keys; ``keep`` preserves
    entries a scoped pin did not trace."""
    entries = dict(keep or {})
    for name, per_mesh in sorted(measured.items()):
        entries[name] = {
            mesh: {
                "bytes_per_device": (
                    int(m["bytes_per_device"] * (1 + MEM_HEADROOM))
                    + MEM_SLACK
                ),
                "collectives": dict(sorted(m["collectives"].items())),
            }
            for mesh, m in sorted(per_mesh.items(), key=lambda kv: int(kv[0]))
        }
    data = {
        "version": 1,
        "backend": backend,
        "jax": jax_version,
        "mem_headroom": MEM_HEADROOM,
        "mem_slack": MEM_SLACK,
        "entries": dict(sorted(entries.items())),
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return data


def check_budget(measured: dict, budget: dict, backend: str,
                 full_grid: bool) -> tuple[list[dict], list[str], bool]:
    """-> (violations, stale, enforced).  Compiled text and allocator
    numbers are backend-shaped, so nothing is enforced against a
    budget pinned on a different backend (enforced=False) — the hlo
    tier's gate.  On the pinning backend, unpinned (entry, mesh) cells
    are violations (nothing stays uncapped), and pinned cells the run
    no longer measures are stale — only when the run covered the full
    registry AND the full mesh grid (``full_grid``)."""
    entries: dict = budget.get("entries", {})
    if budget and budget.get("backend") != backend:
        return [], [], False
    violations: list[dict] = []
    for name in sorted(measured):
        pinned_meshes = entries.get(name, {})
        for mesh in sorted(measured[name], key=int):
            m = measured[name][mesh]
            caps = pinned_meshes.get(mesh)
            if caps is None:
                violations.append({
                    "entry": name, "mesh": int(mesh), "key": "budget",
                    "measured": None, "cap": None,
                    "detail": (
                        f"entry {name} mesh {mesh} has no pinned shard "
                        f"budget — re-pin shard_budget.json "
                        f"({BUDGET_PIN_ENV}=1)"
                    ),
                })
                continue
            got_b = int(m["bytes_per_device"])
            cap_b = int(caps.get("bytes_per_device", 0))
            if got_b > cap_b:
                violations.append({
                    "entry": name, "mesh": int(mesh),
                    "key": "bytes_per_device",
                    "measured": got_b, "cap": cap_b,
                    "detail": (
                        f"entry {name} mesh {mesh}: {got_b} bytes per "
                        f"device > ceiling {cap_b} (+{got_b - cap_b}) "
                        "— replication creep: state that should split "
                        "over the mesh is being copied to every "
                        "device; if intentional, re-pin "
                        f"shard_budget.json ({BUDGET_PIN_ENV}=1)"
                    ),
                })
            want_c = caps.get("collectives", {})
            got_c = m["collectives"]
            for fam in COLLECTIVE_FAMILIES:
                w, g = int(want_c.get(fam, 0)), int(got_c.get(fam, 0))
                if w != g:
                    violations.append({
                        "entry": name, "mesh": int(mesh), "key": fam,
                        "measured": g, "cap": w,
                        "detail": (
                            f"entry {name} mesh {mesh}: {g} {fam} "
                            f"in the compiled module, budget declares "
                            f"exactly {w} — an undeclared collective "
                            "(or a vanished one: the tile may have "
                            "stopped spanning the mesh); if "
                            "intentional, re-pin shard_budget.json "
                            f"({BUDGET_PIN_ENV}=1)"
                        ),
                    })
    stale: list[str] = []
    if full_grid:
        for name in sorted(entries):
            for mesh in sorted(entries[name], key=int):
                if mesh not in measured.get(name, {}):
                    stale.append(f"{name}@mesh{mesh}")
    return violations, stale, True


# ---------------- certificate (SH304) ----------------

def load_certificate(path: str = DEFAULT_CERT) -> dict:
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def save_certificate(entries: dict, path: str, backend: str,
                     jax_version: str) -> dict:
    """Pin per-entry ``{"verdicts", "lane_logs"}`` from the 1-device
    canonical run (the vmap semantics every mesh must reproduce)."""
    data = {
        "version": 1,
        "backend": backend,
        "jax": jax_version,
        "entries": {
            name: {
                "verdicts": e["verdicts"],
                "lane_logs": list(e["lane_logs"]),
            }
            for name, e in sorted(entries.items())
        },
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return data


def first_divergence(ref: dict, got: dict):
    """First lane where two parity results disagree ->
    ``(lane, detail)`` or ``None``.  Lane order IS significance order:
    the first diverging lane names the reproduction target."""
    rv, gv = ref["verdicts"], got["verdicts"]
    rl, gl = list(ref["lane_logs"]), list(got["lane_logs"])
    n = max(len(rv), len(gv), len(rl), len(gl))
    for i in range(n):
        a = rv[i] if i < len(rv) else "?"
        b = gv[i] if i < len(gv) else "?"
        if a != b:
            return i, f"verdict nibble {a!r} != {b!r}"
        la = rl[i] if i < len(rl) else "?"
        lb = gl[i] if i < len(gl) else "?"
        if la != lb:
            return i, (
                f"decision-log sha256 {la[:12]}… != {lb[:12]}…"
            )
    return None


def check_certificate(pinned: dict, results: dict,
                      full: bool) -> list[dict]:
    """SH304 judgment.  ``results`` is ``{entry: {mesh:
    {"verdicts", "lane_logs"}}}`` (string mesh keys, "1" always
    present).  Two comparisons per entry: every mesh against its OWN
    mesh-1 run (mesh invariance — judged even with nothing pinned),
    then mesh-1 against the pinned certificate (history).  Failures
    name the first diverging (entry, mesh, lane)."""
    failures: list[dict] = []
    pe: dict = pinned.get("entries", {})
    for name in sorted(results):
        per_mesh = results[name]
        ref = per_mesh.get("1")
        if ref is None:
            continue
        for mesh in sorted(per_mesh, key=int):
            if mesh == "1":
                continue
            div = first_divergence(ref, per_mesh[mesh])
            if div is not None:
                lane, detail = div
                failures.append({
                    "entry": name, "mesh": int(mesh), "lane": lane,
                    "detail": (
                        f"entry {name}: mesh {mesh} diverges from the "
                        f"1-device run at lane {lane} ({detail}) — "
                        "the tile changed lane semantics; lanes must "
                        "be mesh-invariant"
                    ),
                })
        cert = pe.get(name)
        if cert is None:
            failures.append({
                "entry": name, "mesh": 1, "lane": None,
                "detail": (
                    f"entry {name} has no pinned parity certificate — "
                    f"re-pin shard_certificate.json ({PIN_ENV}=1)"
                ),
            })
            continue
        div = first_divergence(cert, ref)
        if div is not None:
            lane, detail = div
            failures.append({
                "entry": name, "mesh": 1, "lane": lane,
                "detail": (
                    f"entry {name}: the 1-device run drifted from the "
                    f"pinned certificate at lane {lane} ({detail}) — "
                    "lane behavior changed; if intentional, re-pin "
                    f"shard_certificate.json ({PIN_ENV}=1)"
                ),
            })
    if full:
        for name in sorted(set(pe) - set(results)):
            failures.append({
                "entry": name, "mesh": None, "lane": None,
                "detail": (
                    f"certificate entry {name} is pinned but no "
                    "registered entry produces it — stale pin; re-pin "
                    f"shard_certificate.json ({PIN_ENV}=1)"
                ),
            })
    return failures
