"""Compile-census regression guard: count XLA compilations per test
module against a pinned budget.

The static JAX rules (rules_jax.py) catch retrace *patterns*; this is
their runtime shadow: every actual XLA compilation during the tier-1
suite is counted via ``jax.monitoring``'s
``/jax/core/compile/backend_compile_duration`` event and attributed
to the test module that triggered it.  An accidental retrace storm —
a jit cache key that started varying per round, a shape that stopped
being static — shows up as a module blowing its pinned budget, and CI
fails naming the culprit module instead of just getting slower.

Budgets live in ``compile_budget.json`` next to this file, measured
from a full tier-1 run and pinned with headroom (compilation counts
are deterministic for a fixed suite order — pytest's default
collection order is deterministic, no ordering plugin is installed,
and the tier-1 driver additionally passes ``-p no:randomly``; if a
test-ordering plugin is ever adopted, re-pin and disable it for
census runs).  Budgets are per test module
because in-process jit caches are shared: a module's count depends on
what compiled before it, so they are only comparable for full-suite
runs.  Enforcement therefore triggers only when every budgeted module
was visited (or when forced via ``TPU_PAXOS_COMPILE_CENSUS=1``);
``TPU_PAXOS_COMPILE_CENSUS=0`` disables the guard entirely.

Wiring (tests/conftest.py): a session-long ``CompileCensus`` is
started at collection time, ``pytest_runtest_setup`` labels counts
with the running test's module, and ``pytest_sessionfinish`` enforces
the budget, failing the run with a named culprit.  The ``compile_census``
fixture exposes the active census to tests.

Import discipline: this module only imports jax inside
``CompileCensus.start`` — ``tpu_paxos.analysis`` stays importable
without jax.
"""

from __future__ import annotations

import contextlib
import json
import os

#: The jax.monitoring event recorded once per backend (XLA) compile.
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: Our own monitoring event, recorded on every engine-scope entry so
#: external jax.monitoring backends see the scope boundaries too.
ENGINE_SCOPE_EVENT = "/tpu_paxos/engine_scope"

#: Label for compiles outside any engine scope (test scaffolding,
#: fixture setup, host-side helpers).
NO_ENGINE = "<outside-engine>"

#: Active engine-scope stack (innermost last).  A plain module-level
#: list, not a contextvar: engines drive compiles synchronously on
#: the calling thread, and the census reads it from a synchronous
#: monitoring callback.
_ENGINE_STACK: list[str] = []


@contextlib.contextmanager
def engine_scope(name: str):
    """Attribute XLA compiles inside the block to engine ``name``.

    Engine entry points (core/sim.run_state, membership run_rounds,
    the sharded runners) wrap their jitted calls in this scope, so the
    compile census reports compiles per *engine* as well as per test
    module — a retrace storm then names both the module that triggered
    it and the engine whose cache key regressed.  Also records a
    jax.monitoring event per entry (only when jax is already loaded —
    the scope itself must stay usable, and cheap, without jax)."""
    import sys

    _ENGINE_STACK.append(name)
    try:
        mon = sys.modules.get("jax.monitoring")
        if mon is not None:
            try:
                mon.record_event(ENGINE_SCOPE_EVENT, engine=name)
            except TypeError:  # older record_event: no kwargs
                try:
                    mon.record_event(ENGINE_SCOPE_EVENT)
                except Exception:
                    pass
            except Exception:
                # a third-party monitoring listener must never break
                # (or mislabel — the finally below pops) an engine run
                pass
        yield
    finally:
        _ENGINE_STACK.pop()


def current_engine() -> str:
    return _ENGINE_STACK[-1] if _ENGINE_STACK else NO_ENGINE

DEFAULT_BUDGET = os.path.join(
    os.path.dirname(__file__), "compile_budget.json"
)

#: Label for compilations outside any test (collection, conftest
#: imports, fixtures of the first test's module setup).  Unbudgeted.
STARTUP = "<startup>"


class CompileCensus:
    """Counts XLA compilations, attributed to a caller-set label.

    jax.monitoring has no listener-removal API (0.4.x), so ``stop()``
    deactivates the callback instead of unregistering it; a census
    object registers at most once."""

    def __init__(self):
        self.counts: dict[str, int] = {}
        self.visited: set[str] = set()  # labels seen, even with 0 compiles
        #: compiles per engine scope (engine_scope()), the per-engine
        #: attribution axis — orthogonal to the per-module counts
        self.engine_counts: dict[str, int] = {}
        self._label = STARTUP
        self._active = False
        self._registered = False

    # -- counting --
    def _on_event(self, event: str, duration: float = 0.0, **kw) -> None:
        if self._active and event == COMPILE_EVENT:
            self.counts[self._label] = self.counts.get(self._label, 0) + 1
            eng = current_engine()
            self.engine_counts[eng] = self.engine_counts.get(eng, 0) + 1

    def start(self) -> "CompileCensus":
        if not self._registered:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                self._on_event
            )
            self._registered = True
        self._active = True
        return self

    def stop(self) -> None:
        self._active = False

    def set_label(self, label: str) -> None:
        self._label = label
        self.visited.add(label)

    def total(self) -> int:
        return sum(self.counts.values())

    # -- budget --
    def check_budget(self, budget: dict) -> list[str]:
        """Violation strings (empty = within budget).  Only labels
        present in the budget are judged; unknown labels fall under
        ``default_budget`` when set."""
        budgets: dict[str, int] = budget.get("budgets", {})
        default = budget.get("default_budget")
        out = []
        for label in sorted(set(self.counts) | set(budgets)):
            if label == STARTUP:
                continue
            n = self.counts.get(label, 0)
            cap = budgets.get(label, default)
            if cap is not None and n > cap:
                out.append(
                    f"{label}: {n} XLA compilations > budget {cap} — "
                    "retrace regression? (see analysis/rules_jax.py "
                    "JAX101/JAX104 for the usual causes; re-pin "
                    "compile_budget.json only for intentional changes)"
                )
        return out

    def should_enforce(self, budget: dict) -> bool:
        """Budgets compare like-for-like only when the whole budgeted
        suite ran in this process (shared jit caches; see module doc)."""
        forced = os.environ.get("TPU_PAXOS_COMPILE_CENSUS", "")
        if forced == "0":
            return False
        if forced == "1":
            return True
        budgets = budget.get("budgets", {})
        return bool(budgets) and set(budgets) <= self.visited

    def report(self) -> str:
        lines = ["compile census (XLA compilations per test module):"]
        lines.extend(
            f"  {label:<40s} {n:>4d}"
            for label, n in sorted(self.counts.items())
        )
        lines.append(f"  {'total':<40s} {self.total():>4d}")
        if self.engine_counts:
            lines.append("compile census (per engine scope):")
            lines.extend(
                f"  {eng:<40s} {n:>4d}"
                for eng, n in sorted(self.engine_counts.items())
            )
        return "\n".join(lines)


def load_budget(path: str = DEFAULT_BUDGET) -> dict:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def save_budget(
    counts: dict[str, int], path: str, headroom: float = 0.3,
    slack: int = 8, visited: set[str] | None = None,
) -> dict:
    """Pin a measured census as the new budget: per-module cap =
    ceil(count * (1 + headroom)) + slack.  The slack floor absorbs
    single-compile jitter in tiny modules; the proportional part
    scales with module size.  ``visited`` modules with zero compiles
    are pinned at the floor too — otherwise a module that compiled
    nothing at pin time stays uncapped forever and a later retrace
    regression there passes silently."""
    labels = set(counts) | set(visited or ())
    budgets = {
        label: int(counts.get(label, 0) * (1 + headroom)) + slack
        for label in sorted(labels)
        if label != STARTUP
    }
    data = {
        "version": 1,
        "event": COMPILE_EVENT,
        "headroom": headroom,
        "slack": slack,
        "budgets": budgets,
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return data
