"""Compiled-HLO text normalizer for the hlo-audit tier (pure stdlib).

XLA's post-optimization module text (``lowered.compile().as_text()``)
is the artifact the third analysis tier pins — but raw, it is full of
noise that churns without any semantic change: global value-numbering
suffixes (``%add.14201``), per-op ``metadata={op_name=... source_line=...}``
provenance, minor-to-major layout braces (``s32[3,16]{1,0}``), and
``/*index=N*/`` pretty-printer comments.  :func:`normalize` strips all
of that and renumbers every ``%`` identifier per base name in order of
first appearance, so

- the same entry lowered twice normalizes byte-identically,
- a pure metadata / numbering / layout perturbation normalizes away,
- a *structural* change (an extra ``convert``, a broken fusion, a
  dropped ``input_output_alias``) does NOT — it shows up as a readable
  unified diff against the pinned golden.

The module header keeps exactly two load-bearing facts: the module
name and the ``input_output_alias`` table (the donation checker's
evidence).  Everything else on the header line (schedules, layouts,
SPMD propagation flags) is dropped.

Also here, because they parse the same text:

- :func:`opcode_histogram` — per-primitive instruction counts (the
  fusion / copy / convert / transpose / while census the per-entry
  HLO budget caps).
- :func:`alias_table` — the parsed ``input_output_alias`` entries
  (output index, parameter number, kind) the donation checker reads.

No jax import anywhere in this module: it must run on a raw text dump
(e.g. a triage artifact) in a jax-free CI image.
"""

from __future__ import annotations

import re

__all__ = [
    "normalize", "opcode_histogram", "histogram_summary", "alias_table",
    "aliased_params",
]

_INDEX_COMMENT = re.compile(r"/\*index=\d+\*/\s?")
#: minor-to-major layout braces directly after a shape: ``s32[3,16]{1,0}``
#: (TPU adds tiling after a colon: ``{1,0:T(8,128)}``) — never the brace
#: opening a computation body, which follows ``)`` or whitespace.
_LAYOUT = re.compile(r"(\[[0-9,]*\])\{[0-9,]*(?::[^}]*)?\}")
#: every %-identifier (with or without a value-numbering suffix), plus
#: bare ``name.N`` tokens — computation signatures print parameter ids
#: without the ``%`` sigil (``(param_0.2: u32[], ...)``).  Floats never
#: match: the base must start with a letter or underscore.
_IDENT = re.compile(r"%?[A-Za-z_][\w-]*\.\d+|%[A-Za-z_][\w-]*\b")
#: the quoted-string form of backend_config (proto bytes / b64).
_BACKEND_CONFIG_STR = re.compile(r",?\s*backend_config=\"(?:[^\"\\]|\\.)*\"")
_ALIAS_ENTRY = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{[\d,\s]*\},\s*([\w-]+)\)"
)
#: ``%id = <type> opcode(...`` — type is a scalar/array form or a
#: ``(tuple, of, types)``; opcode is the lower-case instruction name.
_INSTR = re.compile(
    r"^\s*(?:ROOT\s+)?%[\w.-]+\s*=\s*"
    r"(?:\([^)]*\)|[\w!\[\],]+)\s+"
    r"([a-z][a-z0-9-]*)\("
)


def _extract_attr(line: str, attr: str) -> str | None:
    """The brace-balanced body of ``attr={...}`` in ``line`` (the
    alias table nests braces: ``{ {0}: (0, {}, may-alias) }``)."""
    key = attr + "={"
    start = line.find(key)
    if start < 0:
        return None
    i = start + len(key)
    depth = 1
    while i < len(line) and depth:
        if line[i] == "{":
            depth += 1
        elif line[i] == "}":
            depth -= 1
        i += 1
    return line[start + len(key):i - 1]


def _strip_attr(line: str, attr: str) -> str:
    """Remove ``attr={...}`` (with the preceding ``, `` if any) from a
    line, brace- and quote-aware — op_name strings may contain braces
    (jaxpr pretty-printed params leak into provenance)."""
    key = attr + "={"
    out = line
    while True:
        start = out.find(key)
        if start < 0:
            return out
        i = start + len(key)
        depth, in_str = 1, False
        while i < len(out) and depth:
            ch = out[i]
            if in_str:
                if ch == '"' and out[i - 1] != "\\":
                    in_str = False
            elif ch == '"':
                in_str = True
            elif ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
            i += 1
        cut_from = start
        # also eat the separator before the attribute
        pre = out[:start].rstrip()
        if pre.endswith(","):
            cut_from = len(pre) - 1
        out = out[:cut_from] + out[i:]


def _normalize_header(line: str) -> str:
    """``HloModule <name>`` + the alias table; all other header fields
    (is_scheduled, entry_computation_layout, SPMD flags, ...) are
    compiler bookkeeping, not program structure."""
    name = line.split(",", 1)[0].strip()
    # the module name itself can carry a numbering suffix
    name = re.sub(r"\.\d+$", "", name)
    alias = _extract_attr(line, "input_output_alias")
    if alias is not None:
        return f"{name}, input_output_alias={{{alias.strip()}}}"
    return name


def normalize(text: str) -> str:
    """Normalize one compiled HLO module's text (see module doc)."""
    lines = text.splitlines()
    out: list[str] = []
    counters: dict[str, int] = {}
    mapping: dict[str, str] = {}

    def canon(m: re.Match) -> str:
        tok = m.group(0)
        pct = "%" if tok.startswith("%") else ""
        key = tok.lstrip("%")  # %add.5 and bare add.5 are one value
        got = mapping.get(key)
        if got is None:
            base = key.rsplit(".", 1)[0] if "." in key else key
            n = counters.get(base, 0)
            counters[base] = n + 1
            got = mapping[key] = f"{base}.{n}"
        return pct + got

    for i, line in enumerate(lines):
        if i == 0 and line.startswith("HloModule"):
            out.append(_normalize_header(line))
            continue
        line = _INDEX_COMMENT.sub("", line)
        line = _strip_attr(line, "metadata")
        # backend_config is scheduling bookkeeping, not program
        # structure — on CPU it records the intra-op parallelism split
        # ("outer_dimension_partitions"), which tracks the host's
        # core/device provisioning, not the traced program
        line = _strip_attr(line, "backend_config")
        line = _BACKEND_CONFIG_STR.sub("", line)
        line = _LAYOUT.sub(r"\1", line)
        line = _IDENT.sub(canon, line)
        out.append(line.rstrip())
    # collapse the blank-line runs the attribute stripping can leave
    norm: list[str] = []
    for line in out:
        if line == "" and norm and norm[-1] == "":
            continue
        norm.append(line)
    return "\n".join(norm).strip() + "\n"


def opcode_histogram(text: str) -> dict[str, int]:
    """Instruction counts per HLO opcode (works on raw or normalized
    text — the instruction grammar survives normalization)."""
    hist: dict[str, int] = {}
    for line in text.splitlines():
        m = _INSTR.match(line)
        if m:
            op = m.group(1)
            hist[op] = hist.get(op, 0) + 1
    return dict(sorted(hist.items()))


#: The budget-bearing histogram keys: total instruction count plus the
#: regression-prone families — fusion breaks show as fusion-count
#: drift, silent copies/converts/transposes as their own counts, and
#: loop-structure changes (an unrolled scan, a split while) as the
#: while count.
SUMMARY_KEYS = ("fusion", "copy", "convert", "transpose", "while")


def histogram_summary(hist: dict[str, int]) -> dict[str, int]:
    """Reduce a full opcode histogram to the budgeted keys.  ``copy``
    folds in async copy pairs; every key is always present so a pin at
    0 means "this family is absent" and any appearance breaches."""
    out = {"hlo_ops": sum(hist.values())}
    for key in SUMMARY_KEYS:
        out[key] = hist.get(key, 0)
    out["copy"] += hist.get("copy-start", 0) + hist.get("copy-done", 0)
    return out


def alias_table(text: str) -> list[dict]:
    """Parse the header's ``input_output_alias`` into
    ``[{output, param, kind}, ...]`` (empty = no donation survived
    compilation)."""
    header = text.splitlines()[0] if text else ""
    body = _extract_attr(header, "input_output_alias")
    if body is None:
        return []
    out = []
    for om, pm, kind in _ALIAS_ENTRY.findall(body):
        out.append({
            "output": tuple(int(x) for x in om.replace(",", " ").split()),
            "param": int(pm),
            "kind": kind,
        })
    return out


def aliased_params(text: str) -> set[int]:
    """Parameter numbers that alias some output in the compiled
    module — the donation checker's ground truth."""
    return {a["param"] for a in alias_table(text)}
