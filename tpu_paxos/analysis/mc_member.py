"""mc churn scope: exhaustive bounded model checking of membership
reconfiguration under faults, through the member fleet.

PR 8's checker (``analysis/modelcheck.py``) certifies the GENERAL
engine's fault universe; this module is its membership sibling — the
exhaustive baseline denominator ROADMAP item 3's churn search divides
by, the way the fault scopes are item 1's.  A declared
:class:`ChurnScope` (``mc_scope.json`` entries with ``"type":
"churn"``) quantizes the churn universe to a finite grid:

- **event letters** — one per (event kind x argument x quantized
  ``t0``): ``plain`` value injections (vids ``PLAIN_VID_BASE + i``),
  ``add`` / ``del`` acceptor changes (vids from
  ``membership.engine.change_vid``), injected at a ``t0_grid`` round;
- **churn variants** — ordered sequences of up to ``max_events``
  letters (distinct change vids, every ``del`` preceded by its
  ``add`` — the initial view is node 0 alone, so a bare delete names
  a non-member) crossed with per-event ``wait_gates`` (the first
  event is always ``WAIT_NONE``, the ``ChurnSchedule`` contract);
  variant 0 is the empty schedule — the fault-only baseline lane;
- **fault letters** — the SAME episode alphabet builder as the fault
  scopes (``modelcheck.episode_alphabet``), restricted to kinds the
  membership engine admits (:data:`MEMBER_UNSUPPORTED_KINDS` is the
  data-driven rejection table, ``modelcheck.UNSUPPORTED_KINDS``'s
  discipline).

The codec is ``index = ((variant * n_fault_combos + fault_rank) *
n_seeds + seed)`` — variants list-ranked in deterministic enumeration
order, fault combinations ranked by the combinatorial number system
(``modelcheck.combo_unrank``).  A scenario's index is its STABLE NAME
in certificates and failure messages, exactly like the fault scopes.

Feasibility (named rule, never silent): a scenario is dispatchable
iff its scheduled crash set is disjoint from ``{0} | targets`` —
node 0 is the harness driver (``membership.engine``'s
``_check_member_schedule`` rejects crashing it by name), and a crash
inside the churn's named acceptor set can leave an epoch's quorum
permanently unreachable, making liveness vacuously unjudgeable (the
membership analog of the fault scopes' crash minority cap).  There
is NO node-permutation reduction here: every add/del letter names a
node, so the movable-node group of the fault scopes is broken by
construction — the certificate's full and reduced counts differ only
by the feasibility rule.

Chunks dispatch through ``fleet/envelope.member_runner_for`` (the
shared member envelope — zero warm compiles after the first chunk,
``compiles_per_chunk`` pins it) and are judged by
``fleet/member_runner.member_lane_verdict`` ON DEVICE; the verdict
nibble is ``(ok << 3) | (quorum << 2) | (catchup << 1) | coverage``
(``completed`` folds into ``ok``).  Certificates ride the shared
machinery in ``modelcheck`` (same file, same re-pin env var, same
first-diverging-scenario drift naming).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from itertools import permutations, product

from tpu_paxos.analysis import modelcheck as mcm
from tpu_paxos.analysis.chunking import chunk_pad
from tpu_paxos.core import faults as fltm
from tpu_paxos.membership import churn_table as ctm

ScopeError = mcm.ScopeError

#: Episode kinds the MEMBERSHIP engine cannot take, kind -> reason —
#: the same data-driven rejection discipline as
#: ``modelcheck.UNSUPPORTED_KINDS`` (named rejection, never silent
#: exclusion).
MEMBER_UNSUPPORTED_KINDS: dict[str, str] = {
    "gray": (
        "the membership engine's synchronous network has no arrival "
        "calendar to inflate (membership/engine._check_member_schedule "
        "rejects gray by name); gray weather is certified by the "
        "fault scopes' gray axis"
    ),
}

#: Event-letter kinds, in enumeration order within a letter class.
EV_PLAIN, EV_ADD, EV_DEL = "plain", "add", "del"

#: Plain-value vid base: plain letter ``i`` injects vid ``BASE + i``
#: (well below ``membership.engine.CHANGE_BASE``, so plain and change
#: vids can never collide).
PLAIN_VID_BASE = 100


@dataclasses.dataclass(frozen=True)
class ChurnScope:
    """One declared churn-checking scope (module doc).  Plain data,
    stable serialization/hash — ``to_dict`` carries ``"type":
    "churn"`` so a churn scope can never hash-collide with a fault
    scope of coincidentally equal fields."""

    n_nodes: int
    n_instances: int
    max_rounds: int  # member-driver convergence budget
    horizon: int  # every fault episode (and t0) stays inside this
    plain_values: int = 1  # distinct plain-value letters
    add_targets: tuple = ()  # addable acceptors (never node 0)
    del_targets: tuple = ()  # deletable acceptors (subset of adds)
    t0_grid: tuple = (0,)  # quantized injection rounds
    wait_gates: tuple = (ctm.WAIT_NONE,)  # gates for events past the first
    max_events: int = 2  # schedule length cap
    # fault axis — the member-legal subset of the fault-scope grammar
    intervals: tuple = ()
    kinds: tuple = ()
    partition_group_sizes: tuple = (1,)
    pause_set_sizes: tuple = (1,)
    burst_rates: tuple = ()
    crash_rounds: tuple = ()
    crash_set_sizes: tuple = (1,)
    max_fault_episodes: int = 1
    seeds: tuple = (0,)
    crash_rate: int = 0  # i.i.d. knob — COMPILE-TIME in the member engine
    chunk_lanes: int = 16

    _FIELDS = (
        "n_nodes", "n_instances", "max_rounds", "horizon",
        "plain_values", "add_targets", "del_targets", "t0_grid",
        "wait_gates", "max_events", "intervals", "kinds",
        "partition_group_sizes", "pause_set_sizes", "burst_rates",
        "crash_rounds", "crash_set_sizes", "max_fault_episodes",
        "seeds", "crash_rate", "chunk_lanes",
    )

    @classmethod
    def from_dict(cls, d: dict) -> "ChurnScope":
        if not isinstance(d, dict):
            raise ScopeError("scope must be a JSON object")
        unknown = sorted(set(d) - set(cls._FIELDS))
        if unknown:
            raise ScopeError(f"unknown scope field(s): {', '.join(unknown)}")
        missing = [
            f for f in ("n_nodes", "n_instances", "max_rounds", "horizon")
            if f not in d
        ]
        if missing:
            raise ScopeError(f"scope missing field(s): {', '.join(missing)}")
        kw = dict(d)
        if "intervals" in kw:
            kw["intervals"] = tuple(
                (int(t0), int(t1)) for t0, t1 in kw["intervals"]
            )
        for f in ("add_targets", "del_targets", "t0_grid", "wait_gates",
                  "kinds", "partition_group_sizes", "pause_set_sizes",
                  "burst_rates", "crash_rounds", "crash_set_sizes",
                  "seeds"):
            if f in kw:
                kw[f] = tuple(kw[f])
        try:
            scope = cls(**kw)
        except TypeError as e:
            raise ScopeError(f"bad scope field types: {e}") from None
        scope.validate()
        return scope

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["intervals"] = [list(iv) for iv in self.intervals]
        for f in ("add_targets", "del_targets", "t0_grid", "wait_gates",
                  "kinds", "partition_group_sizes", "pause_set_sizes",
                  "burst_rates", "crash_rounds", "crash_set_sizes",
                  "seeds"):
            d[f] = list(d[f])
        d["type"] = "churn"
        return d

    def sha256(self) -> str:
        text = json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(text.encode()).hexdigest()

    def validate(self) -> None:
        if self.n_nodes < 2:
            raise ScopeError("n_nodes must be >= 2")
        if self.n_instances < 1:
            raise ScopeError("n_instances must be >= 1")
        if self.max_rounds < 1:
            raise ScopeError("max_rounds must be >= 1")
        if self.horizon < 1:
            raise ScopeError("horizon must be >= 1")
        if self.plain_values < 0:
            raise ScopeError("plain_values must be >= 0")
        for what, targets in (("add_targets", self.add_targets),
                              ("del_targets", self.del_targets)):
            if len(set(targets)) != len(targets):
                raise ScopeError(f"{what} must be distinct")
            for t in targets:
                if not 1 <= t < self.n_nodes:
                    raise ScopeError(
                        f"{what} entries must be in [1, n_nodes) — "
                        "node 0 is the harness driver"
                    )
        if not set(self.del_targets) <= set(self.add_targets):
            raise ScopeError(
                "del_targets must be a subset of add_targets: the "
                "initial view is node 0 alone, so a delete is only "
                "enumerable after its add"
            )
        if not self.t0_grid or len(set(self.t0_grid)) != len(self.t0_grid):
            raise ScopeError("t0_grid must be non-empty and distinct")
        for t0 in self.t0_grid:
            if not 0 <= t0 < self.horizon:
                raise ScopeError("t0_grid entries must be in [0, horizon)")
        gates = (ctm.WAIT_NONE, ctm.WAIT_CHOSEN, ctm.WAIT_APPLIED)
        if not self.wait_gates or len(set(self.wait_gates)) != len(
            self.wait_gates
        ):
            raise ScopeError("wait_gates must be non-empty and distinct")
        for w in self.wait_gates:
            if w not in gates:
                raise ScopeError(f"wait_gates entries must be in {gates}")
        if not 1 <= self.max_events <= ctm.MAX_EVENTS:
            raise ScopeError(
                f"max_events must be in [1, {ctm.MAX_EVENTS}]"
            )
        if not event_letters(self):
            raise ScopeError(
                "no churn letters: declare plain_values and/or "
                "add_targets"
            )
        bad = sorted(set(self.kinds) - set(fltm.KINDS))
        if bad:
            raise ScopeError(f"unknown episode kind(s): {', '.join(bad)}")
        for k in self.kinds:
            reason = MEMBER_UNSUPPORTED_KINDS.get(
                k, mcm.UNSUPPORTED_KINDS.get(k)
            )
            if reason is not None:
                raise ScopeError(
                    f"episode kind {k!r} is not enumerable by the "
                    f"churn checker: {reason}"
                )
        if self.kinds and not self.intervals:
            if set(self.kinds) != {"crash"}:
                raise ScopeError("interval kinds need intervals")
        for t0, t1 in self.intervals:
            if not 0 <= t0 < t1 <= self.horizon:
                raise ScopeError(
                    f"interval [{t0}, {t1}) must be non-empty inside "
                    f"[0, horizon={self.horizon}]"
                )
        if "burst" in self.kinds and not self.burst_rates:
            raise ScopeError("burst in kinds needs burst_rates")
        for r in self.burst_rates:
            if not 0 < r <= 10_000:
                raise ScopeError("burst rates must be in (0, 10000]")
        if "crash" in self.kinds and not self.crash_rounds:
            raise ScopeError("crash in kinds needs crash_rounds")
        for t in self.crash_rounds:
            if not 0 <= t < self.horizon:
                raise ScopeError("crash rounds must be in [0, horizon)")
        for sizes, what in (
            (self.partition_group_sizes, "partition_group_sizes"),
            (self.pause_set_sizes, "pause_set_sizes"),
            (self.crash_set_sizes, "crash_set_sizes"),
        ):
            for k in sizes:
                if not 1 <= k < self.n_nodes:
                    raise ScopeError(
                        f"{what} entries must be in [1, n_nodes)"
                    )
        if not 0 <= self.max_fault_episodes <= mcm.MAX_SCOPE_EPISODES:
            raise ScopeError(
                f"max_fault_episodes must be in "
                f"[0, {mcm.MAX_SCOPE_EPISODES}]"
            )
        if not self.seeds or len(set(self.seeds)) != len(self.seeds):
            raise ScopeError("seeds must be non-empty and distinct")
        if not 0 <= self.crash_rate <= 10_000:
            raise ScopeError("crash_rate must be in [0, 10000]")
        if self.chunk_lanes < 1:
            raise ScopeError("chunk_lanes must be >= 1")


def _fault_proxy(scope: ChurnScope) -> mcm.McScope:
    """A fault-scope view of the churn scope's fault axis, so the
    letter builder is SHARED with the fault scopes (one alphabet
    implementation — a grammar change cannot diverge between
    checkers).  Constructed directly (no validate): the churn
    validator already checked the member-legal subset."""
    return mcm.McScope(
        n_nodes=scope.n_nodes,
        proposers=1,
        horizon=scope.horizon,
        max_rounds=scope.max_rounds,
        intervals=scope.intervals or ((0, 1),),
        kinds=scope.kinds,
        partition_group_sizes=scope.partition_group_sizes,
        pause_set_sizes=scope.pause_set_sizes,
        burst_rates=scope.burst_rates,
        crash_rounds=scope.crash_rounds,
        crash_set_sizes=scope.crash_set_sizes,
        max_episodes=scope.max_fault_episodes,
    )


def event_letters(scope: ChurnScope) -> list[tuple]:
    """The churn event alphabet, deterministic order: plains, then
    adds, then dels; within a class, arguments in listed order x the
    ``t0_grid`` in listed order.  A letter is ``(kind, arg, t0)``."""
    out: list[tuple] = []
    for i in range(scope.plain_values):
        for t0 in scope.t0_grid:
            out.append((EV_PLAIN, int(i), int(t0)))
    for kind, targets in ((EV_ADD, scope.add_targets),
                          (EV_DEL, scope.del_targets)):
        for tgt in targets:
            for t0 in scope.t0_grid:
                out.append((kind, int(tgt), int(t0)))
    return out


def _seq_valid(letters: list[tuple], seq: tuple[int, ...]) -> bool:
    """A letter sequence materializes to a legal ChurnSchedule iff
    its vids are distinct (two t0 spellings of one event are the same
    vid) and every del's target was added earlier in the sequence."""
    vids: set = set()
    added: set = set()
    for li in seq:
        kind, arg, _ = letters[li]
        ident = (kind if kind == EV_PLAIN else kind, arg)
        if ident in vids:
            return False
        vids.add(ident)
        if kind == EV_DEL and arg not in added:
            return False
        if kind == EV_ADD:
            added.add(arg)
    return True


def churn_variants(scope: ChurnScope) -> list:
    """Every enumerable churn variant, deterministic order: variant 0
    is the EMPTY schedule (fault-only baseline lane); then by length,
    letter tuples in lexicographic index order, wait assignments in
    ``wait_gates`` listed order (mixed radix over positions >= 1 —
    the first event is forced ``WAIT_NONE``).  A variant is
    ``None`` or ``(letter_indices, waits)``."""
    letters = event_letters(scope)
    out: list = [None]
    for k in range(1, scope.max_events + 1):
        for seq in permutations(range(len(letters)), k):
            if not _seq_valid(letters, seq):
                continue
            for waits in product(scope.wait_gates, repeat=k - 1):
                out.append((seq, (ctm.WAIT_NONE,) + tuple(waits)))
    return out


class ChurnScenario:
    """One decoded churn scenario; ``index`` is its stable name."""

    __slots__ = ("index", "variant", "combo", "seed")

    def __init__(self, index, variant, combo, seed):
        self.index = index
        self.variant = variant  # variant list index
        self.combo = combo  # fault-alphabet index tuple
        self.seed = seed  # seed list index


class ChurnEnum:
    """The churn scope's enumerator: event letters, variant list,
    fault alphabet, bijective codec, feasibility filtering."""

    def __init__(self, scope: ChurnScope):
        self.scope = scope
        self.letters = event_letters(scope)
        self.variants = churn_variants(scope)
        self.n_variants = len(self.variants)
        self.fault_alphabet = mcm.episode_alphabet(_fault_proxy(scope))
        self.m = len(self.fault_alphabet)
        self.n_fault_combos = mcm.n_combos(
            self.m, scope.max_fault_episodes
        )
        self.n_seeds = len(scope.seeds)
        self.total = self.n_variants * self.n_fault_combos * self.n_seeds
        self.reduced = self._reduced_indices()

    # -- codec --

    def decode(self, index: int) -> ChurnScenario:
        if not 0 <= index < self.total:
            raise IndexError(
                f"scenario index {index} outside [0, {self.total})"
            )
        r, seed = divmod(index, self.n_seeds)
        vi, fr = divmod(r, self.n_fault_combos)
        combo = mcm.combo_unrank(
            fr, self.m, self.scope.max_fault_episodes
        )
        return ChurnScenario(index, vi, combo, seed)

    def encode(self, sc: ChurnScenario) -> int:
        fr = mcm.combo_rank(
            sc.combo, self.m, self.scope.max_fault_episodes
        )
        return (
            sc.variant * self.n_fault_combos + fr
        ) * self.n_seeds + sc.seed

    # -- feasibility --

    def variant_targets(self, vi: int) -> set:
        """The nodes a variant's change letters name."""
        v = self.variants[vi]
        if v is None:
            return set()
        return {
            self.letters[li][1]
            for li in v[0]
            if self.letters[li][0] != EV_PLAIN
        }

    def combo_feasible(self, combo: tuple, vi: int) -> bool:
        """Dispatchable iff scheduled crashes avoid ``{0} | targets``
        (module doc: the driver plus the churn's named acceptors —
        a crash inside the epoch acceptor set can wedge its quorum
        forever, making liveness vacuously unjudgeable)."""
        protected = {0} | self.variant_targets(vi)
        for i in combo:
            e = self.fault_alphabet[i]
            if e.kind == "crash" and set(e.nodes) & protected:
                return False
        return True

    def _reduced_indices(self) -> list[int]:
        out = []
        for vi in range(self.n_variants):
            for fr in range(self.n_fault_combos):
                combo = mcm.combo_unrank(
                    fr, self.m, self.scope.max_fault_episodes
                )
                if not self.combo_feasible(combo, vi):
                    continue
                base = (vi * self.n_fault_combos + fr) * self.n_seeds
                out.extend(range(base, base + self.n_seeds))
        return out

    # -- materialization --

    def churn_of(self, sc: ChurnScenario):
        v = self.variants[sc.variant]
        if v is None:
            return None
        from tpu_paxos.membership import engine as meng

        seq, waits = v
        events = []
        for li, w in zip(seq, waits):
            kind, arg, t0 = self.letters[li]
            if kind == EV_PLAIN:
                vid = PLAIN_VID_BASE + arg
            elif kind == EV_ADD:
                vid = meng.change_vid(arg, meng.ADD_ACCEPTOR)
            else:
                vid = meng.change_vid(arg, meng.DEL_ACCEPTOR)
            events.append(
                ctm.ChurnEvent(vid=vid, t0=t0, wait=int(w))
            )
        return ctm.ChurnSchedule(tuple(events))

    def schedule_of(self, sc: ChurnScenario):
        if not sc.combo:
            return None
        return fltm.FaultSchedule(
            tuple(self.fault_alphabet[i] for i in sc.combo)
        )

    def describe(self, sc: ChurnScenario) -> dict:
        v = self.variants[sc.variant]
        sched = self.schedule_of(sc)
        return {
            "index": sc.index,
            "variant": sc.variant,
            "events": [] if v is None else [
                {
                    "kind": self.letters[li][0],
                    "arg": self.letters[li][1],
                    "t0": self.letters[li][2],
                    "wait": int(w),
                }
                for li, w in zip(v[0], v[1])
            ],
            "combo": list(sc.combo),
            "episodes": sched.to_dict()["episodes"] if sched else [],
            "seed": int(self.scope.seeds[sc.seed]),
        }


# ---------------- chunked dispatch ----------------

def run_scope(
    scope: ChurnScope,
    triage_dir: str | None = None,
    verbose: bool = True,
    max_counterexamples: int = 8,
    chunk_limit: int | None = None,
) -> dict:
    """Enumerate and dispatch the churn scope through the member
    fleet; returns the ``modelcheck.run_scope``-shaped summary (same
    certificate machinery).  Counterexamples carry the failing lane's
    decision-log sha and a JSON description dump
    (``mc_member_scenario_<index>.json``) — the member engine's
    single-run parity contract (tests/test_member_fleet.py) makes the
    lane log the replay surface."""
    import jax

    from tpu_paxos.analysis import tracecount
    from tpu_paxos.analysis import triage as triage_mod
    from tpu_paxos.fleet import envelope as env
    from tpu_paxos.utils import log as logm

    logger = logm.get_logger(
        "mc", logm.parse_level("INFO" if verbose else "WARN")
    )
    enum = ChurnEnum(scope)
    runner = env.member_runner_for(
        scope.n_nodes, scope.n_instances,
        crash_rate=scope.crash_rate,
        max_rounds=scope.max_rounds,
    )
    # share modelcheck's module-level census (jax.monitoring has no
    # listener-removal API — one census for the whole mc tier)
    if mcm._mc_census is None:
        mcm._mc_census = tracecount.CompileCensus()
    census = mcm._mc_census.start()
    all_chunks = chunk_pad(enum.reduced, scope.chunk_lanes)
    chunks = all_chunks[:chunk_limit] if chunk_limit else all_chunks
    nibbles: list[str] = []
    compiles_per_chunk: list[int] = []
    counterexamples: list[dict] = []
    lanes_total = 0
    seconds = 0.0
    try:
        for ci, (chunk, n_real) in enumerate(chunks):
            scenarios = [enum.decode(i) for i in chunk]
            before = census.engine_counts.get("member", 0)
            rep = runner.run(
                [scope.seeds[sc.seed] for sc in scenarios],
                [enum.churn_of(sc) for sc in scenarios],
                [enum.schedule_of(sc) for sc in scenarios],
            )
            compiles_per_chunk.append(
                census.engine_counts.get("member", 0) - before
            )
            lanes_total += n_real
            seconds += rep.seconds
            for li in range(n_real):
                v = rep.verdict
                ok, qu = bool(v.ok[li]), bool(v.quorum[li])
                cu, cov = bool(v.catchup[li]), bool(v.coverage[li])
                nibbles.append(
                    f"{(ok << 3) | (qu << 2) | (cu << 1) | cov:x}"
                )
                if ok:
                    continue
                sc = scenarios[li]
                log_text = rep.lane_log(li)
                cx = {
                    "scenario": enum.describe(sc),
                    "verdict": {
                        "quorum": qu, "catchup": cu, "coverage": cov,
                        "completed": bool(v.completed[li]),
                        "rounds": int(v.rounds[li]),
                    },
                    "decision_log_sha256": hashlib.sha256(
                        log_text.encode()
                    ).hexdigest(),
                }
                logger.error(
                    "COUNTEREXAMPLE churn scenario %d: %s",
                    sc.index, json.dumps(cx["verdict"], sort_keys=True),
                )
                if triage_dir and len(counterexamples) < max_counterexamples:
                    os.makedirs(triage_dir, exist_ok=True)
                    path = os.path.join(
                        triage_dir,
                        triage_mod.dump_name(
                            "mc", f"member_scenario_{sc.index}", "json"
                        ),
                    )
                    with open(path, "w") as f:
                        json.dump(
                            dict(cx, scope_sha256=scope.sha256()),
                            f, indent=1, sort_keys=True,
                        )
                        f.write("\n")
                    cx["artifact"] = path
                    triage_mod.prune(triage_dir)
                counterexamples.append(cx)
            if verbose and (ci % 8 == 0 or ci == len(chunks) - 1):
                logger.info(
                    "churn chunk %d/%d: %d scenarios judged, %d "
                    "counterexamples (%.1f lanes/sec)",
                    ci + 1, len(chunks), lanes_total,
                    len(counterexamples), rep.lanes_per_sec,
                )
            if len(counterexamples) >= max_counterexamples:
                logger.error(
                    "counterexample budget (%d) reached after chunk "
                    "%d/%d; stopping early", max_counterexamples,
                    ci + 1, len(chunks),
                )
                chunks = chunks[:ci + 1]
                break
    finally:
        census.stop()
    bits = "".join(nibbles)
    return {
        "metric": "modelcheck-member",
        "backend": jax.default_backend(),
        "scope_sha256": scope.sha256(),
        # shape pins (shared certificate fields): "alphabet" counts
        # EVERY letter — churn events plus fault episodes; "combos"
        # is the (variant x fault-combination) grid
        "alphabet": len(enum.letters) + enum.m,
        "combos": enum.n_variants * enum.n_fault_combos,
        "churn_letters": len(enum.letters),
        "churn_variants": enum.n_variants,
        "fault_alphabet": enum.m,
        "fault_combos": enum.n_fault_combos,
        "scenarios_full": enum.total,
        "scenarios_reduced": len(enum.reduced),
        "chunk_lanes": scope.chunk_lanes,
        "chunks": len(all_chunks),
        "chunks_run": len(chunks),
        "lanes_judged": lanes_total,
        "lanes_per_sec": round(lanes_total / max(seconds, 1e-9), 2),
        "compiles_per_chunk": compiles_per_chunk,
        "verdict_bits": bits,
        "verdict_bits_sha256": hashlib.sha256(bits.encode()).hexdigest(),
        "counterexamples": counterexamples,
        "anomalies": [],
        "seeded_wedge": mcm._seeded_wedge_flag(),
        "ok": not counterexamples,
    }


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """The churn-chunk surface: one canonical chunk of a tiny churn
    scope, decoded through the codec and dispatched exactly as
    run_scope stacks it (runtime churn tables + runtime fault masks
    through the member fleet program) — the op/HLO budgets pin the
    program the churn checker actually runs."""
    import jax
    import jax.numpy as jnp

    from tpu_paxos.analysis.registry import AuditEntry
    from tpu_paxos.fleet import member_runner as mfr
    from tpu_paxos.fleet import schedule_table as stm
    from tpu_paxos.membership import engine as meng
    from tpu_paxos.utils import prng

    def build():
        scope = ChurnScope.from_dict({
            "n_nodes": 3, "n_instances": 8, "max_rounds": 64,
            "horizon": 12, "plain_values": 1, "add_targets": [1],
            "del_targets": [], "t0_grid": [0],
            "wait_gates": [ctm.WAIT_NONE, ctm.WAIT_APPLIED],
            "max_events": 2, "intervals": [[2, 8]],
            "kinds": ["pause", "crash"], "pause_set_sizes": [1],
            "crash_rounds": [4], "crash_set_sizes": [1],
            "max_fault_episodes": 1, "seeds": [0], "crash_rate": 500,
            "chunk_lanes": 2,
        })
        enum = ChurnEnum(scope)
        runner = mfr.MemberFleetRunner(
            scope.n_nodes, scope.n_instances,
            max_episodes=2, crash_rate=scope.crash_rate,
            max_rounds=scope.max_rounds,
        )
        (chunk, _), = chunk_pad(enum.reduced[:2], scope.chunk_lanes)
        scenarios = [enum.decode(i) for i in chunk]
        ctabs = jax.tree.map(
            jnp.asarray,
            ctm.encode_churn_batch(
                [enum.churn_of(sc) for sc in scenarios],
                scope.n_nodes, runner.max_events,
            ),
        )
        ftabs = jax.tree.map(
            jnp.asarray,
            stm.encode_batch(
                [enum.schedule_of(sc) for sc in scenarios],
                scope.n_nodes, runner.max_episodes,
            ),
        )
        roots = jnp.stack([
            prng.root_key(scope.seeds[sc.seed]) for sc in scenarios
        ])
        st0 = meng._init(scope.n_nodes, scope.n_instances, runner.c)
        return runner._fn, (roots, st0, ctabs, ftabs)

    return [
        AuditEntry(
            "mc.member_chunk", build,
            why=(
                "the churn-chunk body IS the member fleet's vmapped "
                "whole-run churn driver — same program family as "
                "member.fleet_lanes, traced from the mc codec's "
                "decoded chunk"
            ),
        ),
    ]
