"""Auditable-entry-point registry for the trace-time IR audit.

The audit (``jaxpr_audit.py``) can only see what it traces, so every
jitted surface of the engines must be *registered*: each provider
module (the engines + the sharded path, ``AUDIT_PROVIDERS``) defines
an ``audit_entries()`` function returning :class:`AuditEntry` objects
— canonical small-config traces of its entry points.  The registry
deliberately lives WITH the engines, not in a central table here:
adding a jitted surface to an engine without registering it fails the
audit's unregistered-function sweep (``jaxpr_audit._sweep_module``),
which statically finds every ``jax.jit`` / ``pallas_call`` /
``shard_map`` site in a provider file and requires its name to appear
in some entry's ``covers`` tuple or in the module's ``AUDIT_EXEMPT``
dict (name -> reason).

Import discipline: this module is pure stdlib — an engine importing
it must not pull jax transitively.  ``AuditEntry.build`` thunks (and
``collect()``, which imports the engine providers) are only invoked
by the audit itself, which owns the jax dependency.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

#: Modules whose jitted surfaces are subject to the audit.  A new
#: engine module with jitted entry points must be added here AND
#: provide ``audit_entries()`` — the sweep runs over exactly this set,
#: and tests/test_jaxpr_audit.py pins that each provider registers at
#: least one entry.
AUDIT_PROVIDERS = (
    "tpu_paxos.core.sim",
    "tpu_paxos.core.simkern",
    "tpu_paxos.core.fastwin",
    "tpu_paxos.core.fast",
    "tpu_paxos.membership.engine",
    "tpu_paxos.parallel.sharded",
    "tpu_paxos.parallel.sharded_sim",
    "tpu_paxos.fleet.runner",
    "tpu_paxos.fleet.member_runner",
    "tpu_paxos.analysis.modelcheck",
    "tpu_paxos.analysis.mc_member",
    "tpu_paxos.serve.driver",
    "tpu_paxos.serve.fleet",
    "tpu_paxos.serve.control",
)


@dataclasses.dataclass(frozen=True)
class AuditEntry:
    """One auditable entry point: a canonical small-config trace.

    ``build()`` returns ``(fn, args)``; the audit runs
    ``jax.make_jaxpr(fn)(*args)`` for the IR rules and op census and
    ``jax.jit(fn).lower(*args).cost_analysis()`` for the FLOP/bytes
    estimates.  Building may allocate tiny device arrays; it must be
    deterministic (fixed shapes, fixed seed) — the op budget is pinned
    against exactly this trace.
    """

    #: Report/budget key, e.g. ``"sim.run_rounds"``.
    name: str
    #: () -> (fn, args) — the canonical trace.
    build: Callable[[], tuple]
    #: Statically-visible jit/pallas/shard_map surface names in the
    #: provider file this entry exercises (function qualnames, or the
    #: assignment target for module-level ``x = jax.jit(f)``).
    covers: tuple = ()
    #: Mesh axis names this entry's collectives may reduce over
    #: (IR203).  Empty = collectives are forbidden in this entry.
    mesh_axes: tuple = ()
    #: Rule ids waived for this entry — the trace-time analog of the
    #: paxlint pragma.  Give the reason in ``why``.
    allow: tuple = ()
    why: str = ""
    #: Include XLA cost_analysis (flops / bytes accessed) in the
    #: census.  Off for entries whose lowering is backend-exotic
    #: (interpret-mode pallas) or whose cost numbers would be noise.
    cost: bool = True
    #: IR205 threshold: largest jaxpr constant (bytes) this entry may
    #: bake in.  Engines bake small static tables (proposer maps,
    #: schedule masks for canonical configs); a const past this is an
    #: accidentally-captured host array.
    const_budget: int = 65536
    #: Trace under jax.experimental.enable_x64 — a fixture/testing
    #: knob (the IR202 seeded-violation fixture needs 64-bit types to
    #: exist); engine entries never set it.
    x64: bool = False
    #: --- hlo-audit tier (analysis/hlo_audit.py) ---
    #: Positional arg indices of the canonical call that the PRODUCT's
    #: own jit declares as donated (``donate_argnums``).  The compiled
    #: artifact must show input/output aliasing for every array leaf
    #: of these args, or the donation checker fails naming the entry
    #: and the parameter — a donation silently dropped (refactor, flag,
    #: wrapper re-jit) is a doubled buffer, not a style issue.
    #: Donated args must precede any non-array positional arg so the
    #: flattened parameter numbering is derivable (see
    #: ``hlo_audit.expected_donated_params``).
    donate_argnums: tuple = ()
    #: Optional HLO-tier build override: () -> (lowerable, args,
    #: kwargs); the tier calls ``lowerable.lower(*args, **kwargs)``.
    #: Needed when the jaxpr-tier ``build`` wraps the product jit in a
    #: closure (static args) — re-jitting a closure would silently
    #: re-add whatever the product jit dropped, so the DONATION check
    #: must lower through the product's own jitted callable.  Default:
    #: derived from ``build()``.
    hlo_build: Callable[[], tuple] | None = None
    #: Pin the normalized compiled-module text as a golden
    #: (tests/data/hlo/) and diff against it — reserved for the hot
    #: kernels whose lowering IS the perf contract; every entry gets
    #: the per-primitive histogram + memory-ceiling budget regardless.
    hlo_golden: bool = False
    #: --- shard-audit tier (analysis/shard_audit.py) ---
    #: Mesh-polymorphic build: (mesh) -> (fn, args), the canonical
    #: trace laid out over THAT mesh.  Opts the entry into the
    #: SH302/SH303 grid — per-mesh-shape per-device memory ceilings
    #: and the collective census (all-reduce / all-gather /
    #: collective-permute / reduce-scatter counts) against
    #: analysis/shard_budget.json.  The entry must build under every
    #: shape of the committed grid (state sizes divide 8).
    shard_build: Callable | None = None
    #: () -> (family, stacked_state_pytree) for SH301: every array
    #: leaf of the pytree must be matched by the committed partition
    #: rules (parallel/partition_rules.py) under the given family
    #: prefix, or the audit fails naming the leaf's pytree path.
    shard_state: Callable | None = None
    #: (n_devices) -> {"verdicts": str, "lane_logs": [str, ...]} for
    #: SH304: run the driver end to end on an n-device mesh and
    #: return the per-lane verdict nibbles (one hex digit per lane)
    #: plus each lane's decision-log sha256.  The audit requires the
    #: result bitwise identical across every mesh shape in the grid
    #: and against the pinned certificate
    #: (analysis/shard_certificate.json).
    shard_parity: Callable | None = None


class RegistryError(Exception):
    """A provider is malformed: missing audit_entries(), duplicate
    entry names, or a non-AuditEntry in the returned list."""


def provider_module(name: str):
    """Import one provider module (jax import happens here — callers
    that must stay jax-free use only the static sweep)."""
    import importlib

    return importlib.import_module(name)


def exemptions(mod) -> dict[str, str]:
    """The module's declared sweep exemptions: surface name -> reason.
    An exemption documents a jitted surface that is deliberately not
    audit-traced (e.g. a debug-only helper)."""
    ex = getattr(mod, "AUDIT_EXEMPT", {})
    if not isinstance(ex, dict):
        raise RegistryError(
            f"{mod.__name__}.AUDIT_EXEMPT must be a dict of "
            "surface-name -> reason"
        )
    return ex


def collect(providers=AUDIT_PROVIDERS) -> list[AuditEntry]:
    """Import every provider and gather its registered entries.
    Raises RegistryError on a provider without ``audit_entries()`` or
    on duplicate entry names (budgets key on the name)."""
    out: list[AuditEntry] = []
    seen: dict[str, str] = {}
    for name in providers:
        mod = provider_module(name)
        prov = getattr(mod, "audit_entries", None)
        if prov is None:
            raise RegistryError(
                f"audit provider {name} defines no audit_entries() — "
                "every engine module with jitted surfaces must "
                "register its entry points (see analysis/registry.py)"
            )
        for e in prov():
            if not isinstance(e, AuditEntry):
                raise RegistryError(
                    f"{name}.audit_entries() returned a non-AuditEntry: "
                    f"{e!r}"
                )
            if e.name in seen:
                raise RegistryError(
                    f"duplicate audit entry name {e.name!r} "
                    f"({seen[e.name]} and {name})"
                )
            seen[e.name] = name
            out.append(e)
    return out
