"""mc controller scope: exhaustive model checking of the admission
controller's policy invariants.

PR 16's cause-aware controller (``serve/control.py``) carries four
contracts — never shed on a gray-region window (the veto holds even
beside saturation), admit every value exactly once with TRUE arrival
stamps through deferral, step the ladder monotonically (degrade one
rung down, restore one rung up, never outside ``[0, top]``), and
restore only after ``patience`` calm dispatches.  Before this scope
they were pinned on a handful of seeded test schedules
(tests/test_control.py); here they become machine-checked invariants
over an EXHAUSTIVE grid, riding the mc tier's codec / chunking /
certificate machinery (``mc_scope.json`` entries with ``"type":
"control"``).

Two planes, one scenario index space:

- **host plane** — every (policy, dispatch-letter sequence) pair.
  The policy grid is ``tier_bands x patiences x ladders`` (canonical
  cause table).  A dispatch letter is ``(cause-name window set, burn
  reading)``; the empty set is a quiet dispatch (the restore path's
  food).  Sequences of length ``1..max_dispatches`` are ranked by a
  length-stratified base-L positional codec.  Each scenario drives
  ``decide()`` through the letters and judges the trail against an
  INDEPENDENT oracle (:func:`judge_sequence` — predicted-state
  reconstruction, not a re-run of ``decide``'s code), then exercises
  the admission ledger (:func:`_admission_exact`): the sequence's
  degraded timeline replayed as floors over a tiered
  ``ControlledPlan``, drained floors-off, every vid exactly once with
  its original stamp.
- **e2e plane** — a small grid of REAL ``controlled_serve_run``
  device lanes (policy-grid index x arrival seed) on the shared
  test_control geometry, judged by the SAME trail checker.  Device
  causes are saturation-plane: the serve stack has no gray-weather
  path, so gray letters exist only in the host plane — which is
  exactly where the seeded shed-on-gray wedge
  (``TPU_PAXOS_SEEDED_WEDGE=shed-on-gray``,
  ``serve/control.wedged_policy``) is provably FOUND: every
  gray-naming sequence under a wedged policy fails the veto
  invariant, shrinks greedily to a minimal sequence, and lands as a
  byte-replaying ``mc-control`` artifact (``python -m tpu_paxos
  repro`` routes it back through :func:`reproduce` — the trail is
  pure host arithmetic, so replay is exact byte compare).

There is no symmetry reduction: policy knobs and cause names pin
every identity, so full == reduced and the certificate's counts say
so.  The verdict nibble is ``(ok << 3) | (veto << 2) | (ladder << 1)
| admission``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time

import numpy as np

from tpu_paxos.analysis import modelcheck as mcm
from tpu_paxos.analysis.chunking import chunk_pad
from tpu_paxos.serve import arrivals as arrv
from tpu_paxos.serve import control as ctl
from tpu_paxos.telemetry import diagnose as diag

ScopeError = mcm.ScopeError

#: Artifact engine discriminator (``__main__.run_repro`` routes it).
ARTIFACT_ENGINE = "mc-control"

#: Sequence-length ceiling: the scenario count grows as ``L^k``.
MAX_CTL_DISPATCHES = 6


@dataclasses.dataclass(frozen=True)
class ControlScope:
    """One declared controller-checking scope (module doc).  Plain
    data, stable serialization/hash; ``to_dict`` carries ``"type":
    "control"``."""

    tier_bands: tuple  # ((n_tiers, defer_tier, shed_tier), ...)
    patiences: tuple
    ladders: tuple  # ladder tuples; () = fixed granularity
    window_sets: tuple  # cause-NAME tuples; () = quiet dispatch
    burn_tiers: tuple  # quantized burn readings (milli)
    max_dispatches: int = 3
    burn_low_milli: int = 500
    plan_values: int = 6  # per-stream values in the admission exercise
    chunk_lanes: int = 64
    e2e_policies: tuple = ()  # policy-grid indices run on device
    e2e_arrival_seeds: tuple = ()

    _FIELDS = (
        "tier_bands", "patiences", "ladders", "window_sets",
        "burn_tiers", "max_dispatches", "burn_low_milli",
        "plan_values", "chunk_lanes", "e2e_policies",
        "e2e_arrival_seeds",
    )

    @classmethod
    def from_dict(cls, d: dict) -> "ControlScope":
        if not isinstance(d, dict):
            raise ScopeError("scope must be a JSON object")
        unknown = sorted(set(d) - set(cls._FIELDS))
        if unknown:
            raise ScopeError(f"unknown scope field(s): {', '.join(unknown)}")
        missing = [
            f for f in ("tier_bands", "patiences", "ladders",
                        "window_sets", "burn_tiers")
            if f not in d
        ]
        if missing:
            raise ScopeError(f"scope missing field(s): {', '.join(missing)}")
        kw = dict(d)
        if "tier_bands" in kw:
            kw["tier_bands"] = tuple(
                tuple(int(x) for x in band) for band in kw["tier_bands"]
            )
        if "ladders" in kw:
            kw["ladders"] = tuple(
                tuple(int(s) for s in lad) for lad in kw["ladders"]
            )
        if "window_sets" in kw:
            kw["window_sets"] = tuple(
                tuple(str(nm) for nm in ws) for ws in kw["window_sets"]
            )
        for f in ("patiences", "burn_tiers", "e2e_policies",
                  "e2e_arrival_seeds"):
            if f in kw:
                kw[f] = tuple(kw[f])
        try:
            scope = cls(**kw)
        except TypeError as e:
            raise ScopeError(f"bad scope field types: {e}") from None
        scope.validate()
        return scope

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["tier_bands"] = [list(b) for b in self.tier_bands]
        d["ladders"] = [list(lad) for lad in self.ladders]
        d["window_sets"] = [list(ws) for ws in self.window_sets]
        for f in ("patiences", "burn_tiers", "e2e_policies",
                  "e2e_arrival_seeds"):
            d[f] = list(d[f])
        d["type"] = "control"
        return d

    def sha256(self) -> str:
        text = json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(text.encode()).hexdigest()

    def validate(self) -> None:
        if not self.tier_bands or len(set(self.tier_bands)) != len(
            self.tier_bands
        ):
            raise ScopeError("tier_bands must be non-empty and distinct")
        for band in self.tier_bands:
            if len(band) != 3:
                raise ScopeError(
                    "each tier band is (n_tiers, defer_tier, shed_tier)"
                )
            n_t, df, sh_ = band
            if not 1 <= df <= sh_ <= n_t:
                raise ScopeError(
                    f"tier band {band} must satisfy 1 <= defer <= "
                    "shed <= n_tiers"
                )
        if not self.patiences or len(set(self.patiences)) != len(
            self.patiences
        ):
            raise ScopeError("patiences must be non-empty and distinct")
        for p in self.patiences:
            if p < 1:
                raise ScopeError("patiences entries must be >= 1")
        if not self.ladders or len(set(self.ladders)) != len(self.ladders):
            raise ScopeError("ladders must be non-empty and distinct")
        for lad in self.ladders:
            if any(s < 1 for s in lad):
                raise ScopeError("ladder entries must be >= 1")
            if list(lad) != sorted(lad):
                raise ScopeError(f"ladder {lad} must ascend")
        if not self.window_sets or len(set(self.window_sets)) != len(
            self.window_sets
        ):
            raise ScopeError("window_sets must be non-empty and distinct")
        for ws in self.window_sets:
            if len(set(ws)) != len(ws):
                raise ScopeError(f"window set {ws} must be distinct")
            for nm in ws:
                if nm not in diag.CAUSE_IDS:
                    raise ScopeError(
                        f"unknown cause name {nm!r} (one of "
                        f"{sorted(diag.CAUSE_IDS)})"
                    )
        if not self.burn_tiers or len(set(self.burn_tiers)) != len(
            self.burn_tiers
        ):
            raise ScopeError("burn_tiers must be non-empty and distinct")
        for b in self.burn_tiers:
            if not 0 <= b <= 100_000:
                raise ScopeError("burn_tiers entries must be in [0, 100000]")
        if not 1 <= self.max_dispatches <= MAX_CTL_DISPATCHES:
            raise ScopeError(
                f"max_dispatches must be in [1, {MAX_CTL_DISPATCHES}]"
            )
        if self.burn_low_milli < 0:
            raise ScopeError("burn_low_milli must be >= 0")
        if not 1 <= self.plan_values <= 64:
            raise ScopeError("plan_values must be in [1, 64]")
        if self.chunk_lanes < 1:
            raise ScopeError("chunk_lanes must be >= 1")
        n_pol = (
            len(self.tier_bands) * len(self.patiences) * len(self.ladders)
        )
        if bool(self.e2e_policies) != bool(self.e2e_arrival_seeds):
            raise ScopeError(
                "e2e_policies and e2e_arrival_seeds come together "
                "(the e2e grid is their product)"
            )
        if len(set(self.e2e_policies)) != len(self.e2e_policies):
            raise ScopeError("e2e_policies must be distinct")
        for pi in self.e2e_policies:
            if not 0 <= pi < n_pol:
                raise ScopeError(
                    f"e2e_policies entry {pi} outside the policy grid "
                    f"[0, {n_pol})"
                )
        if len(set(self.e2e_arrival_seeds)) != len(self.e2e_arrival_seeds):
            raise ScopeError("e2e_arrival_seeds must be distinct")


def policy_grid(scope: ControlScope) -> list:
    """The policy axis, deterministic band x patience x ladder order,
    canonical cause table (``serve/control.default_table``)."""
    out = []
    for n_t, df, sh_ in scope.tier_bands:
        for pat in scope.patiences:
            for lad in scope.ladders:
                out.append(ctl.ControlPolicy(
                    n_tiers=n_t, defer_tier=df, shed_tier=sh_,
                    burn_low_milli=scope.burn_low_milli,
                    patience=pat, ladder=tuple(lad),
                ))
    return out


class CtlScenario:
    """One decoded controller scenario; ``index`` is its stable name.
    ``seq`` is the dispatch-letter index tuple (host plane) or None
    (e2e plane, ``e2e_seed`` set)."""

    __slots__ = ("index", "policy", "seq", "e2e_seed")

    def __init__(self, index, policy, seq, e2e_seed=None):
        self.index = index
        self.policy = policy  # policy-grid index
        self.seq = seq
        self.e2e_seed = e2e_seed


class ControlEnum:
    """The controller scope's enumerator: policy grid, dispatch
    letters, length-stratified sequence codec, e2e cell tail."""

    def __init__(self, scope: ControlScope):
        self.scope = scope
        self.policies = policy_grid(scope)
        self.n_policies = len(self.policies)
        self.letters = [
            (ws, int(b))
            for ws in scope.window_sets for b in scope.burn_tiers
        ]
        self.n_letters = len(self.letters)
        self.n_seq = sum(
            self.n_letters ** k
            for k in range(1, scope.max_dispatches + 1)
        )
        self.host_total = self.n_policies * self.n_seq
        self.n_e2e = (
            len(scope.e2e_policies) * len(scope.e2e_arrival_seeds)
        )
        self.total = self.host_total + self.n_e2e
        # no reduction: policy knobs and cause names pin every
        # identity — there is no node group to quotient by
        self.reduced = list(range(self.total))

    # -- sequence codec (length-stratified base-L positional) --

    def seq_unrank(self, r: int) -> tuple:
        k = 1
        while r >= self.n_letters ** k:
            r -= self.n_letters ** k
            k += 1
        digits = []
        for _ in range(k):
            r, d = divmod(r, self.n_letters)
            digits.append(d)
        return tuple(reversed(digits))

    def seq_rank(self, seq: tuple) -> int:
        off = sum(
            self.n_letters ** j for j in range(1, len(seq))
        )
        r = 0
        for d in seq:
            r = r * self.n_letters + d
        return off + r

    # -- scenario codec --

    def decode(self, index: int) -> CtlScenario:
        if not 0 <= index < self.total:
            raise IndexError(
                f"scenario index {index} outside [0, {self.total})"
            )
        if index < self.host_total:
            pi, sr = divmod(index, self.n_seq)
            return CtlScenario(index, pi, self.seq_unrank(sr))
        ei = index - self.host_total
        a, b = divmod(ei, len(self.scope.e2e_arrival_seeds))
        return CtlScenario(
            index, int(self.scope.e2e_policies[a]), None,
            e2e_seed=int(self.scope.e2e_arrival_seeds[b]),
        )

    def encode(self, sc: CtlScenario) -> int:
        if sc.seq is not None:
            return sc.policy * self.n_seq + self.seq_rank(sc.seq)
        a = self.scope.e2e_policies.index(sc.policy)
        b = self.scope.e2e_arrival_seeds.index(sc.e2e_seed)
        return (
            self.host_total
            + a * len(self.scope.e2e_arrival_seeds) + b
        )

    def policy_of(self, pi: int) -> ctl.ControlPolicy:
        """Materialize policy ``pi`` — the seeded shed-on-gray wedge
        rewrites the table here when armed (module doc)."""
        p = self.policies[pi]
        return ctl.wedged_policy(p) if ctl.seeded_policy_wedge() else p

    def describe(self, sc: CtlScenario) -> dict:
        d = {
            "index": sc.index,
            "policy": ctl.policy_to_dict(self.policy_of(sc.policy)),
            "policy_index": sc.policy,
        }
        if sc.seq is not None:
            d["sequence"] = [
                {
                    "causes": list(self.letters[li][0]),
                    "burn_milli": self.letters[li][1],
                }
                for li in sc.seq
            ]
        else:
            d["arrival_seed"] = int(sc.e2e_seed)
        return d


# ---------------- the host-plane oracle -----------------------------


def _trail_legal(policy: ctl.ControlPolicy, decisions) -> bool:
    """Ladder/flag transition legality of a decision trail, judged by
    predicted-state reconstruction (shared by both planes): degrade
    steps exactly one rung down (floor 0) and arms degradation, hold
    changes neither, restore steps exactly one rung up (cap top),
    disarms, and only fires when something was degraded or below
    top."""
    level, degraded = policy.top_level, False
    for dc in decisions:
        act = dc["action"]
        if act == "degrade":
            level = max(0, level - 1)
            if dc["level"] != level or not dc["degraded"]:
                return False
            degraded = True
        elif act == "hold":
            if dc["level"] != level or dc["degraded"] != degraded:
                return False
        elif act == "restore":
            if not (degraded or level < policy.top_level):
                return False
            level = min(policy.top_level, level + 1)
            if dc["level"] != level or dc["degraded"]:
                return False
            degraded = False
        else:
            return False
    return True


def judge_sequence(
    policy: ctl.ControlPolicy, letters, plan_values: int,
):
    """Drive ``decide()`` through materialized dispatch letters
    (``(cause-name tuple, burn_milli)`` pairs, dispatch ``d`` naming
    window ``d``) and judge the trail:

    - **veto** — no degrade decision covers a gray-naming window;
    - **ladder** — every named breach decides, transitions are
      :func:`_trail_legal`, restore fires exactly when owed
      (``patience`` consecutive calm low-burn dispatches AND degraded
      or below top — both directions: an early restore and a missed
      restore each break the bit);
    - **admission** — :func:`_admission_exact` over the sequence's
      degraded-floor timeline.

    Returns ``(decisions, bits)``."""
    gray = diag.CAUSE_IDS["gray-region"]
    st = ctl.ControllerState(level=policy.top_level)
    decisions: list = []
    veto_ok = ladder_ok = True
    quiet_run = 0  # decide's calm counter, tracked independently
    degr_timeline: list = []
    for d, (names, burn) in enumerate(letters, start=1):
        degr_timeline.append(st.degraded)
        new_windows = (
            [] if not names else
            [(d, tuple(sorted(diag.CAUSE_IDS[nm] for nm in names)))]
        )
        pre_level, pre_degraded = st.level, st.degraded
        dec = ctl.decide(
            policy, st, dispatch=d, burn_milli=burn,
            new_windows=new_windows,
        )
        if dec is None:
            if new_windows:
                ladder_ok = False  # a named breach must decide
            if burn <= policy.burn_low_milli:
                if quiet_run + 1 >= policy.patience and (
                    pre_degraded or pre_level < policy.top_level
                ):
                    ladder_ok = False  # restore owed, not granted
                quiet_run += 1
            else:
                quiet_run = 0
            continue
        decisions.append(dec)
        if dec["action"] == "degrade" and any(
            gray in cs for w, cs in new_windows if w in dec["windows"]
        ):
            veto_ok = False
        if dec["action"] == "restore":
            if not (
                burn <= policy.burn_low_milli
                and quiet_run + 1 >= policy.patience
                and (pre_degraded or pre_level < policy.top_level)
            ):
                ladder_ok = False  # restore granted, not owed
        quiet_run = 0
    ladder_ok = ladder_ok and _trail_legal(policy, decisions)
    admission_ok = _admission_exact(policy, degr_timeline, plan_values)
    return decisions, {
        "veto": veto_ok, "ladder": ladder_ok, "admission": admission_ok,
    }


def _collect(adm, arr, keep, admitted: dict, shed: dict) -> bool:
    ok = True
    p, k = adm.shape
    for pi in range(p):
        for s in range(k):
            vid = int(adm[pi, s])
            if vid < 0:
                continue
            if vid in admitted or vid in shed:
                ok = False  # a vid may leave the queue exactly once
            bucket = admitted if keep[pi, s] else shed
            bucket[vid] = int(arr[pi, s])
    return ok


def _admission_exact(
    policy: ctl.ControlPolicy, degr_timeline, plan_values: int,
) -> bool:
    """Exactly-once / true-stamp admission over a small two-stream
    tiered plan: the sequence's degraded timeline replays as floors,
    then the plan drains floors-off (the restore path pulls every
    deferred value).  Checks: each vid admitted XOR shed exactly
    once, union complete, admitted stamps equal the original
    arrivals (deferral never re-stamps), the shed ledger names only
    shed-band tiers and agrees with the count."""
    v = int(plan_values)
    streams = [
        np.arange(v, dtype=np.int32),
        np.arange(100, 100 + v, dtype=np.int32),
    ]
    arrivals = [
        np.arange(v, dtype=np.int32) * 3,
        np.arange(v, dtype=np.int32) * 3 + 1,
    ]
    prios = [
        np.arange(v, dtype=np.int32) % policy.n_tiers,
        (np.arange(v, dtype=np.int32) + 1) % policy.n_tiers,
    ]
    plan = ctl.ControlledPlan(streams, arrivals, prios, 4)
    k = max(plan.max_block, 1) + 2
    stamp = {
        int(vid): int(ar)
        for s, a in zip(streams, arrivals)
        for vid, ar in zip(s, a)
    }
    admitted: dict = {}
    shed: dict = {}
    ok = True
    j = 0
    for degraded in degr_timeline:
        if plan.exhausted:
            break
        sf = policy.shed_tier if degraded else None
        df = policy.defer_tier if degraded else None
        adm, arr, keep = plan.take(
            j, k, shed_floor=sf, defer_floor=df
        )
        j += 1
        ok &= _collect(adm, arr, keep, admitted, shed)
    while not plan.exhausted and j < plan.n_windows + 64:
        adm, arr, keep = plan.take(j, k)
        j += 1
        ok &= _collect(adm, arr, keep, admitted, shed)
    ok &= plan.exhausted
    ok &= not (set(admitted) & set(shed))
    ok &= (set(admitted) | set(shed)) == set(stamp)
    ok &= len(shed) == plan.shed_count == len(plan.shed_records)
    ok &= all(stamp[vid] == ar for vid, ar in admitted.items())
    ok &= all(
        r["tier"] >= policy.shed_tier for r in plan.shed_records
    )
    return bool(ok)


def violation_of(bits: dict) -> str | None:
    if not bits["veto"]:
        return "ctl-gray-veto"
    if not bits["ladder"]:
        return "ctl-ladder"
    if not bits["admission"]:
        return "ctl-admission"
    return None


def shrink_sequence(
    policy: ctl.ControlPolicy, letters_all, seq: tuple,
    plan_values: int,
) -> tuple:
    """Greedy dispatch-letter drop to fixpoint, preserving SOME
    violation (the mc fault scopes' shrink philosophy: the smallest
    sequence that still breaks a contract)."""

    def violated(s):
        _, bits = judge_sequence(
            policy, [letters_all[li] for li in s], plan_values
        )
        return violation_of(bits) is not None

    cur = list(seq)
    changed = True
    while changed:
        changed = False
        for i in range(len(cur)):
            cand = cur[:i] + cur[i + 1:]
            if cand and violated(tuple(cand)):
                cur = cand
                changed = True
                break
    return tuple(cur)


# ---------------- the repro artifact --------------------------------


def save_ctl_artifact(
    path: str, scope: ControlScope, policy: ctl.ControlPolicy,
    letters, violation: str, decisions,
) -> dict:
    """Self-contained mc-control artifact: the (possibly wedged)
    policy, the materialized dispatch letters, the violation, and the
    trail with its control-log sha — everything :func:`reproduce`
    needs, independent of the wedge env var at replay time."""
    art = {
        "engine": ARTIFACT_ENGINE,
        "scope_sha256": scope.sha256(),
        "plan_values": int(scope.plan_values),
        "policy": ctl.policy_to_dict(policy),
        "sequence": [
            {"causes": list(names), "burn_milli": int(b)}
            for names, b in letters
        ],
        "violation": violation,
        "decisions": decisions,
        "control_log_sha256": hashlib.sha256(
            ctl.control_log(decisions).encode()
        ).hexdigest(),
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(art, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return art


def reproduce(path: str) -> dict:
    """Re-execute an mc-control artifact.  The decide() trail is pure
    host arithmetic, so replay is exact: ``match`` iff the control
    log byte-compares equal (sha256) AND the decision trail AND the
    violation are identical."""
    from tpu_paxos.analysis.artifact_schema import ArtifactSchemaError

    try:
        with open(path) as f:
            art = json.load(f)
    except OSError as e:
        raise ArtifactSchemaError(
            "", f"unreadable artifact: {e}"
        ) from None
    except json.JSONDecodeError as e:
        raise ArtifactSchemaError(
            "", f"invalid JSON (truncated write?): {e}"
        ) from None
    if not isinstance(art, dict):
        raise ArtifactSchemaError("", "artifact must be a JSON object")
    for field in ("engine", "policy", "sequence", "violation",
                  "decisions", "control_log_sha256", "plan_values"):
        if field not in art:
            raise ArtifactSchemaError(
                field, "missing mc-control artifact field"
            )
    if art["engine"] != ARTIFACT_ENGINE:
        raise ArtifactSchemaError(
            "engine", "not an mc-control artifact"
        )
    policy = ctl.policy_from_dict(art["policy"])
    letters = [
        (tuple(e["causes"]), int(e["burn_milli"]))
        for e in art["sequence"]
    ]
    decisions, bits = judge_sequence(
        policy, letters, art["plan_values"]
    )
    violation = violation_of(bits) or "none"
    sha = hashlib.sha256(
        ctl.control_log(decisions).encode()
    ).hexdigest()
    return {
        "artifact": path,
        "engine": ARTIFACT_ENGINE,
        "violation": violation,
        "recorded_violation": art["violation"],
        "decision_log": ctl.control_log(decisions),
        "decision_log_sha256": sha,
        "recorded_sha256": art["control_log_sha256"],
        "decisions_match": decisions == art["decisions"],
        "match": (
            sha == art["control_log_sha256"]
            and decisions == art["decisions"]
            and violation == art["violation"]
        ),
    }


# ---------------- the e2e device cells ------------------------------


def _run_e2e_cell(enum: ControlEnum, sc: CtlScenario):
    """One device lane: the controller driving a REAL controlled
    serve run on the shared small geometry (tests/test_control.py's),
    arrival seed varying per cell, judged by the same trail checker
    as the host plane plus the on-device exactly-once ledger (shed
    vids distinct, never chosen).  Completion (``done``/backlog) is
    reported, not judged: it is workload-dependent, not a policy
    contract."""
    from tpu_paxos.config import FaultConfig, SimConfig
    from tpu_paxos.serve import harness as sh

    policy = enum.policy_of(sc.policy)
    wl = [
        np.arange(0, 10, dtype=np.int32),
        np.arange(20, 30, dtype=np.int32),
    ]
    rounds = arrv.poisson_rounds(20, 4000, int(sc.e2e_seed))
    arrs = [np.sort(rounds[0::2]), np.sort(rounds[1::2])]
    prios = [
        arrv.tier_priorities(w, n_tiers=policy.n_tiers) for w in wl
    ]
    cfg = SimConfig(
        n_nodes=3, n_instances=48, proposers=(0, 1), seed=3,
        max_rounds=4000,
        faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
    )
    slo = sh.ServeSLO(latency_rounds=16, budget_milli=150)
    rep = ctl.controlled_serve_run(
        cfg, wl, arrs, priorities=prios, control=policy,
        rounds_per_window=8, windows_per_dispatch=2, admit_width=10,
        window_rounds=32, slo=slo,
    )
    gray = diag.CAUSE_IDS["gray-region"]
    veto = not any(
        dc["action"] == "degrade" and gray in dc["cause_ids"]
        for dc in rep.decisions
    )
    ladder = _trail_legal(policy, rep.decisions)
    shed_vids = [r["vid"] for r in rep.sheds]
    chosen = {int(v) for v in np.asarray(rep.chosen_vid) if v >= 0}
    once = (
        len(shed_vids) == len(set(shed_vids))
        and not (set(shed_vids) & chosen)
    )
    bits = {"veto": veto, "ladder": ladder, "admission": once}
    info = {
        "arrival_seed": int(sc.e2e_seed),
        "dispatches": int(rep.dispatches),
        "decisions": len(rep.decisions),
        "shed": int(rep.shed_count),
        "done": bool(rep.done),
        "backlog": int(rep.backlog),
        "decision_log_sha256": rep.decision_log_sha256,
    }
    return bits, rep.decisions, info


# ---------------- chunked dispatch ----------------------------------


def run_scope(
    scope: ControlScope,
    triage_dir: str | None = None,
    verbose: bool = True,
    max_counterexamples: int = 8,
    chunk_limit: int | None = None,
) -> dict:
    """Enumerate and judge the controller scope; returns the
    ``modelcheck.run_scope``-shaped summary.  The e2e cells run FIRST
    (one chunk each — the first warms the shared controlled-window
    compile, so every later chunk reports zero) and the host plane
    follows in ``chunk_lanes``-sized chunks; verdict nibbles are
    assembled in scenario-index order regardless.  Host
    counterexamples shrink greedily and land as byte-replaying
    mc-control artifacts through the triage stack."""
    import jax

    from tpu_paxos.analysis import tracecount
    from tpu_paxos.analysis import triage as triage_mod
    from tpu_paxos.utils import log as logm

    logger = logm.get_logger(
        "mc", logm.parse_level("INFO" if verbose else "WARN")
    )
    enum = ControlEnum(scope)
    if mcm._mc_census is None:
        mcm._mc_census = tracecount.CompileCensus()
    census = mcm._mc_census.start()
    host_chunks = chunk_pad(
        list(range(enum.host_total)), scope.chunk_lanes
    )
    work = [
        ("e2e", i) for i in range(enum.host_total, enum.total)
    ] + [("host", ch) for ch in host_chunks]
    n_chunks = len(work)
    if chunk_limit:
        work = work[:chunk_limit]
    nibble_by_idx: dict = {}
    compiles_per_chunk: list[int] = []
    counterexamples: list[dict] = []
    lanes_total = 0
    t0 = time.perf_counter()  # paxlint: allow[DET001] lanes/sec metric only; never reaches artifacts
    try:
        for ci, (kind, item) in enumerate(work):
            before = census.engine_counts.get("serve_control", 0)
            judged = []
            if kind == "e2e":
                sc = enum.decode(item)
                bits, decisions, info = _run_e2e_cell(enum, sc)
                judged.append((sc, bits, decisions, info))
            else:
                chunk, n_real = item
                for idx in chunk[:n_real]:
                    sc = enum.decode(idx)
                    policy = enum.policy_of(sc.policy)
                    letters = [enum.letters[li] for li in sc.seq]
                    decisions, bits = judge_sequence(
                        policy, letters, scope.plan_values
                    )
                    judged.append((sc, bits, decisions, None))
            compiles_per_chunk.append(
                census.engine_counts.get("serve_control", 0) - before
            )
            lanes_total += len(judged)
            for sc, bits, decisions, info in judged:
                ok = (
                    bits["veto"] and bits["ladder"] and bits["admission"]
                )
                nib = (
                    (ok << 3) | (bits["veto"] << 2)
                    | (bits["ladder"] << 1) | bits["admission"]
                )
                nibble_by_idx[sc.index] = f"{nib:x}"
                if ok:
                    continue
                viol = violation_of(bits)
                cx = {
                    "scenario": enum.describe(sc),
                    "violation": viol,
                }
                if info is not None:
                    cx["e2e"] = info
                logger.error(
                    "COUNTEREXAMPLE control scenario %d: %s",
                    sc.index, viol,
                )
                if (
                    sc.seq is not None and triage_dir
                    and len(counterexamples) < max_counterexamples
                ):
                    policy = enum.policy_of(sc.policy)
                    small = shrink_sequence(
                        policy, enum.letters, sc.seq,
                        scope.plan_values,
                    )
                    letters = [enum.letters[li] for li in small]
                    sdec, sbits = judge_sequence(
                        policy, letters, scope.plan_values
                    )
                    os.makedirs(triage_dir, exist_ok=True)
                    path = os.path.join(
                        triage_dir,
                        triage_mod.dump_name(
                            "mc", f"ctl_scenario_{sc.index}", "json"
                        ),
                    )
                    save_ctl_artifact(
                        path, scope, policy, letters,
                        violation_of(sbits) or viol, sdec,
                    )
                    cx["artifact"] = path
                    cx["shrunk_dispatches"] = len(small)
                    triage_mod.prune(triage_dir)
                counterexamples.append(cx)
            if verbose and (ci % 16 == 0 or ci == len(work) - 1):
                logger.info(
                    "control chunk %d/%d: %d scenarios judged, %d "
                    "counterexamples",
                    ci + 1, len(work), lanes_total,
                    len(counterexamples),
                )
            if len(counterexamples) >= max_counterexamples:
                logger.error(
                    "counterexample budget (%d) reached after chunk "
                    "%d/%d; stopping early", max_counterexamples,
                    ci + 1, len(work),
                )
                break
    finally:
        census.stop()
    seconds = time.perf_counter() - t0  # paxlint: allow[DET001] lanes/sec metric only; never reaches artifacts
    bits_str = "".join(
        nibble_by_idx[i] for i in sorted(nibble_by_idx)
    )
    return {
        "metric": "modelcheck-control",
        "backend": jax.default_backend(),
        "scope_sha256": scope.sha256(),
        # shape pins (shared certificate fields): "alphabet" counts
        # dispatch letters, "combos" the bounded sequences
        "alphabet": enum.n_letters,
        "combos": enum.n_seq,
        "policies": enum.n_policies,
        "e2e_cells": enum.n_e2e,
        "scenarios_full": enum.total,
        "scenarios_reduced": len(enum.reduced),
        "chunk_lanes": scope.chunk_lanes,
        "chunks": n_chunks,
        "chunks_run": len(compiles_per_chunk),
        "lanes_judged": lanes_total,
        "lanes_per_sec": round(lanes_total / max(seconds, 1e-9), 2),
        "compiles_per_chunk": compiles_per_chunk,
        "verdict_bits": bits_str,
        "verdict_bits_sha256": hashlib.sha256(
            bits_str.encode()
        ).hexdigest(),
        "counterexamples": counterexamples,
        "anomalies": [],
        "seeded_wedge": mcm._seeded_wedge_flag(),
        "ok": not counterexamples,
    }
