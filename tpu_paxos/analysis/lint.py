"""paxlint engine: AST walking, reachability, pragmas, baseline, CLI.

The engine is deliberately jax-free (pure ``ast`` + stdlib): it must
run in CI images without an accelerator stack and finish in seconds.
Rule logic lives in the family modules (``rules_det``,
``rules_jax``, ``rules_ctl``);
this module owns everything shared:

- **File walk & module naming** — lints ``tpu_paxos/**/*.py`` by
  default, mapping paths to dotted module names.
- **Replay-critical reachability** — the DET rules apply to the
  import closure of the replay-critical roots (``core/``,
  ``membership/``, ``replay/``, ``harness/shrink.py``): any module
  those roots import, directly or transitively (function-level lazy
  imports count — they execute at runtime), can feed bytes into a
  decision log or repro artifact.
- **Sink functions** — a function that itself serializes or writes
  (``json.dump``, ``hashlib``, ``.write(...)``, ``np.savez``,
  ``pickle.dump``, ``print``) is order/time-escaping wherever it
  lives; DET rules also apply inside such functions outside the
  closure (this is what catches a wall-clock stamp formatted into a
  log line).
- **Pragmas** — ``# paxlint: allow[RULE]`` (comma-separated ids or
  ``*``) on the offending line, or on a standalone comment line
  immediately above it, suppresses a finding.  Put the reason in the
  rest of the comment.
- **Baseline** — ``baseline.json`` (committed) maps ``(rule, file)``
  to an allowed count, so pre-existing findings can be burned down
  without blocking CI.  Stale entries (count higher than reality) are
  themselves an error: the baseline may only shrink.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

RULES: dict[str, str] = {}  # rule id -> one-line doc (filled by families)

#: Modules whose transitive import closure is replay-critical: bytes
#: they produce are hashed/byte-compared by repro artifacts, injection
#: logs, and decision-log replay.
REPLAY_ROOTS = (
    "tpu_paxos.core",
    "tpu_paxos.membership",
    "tpu_paxos.replay",
    "tpu_paxos.harness.shrink",
    "tpu_paxos.fleet.evolve",
)

_PRAGMA_RE = re.compile(r"#\s*paxlint:\s*allow\[([A-Za-z0-9_*,\s]+)\]")

#: Call suffixes that make the enclosing function a serialization /
#: output sink (order and time escape the process there).
_SINK_CALLS = (
    "json.dump", "json.dumps", "pickle.dump", "pickle.dumps",
    "np.savez", "numpy.savez", "np.save", "numpy.save", "print",
)
_SINK_ATTRS = ("write", "hexdigest", "digest")
_SINK_PREFIXES = ("hashlib.",)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, pinned to a source location."""

    rule: str
    file: str  # posix path, relative to the lint root
    line: int
    col: int
    message: str
    hint: str

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
        }


@dataclasses.dataclass
class ModuleContext:
    """Everything a rule checker needs about one source file."""

    path: str  # posix, relative to lint root
    module: str  # dotted name ("" when outside a package)
    tree: ast.Module
    lines: list[str]
    replay_critical: bool

    def finding(self, rule: str, node: ast.AST, message: str,
                hint: str) -> Finding:
        return Finding(
            rule=rule,
            file=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
            hint=hint,
        )


# ---------------- shared AST helpers ----------------

def call_name(node: ast.AST) -> str:
    """Dotted name of a call target / attribute chain: ``time.time``,
    ``jax.config.update``, ``self.stream.write``.  '' when the chain
    bottoms out in anything but a Name (subscripts, calls, ...)."""
    if isinstance(node, ast.Call):
        node = node.func
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return ""
    parts.append(node.id)
    return ".".join(reversed(parts))


def attach_parents(tree: ast.Module) -> None:
    """Give every node a ``.paxlint_parent`` pointer (the engine's one
    tree mutation; rule modules rely on it for scope questions)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.paxlint_parent = node  # type: ignore[attr-defined]


def enclosing_function(node: ast.AST):
    """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda, or None."""
    cur = getattr(node, "paxlint_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return cur
        cur = getattr(cur, "paxlint_parent", None)
    return None


def is_sink_function(func: ast.AST) -> bool:
    """Does this function body itself serialize/write/print?  (Nested
    function defs are separate scopes and do not count.)"""
    for node in _walk_scope(func):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if not name:
            continue
        if name in _SINK_CALLS or name.startswith(_SINK_PREFIXES):
            return True
        if name.rsplit(".", 1)[-1] in _SINK_ATTRS and "." in name:
            return True
    return False


def _walk_scope(func: ast.AST):
    """Walk a function body without descending into nested defs."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ---------------- pragmas ----------------

def pragma_map(lines: list[str]) -> dict[int, set[str]]:
    """1-based line -> set of allowed rule ids ('*' allows all).  A
    pragma on a standalone comment line also covers the next line."""
    allowed: dict[int, set[str]] = {}
    for i, text in enumerate(lines, start=1):
        m = _PRAGMA_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        allowed.setdefault(i, set()).update(rules)
        if text.lstrip().startswith("#"):  # standalone comment line
            allowed.setdefault(i + 1, set()).update(rules)
    return allowed


def _suppressed(f: Finding, allowed: dict[int, set[str]]) -> bool:
    rules = allowed.get(f.line, ())
    return f.rule in rules or "*" in rules


# ---------------- file walk & import closure ----------------

#: Default lint scope: the package AND the test/example trees (tests
#: assert on serialized engine output and examples are copy-paste
#: templates — a nondeterministic pattern in either propagates).
DEFAULT_SCOPE = ("tpu_paxos", "tests", "examples", "scripts")


def walk_files(root: str, paths: list[str] | None = None) -> list[str]:
    """Python files to lint, as posix paths relative to ``root``.
    Default target: every ``DEFAULT_SCOPE`` directory that exists
    under ``root`` (at minimum the ``tpu_paxos`` package)."""
    if paths:
        out: list[str] = []
        for p in paths:
            full = p if os.path.isabs(p) else os.path.join(root, p)
            if os.path.isdir(full):
                for dirpath, _dirs, files in sorted(os.walk(full)):
                    out.extend(
                        os.path.join(dirpath, f)
                        for f in sorted(files) if f.endswith(".py")
                    )
            elif os.path.exists(full):
                out.append(full)
            else:
                # a typo'd CI path must fail loudly, not lint nothing
                # and report clean
                raise FileNotFoundError(f"lint path does not exist: {p}")
        # dedupe: overlapping arguments (a dir plus a file inside it)
        # must not lint a file twice — duplicates double-count
        # findings past the baseline
        return sorted({
            os.path.relpath(f, root).replace(os.sep, "/") for f in out
        })
    out = []
    for top in DEFAULT_SCOPE:
        d = os.path.join(root, top)
        if not os.path.isdir(d):
            continue  # a bare package checkout still lints
        for dirpath, _dirs, files in sorted(os.walk(d)):
            out.extend(
                os.path.join(dirpath, f) for f in sorted(files)
                if f.endswith(".py")
            )
    return sorted(
        os.path.relpath(f, root).replace(os.sep, "/") for f in out
    )


def module_name(relpath: str) -> str:
    """Dotted module name for a repo-relative path ('' if the path is
    not inside a package directory we recognize)."""
    if not relpath.endswith(".py"):
        return ""
    mod = relpath[:-3].replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _module_imports(
    tree: ast.Module, module: str, is_pkg: bool = False
) -> set[str]:
    """Dotted names this module imports (absolute + resolved relative),
    including function-level lazy imports — those still execute."""
    out: set[str] = set()
    # anchor for relative imports: level 1 means the containing
    # package — the module itself when this is a package __init__,
    # its parent otherwise
    anchor = module.split(".") if is_pkg else module.split(".")[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            out.update(a.name for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative
                base = anchor[: len(anchor) - (node.level - 1)]
                prefix = ".".join(base + ([node.module] if node.module else []))
            else:
                prefix = node.module or ""
            if prefix:
                out.add(prefix)
                out.update(f"{prefix}.{a.name}" for a in node.names)
    return out


@dataclasses.dataclass
class ParsedFile:
    """One source file, read and parsed exactly once per lint run
    (shared by the closure builder and the rule walk)."""

    source: str | None  # None: unreadable
    tree: ast.Module | None  # None: unreadable or syntax error
    error: SyntaxError | None = None


def parse_all(files: list[str], root: str) -> dict[str, ParsedFile]:
    out: dict[str, ParsedFile] = {}
    for rel in files:
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            out[rel] = ParsedFile(None, None)
            continue
        try:
            out[rel] = ParsedFile(source, ast.parse(source, filename=rel))
        except SyntaxError as e:
            out[rel] = ParsedFile(source, None, e)
    return out


def replay_closure(
    files: list[str], root: str,
    parsed: dict[str, ParsedFile] | None = None,
) -> set[str]:
    """Modules reachable (by import) from the replay-critical roots."""
    if parsed is None:
        parsed = parse_all(files, root)
    graph: dict[str, set[str]] = {}
    names: set[str] = set()
    for rel in files:
        mod = module_name(rel)
        if not mod:
            continue
        names.add(mod)
        tree = parsed[rel].tree if rel in parsed else None
        if tree is None:
            continue
        graph[mod] = _module_imports(
            tree, mod, is_pkg=rel.endswith("/__init__.py")
        )
    def expand(mod: str) -> set[str]:
        """Direct imports plus ancestor packages: importing a
        submodule executes every package ``__init__`` above it."""
        out = set(graph.get(mod, ()))
        for dep in list(out) + [mod]:
            while "." in dep:
                dep = dep.rsplit(".", 1)[0]
                out.add(dep)
        return {d for d in out if d in names}

    closure = {
        m for m in names
        if any(m == r or m.startswith(r + ".") for r in REPLAY_ROOTS)
    }
    frontier = list(closure)
    while frontier:
        for dep in expand(frontier.pop()):
            if dep not in closure:
                closure.add(dep)
                frontier.append(dep)
    return closure


# ---------------- baseline ----------------

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str | None) -> dict[tuple[str, str], int]:
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return {
        (e["rule"], e["file"]): int(e["count"])
        for e in data.get("entries", [])
    }


def apply_baseline(
    findings: list[Finding], baseline: dict[tuple[str, str], int]
) -> tuple[list[Finding], list[dict]]:
    """Subtract baselined findings.  Returns (remaining, stale) where
    ``stale`` lists baseline entries whose count exceeds what the code
    still produces — those must be removed from baseline.json."""
    budget = dict(baseline)
    remaining: list[Finding] = []
    for f in findings:
        key = (f.rule, f.file)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            remaining.append(f)
    stale = [
        {"rule": rule, "file": file, "unused": left}
        for (rule, file), left in sorted(budget.items()) if left > 0
    ]
    return remaining, stale


# ---------------- engine ----------------

def lint_files(
    root: str,
    paths: list[str] | None = None,
    replay_critical_override: bool | None = None,
    files: list[str] | None = None,
) -> list[Finding]:
    """Lint files under ``root`` and return pragma-filtered findings
    (baseline NOT applied — that is the caller's policy decision).
    ``files`` lets a caller that already walked the tree skip the
    second walk."""
    from tpu_paxos.analysis import (
        rules_ctl, rules_det, rules_jax, rules_shard,
    )

    if files is None:
        files = walk_files(root, paths)
    parsed = parse_all(files, root)
    closure = replay_closure(files, root, parsed)
    findings: list[Finding] = []
    for rel in files:
        pf = parsed[rel]
        if pf.source is None:
            continue
        if pf.tree is None:
            e = pf.error
            findings.append(Finding(
                rule="PARSE", file=rel, line=(e.lineno if e else 1) or 1,
                col=(e.offset if e else 0) or 0,
                message=f"syntax error: {e.msg if e else 'unparseable'}",
                hint="fix the syntax error; paxlint needs a parseable file",
            ))
            continue
        source, tree = pf.source, pf.tree
        mod = module_name(rel)
        critical = (
            replay_critical_override
            if replay_critical_override is not None
            else mod in closure
        )
        ctx = ModuleContext(
            path=rel, module=mod, tree=tree,
            lines=source.splitlines(), replay_critical=critical,
        )
        attach_parents(tree)
        raw = (rules_det.check_module(ctx) + rules_jax.check_module(ctx)
               + rules_ctl.check_module(ctx)
               + rules_shard.check_module(ctx))
        allowed = pragma_map(ctx.lines)
        findings.extend(f for f in raw if not _suppressed(f, allowed))
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings


def lint_source(
    source: str, path: str = "fixture.py", replay_critical: bool = True
) -> list[Finding]:
    """Lint a source string (the fixture-test entry point)."""
    from tpu_paxos.analysis import (
        rules_ctl, rules_det, rules_jax, rules_shard,
    )

    tree = ast.parse(source, filename=path)
    ctx = ModuleContext(
        path=path, module=module_name(path), tree=tree,
        lines=source.splitlines(), replay_critical=replay_critical,
    )
    attach_parents(tree)
    raw = (rules_det.check_module(ctx) + rules_jax.check_module(ctx)
               + rules_ctl.check_module(ctx)
               + rules_shard.check_module(ctx))
    allowed = pragma_map(ctx.lines)
    out = [f for f in raw if not _suppressed(f, allowed)]
    out.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return out


def run_lint(
    root: str | None = None,
    paths: list[str] | None = None,
    baseline_path: str | None = DEFAULT_BASELINE,
) -> dict:
    """Full lint run as a JSON-ready report dict (the CLI's payload).
    ``ok`` is True iff zero unsuppressed findings AND zero stale
    baseline entries."""
    root = root or os.getcwd()
    files = walk_files(root, paths)
    raw = lint_files(root, paths, files=files)
    remaining, stale = apply_baseline(raw, load_baseline(baseline_path))
    if paths:
        # path-scoped run: baseline entries for files outside the
        # selection were never given a chance to match — only judge
        # staleness for files actually linted
        selected = set(files)
        stale = [s for s in stale if s["file"] in selected]
    counts: dict[str, int] = {}
    for f in remaining:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "version": 1,
        # zero files is a misconfiguration (wrong --root), not a clean
        # tree — never report ok for a lint that looked at nothing
        "ok": bool(files) and not remaining and not stale,
        "files": len(files),
        "findings": [f.to_json() for f in remaining],
        "baselined": len(raw) - len(remaining),
        "stale_baseline": stale,
        "counts": dict(sorted(counts.items())),
    }


def main(argv=None) -> int:
    """``python -m tpu_paxos lint`` — exits 0 iff the tree is clean."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tpu_paxos lint",
        description="paxlint: determinism & JAX-purity static analysis",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the tpu_paxos "
                    "package under --root)")
    ap.add_argument("--root", default=os.getcwd(),
                    help="repo root paths are reported relative to")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON report on stdout")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (committed known findings)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--rules", action="store_true",
                    help="list rule ids and exit")
    ap.add_argument("--fix", action="store_true",
                    help="emit mechanical rewrites for the findings "
                    "(sorted() wraps for DET003, pragma scaffolds "
                    "with TODO reasons elsewhere) as a unified diff")
    ap.add_argument("--write", action="store_true",
                    help="with --fix: apply the rewrites in place "
                    "instead of printing the diff")
    args = ap.parse_args(argv)
    if args.write and not args.fix:
        ap.error("--write requires --fix")
    if args.fix and args.json:
        ap.error("--fix does not support --json (the diff IS the "
                 "output; run a plain --json pass for the report)")
    if args.rules:
        from tpu_paxos.analysis import (  # noqa: F401
            rules_ctl, rules_det, rules_jax, rules_shard,
        )

        for rid, doc in sorted(RULES.items()):
            print(f"{rid}  {doc}")
        return 0
    try:
        report = run_lint(
            root=args.root,
            paths=args.paths or None,
            baseline_path=None if args.no_baseline else args.baseline,
        )
    except FileNotFoundError as e:
        print(f"paxlint: {e}")
        return 2
    if args.fix:
        from tpu_paxos.analysis import fix as fixm

        plans = fixm.plan_fixes(report, args.root)
        if args.write:
            try:
                written = fixm.apply_fixes(plans, args.root)
            except RuntimeError as e:
                print(f"paxlint --fix: {e}")
                return 2
            for rel in written:
                print(f"fixed: {rel}")
            print(
                f"paxlint --fix: {len(written)} file"
                f"{'s' if len(written) != 1 else ''} rewritten — "
                "re-run `make lint`; replace every scaffolded TODO "
                "reason before review"
            )
        else:
            diff = fixm.render_diff(plans)
            if diff:
                print(diff, end="")
            print(
                f"paxlint --fix (dry run): {len(plans)} file"
                f"{'s' if len(plans) != 1 else ''} would change — "
                "apply with `lint --fix --write`"
            )
        # fix mode reports what it would do; the exit code still
        # reflects the tree as it stands
        return 0 if report["ok"] else 1
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        for f in report["findings"]:
            print(
                f"{f['file']}:{f['line']}:{f['col']}: {f['rule']} "
                f"{f['message']}\n    hint: {f['hint']}"
            )
        for s in report["stale_baseline"]:
            print(
                f"baseline.json: stale entry {s['rule']} for "
                f"{s['file']} ({s['unused']} unused) — remove it"
            )
        if not report["files"]:
            print(
                f"paxlint: no python files found under {args.root!r} "
                "(wrong --root?)"
            )
        n = len(report["findings"])
        print(
            f"paxlint: {report['files']} files, "
            f"{n} finding{'s' if n != 1 else ''}, "
            f"{report['baselined']} baselined, "
            f"{len(report['stale_baseline'])} stale baseline entries"
        )
    return 0 if report["ok"] else 1
