"""shard-audit: mesh-polymorphic SPMD contracts for the registered
entries.

The fifth static-analysis tier.  paxlint reads source, the jaxpr
audit reads traced IR, the hlo audit reads ONE compiled artifact, and
the model checker certifies lane semantics on the host — none of them
can see how a program CHANGES as the mesh reshapes.  That is exactly
where SPMD bugs live: a state leaf nobody ruled silently replicates
to every device, an accidental collective appears only once the tile
spans two devices, and a subtly mesh-dependent lane program produces
verdicts that drift between a 1-chip dev box and an 8-chip pod.  This
tier lowers every opted-in :class:`~tpu_paxos.analysis.registry.
AuditEntry` under a virtual mesh grid (``MESH_GRID``, truncated to
the devices the host exposes) and enforces four contracts:

- **SH301 — partition-rule coverage.**  Every array leaf of every
  registered stacked-state pytree (``entry.shard_state``) must match
  a rule of the committed partition table
  (``parallel/partition_rules.py``); unmatched leaves fail BY PYTREE
  PATH, rules matching no leaf are stale and fail like dead budget
  entries.  The engines build their specs from the same table, so the
  audit certifies the layout the runtime actually uses.
- **SH302 — replication-waste ceilings.**  Per mesh shape, each
  compiled entry's per-device peak bytes
  (``compiled.memory_analysis()``) stay under the pinned ceilings in
  ``analysis/shard_budget.json`` — a leaf that stops splitting shows
  up as a flat bytes curve and breaches the large-mesh ceilings.
- **SH303 — collective census.**  Per mesh shape, the compiled
  module's all-reduce / all-gather / collective-permute /
  reduce-scatter counts equal the pinned counts EXACTLY (both
  directions; see ``shard_rules`` for why there is no headroom).
- **SH304 — cross-mesh parity certificates.**  The fleet drivers
  (``entry.shard_parity``) run end to end per mesh shape; per-lane
  verdict nibbles + per-lane decision-log sha256 must be bitwise
  identical across every shape AND match the pinned
  ``analysis/shard_certificate.json``.  Drift names the first
  diverging (entry, mesh, lane) — the reproduction target.

``python -m tpu_paxos audit --shard`` (what ``make shard-audit``
runs via ``--shard-only``) adds this tier after the jaxpr tier.
Re-pin: ``TPU_PAXOS_SHARD_PIN=1`` for the certificate,
``TPU_PAXOS_SHARD_BUDGET_PIN=1`` for the budget (both under the
make audit env so the host exposes the full 8-device grid); pinning
refuses while ``TPU_PAXOS_SHARD_WEDGE`` arms a seeded regression.

Import discipline: jax only inside :func:`run_shard_audit`; the
rules/budget/certificate layer (``shard_rules``) and the partition
table's matching logic are stdlib-only.
"""

from __future__ import annotations

import json
import os

from tpu_paxos.analysis import hlo_norm, shard_rules as shr, triage
from tpu_paxos.analysis import registry as regm

#: The committed virtual mesh grid.  Powers of two up to one host's
#: ``--xla_force_host_platform_device_count=8`` (the make audit env);
#: every shard_build/shard_parity geometry is sized to divide 8.
MESH_GRID = (1, 2, 4, 8)


def _wedge() -> str:
    """The armed seeded-regression wedge ('' = none)."""
    w = os.environ.get(shr.WEDGE_ENV, "")
    if w and w not in shr.WEDGES:
        raise ValueError(
            f"unknown {shr.WEDGE_ENV} value {w!r} — one of "
            f"{', '.join(shr.WEDGES)}"
        )
    return w


def usable_grid(grid=MESH_GRID) -> tuple:
    """The grid shapes this host can actually build (virtual devices
    come from --xla_force_host_platform_device_count; a bare
    interpreter may expose only 1)."""
    import jax

    n = len(jax.devices())
    return tuple(g for g in grid if g <= n)


def run_shard_audit(
    providers=regm.AUDIT_PROVIDERS,
    budget_path: str | None = shr.DEFAULT_BUDGET,
    cert_path: str | None = shr.DEFAULT_CERT,
    pin: bool = False,
    pin_budget: bool = False,
    triage_dir: str = "stress-triage",
    grid=MESH_GRID,
) -> dict:
    """Run the four SH contracts over the registered entries; returns
    a JSON-ready report (``ok`` iff coverage clean AND budget clean /
    unenforceable AND parity clean).  ``pin`` re-pins the certificate
    from the 1-device runs, ``pin_budget`` the per-mesh budget — both
    refuse while a wedge is armed (the pin would enshrine the seeded
    bug)."""
    import jax

    from tpu_paxos.analysis import hlo_audit
    from tpu_paxos.parallel import mesh as pmesh
    from tpu_paxos.parallel import partition_rules as prules

    wedge = _wedge()
    if (pin or pin_budget) and wedge:
        raise regm.RegistryError(
            f"shard-audit: refusing to pin with {shr.WEDGE_ENV}={wedge} "
            "— the pin would enshrine the seeded bug"
        )

    backend = jax.default_backend()
    jax_version = jax.__version__
    entries = regm.collect(providers)
    full = tuple(providers) == tuple(regm.AUDIT_PROVIDERS)
    shapes = usable_grid(grid)
    full_grid = full and tuple(shapes) == tuple(grid)
    dumped: list[str] = []

    # ---- SH301: partition-rule coverage over the stacked states ----
    trees: dict = {}
    for e in entries:
        if e.shard_state is not None:
            trees[e.name] = e.shard_state()
    if wedge == "unruled-leaf":
        import numpy as np

        # a synthetic state family no table row covers — proves an
        # unruled leaf fails loudly, named by path
        trees["__wedge__"] = ("wedge", {"unruled": np.zeros((2, 2))})
    cov = prules.coverage(trees)
    if not full:
        cov["stale_rules"] = []  # scoped runs never see every family
    coverage_bad = bool(
        cov["unmatched"] or cov["rank"] or cov["stale_rules"]
    )

    # ---- SH302 + SH303: per-mesh compile census --------------------
    measured: dict = {}
    texts: dict[str, str] = {}
    grid_entries = [e for e in entries if e.shard_build is not None]
    for e in grid_entries:
        per_mesh: dict = {}
        for n in shapes:
            fn, args = e.shard_build(pmesh.make_instance_mesh(n))
            lowerable = fn if hasattr(fn, "lower") else jax.jit(fn)
            compiled = lowerable.lower(*args).compile()
            text = compiled.as_text() or ""
            census = shr.collective_census(
                hlo_norm.opcode_histogram(text)
            )
            cell = {
                "bytes_per_device": int(
                    hlo_audit.memory_ceiling(compiled)["mem_bytes"]
                ),
                "collectives": census,
            }
            per_mesh[str(n)] = cell
            texts[f"{e.name}@mesh{n}"] = text
        measured[e.name] = per_mesh
    if wedge == "undeclared-collective" and measured:
        # inject one phantom collective at the largest shape of the
        # first entry — the census must fail naming (entry, mesh, op)
        name = sorted(measured)[0]
        cell = measured[name][str(shapes[-1])]
        cell["collectives"]["collective-permute"] += 1

    budget = shr.load_budget(budget_path) if budget_path else {}
    violations: list[dict] = []
    stale: list[str] = []
    enforced = False
    if pin_budget:
        path = budget_path or shr.DEFAULT_BUDGET
        existing = shr.load_budget(path)
        keep = None if full_grid else {
            n: caps
            for n, caps in sorted(existing.get("entries", {}).items())
            if n not in measured and existing.get("backend") == backend
        }
        shr.save_budget(measured, path, backend, jax_version, keep=keep)
    elif budget_path:
        violations, stale, enforced = shr.check_budget(
            measured, budget, backend, full_grid
        )

    # ---- SH304: cross-mesh parity ----------------------------------
    results: dict = {}
    for e in entries:
        if e.shard_parity is None:
            continue
        results[e.name] = {
            str(n): e.shard_parity(n) for n in shapes
        }
    if wedge == "parity-fork" and results:
        # flip lane 0's verdict nibble at the largest multi-device
        # shape of the first parity entry — the certificate must fail
        # naming the first diverging (entry, mesh, lane)
        name = sorted(results)[0]
        forked = [n for n in shapes if n > 1]
        if forked:
            cell = results[name][str(forked[-1])]
            v = cell["verdicts"]
            cell["verdicts"] = (
                format(int(v[0], 16) ^ 0x1, "x") + v[1:]
            )
    pinned_cert = shr.load_certificate(cert_path) if cert_path else {}
    parity_failures: list[dict] = []
    if pin:
        ones = {
            name: per_mesh["1"]
            for name, per_mesh in sorted(results.items())
            if "1" in per_mesh
        }
        # mesh invariance is still judged while pinning — a pin must
        # not paper over a fork between shapes of THIS run
        parity_failures = [
            f for f in shr.check_certificate({}, results, full=False)
            if f["mesh"] != 1
        ]
        if not parity_failures:
            existing = shr.load_certificate(cert_path or shr.DEFAULT_CERT)
            if not full:
                for name, cert in sorted(
                    existing.get("entries", {}).items()
                ):
                    ones.setdefault(name, cert)
            shr.save_certificate(
                ones, cert_path or shr.DEFAULT_CERT, backend, jax_version
            )
    elif cert_path:
        parity_failures = shr.check_certificate(
            pinned_cert, results, full=full_grid
        )

    # ---- triage dumps ----------------------------------------------
    for v in violations:
        key = f"{v['entry']}@mesh{v['mesh']}"
        if key in texts:
            try:
                dumped.append(triage.write_dump(
                    triage_dir, "shard", key, texts[key], ext="txt"
                ))
            except OSError:
                pass  # read-only checkout must not mask the breach
    for f in parity_failures:
        name = f["entry"]
        if name in results:
            try:
                dumped.append(triage.write_dump(
                    triage_dir, "shard", name,
                    json.dumps(results[name], indent=1, sort_keys=True),
                    ext="json",
                ))
            except OSError:
                pass

    report = {
        "version": 1,
        "backend": backend,
        "jax": jax_version,
        "grid": list(shapes),
        "grid_truncated": list(shapes) != list(grid),
        "enforced": bool(enforced),
        "wedge": wedge,
        "coverage": cov,
        "budget": {
            "path": budget_path or "",
            "pinned": bool(pin_budget),
            "violations": violations,
            "stale": stale,
        },
        "parity": {
            "path": cert_path or "",
            "pinned": bool(pin),
            "entries": {
                name: sorted(per_mesh, key=int)
                for name, per_mesh in sorted(results.items())
            },
            "failures": parity_failures,
        },
        "dumped": sorted(set(dumped)),
        "ok": not coverage_bad and not violations and not stale
        and not parity_failures,
    }
    return report
