"""CTL rule family: the cause-code naming contract, statically.

The contract (telemetry/diagnose.py): every breach cause has exactly
one integer wire code, assigned in ``CAUSE_IDS`` canonical order —
codes never move, but they are an *encoding*, not an API.  Host code
that compares against a raw integer (``dc["cause_id"] == 2``) keeps
working right up until someone reads the table, wonders what 2 means,
and "fixes" it — or until a new cause is appended and a reviewer has
to re-derive which literals are load-bearing.  The one sanctioned
spelling is the named lookup: ``diag.CAUSE_IDS["gray-region"]`` /
``diag.cause_code(name)``.

Rules (scope: every linted module; the single path exemption is
``telemetry/diagnose.py``, which OWNS the table and necessarily
relates names to integers):

- CTL001  an integer literal compared (``==``/``!=``/``in``/``not
          in``/ordering) against a cause-code expression — any side
          of the comparison whose source mentions ``cause``
          (``cause_id``, ``cause_ids``, ``cause_code(...)``,
          ``CAUSE_IDS[...]``...).  Spell the code by name.
"""

from __future__ import annotations

import ast

from tpu_paxos.analysis import lint

lint.RULES.update({
    "CTL001": "integer cause-code literal compared against a cause "
              "expression outside telemetry/diagnose.py",
})

#: The module that owns the name<->code table: relating literals to
#: names is its whole job.
_TABLE_OWNER = "tpu_paxos/telemetry/diagnose.py"


def _pragma_hint(rule: str) -> str:
    return f"or mark intentional: `# paxlint: allow[{rule}] <reason>`"


def _is_int_literal(expr: ast.AST) -> bool:
    # bool is an int subclass; True/False are not wire codes
    return (
        isinstance(expr, ast.Constant)
        and type(expr.value) is int
    )


def _mentions_cause(expr: ast.AST) -> bool:
    """Does the expression's source spell ``cause`` anywhere — a
    ``cause_id`` key, a ``cause_code()`` call, a ``CAUSE_IDS`` row?
    Source-level on purpose: the cause vocabulary is a naming
    convention, and the rule polices exactly that convention."""
    try:
        return "cause" in ast.unparse(expr).lower()
    except Exception:  # pragma: no cover - unparse is total on exprs
        return False


def check_module(ctx: lint.ModuleContext) -> list[lint.Finding]:
    if ctx.path.replace("\\", "/").endswith(_TABLE_OWNER):
        return []
    findings: list[lint.Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left, *node.comparators]
        lits = [s for s in sides if _is_int_literal(s)]
        if not lits:
            continue
        if not any(
            _mentions_cause(s) for s in sides if not _is_int_literal(s)
        ):
            continue
        code = lits[0].value
        findings.append(ctx.finding(
            "CTL001", node,
            f"raw cause-code literal {code} in a comparison — wire "
            "codes are an encoding, not an API",
            "spell it by name: diag.CAUSE_IDS[\"<cause>\"] or "
            "diag.cause_code(\"<cause>\"); "
            + _pragma_hint("CTL001"),
        ))
    return findings
