"""DET rule family: the determinism/replay contract, statically.

The contract (README "Determinism"): an engine run is a pure function
of (seed, config, schedule); decision logs, repro artifacts, and
injection logs byte-compare equal across record and replay.  Anything
that lets wall-clock time, hash-seed-dependent iteration order, or
unseeded randomness reach those bytes breaks replay in ways no fixed-
seed unit test can see.

Rules (scope: the replay-critical import closure, plus — for DET001/
DET002/DET003 — any *sink function* that itself serializes/writes,
wherever it lives; see lint.py for both definitions):

- DET001  wall-clock reads (``time.time``/``strftime``/
          ``perf_counter``/``datetime.now``...).
- DET002  unseeded randomness (``random.*`` module functions, legacy
          ``np.random.*`` globals, argless ``default_rng()``,
          ``os.urandom``, ``uuid.uuid*``, ``secrets.*``).
- DET003  unordered iteration where order escapes: iterating a
          set-typed expression unsorted (``for``/comprehension/
          ``join``/``list``/``tuple``/``*``-unpack), or iterating
          ``.items()``/``.keys()``/``.values()`` inside a sink
          function.  ``sorted(...)`` at the iteration site clears it.
          Dataflow-aware through locals: ``s = set(...); for x in s``
          is caught too — a name every assignment of which (in its
          function scope) is a set expression / dict view carries
          that kind to its iteration sites; any other rebinding
          (non-set assignment, loop target, unpacking) clears it.
- DET004  ``jax.config.update`` anywhere outside ``utils/prng.py`` —
          config flags can change sampled values (the PR 1 threefry
          incident), so the one sanctioned home is the prng module
          that owns the determinism contract.
"""

from __future__ import annotations

import ast

from tpu_paxos.analysis import lint

lint.RULES.update({
    "DET001": "wall-clock read in replay-critical code or a "
              "serialization sink",
    "DET002": "unseeded randomness in replay-critical code or a "
              "serialization sink",
    "DET003": "unordered set/dict iteration where order escapes the "
              "process",
    "DET004": "jax.config.update outside utils/prng.py",
})

_WALL_CLOCK = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.localtime",
    "time.gmtime", "time.strftime", "time.ctime", "time.asctime",
    "datetime.datetime.now", "datetime.now", "datetime.datetime.utcnow",
    "datetime.utcnow", "datetime.date.today", "date.today",
    "datetime.today",
}

_RANDOM_EXACT = {"os.urandom", "uuid.uuid1", "uuid.uuid4"}
_RANDOM_PREFIXES = ("random.", "np.random.", "numpy.random.", "secrets.")

#: Order-insensitive consumers: a set expression inside these is fine.
_ORDER_SAFE_CALLS = {
    "sorted", "len", "min", "max", "sum", "any", "all", "set",
    "frozenset", "bool",
}

#: Iteration-forcing calls whose argument order escapes into the
#: result (and typically onward into output).
_ITER_CALLS = {"list", "tuple", "enumerate", "iter", "next", "str",
               "repr", "format"}

_DICT_VIEW_METHODS = {"keys", "values", "items"}

#: jax modules namespace the seeded counter-based PRNG lives in —
#: never flagged by DET002.
_SEEDED_PREFIXES = ("jax.random.", "prng.", "jrandom.")


def _pragma_hint(rule: str) -> str:
    return f"or mark intentional: `# paxlint: allow[{rule}] <reason>`"


def check_module(ctx: lint.ModuleContext) -> list[lint.Finding]:
    findings: list[lint.Finding] = []
    sink_cache: dict[ast.AST, bool] = {}
    kinds_cache: dict[ast.AST, dict[str, str]] = {}

    def in_scope(node: ast.AST) -> bool:
        """DET001-003 scope: replay closure, or inside a sink fn."""
        if ctx.replay_critical:
            return True
        fn = lint.enclosing_function(node)
        if fn is None:
            return False
        if fn not in sink_cache:
            sink_cache[fn] = lint.is_sink_function(fn)
        return sink_cache[fn]

    def local_kinds(node: ast.AST) -> dict[str, str]:
        """Set/dict-view locals of the scope containing ``node`` (the
        dataflow side of DET003), computed once per scope."""
        scope = lint.enclosing_function(node) or ctx.tree
        if scope not in kinds_cache:
            kinds_cache[scope] = _scope_kinds(scope)
        return kinds_cache[scope]

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = lint.call_name(node)
            if not name:
                continue
            _check_wall_clock(ctx, node, name, in_scope, findings)
            _check_randomness(ctx, node, name, in_scope, findings)
            _check_config_update(ctx, node, name, findings)
        itered = _iterated_exprs(node)
        for expr in itered:
            _check_unordered(
                ctx, node, expr, in_scope, findings, local_kinds
            )
    return findings


# ---------------- DET001 / DET002 / DET004 ----------------

def _check_wall_clock(ctx, node, name, in_scope, findings) -> None:
    if name in _WALL_CLOCK and in_scope(node):
        findings.append(ctx.finding(
            "DET001", node,
            f"wall-clock read `{name}()` can reach replayed/serialized "
            "bytes",
            "gate it behind utils/log.deterministic_mode() (zeroed "
            "stamps) or move timing out of the serialization path; "
            + _pragma_hint("DET001"),
        ))


def _check_randomness(ctx, node, name, in_scope, findings) -> None:
    if name.startswith(_SEEDED_PREFIXES):
        return
    unseeded = (
        name in _RANDOM_EXACT
        or (name.startswith(_RANDOM_PREFIXES)
            # seeded constructions are the sanctioned pattern
            and not (name.endswith(".default_rng") and node.args))
    )
    if unseeded and in_scope(node):
        findings.append(ctx.finding(
            "DET002", node,
            f"unseeded randomness `{name}()` in replay-critical code",
            "derive randomness from utils/prng streams (pure function "
            "of seed/tag/round) or seed an explicit Generator; "
            + _pragma_hint("DET002"),
        ))


def _check_config_update(ctx, node, name, findings) -> None:
    if name != "jax.config.update":
        return
    if ctx.path.replace("\\", "/").endswith("tpu_paxos/utils/prng.py"):
        return
    findings.append(ctx.finding(
        "DET004", node,
        "jax.config.update outside utils/prng.py — config flags can "
        "silently change sampled values (the threefry incident)",
        "move determinism-relevant flags into utils/prng.py; for "
        "value-neutral platform/provisioning flags, "
        + _pragma_hint("DET004"),
    ))


# ---------------- DET003 ----------------

def _iterated_exprs(node: ast.AST) -> list[ast.AST]:
    """Expressions whose iteration order this node consumes."""
    out: list[ast.AST] = []
    if isinstance(node, ast.For):
        out.append(node.iter)
    elif isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                           ast.DictComp)):
        out.extend(gen.iter for gen in node.generators)
    elif isinstance(node, ast.Starred):
        out.append(node.value)
    elif isinstance(node, ast.Call):
        name = lint.call_name(node)
        if name in _ITER_CALLS and node.args:
            out.append(node.args[0])
        elif name.endswith(".join") and node.args:
            out.append(node.args[0])
    return out


_EMPTY_KINDS: dict[str, str] = {}


def _is_set_expr(expr: ast.AST, kinds: dict[str, str] = _EMPTY_KINDS) -> bool:
    """Syntactic evidence that ``expr`` is a set (hash-ordered).
    ``kinds`` resolves local names the dataflow pass proved set-typed
    (``s = set(...)`` ... ``s``)."""
    if isinstance(expr, ast.Set):
        return True
    if isinstance(expr, ast.SetComp):
        return True
    if isinstance(expr, ast.Name):
        return kinds.get(expr.id) == "set"
    if isinstance(expr, ast.Call):
        name = lint.call_name(expr)
        if name in ("set", "frozenset"):
            return True
        # repo idiom: accessors named *_set() return sets
        # (MemberSim.crashed_set / acceptor_set / learner_set)
        if name.rsplit(".", 1)[-1].endswith("_set"):
            return True
        if name.rsplit(".", 1)[-1] in (
            "union", "intersection", "difference", "symmetric_difference"
        ):
            return True
    if isinstance(expr, ast.BinOp) and isinstance(
        expr.op, (ast.Sub, ast.BitAnd, ast.BitOr, ast.BitXor)
    ):
        return (
            _is_set_expr(expr.left, kinds)
            or _is_set_expr(expr.right, kinds)
        )
    return False


def _is_dict_view(expr: ast.AST, kinds: dict[str, str] = _EMPTY_KINDS) -> bool:
    if isinstance(expr, ast.Name):
        return kinds.get(expr.id) == "dictview"
    return (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr in _DICT_VIEW_METHODS
        and not expr.args
    )


def _expr_kind(expr: ast.AST, kinds: dict[str, str]) -> str | None:
    if _is_set_expr(expr, kinds):
        return "set"
    if _is_dict_view(expr, kinds):
        return "dictview"
    return None


def _scope_kinds(scope: ast.AST) -> dict[str, str]:
    """Dataflow pass for DET003: names in ``scope`` (a function or the
    module; nested defs are separate scopes) whose EVERY binding is a
    set expression or dict view.  Conservative by construction — any
    other binding (non-set assignment, for-target, unpacking, walrus)
    poisons the name, so a reassigned local never false-positives.
    Name-to-name chains (``t = s``) resolve via a short fixpoint."""
    walk = list(lint._walk_scope(scope))
    # parameters are caller-controlled: a param conditionally shadowed
    # by a set assignment (`if s is None: s = set(...)`) must never be
    # tracked — the caller may pass a sorted list
    always_bad: set[str] = set()
    args = getattr(scope, "args", None)
    if args is not None:
        always_bad.update(
            a.arg for a in args.posonlyargs + args.args + args.kwonlyargs
        )
        for va in (args.vararg, args.kwarg):
            if va is not None:
                always_bad.add(va.arg)
    for node in walk:
        if isinstance(node, ast.ExceptHandler) and node.name:
            always_bad.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            always_bad.update(
                (a.asname or a.name).split(".")[0] for a in node.names
            )
    kinds: dict[str, str] = {}
    for _ in range(4):  # fixpoint for short assignment chains
        bad: set[str] = set(always_bad)
        new: dict[str, str] = {}

        def bind(name: str, kind: str | None) -> None:
            if kind is None or (name in new and new[name] != kind):
                bad.add(name)
            else:
                new[name] = kind

        for node in walk:
            if isinstance(node, ast.Assign):
                if len(node.targets) == 1 and isinstance(
                    node.targets[0], ast.Name
                ):
                    bind(node.targets[0].id, _expr_kind(node.value, kinds))
                else:  # unpacking / chained / attribute targets
                    for t in node.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                bad.add(n.id)
            elif isinstance(node, ast.AnnAssign):
                if isinstance(node.target, ast.Name):
                    if node.value is not None:
                        bind(node.target.id,
                             _expr_kind(node.value, kinds))
            elif isinstance(node, ast.AugAssign):
                # |=/-=/&= preserve set-ness; any other aug on a
                # tracked name poisons it
                if isinstance(node.target, ast.Name) and not isinstance(
                    node.op, (ast.BitOr, ast.BitAnd, ast.BitXor, ast.Sub)
                ):
                    bad.add(node.target.id)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        bad.add(n.id)
            elif isinstance(node, ast.NamedExpr):
                if isinstance(node.target, ast.Name):
                    bind(node.target.id, _expr_kind(node.value, kinds))
            elif isinstance(node, (ast.comprehension,)):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        bad.add(n.id)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        for n in ast.walk(item.optional_vars):
                            if isinstance(n, ast.Name):
                                bad.add(n.id)
        resolved = {n: k for n, k in new.items() if n not in bad}
        if resolved == kinds:
            break
        kinds = resolved
    return kinds


def _order_consumed_safely(node: ast.AST) -> bool:
    """Is the *iteration context itself* wrapped in an order-
    insensitive consumer (``sorted(list(S))``, ``len([... for ...])``,
    membership tests)?"""
    parent = getattr(node, "paxlint_parent", None)
    if isinstance(parent, ast.Call):
        if lint.call_name(parent) in _ORDER_SAFE_CALLS:
            return True
    if isinstance(parent, ast.Compare):
        return True  # subset/equality tests are order-insensitive
    return False


def _check_unordered(ctx, node, expr, in_scope, findings,
                     local_kinds=lambda node: _EMPTY_KINDS) -> None:
    if not in_scope(node):
        return
    if _order_consumed_safely(node):
        return
    kinds = local_kinds(node)
    via = (
        f" (local `{expr.id}` is set-typed by assignment)"
        if isinstance(expr, ast.Name) else ""
    )
    if _is_set_expr(expr, kinds):
        findings.append(ctx.finding(
            "DET003", expr,
            "iteration over a set — hash order can escape into "
            f"logs/serialized bytes{via}",
            "wrap in sorted(...) where the order leaves the process; "
            + _pragma_hint("DET003"),
        ))
    elif _is_dict_view(expr, kinds):
        fn = lint.enclosing_function(node)
        if fn is not None and lint.is_sink_function(fn):
            findings.append(ctx.finding(
                "DET003", expr,
                "dict-view iteration feeding a serialization sink — "
                "insertion order escapes the process",
                "sort the items (sorted(d.items())) or use "
                "json.dumps(..., sort_keys=True); "
                + _pragma_hint("DET003"),
            ))
