"""JSON-schema validation for shrink/repro artifacts.

A repro artifact is the contract between a failing stress run and a
future ``python -m tpu_paxos repro`` — often on another machine,
weeks later, against a newer checkout.  A malformed or hand-edited
artifact used to surface as a ``KeyError`` or a jax shape error deep
inside the engine; this module front-loads the check at load time
with an error that names the offending field
(``cfg.faults.drop_rate: expected int >= 0, got -3``).

The validator is a ~100-line declarative walker, not the ``jsonschema``
package: the container must not grow dependencies, the analysis
subpackage must import without jax, and the artifact grammar is small
enough that a full JSON-Schema engine would be mostly dead weight.

``ARTIFACT_FORMAT`` lives here (not in ``harness/shrink.py``) so that
schema-checking an artifact never drags in the engine stack; shrink
re-exports it for compatibility.
"""

from __future__ import annotations

ARTIFACT_FORMAT = "tpu-paxos-repro-1"

_SHA256_HEX = frozenset("0123456789abcdef")

EPISODE_KINDS = ("partition", "one_way", "pause", "burst", "crash", "gray")


class ArtifactSchemaError(ValueError):
    """Artifact failed validation; ``field`` names the offender."""

    def __init__(self, field: str, problem: str):
        self.field = field
        self.problem = problem
        where = f" field {field!r}" if field else ""
        super().__init__(f"repro artifact{where}: {problem}")


# -- schema vocabulary -------------------------------------------------
# A spec is one of:
#   Int(min=..)            — int (bool excluded)
#   Str()                  — str
#   Const(v)               — exactly v
#   Nullable(spec)         — None or spec
#   ListOf(spec)           — list with every element matching spec
#   Obj({k: spec}, required=(...), extra_ok=True)
#   Any()                  — anything (extension point)

class Int:
    def __init__(self, min: int | None = None):  # noqa: A002
        self.min = min

    def check(self, v, at):
        if isinstance(v, bool) or not isinstance(v, int):
            raise ArtifactSchemaError(at, f"expected int, got {_tn(v)}")
        if self.min is not None and v < self.min:
            raise ArtifactSchemaError(
                at, f"expected int >= {self.min}, got {v}"
            )


class Str:
    def check(self, v, at):
        if not isinstance(v, str):
            raise ArtifactSchemaError(at, f"expected str, got {_tn(v)}")


class Const:
    def __init__(self, value):
        self.value = value

    def check(self, v, at):
        if v != self.value:
            raise ArtifactSchemaError(
                at, f"expected {self.value!r}, got {v!r}"
            )


class Nullable:
    def __init__(self, spec):
        self.spec = spec

    def check(self, v, at):
        if v is not None:
            self.spec.check(v, at)


class ListOf:
    def __init__(self, spec):
        self.spec = spec

    def check(self, v, at):
        if not isinstance(v, list):
            raise ArtifactSchemaError(at, f"expected list, got {_tn(v)}")
        for i, el in enumerate(v):
            self.spec.check(el, f"{at}[{i}]")


class Obj:
    def __init__(self, props: dict, required=None, extra_ok=True):
        self.props = props
        self.required = tuple(
            props.keys() if required is None else required
        )
        self.extra_ok = extra_ok

    def check(self, v, at):
        if not isinstance(v, dict):
            raise ArtifactSchemaError(at, f"expected object, got {_tn(v)}")
        for key in self.required:
            if key not in v:
                raise ArtifactSchemaError(
                    f"{at}.{key}" if at else key, "missing required field"
                )
        if not self.extra_ok:
            unknown = sorted(set(v) - set(self.props))
            if unknown:
                raise ArtifactSchemaError(
                    f"{at}.{unknown[0]}" if at else unknown[0],
                    "unknown field",
                )
        for key, spec in self.props.items():
            if key in v:
                spec.check(v[key], f"{at}.{key}" if at else key)


class Any:
    def check(self, v, at):
        pass


class Bool:
    def check(self, v, at):
        if not isinstance(v, bool):
            raise ArtifactSchemaError(at, f"expected bool, got {_tn(v)}")


class Sha256Hex:
    def check(self, v, at):
        Str().check(v, at)
        if len(v) != 64 or not set(v) <= _SHA256_HEX:
            raise ArtifactSchemaError(
                at, "expected 64 lowercase hex chars (sha256)"
            )


class OneOf:
    def __init__(self, *values):
        self.values = values

    def check(self, v, at):
        if v not in self.values:
            raise ArtifactSchemaError(
                at, f"expected one of {list(self.values)}, got {v!r}"
            )


def _tn(v) -> str:
    return "null" if v is None else type(v).__name__


# -- the artifact grammar (mirrors harness/shrink._cfg_to_dict and
# core/faults.FaultSchedule.to_dict; Episode.__post_init__ revalidates
# the semantic constraints on load) --------------------------------

# The engine-config structs are CLOSED (extra_ok=False): these dicts
# are splatted into dataclass constructors / Episode fields on load,
# where an unknown or misspelled key dies as a bare TypeError — the
# schema must name it first.  Only ``extra_checks`` (an open
# extension dict by design) and the artifact top level under a future
# format bump stay tolerant.
_EPISODE = Obj({
    "kind": OneOf(*EPISODE_KINDS),
    "t0": Int(min=0),
    "t1": Int(min=1),
    "groups": ListOf(ListOf(Int())),
    "src": ListOf(Int()),
    "dst": ListOf(Int()),
    "nodes": ListOf(Int()),
    "drop_rate": Int(min=0),
    "delay": Int(min=0),  # gray: per-message delay inflation rounds
}, required=("kind", "t0", "t1"), extra_ok=False)

_SCHEDULE = Obj(
    {"episodes": ListOf(_EPISODE)}, required=("episodes",), extra_ok=False
)

_PROTOCOL = Obj({
    "prepare_delay_min": Int(min=0),
    "prepare_delay_max": Int(min=0),
    "prepare_retry_count": Int(min=0),
    "prepare_retry_timeout": Int(min=0),
    "accept_retry_count": Int(min=0),
    "accept_retry_timeout": Int(min=0),
    "commit_retry_timeout": Int(min=0),
}, extra_ok=False)

# Per-edge [A, A] fault tables (config.EdgeFaultConfig): four square
# int matrices; squareness/range/min<=max are revalidated semantically
# by the config constructors on load — the schema names the field.
_EDGES = Obj({
    "drop_rate": ListOf(ListOf(Int(min=0))),
    "dup_rate": ListOf(ListOf(Int(min=0))),
    "min_delay": ListOf(ListOf(Int(min=0))),
    "max_delay": ListOf(ListOf(Int(min=0))),
}, extra_ok=False)

_FAULTS = Obj({
    "drop_rate": Int(min=0),
    "dup_rate": Int(min=0),
    "min_delay": Int(min=0),
    "max_delay": Int(min=0),
    "crash_rate": Int(min=0),
    "schedule": Nullable(_SCHEDULE),
    # WAN fields (written only when non-default — hence OPTIONAL, so
    # classic artifacts validate unchanged)
    "edges": Nullable(_EDGES),
    "delivery_cut": Bool(),
}, required=(
    "drop_rate", "dup_rate", "min_delay", "max_delay", "crash_rate",
    "schedule",
), extra_ok=False)

_CFG = Obj({
    "n_nodes": Int(min=1),
    "n_instances": Int(min=1),
    "proposers": ListOf(Int(min=0)),
    "seed": Int(min=0),
    "max_rounds": Int(min=1),
    "assign_window": Int(min=1),
    "protocol": _PROTOCOL,
    "faults": _FAULTS,
}, extra_ok=False)

# Controlled-serve replay block (serve/control.save_artifact).  CLOSED
# like the engine-config structs: these dicts are splatted into
# ControlPolicy / ServeSLO constructors on load.  The whole block is
# OPTIONAL and absent from every classic sim/sharded artifact, so
# existing artifacts stay byte-identical.
_CONTROL_POLICY = Obj({
    "n_tiers": Int(min=1),
    "defer_tier": Int(min=1),
    "shed_tier": Int(min=1),
    "burn_low_milli": Int(min=0),
    "patience": Int(min=1),
    "ladder": ListOf(Int(min=1)),
    "table": ListOf(Obj({
        "cause_id": Int(min=0),
        "action": OneOf("shed", "hold", "never"),
    }, extra_ok=False)),
}, extra_ok=False)

_CONTROL_DECISION = Obj({
    "dispatch": Int(min=1),
    "action": OneOf("degrade", "hold", "restore"),
    "level": Int(min=0),
    "degraded": Bool(),
    "cause_ids": ListOf(Int(min=0)),
    "windows": ListOf(Int(min=0)),
}, extra_ok=False)

_SERVE_SLO = Obj({
    "latency_rounds": Int(min=1),
    "budget_milli": Int(min=1),
    "burn_breach_milli": Int(min=0),
}, extra_ok=False)

_SERVE = Obj({
    "arrivals": ListOf(ListOf(Int(min=0))),
    "priorities": Nullable(ListOf(ListOf(Int(min=0)))),
    "rounds_per_window": Int(min=1),
    "windows_per_dispatch": Int(min=1),
    "admit_width": Int(min=1),
    "window_rounds": Int(min=1),
    "slo": Nullable(_SERVE_SLO),
    "control": Nullable(_CONTROL_POLICY),
    "decisions": ListOf(_CONTROL_DECISION),
}, extra_ok=False)

ARTIFACT_SCHEMA = Obj({
    "format": Const(ARTIFACT_FORMAT),
    # replay engine selector (optional; absent = "sim").  "sharded"
    # artifacts also record the device count their decision log was
    # produced at — placement, hence the log, depends on it.  "serve"
    # artifacts replay through serve/control.reproduce and carry the
    # "serve" block (arrivals/priorities/policy/decision trail).
    "engine": OneOf("sim", "sharded", "serve"),
    "devices": Int(min=1),
    "cfg": _CFG,
    "workload": ListOf(ListOf(Int())),
    "gates": Nullable(ListOf(ListOf(Int()))),
    "chains": ListOf(ListOf(Int())),
    "extra_checks": Obj({}, required=()),
    "violation": Str(),
    "decision_log_sha256": Sha256Hex(),
    "rounds": Int(min=0),
    "serve": _SERVE,
}, required=(
    "format", "cfg", "workload", "gates", "chains", "violation",
    "decision_log_sha256",
))


def validate_artifact(art) -> None:
    """Raise ArtifactSchemaError naming the offending field if ``art``
    is not a well-formed repro artifact."""
    if not isinstance(art, dict):
        raise ArtifactSchemaError("", f"expected object, got {_tn(art)}")
    # judge the format stamp before anything else: an artifact from a
    # different format version should be rejected AS that, not as
    # missing whichever field this version happens to require first
    Const(ARTIFACT_FORMAT).check(art.get("format"), "format")
    ARTIFACT_SCHEMA.check(art, "")
    # cross-field: a proposer index must address a real node, and the
    # workload must carry one queue per proposer — both produce
    # baffling downstream shape errors if left to the engine
    cfg = art["cfg"]
    if "proposers" in cfg and "n_nodes" in cfg:
        for i, p in enumerate(cfg["proposers"]):
            if p >= cfg["n_nodes"]:
                raise ArtifactSchemaError(
                    f"cfg.proposers[{i}]",
                    f"proposer {p} out of range for n_nodes="
                    f"{cfg['n_nodes']}",
                )
        if len(art["workload"]) != len(cfg["proposers"]):
            raise ArtifactSchemaError(
                "workload",
                f"{len(art['workload'])} queues for "
                f"{len(cfg['proposers'])} proposers",
            )
    if art["gates"] is not None and len(art["gates"]) != len(
        art["workload"]
    ):
        raise ArtifactSchemaError(
            "gates",
            f"{len(art['gates'])} gate rows for "
            f"{len(art['workload'])} workload queues",
        )
    # a serve artifact and its serve block imply each other, and the
    # plan arrays must stay row-parallel with the workload streams
    if (art.get("engine") == "serve") != ("serve" in art):
        raise ArtifactSchemaError(
            "serve",
            "engine \"serve\" and the serve block imply each other",
        )
    if "serve" in art:
        sv = art["serve"]
        for key in ("arrivals", "priorities"):
            rows = sv.get(key)
            if rows is not None and len(rows) != len(art["workload"]):
                raise ArtifactSchemaError(
                    f"serve.{key}",
                    f"{len(rows)} rows for "
                    f"{len(art['workload'])} workload streams",
                )
