"""Decision-log emitter in the reference's debug value grammar.

The grammar (ref multi/paxos.cpp:18-22):

    no-op:      [instance-id] = <proposal-id>(proposer:value-id)-
    normal:     [instance-id] = <proposal-id>(proposer:value-id)+value
    add member: [instance-id] = <proposal-id>(proposer:value-id)m+id=ip:port
    del member: [instance-id] = <proposal-id>(proposer:value-id)m-id

One line per decided instance, in instance order; the log is a pure
function of the engine result, so two same-seed runs emit
byte-identical logs (the replay-diff test, spirit of
ref member/diff.sh:1-3).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from tpu_paxos.core import values as val


def decision_log(
    chosen_vid: np.ndarray,
    chosen_ballot: np.ndarray,
    stride: int,
    n_instances: int,
    payload: Callable[[int], str] | None = None,
    membership: Callable[[int], str] | None = None,
) -> str:
    """Render the decided log.

    ``stride`` is the workload's vid stride (canonical encoding
    ``vid = proposer * stride + seq``, core/values.py).  ``payload``
    optionally maps a real vid to its payload string (defaults to the
    vid's decimal value-id — the reference harness's values are small
    ints too, ref multi/main.cpp:202-219).  ``membership`` maps a
    membership-change vid to its ``m+id=ip:port`` / ``m-id`` suffix
    (membership/ provides one); vids it returns None for fall through
    to the normal grammar.
    """
    chosen_vid = np.asarray(chosen_vid)
    chosen_ballot = np.asarray(chosen_ballot)
    # Large plain logs (no custom payload/membership rendering) go
    # through the native C++ renderer — same grammar, one pass, no
    # per-line Python string work.  Equivalence pinned by
    # tests/test_native.py.
    if payload is None and membership is None and len(chosen_vid) >= 1 << 14:
        from tpu_paxos import native

        if native.available():
            return native.render_decision_log(
                chosen_vid, chosen_ballot, stride, n_instances
            )
    lines = []
    for i in range(len(chosen_vid)):
        v = int(chosen_vid[i])
        if v == int(val.NONE):
            continue
        b = int(chosen_ballot[i])
        if v <= val.NOOP_BASE:
            proposer, inst, _ = val.decode_host(v, stride, n_instances)
            lines.append(f"[{i}] = <{b}>({proposer}:{inst})-")
            continue
        if membership is not None:
            m = membership(v)
            if m is not None:
                # Change vids encode (target node, kind), not
                # (proposer, seq) — the real-vid stride decode would
                # render meaningless large numbers.  The reference
                # prints the proposing node here (ref
                # multi/paxos.cpp:21-22); the change encoding doesn't
                # carry it, so render the change's own identity.
                from tpu_paxos.membership import engine as mem

                node, kind = mem.decode_change(v)
                lines.append(f"[{i}] = <{b}>({node}:{kind}){m}")
                continue
        proposer, seq, _ = val.decode_host(v, stride, n_instances)
        body = payload(v) if payload is not None else str(seq)
        lines.append(f"[{i}] = <{b}>({proposer}:{seq})+{body}")
    return "\n".join(lines) + ("\n" if lines else "")
