"""Determinism layer: decision logs + byte-identical replay.

The reference's signature capability is deterministic record/replay of
a whole multithreaded run (``Indet``, ref member/indet.h:182-194,
member/run.sh:1-18: run, re-run in replay mode, ``diff`` the logs —
byte-identical output is the pass criterion).  In this framework the
entire schedule is already a pure function of (config, seed): the
engine's randomness is counter-based ``jax.random`` keyed on
(seed, stream, round), so *replay is re-execution*.  What this package
provides is the observable artifact: the decision log in the
reference's grammar, so two same-seed runs can be byte-compared the
way ``member/diff.sh`` compares record and replay logs.
"""

from tpu_paxos.replay.decision_log import decision_log

__all__ = ["decision_log"]
