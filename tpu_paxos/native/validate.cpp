// Native host-side fast paths for tpu_paxos (C ABI, consumed via
// ctypes — no pybind11 in this environment).
//
// The reference is 100% native C++ (SURVEY.md: 5,814 LoC, g++,
// -pthread); its harness both validates and prints the committed log
// in-process (ref multi/main.cpp:567-573, multi/paxos.cpp:1694-1703).
// In this framework the TPU does the protocol work, but the
// whole-run validation and decision-log rendering are host-side and
// become the bottleneck at 10^7..10^8 instances; these single-pass
// C++ loops replace multi-pass numpy / Python string formatting.
// harness/validate.py and replay/decision_log.py fall back to the
// pure-Python implementations when the shared library is unavailable,
// and the test suite pins native/python equivalence.
//
// Build: g++ -O2 -shared -fPIC -o libtpupaxos.so validate.cpp
// (done on demand by tpu_paxos/native/__init__.py).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

namespace {
constexpr int32_t kNone = -1;
constexpr int32_t kNoopBase = -2;  // vids <= this are no-ops
}  // namespace

extern "C" {

// Agreement: no two nodes learned different values for one instance
// (ref multi/main.cpp:567-570).  learned is [I, A] row-major.
// Returns 0 and leaves *bad untouched when consistent; returns 1 and
// writes the first violating instance otherwise.
int tp_check_agreement(const int32_t* learned, int64_t n_instances,
                       int64_t n_nodes, int64_t* bad) {
  for (int64_t i = 0; i < n_instances; ++i) {
    const int32_t* row = learned + i * n_nodes;
    int32_t seen = kNone;
    for (int64_t a = 0; a < n_nodes; ++a) {
      const int32_t v = row[a];
      if (v == kNone) continue;
      if (seen == kNone) {
        seen = v;
      } else if (v != seen) {
        *bad = i;
        return 1;
      }
    }
  }
  return 0;
}

// Per-instance chosen value: the value any knowing node learned
// (callers run tp_check_agreement first, so knowers agree).
void tp_chosen_per_instance(const int32_t* learned, int64_t n_instances,
                            int64_t n_nodes, int32_t* out) {
  for (int64_t i = 0; i < n_instances; ++i) {
    const int32_t* row = learned + i * n_nodes;
    int32_t seen = kNone;
    for (int64_t a = 0; a < n_nodes; ++a) {
      if (row[a] != kNone) {
        seen = row[a];
        break;
      }
    }
    out[i] = seen;
  }
}

// Exactly-once: no real (vid >= 0) value appears at two instances.
// chosen is [I].  Returns 0 when clean; 1 and the duplicated vid via
// *dup_vid otherwise.  Uses a bitset over the dense vid space when
// max_vid is provided (>= 0), else a sorted vector.  A vid above
// max_vid returns 2 (bound too small) rather than being silently
// skipped — the caller retries without the bound.
int tp_check_unique(const int32_t* chosen, int64_t n_instances,
                    int64_t max_vid, int32_t* dup_vid) {
  if (max_vid >= 0) {
    std::vector<uint8_t> seen(static_cast<size_t>(max_vid) + 1, 0);
    for (int64_t i = 0; i < n_instances; ++i) {
      const int32_t v = chosen[i];
      if (v < 0) continue;  // NONE or no-op
      if (v > max_vid) {
        *dup_vid = v;
        return 2;
      }
      if (seen[v]) {
        *dup_vid = v;
        return 1;
      }
      seen[v] = 1;
    }
    return 0;
  }
  std::vector<int32_t> vals;
  vals.reserve(static_cast<size_t>(n_instances));
  for (int64_t i = 0; i < n_instances; ++i)
    if (chosen[i] >= 0) vals.push_back(chosen[i]);
  if (vals.empty()) return 0;
  std::sort(vals.begin(), vals.end());
  for (size_t k = 1; k < vals.size(); ++k)
    if (vals[k] == vals[k - 1]) {
      *dup_vid = vals[k];
      return 1;
    }
  return 0;
}

// Decision-log renderer in the reference's value grammar
// (ref multi/paxos.cpp:18-22):
//   no-op:  [i] = <ballot>(proposer:value-id)-
//   normal: [i] = <ballot>(proposer:value-id)+value-id
// Membership-change vids are host-rendered by the Python layer (they
// need the intern table); callers route logs containing them to the
// Python path.  Two modes: buf == nullptr sizes the output; otherwise
// writes up to cap bytes.  Returns the total byte length needed
// (excluding the NUL), or -1 if cap was insufficient.
int64_t tp_render_decision_log(const int32_t* chosen_vid,
                               const int32_t* chosen_ballot,
                               int64_t n_instances, int32_t stride,
                               int32_t noop_modulus, char* buf, int64_t cap) {
  int64_t total = 0;
  char line[96];
  for (int64_t i = 0; i < n_instances; ++i) {
    const int32_t v = chosen_vid[i];
    if (v == kNone) continue;
    const int32_t b = chosen_ballot[i];
    int len;
    if (v <= kNoopBase) {
      const int64_t k = static_cast<int64_t>(kNoopBase) - v;
      const int64_t proposer = k / noop_modulus;
      const int64_t inst = k % noop_modulus;
      len = std::snprintf(line, sizeof line, "[%lld] = <%d>(%lld:%lld)-\n",
                          static_cast<long long>(i), b,
                          static_cast<long long>(proposer),
                          static_cast<long long>(inst));
    } else {
      const int32_t proposer = v / stride;
      const int32_t seq = v % stride;
      len = std::snprintf(line, sizeof line, "[%lld] = <%d>(%d:%d)+%d\n",
                          static_cast<long long>(i), b, proposer, seq, seq);
    }
    if (buf != nullptr) {
      if (total + len > cap) return -1;
      std::memcpy(buf + total, line, static_cast<size_t>(len));
    }
    total += len;
  }
  return total;
}

}  // extern "C"
