"""``tpu_paxos.native`` — C++ host-side fast paths via ctypes.

Compiled on first use (g++, same toolchain discipline as the
reference's one-line Makefiles, ref multi/Makefile:1-2) into
``build/native/`` next to the repo root; importers call
``available()`` and fall back to the pure-Python implementations when
the toolchain or the build is unavailable, so the framework never
*requires* native code — it just gets fast validation and log
rendering at multi-million-instance scale when it can.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "validate.cpp")
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_BUILD_DIR = os.path.join(_REPO, "build", "native")
_LIB_PATH = os.path.join(_BUILD_DIR, "libtpupaxos.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_failed: str | None = None


def _build() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if not (
        os.path.exists(_LIB_PATH)
        and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC)
    ):
        # per-process unique tmp + atomic replace: concurrent first
        # builds (bench parent + child, parallel pytest) must never
        # interleave g++ output into one corrupt .so
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
        os.close(fd)
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC],
                check=True,
                capture_output=True,
                text=True,
            )
            os.replace(tmp, _LIB_PATH)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return _LIB_PATH


def _load() -> ctypes.CDLL | None:
    global _lib, _failed
    with _lock:
        if _lib is not None or _failed is not None:
            return _lib
        try:
            lib = ctypes.CDLL(_build())
        except (OSError, subprocess.CalledProcessError) as e:
            _failed = str(e)
            return None
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.tp_check_agreement.argtypes = [
            i32p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.tp_check_agreement.restype = ctypes.c_int
        lib.tp_chosen_per_instance.argtypes = [
            i32p, ctypes.c_int64, ctypes.c_int64, i32p,
        ]
        lib.tp_chosen_per_instance.restype = None
        lib.tp_check_unique.argtypes = [
            i32p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.tp_check_unique.restype = ctypes.c_int
        lib.tp_render_decision_log.argtypes = [
            i32p, i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int64,
        ]
        lib.tp_render_decision_log.restype = ctypes.c_int64
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native library is (or can be) loaded."""
    return _load() is not None


def check_agreement(learned: np.ndarray) -> int | None:
    """First instance where two nodes learned different values, or
    None when all replicas agree."""
    lib = _load()
    assert lib is not None, "call available() first"
    learned = np.ascontiguousarray(learned, np.int32)
    bad = ctypes.c_int64(-1)
    rc = lib.tp_check_agreement(
        learned, learned.shape[0], learned.shape[1], ctypes.byref(bad)
    )
    return int(bad.value) if rc else None


def chosen_per_instance(learned: np.ndarray) -> np.ndarray:
    lib = _load()
    assert lib is not None, "call available() first"
    learned = np.ascontiguousarray(learned, np.int32)
    out = np.empty(learned.shape[0], np.int32)
    lib.tp_chosen_per_instance(learned, learned.shape[0], learned.shape[1], out)
    return out


def check_unique(chosen: np.ndarray, max_vid: int = -1) -> int | None:
    """A real vid chosen at two instances, or None when exactly-once
    holds.  ``max_vid >= 0`` enables the dense-bitset fast path; a
    vid above the bound transparently falls back to the sort path, so
    the verdict never depends on the bound being right."""
    lib = _load()
    assert lib is not None, "call available() first"
    chosen = np.ascontiguousarray(chosen, np.int32)
    dup = ctypes.c_int32(-1)
    rc = lib.tp_check_unique(chosen, len(chosen), max_vid, ctypes.byref(dup))
    if rc == 2:  # bound too small for the data — retry unbounded
        dup = ctypes.c_int32(-1)
        rc = lib.tp_check_unique(chosen, len(chosen), -1, ctypes.byref(dup))
    return int(dup.value) if rc else None


def render_decision_log(
    chosen_vid: np.ndarray,
    chosen_ballot: np.ndarray,
    stride: int,
    n_instances: int,
) -> str:
    """The reference value grammar (ref multi/paxos.cpp:18-22) for
    real + no-op vids.  Membership-change vids need the host intern
    table — callers with those use the Python renderer."""
    lib = _load()
    assert lib is not None, "call available() first"
    cv = np.ascontiguousarray(chosen_vid, np.int32)
    cb = np.ascontiguousarray(chosen_ballot, np.int32)
    need = lib.tp_render_decision_log(
        cv, cb, len(cv), stride, n_instances, None, 0
    )
    if need == 0:
        return ""
    buf = ctypes.create_string_buffer(need)
    wrote = lib.tp_render_decision_log(
        cv, cb, len(cv), stride, n_instances, buf, need
    )
    assert wrote == need
    return buf.raw[:need].decode()
