"""Scale-out: device meshes and instance-axis sharded round loops."""
