"""Device mesh construction — the distributed communication backend.

The reference's backend is the ``NetWork`` SPI over in-process queues
(ref multi/paxos.h:193-212, multi/main.cpp:51-162).  Here the backend
is the XLA collective layer: consensus state is sharded over the
*instance* axis of a ``jax.sharding.Mesh`` (Paxos instances are
embarrassingly parallel — only proposer-global scalars need
communication), so the only cross-chip traffic is tiny ``pmax``/
``psum`` reductions of per-acceptor scalars, which ride ICI inside a
slice and DCN across slices.

Mesh axes:
- ``i``: instance-axis shards (ICI).  Protocol arrays keep instances
  MINOR ([A, I] / [P, I] / [P, A, I] — see core/fast.py's layout
  note) and are split along that minor instance axis
  (``P(None, 'i')`` / ``P(None, None, 'i')``); plain [I] vectors
  split on dim 0 (``shard_instances``).
- per-acceptor scalars ([nodes]-shaped) are replicated.

Multi-host: a 2-D ``('dcn', 'i')`` mesh (``make_instance_mesh`` with
``dcn_hosts > 1``) splits instances over hosts on the outer axis and
over a host's chips on the inner one; the round functions are
unchanged because every collective reduces over *all* mesh axes
(``instance_axes``) and XLA routes each hop over the right fabric —
ICI within a slice, DCN between hosts.  Production multi-process use
is ``jax.distributed.initialize()`` + the same mesh over
``jax.devices()``; here the 2-D path is exercised on a virtual
device mesh (tests/test_multihost.py, the driver dryrun).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

INSTANCE_AXIS = "i"
DCN_AXIS = "dcn"


def _spec_axes(specs) -> set:
    """Mesh axis names referenced by any ``PartitionSpec`` leaf of a
    spec pytree (a spec dim is an axis name or a tuple of names)."""
    names: set = set()
    for leaf in jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P)):
        if not isinstance(leaf, P):
            continue
        for dim in leaf:
            if dim is None:
                continue
            dims = dim if isinstance(dim, (tuple, list)) else (dim,)
            names.update(str(d) for d in dims)
    return names


def shard_map(f, mesh: Mesh, in_specs, out_specs):
    """Version-portable ``shard_map`` without replication checking
    (the round functions assert their own replication invariants; the
    checker's conservative analysis rejects the cond-gated
    collectives).  New jax exposes ``jax.shard_map(check_vma=...)``;
    older releases only have the experimental module with
    ``check_rep``.

    Specs are validated against the mesh up front: jax's own error
    for an axis name absent from the mesh surfaces deep in lowering
    without naming the spec (and with replication checking off some
    versions silently treat the dim as replicated) — exactly the gap
    a mesh-polymorphic caller reusing a spec built for a different
    mesh would fall into.  Rejection is BY NAME (pinned by
    tests/test_shard_audit.py)."""
    unknown = sorted(
        _spec_axes((in_specs, out_specs)) - set(mesh.axis_names)
    )
    if unknown:
        raise ValueError(
            f"shard_map spec names mesh axis {unknown[0]!r} but the "
            f"mesh has axes {tuple(mesh.axis_names)} — build specs "
            "from this mesh (parallel/mesh.instance_spec or "
            "parallel/partition_rules.tree_spec), not another's"
        )
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def make_instance_mesh(
    n_devices: int | None = None, devices=None, dcn_hosts: int = 1
) -> Mesh:
    """Mesh over the instance axis.  ``n_devices=None`` uses every
    visible device (the v5e-8 slice in the target config).  With
    ``dcn_hosts > 1`` the mesh is 2-D ``(dcn_hosts, chips_per_host)``
    with axes ``('dcn', 'i')`` — the multi-host shape."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    if dcn_hosts > 1:
        # Degrade gracefully when fewer devices are visible than the
        # caller planned for (the 1-D path's contract): clamp the host
        # axis to the largest divisor of the device count.
        import math

        dcn_hosts = math.gcd(dcn_hosts, len(devices))
        return jax.make_mesh(
            (dcn_hosts, len(devices) // dcn_hosts),
            (DCN_AXIS, INSTANCE_AXIS),
            devices=devices,
        )
    return jax.make_mesh((len(devices),), (INSTANCE_AXIS,), devices=devices)


def instance_axes(mesh: Mesh) -> tuple[str, ...]:
    """Every mesh axis shards the instance dimension; collectives
    reduce over this whole tuple (a linear shard index comes from
    ``jax.lax.axis_index(instance_axes(mesh))``)."""
    return tuple(mesh.axis_names)


def instance_spec(mesh: Mesh | None = None) -> P:
    """Spec for [instances, ...] arrays: split dim 0 over the mesh."""
    return P(instance_axes(mesh) if mesh is not None else INSTANCE_AXIS)


def replicated_spec() -> P:
    return P()


def shard_instances(mesh: Mesh, arr):
    """Place an [I, ...] array sharded over the instance axis."""
    return jax.device_put(arr, NamedSharding(mesh, instance_spec(mesh)))
