"""Device mesh construction — the distributed communication backend.

The reference's backend is the ``NetWork`` SPI over in-process queues
(ref multi/paxos.h:193-212, multi/main.cpp:51-162).  Here the backend
is the XLA collective layer: consensus state is sharded over the
*instance* axis of a ``jax.sharding.Mesh`` (Paxos instances are
embarrassingly parallel — only proposer-global scalars need
communication), so the only cross-chip traffic is tiny ``pmax``/
``psum`` reductions of per-acceptor scalars, which ride ICI inside a
slice and DCN across slices.

Mesh axes:
- ``i``: instance-axis shards (ICI).  Protocol arrays keep instances
  MINOR ([A, I] / [P, I] / [P, A, I] — see core/fast.py's layout
  note) and are split along that minor instance axis
  (``P(None, 'i')`` / ``P(None, None, 'i')``); plain [I] vectors
  split on dim 0 (``shard_instances``).
- per-acceptor scalars ([nodes]-shaped) are replicated.

Multi-host: ``jax.distributed.initialize()`` + the same mesh spanning
all processes gives the DCN scale-out path; the round functions are
unchanged because shard_map hides the topology.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

INSTANCE_AXIS = "i"


def make_instance_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the instance axis.  ``n_devices=None`` uses every
    visible device (the v5e-8 slice in the target config)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return jax.make_mesh((len(devices),), (INSTANCE_AXIS,), devices=devices)


def instance_spec() -> P:
    """Spec for [instances, ...] arrays: split dim 0 over the mesh."""
    return P(INSTANCE_AXIS)


def replicated_spec() -> P:
    return P()


def shard_instances(mesh: Mesh, arr):
    """Place an [I, ...] array sharded over the instance axis."""
    return jax.device_put(arr, NamedSharding(mesh, instance_spec()))
