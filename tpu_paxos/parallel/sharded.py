"""Instance-axis sharded protocol rounds (shard_map + collectives).

The [nodes, instances] SoA state (instances minor — see core/fast.py's
layout note) is split along the instance axis across the mesh;
per-acceptor scalars (promised, max_seen) are replicated.  The
only cross-shard communication the protocol needs is:

- ``pmax`` of the max-ballot-seen when a proposer picks a new ballot
  (the global analog of ref multi/paxos.cpp:792-799's max_proposal_id_),
- ``psum`` of chosen counts for the quiescence predicate
  (the reference's "total executed" counter, ref multi/main.cpp:329).

Everything else — promise compares, adoption, accept stores, learning —
is local to a shard, which is why this scales linearly over ICI.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpu_paxos.analysis import tracecount
from tpu_paxos.core import ballot as bal
from tpu_paxos.core import fast
from tpu_paxos.core import values as val
from tpu_paxos.parallel.mesh import INSTANCE_AXIS, instance_axes
from tpu_paxos.parallel.mesh import shard_map as pmesh_shard_map


def _state_specs(axes=INSTANCE_AXIS) -> fast.FastState:
    """PartitionSpec pytree for FastState: [A, I] arrays split over
    the (minor) instance axis, [A] scalars replicated.  ``axes`` is
    the mesh axis name (or tuple of names, for the 2-D dcn x ici
    multi-host mesh) sharding the instance dimension.  The dims come
    from the committed partition-rule table
    (parallel/partition_rules.py) — a FastState field the table does
    not rule fails here by name (SH301's runtime twin)."""
    from tpu_paxos.parallel import partition_rules as prules

    def spec(field: str):
        hit = prules.match_path(f"fast/{field}")
        if hit is None:
            raise prules.PartitionRuleError(
                f"no committed partition rule matches leaf "
                f"fast/{field} — add a rule to "
                "parallel/partition_rules.py (SH301)"
            )
        return prules.spec_of(hit[1], axes)

    return fast.FastState(
        **{f: spec(f) for f in fast.FastState._fields}
    )


def _choose_all_local(
    state: fast.FastState, vids, proposer: int, quorum: int, axes=INSTANCE_AXIS
):
    """Per-shard body of the fused choose-all: identical to the
    single-chip fast path except the ballot is derived from the
    *global* max ballot seen (pmax over shards)."""
    global_max = jax.lax.pmax(jnp.max(state.max_seen), axes)
    _, ballot = bal.bump_past(jnp.int32(0), jnp.int32(proposer), global_max)

    state, prepared, adopted_ballot, adopted_vid = fast.phase1_prepare(
        state, ballot, quorum
    )
    use_adopted = adopted_ballot != bal.NONE
    batch = jnp.where(use_adopted, adopted_vid, vids)
    batch = jnp.where(prepared, batch, val.NONE)
    state, chosen = fast.phase2_accept(state, ballot, batch, quorum)
    state = fast.phase3_learn(state, batch, chosen)

    local_chosen = jnp.sum((state.learned[0] != val.NONE).astype(jnp.int32))
    n_chosen = jax.lax.psum(local_chosen, axes)
    return state, n_chosen


def sharded_choose_all(mesh: Mesh, proposer: int, quorum: int):
    """Build the jitted, shard_map'd choose-all for a mesh.

    Returns ``fn(state, vids) -> (state, n_chosen)`` where [I, ...]
    inputs are sharded over the instance axis.
    """
    axes = instance_axes(mesh)
    body = functools.partial(
        _choose_all_local, proposer=proposer, quorum=quorum, axes=axes
    )
    mapped = pmesh_shard_map(
        body,
        mesh,
        in_specs=(_state_specs(axes), P(axes)),
        out_specs=(_state_specs(axes), P()),
    )
    jitted = jax.jit(mapped)

    def step(state, vids):
        with tracecount.engine_scope("sharded_fast"):
            return jitted(state, vids)

    step.lower = jitted.lower  # keep the AOT surface for benchmarks
    return step


def init_sharded_state(mesh: Mesh, n_instances: int, n_nodes: int) -> fast.FastState:
    """FastState with [A, I] arrays laid out over the (minor) instance axis."""
    if n_instances % mesh.size != 0:
        raise ValueError(
            f"n_instances ({n_instances}) must divide evenly over "
            f"{mesh.size} devices"
        )
    state = fast.init_state(n_instances, n_nodes)
    from jax.sharding import NamedSharding

    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        _state_specs(instance_axes(mesh)),
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.tree.map(jax.device_put, state, shardings)


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """Canonical sharded-fast-path trace (analysis/registry.py).  A
    1-device mesh keeps the trace shape-identical however many
    devices the host has; the collectives (pmax/psum over 'i') are in
    the jaxpr regardless of mesh size, which is what IR203 checks."""
    from tpu_paxos.analysis.registry import AuditEntry
    from tpu_paxos.parallel import mesh as pmesh

    def _setup(mesh):
        n = 16
        state = init_sharded_state(mesh, n, n_nodes=3)
        vids = pmesh.shard_instances(
            mesh, jnp.arange(n, dtype=jnp.int32)
        )
        return sharded_choose_all(mesh, proposer=0, quorum=2), (state, vids)

    def build():
        return _setup(pmesh.make_instance_mesh(1))

    def shard_state():
        # the [A, I] protocol state the partition table must cover
        mesh = pmesh.make_instance_mesh(1)
        return "fast", init_sharded_state(mesh, 16, n_nodes=3)

    return [AuditEntry("sharded.choose_all", build,
                       covers=("sharded_choose_all",),
                       mesh_axes=(INSTANCE_AXIS,),
                       shard_build=_setup,
                       shard_state=shard_state)]
