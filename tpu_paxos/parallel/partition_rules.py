"""Committed partition-rule table — the one source of truth for how
state pytrees lay out over the device mesh.

The SPMD surfaces of this repo shard exactly two ways: protocol
arrays split over the *instance* axis (the sharded engines,
``parallel/sharded.py`` / ``parallel/sharded_sim.py``), and fleet
state stacked over a leading *lane* axis that tiles over devices
(``fleet/runner.py``, ``fleet/member_runner.py``, ``serve/fleet.py``).
Before this table, each call site hand-built its ``PartitionSpec``
pytree — so a new state leaf silently inherited whatever the closest
copy-paste said (usually: fully replicated), and nothing could audit
the decision.  Now the layout is *data*: ``RULES`` maps a regex over
the leaf's pytree path (``<family>/<field>/<field>`` — the snippet
exemplar's ``match_partition_rules`` pattern) to a dims template, the
engines derive their spec pytrees from it (:func:`tree_spec`), and
the shard-audit tier (``analysis/shard_audit.py``, SH301) holds it to
two contracts: every array leaf of every registered stacked-state
pytree must match some rule (an unmatched leaf fails BY PATH — it
would otherwise replicate silently), and every rule must match some
leaf (a rule matching nothing is stale and fails too).

Dims language (first matching rule wins, scalars are free):

- ``REP`` — fully replicated at any rank (``PartitionSpec()``).
- a tuple of per-dimension entries, each ``None`` (unsharded) or
  ``LANE`` (split over the mesh's lane/instance axes — substituted
  with the actual axis-name tuple at spec-build time, so one rule
  serves the 1-D ``('i',)`` and 2-D ``('dcn', 'i')`` meshes alike).
  A trailing ``...`` means "any remaining dims, unsharded"; without
  it the tuple length must equal the leaf's rank exactly, so a rule
  drifting from the state layout it was written for fails loudly
  instead of sharding the wrong dimension.
- rank-0 and single-element leaves need no rule: they are replicated
  wherever they live (the snippet-[1] scalar case).

Import discipline: the table and the matching logic are pure stdlib;
jax is imported only inside the spec-building/coverage functions, so
the jax-free analysis layer (``analysis/shard_rules.py``) can read
and document the rules without pulling the runtime.
"""

from __future__ import annotations

import re

#: Dim sentinel: split this dimension over the mesh's lane/instance
#: axes (``parallel/mesh.instance_axes`` — ``('i',)`` or
#: ``('dcn', 'i')``).
LANE = "lane"

#: Dims sentinel: fully replicated at any rank.
REP = "replicated"

#: The committed table: (path regex, dims).  Ordered — the FIRST
#: matching rule wins, so family catch-alls (``^sim/prop/``) sit
#: below the sharded leaves they would otherwise swallow.
RULES: tuple = (
    # ---- fast: parallel/sharded.py FastState ([A, I] SoA) ----------
    # per-acceptor scalars replicate; protocol arrays split on the
    # minor instance axis (core/fast.py's layout note)
    (r"^fast/(promised|max_seen)$", REP),
    (r"^fast/(acc_ballot|acc_vid|learned)$", (None, LANE)),
    # ---- sim: parallel/sharded_sim.py global SimState --------------
    (r"^sim/acc/(promised|max_seen)$", REP),
    (r"^sim/acc/(acc_ballot|acc_vid)$", (None, LANE)),
    (r"^sim/learned$", (None, LANE)),
    (r"^sim/prop/(adopted_b|adopted_v|cur_batch|own_assign|commit_vid)$",
     (None, LANE)),
    (r"^sim/prop/(acks|commit_acked)$", (None, None, LANE)),
    # per-shard private queues: leading axis = shard
    (r"^sim/prop/(pend|gate)$", (LANE, None, None)),
    (r"^sim/prop/(head|tail)$", (LANE, None)),
    # [P]/[P, A] proposer control plane: replicated (updates are
    # functions of replicated arrivals + the global reductions)
    (r"^sim/prop/", REP),
    (r"^sim/net/", REP),  # network calendars: replicated
    (r"^sim/met/chosen_(vid|round|ballot)$", (LANE,)),
    (r"^sim/met/msgs$", REP),
    (r"^sim/(crashed|qsums)$", REP),
    # ---- lane-stacked fleets: leading lane axis tiles the mesh,
    # everything behind it is lane-local (lanes are independent — the
    # cross-mesh parity basis the shard audit certifies) ------------
    (r"^fleet/", (LANE, ...)),
    (r"^member/", (LANE, ...)),
    (r"^serve/", (LANE, ...)),
)


class PartitionRuleError(ValueError):
    """A stacked-state leaf no committed rule matches (named by pytree
    path), or a matched rule whose rank disagrees with the leaf."""


def _key_part(key) -> str:
    """One pytree path key as a path segment: attribute name for
    NamedTuple/dataclass fields, index for sequences, key for dicts."""
    for attr in ("name", "idx", "key"):
        v = getattr(key, attr, None)
        if v is not None:
            return str(v)
    return str(key)


def leaf_path(family: str, path) -> str:
    """``family/part/part`` path string for one flattened leaf."""
    return "/".join([family, *(_key_part(k) for k in path)])


def is_trivial(leaf) -> bool:
    """Rank-0 / single-element leaves need no rule: they replicate
    wherever they live."""
    shape = tuple(getattr(leaf, "shape", ()))
    if not shape:
        return True
    n = 1
    for d in shape:
        n *= int(d)
    return n == 1


def match_path(path: str):
    """First matching rule for a leaf path -> ``(index, dims)`` or
    ``None``.  Jax-free on purpose (the audit's SH301 docs and the
    unit tests judge the table without the runtime)."""
    for idx, (pat, dims) in enumerate(RULES):
        if re.search(pat, path):
            return idx, dims
    return None


def rank_problem(dims, ndim: int) -> str | None:
    """Why ``dims`` cannot spec a rank-``ndim`` leaf (None = fine)."""
    if dims == REP:
        return None
    fixed = [d for d in dims if d is not Ellipsis]
    open_rank = len(fixed) != len(dims)
    if open_rank:
        if ndim < len(fixed):
            return (
                f"rule wants rank >= {len(fixed)} "
                f"(dims {dims!r}), leaf has rank {ndim}"
            )
        return None
    if ndim != len(dims):
        return (
            f"rule pins rank {len(dims)} (dims {dims!r}), leaf has "
            f"rank {ndim} — the rule drifted from the state layout"
        )
    return None


def spec_of(dims, axes):
    """Build the ``PartitionSpec`` for a dims template; ``axes`` is
    the mesh's lane/instance axis name (or tuple of names) that
    ``LANE`` substitutes."""
    from jax.sharding import PartitionSpec as P

    if dims == REP:
        return P()
    out = []
    for d in dims:
        if d is Ellipsis:
            break  # trailing dims unsharded: P() pads with None
        out.append(axes if d == LANE else None)
    return P(*out)


def tree_spec(family: str, tree, axes):
    """Spec pytree for ``tree`` derived from the committed table —
    what the sharded engines feed ``shard_map`` / ``NamedSharding``.
    Raises :class:`PartitionRuleError` naming the pytree path of any
    leaf the table does not cover (a new state field must be ruled
    before it can ship, which is SH301 enforced at runtime too)."""
    import jax

    def one(path, leaf):
        if is_trivial(leaf):
            return spec_of(REP, axes)
        p = leaf_path(family, path)
        hit = match_path(p)
        if hit is None:
            raise PartitionRuleError(
                f"no committed partition rule matches leaf {p} "
                f"(shape {tuple(leaf.shape)}) — add a rule to "
                "parallel/partition_rules.py (SH301: an unruled leaf "
                "would silently replicate)"
            )
        idx, dims = hit
        problem = rank_problem(dims, len(leaf.shape))
        if problem:
            raise PartitionRuleError(
                f"partition rule {RULES[idx][0]!r} matched leaf {p} "
                f"but {problem}"
            )
        return spec_of(dims, axes)

    return jax.tree_util.tree_map_with_path(one, tree)


def coverage(trees: dict) -> dict:
    """SH301 sweep over ``{entry_name: (family, state_pytree)}``:
    match every array leaf, account which rules fired.  Returns a
    JSON-ready dict — ``unmatched`` (leaves no rule covers, by pytree
    path), ``rank`` (rule/leaf rank disagreements), ``stale_rules``
    (rules matching no leaf of any registered tree: dead table rows
    fail exactly like dead budget entries)."""
    import jax

    unmatched: list[dict] = []
    rank_bad: list[dict] = []
    used: set[int] = set()
    leaves = 0
    for entry in sorted(trees):
        family, tree = trees[entry]
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            leaves += 1
            if is_trivial(leaf):
                continue
            p = leaf_path(family, path)
            hit = match_path(p)
            if hit is None:
                unmatched.append({
                    "entry": entry, "path": p,
                    "shape": [int(d) for d in leaf.shape],
                })
                continue
            idx, dims = hit
            used.add(idx)
            problem = rank_problem(dims, len(getattr(leaf, "shape", ())))
            if problem:
                rank_bad.append({
                    "entry": entry, "path": p,
                    "rule": RULES[idx][0], "detail": problem,
                })
    stale = [
        {"index": i, "rule": pat}
        for i, (pat, _dims) in enumerate(RULES)
        if i not in used
    ]
    return {
        "rules": len(RULES),
        "leaves": leaves,
        "unmatched": unmatched,
        "rank": rank_bad,
        "stale_rules": stale,
    }
