"""Instance-axis sharded general engine (shard_map over core/sim).

The full protocol ladder — retries, faults, crashes, hole-filling,
conflict re-proposal, in-order gates — runs sharded: ``core/sim``'s
``round_fn`` is built with an ``axis_name`` and becomes the per-shard
body of a ``shard_map`` over the instance axis (BASELINE config 4's
shape: 7-node, 100M instances over a v5e-8 slice).  This is the
scale-out the reference reaches with one thread per node over
in-process queues (ref multi/main.cpp:51-162) — here each shard owns a
contiguous block of instances and the cross-shard traffic is a handful
of scalar/[P]-sized ``pmax``/``psum`` reductions per round over ICI.

Sharding layout:
- ``[A, I]`` / ``[P, I]`` / ``[P, A, I]`` protocol arrays (instances
  minor — see core/sim.py's layout note): split over the instance
  axis.
- ``[P]`` / ``[A]`` scalars and the network calendars: replicated —
  their updates are functions of replicated arrivals plus the global
  reductions, so every shard computes identical copies.
- Queue state (``pend``/``gate``/``head``/``tail``): per-shard
  *private* — each proposer's workload is round-robin split across
  shards and each shard first-fit-assigns its own queue onto its own
  free instances.  Assignment order therefore differs from the
  unsharded engine (values land at shard-local lowest-free instances,
  not global), which changes *placement*, never *safety*: the
  invariant checks (agreement, exactly-once, in-order gates) and the
  chosen-value multiset are placement-independent, and the reference
  itself never pins values to instances (``AvailableInstanceIDs.Next``
  is just "some free id", ref multi/paxos.cpp:253-318).
- Conflict re-proposals requeue into the conflicting shard's own
  queue, so the per-shard capacity proof of ``prepare_queues`` holds
  with ``i_local`` headroom.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_paxos.analysis import tracecount
from tpu_paxos.config import SimConfig
from tpu_paxos.core import sim as simm
from tpu_paxos.core import values as val
from tpu_paxos.parallel import mesh as pmesh
from tpu_paxos.parallel.mesh import INSTANCE_AXIS, instance_axes
from tpu_paxos.utils import prng


def _state_specs(st: simm.SimState, axes=INSTANCE_AXIS) -> simm.SimState:
    """PartitionSpec pytree for a (global, queue-wrapped) SimState
    under the instance mesh, derived PER LEAF from the committed
    partition-rule table (parallel/partition_rules.py): [.., I]
    protocol arrays split on the minor instance axis, the per-shard
    queue leaves on their leading shard axis, [P]/[A] control plane
    and calendars replicated.  ``axes`` is the mesh axis name (or
    tuple of names for the 2-D dcn x ici multi-host mesh).  A state
    leaf the table does not rule raises by pytree path — the runtime
    twin of the shard audit's SH301."""
    from tpu_paxos.parallel import partition_rules as prules

    return prules.tree_spec("sim", st, axes)


def _unwrap(st: simm.SimState) -> simm.SimState:
    """Strip the leading shard axis from the per-shard queue leaves
    (local block [1, P, C] -> [P, C]) so round_fn sees its usual
    shapes."""
    pr = st.prop
    return st._replace(
        prop=pr._replace(
            pend=pr.pend[0], gate=pr.gate[0], head=pr.head[0], tail=pr.tail[0]
        )
    )


def _wrap(st: simm.SimState) -> simm.SimState:
    pr = st.prop
    return st._replace(
        prop=pr._replace(
            pend=pr.pend[None],
            gate=pr.gate[None],
            head=pr.head[None],
            tail=pr.tail[None],
        )
    )


def split_workload(
    workload: list[np.ndarray],
    gates: list[np.ndarray] | None,
    n_shards: int,
):
    """Chain-aware round-robin split of each proposer's (vid, gate)
    sequence over shards; returns per-shard workload/gates lists.

    A gated entry must land on the shard where its gate's value lands
    (whatever entry that gate points at — immediate predecessor,
    branching fan-out, a forward reference, or another proposer's
    value): the executed-order guarantee relies on assignment
    monotonicity, which holds within a shard's region (per-proposer
    frontiers include all committed instances) but not across regions,
    and the engine's gate test is shard-local.  Entries are therefore
    grouped into connected components of the gate graph (union-find)
    and whole components round-robin over shards.  Gates referencing
    vids outside the workload leave their entry in its own component
    (such gates never satisfy, exactly as unsharded)."""
    nonev = int(val.NONE)
    entries = []  # (pi, vid, gate) in scan order
    vid_pos: dict[int, int] = {}
    for pi, w in enumerate(workload):
        w = np.asarray(w, np.int32)
        g = (
            np.full(len(w), nonev, np.int32)
            if gates is None or not len(gates[pi])
            else np.asarray(gates[pi], np.int32)
        )
        for k in range(len(w)):
            vid_pos.setdefault(int(w[k]), len(entries))
            entries.append((pi, int(w[k]), int(g[k])))

    parent = list(range(len(entries)))

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for e, (_, _, gv) in enumerate(entries):
        if gv != nonev and gv in vid_pos:
            ra, rb = find(e), find(vid_pos[gv])
            if ra != rb:
                parent[max(ra, rb)] = min(ra, rb)

    shard_of_root: dict[int, int] = {}
    wls = [[[] for _ in workload] for _ in range(n_shards)]
    gts = [[[] for _ in workload] for _ in range(n_shards)]
    nxt = 0
    for e, (pi, v, gv) in enumerate(entries):
        r = find(e)
        if r not in shard_of_root:
            shard_of_root[r] = nxt % n_shards
            nxt += 1
        shard = shard_of_root[r]
        wls[shard][pi].append(v)
        gts[shard][pi].append(gv)
    to_np = lambda seqs: [np.asarray(s, np.int32) for s in seqs]  # noqa: E731
    return (
        [to_np(wl) for wl in wls],
        None if gates is None else [to_np(gt) for gt in gts],
    )


def min_instances(
    workload: list[np.ndarray],
    gates: list[np.ndarray] | None,
    n_shards: int,
) -> int:
    """Smallest mesh-aligned ``n_instances`` that gives every shard 2x
    its largest workload: the chain-aware split keeps whole gate
    chains on one shard, so per-shard demand is set by the biggest
    component cluster, not ``total/n_shards``, and the 2x headroom
    mirrors the unsharded harness sizing (conflict re-proposals and
    hole-filling no-ops consume extra instances)."""
    wls, _ = split_workload(workload, gates, n_shards)
    max_load = max(sum(len(w) for w in wl) for wl in wls)
    return n_shards * max(2 * max_load, 1)


def prepare_queues_sharded(
    cfg: SimConfig,
    workload: list[np.ndarray],
    gates: list[np.ndarray] | None,
    n_shards: int,
):
    """Per-shard queue arrays: returns (pend [D, P, C+W], gate
    [D, P, C+W] — W-padded like ``prepare_queues``'s rows —
    tail [D, P], c) with a uniform capacity C sized by the largest
    shard-local workload plus ``i_local`` requeue headroom (the
    per-shard version of ``prepare_queues``'s capacity proof)."""
    p = len(cfg.proposers)
    i_loc = cfg.n_instances // n_shards
    wls, gts = split_workload(workload, gates, n_shards)
    c = max(
        max((len(w) for w in wl), default=0) for wl in wls
    ) + i_loc + 8
    # rows pre-padded by the window width — see prepare_queues
    width = c + cfg.assign_window
    pend = np.full((n_shards, p, width), int(val.NONE), np.int32)
    gate = np.full((n_shards, p, width), int(val.NONE), np.int32)
    tail = np.zeros((n_shards, p), np.int32)
    for s in range(n_shards):
        for pi, wl in enumerate(wls[s]):
            pend[s, pi, : len(wl)] = wl
            tail[s, pi] = len(wl)
            if gts is not None and len(gts[s][pi]):
                g = gts[s][pi]
                gate[s, pi, : len(g)] = g
    return pend, gate, tail, c


def init_sharded_state(
    cfg: SimConfig, mesh: Mesh, pend, gate, tail, root: jax.Array
) -> simm.SimState:
    """Global SimState laid out over the mesh (queue leaves carry the
    leading shard axis)."""
    p = len(cfg.proposers)
    dummy = np.full((p, pend.shape[2]), int(val.NONE), np.int32)
    st = simm.init_state(cfg, dummy, dummy, np.zeros((p,), np.int32), root)
    st = st._replace(
        prop=st.prop._replace(
            pend=jnp.asarray(pend),
            gate=jnp.asarray(gate),
            head=jnp.zeros(tail.shape, jnp.int32),
            tail=jnp.asarray(tail),
        )
    )
    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        _state_specs(st, instance_axes(mesh)),
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.tree.map(jax.device_put, st, shardings)


def build_runner(
    cfg: SimConfig,
    mesh: Mesh,
    workload: list[np.ndarray] | None = None,
    gates: list[np.ndarray] | None = None,
):
    """Compile-once runner: returns ``(fn, root, state, expected)``
    where ``fn(root, state) -> final state`` is the jitted shard_map'd
    whole-run loop.  Benchmarks call ``fn`` twice to time steady-state
    without compilation."""
    d = mesh.size
    if cfg.n_instances % d:
        raise ValueError(
            f"n_instances ({cfg.n_instances}) must divide over {d} devices"
        )
    if workload is None:
        workload = simm.default_workload(cfg)
    pend, gate, tail, c = prepare_queues_sharded(cfg, workload, gates, d)
    # Liveness precondition: a shard cannot place more values than it
    # has instances (instances are never reused) — undersized configs
    # used to spin to max_rounds instead of failing fast.
    max_load = int(tail.sum(axis=1).max())
    if cfg.n_instances // d < max_load:
        raise ValueError(
            f"shard workload of {max_load} values exceeds "
            f"{cfg.n_instances // d} instances per shard; need "
            f"n_instances >= {min_instances(workload, gates, d)} "
            f"(see min_instances)"
        )
    root = prng.root_key(cfg.seed)
    state = init_sharded_state(cfg, mesh, pend, gate, tail, root)
    axes = instance_axes(mesh)
    round_fn = simm.build_engine(
        cfg,
        c,
        axis_name=axes,
        n_shards=d,
        vid_cap=simm.gates_vid_cap(workload, gates),
    )

    def body(root, st):
        st = _unwrap(st)

        def cond(s):
            return (~s.done) & (s.t < cfg.round_budget)

        def step(s):
            return round_fn(root, s)

        return _wrap(jax.lax.while_loop(cond, step, st))

    specs = _state_specs(state, axes)
    mapped = jax.jit(
        pmesh.shard_map(
            body,
            mesh,
            in_specs=(P(), specs),
            out_specs=specs,
        )
    )

    def runner(root, st):
        with tracecount.engine_scope("sharded_sim"):
            return mapped(root, st)

    runner.lower = mapped.lower  # keep the AOT surface for benchmarks
    expected = np.unique(
        np.concatenate(
            [np.asarray(w, np.int32).reshape(-1) for w in workload]
        )
    )
    return runner, root, state, expected


def to_result(final: simm.SimState, expected: np.ndarray) -> simm.SimResult:
    return simm.to_result(final, expected)


def run_sharded(
    cfg: SimConfig,
    mesh: Mesh,
    workload: list[np.ndarray] | None = None,
    gates: list[np.ndarray] | None = None,
) -> simm.SimResult:
    """Drive the general engine to quiescence with the instance axis
    sharded over ``mesh`` — the sharded twin of ``core.sim.run``."""
    fn, root, state, expected = build_runner(cfg, mesh, workload, gates)
    return to_result(fn(root, state), expected)


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """Canonical sharded-general-engine trace (analysis/registry.py):
    the full round ladder as the shard_map body, over a 1-device mesh
    (shape-identical on any host; the cross-shard pmax/psum reductions
    are in the trace regardless of mesh size)."""
    from tpu_paxos.analysis.registry import AuditEntry
    from tpu_paxos.core.sim import audit_canonical_cfg

    def _setup(mesh):
        cfg = audit_canonical_cfg()
        fn, root, state, _expected = build_runner(cfg, mesh)
        return fn, (root, state)

    def build():
        return _setup(pmesh.make_instance_mesh(1))

    def shard_build(mesh):
        # the canonical cfg's n_instances (16) divides the whole
        # {1, 2, 4, 8} mesh grid — same program, reshaped
        return _setup(mesh)

    def shard_state():
        # the global sharded SimState (queue leaves carry the leading
        # shard axis) the partition table must cover leaf-for-leaf
        cfg = audit_canonical_cfg()
        _fn, _root, state, _expected = build_runner(
            cfg, pmesh.make_instance_mesh(1)
        )
        return "sim", state

    return [AuditEntry(
        "sharded_sim.run_rounds", build,
        covers=("build_runner",),
        mesh_axes=(INSTANCE_AXIS,),
        allow=("IR204",),
        why="same unique-key compaction sorts as sim.run_rounds (the "
            "shard_map body IS core/sim's round_fn)",
        shard_build=shard_build,
        shard_state=shard_state,
    )]
