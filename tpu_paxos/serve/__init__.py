"""Open-loop serving harness: consensus as a traffic-serving service.

Everything else in the repo benches CLOSED-loop batch — the driver
owns the value stream and the clock stops at quiescence.  This
package is the production shape (ROADMAP item 1): values *arrive*
(Poisson or trace replay at a configured offered rate, in rounds of
the virtual clock), get admitted into the general engine's contiguous
free-suffix ring mid-flight, and the metric is commit latency
(p50/p99/p999) at a sustained offered load, measured on device by the
flight recorder's latency ledger with admission stamped at INGEST
time.

Submodules are lazily re-exported (PEP 562), mirroring ``fleet``:
``driver`` owns the jitted dispatch-window surface (an audit
provider), ``harness`` the host-side ingestion loop + CLI,
``arrivals`` the arrival processes (pure numpy, jax-free), and
``fleet`` the multi-tenant serve lanes (the dispatch window vmapped
over ``[lanes]`` tenant streams with on-device per-lane SLO
verdicts — its own audit provider).
"""

_SUBMODULES = ("arrivals", "breach", "driver", "fleet", "harness")


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib

        return importlib.import_module(f"tpu_paxos.serve.{name}")
    raise AttributeError(f"module 'tpu_paxos.serve' has no attribute {name!r}")
