"""Serve-axis breach fitness for the selection loop (fleet/evolve).

The liveness axis hunts wedges with fault-schedule genomes; this
module is its serve-side twin: a genome here is an OFFERED-LOAD shape
(per-tenant arrival process + rate + seeds) under a quantized
"weather" preset, and fitness is the windowed SLO burn rate the
recorder already emits — how close that load shape drove some window
to its error budget.  The serve engines take NO fault schedule (the
i.i.d. drop/dup/delay knobs are COMPILE-TIME constants of the
envelope), so weather cannot be a free per-lane gene: it is drawn
from the small :data:`WEATHERS` preset table, the population is
partitioned into fixed-size weather slots, and one generation costs
one ``serve_fleet_run`` dispatch PER PRESET through the shared
envelope cache — every preset compiles in generation 0 and never
again (census-pinned by tests/test_evolve.py).

Per-genome fitness keeps the lane axis (``telemetry.recorder.
lane_burn_rates``) so selection credits the genome that burned, and
breaching lanes carry the judge's diagnosis block — the stable cause
names ``--hunt`` steers toward (``saturation`` is the serve-reachable
family: backlog growth under queue-dominated latency).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.serve import arrivals as arrv
from tpu_paxos.serve import fleet as sfl
from tpu_paxos.serve import harness as sh
from tpu_paxos.telemetry import recorder as telem

#: Quantized weather presets (name -> FaultConfig knobs).  Each preset
#: is ONE envelope compile; keep this table SMALL and append-only —
#: every entry the population uses is paid for in generation 0.
#: Rates are per 10_000 (config.FaultConfig semantics).
WEATHERS = (
    ("calm", dict(drop_rate=0, dup_rate=0, max_delay=1)),
    ("breezy", dict(drop_rate=500, dup_rate=1000, max_delay=2)),
    ("squall", dict(drop_rate=2000, dup_rate=1000, max_delay=3)),
)
WEATHER_NAMES = tuple(n for n, _ in WEATHERS)

#: Arrival-process gene alphabet (names of the deterministic samplers
#: in serve/arrivals.py).  ``immediate`` is offered-load-infinity.
ARRIVAL_KINDS = ("immediate", "poisson", "bursty", "spike")

#: Offered-rate gene grid, values per 1000 rounds (quantized so the
#: mutation step is a tier move, like the WAN knob tiers).
RATE_GRID = (250, 500, 1000, 2000, 4000)

#: Cause family -> the arrival kinds whose load shape can produce it
#: on the serve axis (the hunt bias table; mirrors evolve's
#: CAUSE_FAMILIES for fault kinds).  Only ``saturation`` is
#: load-reachable — the others need fault schedules the serve engine
#: does not take.
HUNT_KINDS = {"saturation": ("bursty", "spike", "immediate")}


@dataclasses.dataclass(frozen=True)
class ServeGenome:
    """One serve-lane individual: a weather slot plus the load shape.
    ``kinds``/``rates`` are per-tenant (one entry per workload
    stream); ``aseed`` seeds the arrival processes, ``seed`` the
    engine."""

    weather: str
    kinds: tuple
    rates: tuple
    aseed: int
    seed: int

    def __post_init__(self):
        if self.weather not in WEATHER_NAMES:
            raise ValueError(f"unknown weather {self.weather!r}")
        if len(self.kinds) != len(self.rates):
            raise ValueError("kinds/rates must be per-tenant parallel")
        for k in self.kinds:
            if k not in ARRIVAL_KINDS:
                raise ValueError(f"unknown arrival kind {k!r}")
        for r in self.rates:
            if r not in RATE_GRID:
                raise ValueError(f"rate {r} off the RATE_GRID")


def weather_cfg(cfg: SimConfig, weather: str) -> SimConfig:
    """The base config under one weather preset (replaces the whole
    fault layer — serve engines reject schedules anyway)."""
    kw = dict(WEATHERS)[weather]
    return dataclasses.replace(cfg, faults=FaultConfig(**kw))


def _rounds(kind: str, n: int, rate: int, seed: int) -> np.ndarray:
    if kind == "immediate":
        return arrv.immediate_rounds(n)
    if kind == "poisson":
        return arrv.poisson_rounds(n, rate, seed)
    if kind == "bursty":
        return arrv.bursty_rounds(n, rate, seed)
    if kind == "spike":
        return arrv.spike_rounds(n, rate, seed)
    raise ValueError(f"unknown arrival kind {kind!r}")


def lane_of(genome: ServeGenome, workload) -> sfl.ServeLane:
    """Express one genome as a ServeLane over the shared workload:
    per-tenant arrival rounds drawn by the genome's kind/rate genes
    (tenant t's process seeded at ``aseed*131 + t`` so tenants are
    independent but the genome is one deterministic point)."""
    if len(genome.kinds) != len(workload):
        raise ValueError(
            f"genome has {len(genome.kinds)} tenants; workload has "
            f"{len(workload)}"
        )
    arrs = [
        np.sort(_rounds(k, len(wl), r, genome.aseed * 131 + t))
        for t, (k, r, wl) in enumerate(
            zip(genome.kinds, genome.rates, workload)
        )
    ]
    return sfl.ServeLane(workload, arrs, genome.seed)


def sample_serve_genome(
    rng, workload, weather: str, hunt: str | None = None,
    seed_span: int = 1 << 16,
) -> ServeGenome:
    """Draw one individual for a weather slot.  ``hunt`` biases the
    per-tenant kind draw toward :data:`HUNT_KINDS`' family for that
    cause (uniform over the family; uniform over all kinds
    otherwise)."""
    kinds = HUNT_KINDS.get(hunt, ARRIVAL_KINDS)
    ks = tuple(kinds[int(rng.integers(0, len(kinds)))] for _ in workload)
    rs = tuple(
        RATE_GRID[int(rng.integers(0, len(RATE_GRID)))] for _ in workload
    )
    return ServeGenome(
        weather=weather, kinds=ks, rates=rs,
        aseed=int(rng.integers(0, seed_span)),
        seed=int(rng.integers(0, seed_span)),
    )


def mutate_serve_genome(
    rng, g: ServeGenome, hunt: str | None = None,
    seed_span: int = 1 << 16,
) -> ServeGenome:
    """One mutation step: pick a gene family (kind flip, rate tier
    step, arrival reseed, engine reseed) and move it.  The weather
    slot NEVER mutates — it is the envelope partition (a weather flip
    would be a new compile, breaking the zero-warm-compile
    contract)."""
    move = int(rng.integers(0, 4))
    if move == 0:
        t = int(rng.integers(0, len(g.kinds)))
        kinds = HUNT_KINDS.get(hunt, ARRIVAL_KINDS)
        ks = list(g.kinds)
        ks[t] = kinds[int(rng.integers(0, len(kinds)))]
        return dataclasses.replace(g, kinds=tuple(ks))
    if move == 1:
        t = int(rng.integers(0, len(g.rates)))
        i = RATE_GRID.index(g.rates[t])
        step = 1 if rng.integers(0, 2) else -1
        rs = list(g.rates)
        rs[t] = RATE_GRID[min(max(i + step, 0), len(RATE_GRID) - 1)]
        return dataclasses.replace(g, rates=tuple(rs))
    if move == 2:
        return dataclasses.replace(
            g, aseed=int(rng.integers(0, seed_span))
        )
    return dataclasses.replace(g, seed=int(rng.integers(0, seed_span)))


def evaluate(
    cfg: SimConfig,
    genomes,
    workload,
    *,
    slo: sh.ServeSLO,
    rounds_per_window: int = sh.ROUNDS_PER_WINDOW,
    windows_per_dispatch: int = sh.WINDOWS_PER_DISPATCH,
    admit_width: int | None = None,
    mesh=None,
) -> dict:
    """One generation's serve fitness: group the population by
    weather slot (preserving genome order within each), run ONE
    ``serve_fleet_run`` dispatch per preset present through the
    shared envelope cache, and scatter per-lane results back to
    genome order.

    Returns ``{"burn": [n] float, "breach": [n] bool,
    "causes": {genome_index: [cause names]},
    "verdicts": {genome_index: slo verdict}}`` — ``burn`` is the
    max-over-windows burn rate at the SLO's threshold (higher =
    fitter for breach hunting), ``causes`` only for flagged lanes
    whose judge attached a diagnosis."""
    genomes = list(genomes)
    n = len(genomes)
    burn = [0.0] * n
    breach = [False] * n
    causes: dict = {}
    verdicts: dict = {}
    for name, _ in WEATHERS:
        idx = [i for i, g in enumerate(genomes) if g.weather == name]
        if not idx:
            continue
        wcfg = weather_cfg(cfg, name)
        lanes = [lane_of(genomes[i], workload) for i in idx]
        rep = sfl.serve_fleet_run(
            wcfg, lanes,
            rounds_per_window=rounds_per_window,
            windows_per_dispatch=windows_per_dispatch,
            admit_width=admit_width, slo=slo, mesh=mesh,
        )
        rates = telem.lane_burn_rates(
            np.asarray(rep.windows.lat_hist),  # paxlint: allow[JAX103] one transfer per completed preset dispatch, not per round
            slo.latency_rounds, slo.budget_milli,
        )
        flags = np.asarray(rep.breach)  # paxlint: allow[JAX103] one transfer per completed preset dispatch, not per round
        for li, gi in enumerate(idx):
            burn[gi] = float(rates[li])
            breach[gi] = bool(flags[li])
            v = (rep.slo or {}).get(li)
            if v is not None:
                verdicts[gi] = v
                diag = v.get("diagnosis")
                if diag:
                    causes[gi] = list(diag.get("causes", []))
    return {
        "burn": burn, "breach": breach,
        "causes": causes, "verdicts": verdicts,
    }
