"""Arrival processes and the window-quantized admission plan.

Open-loop load is expressed on the VIRTUAL clock: an arrival process
assigns each value an arrival round, and the serve harness admits a
value at the first dispatch-window boundary at or after its arrival
(window quantization is part of the serving latency — a value
arriving mid-window waits for the next upload, exactly like a request
waiting for the next batch in a batched serving system).  Keeping
load in rounds makes every run deterministic and replayable: the same
(seed, rate) always produces the same admission timeline, so the
pipelined and sequential dispatch modes run bit-identical protocol
trajectories and differ only in wall clock.

Offered load is an integer ``rate_milli`` — values per 1000 rounds —
so sweep points serialize exactly in JSON and bench records.  The
offered-load-∞ limit (every value arrives at round 0, the zero-load
parity shape: the serve path must then be decision-log-identical to
the closed-loop engine) is :func:`immediate_rounds`.

Pure numpy — this module must import (and stay deterministic) without
jax, like the rest of the host-side planning layer.
"""

from __future__ import annotations

import numpy as np

#: Local copy of core/values.NONE (-1): importing core.values drags in
#: jax, and this module's jax-freedom is load-bearing (the admission
#: plan runs on the ingestion thread of a serving host; tests pin the
#: import contract).
NONE = -1


def poisson_rounds(n_values: int, rate_milli: int, seed: int) -> np.ndarray:
    """Sorted int32 arrival rounds of a Poisson process at
    ``rate_milli`` values per 1000 rounds: exponential inter-arrival
    gaps with mean ``1000/rate_milli`` rounds, cumulated and floored
    to the round grid.  Deterministic per (n_values, rate_milli,
    seed)."""
    if rate_milli <= 0:
        raise ValueError(
            f"rate_milli must be positive (got {rate_milli}); use "
            "immediate_rounds() for the offered-load-∞ limit"
        )
    # domain-separated from every other harness rng (seed tuples mix
    # like SeedSequence spawn keys)
    rng = np.random.default_rng((0x53455256, int(seed)))
    gaps = rng.exponential(1000.0 / rate_milli, size=int(n_values))
    return np.floor(np.cumsum(gaps)).astype(np.int32)


def pareto_rounds(
    n_values: int, rate_milli: int, seed: int, alpha: float = 1.5
) -> np.ndarray:
    """Heavy-tailed arrivals: Lomax (Pareto-II) inter-arrival gaps
    with tail index ``alpha`` scaled to the same MEAN gap as
    :func:`poisson_rounds` at ``rate_milli`` (``alpha`` must exceed 1
    or the mean diverges) — long quiet stretches punctuated by
    clustered arrivals, the classic open-internet traffic shape the
    exponential's memorylessness cannot produce.  Deterministic per
    (n_values, rate_milli, seed, alpha)."""
    if rate_milli <= 0:
        raise ValueError(
            f"rate_milli must be positive (got {rate_milli}); use "
            "immediate_rounds() for the offered-load-∞ limit"
        )
    if alpha <= 1.0:
        raise ValueError(
            f"alpha must exceed 1 (got {alpha}); at alpha <= 1 the "
            "Lomax mean diverges and rate_milli means nothing"
        )
    rng = np.random.default_rng((0x50415245, int(seed)))
    mean = 1000.0 / rate_milli
    # Lomax mean = scale / (alpha - 1)  =>  scale pins the offered rate
    gaps = rng.pareto(alpha, size=int(n_values)) * (mean * (alpha - 1.0))
    return np.floor(np.cumsum(gaps)).astype(np.int32)


def bursty_rounds(
    n_values: int, rate_milli: int, seed: int, burst: int = 8
) -> np.ndarray:
    """Bursty arrivals: values arrive in geometric-size bursts (mean
    ``burst`` values sharing ONE arrival round) separated by
    exponential gaps scaled so the long-run offered rate is still
    ``rate_milli`` values per 1000 rounds — the batched-upstream shape
    (a replicating shard, a client-side retry storm) that stresses
    admission-window quantization hardest.  Deterministic per
    (n_values, rate_milli, seed, burst)."""
    if rate_milli <= 0:
        raise ValueError(
            f"rate_milli must be positive (got {rate_milli}); use "
            "immediate_rounds() for the offered-load-∞ limit"
        )
    if burst < 1:
        raise ValueError(f"burst must be >= 1 (got {burst})")
    rng = np.random.default_rng((0x42555253, int(seed)))
    n = int(n_values)
    sizes = rng.geometric(1.0 / burst, size=n)  # mean `burst`, >= 1
    # truncate the burst train at exactly n values (sizes are >= 1,
    # so n bursts always cover n values)
    counts = np.clip(n - (np.cumsum(sizes) - sizes), 0, sizes)
    keep = counts > 0
    sizes, counts = sizes[keep], counts[keep]
    # burst START gaps: mean burst arrivals per gap at the target rate
    gaps = rng.exponential(1000.0 / rate_milli * burst, size=len(sizes))
    starts = np.floor(np.cumsum(gaps)).astype(np.int64)
    return np.repeat(starts, counts)[:n].astype(np.int32)


def diurnal_rounds(
    n_values: int, rate_milli: int, seed: int,
    period: int = 2048, depth: float = 0.8,
) -> np.ndarray:
    """Diurnal arrivals: an inhomogeneous Poisson process whose rate
    swings sinusoidally around ``rate_milli`` (peak ``1 + depth``,
    trough ``1 - depth`` of the mean) with period ``period`` rounds —
    the day/night load curve a fleet controller must ride.  Sampled
    exactly by time-warping a unit-rate process through the inverse
    integrated-rate function (bisection on the monotone cumulative
    rate; no thinning, so the draw count is deterministic).
    Deterministic per (n_values, rate_milli, seed, period, depth)."""
    if rate_milli <= 0:
        raise ValueError(
            f"rate_milli must be positive (got {rate_milli}); use "
            "immediate_rounds() for the offered-load-∞ limit"
        )
    if not (0.0 <= depth < 1.0):
        raise ValueError(f"depth must be in [0, 1) (got {depth})")
    if period < 2:
        raise ValueError(f"period must be >= 2 (got {period})")
    rng = np.random.default_rng((0x44495552, int(seed)))
    base = rate_milli / 1000.0  # values per round
    if int(n_values) == 0:
        return np.zeros((0,), np.int32)
    unit = np.cumsum(rng.exponential(1.0, size=int(n_values)))

    def cum_rate(t):
        # integral of base * (1 + depth * sin(2 pi t / period))
        w = 2.0 * np.pi / period
        return base * (t + depth * (1.0 - np.cos(w * t)) / w)

    lo = np.zeros_like(unit)
    hi = np.full_like(unit, unit[-1] / (base * (1.0 - depth)) + period)
    for _ in range(64):  # bisection to well under round resolution
        mid = 0.5 * (lo + hi)
        below = cum_rate(mid) < unit
        lo = np.where(below, mid, lo)
        hi = np.where(below, hi, mid)
    return np.floor(hi).astype(np.int32)


def spike_rounds(
    n_values: int, rate_milli: int, seed: int,
    factor: int = 8, start_frac: float = 0.375, len_frac: float = 0.25,
) -> np.ndarray:
    """Load-spike arrivals: a Poisson process at ``rate_milli`` whose
    rate multiplies by ``factor`` over one contiguous mid-run span —
    the flash-crowd shape the admission controller
    (serve/control.py) is judged against.  The spike spans
    ``[start_frac, start_frac + len_frac)`` of the BASE-rate horizon
    (``1000 * n_values / rate_milli`` rounds), so the same fractions
    mean the same story at every rate.  Sampled exactly by inverting
    the piecewise-linear cumulative rate in closed form (no thinning
    — the draw count is deterministic).  Deterministic per
    (n_values, rate_milli, seed, factor, start_frac, len_frac)."""
    if rate_milli <= 0:
        raise ValueError(
            f"rate_milli must be positive (got {rate_milli}); use "
            "immediate_rounds() for the offered-load-∞ limit"
        )
    if factor < 1:
        raise ValueError(f"factor must be >= 1 (got {factor})")
    if not (0.0 <= start_frac and 0.0 < len_frac):
        raise ValueError("spike span fractions must be positive")
    rng = np.random.default_rng((0x5350494B, int(seed)))
    base = rate_milli / 1000.0  # values per round
    if int(n_values) == 0:
        return np.zeros((0,), np.int32)
    horizon = n_values / base
    t0, t1 = start_frac * horizon, (start_frac + len_frac) * horizon
    unit = np.cumsum(rng.exponential(1.0, size=int(n_values)))
    # cumulative rate: base*t before t0; slope base*factor inside
    # [t0, t1); base again after — invert piecewise
    u0 = base * t0
    u1 = u0 + base * factor * (t1 - t0)
    t = np.where(
        unit <= u0,
        unit / base,
        np.where(
            unit <= u1,
            t0 + (unit - u0) / (base * factor),
            t1 + (unit - u1) / base,
        ),
    )
    return np.floor(t).astype(np.int32)


#: Name -> builder map for the CLI's --arrivals flag (every builder
#: shares the (n_values, rate_milli, seed) signature; extra shape
#: knobs keep their defaults there).
ARRIVAL_BUILDERS = {
    "poisson": poisson_rounds,
    "pareto": pareto_rounds,
    "bursty": bursty_rounds,
    "diurnal": diurnal_rounds,
    "spike": spike_rounds,
}


def tier_priorities(vids, n_tiers: int = 3) -> np.ndarray:
    """A declared per-value priority column: tier ``vid % n_tiers``
    (0 = most important, higher tiers shed/defer first under the
    admission controller's degradation).  Deterministic and
    value-derived so replays reconstruct it from the artifact; real
    deployments would declare tiers per request class the same way."""
    if n_tiers < 1:
        raise ValueError(f"n_tiers must be >= 1 (got {n_tiers})")
    return (np.asarray(vids, np.int64) % int(n_tiers)).astype(np.int32)


def immediate_rounds(n_values: int) -> np.ndarray:
    """The offered-load-∞ limit: every value arrives at round 0 (all
    admitted in window 0 — the zero-load parity shape)."""
    return np.zeros((int(n_values),), np.int32)


def trace_rounds(rounds) -> np.ndarray:
    """Trace replay: an explicit arrival-round sequence.  Must be
    nondecreasing and nonnegative (arrival order is admission order —
    the queue is FIFO per proposer)."""
    arr = np.asarray(rounds, np.int32).reshape(-1)
    if arr.size and int(arr.min()) < 0:
        raise ValueError("trace arrival rounds must be nonnegative")
    if np.any(np.diff(arr) < 0):
        raise ValueError("trace arrival rounds must be nondecreasing")
    return arr


def split_round_robin(vids: np.ndarray, rounds: np.ndarray, n_prop: int):
    """Deal a single (vid, arrival-round) stream round-robin over the
    proposers in arrival order; per-proposer subsequences stay sorted.
    Returns ``(streams, arrs)`` — lists of per-proposer arrays."""
    vids = np.asarray(vids, np.int32).reshape(-1)
    rounds = np.asarray(rounds, np.int32).reshape(-1)
    if vids.shape != rounds.shape:
        raise ValueError("one arrival round per vid required")
    streams = [vids[p::n_prop] for p in range(n_prop)]
    arrs = [rounds[p::n_prop] for p in range(n_prop)]
    return streams, arrs


class ArrivalPlan:
    """The window-quantized admission plan: which values each dispatch
    window uploads, per proposer.

    Window ``j`` covers rounds ``[j*R, (j+1)*R)`` and its admission
    happens at round ``j*R``, BEFORE the window's rounds run — so it
    may admit exactly the values with ``arrival <= j*R`` not yet
    admitted (a value arriving strictly inside a window waits for the
    next boundary; one arriving at the boundary makes the upload).
    Every block is a NONE-padded value prefix per proposer row, ready
    for :func:`tpu_paxos.core.sim.admit_block`.

    ``prios`` is the optional PRIORITY COLUMN (one int tier per value,
    parallel to ``streams``; 0 = most important): the plain plan
    ignores it for admission — window quantization treats every tier
    alike — but carries it per block (:meth:`prio_block`) so the
    admission controller (serve/control.py) can shed or defer at
    declared tiers while deferred values keep their TRUE arrival
    rounds from this plan's ``arrs`` (they charge their real
    queue-wait through the ingest stamps)."""

    def __init__(self, streams, arrs, rounds_per_window: int, prios=None):
        if len(streams) != len(arrs):
            raise ValueError("one arrival array per proposer stream")
        self.streams = [np.asarray(s, np.int32).reshape(-1) for s in streams]
        self.arrs = [trace_rounds(a) for a in arrs]
        for s, a in zip(self.streams, self.arrs):
            if s.shape != a.shape:
                raise ValueError("one arrival round per stream value")
        if prios is None:
            self.prios = None
        else:
            if len(prios) != len(self.streams):
                raise ValueError("one priority array per proposer stream")
            self.prios = [np.asarray(p, np.int32).reshape(-1) for p in prios]
            for s, p in zip(self.streams, self.prios):
                if s.shape != p.shape:
                    raise ValueError("one priority tier per stream value")
                if p.size and int(p.min()) < 0:
                    raise ValueError("priority tiers must be nonnegative")
        if rounds_per_window <= 0:
            raise ValueError("rounds_per_window must be positive")
        self.rounds_per_window = int(rounds_per_window)
        # cut[p][j]: values of proposer p admitted by the start of
        # window j (cumulative); the final window admits everything.
        horizon = max(
            (int(a[-1]) for a in self.arrs if a.size), default=0
        )
        # the last admission window's boundary must reach the latest
        # arrival: ceil(horizon / R) + 1 windows, indices 0..ceil
        r = self.rounds_per_window
        self.n_windows = (horizon + r - 1) // r + 1
        # _cuts[p][j] .. _cuts[p][j+1]: the stream slice window j
        # uploads — cumulative arrivals <= j*R, leading 0 so window 0
        # takes exactly the round-0 arrivals
        self._cuts = [
            np.concatenate([
                [0],
                np.searchsorted(
                    a,
                    np.arange(self.n_windows) * r,
                    side="right",
                ),
            ])
            for a in self.arrs
        ]

    @property
    def n_values(self) -> int:
        return sum(len(s) for s in self.streams)

    @property
    def max_block(self) -> int:
        """Largest per-proposer admission count of any window — the
        floor for the driver's static ``admit_width``."""
        widest = 0
        for cuts in self._cuts:
            widest = max(widest, int(np.diff(cuts).max(initial=0)))
        return max(widest, 1)

    def block(self, j: int, admit_width: int):
        """Window ``j``'s upload: ``(admit [P, K], arr [P, K])`` int32
        — vids as a NONE-padded prefix per row, their arrival rounds
        alongside (0 in padding slots; the stamp scatter drops them).
        Windows past the plan return empty blocks (the drain phase)."""
        p = len(self.streams)
        admit = np.full((p, admit_width), NONE, np.int32)
        arr = np.zeros((p, admit_width), np.int32)
        if j >= self.n_windows:
            return admit, arr
        for pi in range(p):
            lo, hi = int(self._cuts[pi][j]), int(self._cuts[pi][j + 1])
            n = hi - lo
            if n > admit_width:
                raise ValueError(
                    f"window {j} admits {n} values for proposer {pi}; "
                    f"admit_width {admit_width} is too narrow "
                    "(use >= plan.max_block)"
                )
            admit[pi, :n] = self.streams[pi][lo:hi]
            arr[pi, :n] = self.arrs[pi][lo:hi]
        return admit, arr

    def prio_block(self, j: int, admit_width: int) -> np.ndarray:
        """Window ``j``'s priority tiers, ``[P, K]`` int32 aligned
        with :meth:`block`'s layout (0 in padding slots).  Requires a
        declared priority column."""
        if self.prios is None:
            raise ValueError("this plan declares no priority column")
        p = len(self.streams)
        out = np.zeros((p, admit_width), np.int32)
        if j >= self.n_windows:
            return out
        for pi in range(p):
            lo, hi = int(self._cuts[pi][j]), int(self._cuts[pi][j + 1])
            out[pi, :hi - lo] = self.prios[pi][lo:hi]
        return out
