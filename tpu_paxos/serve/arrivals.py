"""Arrival processes and the window-quantized admission plan.

Open-loop load is expressed on the VIRTUAL clock: an arrival process
assigns each value an arrival round, and the serve harness admits a
value at the first dispatch-window boundary at or after its arrival
(window quantization is part of the serving latency — a value
arriving mid-window waits for the next upload, exactly like a request
waiting for the next batch in a batched serving system).  Keeping
load in rounds makes every run deterministic and replayable: the same
(seed, rate) always produces the same admission timeline, so the
pipelined and sequential dispatch modes run bit-identical protocol
trajectories and differ only in wall clock.

Offered load is an integer ``rate_milli`` — values per 1000 rounds —
so sweep points serialize exactly in JSON and bench records.  The
offered-load-∞ limit (every value arrives at round 0, the zero-load
parity shape: the serve path must then be decision-log-identical to
the closed-loop engine) is :func:`immediate_rounds`.

Pure numpy — this module must import (and stay deterministic) without
jax, like the rest of the host-side planning layer.
"""

from __future__ import annotations

import numpy as np

#: Local copy of core/values.NONE (-1): importing core.values drags in
#: jax, and this module's jax-freedom is load-bearing (the admission
#: plan runs on the ingestion thread of a serving host; tests pin the
#: import contract).
NONE = -1


def poisson_rounds(n_values: int, rate_milli: int, seed: int) -> np.ndarray:
    """Sorted int32 arrival rounds of a Poisson process at
    ``rate_milli`` values per 1000 rounds: exponential inter-arrival
    gaps with mean ``1000/rate_milli`` rounds, cumulated and floored
    to the round grid.  Deterministic per (n_values, rate_milli,
    seed)."""
    if rate_milli <= 0:
        raise ValueError(
            f"rate_milli must be positive (got {rate_milli}); use "
            "immediate_rounds() for the offered-load-∞ limit"
        )
    # domain-separated from every other harness rng (seed tuples mix
    # like SeedSequence spawn keys)
    rng = np.random.default_rng((0x53455256, int(seed)))
    gaps = rng.exponential(1000.0 / rate_milli, size=int(n_values))
    return np.floor(np.cumsum(gaps)).astype(np.int32)


def immediate_rounds(n_values: int) -> np.ndarray:
    """The offered-load-∞ limit: every value arrives at round 0 (all
    admitted in window 0 — the zero-load parity shape)."""
    return np.zeros((int(n_values),), np.int32)


def trace_rounds(rounds) -> np.ndarray:
    """Trace replay: an explicit arrival-round sequence.  Must be
    nondecreasing and nonnegative (arrival order is admission order —
    the queue is FIFO per proposer)."""
    arr = np.asarray(rounds, np.int32).reshape(-1)
    if arr.size and int(arr.min()) < 0:
        raise ValueError("trace arrival rounds must be nonnegative")
    if np.any(np.diff(arr) < 0):
        raise ValueError("trace arrival rounds must be nondecreasing")
    return arr


def split_round_robin(vids: np.ndarray, rounds: np.ndarray, n_prop: int):
    """Deal a single (vid, arrival-round) stream round-robin over the
    proposers in arrival order; per-proposer subsequences stay sorted.
    Returns ``(streams, arrs)`` — lists of per-proposer arrays."""
    vids = np.asarray(vids, np.int32).reshape(-1)
    rounds = np.asarray(rounds, np.int32).reshape(-1)
    if vids.shape != rounds.shape:
        raise ValueError("one arrival round per vid required")
    streams = [vids[p::n_prop] for p in range(n_prop)]
    arrs = [rounds[p::n_prop] for p in range(n_prop)]
    return streams, arrs


class ArrivalPlan:
    """The window-quantized admission plan: which values each dispatch
    window uploads, per proposer.

    Window ``j`` covers rounds ``[j*R, (j+1)*R)`` and its admission
    happens at round ``j*R``, BEFORE the window's rounds run — so it
    may admit exactly the values with ``arrival <= j*R`` not yet
    admitted (a value arriving strictly inside a window waits for the
    next boundary; one arriving at the boundary makes the upload).
    Every block is a NONE-padded value prefix per proposer row, ready
    for :func:`tpu_paxos.core.sim.admit_block`."""

    def __init__(self, streams, arrs, rounds_per_window: int):
        if len(streams) != len(arrs):
            raise ValueError("one arrival array per proposer stream")
        self.streams = [np.asarray(s, np.int32).reshape(-1) for s in streams]
        self.arrs = [trace_rounds(a) for a in arrs]
        for s, a in zip(self.streams, self.arrs):
            if s.shape != a.shape:
                raise ValueError("one arrival round per stream value")
        if rounds_per_window <= 0:
            raise ValueError("rounds_per_window must be positive")
        self.rounds_per_window = int(rounds_per_window)
        # cut[p][j]: values of proposer p admitted by the start of
        # window j (cumulative); the final window admits everything.
        horizon = max(
            (int(a[-1]) for a in self.arrs if a.size), default=0
        )
        # the last admission window's boundary must reach the latest
        # arrival: ceil(horizon / R) + 1 windows, indices 0..ceil
        r = self.rounds_per_window
        self.n_windows = (horizon + r - 1) // r + 1
        # _cuts[p][j] .. _cuts[p][j+1]: the stream slice window j
        # uploads — cumulative arrivals <= j*R, leading 0 so window 0
        # takes exactly the round-0 arrivals
        self._cuts = [
            np.concatenate([
                [0],
                np.searchsorted(
                    a,
                    np.arange(self.n_windows) * r,
                    side="right",
                ),
            ])
            for a in self.arrs
        ]

    @property
    def n_values(self) -> int:
        return sum(len(s) for s in self.streams)

    @property
    def max_block(self) -> int:
        """Largest per-proposer admission count of any window — the
        floor for the driver's static ``admit_width``."""
        widest = 0
        for cuts in self._cuts:
            widest = max(widest, int(np.diff(cuts).max(initial=0)))
        return max(widest, 1)

    def block(self, j: int, admit_width: int):
        """Window ``j``'s upload: ``(admit [P, K], arr [P, K])`` int32
        — vids as a NONE-padded prefix per row, their arrival rounds
        alongside (0 in padding slots; the stamp scatter drops them).
        Windows past the plan return empty blocks (the drain phase)."""
        p = len(self.streams)
        admit = np.full((p, admit_width), NONE, np.int32)
        arr = np.zeros((p, admit_width), np.int32)
        if j >= self.n_windows:
            return admit, arr
        for pi in range(p):
            lo, hi = int(self._cuts[pi][j]), int(self._cuts[pi][j + 1])
            n = hi - lo
            if n > admit_width:
                raise ValueError(
                    f"window {j} admits {n} values for proposer {pi}; "
                    f"admit_width {admit_width} is too narrow "
                    "(use >= plan.max_block)"
                )
            admit[pi, :n] = self.streams[pi][lo:hi]
            arr[pi, :n] = self.arrs[pi][lo:hi]
        return admit, arr
