"""Host-side open-loop ingestion loop: double-buffered dispatch
windows over the serve driver, latency-at-load sweeps, and the
``python -m tpu_paxos serve`` CLI.

The loop is the serving hot path this package exists for.  Every
dispatch costs a fixed host+tunnel toll — call dispatch, the
admission upload, the scalar sync, and the metrics render (~90 ms
through the TPU device tunnel per PERF.md §Headline; ~2.4 ms of
call/sync/render overhead even on the CPU dev box) — so the
**double-buffered path** (the default) batches ``windows_per_
dispatch`` admission windows into each dispatch: their upload blocks
travel ahead of the rounds that consume them (the next windows'
admission overlapped with the current window's compute), the donated
loop state chains on device, and while one dispatch computes its
``S x R`` rounds the host assembles the next super-block and renders
the previous dispatch's metrics.  The **sequential-dispatch
baseline** (``windows_per_dispatch=1, pipelined=False``) is the
naive loop: one window per dispatch, block on its outputs, prepare
the next — paying the per-dispatch toll every window.

Every dispatch granularity runs a BIT-IDENTICAL protocol trajectory:
windows are fixed round spans, admission happens every
``rounds_per_window`` rounds stamped with true arrival rounds, and
the plan is precomputed on the virtual clock (serve/arrivals.py) —
so the bench's "at equal p99" is exact, not approximate (pinned by
tests/test_serve.py), and the throughput gap is pure
dispatch-overhead hiding.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import numpy as np

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.serve import arrivals as arrv

#: Default admission-window span, in rounds.  Small windows are the
#: serving-grade operating point: admission latency is bounded by one
#: window span, and the per-dispatch overhead they expose is exactly
#: what the double buffering hides.
ROUNDS_PER_WINDOW = 8

#: Default admission windows per dispatch (the double buffer's
#: amortization depth — the serving twin of the fast path's 16
#: windows/call).  1 = the sequential-dispatch baseline.
WINDOWS_PER_DISPATCH = 8


@dataclasses.dataclass
class ServeReport:
    """One open-loop run's outcome.  ``chosen_vid``/``chosen_ballot``
    transfer AFTER the clock stops (they exist for decision-log
    parity checks, not for the serving loop)."""

    cfg: SimConfig
    n_values: int
    rounds_per_window: int
    windows_per_dispatch: int
    admit_width: int
    pipelined: bool
    dispatches: int
    windows: int
    rounds: int
    done: bool
    decided_values: int  # real stamped values decided (hist mass)
    backlog: int  # admitted values not yet decided at stop
    p50: int
    p99: int
    p999: int
    latency_max: int
    wall_seconds: float
    summary: dict  # final cumulative flight-recorder summary dict
    window_decided: list  # per-dispatch cumulative decided counts
    chosen_vid: np.ndarray
    chosen_ballot: np.ndarray

    @property
    def values_per_sec(self) -> float:
        return self.decided_values / max(self.wall_seconds, 1e-9)


def serve_run(
    cfg: SimConfig,
    workload,
    arrival_rounds,
    *,
    rounds_per_window: int = ROUNDS_PER_WINDOW,
    windows_per_dispatch: int = WINDOWS_PER_DISPATCH,
    admit_width: int | None = None,
    pipelined: bool = True,
) -> ServeReport:
    """Serve one value stream open-loop to completion (or the round
    budget).  ``workload[p]`` is proposer ``p``'s vid sequence in
    queue order; ``arrival_rounds[p]`` its per-value arrival rounds
    (nondecreasing — the queue is FIFO per proposer).  All values
    arriving at round 0 is the zero-load parity shape: the run is
    decision-log-identical to closed-loop ``sim.run(cfg, workload)``.

    ``admit_width`` pins the upload block's static width and
    ``windows_per_dispatch`` the amortization depth (one executable
    per ``(S, K)`` call shape across a sweep); admission timing —
    hence the latency distribution — is identical for every ``S``.
    """
    import jax.numpy as jnp

    from tpu_paxos.analysis import tracecount
    from tpu_paxos.core import values as val
    from tpu_paxos.serve import driver as drv
    from tpu_paxos.telemetry import recorder as telem
    from tpu_paxos.utils import prng

    workload = [np.asarray(w, np.int32).reshape(-1) for w in workload]
    if len(workload) != len(cfg.proposers):
        raise ValueError("one value stream per proposer required")
    plan = arrv.ArrivalPlan(workload, arrival_rounds, rounds_per_window)
    k = int(admit_width or plan.max_block)
    if plan.max_block > k:
        raise ValueError(
            f"admit_width {k} below this plan's max block "
            f"{plan.max_block}"
        )
    s = int(windows_per_dispatch)
    if s < 1:
        raise ValueError("windows_per_dispatch must be >= 1")
    v_bound = drv.vid_bound_of(workload)
    root = prng.root_key(cfg.seed)
    ss, c = drv.init_serve_state(cfg, workload, v_bound, root)
    fn = drv.window_for(cfg, c, v_bound, rounds_per_window)
    p = len(cfg.proposers)
    empty = (
        jnp.full((s, p, k), val.NONE, jnp.int32),
        jnp.zeros((s, p, k), jnp.int32),
    )
    n_disp_admit = (plan.n_windows + s - 1) // s
    # Watchdog: the budget the closed-loop driver grants, in dispatches.
    disp_cap = max(
        cfg.round_budget // (rounds_per_window * s) + 1, n_disp_admit
    )

    def super_block(d):
        """Stack dispatch ``d``'s S admission windows; windows past
        the plan are empty rows (the plan pads them itself)."""
        a = np.stack([plan.block(d * s + i, k)[0] for i in range(s)])
        r = np.stack([plan.block(d * s + i, k)[1] for i in range(s)])
        return jnp.asarray(a), jnp.asarray(r)

    def harvest(out):
        # the one host sync per dispatch: the stop scalars + the
        # metrics-plane render of the cumulative summary
        done, t, summ = out
        return bool(done), int(t), summ

    window_decided: list[int] = []
    pending = None
    last_done, last_t, last_summ = False, 0, None
    d = harvested = 0
    t0 = time.perf_counter()  # paxlint: allow[DET001] wall metric only; never reaches artifacts
    with tracecount.engine_scope("serve"):
        while True:
            blk = super_block(d) if d < n_disp_admit else empty
            ss, done, t, summ = fn(ss, root, *blk)
            d += 1
            if pipelined:
                # double buffer: harvest the PREVIOUS dispatch while
                # this one computes; its scalars are already (or
                # nearly) resolved, so the poll costs no device idle
                if pending is not None:
                    last_done, last_t, last_summ = harvest(pending)
                    window_decided.append(int(last_summ.decided))
                    harvested += 1
                pending = (done, t, summ)
            else:
                # sequential baseline: block on this dispatch before
                # preparing the next — the bubble the double-buffered
                # mode exists to hide
                last_done, last_t, last_summ = harvest((done, t, summ))
                window_decided.append(int(last_summ.decided))
                harvested += 1
            # stop only on a quiescence signal from a dispatch that
            # saw EVERY admission — a mid-stream lull (quiescent
            # before later arrivals) must not end the run
            if harvested >= n_disp_admit and last_done:
                break
            if d >= disp_cap:
                break
        if pending is not None:
            last_done, last_t, last_summ = harvest(pending)
            window_decided.append(int(last_summ.decided))
    wall = time.perf_counter() - t0  # paxlint: allow[DET001] wall metric only; never reaches artifacts

    # Post-clock rendering: the final cumulative summary + decision
    # arrays transfer after the serving loop stopped timing.
    import jax

    host_summ = jax.tree.map(np.asarray, last_summ)
    sd = telem.summary_to_dict(host_summ)
    hist = np.asarray(host_summ.lat_hist)
    lat_max = int(host_summ.lat_max)
    decided_values = int(hist.sum())
    return ServeReport(
        cfg=cfg,
        n_values=plan.n_values,
        rounds_per_window=rounds_per_window,
        windows_per_dispatch=s,
        admit_width=k,
        pipelined=pipelined,
        dispatches=d,
        windows=d * s,
        rounds=last_t,
        done=last_done,
        decided_values=decided_values,
        backlog=plan.n_values - decided_values,
        p50=sd["latency_p50"],
        p99=sd["latency_p99"],
        p999=telem.latency_quantile(hist, 0.999, lat_max),
        latency_max=lat_max,
        wall_seconds=wall,
        summary=sd,
        window_decided=window_decided,
        chosen_vid=np.asarray(ss.sim.met.chosen_vid),
        chosen_ballot=np.asarray(ss.sim.met.chosen_ballot),
    )


def _point(rate_milli: int, rep: ServeReport) -> dict:
    return {
        "rate_milli": int(rate_milli),
        "p50": rep.p50,
        "p99": rep.p99,
        "p999": rep.p999,
        "latency_max": rep.latency_max,
        "decided": rep.decided_values,
        "backlog": rep.backlog,
        "done": rep.done,
        "rounds": rep.rounds,
        "dispatches": rep.dispatches,
        "windows": rep.windows,
        "wall_seconds": round(rep.wall_seconds, 4),
        "values_per_sec": round(rep.values_per_sec, 1),
        "sustained": bool(rep.done and rep.backlog == 0),
    }


def judge_knee(points: list, factor: float = 2.0) -> dict:
    """Bracket the saturation knee from a latency-at-load sweep
    (points sorted by rate).  A point SATURATES when the run failed
    to drain inside the round budget, or its MEDIAN commit latency
    blew past ``factor`` times the lowest-rate median — the classic
    latency-doubling knee.  The judgment deliberately reads p50, not
    p99: the tail carries the fault-retry ladder (a dropped accept's
    ~100-round restart shows up at p99 even at near-zero load), while
    queueing delay past the engine's service rate moves EVERY value —
    the median is the saturation signal.  Returns the bracketing
    rates (None where the sweep never crossed)."""
    if not points:
        return {"last_sustained_milli": None, "first_saturated_milli": None}
    base = max(points[0]["p50"], 1)
    last_ok, first_bad = None, None
    for pt in points:
        # >=: p50 is latency-bucket-quantized, so the doubling point
        # lands exactly ON factor * base
        bad = (not pt["sustained"]) or pt["p50"] >= factor * base
        if bad and first_bad is None:
            first_bad = pt["rate_milli"]
        if not bad and first_bad is None:
            last_ok = pt["rate_milli"]
    return {
        "last_sustained_milli": last_ok,
        "first_saturated_milli": first_bad,
        "p50_factor": factor,
        "p50_base": base,
    }


def sweep_load(
    cfg: SimConfig,
    n_values: int,
    rates_milli,
    *,
    seed: int = 0,
    rounds_per_window: int = ROUNDS_PER_WINDOW,
    windows_per_dispatch: int = WINDOWS_PER_DISPATCH,
    pipelined: bool = True,
    knee_factor: float = 2.0,
    admit_width: int | None = None,
) -> dict:
    """Latency at load: one open-loop Poisson run per offered rate
    (values per 1000 rounds), all sharing ONE compiled window (the
    admit width is the max over every rate's plan — raise it with
    ``admit_width`` to share an executable with runs outside the
    sweep), plus the knee judgment over the resulting points."""
    vids = np.arange(int(n_values), dtype=np.int32)
    n_prop = len(cfg.proposers)
    plans = {}
    for rm in rates_milli:
        rounds = arrv.poisson_rounds(n_values, int(rm), seed)
        plans[int(rm)] = arrv.split_round_robin(vids, rounds, n_prop)
    width = int(admit_width or 1)
    for rm, (streams, arrs) in plans.items():
        width = max(
            width,
            arrv.ArrivalPlan(streams, arrs, rounds_per_window).max_block,
        )
    points = []
    for rm in sorted(plans):
        streams, arrs = plans[rm]
        rep = serve_run(
            cfg, streams, arrs,
            rounds_per_window=rounds_per_window,
            windows_per_dispatch=windows_per_dispatch,
            admit_width=width,
            pipelined=pipelined,
        )
        points.append(_point(rm, rep))
    return {
        "metric": "serve_latency_at_load",
        "n_values": int(n_values),
        "rounds_per_window": int(rounds_per_window),
        "windows_per_dispatch": int(windows_per_dispatch),
        "admit_width": int(width),
        "points": points,
        "knee": judge_knee(points, knee_factor),
    }


def _serve_cfg(args) -> SimConfig:
    n_inst = args.instances or max(64, 2 * args.values)
    return SimConfig(
        n_nodes=args.nodes,
        n_instances=n_inst,
        proposers=tuple(range(args.proposers)),
        seed=args.seed,
        max_rounds=args.max_rounds,
        faults=FaultConfig(
            drop_rate=args.drop_rate,
            dup_rate=args.dup_rate,
            max_delay=args.max_delay,
            crash_rate=args.crash_rate,
        ),
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_paxos serve",
        description="open-loop serving harness: Poisson / trace-replay "
        "arrivals admitted mid-flight through double-buffered dispatch "
        "windows; commit latency (p50/p99/p999) at a sustained "
        "offered load, measured on device",
    )
    ap.add_argument("--nodes", type=int, default=5)
    ap.add_argument("--proposers", type=int, default=2)
    ap.add_argument("--values", type=int, default=256,
                    help="values in the arriving stream")
    ap.add_argument("--rate-milli", type=int, default=2000,
                    help="offered load: values per 1000 rounds "
                    "(0 = offered-load-∞, everything arrives at "
                    "round 0)")
    ap.add_argument("--sweep", type=str, default="",
                    help="comma-separated rate_milli list: run the "
                    "latency-at-load sweep + knee judgment instead "
                    "of a single rate")
    ap.add_argument("--trace", type=str, default="",
                    help="JSON file with an explicit arrival-round "
                    "list (trace replay; overrides --rate-milli)")
    ap.add_argument("--rounds-per-window", type=int,
                    default=ROUNDS_PER_WINDOW)
    ap.add_argument("--windows-per-dispatch", type=int,
                    default=WINDOWS_PER_DISPATCH,
                    help="admission windows batched per dispatch "
                    "(the double buffer's amortization depth)")
    ap.add_argument("--sequential", action="store_true",
                    help="the naive sequential-dispatch baseline: one "
                    "window per dispatch, block on each before "
                    "preparing the next")
    ap.add_argument("--instances", type=int, default=0,
                    help="instance-space size (0 = 2x values)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-rounds", type=int, default=20_000)
    ap.add_argument("--drop-rate", type=int, default=0)
    ap.add_argument("--dup-rate", type=int, default=0)
    ap.add_argument("--max-delay", type=int, default=0)
    ap.add_argument("--crash-rate", type=int, default=0)
    ap.add_argument("--backend", choices=("tpu", "cpu", "auto"),
                    default="auto")
    ap.add_argument("--json", action="store_true",
                    help="emit the full JSON summary (default prints "
                    "a one-line digest)")
    args = ap.parse_args(argv)
    from tpu_paxos.__main__ import _select_backend

    _select_backend(args.backend)
    cfg = _serve_cfg(args)
    pipelined = not args.sequential
    s_disp = 1 if args.sequential else args.windows_per_dispatch
    if args.sweep:
        rates = [int(x) for x in args.sweep.split(",") if x.strip()]
        summary = sweep_load(
            cfg, args.values, rates, seed=args.seed,
            rounds_per_window=args.rounds_per_window,
            windows_per_dispatch=s_disp,
            pipelined=pipelined,
        )
        summary["ok"] = bool(
            summary["points"] and summary["points"][0]["sustained"]
        )
    else:
        vids = np.arange(args.values, dtype=np.int32)
        if args.trace:
            with open(args.trace) as f:
                rounds = arrv.trace_rounds(json.load(f))
            if len(rounds) != args.values:
                raise SystemExit(
                    f"trace has {len(rounds)} arrivals for "
                    f"--values {args.values}"
                )
        elif args.rate_milli <= 0:
            rounds = arrv.immediate_rounds(args.values)
        else:
            rounds = arrv.poisson_rounds(
                args.values, args.rate_milli, args.seed
            )
        streams, arrs = arrv.split_round_robin(
            vids, rounds, args.proposers
        )
        rep = serve_run(
            cfg, streams, arrs,
            rounds_per_window=args.rounds_per_window,
            windows_per_dispatch=s_disp,
            pipelined=pipelined,
        )
        summary = {
            "metric": "serve",
            "mode": "pipelined" if pipelined else "sequential",
            "rate_milli": args.rate_milli,
            **_point(args.rate_milli, rep),
            "latency_hist": rep.summary["latency_hist"],
            "ok": bool(rep.done and rep.backlog == 0),
        }
    print(json.dumps(summary, sort_keys=True))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
