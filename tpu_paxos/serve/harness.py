"""Host-side open-loop ingestion loop: double-buffered dispatch
windows over the serve driver, latency-at-load sweeps, and the
``python -m tpu_paxos serve`` CLI.

The loop is the serving hot path this package exists for.  Every
dispatch costs a fixed host+tunnel toll — call dispatch, the
admission upload, the scalar sync, and the metrics render (~90 ms
through the TPU device tunnel per PERF.md §Headline; ~2.4 ms of
call/sync/render overhead even on the CPU dev box) — so the
**double-buffered path** (the default) batches ``windows_per_
dispatch`` admission windows into each dispatch: their upload blocks
travel ahead of the rounds that consume them (the next windows'
admission overlapped with the current window's compute), the donated
loop state chains on device, and while one dispatch computes its
``S x R`` rounds the host assembles the next super-block and renders
the previous dispatch's metrics.  The **sequential-dispatch
baseline** (``windows_per_dispatch=1, pipelined=False``) is the
naive loop: one window per dispatch, block on its outputs, prepare
the next — paying the per-dispatch toll every window.

Every dispatch granularity runs a BIT-IDENTICAL protocol trajectory:
windows are fixed round spans, admission happens every
``rounds_per_window`` rounds stamped with true arrival rounds, and
the plan is precomputed on the virtual clock (serve/arrivals.py) —
so the bench's "at equal p99" is exact, not approximate (pinned by
tests/test_serve.py), and the throughput gap is pure
dispatch-overhead hiding.

The WINDOWED plane (on by default) makes each dispatch's epilogue a
metrics STREAM, not a run-so-far total: per-virtual-clock-bucket
latency histograms, drop counts, and stall depth arrive with every
harvest, and the :class:`ServeSLO` burn-rate monitor judges them per
dispatch — a latency breach confined to one burst window is named
(bucket index + round span) even when the run-total histogram ends
the run green (the breach diluted below the budget by later
traffic).  ``sweep_load`` carries the verdicts into the sweep
summary and ``judge_knee`` reads the windowed steady-state median,
so saturation can't hide behind the warm-up either.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import numpy as np

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.serve import arrivals as arrv

#: Default admission-window span, in rounds.  Small windows are the
#: serving-grade operating point: admission latency is bounded by one
#: window span, and the per-dispatch overhead they expose is exactly
#: what the double buffering hides.
ROUNDS_PER_WINDOW = 8

#: Default admission windows per dispatch (the double buffer's
#: amortization depth — the serving twin of the fast path's 16
#: windows/call).  1 = the sequential-dispatch baseline.
WINDOWS_PER_DISPATCH = 8

#: Default windowed-plane bucket width, in admission windows: each of
#: the recorder's NUM_WINDOWS time buckets spans this many admission
#: windows (bucket width = WINDOWS_PER_BUCKET * rounds_per_window
#: rounds), so the SLO monitor's burn windows stay aligned with the
#: granularity values actually enter the system at.
WINDOWS_PER_BUCKET = 4


@dataclasses.dataclass(frozen=True)
class ServeSLO:
    """A declared serving SLO judged on the WINDOWED latency series.

    A decided value is GOOD when its commit latency (ingest-to-commit,
    in rounds) is <= ``latency_rounds`` — quantized DOWN to the
    recorder's histogram edge grid (``telemetry/recorder.LAT_EDGES``),
    so the per-window good/bad split is exact, never interpolated.
    ``budget_milli`` is the error budget: the allowed bad fraction per
    1000 decided values.  The per-window BURN RATE is the window's bad
    fraction over the budget (the SRE burn-rate convention: burn 1.0
    = spending the budget exactly; burn 4.0 = spending it 4x too
    fast), and a window at or above ``burn_breach`` is a named breach
    window — which is exactly what the run-total histogram cannot
    see: a mid-run breach that later traffic dilutes below the budget
    leaves the final histogram green.

    ``regions`` declares PER-REGION latency budgets for a WAN-shaped
    deployment: ``((name, latency_rounds), ...)`` pairs keyed off a
    topology preset's region names (``core/wan.py`` — use
    :func:`region_slo` to build one from a preset).  Each region's
    threshold is judged as its own SLO with breach windows NAMED per
    region in the verdict's ``regions`` block — against the region's
    OWN windowed latency series whenever one is available (a run with
    a declared ``region_map``: ``serve_run`` recomputes the per-region
    series post-clock from its own ingest table, and fleet serve
    lanes reduce them ON DEVICE — ``serve/fleet.py``, breach windows
    named per (lane, region)), so a slow far region can no longer
    red-flag a fast near one.  A region with no series (no region map
    declared) falls back to judging the GLOBAL series against its
    budget — the pre-fleet behavior, marked ``"series": "global"`` in
    the verdict.  The global ``latency_rounds`` stays the
    cluster-wide floor judgment; the report's ``ok`` requires the
    global AND every region to hold."""

    latency_rounds: int
    budget_milli: int = 100
    burn_breach: float = 1.0
    regions: tuple = ()

    def __post_init__(self) -> None:
        object.__setattr__(
            self, "regions",
            tuple((str(n), int(r)) for n, r in self.regions),
        )


def region_slo(
    preset,
    budgets: dict,
    *,
    latency_rounds: int,
    budget_milli: int = 100,
    burn_breach: float = 1.0,
) -> ServeSLO:
    """A :class:`ServeSLO` with per-region latency budgets keyed off
    a WAN preset's region names (``core/wan.WanPreset``): ``budgets``
    maps region name -> latency_rounds; every name must belong to the
    preset (a typo'd region would otherwise silently go unjudged)."""
    unknown = sorted(set(budgets) - set(preset.regions))
    if unknown:
        raise ValueError(
            f"unknown region(s) {', '.join(unknown)} for preset "
            f"{preset.name!r} (has: {', '.join(preset.regions)})"
        )
    return ServeSLO(
        latency_rounds=latency_rounds,
        budget_milli=budget_milli,
        burn_breach=burn_breach,
        regions=tuple(sorted(budgets.items())),
    )


def _judge_series(
    hist, wr: int, latency_rounds: int, budget_milli: int,
    burn_breach: float,
) -> dict:
    """One latency threshold judged over a ``[W, B]`` windowed
    histogram: per-window totals/bad-counts/burn rates, named breach
    windows with round spans, and the run-total verdict."""
    import bisect

    from tpu_paxos.telemetry import recorder as telem

    k = bisect.bisect_right(telem.LAT_EDGES, int(latency_rounds))
    eff = telem.LAT_EDGES[k - 1] if k else 0
    tot = hist.sum(axis=1)
    bad = hist[:, k:].sum(axis=1)
    budget = max(int(budget_milli), 1) / 1000.0
    burn = [
        round(float(b) / float(t) / budget, 3) if t else 0.0
        for b, t in zip(bad, tot)
    ]
    breach = [
        w for w, bn in enumerate(burn)
        if tot[w] and bn >= burn_breach
    ]
    t_tot, b_tot = int(tot.sum()), int(bad.sum())
    frac_milli = round(1000.0 * b_tot / t_tot, 1) if t_tot else 0.0
    return {
        "latency_rounds": int(latency_rounds),
        "latency_rounds_effective": int(eff),
        "budget_milli": int(budget_milli),
        "burn_breach": float(burn_breach),
        "window_rounds": wr,
        "decided": tot.tolist(),
        "bad": bad.tolist(),
        "burn": burn,
        "burn_max": max(burn) if burn else 0.0,
        "breach_windows": breach,
        # the overflow bucket aggregates every round past the grid,
        # so its span is open-ended (null), not one window wide — a
        # closed [start, start+wr] there would misdirect an operator
        # to a 1-bucket slice of an arbitrarily long tail
        "breach_spans": [
            [w * wr, None if w == len(burn) - 1 else (w + 1) * wr]
            for w in breach
        ],
        "ok": not breach,
        # the run-total judgment the windowed one exists to correct:
        # a mid-run breach can hide under a green total
        "total_bad_milli": frac_milli,
        "total_ok": frac_milli <= float(budget_milli),
    }


def slo_windows(
    windows_dict: dict,
    slo: ServeSLO,
    region_series=None,
    region_names: tuple = (),
) -> dict:
    """Judge one run's windowed latency series against ``slo``:
    per-window totals/bad-counts/burn rates, the named breach
    windows (with their round spans), and the run-total verdict the
    windowed one is compared against.  ``windows_dict`` is the
    recorder's ``windows_to_dict`` output (the ``"windows"`` block of
    a summary dict) — this function is pure host arithmetic, so the
    monitor can run per dispatch at no device cost.

    With per-region budgets declared (``slo.regions``), each region's
    latency threshold is judged as its own SLO and named in the
    ``regions`` block (``regions_ok`` aggregates them); the top-level
    ``ok`` then requires the global verdict AND every region's.
    ``region_series`` (``[R, W, B]`` per-region windowed histograms,
    ``telemetry/recorder.region_window_hist``) with ``region_names``
    (index order) routes each named region to its OWN series —
    ``"series": "region"`` in its verdict; regions without one fall
    back to the global series (``"series": "global"``), the
    pre-fleet behavior."""
    hist = np.asarray(windows_dict["lat_hist"], np.int64)  # [W, B]
    wr = int(windows_dict["window_rounds"])
    out = _judge_series(
        hist, wr, slo.latency_rounds, slo.budget_milli, slo.burn_breach
    )
    if slo.regions:
        names = tuple(region_names)
        region_verdicts = {}
        for name, lat in slo.regions:
            if region_series is not None and name in names:
                series = np.asarray(
                    region_series, np.int64
                )[names.index(name)]
                which = "region"
            else:
                series, which = hist, "global"
            v = _judge_series(
                series, wr, lat, slo.budget_milli, slo.burn_breach
            )
            v["series"] = which
            region_verdicts[name] = v
        regions_ok = all(v["ok"] for v in region_verdicts.values())
        out["regions"] = {
            name: {
                k: v[k] for k in (
                    "latency_rounds", "latency_rounds_effective",
                    "burn", "burn_max", "breach_windows",
                    "breach_spans", "ok", "total_bad_milli", "total_ok",
                    "series",
                )
            }
            for name, v in region_verdicts.items()
        }
        out["regions_ok"] = regions_ok
        out["ok"] = bool(out["ok"] and regions_ok)
    return out


@dataclasses.dataclass
class ServeReport:
    """One open-loop run's outcome.  ``chosen_vid``/``chosen_ballot``
    transfer AFTER the clock stops (they exist for decision-log
    parity checks, not for the serving loop)."""

    cfg: SimConfig
    n_values: int
    rounds_per_window: int
    windows_per_dispatch: int
    admit_width: int
    pipelined: bool
    dispatches: int
    windows_count: int  # admission windows run (dispatches * S)
    rounds: int
    done: bool
    decided_values: int  # real stamped values decided (hist mass)
    backlog: int  # admitted values not yet decided at stop
    p50: int
    p99: int
    p999: int
    latency_max: int
    wall_seconds: float
    summary: dict  # final cumulative flight-recorder summary dict
    window_decided: list  # per-dispatch cumulative decided counts
    chosen_vid: np.ndarray
    chosen_ballot: np.ndarray
    #: windowed-plane bucket width in rounds (0 = plane disarmed)
    window_rounds: int = 0
    #: the final windowed series (recorder.windows_to_dict) — the
    #: per-bucket p50/p99/drop/stall stream; None when disarmed
    windows: dict | None = None
    #: SLO verdict (slo_windows) — None unless an SLO was declared
    slo: dict | None = None
    #: per-region windowed latency histograms ``[R, W, B]`` (the
    #: host-recomputed twin of the fleet lanes' on-device series,
    #: recorder.region_window_hist_host) — None unless a region map
    #: was declared; regions named by ``region_names``
    region_windows: np.ndarray | None = None
    region_names: tuple = ()
    #: first dispatch (1-based) whose harvested windowed series
    #: already named a breach window — the burn-rate monitor's
    #: per-dispatch output; None = never breached (or no SLO)
    slo_first_breach_dispatch: int | None = None
    #: the final device ServeLoopState (``keep_state=True`` only —
    #: offline export reads the per-instance phase ledger out of it;
    #: the serving loop itself never holds the reference)
    final_state: object | None = None

    @property
    def values_per_sec(self) -> float:
        return self.decided_values / max(self.wall_seconds, 1e-9)


def serve_run(
    cfg: SimConfig,
    workload,
    arrival_rounds,
    *,
    rounds_per_window: int = ROUNDS_PER_WINDOW,
    windows_per_dispatch: int = WINDOWS_PER_DISPATCH,
    admit_width: int | None = None,
    pipelined: bool = True,
    window_rounds: int | None = None,
    slo: ServeSLO | None = None,
    region_map=None,
    region_names: tuple = (),
    keep_state: bool = False,
) -> ServeReport:
    """Serve one value stream open-loop to completion (or the round
    budget).  ``workload[p]`` is proposer ``p``'s vid sequence in
    queue order; ``arrival_rounds[p]`` its per-value arrival rounds
    (nondecreasing — the queue is FIFO per proposer).  All values
    arriving at round 0 is the zero-load parity shape: the run is
    decision-log-identical to closed-loop ``sim.run(cfg, workload)``.

    ``region_map`` (``[A]`` int32 node->region, e.g. a WAN preset's
    ``wan.node_regions``) with ``region_names`` adds PER-REGION
    windowed latency series to the report — recomputed post-clock on
    the host from the harness's own ingest table (zero change to the
    compiled window; the fleet path reduces the same series on
    device) — and routes each declared region SLO to its own series.

    ``admit_width`` pins the upload block's static width and
    ``windows_per_dispatch`` the amortization depth (one executable
    per ``(S, K)`` call shape across a sweep); admission timing —
    hence the latency distribution — is identical for every ``S``.

    ``window_rounds`` sets the windowed time-series plane's bucket
    width (default ``WINDOWS_PER_BUCKET * rounds_per_window``,
    aligned with admission windows; pass 0 to disarm — the exact
    pre-windowing program, the bench's overhead baseline).  The
    bucket width is part of the compiled program, NOT of the
    trajectory: decisions and the cumulative histogram are identical
    for every setting.  With an ``slo``, the windowed burn-rate
    monitor runs per dispatch on the harvested series and the report
    names every breach window (``ServeReport.slo``)."""
    import jax.numpy as jnp

    from tpu_paxos.analysis import tracecount
    from tpu_paxos.core import values as val
    from tpu_paxos.serve import driver as drv
    from tpu_paxos.telemetry import recorder as telem
    from tpu_paxos.utils import prng

    workload = [np.asarray(w, np.int32).reshape(-1) for w in workload]
    if len(workload) != len(cfg.proposers):
        raise ValueError("one value stream per proposer required")
    plan = arrv.ArrivalPlan(workload, arrival_rounds, rounds_per_window)
    k = int(admit_width or plan.max_block)
    if plan.max_block > k:
        raise ValueError(
            f"admit_width {k} below this plan's max block "
            f"{plan.max_block}"
        )
    s = int(windows_per_dispatch)
    if s < 1:
        raise ValueError("windows_per_dispatch must be >= 1")
    if window_rounds is None:
        window_rounds = WINDOWS_PER_BUCKET * rounds_per_window
    ww = int(window_rounds)
    if slo is not None and not ww:
        raise ValueError(
            "the SLO monitor reads the windowed series; "
            "window_rounds=0 disarms it"
        )
    v_bound = drv.vid_bound_of(workload)
    root = prng.root_key(cfg.seed)
    ss, c = drv.init_serve_state(
        cfg, workload, v_bound, root, window_rounds=ww
    )
    fn = drv.window_for(cfg, c, v_bound, rounds_per_window, window_rounds=ww)
    p = len(cfg.proposers)
    empty = (
        jnp.full((s, p, k), val.NONE, jnp.int32),
        jnp.zeros((s, p, k), jnp.int32),
    )
    n_disp_admit = (plan.n_windows + s - 1) // s
    # Watchdog: the budget the closed-loop driver grants, in dispatches.
    disp_cap = max(
        cfg.round_budget // (rounds_per_window * s) + 1, n_disp_admit
    )

    def super_block(d):
        """Stack dispatch ``d``'s S admission windows; windows past
        the plan are empty rows (the plan pads them itself)."""
        a = np.stack([plan.block(d * s + i, k)[0] for i in range(s)])
        r = np.stack([plan.block(d * s + i, k)[1] for i in range(s)])
        return jnp.asarray(a), jnp.asarray(r)

    first_breach: list = []  # [dispatch] set once by the monitor

    def harvest(out):
        # the one host sync per dispatch: the stop scalars + the
        # metrics-plane render of the cumulative summary (and, with
        # an SLO declared, the windowed burn-rate monitor — pure
        # host arithmetic on the [W, B] series that just transferred)
        done, t, summ = out[0], out[1], out[2]
        wsum = out[3] if ww else None
        if slo is not None and not first_breach:
            judged = slo_windows(
                {"window_rounds": ww,
                 "lat_hist": np.asarray(wsum.lat_hist)},
                slo,
            )
            if judged["breach_windows"]:
                first_breach.append(harvested + 1)
        return bool(done), int(t), summ, wsum

    window_decided: list[int] = []
    pending = None
    last_done, last_t, last_summ, last_wsum = False, 0, None, None
    d = harvested = 0
    t0 = time.perf_counter()  # paxlint: allow[DET001] wall metric only; never reaches artifacts
    with tracecount.engine_scope("serve"):
        while True:
            blk = super_block(d) if d < n_disp_admit else empty
            out = fn(ss, root, *blk)
            ss = out[0]
            d += 1
            if pipelined:
                # double buffer: harvest the PREVIOUS dispatch while
                # this one computes; its scalars are already (or
                # nearly) resolved, so the poll costs no device idle
                if pending is not None:
                    last_done, last_t, last_summ, last_wsum = harvest(
                        pending
                    )
                    window_decided.append(int(last_summ.decided))
                    harvested += 1
                pending = out[1:]
            else:
                # sequential baseline: block on this dispatch before
                # preparing the next — the bubble the double-buffered
                # mode exists to hide
                last_done, last_t, last_summ, last_wsum = harvest(out[1:])
                window_decided.append(int(last_summ.decided))
                harvested += 1
            # stop only on a quiescence signal from a dispatch that
            # saw EVERY admission — a mid-stream lull (quiescent
            # before later arrivals) must not end the run
            if harvested >= n_disp_admit and last_done:
                break
            if d >= disp_cap:
                break
        if pending is not None:
            last_done, last_t, last_summ, last_wsum = harvest(pending)
            window_decided.append(int(last_summ.decided))
            harvested += 1
    wall = time.perf_counter() - t0  # paxlint: allow[DET001] wall metric only; never reaches artifacts

    # Post-clock rendering: the final cumulative summary + decision
    # arrays transfer after the serving loop stopped timing.
    import jax

    host_summ = jax.tree.map(np.asarray, last_summ)
    host_wsum = (
        jax.tree.map(np.asarray, last_wsum) if last_wsum is not None
        else None
    )
    sd = telem.summary_to_dict(
        host_summ, host_wsum, ww, region_names=tuple(region_names)
    )
    hist = np.asarray(host_summ.lat_hist)
    lat_max = int(host_summ.lat_max)
    decided_values = int(hist.sum())
    windows_dict = sd.get("windows")
    region_hists = None
    if region_map is not None and ww:
        # post-clock host twin of the fleet lanes' on-device series:
        # the ingest table is the harness's OWN data (every value's
        # true arrival round), the decision arrays transfer after the
        # clock stopped anyway — no compiled-program change
        rmap = np.asarray(region_map, np.int32).reshape(cfg.n_nodes)
        ingest_host = np.full((v_bound,), int(val.NONE), np.int32)
        vid_region = np.zeros((v_bound,), np.int32)
        for node, s_p, a_p in zip(cfg.proposers, plan.streams, plan.arrs):
            ingest_host[s_p] = a_p
            vid_region[s_p] = rmap[node]
        region_hists = telem.region_window_hist_host(
            ingest_host,
            np.asarray(ss.sim.met.chosen_vid),
            np.asarray(ss.sim.met.chosen_round),
            vid_region, ww,
        )
    slo_dict = (
        slo_windows(windows_dict, slo, region_series=region_hists,
                    region_names=region_names)
        if slo is not None and windows_dict is not None else None
    )
    if slo_dict is not None:
        # breach attribution (telemetry/diagnose.py): label every
        # named breach window with its ranked causes — pure host
        # arithmetic on the already-harvested series
        from tpu_paxos.telemetry import diagnose as diag

        diag.attach_diagnosis(
            slo_dict, windows_dict,
            region_map=region_map, region_names=tuple(region_names),
            region_pairs=sd.get("region_pairs"),
            region_series=region_hists,
        )
    return ServeReport(
        cfg=cfg,
        n_values=plan.n_values,
        rounds_per_window=rounds_per_window,
        windows_per_dispatch=s,
        admit_width=k,
        pipelined=pipelined,
        dispatches=d,
        windows_count=d * s,
        rounds=last_t,
        done=last_done,
        decided_values=decided_values,
        backlog=plan.n_values - decided_values,
        p50=sd["latency_p50"],
        p99=sd["latency_p99"],
        p999=telem.latency_quantile(hist, 0.999, lat_max),
        latency_max=lat_max,
        wall_seconds=wall,
        summary=sd,
        window_decided=window_decided,
        chosen_vid=np.asarray(ss.sim.met.chosen_vid),
        chosen_ballot=np.asarray(ss.sim.met.chosen_ballot),
        window_rounds=ww,
        windows=windows_dict,
        slo=slo_dict,
        slo_first_breach_dispatch=(
            first_breach[0] if first_breach else None
        ),
        region_windows=region_hists,
        region_names=tuple(region_names),
        final_state=ss if keep_state else None,
    )


def _steady_p50(rep: ServeReport) -> int | None:
    """Steady-state median from the windowed series: the MEDIAN of
    the per-bucket p50s over the buckets that decided anything
    (later-middle on even counts, leaning toward the loaded end).
    The run-total p50 averages the unloaded warm-up in, so a run
    that saturates mid-sweep can average back under the doubling
    line; a single bucket would be hostage to the straggler drain
    tail (small-n, retry-biased slow) or a one-off duel cluster —
    the typical-window median sees sustained queueing and nothing
    else.  None when the plane is disarmed."""
    if rep.windows is None:
        return None
    # filter on the quantile itself, not the decided count: decided
    # includes no-op fills (which carry no latency), so a fill-only
    # bucket reports -1 — a sentinel, not a latency of -1
    p50s = [int(p) for p in rep.windows["latency_p50"] if int(p) >= 0]
    if not p50s:
        return None
    return sorted(p50s)[len(p50s) // 2]


def _point(rate_milli: int, rep: ServeReport) -> dict:
    steady = _steady_p50(rep)
    return {
        "rate_milli": int(rate_milli),
        "p50": rep.p50,
        "p99": rep.p99,
        "p999": rep.p999,
        "latency_max": rep.latency_max,
        "decided": rep.decided_values,
        "backlog": rep.backlog,
        "done": rep.done,
        "rounds": rep.rounds,
        "dispatches": rep.dispatches,
        "windows": rep.windows_count,
        "wall_seconds": round(rep.wall_seconds, 4),
        "values_per_sec": round(rep.values_per_sec, 1),
        "sustained": bool(rep.done and rep.backlog == 0),
        **({
            "p50_steady": steady,
            "p50_windows": rep.windows["latency_p50"],
            "p99_windows": rep.windows["latency_p99"],
            "window_rounds": rep.window_rounds,
        } if steady is not None else {}),
        **({"slo": rep.slo} if rep.slo is not None else {}),
    }


def judge_knee(points: list, factor: float = 2.0) -> dict:
    """Bracket the saturation knee from a latency-at-load sweep
    (points sorted by rate).  A point SATURATES when the run failed
    to drain inside the round budget, or its MEDIAN commit latency
    blew past ``factor`` times the lowest-rate median — the classic
    latency-doubling knee.  The judgment deliberately reads p50, not
    p99: the tail carries the fault-retry ladder (a dropped accept's
    ~100-round restart shows up at p99 even at near-zero load), while
    queueing delay past the engine's service rate moves EVERY value —
    the median is the saturation signal.

    Points carrying the windowed series are judged on ``p50_steady``
    (the last active bucket's median) instead of the run-total p50:
    the total smears the unloaded warm-up over the whole run, so a
    run that saturates mid-sweep can average back under the doubling
    line — the steady-state median is where queueing actually shows.
    Returns the bracketing rates (None where the sweep never
    crossed)."""
    if not points:
        return {"last_sustained_milli": None, "first_saturated_milli": None}

    def med(pt):
        return pt.get("p50_steady") or pt["p50"]

    windowed = any("p50_steady" in pt for pt in points)
    base = max(med(points[0]), 1)
    last_ok, first_bad = None, None
    for pt in points:
        # >=: p50 is latency-bucket-quantized, so the doubling point
        # lands exactly ON factor * base
        bad = (not pt["sustained"]) or med(pt) >= factor * base
        if bad and first_bad is None:
            first_bad = pt["rate_milli"]
        if not bad and first_bad is None:
            last_ok = pt["rate_milli"]
    return {
        "last_sustained_milli": last_ok,
        "first_saturated_milli": first_bad,
        "p50_factor": factor,
        "p50_base": base,
        "p50_metric": "p50_steady" if windowed else "p50",
    }


def sweep_load(
    cfg: SimConfig,
    n_values: int,
    rates_milli,
    *,
    seed: int = 0,
    rounds_per_window: int = ROUNDS_PER_WINDOW,
    windows_per_dispatch: int = WINDOWS_PER_DISPATCH,
    pipelined: bool = True,
    knee_factor: float = 2.0,
    admit_width: int | None = None,
    window_rounds: int | None = None,
    slo: ServeSLO | None = None,
) -> dict:
    """Latency at load: one open-loop Poisson run per offered rate
    (values per 1000 rounds), all sharing ONE compiled window (the
    admit width is the max over every rate's plan — raise it with
    ``admit_width`` to share an executable with runs outside the
    sweep), plus the knee judgment over the resulting points (the
    windowed steady-state median when the plane is armed — the
    default).  With an ``slo``, every point carries its burn-rate
    verdict and the summary names each rate's breach windows."""
    vids = np.arange(int(n_values), dtype=np.int32)
    n_prop = len(cfg.proposers)
    plans = {}
    for rm in rates_milli:
        rounds = arrv.poisson_rounds(n_values, int(rm), seed)
        plans[int(rm)] = arrv.split_round_robin(vids, rounds, n_prop)
    width = int(admit_width or 1)
    for rm, (streams, arrs) in plans.items():
        width = max(
            width,
            arrv.ArrivalPlan(streams, arrs, rounds_per_window).max_block,
        )
    points = []
    for rm in sorted(plans):
        streams, arrs = plans[rm]
        rep = serve_run(
            cfg, streams, arrs,
            rounds_per_window=rounds_per_window,
            windows_per_dispatch=windows_per_dispatch,
            admit_width=width,
            pipelined=pipelined,
            window_rounds=window_rounds,
            slo=slo,
        )
        points.append(_point(rm, rep))
    out = {
        "metric": "serve_latency_at_load",
        "n_values": int(n_values),
        "rounds_per_window": int(rounds_per_window),
        "windows_per_dispatch": int(windows_per_dispatch),
        "admit_width": int(width),
        "points": points,
        "knee": judge_knee(points, knee_factor),
    }
    if slo is not None:
        out["slo"] = {
            "latency_rounds": int(slo.latency_rounds),
            "budget_milli": int(slo.budget_milli),
            "burn_breach": float(slo.burn_breach),
            # every rate's named breach windows — the mid-run
            # story the per-point run-total columns cannot tell
            "breach_windows": {
                str(pt["rate_milli"]): pt["slo"]["breach_windows"]
                for pt in points if "slo" in pt
            },
            # breach attribution per rate: the diagnosis plane's
            # named causes (telemetry/diagnose.py) — why each rate's
            # windows breached, not just that they did
            "breach_causes": {
                str(pt["rate_milli"]):
                    pt["slo"].get("diagnosis", {}).get("causes", [])
                for pt in points if "slo" in pt
            },
            "ok": all(
                pt["slo"]["ok"] for pt in points if "slo" in pt
            ),
        }
    return out


def _serve_cfg(args) -> SimConfig:
    n_inst = args.instances or max(64, 2 * args.values)
    return SimConfig(
        n_nodes=args.nodes,
        n_instances=n_inst,
        proposers=tuple(range(args.proposers)),
        seed=args.seed,
        max_rounds=args.max_rounds,
        faults=FaultConfig(
            drop_rate=args.drop_rate,
            dup_rate=args.dup_rate,
            max_delay=args.max_delay,
            crash_rate=args.crash_rate,
        ),
        **({"assign_window": args.assign_window}
           if getattr(args, "assign_window", 0) else {}),
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_paxos serve",
        description="open-loop serving harness: Poisson / trace-replay "
        "arrivals admitted mid-flight through double-buffered dispatch "
        "windows; commit latency (p50/p99/p999) at a sustained "
        "offered load, measured on device",
    )
    ap.add_argument("--nodes", type=int, default=5)
    ap.add_argument("--proposers", type=int, default=2)
    ap.add_argument("--values", type=int, default=256,
                    help="values in the arriving stream")
    ap.add_argument("--rate-milli", type=int, default=2000,
                    help="offered load: values per 1000 rounds "
                    "(0 = offered-load-∞, everything arrives at "
                    "round 0)")
    ap.add_argument("--sweep", type=str, default="",
                    help="comma-separated rate_milli list: run the "
                    "latency-at-load sweep + knee judgment instead "
                    "of a single rate")
    ap.add_argument("--trace", type=str, default="",
                    help="JSON file with an explicit arrival-round "
                    "list (trace replay; overrides --rate-milli)")
    ap.add_argument("--arrivals", type=str, default="poisson",
                    choices=sorted(arrv.ARRIVAL_BUILDERS),
                    help="arrival process at --rate-milli: poisson, "
                    "heavy-tailed pareto, bursty, or diurnal "
                    "(serve/arrivals.py; deterministic per seed)")
    ap.add_argument("--rounds-per-window", type=int,
                    default=ROUNDS_PER_WINDOW)
    ap.add_argument("--windows-per-dispatch", type=int,
                    default=WINDOWS_PER_DISPATCH,
                    help="admission windows batched per dispatch "
                    "(the double buffer's amortization depth)")
    ap.add_argument("--sequential", action="store_true",
                    help="the naive sequential-dispatch baseline: one "
                    "window per dispatch, block on each before "
                    "preparing the next")
    ap.add_argument("--window-rounds", type=int, default=-1,
                    help="windowed time-series bucket width in rounds "
                    "(-1 = 4 admission windows; 0 disarms the plane)")
    ap.add_argument("--slo-latency", type=int, default=0,
                    help="declare a latency SLO: commit latency (in "
                    "rounds, quantized to the histogram edges) every "
                    "value should meet; arms the windowed burn-rate "
                    "monitor (0 = no SLO)")
    ap.add_argument("--slo-budget-milli", type=int, default=100,
                    help="SLO error budget: allowed slow-value "
                    "fraction per 1000 decided (with --slo-latency)")
    ap.add_argument("--control", action="store_true",
                    help="arm the adaptive admission controller "
                    "(serve/control.py): between dispatches, read the "
                    "previous dispatch's burn + ranked causes and "
                    "shed/defer declared priority tiers (requires "
                    "--slo-latency)")
    ap.add_argument("--control-ab", action="store_true",
                    help="the spike A/B judgment: one load spike "
                    "served controller-off and controller-on at the "
                    "same offered trajectory, compared on the "
                    "breach-window list (requires --slo-latency)")
    ap.add_argument("--spike-factor", type=int, default=4,
                    help="--control-ab spike: arrival-rate multiplier "
                    "over the mid-run spike span")
    ap.add_argument("--spike-start-frac", type=float, default=0.375,
                    help="--control-ab spike: where the spike starts, "
                    "as a fraction of the value stream")
    ap.add_argument("--spike-len-frac", type=float, default=0.25,
                    help="--control-ab spike: spike span as a "
                    "fraction of the value stream")
    ap.add_argument("--assign-window", type=int, default=0,
                    help="cap concurrent assignment (SimConfig."
                    "assign_window; 0 = engine default).  The spike "
                    "A/B needs a bounded admission capacity for a "
                    "spike to build a real queue")
    ap.add_argument("--priority-tiers", type=int, default=3,
                    help="declared per-value priority tiers (tier 0 "
                    "= always admit)")
    ap.add_argument("--defer-tier", type=int, default=0,
                    help="lowest tier the controller DEFERS under "
                    "degradation (0 = policy default: shed-only, no "
                    "defer band)")
    ap.add_argument("--shed-tier", type=int, default=0,
                    help="lowest tier the controller SHEDS under "
                    "degradation (0 = policy default: top tier)")
    ap.add_argument("--save-artifact", type=str, default="",
                    help="write the controlled run's repro artifact "
                    "(policy + decision trail; replay with `python "
                    "-m tpu_paxos repro`)")
    ap.add_argument("--instances", type=int, default=0,
                    help="instance-space size (0 = 2x values)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--arrival-seed", type=int, default=-1,
                    help="arrival-process seed, decoupled from the "
                    "engine --seed (-1 = same as --seed).  The "
                    "committed spike A/B (BENCH_serve_control.json) "
                    "draws arrivals at seed 0 on an engine at seed 3")
    ap.add_argument("--max-rounds", type=int, default=20_000)
    ap.add_argument("--drop-rate", type=int, default=0)
    ap.add_argument("--dup-rate", type=int, default=0)
    ap.add_argument("--max-delay", type=int, default=0)
    ap.add_argument("--crash-rate", type=int, default=0)
    ap.add_argument("--backend", choices=("tpu", "cpu", "auto"),
                    default="auto")
    ap.add_argument("--json", action="store_true",
                    help="emit the full JSON summary (default prints "
                    "a one-line digest)")
    args = ap.parse_args(argv)
    from tpu_paxos.__main__ import _select_backend

    _select_backend(args.backend)
    cfg = _serve_cfg(args)
    pipelined = not args.sequential
    s_disp = 1 if args.sequential else args.windows_per_dispatch
    w_rounds = None if args.window_rounds < 0 else args.window_rounds
    slo = (
        ServeSLO(latency_rounds=args.slo_latency,
                 budget_milli=args.slo_budget_milli)
        if args.slo_latency else None
    )
    policy = None
    if args.control or args.control_ab:
        from tpu_paxos.serve import control as ctlm

        if slo is None:
            raise SystemExit(
                "--control/--control-ab read SLO verdicts; declare "
                "--slo-latency"
            )
        n_tiers = args.priority_tiers
        shed_tier = args.shed_tier or n_tiers - 1 or 1
        policy = ctlm.ControlPolicy(
            n_tiers=n_tiers,
            defer_tier=args.defer_tier or shed_tier,
            shed_tier=shed_tier,
        )
    a_seed = args.seed if args.arrival_seed < 0 else args.arrival_seed
    if args.control_ab:
        summary = ctlm.spike_ab(
            cfg, args.values, args.rate_milli or 2000,
            slo=slo, seed=a_seed, policy=policy,
            rounds_per_window=args.rounds_per_window,
            windows_per_dispatch=s_disp,
            spike_factor=args.spike_factor,
            spike_start_frac=args.spike_start_frac,
            spike_len_frac=args.spike_len_frac,
            window_rounds=w_rounds,
            artifact_path=args.save_artifact or None,
        )
    elif args.sweep:
        rates = [int(x) for x in args.sweep.split(",") if x.strip()]
        summary = sweep_load(
            cfg, args.values, rates, seed=a_seed,
            rounds_per_window=args.rounds_per_window,
            windows_per_dispatch=s_disp,
            pipelined=pipelined,
            window_rounds=w_rounds,
            slo=slo,
        )
        summary["ok"] = bool(
            summary["points"] and summary["points"][0]["sustained"]
            and summary.get("slo", {}).get("ok", True)
        )
    else:
        vids = np.arange(args.values, dtype=np.int32)
        if args.trace:
            with open(args.trace) as f:
                rounds = arrv.trace_rounds(json.load(f))
            if len(rounds) != args.values:
                raise SystemExit(
                    f"trace has {len(rounds)} arrivals for "
                    f"--values {args.values}"
                )
        elif args.rate_milli <= 0:
            rounds = arrv.immediate_rounds(args.values)
        else:
            rounds = arrv.ARRIVAL_BUILDERS[args.arrivals](
                args.values, args.rate_milli, a_seed
            )
        streams, arrs = arrv.split_round_robin(
            vids, rounds, args.proposers
        )
        if args.control:
            rep = ctlm.controlled_serve_run(
                cfg, streams, arrs,
                control=policy,
                rounds_per_window=args.rounds_per_window,
                windows_per_dispatch=s_disp,
                window_rounds=w_rounds,
                slo=slo,
            )
            if args.save_artifact:
                ctlm.save_artifact(args.save_artifact, rep)
        else:
            rep = serve_run(
                cfg, streams, arrs,
                rounds_per_window=args.rounds_per_window,
                windows_per_dispatch=s_disp,
                pipelined=pipelined,
                window_rounds=w_rounds,
                slo=slo,
            )
        point = (
            ctlm._ab_point(rep) if args.control
            else _point(args.rate_milli, rep)
        )
        summary = {
            "metric": "serve",
            "mode": (
                "controlled" if args.control
                else "pipelined" if pipelined else "sequential"
            ),
            "rate_milli": args.rate_milli,
            **point,
            "latency_hist": rep.summary["latency_hist"],
            "ok": bool(
                rep.done and rep.backlog == 0
                and (rep.slo is None or rep.slo["ok"])
            ),
        }
        if rep.slo_first_breach_dispatch is not None:
            summary["slo_first_breach_dispatch"] = (
                rep.slo_first_breach_dispatch
            )
    print(json.dumps(summary, sort_keys=True))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
