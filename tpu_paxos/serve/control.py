"""Adaptive serving: a deterministic, cause-aware admission
controller that degrades gracefully under load spikes.

The serve stack can OBSERVE everything — windowed SLO burn rates
(harness.slo_windows), per-lane breach vectors (fleet._slo_breach),
ranked breach causes (telemetry/diagnose.py) — but nothing ACTS on
any of it.  This module closes the loop: between dispatches the
controller reads the previous dispatch's already-harvested windowed
series (no new syncs beyond the harvest the monitor already pays),
judges it, and adjusts the NEXT dispatch:

* **Granularity.**  A degraded controller steps DOWN the dispatch
  ladder (fewer admission windows per dispatch — tighter control
  latency: verdicts arrive every ``S*R`` rounds); a calm one steps
  back up for throughput.  ``S`` is a call shape of the one compiled
  window, so the ladder costs dispatches, not compiles.
* **Admission.**  Queued arrivals carry declared PRIORITY TIERS
  (``arrivals.ArrivalPlan`` priority column).  Under degradation the
  top tiers are SHED — uploaded in the admission block with
  ``keep=False`` so ``core/sim.admit_block`` masks them on device and
  the shed count stays an on-device fact — and the middle band is
  DEFERRED: held in the host queue with their TRUE arrival rounds, so
  when they finally admit, the ingest stamps charge their real
  queue-wait to the latency ledger.  Nothing is silently dropped:
  every shed is a logged decision.
* **Cause awareness.**  Decisions key on the diagnosis plane's STABLE
  cause codes (``diagnose.CAUSE_IDS``), through a policy table:
  shed on ``saturation`` (load the engine cannot absorb), NEVER on
  ``gray-region`` (a slow node is not excess load — shedding
  customers for it is wrong twice), hold steady through
  ``duel-churn``/``partition`` (self-healing; shedding prolongs
  nothing).  The ``never`` action is a VETO: a window where gray
  fired is never shed-worthy even when saturation fired beside it.

Everything stays byte-replayable: the controller is pure host
arithmetic over the deterministic harvested series, every decision is
appended to the decision log (:func:`control_log`), and the serve
repro artifact records policy + decision trail so ``python -m
tpu_paxos repro`` re-runs the controlled loop sha256-identically
(:func:`reproduce`).  On fleet lanes the controller state rides the
donated loop-state chain (:class:`ControlLoopState` adds one tiny
``[2]`` counter leaf) and per-tenant decisions consume the
per-dispatch ``[lanes]`` breach vector — only flagged lanes pay a
series transfer, and the whole controlled sweep shares the envelope
cache's one executable per shape (zero warm compiles,
BENCH_serve_control.json pins it).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import os
import time
from typing import NamedTuple

import numpy as np

from tpu_paxos.config import SimConfig
from tpu_paxos.serve import arrivals as arrv
from tpu_paxos.serve import harness as sh
from tpu_paxos.telemetry import diagnose as diag

#: Policy-table actions: what a named breach cause asks of admission.
#: ``shed`` marks the window shed-worthy; ``hold`` does nothing;
#: ``never`` VETOES shedding for the whole window even when a
#: shed-worthy cause fired beside it.
ACTIONS = ("shed", "hold", "never")

#: Control-decision kinds (the decision-log / artifact vocabulary).
DECISION_ACTIONS = ("degrade", "hold", "restore")

#: Decision-log vid stride for serve streams (harness workloads use
#: plain ``arange`` vids; the stride only shapes no-op rendering and
#: must merely be CONSISTENT between record and replay).
LOG_STRIDE = 30


def default_table() -> tuple:
    """The cause-aware policy table of the tentpole contract, keyed
    on stable codes: shed on saturation, never on gray-region, hold
    through partition and duel-churn.  Codes absent from a table act
    as ``hold`` (including ``unknown`` = 0)."""
    return (
        (diag.CAUSE_IDS["saturation"], "shed"),
        (diag.CAUSE_IDS["gray-region"], "never"),
        (diag.CAUSE_IDS["partition"], "hold"),
        (diag.CAUSE_IDS["duel-churn"], "hold"),
    )


@dataclasses.dataclass(frozen=True)
class ControlPolicy:
    """A declared controller policy — plain data, artifact-roundtrip
    exact (:func:`policy_to_dict` / :func:`policy_from_dict`).

    Tiers partition the priority column: values with tier >=
    ``shed_tier`` are SHED under degradation, tiers in
    ``[defer_tier, shed_tier)`` are DEFERRED (held with true arrival
    stamps), lower tiers always admit.  ``defer_tier == shed_tier``
    declares no defer band (shed-only — the bench's shape: deferral
    moves load later, which under a spike can mint NEW breach
    windows after it).  ``ladder`` is an ascending tuple of
    windows-per-dispatch settings; degrade steps toward ``ladder[0]``
    (tight control), restore back up (throughput).  Empty = fixed
    granularity.  Restore needs ``patience`` consecutive calm
    dispatches with recent burn <= ``burn_low_milli`` (burn x1000,
    the SRE burn-rate convention)."""

    n_tiers: int = 3
    defer_tier: int = 1
    shed_tier: int = 2
    burn_low_milli: int = 500
    patience: int = 2
    ladder: tuple = ()
    table: tuple = ()

    def __post_init__(self) -> None:
        if self.n_tiers < 1:
            raise ValueError(f"n_tiers must be >= 1 (got {self.n_tiers})")
        if not (1 <= self.defer_tier <= self.shed_tier <= self.n_tiers):
            raise ValueError(
                "tier bands must satisfy 1 <= defer_tier <= shed_tier "
                f"<= n_tiers (got defer={self.defer_tier}, "
                f"shed={self.shed_tier}, n={self.n_tiers})"
            )
        if self.patience < 1:
            raise ValueError(f"patience must be >= 1 (got {self.patience})")
        ladder = tuple(int(s) for s in self.ladder)
        if any(s < 1 for s in ladder):
            raise ValueError(f"ladder entries must be >= 1 (got {ladder})")
        if list(ladder) != sorted(ladder):
            raise ValueError(f"ladder must ascend (got {ladder})")
        object.__setattr__(self, "ladder", ladder)
        table = tuple(
            (int(c), str(a)) for c, a in (self.table or default_table())
        )
        for c, a in table:
            if a not in ACTIONS:
                raise ValueError(
                    f"unknown policy action {a!r} for cause {c} "
                    f"(one of {ACTIONS})"
                )
            if c not in diag.CAUSE_NAMES:
                raise ValueError(f"unknown cause code {c} in policy table")
        if len({c for c, _ in table}) != len(table):
            raise ValueError("duplicate cause code in policy table")
        object.__setattr__(self, "table", table)

    @property
    def top_level(self) -> int:
        return max(len(self.ladder) - 1, 0)


def policy_to_dict(p: ControlPolicy) -> dict:
    """Artifact-exact rendering (closed schema; see
    analysis/artifact_schema.py's ``serve.control`` block)."""
    return {
        "n_tiers": int(p.n_tiers),
        "defer_tier": int(p.defer_tier),
        "shed_tier": int(p.shed_tier),
        "burn_low_milli": int(p.burn_low_milli),
        "patience": int(p.patience),
        "ladder": [int(s) for s in p.ladder],
        "table": [
            {"cause_id": int(c), "action": a} for c, a in p.table
        ],
    }


def policy_from_dict(d: dict) -> ControlPolicy:
    return ControlPolicy(
        n_tiers=d["n_tiers"],
        defer_tier=d["defer_tier"],
        shed_tier=d["shed_tier"],
        burn_low_milli=d["burn_low_milli"],
        patience=d["patience"],
        ladder=tuple(d["ladder"]),
        table=tuple((e["cause_id"], e["action"]) for e in d["table"]),
    )


#: The seeded CONTROLLER wedge value (checker-recall knob, the
#: policy-plane sibling of ``core/sim.seeded_wedge``'s ``takeover``):
#: ``TPU_PAXOS_SEEDED_WEDGE=shed-on-gray`` makes
#: :func:`wedged_policy` rewriting ACTIVE in the mc controller scope's
#: policy materialization — the exact bug the never-shed-on-gray veto
#: exists to prevent.  Unlike ``takeover`` this selects no traced
#: program (pure host policy data), but the same hygiene applies: any
#: armed wedge value makes certificates unpinnable (``mc --pin``
#: refuses).
WEDGE_SHED_ON_GRAY = "shed-on-gray"


def seeded_policy_wedge() -> bool:
    """True iff the seeded controller wedge is armed (test-only; see
    core/sim.seeded_wedge — never set in production runs)."""
    from tpu_paxos.core import sim as simm

    return simm.seeded_wedge() == WEDGE_SHED_ON_GRAY


def wedged_policy(p: ControlPolicy) -> ControlPolicy:
    """``p`` with its gray-region row forced to ``shed`` — the seeded
    policy bug the mc controller scope must provably find (the
    gray-veto invariant then fails on every gray-naming window).
    Deterministic: the table is re-sorted by cause code."""
    table = dict(p.table)
    table[diag.CAUSE_IDS["gray-region"]] = "shed"
    return dataclasses.replace(p, table=tuple(sorted(table.items())))


@dataclasses.dataclass
class ControllerState:
    """The controller's host-side state between dispatches: the
    current ladder level, whether admission is degraded (shed/defer
    floors armed), and the calm-dispatch counter toward restore."""

    level: int
    degraded: bool = False
    calm: int = 0


def decide(
    policy: ControlPolicy,
    st: ControllerState,
    *,
    dispatch: int,
    burn_milli: int,
    new_windows,
) -> dict | None:
    """One control step: judge the dispatch's NEWLY named breach
    windows (``(window, cause_code_tuple)`` pairs — every fired
    candidate cause, not just the top one) against the policy table,
    mutate ``st``, and return the decision record (None = no
    decision: a quiet dispatch still counting toward restore).

    A window is shed-worthy iff some code maps to ``shed`` AND no
    code maps to ``never`` — the veto is per WINDOW, so gray beside
    saturation still blocks the shed (the never-shed-on-gray
    contract, pinned by tests/test_control.py).  Shed-worthy windows
    degrade (arm the floors, step the ladder down); other breaches
    hold (reset calm, change nothing); ``patience`` calm dispatches
    at burn <= ``burn_low_milli`` restore."""
    table = dict(policy.table)
    new_windows = [(int(w), tuple(int(c) for c in cs))
                   for w, cs in new_windows]
    shed_w, hold_w = [], []
    for w, codes in new_windows:
        acts = {table.get(c, "hold") for c in codes}
        if "shed" in acts and "never" not in acts:
            shed_w.append(w)
        else:
            hold_w.append(w)

    def rec(action, windows):
        codes = sorted({
            c for w, cs in new_windows if w in windows for c in cs
        })
        return {
            "dispatch": int(dispatch),
            "action": action,
            "level": int(st.level),
            "degraded": bool(st.degraded),
            "cause_ids": codes,
            "windows": sorted(int(w) for w in windows),
        }

    if shed_w:
        st.degraded = True
        st.level = max(0, st.level - 1)
        st.calm = 0
        return rec("degrade", shed_w)
    if hold_w:
        st.calm = 0
        return rec("hold", hold_w)
    if int(burn_milli) <= policy.burn_low_milli:
        st.calm += 1
        if st.calm >= policy.patience and (
            st.degraded or st.level < policy.top_level
        ):
            st.degraded = False
            st.level = min(policy.top_level, st.level + 1)
            st.calm = 0
            return rec("restore", [])
    else:
        st.calm = 0
    return None


class ControlledPlan:
    """The controller's admission queue over an
    :class:`arrivals.ArrivalPlan` with a priority column: windows are
    taken IN ORDER, each yielding the upload triple ``(admit, arr,
    keep)`` under the active floors.  Sheds ride the block with
    ``keep=False`` (masked on device, charged to the shed ledger
    here); deferred values stay queued with their TRUE arrival
    rounds, so a later admission stamps their real queue-wait; width
    spill stays queued too (and also charges its wait).  With no
    floors the output is exactly :meth:`ArrivalPlan.block` — the
    inert-controller trajectory-parity pin."""

    def __init__(self, workload, arrival_rounds, priorities,
                 rounds_per_window: int):
        self.plan = arrv.ArrivalPlan(
            workload, arrival_rounds, rounds_per_window,
            prios=priorities,
        )
        self.n_values = self.plan.n_values
        self.max_block = self.plan.max_block
        self.n_windows = self.plan.n_windows
        self._queues = [
            collections.deque() for _ in range(len(self.plan.streams))
        ]
        self._next_window = 0
        self.shed_records: list[dict] = []
        self.shed_count = 0

    @property
    def exhausted(self) -> bool:
        """Every planned value has left the queue (admitted or
        shed).  Deferred values hold this False until they drain."""
        return (
            self._next_window >= self.n_windows
            and all(not q for q in self._queues)
        )

    @property
    def queued(self) -> int:
        return sum(len(q) for q in self._queues)

    def take(self, j: int, admit_width: int, *,
             shed_floor: int | None = None,
             defer_floor: int | None = None):
        """Window ``j``'s upload under the active floors:
        ``(admit [P, K], arr [P, K], keep [P, K] bool)``."""
        if j != self._next_window:
            raise ValueError(
                f"windows must be taken in order (expected "
                f"{self._next_window}, got {j})"
            )
        self._next_window += 1
        k = int(admit_width)
        p = len(self.plan.streams)
        admit = np.full((p, k), arrv.NONE, np.int32)
        arr = np.zeros((p, k), np.int32)
        keep = np.zeros((p, k), bool)
        for pi in range(p):
            q = self._queues[pi]
            if j < self.n_windows:
                lo = int(self.plan._cuts[pi][j])
                hi = int(self.plan._cuts[pi][j + 1])
                prios = self.plan.prios
                for idx in range(lo, hi):
                    q.append((
                        int(self.plan.streams[pi][idx]),
                        int(self.plan.arrs[pi][idx]),
                        int(prios[pi][idx]) if prios is not None else 0,
                    ))
            filled = 0
            deferred = []
            while q and filled < k:
                vid, ar, tier = q.popleft()
                if shed_floor is not None and tier >= shed_floor:
                    # shed: uploaded masked — the device counts it,
                    # the host ledger names it
                    admit[pi, filled] = vid
                    arr[pi, filled] = ar
                    filled += 1
                    self.shed_records.append({
                        "window": int(j), "proposer": int(pi),
                        "vid": int(vid), "tier": int(tier),
                        "arrival": int(ar),
                    })
                    self.shed_count += 1
                elif defer_floor is not None and tier >= defer_floor:
                    deferred.append((vid, ar, tier))
                else:
                    admit[pi, filled] = vid
                    arr[pi, filled] = ar
                    keep[pi, filled] = True
                    filled += 1
            # deferred values rejoin AHEAD of later arrivals — FIFO
            # within each tier is preserved, lower tiers may overtake
            # (that is what priority means)
            q.extendleft(reversed(deferred))
        return admit, arr, keep


# ---------------- the compiled controlled window --------------------


class ControlLoopState(NamedTuple):
    """The controlled run's donated loop state: the serve driver's
    whole-run state plus one ``[2]`` int32 counter leaf ``(shed,
    admitted)`` — the controller's on-device ledger, chained across
    dispatches like every other buffer (the donation checker accounts
    for it; audit entry ``serve.control_window``)."""

    serve: object  # serve/driver.ServeLoopState
    ctl: object  # [2] int32 — running (shed, admitted) totals


def build_control_window(
    cfg: SimConfig,
    queue_cap: int,
    vid_bound: int,
    rounds_per_window: int,
    window_rounds: int,
):
    """Compile-time closure for one CONTROLLED serving envelope: the
    jitted ``control_window(cs, root, admits, arrs, keeps) -> (cs,
    done, t, summary, window_summary)`` with the loop state donated.
    Identical to ``serve/driver.build_serve_window`` except the
    per-sub-window ``keeps [S, P, K]`` mask: kept values stamp ingest
    and admit; shed values only bump the on-device shed counter
    (``admit_block``'s keep mask compacts survivors on device).  An
    all-True mask runs the exact uncontrolled trajectory — the
    inert-policy parity pin (tests/test_control.py)."""
    import jax
    import jax.numpy as jnp

    from tpu_paxos.core import sim as simm
    from tpu_paxos.core import values as val
    from tpu_paxos.serve import driver as drv
    from tpu_paxos.telemetry import recorder as telem

    if cfg.faults.schedule is not None:
        raise ValueError(
            "serve engines take no fault schedule (correlated-fault "
            "serving rides the fleet envelope, not this driver)"
        )
    ww = int(window_rounds)
    if ww <= 0:
        raise ValueError(
            "the controller reads the windowed series; window_rounds "
            "must be positive"
        )
    round_fn = simm.build_engine(
        cfg, queue_cap, vid_cap=0, telemetry=True, window_rounds=ww
    )
    r = int(rounds_per_window)
    v_bound = int(vid_bound)

    def control_window(cs, root, admits, arrs, keeps):
        s = admits.shape[0]

        def sub(i, carry):
            (st, tl, ingest), ctl = carry
            admit, arr, kp = admits[i], arrs[i], keeps[i]
            # only KEPT values stamp ingest: a shed value never
            # enters the engine, so it must not enter the ledger
            kept = jnp.where(kp, admit, val.NONE)
            flat_v = kept.reshape(-1)
            idx = jnp.where(
                (flat_v >= 0) & (flat_v < v_bound), flat_v, v_bound
            )
            ingest = ingest.at[idx].set(arr.reshape(-1), mode="drop")
            st = simm.admit_block(st, admit, keep=kp)
            live = admit != val.NONE
            ctl = ctl + jnp.stack([
                jnp.sum(live & jnp.logical_not(kp)),
                jnp.sum(live & kp),
            ]).astype(jnp.int32)

            def body(_, c):
                return round_fn(root, c[0], tele=c[1])

            st, tl = jax.lax.fori_loop(0, r, body, (st, tl))
            return (drv.ServeLoopState(st, tl, ingest), ctl)

        (st, tl, ingest), ctl = jax.lax.fori_loop(
            0, s, sub,
            (drv.ServeLoopState(*cs.serve), cs.ctl),
        )
        adm = telem.serve_admit_rounds(ingest, st.met.chosen_vid)
        base, wins = tl
        summ = telem.summarize(base._replace(admit_round=adm), st, 0)
        wsum = telem.summarize_windows(
            wins, adm, st.met.chosen_vid, st.met.chosen_round, ww,
            batch_round=base.admit_round,
            learned_round=base.learned_round,
            committed_round=base.committed_round,
        )
        return (
            ControlLoopState(drv.ServeLoopState(st, tl, ingest), ctl),
            st.done, st.t, summ, wsum,
        )

    return jax.jit(control_window, donate_argnums=(0,))


_CACHE: dict = {}


def clear_cache() -> None:
    """Drop every cached controlled window (tests; frees
    executables)."""
    _CACHE.clear()


def control_window_for(
    cfg: SimConfig, queue_cap: int, vid_bound: int,
    rounds_per_window: int, window_rounds: int,
):
    """Envelope-keyed cache over :func:`build_control_window`
    (``serve/driver.window_for``'s discipline, same
    ``engine_static_key`` source of compile-time truth): a controlled
    sweep's A/B twins and every ladder level share ONE cached builder
    — ``S`` and ``K`` are call shapes."""
    if cfg.faults.schedule is not None:
        # checked here like driver.window_for: the key ignores the
        # schedule, so a schedule-bearing cfg would otherwise HIT a
        # warm cache and silently drop its correlated faults
        raise ValueError(
            "serve engines take no fault schedule (correlated-fault "
            "serving rides the fleet envelope, not this driver)"
        )
    from tpu_paxos.serve import driver as drv

    key = (
        "control",
        drv.engine_static_key(cfg),
        int(queue_cap),
        int(vid_bound),
        int(rounds_per_window),
        int(window_rounds),
    )
    fn = _CACHE.get(key)
    if fn is None:
        fn = build_control_window(
            cfg, queue_cap, vid_bound, rounds_per_window, window_rounds
        )
        _CACHE[key] = fn
    return fn


def init_control_state(
    cfg: SimConfig, workload, vid_bound: int, root, window_rounds: int,
):
    """Fresh controlled loop state: the serve driver's plus a zeroed
    ``[2]`` control counter.  Returns ``(state, queue_cap)``."""
    import jax.numpy as jnp

    from tpu_paxos.serve import driver as drv

    ss, c = drv.init_serve_state(
        cfg, workload, vid_bound, root, window_rounds=window_rounds
    )
    return ControlLoopState(ss, jnp.zeros((2,), jnp.int32)), c


# ---------------- the controlled host loop --------------------------


def control_log(decisions) -> str:
    """The control decisions in decision-log line grammar — appended
    after the protocol decision log, so a controlled run's replay pin
    covers WHAT was decided and WHY admission changed:

        [ctl <dispatch>] <action> level=<l> causes=<ids> windows=<ws>

    Pure function of the decision list; byte-identical across
    replays."""
    lines = []
    for dc in decisions:
        lines.append(
            "[ctl %d] %s level=%d causes=%s windows=%s\n" % (
                dc["dispatch"], dc["action"], dc["level"],
                ",".join(str(c) for c in dc["cause_ids"]) or "-",
                ",".join(str(w) for w in dc["windows"]) or "-",
            )
        )
    return "".join(lines)


def decision_log_text(chosen_vid, chosen_ballot, decisions) -> str:
    """A controlled run's FULL replay pin: the protocol decision log
    (replay/decision_log grammar) plus the control trail.  With no
    decisions this is byte-identical to the plain serve log — the
    controller-off sha equals PR-15 behavior by construction."""
    from tpu_paxos.replay.decision_log import decision_log as _dlog

    cv = np.asarray(chosen_vid)
    return _dlog(
        cv, np.asarray(chosen_ballot), stride=LOG_STRIDE,
        n_instances=len(cv),
    ) + control_log(decisions)


def _log_sha(chosen_vid, chosen_ballot, decisions) -> str:
    return hashlib.sha256(
        decision_log_text(chosen_vid, chosen_ballot, decisions).encode()
    ).hexdigest()


@dataclasses.dataclass
class ControlReport:
    """One controlled open-loop run's outcome.  Carries its own plan
    inputs (workload/arrivals/priorities) so :func:`save_artifact` is
    self-contained, and the combined decision-log sha — the replay
    pin covering protocol decisions AND control decisions."""

    cfg: SimConfig
    policy: ControlPolicy | None
    slo_cfg: object  # sh.ServeSLO | None
    workload: list
    arrivals: list
    priorities: list | None
    n_values: int
    rounds_per_window: int
    windows_per_dispatch: int  # initial S (ladder top when laddered)
    admit_width: int
    window_rounds: int
    ladder: tuple
    dispatches: int
    rounds: int
    done: bool
    decided_values: int
    shed_count: int
    p50: int
    p99: int
    latency_max: int
    wall_seconds: float
    summary: dict
    windows: dict | None
    slo: dict | None
    decisions: list
    sheds: list
    window_decided: list
    chosen_vid: np.ndarray
    chosen_ballot: np.ndarray
    decision_log_sha256: str
    slo_first_breach_dispatch: int | None = None
    final_state: object | None = None

    @property
    def backlog(self) -> int:
        """Planned values neither decided nor deliberately shed."""
        return self.n_values - self.decided_values - self.shed_count

    @property
    def values_per_sec(self) -> float:
        return self.decided_values / max(self.wall_seconds, 1e-9)


def controlled_serve_run(
    cfg: SimConfig,
    workload,
    arrival_rounds,
    *,
    priorities=None,
    control: ControlPolicy | None = None,
    rounds_per_window: int = sh.ROUNDS_PER_WINDOW,
    windows_per_dispatch: int = sh.WINDOWS_PER_DISPATCH,
    admit_width: int | None = None,
    window_rounds: int | None = None,
    slo=None,
    keep_state: bool = False,
) -> ControlReport:
    """Serve one value stream through the CONTROLLED loop.

    ``control=None`` runs the inert controller: all-True keep masks,
    fixed granularity, no decisions — the same trajectory (and the
    same decision-log sha) as ``harness.serve_run`` on the same plan,
    pinned by tests/test_control.py.  A policy requires an ``slo``
    (the controller reads its verdicts) and consumes ``priorities``
    (per-proposer tier arrays; default tier 0 everywhere — shedding
    then has nothing to bite, granularity control still works).

    The loop harvests SEQUENTIALLY (one sync per dispatch): the
    controller's whole point is reading dispatch ``d``'s verdict
    before shaping dispatch ``d+1``, so the double buffer's one-
    dispatch decision lag is traded away for control latency.  Every
    decision is deterministic host arithmetic over the harvested
    series; the decision trail is part of the replay pin."""
    import jax
    import jax.numpy as jnp

    from tpu_paxos.analysis import tracecount
    from tpu_paxos.telemetry import recorder as telem
    from tpu_paxos.utils import prng

    workload = [np.asarray(w, np.int32).reshape(-1) for w in workload]
    if len(workload) != len(cfg.proposers):
        raise ValueError("one value stream per proposer required")
    plan = ControlledPlan(
        workload, arrival_rounds, priorities, rounds_per_window
    )
    if control is not None:
        if slo is None:
            raise ValueError(
                "a control policy reads SLO verdicts; declare an slo"
            )
        if plan.plan.prios is not None:
            hi = max(
                (int(p.max()) for p in plan.plan.prios if p.size),
                default=0,
            )
            if hi >= control.n_tiers:
                raise ValueError(
                    f"priority tier {hi} out of range for policy "
                    f"n_tiers={control.n_tiers}"
                )
    k = int(admit_width or plan.max_block)
    if plan.max_block > k:
        raise ValueError(
            f"admit_width {k} below this plan's max block "
            f"{plan.max_block}"
        )
    s = int(windows_per_dispatch)
    if s < 1:
        raise ValueError("windows_per_dispatch must be >= 1")
    if window_rounds is None:
        window_rounds = sh.WINDOWS_PER_BUCKET * rounds_per_window
    ww = int(window_rounds)
    if ww <= 0:
        raise ValueError(
            "the controller reads the windowed series; window_rounds "
            "must be positive"
        )
    ladder = (
        control.ladder if control is not None and control.ladder else (s,)
    )
    from tpu_paxos.serve import driver as drv

    v_bound = drv.vid_bound_of(workload)
    root = prng.root_key(cfg.seed)
    cs, c = init_control_state(
        cfg, workload, v_bound, root, window_rounds=ww
    )
    fn = control_window_for(cfg, c, v_bound, rounds_per_window, ww)
    p = len(cfg.proposers)
    st_c = ControllerState(level=len(ladder) - 1)
    seen: set = set()
    decisions: list = []
    window_decided: list = []
    first_breach: int | None = None
    disp_cap = max(
        cfg.round_budget // (rounds_per_window * min(ladder)) + 1,
        (plan.n_windows + min(ladder) - 1) // min(ladder),
    )
    d = 0
    w_next = 0
    last_done, last_t = False, 0
    last_summ = last_wsum = None
    t0 = time.perf_counter()  # paxlint: allow[DET001] wall metric only; never reaches artifacts
    with tracecount.engine_scope("serve_control"):
        while True:
            s_d = ladder[st_c.level]
            shed_floor = defer_floor = None
            if control is not None and st_c.degraded:
                shed_floor = control.shed_tier
                defer_floor = control.defer_tier
            adm = np.full((s_d, p, k), arrv.NONE, np.int32)
            arr = np.zeros((s_d, p, k), np.int32)
            kp = np.zeros((s_d, p, k), bool)
            for i in range(s_d):
                adm[i], arr[i], kp[i] = plan.take(
                    w_next + i, k,
                    shed_floor=shed_floor, defer_floor=defer_floor,
                )
            w_next += s_d
            out = fn(
                cs, root, jnp.asarray(adm), jnp.asarray(arr),
                jnp.asarray(kp),
            )
            cs = out[0]
            d += 1
            # sequential harvest: the controller must read THIS
            # dispatch's verdict before shaping the next
            last_done, last_t = bool(out[1]), int(out[2])
            last_summ, last_wsum = out[3], out[4]
            window_decided.append(int(np.asarray(last_summ.decided)))  # paxlint: allow[JAX103] sequential harvest by design: the controller must read THIS dispatch before shaping the next
            if slo is not None:
                lat_hist = np.asarray(last_wsum.lat_hist)  # paxlint: allow[JAX103] same per-dispatch harvest: the burn series IS the control input
                judged = sh.slo_windows(
                    {"window_rounds": ww, "lat_hist": lat_hist}, slo
                )
                if judged["breach_windows"] and first_breach is None:
                    first_breach = d
                if control is not None:
                    # only COMPLETE buckets may drive a decision: a
                    # half-filled bucket's burn is a small-sample
                    # transient that the final verdict may retract
                    full = last_t // ww
                    new = [
                        w for w in judged["breach_windows"]
                        if w < full and w not in seen
                    ]
                    new_with_codes = []
                    if new:
                        # only a dispatch that NAMED new breach
                        # windows pays the full series transfer —
                        # diagnosis reads the whole windows dict
                        lat_max = int(np.asarray(last_summ.lat_max))  # paxlint: allow[JAX103] only a dispatch naming NEW breach windows pays this transfer
                        wd = telem.windows_to_dict(
                            jax.tree.map(np.asarray, last_wsum),
                            ww, lat_max,
                        )
                        dg = diag.diagnose_breaches(wd, new)
                        for v in dg["windows"]:
                            codes = tuple(sorted({
                                diag.cause_code(cand["cause"])
                                for cand in v["candidates"]
                            })) or (0,)
                            new_with_codes.append(
                                (int(v["window"]), codes)
                            )
                        seen.update(new)
                    lo_b = max(0, last_t - s_d * rounds_per_window) // ww
                    hi_b = min(len(judged["burn"]), full) - 1
                    recent = max(
                        (judged["burn"][b]
                         for b in range(lo_b, hi_b + 1)),
                        default=0.0,
                    )
                    dec = decide(
                        control, st_c,
                        dispatch=d,
                        burn_milli=int(round(recent * 1000)),
                        new_windows=new_with_codes,
                    )
                    if dec is not None:
                        decisions.append(dec)
            if plan.exhausted and last_done:
                break
            if d >= disp_cap:
                break
    wall = time.perf_counter() - t0  # paxlint: allow[DET001] wall metric only; never reaches artifacts

    host_summ = jax.tree.map(np.asarray, last_summ)
    host_wsum = jax.tree.map(np.asarray, last_wsum)
    sd = telem.summary_to_dict(host_summ, host_wsum, ww)
    hist = np.asarray(host_summ.lat_hist)
    lat_max = int(host_summ.lat_max)
    decided_values = int(hist.sum())
    windows_dict = sd.get("windows")
    slo_dict = (
        sh.slo_windows(windows_dict, slo)
        if slo is not None and windows_dict is not None else None
    )
    if slo_dict is not None:
        diag.attach_diagnosis(slo_dict, windows_dict)
    ctl_host = np.asarray(cs.ctl)
    if int(ctl_host[0]) != plan.shed_count:
        # the device ledger and the host ledger count the same
        # events; a skew means the mask upload went wrong
        raise RuntimeError(
            f"shed ledger skew: device {int(ctl_host[0])} vs host "
            f"{plan.shed_count}"
        )
    chosen_vid = np.asarray(cs.serve.sim.met.chosen_vid)
    chosen_ballot = np.asarray(cs.serve.sim.met.chosen_ballot)
    return ControlReport(
        cfg=cfg,
        policy=control,
        slo_cfg=slo,
        workload=workload,
        arrivals=[np.asarray(a, np.int32) for a in arrival_rounds],
        priorities=(
            None if priorities is None
            else [np.asarray(q, np.int32) for q in priorities]
        ),
        n_values=plan.n_values,
        rounds_per_window=int(rounds_per_window),
        windows_per_dispatch=int(ladder[-1]),
        admit_width=k,
        window_rounds=ww,
        ladder=tuple(ladder),
        dispatches=d,
        rounds=last_t,
        done=last_done,
        decided_values=decided_values,
        shed_count=plan.shed_count,
        p50=sd["latency_p50"],
        p99=sd["latency_p99"],
        latency_max=lat_max,
        wall_seconds=wall,
        summary=sd,
        windows=windows_dict,
        slo=slo_dict,
        decisions=decisions,
        sheds=list(plan.shed_records),
        window_decided=window_decided,
        chosen_vid=chosen_vid,
        chosen_ballot=chosen_ballot,
        decision_log_sha256=_log_sha(
            chosen_vid, chosen_ballot, decisions
        ),
        slo_first_breach_dispatch=first_breach,
        final_state=cs if keep_state else None,
    )


# ---------------- the repro artifact --------------------------------


def save_artifact(path: str, report: ControlReport) -> dict:
    """Write a controlled run's self-contained repro artifact
    (engine ``"serve"``): config, plan inputs, SLO, policy, the
    decision trail, and the combined decision-log sha.  Schema-closed
    additive — classic sim artifacts never carry the ``serve`` block
    and stay byte-identical (analysis/artifact_schema.py)."""
    from tpu_paxos.analysis import artifact_schema as schema
    from tpu_paxos.harness import shrink

    art = {
        "format": schema.ARTIFACT_FORMAT,
        "engine": "serve",
        "cfg": shrink._cfg_to_dict(report.cfg),
        "workload": [np.asarray(w).tolist() for w in report.workload],
        "gates": None,
        "chains": [],
        "extra_checks": {},
        "violation": "serve-control",
        "decision_log_sha256": report.decision_log_sha256,
        "rounds": int(report.rounds),
        "serve": {
            "arrivals": [
                np.asarray(a).tolist() for a in report.arrivals
            ],
            "priorities": (
                None if report.priorities is None
                else [np.asarray(q).tolist() for q in report.priorities]
            ),
            "rounds_per_window": int(report.rounds_per_window),
            "windows_per_dispatch": int(report.windows_per_dispatch),
            "admit_width": int(report.admit_width),
            "window_rounds": int(report.window_rounds),
            "slo": (
                None if report.slo_cfg is None else {
                    "latency_rounds": int(report.slo_cfg.latency_rounds),
                    "budget_milli": int(report.slo_cfg.budget_milli),
                    "burn_breach_milli": int(
                        round(report.slo_cfg.burn_breach * 1000)
                    ),
                }
            ),
            "control": (
                None if report.policy is None
                else policy_to_dict(report.policy)
            ),
            "decisions": report.decisions,
        },
    }
    schema.validate_artifact(art)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(art, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return art


def load_artifact(path: str) -> dict:
    """Load + schema-validate a serve artifact (clean
    ArtifactSchemaError surface, shrink.load_artifact's discipline)."""
    from tpu_paxos.analysis import artifact_schema as schema

    try:
        with open(path) as f:
            art = json.load(f)
    except OSError as e:
        raise schema.ArtifactSchemaError(
            "", f"unreadable artifact: {e}"
        ) from None
    except json.JSONDecodeError as e:
        raise schema.ArtifactSchemaError(
            "", f"invalid JSON (truncated write?): {e}"
        ) from None
    try:
        schema.validate_artifact(art)
    except schema.ArtifactSchemaError as e:
        raise schema.ArtifactSchemaError(
            e.field, f"{e.problem} (artifact {path!r})"
        ) from None
    if art.get("engine") != "serve" or "serve" not in art:
        raise schema.ArtifactSchemaError(
            "engine", "not a serve artifact (use the sim repro path)"
        )
    return art


def reproduce(path: str) -> dict:
    """Re-execute a controlled serve artifact; ``match`` is True iff
    the combined decision log byte-compares equal (sha256) AND the
    control decision trail is identical."""
    from tpu_paxos.harness import shrink

    art = load_artifact(path)
    cfg = shrink._cfg_from_dict(art["cfg"])
    sv = art["serve"]
    slo_d = sv["slo"]
    slo = (
        None if slo_d is None else sh.ServeSLO(
            latency_rounds=slo_d["latency_rounds"],
            budget_milli=slo_d["budget_milli"],
            burn_breach=slo_d["burn_breach_milli"] / 1000.0,
        )
    )
    policy = (
        None if sv["control"] is None
        else policy_from_dict(sv["control"])
    )
    rep = controlled_serve_run(
        cfg,
        [np.asarray(w, np.int32) for w in art["workload"]],
        [np.asarray(a, np.int32) for a in sv["arrivals"]],
        priorities=(
            None if sv["priorities"] is None
            else [np.asarray(q, np.int32) for q in sv["priorities"]]
        ),
        control=policy,
        rounds_per_window=sv["rounds_per_window"],
        windows_per_dispatch=sv["windows_per_dispatch"],
        admit_width=sv["admit_width"],
        window_rounds=sv["window_rounds"],
        slo=slo,
    )
    return {
        "artifact": path,
        "engine": "serve",
        "violation": art["violation"],
        "recorded_violation": art["violation"],
        "decision_log": decision_log_text(
            rep.chosen_vid, rep.chosen_ballot, rep.decisions
        ),
        "decision_log_sha256": rep.decision_log_sha256,
        "recorded_sha256": art["decision_log_sha256"],
        "decisions_match": rep.decisions == sv["decisions"],
        "rounds": rep.rounds,
        "done": rep.done,
        "match": (
            rep.decision_log_sha256 == art["decision_log_sha256"]
            and rep.decisions == sv["decisions"]
        ),
    }


# ---------------- the spike A/B judgment ----------------------------


def _ab_point(rep: ControlReport) -> dict:
    v = rep.slo or {}
    return {
        "p50": rep.p50,
        "p99": rep.p99,
        "decided": rep.decided_values,
        "shed": rep.shed_count,
        "backlog": rep.backlog,
        "done": rep.done,
        "rounds": rep.rounds,
        "dispatches": rep.dispatches,
        "breach_windows": v.get("breach_windows", []),
        "breach_spans": v.get("breach_spans", []),
        "burn_max": v.get("burn_max", 0.0),
        "total_bad_milli": v.get("total_bad_milli", 0.0),
        "causes": v.get("diagnosis", {}).get("causes", []),
        "decisions": len(rep.decisions),
        "decision_log_sha256": rep.decision_log_sha256,
    }


def spike_ab(
    cfg: SimConfig,
    n_values: int,
    rate_milli: int,
    *,
    slo,
    seed: int = 0,
    policy: ControlPolicy | None = None,
    rounds_per_window: int = sh.ROUNDS_PER_WINDOW,
    windows_per_dispatch: int = sh.WINDOWS_PER_DISPATCH,
    spike_factor: int = 8,
    spike_start_frac: float = 0.375,
    spike_len_frac: float = 0.25,
    admit_width: int | None = None,
    window_rounds: int | None = None,
    artifact_path: str | None = None,
) -> dict:
    """THE judgment (BENCH_serve_control.json): one load spike
    (``arrivals.spike_rounds``) served twice at the same offered
    trajectory — controller off (inert) and on — and compared on the
    breach-window list.  The controller wins when it names strictly
    FEWER breach windows, sheds only outside gray-region-attributed
    windows, and its artifact replays sha256-identically.

    ``policy`` defaults to the SHED-ONLY shape (``defer_tier ==
    shed_tier``): under a spike, deferral moves tier-1 load AFTER the
    spike where its accumulated queue-wait can mint brand-new breach
    windows — the defer band is exercised by tests, not by the
    headline A/B."""
    if policy is None:
        policy = ControlPolicy(n_tiers=3, defer_tier=2, shed_tier=2)
    rounds = arrv.spike_rounds(
        n_values, rate_milli, seed, factor=spike_factor,
        start_frac=spike_start_frac, len_frac=spike_len_frac,
    )
    vids = np.arange(int(n_values), dtype=np.int32)
    prios = arrv.tier_priorities(vids, policy.n_tiers)
    n_prop = len(cfg.proposers)
    streams, arrs = arrv.split_round_robin(vids, rounds, n_prop)
    prios_split = [prios[p::n_prop] for p in range(n_prop)]
    width = int(admit_width or arrv.ArrivalPlan(
        streams, arrs, rounds_per_window
    ).max_block)
    common = dict(
        priorities=prios_split,
        rounds_per_window=rounds_per_window,
        windows_per_dispatch=windows_per_dispatch,
        admit_width=width,
        window_rounds=window_rounds,
        slo=slo,
    )
    off = controlled_serve_run(
        cfg, streams, arrs, control=None, **common
    )
    on = controlled_serve_run(
        cfg, streams, arrs, control=policy, **common
    )
    off_bw = (off.slo or {}).get("breach_windows", [])
    on_bw = (on.slo or {}).get("breach_windows", [])
    # zero sheds inside gray-region-attributed windows: a bucket is
    # gray-touched when ANY diagnosis candidate named gray-region
    gray_buckets = {
        int(v["window"])
        for v in (on.slo or {}).get("diagnosis", {}).get("windows", [])
        if any(c["cause"] == "gray-region" for c in v["candidates"])
    }
    ww = on.window_rounds
    shed_buckets = {
        (rec["window"] * on.rounds_per_window) // ww for rec in on.sheds
    }
    gray_violations = sorted(gray_buckets & shed_buckets)
    out = {
        "metric": "serve_control_spike_ab",
        "n_values": int(n_values),
        "rate_milli": int(rate_milli),
        "spike_factor": int(spike_factor),
        "spike_start_frac": float(spike_start_frac),
        "spike_len_frac": float(spike_len_frac),
        "seed": int(seed),
        "rounds_per_window": int(rounds_per_window),
        "windows_per_dispatch": int(windows_per_dispatch),
        "admit_width": width,
        "window_rounds": int(ww),
        "policy": policy_to_dict(policy),
        "slo": {
            "latency_rounds": int(slo.latency_rounds),
            "budget_milli": int(slo.budget_milli),
            "burn_breach_milli": int(round(slo.burn_breach * 1000)),
        },
        "off": _ab_point(off),
        "on": _ab_point(on),
        "fewer_breach_windows": len(on_bw) < len(off_bw),
        "breach_rounds_off": len(off_bw) * ww,
        "breach_rounds_on": len(on_bw) * ww,
        "gray_shed_violations": gray_violations,
        "sheds": on.shed_count,
        "decisions": len(on.decisions),
    }
    if artifact_path is not None:
        save_artifact(artifact_path, on)
        out["replay"] = reproduce(artifact_path)
    out["ok"] = bool(
        off_bw
        and len(on_bw) < len(off_bw)
        and not gray_violations
        and on.shed_count > 0
        and out.get("replay", {}).get("match", True)
    )
    return out


# ---------------- fleet lanes ---------------------------------------


class ControlFleetRunner:
    """Compile-once CONTROLLED fleet front end: the serve fleet
    runner's vmapped dispatch window plus the per-lane keep mask and
    the ``[lanes, 2]`` control counters riding the donated stacked
    loop state.  Cached per serve envelope by
    ``fleet/envelope.serve_control_for`` — a controlled sweep shares
    one executable per (L, S, K) call shape with zero warm compiles
    (the audit's entry is ``serve.control_fleet``)."""

    def __init__(
        self,
        cfg: SimConfig,
        queue_cap: int,
        vid_bound: int,
        rounds_per_window: int,
        window_rounds: int,
        mesh=None,
    ):
        import jax
        import jax.numpy as jnp

        from tpu_paxos.core import sim as simm
        from tpu_paxos.core import values as val
        from tpu_paxos.serve import driver as drv
        from tpu_paxos.serve import fleet as sflt
        from tpu_paxos.telemetry import recorder as telem

        if cfg.faults.schedule is not None:
            raise ValueError(
                "serve engines take no fault schedule (correlated-"
                "fault serving rides the fleet envelope, not this "
                "driver)"
            )
        ww = int(window_rounds)
        if ww <= 0:
            raise ValueError(
                "fleet control rides the windowed plane; "
                "window_rounds must be positive"
            )
        self.cfg = cfg
        self.queue_cap = int(queue_cap)
        self.vid_bound = int(vid_bound)
        self.rounds_per_window = int(rounds_per_window)
        self.window_rounds = ww
        self.mesh = mesh
        round_fn = simm.build_engine(
            cfg, self.queue_cap, vid_cap=0, telemetry=True,
            window_rounds=ww,
        )
        r = self.rounds_per_window
        v_bound = self.vid_bound

        def lane(cs, root, admits, arrs, keeps, vid_region, rmap):
            s = admits.shape[0]

            def sub(i, carry):
                (st, tl, ingest), ctl = carry
                admit, arr, kp = admits[i], arrs[i], keeps[i]
                kept = jnp.where(kp, admit, val.NONE)
                flat_v = kept.reshape(-1)
                idx = jnp.where(
                    (flat_v >= 0) & (flat_v < v_bound), flat_v, v_bound
                )
                ingest = ingest.at[idx].set(
                    arr.reshape(-1), mode="drop"
                )
                st = simm.admit_block(st, admit, keep=kp)
                live = admit != val.NONE
                ctl = ctl + jnp.stack([
                    jnp.sum(live & jnp.logical_not(kp)),
                    jnp.sum(live & kp),
                ]).astype(jnp.int32)

                def body(_, c):
                    return round_fn(root, c[0], tele=c[1])

                st, tl = jax.lax.fori_loop(0, r, body, (st, tl))
                return (drv.ServeLoopState(st, tl, ingest), ctl)

            (st, tl, ingest), ctl = jax.lax.fori_loop(
                0, s, sub,
                (drv.ServeLoopState(*cs.serve), cs.ctl),
            )
            adm = telem.serve_admit_rounds(ingest, st.met.chosen_vid)
            base, wins = tl
            summ = telem.summarize(
                base._replace(admit_round=adm), st, 0, rmap
            )
            wsum = telem.summarize_windows(
                wins, adm, st.met.chosen_vid, st.met.chosen_round, ww,
                batch_round=base.admit_round,
                learned_round=base.learned_round,
                committed_round=base.committed_round,
            )
            rw = telem.region_window_hist(
                adm, st.met.chosen_vid, st.met.chosen_round,
                vid_region, ww,
            )
            return (
                ControlLoopState(drv.ServeLoopState(st, tl, ingest), ctl),
                st.done, st.t, summ, wsum, rw,
            )

        fl = jax.vmap(lane)
        if mesh is not None and mesh.size > 1:
            from tpu_paxos.parallel import mesh as pmesh

            # lane-axis spec from the mesh module (SH001: axis names
            # route through parallel/, never hand-built here)
            spec = pmesh.instance_spec(mesh)
            fl = pmesh.shard_map(
                fl, mesh, in_specs=(spec,) * 7, out_specs=(spec,) * 6
            )

        def dispatch(css, roots, admits, arrs, keeps, vid_regions,
                     rmaps, slo_k, region_k, budget_milli, burn_milli):
            css, done, t, summ, wsum, rw = fl(
                css, roots, admits, arrs, keeps, vid_regions, rmaps
            )
            breach = sflt._slo_breach(
                wsum.lat_hist, rw, slo_k, region_k, budget_milli,
                burn_milli,
            )
            decided = jnp.sum(summ.lat_hist, axis=-1)
            return css, done, t, decided, breach, summ, wsum, rw

        self._fn = jax.jit(dispatch, donate_argnums=(0,))

        def init_lane(pend, gate, tail, root):
            st = simm.init_state(cfg, pend, gate, tail, root)
            tele = (
                telem.init_telemetry(
                    cfg.n_instances, len(cfg.proposers), cfg.n_nodes
                ),
                telem.init_windows(cfg.n_nodes),
            )
            ingest = jnp.full((v_bound,), val.NONE, jnp.int32)
            return ControlLoopState(
                drv.ServeLoopState(st, tele, ingest),
                jnp.zeros((2,), jnp.int32),
            )

        self._init = jax.jit(jax.vmap(init_lane))


def controlled_fleet_run(
    cfg: SimConfig,
    lanes,
    *,
    control: ControlPolicy,
    priorities=None,
    rounds_per_window: int = sh.ROUNDS_PER_WINDOW,
    windows_per_dispatch: int = sh.WINDOWS_PER_DISPATCH,
    admit_width: int | None = None,
    window_rounds: int | None = None,
    slo=None,
    region_map=None,
    region_names: tuple = (),
    mesh=None,
):
    """Fleet serving under PER-TENANT control: every lane carries its
    own controller state and admission queue, and decisions consume
    the per-dispatch ``[lanes]`` on-device breach vector — an
    unflagged lane pays nothing (its quiet dispatch counts toward
    restore at burn 0); a flagged lane pays one series transfer for
    diagnosis, exactly the fleet monitor's existing discipline.

    ``priorities`` is per-lane per-proposer tier arrays; default
    derives ``arrivals.tier_priorities`` from each stream.  Ladders
    are per-run dispatch shapes, so per-LANE granularity cannot fork
    inside one vmapped dispatch — fleet policies must declare an
    empty ladder.  Returns a :class:`ControlFleetReport` (a
    ``ServeFleetReport`` plus the decision/shed ledgers)."""
    import jax
    import jax.numpy as jnp

    from tpu_paxos.analysis import tracecount
    from tpu_paxos.core import sim as simm
    from tpu_paxos.core import values as val
    from tpu_paxos.fleet import envelope as envm
    from tpu_paxos.serve import driver as drv
    from tpu_paxos.serve import fleet as sflt
    from tpu_paxos.telemetry import recorder as telem
    from tpu_paxos.utils import prng

    if control.ladder:
        raise ValueError(
            "fleet lanes share one dispatch call shape; a fleet "
            "policy must declare an empty ladder"
        )
    if slo is None:
        raise ValueError(
            "a control policy reads SLO verdicts; declare an slo"
        )
    lanes = [
        sflt._check_lane(
            cfg, ln if isinstance(ln, sflt.ServeLane)
            else sflt.ServeLane(*ln), i,
        )
        for i, ln in enumerate(lanes)
    ]
    if not lanes:
        raise ValueError("at least one lane required")
    n_lanes = len(lanes)
    if mesh is not None and n_lanes % max(mesh.size, 1):
        raise ValueError(
            f"{n_lanes} lanes do not tile over {mesh.size} devices"
        )
    if priorities is None:
        priorities = [
            [arrv.tier_priorities(s, control.n_tiers)
             for s in ln.workload]
            for ln in lanes
        ]
    plans = [
        ControlledPlan(
            ln.workload, ln.arrivals, prio, rounds_per_window
        )
        for ln, prio in zip(lanes, priorities)
    ]
    k = int(admit_width or max(p.max_block for p in plans))
    if max(p.max_block for p in plans) > k:
        raise ValueError(
            f"admit_width {k} below this fleet's max block "
            f"{max(p.max_block for p in plans)}"
        )
    s = int(windows_per_dispatch)
    if s < 1:
        raise ValueError("windows_per_dispatch must be >= 1")
    if window_rounds is None:
        window_rounds = sh.WINDOWS_PER_BUCKET * rounds_per_window
    ww = int(window_rounds)
    c = max(
        simm.prepare_queues(cfg, ln.workload)[3] for ln in lanes
    )
    v_bound = max(drv.vid_bound_of(ln.workload) for ln in lanes)
    runner = envm.serve_control_for(
        cfg, c, v_bound, rounds_per_window,
        window_rounds=ww, mesh=mesh,
    )
    p = len(cfg.proposers)
    width = c + cfg.assign_window
    pend = np.full((n_lanes, p, width), int(val.NONE), np.int32)
    gate = np.full((n_lanes, p, width), int(val.NONE), np.int32)
    tail = np.zeros((n_lanes, p), np.int32)
    roots = jnp.stack([prng.root_key(ln.seed) for ln in lanes])
    a = cfg.n_nodes
    if region_map is None:
        rmap = np.zeros((a,), np.int32)
    else:
        rmap = np.asarray(region_map, np.int32).reshape(a)
    rmaps = np.broadcast_to(rmap, (n_lanes, a))
    vid_regions = np.zeros((n_lanes, v_bound), np.int32)
    for li, ln in enumerate(lanes):
        for node, stream in zip(cfg.proposers, ln.workload):
            vid_regions[li, stream] = rmap[node]
    slo_args = tuple(
        jnp.asarray(x) for x in sflt._slo_args(slo, region_names)
    )
    states = [ControllerState(level=0) for _ in range(n_lanes)]
    seen: list[set] = [set() for _ in range(n_lanes)]
    decisions: list = []
    first_breach: list = [None] * n_lanes
    disp_cap = max(
        cfg.round_budget // (rounds_per_window * s) + 1,
        max((pl.n_windows + s - 1) // s for pl in plans),
    )
    d = 0
    w_next = 0
    last_done = np.zeros((n_lanes,), bool)
    last_t = np.zeros((n_lanes,), np.int32)
    last_decided = np.zeros((n_lanes,), np.int32)
    last_breach = np.zeros((n_lanes,), bool)
    last_dev = None
    t0 = time.perf_counter()  # paxlint: allow[DET001] wall metric only; never reaches artifacts
    with tracecount.engine_scope("serve_control_fleet"):
        css = runner._init(
            jnp.asarray(pend), jnp.asarray(gate), jnp.asarray(tail),
            roots,
        )
        while True:
            adm = np.full((n_lanes, s, p, k), arrv.NONE, np.int32)
            arr = np.zeros((n_lanes, s, p, k), np.int32)
            kp = np.zeros((n_lanes, s, p, k), bool)
            for li, pl in enumerate(plans):
                sf = df = None
                if states[li].degraded:
                    sf, df = control.shed_tier, control.defer_tier
                for i in range(s):
                    adm[li, i], arr[li, i], kp[li, i] = pl.take(
                        w_next + i, k, shed_floor=sf, defer_floor=df
                    )
            w_next += s
            out = runner._fn(
                css, roots, jnp.asarray(adm), jnp.asarray(arr),
                jnp.asarray(kp), jnp.asarray(vid_regions),
                jnp.asarray(rmaps), *slo_args,
            )
            css = out[0]
            d += 1
            # sequential harvest (four [lanes] vectors) — the
            # controller reads this dispatch before shaping the next
            last_done, last_t, last_decided, last_breach = (
                np.asarray(out[1]), np.asarray(out[2]),  # paxlint: allow[JAX103] the harvest IS the per-dispatch sync: the controller consumes the [lanes] breach vector by design
                np.asarray(out[3]), np.asarray(out[4]),
            )
            last_dev = out[5:]
            summ_d, wsum_d, _ = last_dev
            for li in range(n_lanes):
                if last_breach[li] and first_breach[li] is None:
                    first_breach[li] = d
                new_with_codes = []
                burn_milli = 0
                if last_breach[li]:
                    # flagged lane: ONE series transfer feeds the
                    # judge + the diagnosis, the fleet monitor's
                    # existing flagged-lane discipline
                    lane_w = jax.tree.map(
                        lambda x, li=li: np.asarray(x[li]), wsum_d
                    )  # paxlint: allow[JAX103] flagged-lane confirm transfer, one slice
                    lat_max = int(np.asarray(summ_d.lat_max[li]))  # paxlint: allow[JAX103] same flagged-lane confirm
                    wd = telem.windows_to_dict(lane_w, ww, lat_max)
                    judged = sh.slo_windows(wd, slo)
                    # complete buckets only (see the single loop): a
                    # half-filled bucket's burn is a transient
                    t_li = int(last_t[li])
                    full = t_li // ww
                    new = [
                        w for w in judged["breach_windows"]
                        if w < full and w not in seen[li]
                    ]
                    if new:
                        dg = diag.diagnose_breaches(wd, new)
                        for v in dg["windows"]:
                            codes = tuple(sorted({
                                diag.cause_code(cand["cause"])
                                for cand in v["candidates"]
                            })) or (0,)
                            new_with_codes.append(
                                (int(v["window"]), codes)
                            )
                        seen[li].update(new)
                    lo_b = max(0, t_li - s * rounds_per_window) // ww
                    hi_b = min(len(judged["burn"]), full) - 1
                    burn_milli = int(round(1000 * max(
                        (judged["burn"][b]
                         for b in range(lo_b, hi_b + 1)),
                        default=0.0,
                    )))
                dec = decide(
                    control, states[li], dispatch=d,
                    burn_milli=burn_milli, new_windows=new_with_codes,
                )
                if dec is not None:
                    decisions.append({"lane": li, **dec})
            if all(pl.exhausted for pl in plans) and last_done.all():
                break
            if d >= disp_cap:
                break
    wall = time.perf_counter() - t0  # paxlint: allow[DET001] wall metric only; never reaches artifacts

    summaries, windows, region_windows = last_dev
    slo_dict = {}
    for i in np.flatnonzero(last_breach):
        i = int(i)
        lane_w = jax.tree.map(lambda x, i=i: np.asarray(x[i]), windows)  # paxlint: allow[JAX103] post-clock confirm: flagged lanes only
        lane_s = jax.tree.map(lambda x, i=i: np.asarray(x[i]), summaries)  # paxlint: allow[JAX103] same flagged-lane confirm transfer
        sd_i = telem.summary_to_dict(
            lane_s, lane_w, ww, region_names=tuple(region_names)
        )
        wd_i = sd_i["windows"]
        verdict = sh.slo_windows(
            wd_i, slo,
            region_series=np.asarray(region_windows[i]),
            region_names=region_names,
        )
        diag.attach_diagnosis(
            verdict, wd_i,
            region_map=np.asarray(rmap),
            region_names=tuple(region_names),
            region_pairs=sd_i.get("region_pairs"),
            region_series=np.asarray(region_windows[i]),
        )
        slo_dict[i] = verdict
    sheds = [rec for pl in plans for rec in pl.shed_records]
    shed_total = sum(pl.shed_count for pl in plans)
    ctl_dev = np.asarray(css.ctl)  # [lanes, 2]
    if int(ctl_dev[:, 0].sum()) != shed_total:
        raise RuntimeError(
            f"shed ledger skew: device {int(ctl_dev[:, 0].sum())} vs "
            f"host {shed_total}"
        )
    return ControlFleetReport(
        cfg=cfg,
        n_lanes=n_lanes,
        seeds=[ln.seed for ln in lanes],
        rounds_per_window=int(rounds_per_window),
        windows_per_dispatch=s,
        admit_width=k,
        window_rounds=ww,
        dispatches=d,
        rounds=int(last_t.max()),
        done=bool(last_done.all()),
        n_values=[pl.n_values for pl in plans],
        decided=last_decided,
        wall_seconds=wall,
        breach=last_breach,
        first_breach_dispatch=first_breach,
        slo=slo_dict or None,
        region_names=tuple(region_names),
        final=css,
        summaries=summaries,
        windows=windows,
        region_windows=region_windows,
        policy=control,
        decisions=decisions,
        sheds=sheds,
        shed_total=shed_total,
        lane_shed=[pl.shed_count for pl in plans],
    )


# dataclass inheritance at import time needs the base resolved; the
# serve stack is already loaded when this module is (control is only
# reached through serve entry points)
from tpu_paxos.serve import fleet as _sflt  # noqa: E402


@dataclasses.dataclass
class ControlFleetReport(_sflt.ServeFleetReport):
    """A :class:`serve.fleet.ServeFleetReport` plus the controller's
    ledgers — drop-in for ``fleet._fleet_point`` (sweep cells), with
    ``backlog`` excluding deliberately shed values."""

    policy: ControlPolicy = None
    decisions: list = dataclasses.field(default_factory=list)
    sheds: list = dataclasses.field(default_factory=list)
    shed_total: int = 0
    lane_shed: list = dataclasses.field(default_factory=list)

    @property
    def backlog(self) -> int:
        return (
            int(sum(self.n_values)) - self.decided_total
            - int(self.shed_total)
        )

    def lane_chosen(self, i: int):
        # ``final`` is the ControlLoopState wrapper; the base class
        # accessors expect the bare fleet state underneath it
        import numpy as _np

        met = self.final.serve.sim.met
        return (
            _np.asarray(met.chosen_vid[i]),
            _np.asarray(met.chosen_ballot[i]),
        )


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------


def audit_entries():
    """Canonical controlled-window traces (analysis/registry.py):
    the serve audit geometry with i.i.d. faults on, a 2-sub-window
    dispatch whose keep mask sheds one real value — so the lowered
    program exercises the admit-block compaction sort AND the control
    counters.  ``donate_argnums=(0,)`` arms the HLO tier's aliasing
    checker on every leaf of :class:`ControlLoopState` — including
    the new ``ctl`` counter leaf the satellite contract names.  The
    fleet twin traces :class:`ControlFleetRunner`'s product jit the
    same way."""
    import jax.numpy as jnp

    from tpu_paxos.analysis.registry import AuditEntry
    from tpu_paxos.config import FaultConfig
    from tpu_paxos.core import sim as simm
    from tpu_paxos.core import values as val
    from tpu_paxos.core.sim import audit_canonical_cfg
    from tpu_paxos.serve import driver as drv
    from tpu_paxos.utils import prng

    r_window, s_windows, k_admit, n_lanes = 8, 2, 4, 2
    w_rounds = r_window * 4

    def _cfg_workload():
        cfg = dataclasses.replace(
            audit_canonical_cfg(),
            faults=FaultConfig(
                drop_rate=500, crash_rate=1000, max_delay=2
            ),
        )
        return cfg, simm.default_workload(cfg)

    def _blocks(workload, p):
        admits = np.full(
            (s_windows, p, k_admit), int(val.NONE), np.int32
        )
        arrs = np.zeros((s_windows, p, k_admit), np.int32)
        keeps = np.ones((s_windows, p, k_admit), bool)
        for pi, w in enumerate(workload):
            w = np.asarray(w, np.int32)
            for si in range(s_windows):
                blk = w[si * k_admit:(si + 1) * k_admit]
                admits[si, pi, :len(blk)] = blk
                arrs[si, pi, :len(blk)] = si * r_window
        # one real shed so the mask path (compaction + counter) is
        # live in the lowered program, not constant-folded away
        keeps[0, 0, 0] = False
        return admits, arrs, keeps

    def _setup():
        cfg, workload = _cfg_workload()
        v_bound = drv.vid_bound_of(workload)
        root = prng.root_key(cfg.seed)
        cs, c = init_control_state(
            cfg, workload, v_bound, root, window_rounds=w_rounds
        )
        fn = control_window_for(cfg, c, v_bound, r_window, w_rounds)
        admits, arrs, keeps = _blocks(workload, len(cfg.proposers))
        return fn, (
            cs, root, jnp.asarray(admits), jnp.asarray(arrs),
            jnp.asarray(keeps),
        )

    def build():
        return _setup()

    def hlo_build():
        fn, args = _setup()
        return fn, args, {}

    def _fleet_setup():
        from tpu_paxos.serve import fleet as sflt

        cfg, workload = _cfg_workload()
        v_bound = drv.vid_bound_of(workload)
        _, _, _, c = simm.prepare_queues(cfg, workload)
        runner = ControlFleetRunner(cfg, c, v_bound, r_window, w_rounds)
        p = len(cfg.proposers)
        width = c + cfg.assign_window
        pend = np.full((n_lanes, p, width), int(val.NONE), np.int32)
        gate = np.full((n_lanes, p, width), int(val.NONE), np.int32)
        tail = np.zeros((n_lanes, p), np.int32)
        roots = jnp.stack([prng.root_key(sd) for sd in (0, 1)])
        css = runner._init(
            jnp.asarray(pend), jnp.asarray(gate), jnp.asarray(tail),
            roots,
        )
        admits, arrs, keeps = _blocks(workload, p)
        admits = np.broadcast_to(
            admits, (n_lanes, *admits.shape)
        ).copy()
        arrs = np.broadcast_to(arrs, (n_lanes, *arrs.shape)).copy()
        keeps = np.broadcast_to(keeps, (n_lanes, *keeps.shape)).copy()
        vid_regions = np.zeros((n_lanes, v_bound), np.int32)
        rmaps = np.zeros((n_lanes, cfg.n_nodes), np.int32)
        slo_args = tuple(
            jnp.asarray(x)
            for x in sflt._slo_args(
                sh.ServeSLO(latency_rounds=16, budget_milli=100), ()
            )
        )
        args = (
            css, roots, jnp.asarray(admits), jnp.asarray(arrs),
            jnp.asarray(keeps), jnp.asarray(vid_regions),
            jnp.asarray(rmaps), *slo_args,
        )
        return runner._fn, args

    def fleet_build():
        return _fleet_setup()

    def fleet_hlo_build():
        fn, args = _fleet_setup()
        return fn, args, {}

    ir204_why = (
        "the window body IS core/sim's round_fn (same unique-key "
        "compaction sorts as sim.run_rounds) plus admit_block's keep-"
        "mask prefix compaction — a stable argsort by design"
    )
    return [
        AuditEntry(
            "serve.control_window", build,
            covers=("build_control_window",),
            allow=("IR204",), why=ir204_why,
            donate_argnums=(0,),
            hlo_build=hlo_build,
            hlo_golden=True,
        ),
        AuditEntry(
            "serve.control_fleet", fleet_build,
            covers=("ControlFleetRunner.__init__",),
            allow=("IR204",), why=ir204_why,
            donate_argnums=(0,),
            hlo_build=fleet_hlo_build,
            hlo_golden=True,
        ),
    ]
