"""The serve driver: donated, double-buffered dispatch windows over
the general engine.

One :func:`serve_window` call is ONE DISPATCH carrying ``S`` admission
windows (``admits``/``arrs`` are ``[S, P, K]``): for each sub-window
it stamps the uploaded values' arrival rounds into the per-vid ingest
table, appends them to the proposer queues (``core/sim.admit_block``
— the contiguous free-suffix ring the engine already maintains), and
runs ``rounds_per_window`` engine rounds with the flight recorder
armed; the dispatch epilogue reduces the run-so-far commit-latency
histogram ON DEVICE (``telemetry/recorder.summarize`` with
``admit_round`` replaced by the ingest-time stamps,
:func:`~tpu_paxos.telemetry.recorder.serve_admit_rounds`).

Batching windows per dispatch is the serving twin of the fast path's
16-windows-per-call (PERF.md §Headline): every dispatch pays a fixed
host+tunnel+epilogue overhead (~90 ms through the TPU device tunnel;
~2.4 ms of call/sync/render overhead even on the CPU dev box), and
``S`` admission windows amortize it while the admission GRANULARITY —
values enter the queue every ``rounds_per_window`` rounds, stamped
with their true arrival rounds — stays exactly that of
one-window-per-dispatch sequential dispatch.  The virtual trajectory
is bit-identical for every ``S`` (pinned by tests/test_serve.py), so
latency-at-load compares at EXACTLY equal p50/p99/p999 and the
speedup is pure dispatch-overhead hiding (BENCH_serve.json).

The whole loop state — engine state, recorder accumulators, ingest
table — is ONE donated argument (``donate_argnums=(0,)``): windows
chain buffers in place and no queue state ever round-trips the host.
The donation is enforced by the HLO audit tier's aliasing checker
(``make audit``): every array leaf of :class:`ServeLoopState` must
appear in the compiled ``input_output_alias`` table, or the audit
fails naming the leaf.

Dispatches run a FIXED round count (no early exit at quiescence), so
the virtual clock after dispatch ``d`` is exactly ``(d+1) * S * R`` —
the admission plan (serve/arrivals.py) is computable entirely up
front and every dispatch granularity runs the same trajectory.
Rounds past quiescence are decision-neutral: decisions are
write-once, idle rounds are event-gated, and PRNG streams key on the
round counter.

The harness (serve/harness.py) owns the host loop; this module owns
every jitted surface so the audit's unregistered-function sweep
covers the package.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import sim as simm
from tpu_paxos.core import values as val
from tpu_paxos.telemetry import recorder as telem
from tpu_paxos.utils import prng


class ServeLoopState(NamedTuple):
    """The donated whole-run loop state chained across dispatches.
    Every leaf is a device array (the donation checker's accounting
    requires an all-array donated arg)."""

    sim: object  # simm.SimState — engine state incl. the queue ring
    tele: object  # telem.Telemetry — recorder accumulators; on a
    #     windowed build (window_rounds > 0) the (Telemetry,
    #     TelemetryWindows) pair — the [W] series rings ride the same
    #     donated arg and chain on device like every other buffer
    ingest: object  # [V] int32 arrival round per vid (NONE: never)


def empty_queues(cfg: SimConfig, workload):
    """Queue arrays sized by the FULL planned value stream (the
    capacity proof ``admit_block`` relies on) but EMPTY — open-loop
    runs start with nothing queued and admit at window boundaries.
    Returns ``(pend, gate, tail, queue_cap)``."""
    pend, gate, tail, c = simm.prepare_queues(cfg, workload, None)
    return (
        np.full_like(pend, int(val.NONE)),
        gate,  # all NONE already: serve traffic is ungated
        np.zeros_like(tail),
        c,
    )


def vid_bound_of(workload) -> int:
    """Ingest-table size: one slot per vid up to the stream's max."""
    hi = max(
        (int(np.max(w)) for w in workload if len(w)), default=-1
    )
    if hi < 0:
        raise ValueError("serve workload must carry at least one value")
    return hi + 1


def init_serve_state(
    cfg: SimConfig, workload, vid_bound: int, root,
    window_rounds: int = 0,
    geometry=None, geom=None, pknobs=None,
) -> tuple[ServeLoopState, int]:
    """Fresh loop state for one serve run: empty queues, zeroed
    recorder (plus zeroed ``[W]`` window rings when ``window_rounds``
    is nonzero — must match the builder's), all-NONE ingest table.
    Geometry-padded serving passes the builder's GeometryEnvelope plus
    this tenant's traced ``geom``/``pknobs`` (core/geom) so the
    initial backoff draw matches the true geometry bit for bit.
    Returns ``(state, queue_cap)``."""
    pend, gate, tail, c = empty_queues(cfg, workload)
    st = simm.init_state(
        cfg, pend, gate, tail, root,
        geometry=geometry, geom=geom, pknobs=pknobs,
    )
    tele = telem.init_telemetry(
        cfg.n_instances, len(cfg.proposers), cfg.n_nodes
    )
    if window_rounds:
        tele = (tele, telem.init_windows(cfg.n_nodes))
    ingest = jnp.full((int(vid_bound),), val.NONE, jnp.int32)
    return ServeLoopState(sim=st, tele=tele, ingest=ingest), c


def build_serve_window(
    cfg: SimConfig,
    queue_cap: int,
    vid_bound: int,
    rounds_per_window: int,
    window_rounds: int = 0,
    geometry=None,
):
    """Compile-time closure for one serving envelope: the jitted
    ``serve_window(ss, root, admits, arrs) -> (ss, done, t, summary)``
    with the loop state donated.  ``admits``/``arrs`` are ``[S, P,
    K]`` stacks of the per-window upload blocks from
    ``arrivals.ArrivalPlan.block``; ``S`` (windows per dispatch) and
    ``K`` (admit width) are call shapes, so a run reusing one
    ``(S, K)`` pair shares one executable and the ``S = 1``
    sequential-dispatch baseline is the SAME program at a different
    shape.  Use :func:`window_for` for the cached builder.

    A nonzero ``window_rounds`` arms the recorder's WINDOWED
    time-series plane (the serving default — harness.serve_run aligns
    the bucket width with its admission windows): the loop state's
    telemetry leg becomes the ``(Telemetry, TelemetryWindows)`` pair,
    and the epilogue additionally closes the windowed series with the
    ingest-time admission stamps (``summarize_windows``), so every
    dispatch hands the harness per-bucket p50/p99 as a STREAM — the
    call returns ``(ss, done, t, summary, window_summary)``.  The
    trajectory is identical either way (the recorder is read-only);
    ``window_rounds=0`` traces the exact pre-windowing program.

    ``geometry`` (core/geom.GeometryEnvelope) builds the
    geometry-PADDED window: ``cfg`` must be the envelope's bound cfg
    and the jitted surface becomes ``serve_window(ss, root, admits,
    arrs, gm, pkn)`` — the tenant's true geometry and protocol knobs
    are per-dispatch data, so ONE warm window serves every tenant
    geometry on the menu (pad proposer rows of ``admits`` carry NONE
    and admit nothing)."""
    if cfg.faults.schedule is not None:
        raise ValueError(
            "serve engines take no fault schedule (correlated-fault "
            "serving rides the fleet envelope, not this driver)"
        )
    ww = int(window_rounds)
    round_fn = simm.build_engine(
        cfg, queue_cap, vid_cap=0, telemetry=True, window_rounds=ww,
        geometry=geometry, runtime_protocol=geometry is not None,
    )
    r = int(rounds_per_window)
    v_bound = int(vid_bound)

    def serve_window(ss, root, admits, arrs, *gp):
        gm, pkn = gp if gp else (None, None)
        s = admits.shape[0]

        def sub(i, carry):
            st, tl, ingest = carry
            admit, arr = admits[i], arrs[i]
            # Ingest-time stamping: each uploaded vid's ARRIVAL round
            # (not the upload round) enters the per-vid table; NONE
            # padding routes out of range and drops.
            flat_v = admit.reshape(-1)
            idx = jnp.where(
                (flat_v >= 0) & (flat_v < v_bound), flat_v, v_bound
            )
            ingest = ingest.at[idx].set(arr.reshape(-1), mode="drop")
            st = simm.admit_block(st, admit)

            def body(_, c):
                return round_fn(root, c[0], tele=c[1], geom=gm, pknobs=pkn)

            st, tl = jax.lax.fori_loop(0, r, body, (st, tl))
            return ServeLoopState(st, tl, ingest)

        st, tl, ingest = jax.lax.fori_loop(
            0, s, sub, ServeLoopState(*ss)
        )
        # Run-so-far latency summary with admission stamped at ingest
        # (serve_admit_rounds) — the closed-loop ledger reduction,
        # inside the same jit; nothing per-instance crosses to host.
        adm = telem.serve_admit_rounds(ingest, st.met.chosen_vid)
        if not ww:
            summ = telem.summarize(tl._replace(admit_round=adm), st, 0)
            return ServeLoopState(st, tl, ingest), st.done, st.t, summ
        base, wins = tl
        summ = telem.summarize(base._replace(admit_round=adm), st, 0)
        # the windowed epilogue decomposes phases against the phase
        # ledger: queue-wait = first-accept-batch minus INGEST (the
        # serving queue's real wait), consensus/commit/learn from the
        # in-loop stamps
        wsum = telem.summarize_windows(
            wins, adm, st.met.chosen_vid, st.met.chosen_round, ww,
            batch_round=base.admit_round,
            learned_round=base.learned_round,
            committed_round=base.committed_round,
        )
        return ServeLoopState(st, tl, ingest), st.done, st.t, summ, wsum

    return jax.jit(serve_window, donate_argnums=(0,))


_CACHE: dict = {}


def clear_cache() -> None:
    """Drop every cached window (tests; frees executables)."""
    _CACHE.clear()


def engine_static_key(cfg: SimConfig, geometry=None) -> tuple:
    """THE compile-time facts of a serve engine build, as one hashable
    tuple — the single source of truth shared by :func:`window_for`'s
    cache key and the fleet serve envelope key
    (``fleet/envelope.serve_envelope_key``).  A fact added to the
    engine build MUST land here, or a changed config could HIT a warm
    cache and silently run the wrong executable (exactly how
    ``edges``/``delivery_cut`` were once missing from one of two
    hand-duplicated lists).

    A ``geometry`` envelope COLLAPSES the key: the menu replaces the
    per-geometry (n_nodes, proposers) facts and the protocol tuple
    drops out (traced per dispatch) — one cache slot per bound, not
    per tenant geometry."""
    return (
        simm.seeded_wedge(),
        (
            (cfg.n_nodes, cfg.proposers)
            if geometry is None else ("geom", geometry.menu)
        ),
        cfg.n_instances,
        cfg.assign_window,
        cfg.max_rounds,
        (
            dataclasses.astuple(cfg.protocol)
            if geometry is None else "runtime-protocol"
        ),
        (
            cfg.faults.drop_rate, cfg.faults.dup_rate,
            cfg.faults.min_delay, cfg.faults.max_delay,
            cfg.faults.crash_rate,
            cfg.faults.edges, bool(cfg.faults.delivery_cut),
        ),
    )


def window_for(
    cfg: SimConfig, queue_cap: int, vid_bound: int, rounds_per_window: int,
    window_rounds: int = 0,
    geometry=None,
):
    """Envelope-keyed cache over :func:`build_serve_window` (the
    ``fleet/envelope.runner_for`` discipline): a knee sweep's rate
    points and the bench's dispatch-granularity twins all reuse ONE
    cached builder per (geometry, protocol, knobs, queue shape, vid
    space, window span, windowed-plane bucket width) — and per
    seeded-wedge flag, which selects a different traced engine."""
    if cfg.faults.schedule is not None:
        # checked HERE, not just in build_serve_window: the key below
        # ignores the schedule (serve engines never take one), so a
        # schedule-bearing cfg would otherwise HIT a warm cache and
        # silently drop its correlated faults instead of failing
        raise ValueError(
            "serve engines take no fault schedule (correlated-fault "
            "serving rides the fleet envelope, not this driver)"
        )
    key = (
        engine_static_key(cfg, geometry=geometry),
        int(queue_cap),
        int(vid_bound),
        int(rounds_per_window),
        int(window_rounds),
    )
    fn = _CACHE.get(key)
    if fn is None:
        fn = build_serve_window(
            cfg, queue_cap, vid_bound, rounds_per_window,
            window_rounds=window_rounds, geometry=geometry,
        )
        _CACHE[key] = fn
    return fn


# ---------------- IR-audit registration (analysis/jaxpr_audit) ------

def audit_entries():
    """Canonical serve-window trace (analysis/registry.py): the audit
    config's geometry with i.i.d. faults on, a 2-sub-window dispatch
    of real admission blocks through stamp + append + recorder-armed
    round spans + the on-device ingest-stamped summary.
    ``donate_argnums=(0,)`` arms the HLO tier's aliasing checker on
    the whole loop state — the double-buffered queue surface ROADMAP
    item 1 promised it (``hlo_build`` lowers through the product jit
    itself: a wrapper re-jit would silently re-add a dropped
    donation)."""
    from tpu_paxos.analysis.registry import AuditEntry
    from tpu_paxos.core.sim import audit_canonical_cfg

    r_window, s_windows, k_admit = 8, 2, 4
    # the product path is WINDOWED (harness.serve_run's default): the
    # [W] series rings ride the donated loop state and the aliasing
    # checker must account for every one of their leaves too
    w_rounds = r_window * 4

    def _setup():
        cfg = dataclasses.replace(
            audit_canonical_cfg(),
            faults=FaultConfig(drop_rate=500, crash_rate=1000, max_delay=2),
        )
        workload = simm.default_workload(cfg)
        v_bound = vid_bound_of(workload)
        root = prng.root_key(cfg.seed)
        ss, c = init_serve_state(
            cfg, workload, v_bound, root, window_rounds=w_rounds
        )
        fn = window_for(cfg, c, v_bound, r_window, window_rounds=w_rounds)
        p = len(cfg.proposers)
        admits = np.full((s_windows, p, k_admit), int(val.NONE), np.int32)
        arrs = np.zeros((s_windows, p, k_admit), np.int32)
        for pi, w in enumerate(workload):
            w = np.asarray(w, np.int32)
            for si in range(s_windows):
                blk = w[si * k_admit:(si + 1) * k_admit]
                admits[si, pi, :len(blk)] = blk
                arrs[si, pi, :len(blk)] = si * r_window
        return fn, (ss, root, jnp.asarray(admits), jnp.asarray(arrs))

    def build():
        return _setup()

    def hlo_build():
        fn, args = _setup()
        return fn, args, {}

    def _setup_envelope():
        # the geometry-padded window: same admission blocks, traced
        # through the 5-node / 3-proposer bound with the TRUE (3, 2)
        # geometry and the protocol knobs as trailing runtime inputs;
        # the donated loop state is the PADDED one, so the aliasing
        # checker accounts for every bound-shaped leaf
        from tpu_paxos.core import geom as geo

        genv = geo.GeometryEnvelope(menu=((3, (0, 1)), (5, (0, 1, 2))))
        cfg = dataclasses.replace(
            audit_canonical_cfg(),
            faults=FaultConfig(drop_rate=500, crash_rate=1000, max_delay=2),
        )
        bcfg = genv.bound_cfg(cfg)
        workload = simm.default_workload(cfg)
        v_bound = vid_bound_of(workload)
        root = prng.root_key(cfg.seed)
        gm = geo.geometry_for(genv, cfg.n_nodes, cfg.proposers)
        pkn = geo.protocol_knobs(
            cfg.protocol, stall_patience=simm.IDLE_RESTART_ROUNDS
        )
        wl = workload + [np.zeros((0,), np.int32)]
        ss, c = init_serve_state(
            bcfg, wl, v_bound, root, window_rounds=w_rounds,
            geometry=genv, geom=gm, pknobs=pkn,
        )
        fn = window_for(
            bcfg, c, v_bound, r_window, window_rounds=w_rounds,
            geometry=genv,
        )
        p = len(bcfg.proposers)
        admits = np.full((s_windows, p, k_admit), int(val.NONE), np.int32)
        arrs = np.zeros((s_windows, p, k_admit), np.int32)
        for pi, w in enumerate(workload):
            w = np.asarray(w, np.int32)
            for si in range(s_windows):
                blk = w[si * k_admit:(si + 1) * k_admit]
                admits[si, pi, :len(blk)] = blk
                arrs[si, pi, :len(blk)] = si * r_window
        return fn, (
            ss, root, jnp.asarray(admits), jnp.asarray(arrs),
            jax.tree.map(jnp.asarray, gm),
            jax.tree.map(jnp.asarray, pkn),
        )

    def build_envelope():
        return _setup_envelope()

    def hlo_build_envelope():
        fn, args = _setup_envelope()
        return fn, args, {}

    ir204_why = (
        "the window body IS core/sim's round_fn — same unique-key "
        "compaction sorts as sim.run_rounds"
    )
    return [
        AuditEntry(
            "serve.window", build,
            covers=("build_serve_window",),
            allow=("IR204",), why=ir204_why,
            donate_argnums=(0,),
            hlo_build=hlo_build,
            hlo_golden=True,
        ),
        AuditEntry(
            # the geometry-padded twin: one warm window executable per
            # hardware bound, tenant geometry as runtime data — the
            # donation contract must survive the padding (a dropped
            # alias on the BOUND-shaped queue plane doubles the larger
            # buffer)
            "serve.window_envelope", build_envelope,
            allow=("IR204",), why=ir204_why,
            donate_argnums=(0,),
            hlo_build=hlo_build_envelope,
            hlo_golden=True,
        ),
    ]
